"""Tape-based autograd over eager ops.

Reference semantics replicated: ``record()/pause()`` scopes, ``train_mode/
predict_mode``, ``attach_grad`` leaves, ``backward()`` populating ``.grad``
honoring ``grad_req`` in {'write','add','null'} (ref: python/mxnet/autograd.py,
src/imperative/imperative.cc — Imperative::Backward).

TPU-native design: instead of building an nnvm gradient graph, each recorded
op captures its ``jax.vjp`` closure at invoke time (forward runs once, XLA
keeps the residuals); ``backward()`` walks the tape in reverse topological
order calling the stored vjp closures. Hybridized blocks appear on the tape
as a single CachedOp node whose vjp is the vjp of the whole jitted program —
the analog of CachedOp::Backward.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "Function",
    "set_recording",
    "set_training",
]


_launches = None  # profiler.record_launch, bound on first backward


def _count_launch():
    global _launches
    if _launches is None:
        from . import profiler
        _launches = profiler.record_launch
    _launches()


class _AGState(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False


_state = _AGState()


def is_recording():
    return _state.recording


def is_training():
    return _state.training


def set_recording(is_record):
    prev = _state.recording
    _state.recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _state.training
    _state.training = bool(train_mode_)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev = None

    def __enter__(self):
        self._prev = (_state.recording, _state.training)
        if self._enter_is_record is not None:
            _state.recording = self._enter_is_record
        if self._enter_train_mode is not None:
            _state.training = self._enter_train_mode
        return self

    def __exit__(self, *args):
        _state.recording, _state.training = self._prev


def record(train_mode=True):
    """Scope: ops executed inside are recorded for backward."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# --------------------------------------------------------------------------
# Tape nodes
# --------------------------------------------------------------------------
class AGNode:
    """One recorded op: vjp closure + parent links.

    parents[i] is (AGNode, out_index) for tracked inputs, else None.
    out_avals: (shape, dtype) per output, for synthesizing zero cotangents.
    fwd_fn/in_vals: the pure forward and its primal inputs, kept so
    ``grad(create_graph=True)`` can replay the subgraph functionally
    (higher-order grads need d(residuals)/d(inputs), which a stored vjp
    closure alone cannot provide).
    """

    __slots__ = ("vjp_fn", "parents", "out_avals", "name", "_ct",
                 "_seen_out", "fwd_fn", "in_vals")

    def __init__(self, vjp_fn, parents, out_avals, name="",
                 fwd_fn=None, in_vals=None):
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.out_avals = out_avals
        self.name = name
        self.fwd_fn = fwd_fn
        self.in_vals = in_vals
        self._ct = None  # per-output cotangent accumulation during backward
        self._seen_out = None

    def init_ct(self):
        self._ct = [None] * len(self.out_avals)

    def add_ct(self, idx, val):
        if self._ct[idx] is None:
            self._ct[idx] = val
        else:
            self._ct[idx] = self._ct[idx] + val

    def full_ct(self):
        out = []
        for i, c in enumerate(self._ct):
            if c is None:
                shape, dtype = self.out_avals[i]
                out.append(jnp.zeros(shape, dtype))
            else:
                out.append(c)
        return tuple(out)


class AGLeaf(AGNode):
    """A variable created by attach_grad/mark_variables."""

    __slots__ = ("array_ref", "grad_req")

    def __init__(self, array_ref, grad_req):
        super().__init__(None, [], [(array_ref.shape, array_ref.dtype)], name="leaf")
        self.array_ref = array_ref
        self.grad_req = grad_req


def mark_variables(variables, gradients, grad_reqs="write"):
    """Associate gradient buffers with variables
    (ref: python/mxnet/autograd.py — mark_variables)."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, gradbuf, req in zip(variables, gradients, grad_reqs):
        var._grad = gradbuf
        var._ag_node = (AGLeaf(var, req), 0)


def _toposort(root_nodes):
    order = []
    visited = set()
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for p in node.parents:
            if p is not None and id(p[0]) not in visited:
                stack.append((p[0], False))
    return order  # parents-before-children; reverse for backward


def _run_backward(heads, head_grads, retain_graph=False, collect=None):
    """Core reverse pass. If ``collect`` is a list of leaf NDArray refs,
    returns their cotangents instead of writing ``.grad``."""
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray) or not isinstance(
        head_grads, (list, tuple)
    ):
        head_grads = [head_grads]
    if len(head_grads) != len(heads):
        raise ValueError(
            "head_grads length %d does not match heads length %d"
            % (len(head_grads), len(heads))
        )

    roots = []
    seeds = []
    for h, hg in zip(heads, head_grads):
        entry = getattr(h, "_ag_node", None)
        if entry is None:
            raise ValueError(
                "cannot differentiate a head that was not computed inside "
                "autograd.record() (or lacks attach_grad)"
            )
        node, idx = entry
        roots.append(node)
        g = jnp.ones(h.shape, h.dtype) if hg is None else (
            hg.data if isinstance(hg, NDArray) else jnp.asarray(hg)
        )
        seeds.append((node, idx, g))

    order = _toposort(roots)
    for n in order:
        n.init_ct()
    for node, idx, g in seeds:
        node.add_ct(idx, g)

    leaf_cts = {}
    for node in reversed(order):
        if isinstance(node, AGLeaf):
            ct = node._ct[0]
            if ct is not None:
                key = id(node.array_ref)
                if key in leaf_cts:
                    leaf_cts[key] = (node, leaf_cts[key][1] + ct)
                else:
                    leaf_cts[key] = (node, ct)
            continue
        if node.vjp_fn is None:
            continue
        _count_launch()  # each vjp closure is its own dispatched execution
        in_cts = node.vjp_fn(node.full_ct())
        for parent, ct in zip(node.parents, in_cts):
            if parent is None:
                continue
            # integer/float0 cotangents carry no gradient
            if hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0:
                continue
            parent[0].add_ct(parent[1], ct)
        if not retain_graph:
            node.vjp_fn = None
            node.fwd_fn = None
            node.in_vals = None
        node._ct = None

    if collect is not None:
        out = []
        for arr in collect:
            key = id(arr)
            if key in leaf_cts:
                out.append(leaf_cts[key][1])
            else:
                out.append(None)
        return out

    for _, (node, ct) in leaf_cts.items():
        arr = node.array_ref
        if node.grad_req == "null":
            continue
        if arr._grad is None:
            continue
        if node.grad_req == "add":
            arr._grad._set_data(arr._grad.data + ct.astype(arr._grad.dtype))
        else:
            arr._grad._set_data(ct.astype(arr._grad.dtype))
    return None


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. attached variables
    (ref: python/mxnet/autograd.py — backward)."""
    del train_mode  # forward already ran; mode was captured then
    _run_backward(heads, head_grads, retain_graph=retain_graph)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients of heads w.r.t. variables without touching ``.grad``
    (ref: python/mxnet/autograd.py — grad). With ``create_graph=True`` the
    returned gradients are themselves recorded on the tape (differentiable
    to arbitrary order — gradient penalties, MAML); see
    ``_grad_create_graph`` for the replay design."""
    del train_mode
    from .ndarray.ndarray import NDArray

    if create_graph:
        return _grad_create_graph(heads, variables, head_grads)
    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    for v in variables:
        if getattr(v, "_ag_node", None) is None or not isinstance(v._ag_node[0], AGLeaf):
            raise ValueError(
                "variables passed to grad() must have attach_grad() called "
                "before the recorded computation"
            )
    cts = _run_backward(
        heads, head_grads, retain_graph=bool(retain_graph), collect=variables
    )
    outs = []
    for v, ct in zip(variables, cts):
        if ct is None:
            outs.append(NDArray(jnp.zeros(v.shape, v.dtype)))
        else:
            outs.append(NDArray(ct.astype(v.dtype)))
    return outs[0] if single else outs


def _grad_create_graph(heads, variables, head_grads):
    """Higher-order ``grad`` (ref: python/mxnet/autograd.py —
    grad(create_graph=True); the reference's support was itself partial).

    Design: the tape stores each node's pure forward (``fwd_fn``) and
    primal inputs, so the subgraph from ``variables`` to ``heads`` can be
    replayed as one pure function F(var_vals) -> head_vals. The returned
    gradients are G(var_vals, seed_vals) = vjp(F)(seeds), dispatched
    through ``apply_op`` like any other op — so they land on the tape as a
    normal node whose vjp JAX derives, and differentiating them (to any
    order) needs no further machinery. Ops that drew PRNG keys replay the
    recorded keys (random.capture_keys), keeping stochastic forwards
    (dropout) bit-identical under replay.

    Untracked inputs replay from their recorded primals. Tracked leaves
    replay from their live buffers — mutating a tracked leaf in place
    between the forward and ``grad()`` therefore skews the replay (the
    same saved-tensor caveat torch versions away); custom ``Function``
    nodes carry no pure forward and raise.
    """
    from .ndarray.ndarray import NDArray
    from .ops.registry import apply_op, Op

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if len(head_grads) != len(heads):
        raise ValueError("head_grads length %d != heads length %d"
                         % (len(head_grads), len(heads)))

    for v in variables:
        entry = getattr(v, "_ag_node", None)
        if entry is None or not isinstance(entry[0], AGLeaf):
            raise ValueError(
                "variables passed to grad() must have attach_grad() called "
                "before the recorded computation")

    head_entries = []
    for h in heads:
        entry = getattr(h, "_ag_node", None)
        if entry is None:
            raise ValueError(
                "cannot differentiate a head that was not computed inside "
                "autograd.record()")
        head_entries.append(entry)

    order = _toposort([e[0] for e in head_entries])

    # The returned gradients must be differentiable w.r.t. EVERY tracked
    # leaf in the subgraph — not only `variables` (a WGAN-GP penalty
    # differentiates d y/d x, then backprops THAT into the weights W), so
    # all leaves become traced inputs of the replay.
    leaf_nodes, leaf_pos = [], {}
    by_array = {}  # id(array_ref) -> leaf position (re-attach tolerance)
    for node in order:
        if isinstance(node, AGLeaf) and id(node) not in leaf_pos:
            leaf_pos[id(node)] = len(leaf_nodes)
            by_array.setdefault(id(node.array_ref), len(leaf_nodes))
            leaf_nodes.append(node)

    def leaf_index(v):
        # match like the first-order path does (leaf_cts keys by
        # id(array_ref)): attach_grad() called again after the forward
        # makes a fresh AGLeaf, but the recorded graph still references
        # the old one for the same array
        node = v._ag_node[0]
        if id(node) in leaf_pos:
            return leaf_pos[id(node)]
        if id(v) in by_array:
            return by_array[id(v)]
        # variable not in the head graph at all → appended, zero grads
        leaf_pos[id(node)] = len(leaf_nodes)
        leaf_nodes.append(node)
        return leaf_pos[id(node)]

    var_idx = [leaf_index(v) for v in variables]

    depends = {}
    for node in order:  # parents-before-children
        if isinstance(node, AGLeaf):
            depends[id(node)] = True
            continue
        dep = any(p is not None and depends.get(id(p[0]), False)
                  for p in node.parents)
        depends[id(node)] = dep
        if dep and node.fwd_fn is None:
            raise NotImplementedError(
                "create_graph=True needs node %r's pure forward to "
                "replay, and none was recorded — either the op is a "
                "custom autograd.Function (a user-defined backward has "
                "no pure forward), or MXT_AG_LEAN_TAPE=1 disabled replay "
                "state" % node.name)

    replay_order = [n for n in order if depends[id(n)]
                    and not isinstance(n, AGLeaf)]
    dep_heads = [i for i, e in enumerate(head_entries)
                 if depends[id(e[0])]]

    def replay_heads(leaf_vals):
        env = {}
        for node in replay_order:
            ins = []
            for p, v in zip(node.parents, node.in_vals):
                if p is not None and depends[id(p[0])]:
                    src = p[0]
                    if isinstance(src, AGLeaf):
                        ins.append(leaf_vals[leaf_pos[id(src)]])
                    else:
                        ins.append(env[id(src)][p[1]])
                else:
                    ins.append(v)  # recorded primal (untracked constant)
            out = node.fwd_fn(*ins)
            env[id(node)] = list(out) if isinstance(out, tuple) else [out]
        vals = []
        for i in dep_heads:
            node, idx = head_entries[i]
            if isinstance(node, AGLeaf):  # head IS a leaf
                vals.append(leaf_vals[leaf_pos[id(node)]])
            else:
                vals.append(env[id(node)][idx])
        return tuple(vals)

    n_l = len(leaf_nodes)

    def grad_fn(*flat):
        leaf_vals, seed_vals = flat[:n_l], flat[n_l:]
        _, vjp = jax.vjp(lambda *lv: replay_heads(lv), *leaf_vals)
        all_grads = vjp(tuple(seed_vals))
        return tuple(all_grads[i] for i in var_idx)

    seed_nds = []
    for i in dep_heads:
        hg, h = head_grads[i], heads[i]
        if hg is None:
            seed_nds.append(NDArray(jnp.ones(h.shape, h.dtype)))
        else:
            seed_nds.append(hg.astype(h.dtype) if hg.dtype != h.dtype
                            else hg)

    leaf_inputs = [n.array_ref for n in leaf_nodes]
    op = Op("grad_of_%d_heads" % len(heads), grad_fn, differentiable=True)
    with _RecordingStateScope(True, None):
        outs = apply_op(op, *(leaf_inputs + seed_nds))
    if not isinstance(outs, tuple):
        outs = (outs,)
    return outs[0] if single else list(outs)


class Function:
    """Custom differentiable function
    (ref: python/mxnet/autograd.py — Function).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` using NDArray math. The forward runs
    with recording paused; backward is invoked during the tape's reverse pass.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap_outputs

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if is_recording() and any(getattr(x, "_ag_node", None) for x in inputs):
            parents = [getattr(x, "_ag_node", None) for x in inputs]
            out_avals = [(o.shape, o.dtype) for o in outs]
            fn_self = self

            def vjp_fn(cts):
                from .ndarray.ndarray import NDArray as ND

                ct_nd = [ND(c) for c in cts]
                with pause():
                    in_grads = fn_self.backward(*ct_nd)
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(
                    g.data if g is not None else None for g in in_grads
                )

            node = AGNode(vjp_fn, parents, out_avals, name=type(self).__name__)
            for i, o in enumerate(outs):
                o._ag_node = (node, i)
        return outs[0] if single else outs
