"""Profiler — ``mx.profiler`` API over ``jax.profiler`` (SURVEY §5
tracing/profiling: ref python/mxnet/profiler.py + src/profiler/profiler.cc;
the engine-level ProfileOperator records collapse into XLA's own op-level
trace, which the JAX profiler captures as Perfetto/TensorBoard data).

``set_config(filename=...)`` + ``set_state('run')`` starts a JAX trace; on
``set_state('stop')``/``dump()`` the Perfetto trace lands under the
configured directory. User scopes (Task/Frame/Counter/Marker) annotate the
device trace via ``jax.profiler.TraceAnnotation`` and are also timed
host-side so ``dumps()`` can print the MXNet-style aggregate table without
parsing protobufs.

Env autostart: ``MXT_PROFILER_AUTOSTART=1`` (ref MXNET_PROFILER_AUTOSTART).
"""
from __future__ import annotations

import os
import threading
import time

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "start", "stop", "pause",
           "resume", "dump", "dumps", "Domain", "Task", "Frame", "Counter",
           "Marker", "record_launch", "launch_count", "reset_launch_count",
           "counter_value", "record_host_sync", "host_sync_count",
           "reset_host_sync_count", "set_gauge", "gauge_value",
           "compile_count", "compile_seconds"]

_config = {
    "filename": "profile_output",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
    "continuous_dump": False,
}
_state = "stop"
_paused = False
_trace_dir = None
# aggregate table: name -> [count, total_sec, min_sec, max_sec]
_agg = {}
# _LOCK guards _agg and the counter/gauge name maps below; the metric
# VALUES themselves live in the telemetry registry (telemetry.py), whose
# cells carry their own locks — counters/gauges are bumped both from the
# dispatch thread and from deferred-read callbacks (engine.StepStream
# retirement, DataLoader workers), so every mutation must be guarded
_LOCK = threading.RLock()

# raw profiler name -> sanitized telemetry metric name. The profiler's
# counter/gauge storage moved into the typed telemetry registry; these
# maps track which registry families the profiler owns so dumps() lists
# them and dumps(reset=True) unregisters exactly them.
_counter_names = {}
_gauge_names = {}

_MISSING = object()


def _telemetry():
    from . import telemetry

    return telemetry


class _MetricsView:
    """Live read-only mapping over the profiler-owned slice of the
    telemetry registry — back-compat for code that treated the old
    ``_counters``/``_gauges`` dicts as the source of truth (membership's
    and resilience's `name not in profiler._counters` recreation
    checks)."""

    def __init__(self, names):
        self._names = names

    def get(self, name, default=None):
        metric = self._names.get(name)
        if metric is None:
            return default
        fam = _telemetry().registry().get(metric)
        if fam is None:
            return default
        v = fam.value
        return int(v) if float(v).is_integer() else v

    def __contains__(self, name):
        return self.get(name, _MISSING) is not _MISSING

    def __getitem__(self, name):
        v = self.get(name, _MISSING)
        if v is _MISSING:
            raise KeyError(name)
        return v

    def __iter__(self):
        return iter(list(self._names))

    def __len__(self):
        return len(self._names)

    def clear(self):
        reg = _telemetry().registry()
        with _LOCK:
            for metric in self._names.values():
                reg.unregister(metric)
            self._names.clear()


_counters = _MetricsView(_counter_names)
_gauges = _MetricsView(_gauge_names)


def _counter_child(name):
    """The registry cell behind a profiler counter (created on demand)."""
    tel = _telemetry()
    with _LOCK:
        metric = _counter_names.get(name)
        if metric is None:
            metric = _counter_names[name] = tel.sanitize_metric_name(name)
    return tel.registry().counter(
        metric, "profiler counter %r" % name).default


def _gauge_child(name):
    tel = _telemetry()
    with _LOCK:
        metric = _gauge_names.get(name)
        if metric is None:
            metric = _gauge_names[name] = tel.sanitize_metric_name(name)
    return tel.registry().gauge(
        metric, "profiler gauge %r" % name).default


# hot-path cells cached so record_launch/record_host_sync stay one lock
# + one add (they run on every compiled dispatch / every deferred read)
_launch_cell = None
_sync_cell = None


def _launch():
    global _launch_cell
    c = _launch_cell
    if c is None:
        c = _launch_cell = _telemetry().counter(
            "mxt_xla_launches_total",
            "Compiled-program executions (XLA launches) dispatched by "
            "the framework.").default
    return c


def _syncs():
    global _sync_cell
    c = _sync_cell
    if c is None:
        c = _sync_cell = _telemetry().counter(
            "mxt_host_syncs_total",
            "Device->host synchronizations (blocking reads) performed "
            "by the framework.").default
    return c


def record_launch(n=1):
    """Count ``n`` compiled-program executions (XLA launches) dispatched.
    Called from apply_op / the fused-step jit dispatch sites; each launch
    costs ~3.4 ms on the axon tunnel (PERF.md §1.2), so this counter is
    the cheapest fusion-health signal: a fused train step should show
    exactly 1 per step."""
    _launch().inc(n)


def launch_count():
    return int(_launch().value)


def reset_launch_count():
    return int(_launch().reset())


def record_host_sync(n=1):
    """Count ``n`` device->host synchronizations (blocking reads)."""
    _syncs().inc(n)


def host_sync_count():
    return int(_syncs().value)


def reset_host_sync_count():
    return int(_syncs().reset())


def compile_count():
    """XLA backend compiles this process has performed (incl. persistent-
    cache deserializations — tuning.compile_stats() splits hits/misses).
    Fed by the jax.monitoring listeners tuning/compile_cache.py installs
    at import; the cheapest cold-vs-warm signal next to launch_count."""
    from .tuning import compile_stats

    return int(compile_stats()["compiles"])


def compile_seconds():
    """Total XLA backend-compile wall time (seconds) this process."""
    from .tuning import compile_stats

    return compile_stats()["compile_seconds"]


def set_gauge(name, value):
    """Set a point-in-time gauge (e.g. engine's 'dispatch_depth' — the
    number of fused steps currently in flight). Gauges show in dumps()
    and in telemetry.render_prometheus()."""
    _gauge_child(name).set(value)


def gauge_value(name, default=0):
    return _gauges.get(name, default)


def counter_value(name, default=0):
    """Current value of a named profiler Counter (the dumps() table
    entries) — e.g. resilience's 'skipped_nonfinite_steps'."""
    return _counters.get(name, default)


def set_config(**kwargs):
    """Configure the profiler (ref: MXSetProcessProfilerConfig). Accepts the
    reference's kwargs; ``filename`` names the trace output directory."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError("profiler.set_config: unknown options %s"
                         % sorted(unknown))
    if _state == "run":
        raise MXNetError("cannot reconfigure profiler while running")
    _config.update(kwargs)


def state():
    return _state


def set_state(new_state="stop"):
    """'run' starts a JAX trace; 'stop' ends it (ref:
    MXSetProcessProfilerState)."""
    global _state, _trace_dir
    if new_state not in ("run", "stop"):
        raise MXNetError("profiler state must be 'run' or 'stop', got %r"
                         % (new_state,))
    if new_state == _state:
        return
    import jax

    if new_state == "run":
        base = _config["filename"]
        # the reference writes one chrome-trace JSON file; JAX writes a
        # Perfetto trace directory — use the filename sans extension as dir
        _trace_dir = base[:-5] if base.endswith(".json") else base
        os.makedirs(_trace_dir, exist_ok=True)
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    else:
        jax.profiler.stop_trace()
        _state = "stop"


def start():
    set_state("run")


def stop():
    set_state("stop")


def pause():
    """Suppress user-scope aggregation (the device trace itself cannot be
    paused mid-flight; ref MXProfilePause pauses op recording)."""
    global _paused
    _paused = True


def resume():
    global _paused
    _paused = False


def dump(finished=True):
    """Finish the trace and flush it to disk (ref: MXDumpProfile)."""
    if _state == "run" and finished:
        set_state("stop")
    return _trace_dir


def dumps(reset=False):
    """Aggregate-stats table of user scopes (ref: MXAggregateProfileStatsPrint
    — device-op aggregates live in the Perfetto trace; this table covers
    profiler.Task/Frame scopes and counters). Everything is snapshotted
    under the lock BEFORE formatting — writer threads (deferred-read
    callbacks, server connections) keep mutating while this renders."""
    with _LOCK:
        agg = {name: list(ent) for name, ent in _agg.items()}
    counters = {name: _counters.get(name) for name in _counters}
    gauges = {name: _gauges.get(name) for name in _gauges}
    lines = ["Profile Statistics:",
             "    %-24s %10s %14s %14s %14s"
             % ("Name", "Calls", "Total(ms)", "Min(ms)", "Max(ms)")]
    for name in sorted(agg):
        cnt, tot, mn, mx = agg[name]
        lines.append("    %-24s %10d %14.3f %14.3f %14.3f"
                     % (name, cnt, tot * 1e3, mn * 1e3, mx * 1e3))
    for name in sorted(counters):
        lines.append("    %-24s value=%s" % (name, counters[name]))
    for name in sorted(gauges):
        lines.append("    %-24s value=%s" % (name, gauges[name]))
    lines.append("    %-24s value=%d" % ("xla_launches", launch_count()))
    lines.append("    %-24s value=%d" % ("host_syncs", host_sync_count()))
    lines.append("    %-24s value=%d (%.3fs)"
                 % ("xla_compiles", compile_count(), compile_seconds()))
    if reset:
        with _LOCK:
            _agg.clear()
        _counters.clear()
        _gauges.clear()
        reset_launch_count()
        reset_host_sync_count()
    return "\n".join(lines)


def _record(name, dt):
    if _paused:
        return
    with _LOCK:
        ent = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        ent[0] += 1
        ent[1] += dt
        ent[2] = min(ent[2], dt)
        ent[3] = max(ent[3], dt)


class Domain:
    """Grouping namespace for scopes (ref: profiler.Domain)."""

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return "Domain(%s)" % self.name


class _Scope:
    """Timed scope: host wall-clock into the aggregate table + a
    TraceAnnotation so device ops inside it are grouped in the trace."""

    def __init__(self, name, domain=None):
        self.name = name if domain is None else "%s::%s" % (domain.name,
                                                            name)
        self._t0 = None
        self._ann = None

    def start(self):
        import jax
        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        return self

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._t0 is not None:
            _record(self.name, time.perf_counter() - self._t0)
            self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class Task(_Scope):
    pass


class Frame(_Scope):
    pass


class Counter:
    """Named counter (ref: profiler.Counter). Backed by a telemetry
    registry cell, so creation and every mutation are lock-guarded and
    the value shows in telemetry.render_prometheus() too."""

    def __init__(self, domain, name, value=0):
        self.name = "%s::%s" % (domain.name, name) if domain else name
        self._cell = _counter_child(self.name)
        self._cell.set(value)

    def set_value(self, value):
        self._cell.set(value)

    def increment(self, delta=1):
        self._cell.inc(delta)

    def decrement(self, delta=1):
        self.increment(-delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    """Instant event (ref: profiler.Marker.mark)."""

    def __init__(self, domain, name):
        self.name = "%s::%s" % (domain.name, name) if domain else name

    def mark(self, scope="process"):
        _record("marker:%s" % self.name, 0.0)


if os.environ.get("MXT_PROFILER_AUTOSTART", "") == "1":
    set_config(profile_all=True)
    set_state("run")
