"""Optimizer registry and implementations
(ref: python/mxnet/optimizer/optimizer.py)."""
from .optimizer import (
    Optimizer, Updater, get_updater, create, register,
    SGD, NAG, Adam, AdamW, AdaGrad, RMSProp, AdaDelta, Ftrl, Signum,
    SGLD, DCASGD, LAMB, FTML, Test,
)

__all__ = ["Optimizer", "Updater", "get_updater", "create", "register",
           "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "RMSProp", "AdaDelta",
           "Ftrl", "Signum", "SGLD", "DCASGD", "LAMB", "FTML", "Test"]
