"""Optimizers (ref: python/mxnet/optimizer/optimizer.py).

Same registry + Updater architecture as the reference: `Optimizer.create`
by lowercase name, per-index state dicts, lr/wd multipliers, multi-precision
(fp32 master weights for fp16/bf16 params), and an `Updater` that owns the
states and is picklable (that is what the reference ships to KVStore servers
via set_optimizer). The update math itself runs as fused XLA ops
(ops/optimizer_ops.py) — the analog of the reference's engine-pushed
optimizer kernels (src/operator/optimizer_op.cc).
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from ..base import MXNetError, get_dtype
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd
from .. import ndarray as nd

__all__ = ["Optimizer", "Updater", "get_updater", "create", "register"]


class Optimizer:
    """Base optimizer (ref: optimizer.py — Optimizer)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise ValueError("Cannot find optimizer %s" % name)
        return Optimizer.opt_registry[name.lower()](**kwargs)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.sym_info = ()
        del sym
        self.param_dict = param_dict if param_dict else {}
        self.lr_mult = {}
        self.wd_mult = {}

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for low-precision weights
        (ref: optimizer.py — create_state_multi_precision)."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype in (np.float16,
                                                     get_dtype("bfloat16")):
            weight_master_copy = weight.astype("float32")
            return (weight_master_copy, self.create_state(
                index, weight_master_copy))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) and \
                isinstance(state[0], NDArray) and \
                state[0].dtype == np.float32 and weight.dtype != np.float32:
            weight_master, inner_state = state
            grad32 = grad.astype("float32")
            self.update(index, weight_master, grad32, inner_state)
            weight._set_data(weight_master.data.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = not (n.endswith("_weight") or n.endswith("_gamma"))
            if is_weight and (n.endswith("_bias") or n.endswith("_beta")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = []
        for index in indices:
            mult = 1.0
            if index in self.param_dict:
                mult = self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                mult = self.lr_mult[index]
            elif index in self.idx2name:
                mult = self.lr_mult.get(self.idx2name[index], 1.0)
            lrs.append(lr * mult)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = []
        for index in indices:
            wd = self.wd
            if index in self.param_dict:
                wd *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wd *= self.wd_mult[index]
            elif index in self.idx2name:
                wd *= self.wd_mult.get(self.idx2name[index], 1.0)
            wds.append(wd)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        ret["lr_scheduler"] = self.lr_scheduler
        return ret


register = Optimizer.register
create = Optimizer.create_optimizer


def _is_row_sparse(grad):
    return getattr(grad, "stype", "default") == "row_sparse"


def _common(self, index):
    """(lr, wd) honoring multipliers + update count bump."""
    self._update_count(index)
    return self._get_lr(index), self._get_wd(index)


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision
    (ref: optimizer.py — SGD; op: sgd_update/sgd_mom_update/mp_*)."""

    sparse_capable = True  # has a row_sparse update path

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd.zeros(weight.shape, dtype=weight.dtype)

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (np.float16,
                                                     get_dtype("bfloat16")):
            w32 = weight.astype("float32")
            mom = _nd.zeros(weight.shape, dtype="float32") \
                if self.momentum != 0.0 else None
            return (mom, w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype in (
            np.float16, get_dtype("bfloat16"))
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)

    def _update_impl(self, index, weight, grad, state, multi_precision):
        lr, wd = _common(self, index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient)
        if _is_row_sparse(grad) and not self.lazy_update:
            # std_update semantics (ref: sgd lazy_update=False): ALL rows
            # see wd/momentum decay every step — densify and fall through
            grad = grad.todense()
        if _is_row_sparse(grad):
            # lazy-update semantics: only touched rows (incl. their
            # momentum) change — ref: _sparse_sgd_(mom_)update
            from .. import sparse as _sp
            ckw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient or -1.0)
            if state is not None and not multi_precision:
                _sp.sparse_sgd_mom_update(weight, grad, state,
                                          momentum=self.momentum, **ckw)
            elif state is not None and multi_precision:
                mom, w32 = state
                if mom is not None:
                    _sp.sparse_sgd_mom_update(w32, grad, mom,
                                              momentum=self.momentum, **ckw)
                else:
                    _sp.sparse_sgd_update(w32, grad, **ckw)
                weight._set_data(w32.data.astype(weight.data.dtype))
            else:
                _sp.sparse_sgd_update(weight, grad, **ckw)
            return
        if not multi_precision:
            if state is not None:
                nd.sgd_mom_update(weight, grad, state, momentum=self.momentum,
                                  **kw)
            else:
                nd.sgd_update(weight, grad, lazy_update=self.lazy_update, **kw)
        else:
            mom, w32 = state
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32,
                                     momentum=self.momentum, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, **kw)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref: optimizer.py — NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient)
        if state is not None:
            nd.nag_mom_update(weight, grad, state, momentum=self.momentum,
                              **kw)
        else:
            nd.sgd_update(weight, grad, **kw)


@register
class Adam(Optimizer):
    """Adam (ref: optimizer.py — Adam; op: adam_update)."""

    sparse_capable = True  # has a row_sparse update path

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype=weight.dtype),   # mean
                _nd.zeros(weight.shape, dtype=weight.dtype))   # var

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        t = self._index_update_count[index]
        # bias correction folded into lr (reference does the same)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        if _is_row_sparse(grad) and not self.lazy_update:
            grad = grad.todense()  # std_update: decay every row's m/v
        if _is_row_sparse(grad):
            from .. import sparse as _sp
            _sp.sparse_adam_update(
                weight, grad, mean, var, lr=lr, beta1=self.beta1,
                beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient or -1.0, t=None)
            return
        nd.adam_update(weight, grad, mean, var, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient,
                       lazy_update=self.lazy_update)


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay
    (ref: src/operator/contrib/adamw.cc — contrib adamw_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype=weight.dtype),
                _nd.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        nd.adamw_update(weight, grad, mean, var, lr=lr, wd=wd, eta=1.0,
                        beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=self.clip_gradient)


@register
class AdaGrad(Optimizer):
    """AdaGrad (ref: optimizer.py — AdaGrad; python-side update in the
    reference too)."""

    sparse_capable = True  # has a row_sparse update path

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        if _is_row_sparse(grad):
            from .. import sparse as _sp
            _sp.sparse_adagrad_update(
                weight, grad, state, lr=lr, epsilon=self.float_stable_eps,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient or -1.0)
            return
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        state += grad * grad
        weight -= lr * grad / ((state ** 0.5) + self.float_stable_eps)


@register
class RMSProp(Optimizer):
    """RMSProp, plain (Tieleman) or centered (Graves)
    (ref: optimizer.py — RMSProp)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_nd.zeros(weight.shape, dtype=weight.dtype),  # n
                    _nd.zeros(weight.shape, dtype=weight.dtype),  # g
                    _nd.zeros(weight.shape, dtype=weight.dtype))  # delta
        return _nd.zeros(weight.shape, dtype=weight.dtype)        # n

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        kw = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                  rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient,
                  clip_weights=self.clip_weights)
        if not self.centered:
            nd.rmsprop_update(weight, grad, state, **kw)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  gamma2=self.gamma2, **kw)


@register
class AdaDelta(Optimizer):
    """AdaDelta (ref: optimizer.py — AdaDelta; python-side update)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype=weight.dtype),  # acc_g
                _nd.zeros(weight.shape, dtype=weight.dtype))  # acc_delta

    def update(self, index, weight, grad, state):
        _, wd = _common(self, index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * grad * grad).data)
        current_delta = ((acc_delta + self.epsilon) ** 0.5) / \
            ((acc_g + self.epsilon) ** 0.5) * grad
        acc_delta._set_data(
            (self.rho * acc_delta
             + (1 - self.rho) * current_delta * current_delta).data)
        weight -= current_delta + wd * weight


@register
class Ftrl(Optimizer):
    """FTRL-proximal (ref: optimizer.py — Ftrl; op: ftrl_update)."""

    sparse_capable = True  # has a row_sparse update path

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype=weight.dtype),  # z
                _nd.zeros(weight.shape, dtype=weight.dtype))  # n

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        z, n = state
        if _is_row_sparse(grad):
            from .. import sparse as _sp
            _sp.sparse_ftrl_update(
                weight, grad, z, n, lr=lr, lamda1=self.lamda1,
                beta=self.beta, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient or -1.0)
            return
        nd.ftrl_update(weight, grad, z, n, lr=lr, wd=wd, lamda1=self.lamda1,
                       beta=self.beta, rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient)


@register
class Signum(Optimizer):
    """Sign-momentum SGD (ref: optimizer.py — Signum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient)
        if state is not None:
            nd.signum_update(weight, grad, state, momentum=self.momentum,
                             wd_lh=self.wd_lh, **kw)
        else:
            nd.signsgd_update(weight, grad, **kw)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (ref: optimizer.py — SGLD)."""

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        noise = nd.normal(loc=0, scale=math.sqrt(lr),
                          shape=weight.shape, dtype=weight.dtype)
        weight -= lr / 2 * (grad + wd * weight) - noise


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py — DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_nd.zeros(weight.shape, dtype=weight.dtype), weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight + self.lamda * grad * grad *
                       (weight - previous_weight))
        if mom is not None:
            mom *= self.momentum
            mom += delta
            delta = mom
        previous_weight._set_data(weight.data)
        weight += delta


@register
class LAMB(Optimizer):
    """Layerwise-adaptive large-batch optimizer
    (ref: optimizer.py — LAMB [≥1.6]; ops lamb_update_phase1/2)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype="float32"),
                _nd.zeros(weight.shape, dtype="float32"))

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        t = self._index_update_count[index]
        mean, var = state
        from ..ops.registry import apply_op

        res = apply_op("lamb_update_phase1", weight, grad, mean, var,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t,
                       bias_correction=self.bias_correction, wd=wd,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient)
        g_update, mean_new, var_new = res
        mean._set_data(mean_new.data)
        var._set_data(var_new.data)
        r1 = weight.astype("float32").norm()
        r2 = g_update.norm()
        w_new = apply_op("lamb_update_phase2", weight, g_update, r1, r2,
                         lr=lr,
                         lower_bound=self.lower_bound
                         if self.lower_bound is not None else -1.0,
                         upper_bound=self.upper_bound
                         if self.upper_bound is not None else -1.0)
        weight._set_data(w_new.data)


@register
class Test(Optimizer):
    """Trivial optimizer used by the reference's own unit tests
    (ref: optimizer.py — Test)."""

    def create_state(self, index, weight):
        return _nd.zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._set_data(weight.data)


@register
class FTML(Optimizer):
    """Follow-the-moving-leader (ref: optimizer.py — FTML; op ftml_update)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_nd.zeros(weight.shape, dtype=weight.dtype),  # d
                _nd.zeros(weight.shape, dtype=weight.dtype),  # v
                _nd.zeros(weight.shape, dtype=weight.dtype))  # z

    def update(self, index, weight, grad, state):
        lr, wd = _common(self, index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        d, v, z = state
        v._set_data((self.beta2 * v + (1 - self.beta2) * grad * grad).data)
        d_t = (1 - self.beta1 ** t) / lr * \
            ((v / (1 - self.beta2 ** t)) ** 0.5 + self.epsilon)
        sigma_t = d_t - self.beta1 * d
        z._set_data((self.beta1 * z + (1 - self.beta1) * grad
                     - sigma_t * weight).data)
        d._set_data(d_t.data)
        weight._set_data((-z / d_t).data)


# alias names matching the reference registry
ccSGD = SGD
Optimizer.opt_registry["ccsgd"] = SGD


class Updater:
    """Holds per-index optimizer states and applies updates
    (ref: optimizer.py — Updater; this object is what KVStore serializes to
    servers via set_optimizer)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = False

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices = [index]
            grads = [grad]
            weights = [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            if _is_row_sparse(g) and not getattr(
                    self.optimizer, "sparse_capable", False):
                raise MXNetError(
                    "optimizer %s does not support row_sparse gradients; "
                    "use sgd, adam, adagrad, or ftrl (ref: the reference's "
                    "sparse update kernels cover the same set)"
                    % type(self.optimizer).__name__)
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def get_states(self, dump_optimizer=False):
        """Serialize states (+ optionally the optimizer itself) to bytes
        (ref: optimizer.py — Updater.get_states)."""

        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return tuple(to_np(x) for x in s)
            return s

        states = {i: to_np(s) for i, s in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer))
        return pickle.dumps(states)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2 and \
                isinstance(data[1], Optimizer):
            states, self.optimizer = data
        else:
            states = data

        def to_nd(s):
            if isinstance(s, np.ndarray):
                return _nd.array(s, dtype=s.dtype)
            if isinstance(s, tuple):
                return tuple(to_nd(x) for x in s)
            return s

        self.states = {i: to_nd(s) for i, s in states.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)


def get_updater(optimizer):
    return Updater(optimizer)
