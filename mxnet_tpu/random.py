"""Stateful-seed facade over JAX's functional PRNG.

The reference exposes a global stateful RNG (`mx.random.seed`, per-device
states handed to kernels via ResourceRequest::kRandom — ref: src/resource.cc,
python/mxnet/random.py). JAX PRNG is explicit-key. Bridge: a process-global
key that random ops split from. Inside a traced computation (hybridized
block / jitted step) the key must be an *input*, so a context manager lets
the tracer install a traced base key; random ops then derive per-call keys
with a fold_in counter, keeping the trace deterministic w.r.t. the input key.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "new_key", "key_scope", "current_seed", "get_state",
           "set_state"]


class _RandState(threading.local):
    def __init__(self):
        super().__init__()
        self.key = None
        self.seed_ = None
        # stack of (traced_key, counter_list) installed by tracing scopes
        self.scopes = []
        # autograd replay plumbing (higher-order grad): capture_keys
        # records every key handed out inside a recorded op; replay_keys
        # re-serves the recorded keys so a tape replay reproduces the
        # original stochastic forward bit-for-bit
        self.captures = []
        self.replays = []


_state = _RandState()
_DEFAULT_SEED = 0


def seed(seed_state, ctx="all"):
    """Set the global seed (ref: python/mxnet/random.py — seed()).

    ``ctx`` accepted for API parity; JAX keys are device-agnostic.
    """
    del ctx
    _state.seed_ = int(seed_state)
    _state.key = jax.random.key(int(seed_state))


def current_seed():
    return _state.seed_ if _state.seed_ is not None else _DEFAULT_SEED


def get_state():
    """JSON-serializable snapshot of the global PRNG: the seed AND the
    evolved key (the key advances by split on every new_key() draw, so
    the seed alone cannot reproduce mid-run state). Checkpointing rides
    this (resilience.CheckpointManager)."""
    import numpy as np

    key = _state.key
    data = None
    if key is not None:
        data = np.asarray(jax.random.key_data(key),
                          dtype=np.uint32).tolist()
    return {"seed": _state.seed_, "key_data": data}


def set_state(state):
    """Restore a get_state() snapshot (inverse operation)."""
    import numpy as np

    _state.seed_ = state.get("seed")
    data = state.get("key_data")
    if data is not None:
        _state.key = jax.random.wrap_key_data(
            np.asarray(data, dtype=np.uint32))
    elif _state.seed_ is not None:
        _state.key = jax.random.key(int(_state.seed_))
    else:
        _state.key = None


class key_scope:
    """Install a (possibly traced) base key for random ops in this scope.

    Used by CachedOp/hybridize: the jitted wrapper takes a key argument and
    random ops inside the trace fold a call counter into it.
    """

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _state.scopes.append([self._key, 0])
        return self

    def __exit__(self, *args):
        _state.scopes.pop()


def in_key_scope() -> bool:
    return bool(_state.scopes)


class capture_keys:
    """Record every key new_key() hands out in this scope (autograd's
    record path uses this so create_graph replays are deterministic)."""

    def __init__(self, store):
        self._store = store

    def __enter__(self):
        _state.captures.append(self._store)
        return self._store

    def __exit__(self, *args):
        _state.captures.pop()


class replay_keys:
    """Serve pre-recorded keys from new_key() (tape replay)."""

    def __init__(self, keys):
        self._keys = keys

    def __enter__(self):
        _state.replays.append([self._keys, 0])
        return self

    def __exit__(self, *args):
        _state.replays.pop()


def new_key():
    """Produce a fresh PRNG key for one random op call."""
    if _state.replays:
        entry = _state.replays[-1]
        keys, i = entry
        if i >= len(keys):
            raise RuntimeError(
                "tape replay drew more PRNG keys than the recorded forward")
        entry[1] += 1
        return keys[i]
    if _state.scopes:
        scope = _state.scopes[-1]
        k = jax.random.fold_in(scope[0], scope[1])
        scope[1] += 1
    else:
        if _state.key is None:
            _state.key = jax.random.key(_DEFAULT_SEED)
        _state.key, k = jax.random.split(_state.key)
    if _state.captures:
        _state.captures[-1].append(k)
    return k


def __getattr__(name):  # PEP 562
    """Functional sampling API (ref: python/mxnet/random.py re-exports
    the ndarray.random samplers as mx.random.uniform/normal/...)."""
    _samplers = ("uniform", "normal", "randn", "randint", "gamma",
                 "exponential", "poisson", "negative_binomial",
                 "multinomial", "shuffle", "bernoulli")
    if name in _samplers:
        from .ndarray import random as _ndr

        return getattr(_ndr, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
