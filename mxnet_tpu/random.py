"""Stateful-seed facade over JAX's functional PRNG.

The reference exposes a global stateful RNG (`mx.random.seed`, per-device
states handed to kernels via ResourceRequest::kRandom — ref: src/resource.cc,
python/mxnet/random.py). JAX PRNG is explicit-key. Bridge: a process-global
key that random ops split from. Inside a traced computation (hybridized
block / jitted step) the key must be an *input*, so a context manager lets
the tracer install a traced base key; random ops then derive per-call keys
with a fold_in counter, keeping the trace deterministic w.r.t. the input key.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "new_key", "key_scope", "current_seed"]


class _RandState(threading.local):
    def __init__(self):
        super().__init__()
        self.key = None
        self.seed_ = None
        # stack of (traced_key, counter_list) installed by tracing scopes
        self.scopes = []


_state = _RandState()
_DEFAULT_SEED = 0


def seed(seed_state, ctx="all"):
    """Set the global seed (ref: python/mxnet/random.py — seed()).

    ``ctx`` accepted for API parity; JAX keys are device-agnostic.
    """
    del ctx
    _state.seed_ = int(seed_state)
    _state.key = jax.random.key(int(seed_state))


def current_seed():
    return _state.seed_ if _state.seed_ is not None else _DEFAULT_SEED


class key_scope:
    """Install a (possibly traced) base key for random ops in this scope.

    Used by CachedOp/hybridize: the jitted wrapper takes a key argument and
    random ops inside the trace fold a call counter into it.
    """

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _state.scopes.append([self._key, 0])
        return self

    def __exit__(self, *args):
        _state.scopes.pop()


def in_key_scope() -> bool:
    return bool(_state.scopes)


def new_key():
    """Produce a fresh PRNG key for one random op call."""
    if _state.scopes:
        scope = _state.scopes[-1]
        k = jax.random.fold_in(scope[0], scope[1])
        scope[1] += 1
        return k
    if _state.key is None:
        _state.key = jax.random.key(_DEFAULT_SEED)
    _state.key, sub = jax.random.split(_state.key)
    return sub


def __getattr__(name):  # PEP 562
    """Functional sampling API (ref: python/mxnet/random.py re-exports
    the ndarray.random samplers as mx.random.uniform/normal/...)."""
    _samplers = ("uniform", "normal", "randn", "randint", "gamma",
                 "exponential", "poisson", "negative_binomial",
                 "multinomial", "shuffle", "bernoulli")
    if name in _samplers:
        from .ndarray import random as _ndr

        return getattr(_ndr, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
