"""StreamingDataLoader — the consumer face of the pod-scale data plane
(ref: ImageRecordIter/io.DataIter usage: ``for batch in it`` with
``batch.data``/``batch.label``, rebuilt over the chunk-leased worker
fleet instead of a per-process cursor).

One loader per host. Per epoch it:

1. derives the deterministic chunk partition from the shared
   (manifest, seed, epoch) and installs it in the lease ledger
   (idempotent — whichever host gets there first wins, the rest join);
2. restores its checkpoint cursor, if any, so a resumed host skips the
   chunks it already consumed (no loss, no duplication — the data twin
   of PR 8's step cursor, riding ``CheckpointManager.save(extra=...)``);
3. starts the decode-worker fleet and yields :class:`StreamBatch`es,
   stamping the time it spends WAITING on the fleet's buffer as the
   ``data_wait`` phase span (telemetry + goodput pick it up through the
   existing tap) plus a host-labeled seconds counter so ``mxt_top`` and
   the fleet collector attribute input-boundness per host.

The feed path into the device stays sync-free: batches convert to
NDArrays with one device put each and optionally ride the existing
:class:`~mxnet_tpu.gluon.data.dataloader._DevicePrefetcher` so batch
N+1's H2D transfer overlaps the step running on batch N.
"""
from __future__ import annotations

import time

from ..base import MXNetError
from .ledger import ChunkLedger
from .workers import DecodeWorkerFleet

__all__ = ["StreamingDataLoader", "StreamBatch"]


class StreamBatch:
    """One streamed batch: ``data``/``label`` NDArrays plus provenance
    (which chunk produced it and the (shard, key) record ids inside) —
    the provenance is what the exactly-once tests and the event-log
    trainer (ROADMAP 4) consume."""

    __slots__ = ("data", "label", "ids", "chunk_id")

    def __init__(self, data, label, ids, chunk_id):
        self.data = data
        self.label = label
        self.ids = ids
        self.chunk_id = chunk_id


class StreamingDataLoader:
    """Multi-host streaming loader over a :class:`ShardManifest`.

    ``ledger`` is shared: the in-process :class:`ChunkLedger` default
    serves one host (or N in-process hosts in tests); pass a
    :class:`~.ledger.RemoteLedger` to share the coordinator's ledger
    over the authenticated async transport. ``host_id``/``num_hosts``
    default from the launch line (``MXT_WORKER_ID``/``MXT_NUM_WORKERS``
    — the same topology ``MXT_MESH_SHAPE`` rides in on), so the same
    script streams on 1 host or a pod with zero new configuration.
    """

    def __init__(self, manifest, batch_size, decoder, host_id=None,
                 num_hosts=None, ledger=None, seed=0, start_epoch=0,
                 num_workers=None, buffer_batches=None, steal=None,
                 prefetch_to_device=False, to_device=True):
        from .. import config

        self.manifest = manifest
        self.batch_size = int(batch_size)
        self.decoder = decoder
        self.host = int(config.get("MXT_WORKER_ID")
                        if host_id is None else host_id)
        self.num_hosts = int(config.get("MXT_NUM_WORKERS")
                             if num_hosts is None else num_hosts)
        if self.host >= self.num_hosts:
            raise MXNetError(
                "host_id %d out of range for %d hosts"
                % (self.host, self.num_hosts))
        self.ledger = ledger if ledger is not None else ChunkLedger()
        self.seed = int(seed)
        self.epoch = int(start_epoch)
        self._num_workers = num_workers
        self._buffer_batches = buffer_batches
        self._steal = steal
        self._prefetch_to_device = bool(prefetch_to_device)
        self._to_device = bool(to_device)
        self._resume_cursor = None
        self.fleet = None  # live fleet of the epoch being iterated
        # consumer-side consumption bookkeeping: which chunks this host
        # has FULLY yielded, and how many batches of the in-flight ones
        self._consumed = {}   # chunk_id -> batches yielded
        self._complete = set()
        self._skip = {}       # chunk_id -> batches to drop on resume

    # -- checkpoint cursor -------------------------------------------------
    def _chunk_batches(self, chunk_id):
        n = self.manifest.chunk_records_of(chunk_id)
        return (n + self.batch_size - 1) // self.batch_size

    def cursor(self):
        """JSON-serializable mid-epoch cursor — pass to
        ``CheckpointManager.save(extra=loader.cursor())`` next to the
        step cursor. It tracks CONSUMER-side consumption (what this
        host's training loop actually received), not the ledger's
        decode-side commits: ``committed`` chunks were fully yielded and
        are never re-decoded on resume; a ``partial`` chunk is
        re-decoded (chunk contents are a pure function of the epoch
        coordinates) and its first N batches are dropped, so the resumed
        stream continues sample-exact — no loss, no duplication."""
        partial = {str(c): n for c, n in self._consumed.items()
                   if c not in self._complete and n > 0}
        return {"manifest_id": self.manifest.manifest_id,
                "epoch": self.epoch, "seed": self.seed,
                "committed": sorted(self._complete),
                "partial": partial}

    def restore_cursor(self, cursor):
        """Arm a checkpoint cursor: the next epoch iteration re-installs
        its epoch, pre-commits its fully-consumed chunks in the ledger,
        and drops the already-consumed head of the partial ones."""
        if cursor:
            if str(cursor.get("manifest_id")) != self.manifest.manifest_id:
                raise MXNetError(
                    "data-plane cursor manifest %r does not match this "
                    "loader's manifest %r"
                    % (cursor.get("manifest_id"),
                       self.manifest.manifest_id))
            self._resume_cursor = dict(cursor)
            self.epoch = int(cursor["epoch"])
            self.seed = int(cursor.get("seed", self.seed))
        return self

    # CheckpointManager-style aliases (PR 2/8 trainer protocol naming)
    save_states = cursor
    load_states = restore_cursor

    def stats(self):
        return self.ledger.stats()

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        return self._epoch_iter()

    def _begin_epoch(self):
        owners = self.manifest.owners(self.epoch, self.num_hosts,
                                      self.seed)
        committed = ()
        self._consumed = {}
        self._complete = set()
        self._skip = {}
        cur = self._resume_cursor
        if cur is not None and int(cur.get("epoch", -1)) == self.epoch:
            committed = [int(c) for c in cur.get("committed", ())]
            self._complete = set(committed)
            self._consumed = {c: self._chunk_batches(c)
                              for c in committed}
            self._skip = {int(c): int(n)
                          for c, n in cur.get("partial", {}).items()}
            # partial chunks resume their consumption count at the
            # skip point so completion still triggers at the true tail
            self._consumed.update(self._skip)
            self._resume_cursor = None
        self.ledger.begin_epoch(self.manifest.manifest_id, self.epoch,
                                owners, committed=committed)
        if committed:
            # peers may have installed the epoch first (begin_epoch is
            # first-wins) — merge the cursor into the live table too
            self.ledger.restore({"manifest_id": self.manifest.manifest_id,
                                 "epoch": self.epoch,
                                 "committed": list(committed)})

    def _device_batches(self, fleet):
        from ..ndarray import ndarray as _nd

        for data, labels, ids, cid in fleet.batches():
            if self._to_device:
                yield (_nd.array(data, dtype=data.dtype),
                       _nd.array(labels, dtype=labels.dtype), ids, cid)
            else:
                yield (data, labels, ids, cid)

    def _epoch_iter(self):
        from .. import telemetry

        self._begin_epoch()
        fleet = DecodeWorkerFleet(
            self.manifest, self.ledger, self.host, self.decoder,
            self.batch_size, epoch=self.epoch, seed=self.seed,
            num_workers=self._num_workers,
            buffer_batches=self._buffer_batches, steal=self._steal)
        self.fleet = fleet
        wait_counter = telemetry.counter(
            "mxt_data_wait_seconds_total",
            "Seconds the consumer spent blocked on the data plane "
            "(per-host data_wait attribution).",
            ("host",)).labels(str(self.host))
        base = self._device_batches(fleet.start())
        if self._prefetch_to_device and self._to_device:
            from ..gluon.data.dataloader import _DevicePrefetcher

            base = _DevicePrefetcher(base, 2, True)
        it = iter(base)
        n = 0
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    data, labels, ids, cid = next(it)
                except StopIteration:
                    return
                skip = self._skip.get(cid, 0)
                if skip > 0:
                    # resume replay: this chunk's head was consumed
                    # before the checkpoint — drop the re-decoded copy
                    # (decode is deterministic, so what follows is the
                    # sample-exact continuation)
                    self._skip[cid] = skip - 1
                    continue
                got = self._consumed.get(cid, 0) + 1
                self._consumed[cid] = got
                if got >= self._chunk_batches(cid):
                    self._complete.add(cid)
                n += 1
                dt = time.perf_counter() - t0
                telemetry.record_phase("data_wait", dt,
                                       stream="data_plane", step=n)
                wait_counter.inc(dt)
                yield StreamBatch(data, labels, ids, cid)
        finally:
            fleet.close()
            if not fleet.killed and not fleet.fenced:
                self.epoch += 1
