"""Pod-scale streaming data plane (ROADMAP 3 — the MXNet 1.x data stack
``ImageRecordIter`` / ``io.DataIter`` over recordio shards, rebuilt
TPU-native and multi-host).

The per-process iterator tops out around ~850 img/s per host core while
one chip needs multiples of that — at mesh scale input is the ceiling,
and PR 9's goodput accounting bills the loss as ``data_wait``. This
package replaces the per-process cursor with a leased, stealable chunk
keyspace:

- :class:`~.manifest.ShardManifest` — recordio shards sliced into
  deterministic chunks, partitioned across the mesh's hosts from the
  launch-line topology (``MXT_NUM_WORKERS``/``MXT_MESH_SHAPE``) with an
  epoch-seeded shuffle; chunk contents are a pure function of
  (manifest, seed, epoch), never of the decoding host.
- :class:`~.ledger.ChunkLedger` — exactly-once chunk consumption via
  lease generations (PR 10 ring-epoch style fencing: a zombie host's
  stale commit is refused typed), host fencing that reclaims a dead
  host's chunks for survivors, and cross-host work stealing; shared
  in-process or over the authenticated async transport
  (``data_lease``/``data_steal``/``data_cursor`` ops,
  :class:`~.ledger.RemoteLedger`).
- :class:`~.workers.DecodeWorkerFleet` — ``MXT_DATA_WORKERS`` decode
  threads per host feeding a bounded buffer (backpressure, bytes in
  the HBM ledger's ``prefetch`` pool).
- :class:`~.loader.StreamingDataLoader` — the ``for batch in loader``
  face, stamping per-host ``data_wait`` and carrying a mid-epoch
  checkpoint cursor (``CheckpointManager.save(extra=loader.cursor())``).
"""
from .ledger import ChunkLedger, RemoteLedger, StaleLeaseError
from .loader import StreamBatch, StreamingDataLoader
from .manifest import Chunk, ShardManifest
from .workers import ArrayDecoder, DecodeWorkerFleet, ImageDecoder

__all__ = [
    "ShardManifest", "Chunk", "ChunkLedger", "RemoteLedger",
    "StaleLeaseError", "DecodeWorkerFleet", "ImageDecoder",
    "ArrayDecoder", "StreamingDataLoader", "StreamBatch",
]
