"""Per-host decode-worker fleet — the threaded decode engine of the
streaming data plane (ref: src/io/iter_image_recordio_2.cc's
preprocess_threads + src/io/iter_prefetcher.h's bounded ThreadedIter,
rebuilt around chunk leases instead of a per-process cursor).

``MXT_DATA_WORKERS`` threads per host each run the same loop:

    lease a chunk from the host's own partition
      → (dry) steal from the reclaim pool / the slowest live peer
      → decode the chunk's records into batches (host-side numpy —
        the one layer of this system that is SUPPOSED to touch host
        memory; JPEG decode releases the GIL, so threads scale)
      → COMMIT the chunk (exactly-once point — a stale lease is
        refused typed and the batches are dropped, never fed)
      → enqueue the batches into the host's bounded buffer

The buffer is the backpressure boundary: ``MXT_DATA_BUFFER_BATCHES``
bounds how far decode may run ahead of the consumer, its resident bytes
are accounted in the diagnostics HBM ledger's ``prefetch`` pool (shape
metadata only, never a device read), and a full buffer blocks the
workers instead of OOMing the host. The consumer side
(:class:`~.loader.StreamingDataLoader`) stamps the time it spends
waiting on this queue as the ``data_wait`` phase span — goodput
accounting and ``mxt_top`` attribute input-boundness per host from it.

Decoding is deterministic by construction: a chunk's record order and
augmentation draws derive from (manifest, seed, epoch, chunk) — never
from the host or worker that runs it — so work stealing moves bytes,
not numerics.

Chaos hooks (seeded ``MXT_FAULT`` rules):

- ``data_host_kill:host=I[,after=K]`` — host I's fleet dies at its
  K-th chunk-commit boundary: workers stop, the host fences itself in
  the ledger (standing in for the membership reaper), survivors steal
  the reclaimed chunks.
- ``data_worker_slow:host=I,ms=N`` — host I's decode slows by N ms per
  chunk (steal bait: peers should pick up its tail).
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as np

from ..base import MXNetError
from ..membership import StaleWorkerError
from .manifest import _chunk_seed

__all__ = ["DecodeWorkerFleet", "ImageDecoder", "ArrayDecoder"]

_EOS = object()  # end-of-stream sentinel: last exiting worker enqueues it


# --------------------------------------------------------------------------
# record decoders
# --------------------------------------------------------------------------
class ImageDecoder:
    """JPEG/PNG image record decoder + augmenter — the hot subset of
    ImageRecordIter's pipeline (resize, rand_crop, rand_mirror, crop to
    data_shape, mean/std normalization), emitted straight into a
    preallocated batch slot. ``data_shape`` stays (C, H, W) in both
    layouts, like the reference API."""

    def __init__(self, data_shape, rand_crop=False, rand_mirror=False,
                 resize=-1, mean=None, std=None, layout="NHWC",
                 dtype="float32"):
        if layout not in ("NCHW", "NHWC"):
            raise MXNetError("ImageDecoder layout must be NCHW or NHWC, "
                             "got %r" % (layout,))
        self.data_shape = tuple(data_shape)
        self.rand_crop = bool(rand_crop)
        self.rand_mirror = bool(rand_mirror)
        self.resize = int(resize)
        self.layout = layout
        self.dtype = np.dtype(dtype)
        self.mean = None if mean is None \
            else np.array(mean, dtype=np.float32)
        self.std = None if std is None else np.array(std, dtype=np.float32)
        if self.dtype == np.uint8 and (mean is not None or std is not None):
            raise MXNetError("dtype='uint8' emits raw pixels; normalize "
                             "on device instead of passing mean/std")

    @property
    def sample_shape(self):
        c, h, w = self.data_shape
        return (c, h, w) if self.layout == "NCHW" else (h, w, c)

    @property
    def sample_dtype(self):
        return self.dtype

    def decode(self, raw, slot, rng):
        """Decode one record into ``slot`` (a view into the batch
        buffer); returns the label. Host-side numpy by design — this IS
        the worker boundary the data plane exists to parallelize.

        With a ``resize`` target the JPEG is decoded in DRAFT mode:
        libjpeg's DCT-domain 1/2 / 1/4 / 1/8 scaling decodes straight to
        the smallest power-of-two scale still >= the target, then the
        remaining factor is a cheap bilinear resize — a 2-4x decode
        saving on ImageNet-shaped records vs the per-process iterator's
        full-resolution decode + resize (this is where the
        ``streaming_input_ab`` bench's per-core win comes from; at
        scale 1 the bytes match the non-draft path exactly)."""
        import io as _io

        from PIL import Image

        from ..io.io import _crop, _resize_short
        from ..recordio import unpack

        header, payload = unpack(raw)
        pil = Image.open(_io.BytesIO(payload))
        if self.resize > 0:
            pil.draft("RGB", (self.resize, self.resize))
        pil = pil.convert("RGB")
        img = np.asarray(pil)  # sync-ok: PIL decode, host numpy by design
        if self.resize > 0 and min(img.shape[0], img.shape[1]) \
                != self.resize:
            img = _resize_short(img, self.resize)
        c, h, w = self.data_shape
        img = _crop(img, h, w, rand=self.rand_crop, rng=rng)
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1, :]
        if self.layout == "NCHW":
            slot[...] = np.transpose(img, (2, 0, 1))
        else:
            slot[...] = img
        if self.mean is not None or self.std is not None:
            mean = 0.0 if self.mean is None else self.mean
            std = 1.0 if self.std is None else self.std
            if self.layout == "NCHW":
                slot -= np.reshape(mean, (-1, 1, 1))
                slot /= np.reshape(std, (-1, 1, 1))
            else:
                slot -= mean
                slot /= std
        label = header.label
        if isinstance(label, np.ndarray):
            label = float(label[0])  # sync-ok: host numpy label scalar
        return label


class ArrayDecoder:
    """Raw-array record decoder: the payload is ``shape`` of ``dtype``
    bytes (no image codec) — the cheap path for tests and non-vision
    records packed with :func:`~mxnet_tpu.recordio.pack`."""

    def __init__(self, shape, dtype="float32"):
        self.sample_shape = tuple(shape)
        self.sample_dtype = np.dtype(dtype)

    def decode(self, raw, slot, rng):
        del rng
        from ..recordio import unpack

        header, s = unpack(raw)
        slot[...] = np.frombuffer(
            s, dtype=self.sample_dtype).reshape(self.sample_shape)
        label = header.label
        if isinstance(label, np.ndarray):
            label = float(label[0])  # sync-ok: host numpy label scalar
        return label


# --------------------------------------------------------------------------
# per-host telemetry (host-labeled so the fleet collector's merged page
# attributes input-boundness per host with zero extra wiring)
# --------------------------------------------------------------------------
def _host_metrics(host):
    from .. import telemetry

    lbl = str(int(host))
    return {
        "records": telemetry.counter(
            "mxt_data_records_total",
            "Records decoded by the data-plane worker fleet.",
            ("host",)).labels(lbl),
        "bytes": telemetry.counter(
            "mxt_data_bytes_total",
            "Decoded batch bytes produced by the data-plane fleet.",
            ("host",)).labels(lbl),
        "chunks": telemetry.counter(
            "mxt_data_chunks_total",
            "Chunks committed by this host.", ("host",)).labels(lbl),
        "steals": telemetry.counter(
            "mxt_data_steals_total",
            "Chunks this host stole from peers (dry lease queue).",
            ("host",)).labels(lbl),
        "stale": telemetry.counter(
            "mxt_data_stale_leases_total",
            "Chunk commits refused as stale (zombie lease generations).",
            ("host",)).labels(lbl),
        "depth": telemetry.gauge(
            "mxt_data_queue_depth",
            "Decoded batches buffered ahead of the consumer.",
            ("host",)).labels(lbl),
        "rate": telemetry.gauge(
            "mxt_data_records_per_second",
            "Decode throughput of this host's worker fleet (epoch "
            "running average).", ("host",)).labels(lbl),
    }


class DecodeWorkerFleet:
    """N decode workers feeding one host's bounded batch buffer."""

    def __init__(self, manifest, ledger, host_id, decoder, batch_size,
                 epoch=0, seed=0, num_workers=None, buffer_batches=None,
                 steal=None):
        from .. import config

        self.manifest = manifest
        self.ledger = ledger
        self.host = int(host_id)
        self.decoder = decoder
        self.batch_size = int(batch_size)
        self.epoch = int(epoch)
        self.seed = int(seed)
        if self.batch_size > manifest.chunk_records:
            raise MXNetError(
                "batch_size %d exceeds chunk_records %d — batches never "
                "cross a chunk boundary (that is what makes stolen "
                "chunks decode bit-identically)"
                % (self.batch_size, manifest.chunk_records))
        self.num_workers = int(num_workers if num_workers is not None
                               else config.get("MXT_DATA_WORKERS"))
        depth = int(buffer_batches if buffer_batches is not None
                    else config.get("MXT_DATA_BUFFER_BATCHES"))
        self.steal_enabled = bool(config.get("MXT_DATA_STEAL")
                                  if steal is None else steal)
        self._q = _queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._threads = []
        self._live = 0
        self._wids = set()      # worker ids currently running (resize())
        self._commits = 0       # chunks this fleet committed
        self._records = 0
        self._buffered_bytes = 0
        self._t0 = None
        self.killed = False     # data_host_kill fired
        self.fenced = False     # a commit came back stale — we are dead
        self._errors = []       # worker exceptions, re-raised to consumer
        self._hbm_key = "data-plane-h%d-%x" % (self.host, id(self))
        self._m = _host_metrics(self.host)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._threads:
            return self
        self._t0 = time.perf_counter()
        self._live = self.num_workers
        self._wids = set(range(self.num_workers))
        for wid in range(self.num_workers):
            t = threading.Thread(
                target=self._run, args=(wid,), daemon=True,
                name="data-decode-h%d-w%d" % (self.host, wid))
            self._threads.append(t)
            t.start()
        return self

    def live_workers(self):
        """Worker threads currently decoding (retired, dead, and
        not-yet-started workers excluded) — the autoscaler's 'did the
        last resize land' signal."""
        with self._lock:
            return len(self._wids)

    def resize(self, n):
        """Grow or shrink the decode-worker fleet in place.

        Growing spawns the missing worker ids immediately; shrinking is
        cooperative — surplus workers (``wid >= n``) retire at their
        next chunk boundary, so a shrink never abandons a leased chunk
        mid-decode (the commit still lands, the batches still feed).
        ``n < 1`` refuses typed: a host keeps at least one decode
        worker while it lives (``close()`` is how a fleet stops)."""
        n = int(n)
        if n < 1:
            raise MXNetError(
                "DecodeWorkerFleet.resize(%d): a live host keeps at "
                "least one decode worker — use close() to stop the "
                "fleet" % (n,))
        with self._lock:
            self.num_workers = n
            if not self._threads or self._stop.is_set():
                return self  # not started yet: start() spawns n
            spawn = [wid for wid in range(n) if wid not in self._wids]
            for wid in spawn:
                self._wids.add(wid)
                self._live += 1
        for wid in spawn:
            t = threading.Thread(
                target=self._run, args=(wid,), daemon=True,
                name="data-decode-h%d-w%d" % (self.host, wid))
            self._threads.append(t)
            t.start()
        return self

    def kill(self):
        """Simulate this host's death at a chunk boundary: stop the
        workers and fence the host in the ledger (what the membership
        reaper's death listener does for a real dead process) so
        survivors reclaim its unconsumed chunks."""
        self.killed = True
        self._stop.set()
        try:
            self.ledger.fence_host(self.host)
        except (MXNetError, OSError, ConnectionError):
            pass  # a truly dead host wouldn't manage to fence itself

    def close(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        from .. import diagnostics

        diagnostics.hbm_release("prefetch", self._hbm_key)
        self._m["depth"].set(0)

    # -- chaos hooks -------------------------------------------------------
    def _chaos(self):
        """Consult the seeded fault rules at the chunk boundary; returns
        True when this host just died (data_host_kill)."""
        from .. import resilience

        inj = resilience.fault_point()
        rule = inj.rule("data_host_kill")
        if rule is not None \
                and int(rule.get("host", -1)) == self.host \
                and self._commits >= int(rule.get("after", 0)) \
                and inj.should("data_host_kill"):
            self.kill()
            return True
        rule = inj.rule("data_worker_slow")
        if rule is not None and int(rule.get("host", -1)) == self.host \
                and inj.should("data_worker_slow"):
            ms = float(rule.get("ms", 20.0))  # sync-ok: fault-rule scalar
            time.sleep(ms / 1e3)
        return False

    # -- worker loop -------------------------------------------------------
    def _run(self, wid):
        readers = {}
        try:
            while not self._stop.is_set():
                if wid >= self.num_workers:
                    return  # retired by resize(): shrink lands at a
                    # chunk boundary, never mid-decode
                if self._chaos():
                    return
                try:
                    grants = self.ledger.lease(self.host, 1)
                    stolen = False
                    if not grants and self.steal_enabled:
                        grants = self.ledger.steal(self.host, 1)
                        stolen = bool(grants)
                except StaleWorkerError:
                    self.fenced = True
                    self._m["stale"].inc()
                    return
                if not grants:
                    if self.ledger.finished():
                        return
                    # everything left is leased to live peers: poll —
                    # a late death can still reclaim work for us
                    self._stop.wait(0.005)
                    continue
                if stolen:
                    self._m["steals"].inc(len(grants))
                for grant in grants:
                    self._process(grant[0], grant[1], readers)
                    if self._stop.is_set():
                        return
        except BaseException as e:  # noqa: BLE001 — re-raised in batches()
            # a dead worker must not silently truncate the epoch: the
            # consumer re-raises this instead of ending cleanly
            self._errors.append(e)
            self._stop.set()
        finally:
            for r in readers.values():
                r.close()
            with self._lock:
                self._live -= 1
                self._wids.discard(wid)
                last = self._live <= 0
            if last:
                # wake the consumer immediately instead of letting it
                # discover the drained fleet on a poll timeout; bounded
                # put so a full buffer under a stopped consumer cannot
                # wedge the worker (the poll fallback still ends the
                # stream then)
                try:
                    self._q.put(_EOS, timeout=0.05)
                except _queue.Full:
                    pass

    def _process(self, chunk_id, token, readers):
        chunk = self.manifest.epoch_chunk(chunk_id, self.epoch, self.seed)
        reader = readers.get(chunk.shard_id)
        if reader is None:
            reader = readers[chunk.shard_id] = \
                self.manifest.open_reader(chunk.shard_id)
        # augmentation draws: a pure function of the chunk coordinates,
        # consumed sequentially over the chunk's records — the thief
        # reproduces the owner's batches bit for bit
        rng = np.random.RandomState(_chunk_seed(
            self.manifest.manifest_id, self.seed, self.epoch, chunk_id,
            tag="augment"))
        bs = self.batch_size
        batches = []
        keys = chunk.keys
        for lo in range(0, len(keys), bs):
            part = keys[lo:lo + bs]
            data = np.empty((len(part),) + tuple(self.decoder.sample_shape),
                            self.decoder.sample_dtype)
            labels = np.empty((len(part),), np.float32)
            ids = []
            for j, key in enumerate(part):
                raw = reader.read_idx(key)
                labels[j] = self.decoder.decode(raw, data[j], rng)
                ids.append((chunk.shard_id, key))
            batches.append((data, labels, ids, chunk.chunk_id))
        # commit BEFORE enqueue: the exactly-once point. If the commit
        # comes back stale this host was fenced (or the chunk re-leased
        # to a thief) — feeding the batches anyway would duplicate the
        # new leaseholder's work, so they are dropped on the floor.
        try:
            self.ledger.commit(self.host, chunk.chunk_id, token)
        except StaleWorkerError:
            self.fenced = True
            self._m["stale"].inc()
            self._stop.set()
            return
        self._commits += 1
        self._m["chunks"].inc()
        nrec = len(keys)
        nbytes = sum(d.nbytes + lab.nbytes for d, lab, _, _ in batches)
        self._m["records"].inc(nrec)
        self._m["bytes"].inc(nbytes)
        with self._lock:
            self._records += nrec
            dt = time.perf_counter() - self._t0
        if dt > 0:
            self._m["rate"].set(self._records / dt)
        for b in batches:
            self._put(b)
            if self._stop.is_set():
                return

    # -- bounded buffer (the backpressure boundary) ------------------------
    def _publish_bytes(self):
        from .. import diagnostics

        diagnostics.hbm_set("prefetch", self._hbm_key,
                            self._buffered_bytes)
        self._m["depth"].set(self._q.qsize())

    def _put(self, batch):
        data, labels, _, _ = batch
        while not self._stop.is_set():
            try:
                self._q.put(batch, timeout=0.05)
                break
            except _queue.Full:
                continue  # backpressure: decode blocks, never OOMs
        else:
            return
        with self._lock:
            self._buffered_bytes += data.nbytes + labels.nbytes
        self._publish_bytes()

    def batches(self):
        """Consumer side: yield (data, labels, ids, chunk_id) until the
        epoch is globally finished and this host's buffer drained."""
        while True:
            try:
                batch = self._q.get(timeout=0.02)
            except _queue.Empty:
                with self._lock:
                    workers_done = self._live <= 0
                if workers_done and self._q.empty():
                    batch = _EOS
                else:
                    continue
            if batch is _EOS:
                if self._errors and not self.killed and not self.fenced:
                    raise MXNetError(
                        "data-plane decode worker died: %r"
                        % (self._errors[0],)) from self._errors[0]
                return
            data, labels, _, _ = batch
            with self._lock:
                self._buffered_bytes -= data.nbytes + labels.nbytes
            self._publish_bytes()
            yield batch
