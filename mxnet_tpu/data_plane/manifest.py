"""Shard manifest — the recordio keyspace of a streaming epoch
(ref: src/io/iter_image_recordio_2.cc — ImageRecordIOParser2's
InputSplit over .rec shards; dmlc InputSplit::Create partitions byte
ranges, here the partition unit is a *chunk* of indexed records).

A :class:`ShardManifest` describes a dataset as a list of indexed
recordio shard files and slices their record keys into fixed-size
**chunks** — the unit of lease, steal, and batch formation for the
multi-host data plane:

- **Chunks are static.** Chunk ``i`` always covers the same consecutive
  run of keys inside one shard (sequential read locality), regardless
  of epoch. Only the *visit order* of chunks and the *record order
  inside* each chunk are epoch-shuffled.

- **Chunk contents are a pure function of (manifest, seed, epoch).**
  ``epoch_chunk(cid, epoch, seed)`` derives its intra-chunk permutation
  from a blake2b hash of (manifest_id, seed, epoch, cid) — NOT from the
  identity of the host or worker that decodes it. Work stealing can
  therefore move a chunk between hosts without changing a single byte
  of the batches it produces: bit-identical batch contents whether the
  owner or a thief decodes it (the acceptance property the end-to-end
  test pins).

- **Partitioning needs zero user configuration.** ``owners()`` deals
  the epoch-shuffled chunk order round-robin across the mesh's hosts;
  the host count defaults from the launch line (``MXT_NUM_WORKERS`` /
  ``MXT_MESH_SHAPE`` are both exported by tools/launch.py), so the same
  script streams on 1 host or a pod.

Batches never cross a chunk boundary, so ``chunk_records`` should be a
multiple of the batch size (a tail chunk may still be short — it yields
one short final batch, the reference's ``round_batch=False`` shape).
"""
from __future__ import annotations

import glob as _glob
import hashlib
import os
from collections import namedtuple

import numpy as np

from ..base import MXNetError

__all__ = ["ShardManifest", "Chunk"]

#: One leasable unit of work: a run of record keys inside one shard.
#: ``keys`` is already in the epoch's intra-chunk visit order when the
#: chunk came from :meth:`ShardManifest.epoch_chunk`.
Chunk = namedtuple("Chunk", ["chunk_id", "shard_id", "keys"])


def _chunk_seed(manifest_id, seed, epoch, chunk_id=None, tag="order"):
    """Deterministic 31-bit seed from the (manifest, seed, epoch[, chunk])
    coordinates — host/worker identity never enters, so a stolen chunk
    decodes bit-identically on the thief. ``tag`` separates the streams
    (chunk-order shuffle vs intra-chunk order vs augmentation draws)."""
    h = hashlib.blake2b(digest_size=4)
    h.update(manifest_id.encode("utf-8"))
    h.update(b"|%s|%d|%d" % (tag.encode("utf-8"), int(seed), int(epoch)))
    if chunk_id is not None:
        h.update(b"|%d" % int(chunk_id))
    return int.from_bytes(h.digest(), "little") & 0x7FFFFFFF


class ShardManifest:
    """The record keyspace of a recordio-backed dataset, chunked.

    ``shards`` is a list of ``.rec`` paths (the ``.idx`` sidecar path is
    derived by extension swap) or ``(rec_path, idx_path)`` pairs. Every
    shard must be indexed — random seek is what lets a chunk start
    mid-shard and a rejoined host resume mid-epoch.
    """

    def __init__(self, shards, chunk_records=None):
        from ..recordio import MXIndexedRecordIO

        if not shards:
            raise MXNetError("ShardManifest needs at least one shard")
        if chunk_records is None:
            from .. import config

            chunk_records = int(config.get("MXT_DATA_CHUNK_RECORDS"))
        if chunk_records < 1:
            raise MXNetError("chunk_records must be >= 1, got %d"
                             % chunk_records)
        self.chunk_records = int(chunk_records)
        self.shards = []
        for s in shards:
            if isinstance(s, (tuple, list)):
                rec, idx = s
            else:
                rec = s
                idx = os.path.splitext(s)[0] + ".idx"
            if not os.path.isfile(idx):
                raise MXNetError(
                    "shard %r has no index sidecar %r — the data plane "
                    "needs indexed shards (tools/im2rec.py writes them)"
                    % (rec, idx))
            r = MXIndexedRecordIO(idx, rec, "r")
            keys = tuple(r.keys)
            r.close()
            if not keys:
                raise MXNetError("shard %r is empty" % (rec,))
            self.shards.append({"rec": rec, "idx": idx, "keys": keys})
        # static chunk table: consecutive key runs per shard
        self._chunks = []
        for sid, sh in enumerate(self.shards):
            keys = sh["keys"]
            for lo in range(0, len(keys), self.chunk_records):
                self._chunks.append(
                    (sid, keys[lo:lo + self.chunk_records]))
        self.manifest_id = self._fingerprint()

    @classmethod
    def from_glob(cls, pattern, chunk_records=None):
        """Manifest over every ``.rec`` matching ``pattern`` (sorted, so
        all hosts derive the identical shard order from a shared path)."""
        recs = sorted(_glob.glob(pattern))
        if not recs:
            raise MXNetError("no recordio shards match %r" % (pattern,))
        return cls(recs, chunk_records=chunk_records)

    def _fingerprint(self):
        """Stable id over shard basenames + record counts + chunking —
        hosts sharing a lease ledger must agree on the chunk table, and
        a mismatched manifest is refused typed at ``begin_epoch``."""
        h = hashlib.blake2b(digest_size=8)
        for sh in self.shards:
            h.update(os.path.basename(sh["rec"]).encode("utf-8"))
            h.update(b"|%d;" % len(sh["keys"]))
        h.update(b"c%d" % self.chunk_records)
        return h.hexdigest()

    # -- sizes -------------------------------------------------------------
    @property
    def num_records(self):
        return sum(len(sh["keys"]) for sh in self.shards)

    @property
    def num_chunks(self):
        return len(self._chunks)

    def record_ids(self):
        """Every (shard_id, key) in the manifest — the exactly-once
        assertion's ground truth."""
        return [(sid, k) for sid, sh in enumerate(self.shards)
                for k in sh["keys"]]

    # -- epoch plan --------------------------------------------------------
    def epoch_order(self, epoch, seed=0):
        """The epoch's global chunk visit order (seeded shuffle) —
        identical on every host."""
        order = np.arange(self.num_chunks)
        rng = np.random.RandomState(
            _chunk_seed(self.manifest_id, seed, epoch))
        rng.shuffle(order)
        return [int(c) for c in order]

    def epoch_chunk(self, chunk_id, epoch, seed=0):
        """The chunk with its intra-chunk record order shuffled for this
        epoch — a pure function of the coordinates, never of the decoding
        host."""
        sid, keys = self._chunks[int(chunk_id)]
        idx = np.arange(len(keys))
        rng = np.random.RandomState(
            _chunk_seed(self.manifest_id, seed, epoch, chunk_id))
        rng.shuffle(idx)
        return Chunk(int(chunk_id), sid, tuple(keys[i] for i in idx))

    def owners(self, epoch, num_hosts, seed=0):
        """Deterministic host partition: the epoch-shuffled chunk order
        dealt round-robin over ``num_hosts``. Every host computes the
        same table from the shared (manifest, seed, epoch), so the lease
        ledger's ``begin_epoch`` is idempotent across hosts."""
        if num_hosts < 1:
            raise MXNetError("num_hosts must be >= 1, got %d" % num_hosts)
        order = self.epoch_order(epoch, seed)
        table = {h: [] for h in range(num_hosts)}
        for i, cid in enumerate(order):
            table[i % num_hosts].append(cid)
        return table

    def chunk_records_of(self, chunk_id):
        """Record count of one chunk (only the tail chunk of a shard may
        be short)."""
        return len(self._chunks[int(chunk_id)][1])

    # -- shard IO ----------------------------------------------------------
    def open_reader(self, shard_id):
        """Fresh indexed reader for one shard. One handle per (worker,
        shard) — neither the Python reader nor the native FILE* is safe
        to share across seeking threads. The handles pickle cleanly
        (recordio ``__getstate__``), which is how process-based decode
        workers would receive them."""
        from ..recordio import MXIndexedRecordIO

        sh = self.shards[int(shard_id)]
        return MXIndexedRecordIO(sh["idx"], sh["rec"], "r")
