"""Chunk lease ledger — exactly-once chunk consumption for the
streaming data plane (the sharding half of ps-lite's scheduler role,
applied to input instead of parameters).

One :class:`ChunkLedger` is the authoritative lease table for an epoch:
every chunk of the :class:`~.manifest.ShardManifest` is consumed by
EXACTLY ONE host, even across host deaths, work stealing, and zombie
retries. The fencing design mirrors PR 10's embedding ring epoch:

- **Lease generations.** Every lease/steal hands out a fresh monotone
  token. A commit must present the token of the chunk's CURRENT lease;
  a commit carrying a superseded token — the chunk was reclaimed from a
  fenced host and re-leased to a thief — is refused with a typed
  :class:`StaleLeaseError` (a :class:`StaleWorkerError` subclass, so it
  rides the async transport's existing ``stale`` reply and surfaces
  typed on the zombie's side).

- **Host fencing.** ``fence_host`` (driven by the membership reaper's
  death listener when the ledger is attached to an
  :class:`~mxnet_tpu.async_server.AsyncParamServer`, or directly in
  tests) moves the dead host's pending AND leased-uncommitted chunks
  into a reclaim pool that any dry peer may steal from; everything the
  dead host already committed stays committed — zero loss, zero
  duplication.

- **Work stealing.** A host whose own partition ran dry steals from the
  reclaim pool first, then from the *slowest* live peer (the one with
  the most pending chunks), popping from the TAIL of the victim's queue
  (the work it would reach last).

- **At-least-once transport safety.** The async client retries frames;
  a commit replayed with the SAME token is acknowledged idempotently.

The ledger is shared either in-process (single host / tests) or over
the authenticated async-server transport via ``attach_data_plane`` —
the ``data_lease`` / ``data_steal`` / ``data_cursor`` ops all dispatch
to :meth:`ChunkLedger.handle`, retry-wrapped under ``kv_retry`` on the
client side like every other kvstore op.
"""
from __future__ import annotations

import threading
from collections import deque

from ..base import MXNetError
from ..membership import StaleWorkerError

__all__ = ["ChunkLedger", "RemoteLedger", "StaleLeaseError"]


class StaleLeaseError(StaleWorkerError):
    """A chunk commit arrived under a superseded lease generation or
    from a fenced host: the chunk was (or will be) consumed by its
    current leaseholder, so applying this commit would double-count or
    lose samples. The zombie must drop the chunk's batches."""


class ChunkLedger:
    """Thread-safe chunk lease/commit table for one epoch at a time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._key = None          # (manifest_id, epoch)
        self._pending = {}        # host -> deque(chunk_id)
        self._reclaim = deque()   # chunks reclaimed from fenced hosts
        self._lease = {}          # chunk_id -> (host, token)
        self._done = {}           # chunk_id -> token it committed under
        self._owner0 = {}         # chunk_id -> original owner (stats)
        self._fenced = set()      # fenced host ids
        self._token = 0           # monotone lease-generation counter
        self._total = 0
        self._steals = 0
        self._stales = 0

    # -- epoch lifecycle ---------------------------------------------------
    def begin_epoch(self, manifest_id, epoch, owners, committed=()):
        """Install the epoch's chunk partition. Idempotent and
        first-caller-wins: every host derives the same ``owners`` table
        from the shared (manifest, seed, epoch), so later callers just
        join the epoch in progress. A DIFFERENT manifest for the same
        epoch is a typed error (the hosts disagree about the dataset).
        ``committed`` pre-marks chunks a resumed host's checkpoint
        cursor already consumed — they are never re-leased."""
        key = (str(manifest_id), int(epoch))
        with self._lock:
            if self._key == key:
                return False  # epoch already installed — join it
            if self._key is not None and self._key[0] != key[0] \
                    and self._key[1] == key[1]:
                raise MXNetError(
                    "data-plane manifest mismatch for epoch %d: ledger "
                    "holds %r, begin_epoch got %r — hosts disagree about "
                    "the dataset" % (key[1], self._key[0], key[0]))
            self._key = key
            self._pending = {}
            self._reclaim = deque()
            self._lease = {}
            self._done = {}
            self._owner0 = {}
            self._fenced = set()
            self._steals = 0
            self._stales = 0
            done = set(int(c) for c in committed)
            total = 0
            for host, cids in owners.items():
                q = deque()
                for cid in cids:
                    cid = int(cid)
                    total += 1
                    self._owner0[cid] = int(host)
                    if cid in done:
                        self._done[cid] = -1  # committed before resume
                    else:
                        q.append(cid)
                self._pending[int(host)] = q
            self._total = total
            return True

    def _require_epoch_locked(self):
        if self._key is None:
            raise MXNetError(
                "data-plane ledger has no epoch — call begin_epoch first")

    # -- lease / steal -----------------------------------------------------
    def lease(self, host, n=1):
        """Up to ``n`` chunks from ``host``'s own partition queue.
        Returns ``[(chunk_id, token)]`` (empty when the queue is dry)."""
        host = int(host)
        out = []
        with self._lock:
            self._require_epoch_locked()
            if host in self._fenced:
                raise StaleLeaseError(
                    "host %d is fenced — it must rejoin before leasing "
                    "data chunks" % host)
            q = self._pending.get(host)
            while q and len(out) < int(n):
                cid = q.popleft()
                self._token += 1
                self._lease[cid] = (host, self._token)
                out.append((cid, self._token))
        return out

    def steal(self, thief, n=1):
        """Up to ``n`` chunks for a dry host: the reclaim pool (fenced
        hosts' work) first, then the tail of the slowest live peer's
        queue. Returns ``[(chunk_id, token, victim_host)]`` — victim is
        ``-1`` for reclaimed chunks."""
        thief = int(thief)
        out = []
        with self._lock:
            self._require_epoch_locked()
            if thief in self._fenced:
                raise StaleLeaseError(
                    "host %d is fenced — it must rejoin before stealing "
                    "data chunks" % thief)
            while len(out) < int(n):
                if self._reclaim:
                    cid = self._reclaim.popleft()
                    victim = -1
                else:
                    victim, q = None, None
                    for h, hq in self._pending.items():
                        if h == thief or h in self._fenced or not hq:
                            continue
                        if q is None or len(hq) > len(q):
                            victim, q = h, hq
                    if q is None:
                        break
                    cid = q.pop()  # tail: the work the victim reaches last
                self._token += 1
                self._lease[cid] = (thief, self._token)
                out.append((cid, self._token, victim))
            if out:
                self._steals += len(out)
        return out

    # -- commit (the cursor advance) ---------------------------------------
    def commit(self, host, chunk_id, token):
        """Mark ``chunk_id`` consumed under lease ``token``. Exactly-once:
        a replay with the same token is acknowledged idempotently; a
        superseded token or a fenced host is refused typed."""
        host, cid, token = int(host), int(chunk_id), int(token)
        with self._lock:
            self._require_epoch_locked()
            prev = self._done.get(cid)
            if prev is not None:
                if prev == token:
                    return False  # at-least-once replay of our own commit
                self._stales += 1
                raise StaleLeaseError(
                    "chunk %d was already committed under lease "
                    "generation %d — commit with generation %d is a "
                    "zombie replay" % (cid, prev, token))
            if host in self._fenced:
                self._stales += 1
                raise StaleLeaseError(
                    "host %d was fenced (declared dead); its commit of "
                    "chunk %d under lease generation %d is refused — the "
                    "chunk was reclaimed for the survivors"
                    % (host, cid, token))
            lease = self._lease.get(cid)
            if lease is None or lease != (host, token):
                self._stales += 1
                raise StaleLeaseError(
                    "chunk %d lease generation %d (host %d) is stale — "
                    "current lease is %r; the chunk belongs to its new "
                    "leaseholder" % (cid, token, host, lease))
            del self._lease[cid]
            self._done[cid] = token
            return True

    # -- fencing -----------------------------------------------------------
    def fence_host(self, host):
        """Declare ``host`` dead: its pending and leased-uncommitted
        chunks become stealable by survivors; its committed chunks stay
        committed. Any later lease/steal/commit from the fenced host is
        refused typed. Returns the number of chunks reclaimed."""
        host = int(host)
        with self._lock:
            if self._key is None or host in self._fenced:
                return 0
            self._fenced.add(host)
            n = 0
            q = self._pending.get(host)
            if q:
                while q:
                    self._reclaim.append(q.popleft())
                    n += 1
            for cid, (h, _tok) in list(self._lease.items()):
                if h == host:
                    # the lease entry stays until re-leased, but the
                    # chunk is back in the pool; the zombie's commit is
                    # refused by the fenced-host check either way
                    del self._lease[cid]
                    self._reclaim.append(cid)
                    n += 1
            return n

    # -- views -------------------------------------------------------------
    def cursor(self):
        """Serializable epoch cursor: which chunks are consumed. Rides
        CheckpointManager's ``extra`` payload (like PR 8's step cursor)
        so a restarted host resumes mid-epoch without loss or
        duplication."""
        with self._lock:
            self._require_epoch_locked()
            return {"manifest_id": self._key[0], "epoch": self._key[1],
                    "committed": sorted(self._done)}

    def restore(self, cursor):
        """Merge a checkpoint cursor's committed set into the current
        epoch (same manifest + epoch required, typed otherwise)."""
        with self._lock:
            self._require_epoch_locked()
            if (str(cursor.get("manifest_id")),
                    int(cursor.get("epoch", -1))) != self._key:
                raise MXNetError(
                    "data-plane cursor %r does not match the ledger "
                    "epoch %r" % (cursor, self._key))
            for cid in cursor.get("committed", ()):
                cid = int(cid)
                if cid in self._done:
                    continue
                self._done[cid] = -1
                self._lease.pop(cid, None)
                for q in self._pending.values():
                    try:
                        q.remove(cid)
                    except ValueError:
                        pass
        return self

    def stats(self):
        with self._lock:
            if self._key is None:
                return {"epoch": None}
            return {
                "manifest_id": self._key[0], "epoch": self._key[1],
                "total": self._total,
                "committed": len(self._done),
                "leased": len(self._lease),
                "reclaimable": len(self._reclaim),
                "pending": {h: len(q) for h, q in self._pending.items()},
                "fenced": sorted(self._fenced),
                "steals": self._steals,
                "stale_refused": self._stales,
            }

    def finished(self):
        """True when every chunk of the epoch is committed."""
        with self._lock:
            return self._key is not None and len(self._done) >= self._total

    def idle(self):
        """True when nothing is pending or reclaimable anywhere — the
        remaining work (if any) is leased to live hosts. A dry host
        polls instead of exiting: a late death can still hand it
        reclaimed chunks."""
        with self._lock:
            if self._key is None:
                return True
            return not self._reclaim and not any(
                q for h, q in self._pending.items()
                if h not in self._fenced)

    # -- wire dispatch (async_server attach_data_plane) --------------------
    def handle(self, op, key, payload):
        """One ``data_*`` frame → one reply tuple. StaleLeaseError
        propagates — the server answers it as a typed ``stale`` reply
        and the zombie's client raises StaleWorkerError."""
        del key
        if op == "data_epoch":
            manifest_id, epoch, owners, committed = payload
            fresh = self.begin_epoch(manifest_id, epoch, owners,
                                     committed=committed or ())
            return ("ok", fresh)
        elif op == "data_lease":
            host, n = payload
            return ("ok", self.lease(host, n))
        elif op == "data_steal":
            host, n = payload
            return ("ok", self.steal(host, n))
        elif op == "data_cursor":
            verb = payload[0]
            if verb == "commit":
                _, host, cid, token = payload
                return ("ok", self.commit(host, cid, token))
            elif verb == "get":
                return ("ok", self.cursor())
            elif verb == "restore":
                self.restore(payload[1])
                return ("ok", None)
            return ("err", "unknown data_cursor verb %r" % (verb,))
        elif op == "data_stats":
            return ("ok", self.stats())
        elif op == "data_fence":
            return ("ok", self.fence_host(payload))
        return ("err", "unknown data-plane op %r" % (op,))


class RemoteLedger:
    """Client adapter: the same lease/steal/commit surface as
    :class:`ChunkLedger`, spoken over an
    :class:`~mxnet_tpu.async_server.AsyncClient` to the coordinator's
    attached ledger. Every call rides ``AsyncClient.request`` — i.e.
    ``kv_retry`` with reconnect, bounded deadline, and the typed
    ``stale`` reply surfacing as :class:`StaleWorkerError`."""

    def __init__(self, client):
        self._c = client

    def begin_epoch(self, manifest_id, epoch, owners, committed=()):
        return self._c.request(
            "data_epoch", None,
            (manifest_id, int(epoch), owners, list(committed)))

    def lease(self, host, n=1):
        return self._c.request("data_lease", None, (int(host), int(n)))

    def steal(self, thief, n=1):
        return self._c.request("data_steal", None, (int(thief), int(n)))

    def commit(self, host, chunk_id, token):
        return self._c.request(
            "data_cursor", None,
            ("commit", int(host), int(chunk_id), int(token)))

    def cursor(self):
        return self._c.request("data_cursor", None, ("get",))

    def restore(self, cursor):
        self._c.request("data_cursor", None, ("restore", cursor))
        return self

    def stats(self):
        return self._c.request("data_stats")

    def fence_host(self, host):
        return self._c.request("data_fence", None, int(host))

    def finished(self):
        s = self.stats()
        return s.get("epoch") is not None \
            and s.get("committed", 0) >= s.get("total", 0)

    def idle(self):
        s = self.stats()
        if s.get("epoch") is None:
            return True
        fenced = set(s.get("fenced", ()))
        return not s.get("reclaimable", 0) and not any(
            n for h, n in s.get("pending", {}).items() if h not in fenced)

    def close(self):
        self._c.close()
