#!/usr/bin/env python
"""mxt_top — a curses-free live console over the telemetry subsystem.

Tails either the Prometheus exposition endpoint
(``MXT_TELEMETRY_PORT`` → ``--url http://127.0.0.1:PORT``) or the JSONL
event sink (``MXT_TELEMETRY_JSONL`` → ``--jsonl path``) and renders the
async-training health signals once per interval:

    steps/s            retired fused steps (delta of step-latency count)
    host_syncs/step    device->host reads per step (<= 1/K when healthy)
    launches/step      compiled dispatches per step (1.0 = fully fused)
    dispatch depth     in-flight fused steps right now
    kv rpc p50/p99     server-side KVStore/membership RPC latency
    workers live/lost  membership view
    skipped steps      non-finite guard skips
    xla compiles       backend compiles + persistent-cache hit/miss
                       (a warm-started replica shows hits only)
    tune cache         kernel-autotuner table hit/miss

and, when a GSPMD sharded step is live (mesh gauges present):

    mesh               device count, per-axis extents (all four on a
                       dp×tp×pp×ep mesh), ZeRO stage
    per-dev bytes      param/optimizer bytes held by ONE device (the
                       memory the ZeRO-1/2/3 ladder shrinks ~dp×)
    reshards           in-place elastic mesh reshards so far
    moe load           per-expert kept-token counts + over-capacity
                       drops (windowed publish_moe_telemetry reads)

and, when the process serves (mxnet_tpu/serving/ metrics present):

    serving tok/s      generated tokens per second
    queue depth        requests waiting for a batch slot (+ active/evicted)
    request p50/p99    decode-phase request latency quantiles
    kv pages           paged KV-cache occupancy vs pool capacity

and, when a fleet router is live (serving/fleet.py + router.py):

    fleet replicas     routable / total, draining + dead counts
    disp/hedge/fail    dispatches, hedged duplicates, failovers (plus
                       fenced-zombie replies refused typed)
    routed p50/p99     fleet-level request latency (submit -> commit)

and, when the autoscaler / QoS layer is live (serving/autoscaler.py +
serving/qos.py):

    autoscale          target replicas + up/down/refused decision
                       counts + the most recent decision direction
    tenant <name>      per-tenant admitted / rejected (over-quota) /
                       preempted / inflight

and, with ``--fleet`` (the telemetry_fleet.py collector's merged page —
member-labeled samples from every scraped fleet member):

    fleet members      live/stale member count + stale names
    fleet tok/s        tokens/s summed across every member
    occupancy          active decode slots per replica
    emb hit ratio      per-embedding-server cache hit ratio
    goodput min/mean   worst / average goodput across workers
    scrape age         seconds since each member's last good scrape

and, when the diagnostics layer publishes (mxnet_tpu/diagnostics.py):

    hbm <pool>         per-subsystem device bytes (params / optimizer /
                       kv_cache / inflight_window / prefetch) + peak
                       watermark — the HBM ledger
    goodput            productive fraction of wall-clock, with the top
                       lost-time causes (compile/checkpoint/reshard/
                       stall/data_wait)
    watchdog stalls    hang-watchdog stall reports so far

Usage::

    python tools/mxt_top.py --url http://127.0.0.1:9109
    python tools/mxt_top.py --jsonl telemetry.jsonl
    python tools/mxt_top.py --url ... --once        # one frame, no clear

Plain ANSI output (\\x1b[H\\x1b[J between frames) — works in any terminal
and under ``watch``/``tee``; no curses, no dependencies.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.request

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([0-9eE+.\-]+|NaN|\+Inf)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """{(name, frozenset(label items)): value} from exposition text."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels, value = m.groups()
        lab = dict(_LABEL_RE.findall(labels)) if labels else {}
        try:
            v = float(value)
        except ValueError:
            v = float("inf") if value == "+Inf" else float("nan")
        out[(name, frozenset(lab.items()))] = v
    return out


def metric_sum(samples, name, **match):
    """Sum of every sample of ``name`` whose labels include ``match``."""
    total, seen = 0.0, False
    want = set(match.items())
    for (n, lab), v in samples.items():
        if n == name and want <= set(lab):
            total += v
            seen = True
    return total if seen else None


def histogram_quantiles(samples, name, qs, **match):
    """Quantiles from ``name_bucket`` samples (cumulative counts summed
    over every labelset matching ``match``)."""
    want = set(match.items())
    per_le = {}
    for (n, lab), v in samples.items():
        if n != name + "_bucket":
            continue
        lab = dict(lab)
        le = lab.pop("le", None)
        if le is None or not want <= set(lab.items()):
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        per_le[bound] = per_le.get(bound, 0.0) + v
    if not per_le:
        return [None] * len(qs)
    bounds = sorted(per_le)
    cum = [per_le[b] for b in bounds]
    total = cum[-1]
    if total <= 0:
        return [None] * len(qs)
    out = []
    for q in qs:
        rank = q * total
        got = None
        for b, c in zip(bounds, cum):
            if c >= rank:
                got = b if b != float("inf") else bounds[-2] \
                    if len(bounds) > 1 else None
                break
        out.append(got)
    return out


def _fmt_s(v):
    if v is None:
        return "--"
    if v < 1e-3:
        return "%.0fus" % (v * 1e6)
    if v < 1.0:
        return "%.1fms" % (v * 1e3)
    return "%.2fs" % v


def _fmt(v, spec="%.2f"):
    return "--" if v is None else spec % v


def _fmt_b(v):
    """Human bytes for the per-device param/opt gauges."""
    if v is None:
        return "--"
    for unit in ("B", "KB", "MB", "GB"):
        if v < 1024 or unit == "GB":
            return ("%.0f%s" if unit == "B" else "%.1f%s") % (v, unit)
        v /= 1024.0


class EndpointSource:
    """Scrape --url (or MXT_TELEMETRY_PORT) once per frame."""

    def __init__(self, url):
        self.url = url if "://" in url else "http://" + url

    def sample(self):
        with urllib.request.urlopen(self.url, timeout=5) as r:
            return parse_prometheus(r.read().decode("utf-8"))


class JsonlSource:
    """Tail --jsonl and rebuild the same sample dict from span/rpc/
    metric rows (approximate: JSONL carries events, not the registry —
    the latest 'metrics' snapshot row supplies gauge/counter values)."""

    def __init__(self, path):
        self.path = path
        self._pos = 0
        self._steps = 0
        self._rpc_lat = []
        self._metrics = {}

    def sample(self):
        try:
            with open(self.path) as f:
                f.seek(self._pos)
                # readline(), not `for line in f`: tell() inside file
                # iteration raises OSError in text mode, which the
                # except below used to swallow — --jsonl mode silently
                # dropped every row
                while True:
                    line = f.readline()
                    if not line:
                        break
                    self._pos = f.tell()
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    kind = row.get("kind")
                    if kind == "span" and row.get("name") == "retire":
                        self._steps += 1
                    elif kind == "rpc_span" and \
                            row.get("side") == "server" and \
                            row.get("latency_s") is not None:
                        self._rpc_lat.append(row["latency_s"])
                        del self._rpc_lat[:-4096]
                    elif kind == "metrics":
                        self._metrics = row.get("data", {})
        except OSError:
            pass
        samples = {("mxt_step_latency_seconds_count", frozenset()):
                   float(self._steps)}
        for key, v in self._metrics.items():
            name, _, labpart = key.partition("{")
            if isinstance(v, dict):
                continue
            # snapshot keys carry unquoted labels (name{axis=data}):
            # surface them as real labels so label-matched sections
            # (mesh axes) render in --jsonl mode too; `src` keeps every
            # labelset distinct
            lab = [("src", key)]
            if labpart:
                lab += re.findall(r'(\w+)=([^,}]+)', labpart)
            samples[(name, frozenset(lab))] = float(v)
        if self._rpc_lat:
            lat = sorted(self._rpc_lat)

            def pick(q):
                return lat[min(len(lat) - 1, int(q * len(lat)))]

            samples[("_jsonl_rpc_p50", frozenset())] = pick(0.50)
            samples[("_jsonl_rpc_p99", frozenset())] = pick(0.99)
        return samples


def render(samples, prev, dt):
    def rate(name, **match):
        cur = metric_sum(samples, name, **match)
        old = metric_sum(prev, name, **match) if prev else None
        if cur is None or old is None or dt <= 0:
            return None, cur
        return max(0.0, cur - old) / dt, cur

    steps_rate, steps_total = rate("mxt_step_latency_seconds_count")
    syncs_rate, _ = rate("mxt_host_syncs_total")
    launch_rate, _ = rate("mxt_xla_launches_total")
    per_step = lambda r: None if (r is None or not steps_rate) \
        else r / steps_rate
    depth = metric_sum(samples, "dispatch_depth")
    p50, p99 = histogram_quantiles(
        samples, "mxt_kvstore_rpc_latency_seconds", (0.50, 0.99),
        side="server")
    if p50 is None:
        p50 = metric_sum(samples, "_jsonl_rpc_p50")
        p99 = metric_sum(samples, "_jsonl_rpc_p99")
    live = metric_sum(samples, "mxt_membership_live_workers")
    lost = metric_sum(samples, "lost_workers")
    skipped = metric_sum(samples, "skipped_nonfinite_steps")
    compiles = metric_sum(samples, "mxt_compiles_total")
    compile_s = metric_sum(samples, "mxt_compile_seconds_sum",
                           phase="compile")
    cc_hits = metric_sum(samples, "mxt_compile_cache_total", outcome="hit")
    cc_miss = metric_sum(samples, "mxt_compile_cache_total",
                         outcome="miss")
    tune_hits = metric_sum(samples, "mxt_tune_cache_hits_total")
    tune_miss = metric_sum(samples, "mxt_tune_cache_misses_total")

    # mesh / GSPMD section (mxnet_tpu/parallel/): only rendered when a
    # ShardedTrainStep has published its mesh gauges — a single-device
    # trainer or a pure server shows no mesh noise
    mesh_dev = metric_sum(samples, "mxt_mesh_devices")
    zero_stage = metric_sum(samples, "mxt_zero_stage")
    mesh_pbytes = metric_sum(samples, "mxt_per_device_param_bytes")
    mesh_obytes = metric_sum(samples, "mxt_per_device_opt_bytes")
    reshards = metric_sum(samples, "mxt_reshard_events_total")
    mesh_axes = []
    for (n, lab), v in sorted(samples.items()):
        if n == "mxt_mesh_axis_size":
            d = dict(lab)
            if "axis" in d:
                mesh_axes.append("%s=%d" % (d["axis"], int(v)))
    # MoE router accounting (parallel/unified.py): only rendered when a
    # PipelineMoEBlock's windowed publish has landed — dense trainers
    # show no expert noise
    moe_load = []
    for (n, lab), v in sorted(samples.items()):
        if n == "mxt_moe_expert_load":
            d = dict(lab)
            if "expert" in d:
                moe_load.append("e%s=%d" % (d["expert"], int(v)))
    moe_drops = metric_sum(samples, "mxt_moe_router_drops_total")

    # diagnostics section (mxnet_tpu/diagnostics.py): only rendered
    # when the HBM ledger / goodput ledger have published — a process
    # without the diagnostics layer shows no memory/goodput noise
    hbm_pools = {}
    hbm_peaks = {}
    for (n, lab), v in sorted(samples.items()):
        d = dict(lab)
        if "pool" in d:
            if n == "mxt_hbm_bytes":
                hbm_pools[d["pool"]] = v
            elif n == "mxt_hbm_peak_bytes":
                hbm_peaks[d["pool"]] = v
    goodput = metric_sum(samples, "mxt_goodput_ratio")
    lost_causes = []
    for (n, lab), v in samples.items():
        if n == "mxt_lost_seconds_total":
            d = dict(lab)
            if "cause" in d and v > 0:
                lost_causes.append((v, d["cause"]))
    lost_causes.sort(reverse=True)
    stalls = metric_sum(samples, "mxt_watchdog_stalls_total")

    # embedding section (mxnet_tpu/embedding/): only rendered when a
    # sharded embedding client has published its cache gauges — a dense
    # trainer or a server-only process shows no embedding noise
    emb_resident = metric_sum(samples, "mxt_embedding_rows_resident")
    emb_hits = metric_sum(samples, "mxt_embedding_cache_hits_total")
    emb_miss = metric_sum(samples, "mxt_embedding_cache_misses_total")
    emb_evict = metric_sum(samples, "mxt_embedding_cache_evictions_total")
    emb_ratio = None
    if emb_hits is not None or emb_miss is not None:
        total = (emb_hits or 0) + (emb_miss or 0)
        emb_ratio = (emb_hits or 0) / total if total else None
    emb_p50, emb_p99 = histogram_quantiles(
        samples, "mxt_embedding_pull_seconds", (0.50, 0.99))
    emb_bytes_rate, _ = rate("mxt_embedding_bytes_total")

    # fleet section (serving/fleet.py + serving/router.py): only
    # rendered when a fleet router has published replica-state gauges
    flt_states = {}
    for (n, lab), v in samples.items():
        if n == "mxt_fleet_replicas":
            d = dict(lab)
            if "state" in d:
                flt_states[d["state"]] = v
    flt_disp = metric_sum(samples, "mxt_fleet_dispatch_total")
    flt_hedge = metric_sum(samples, "mxt_fleet_hedges_total")
    flt_fail = metric_sum(samples, "mxt_fleet_failovers_total")
    flt_stale = metric_sum(samples, "mxt_fleet_stale_replies_total")
    flt_p50, flt_p99 = histogram_quantiles(
        samples, "mxt_fleet_request_latency_seconds", (0.50, 0.99))

    # fleet-SCOPE section (telemetry_fleet.py collector page, reached
    # via --fleet): only rendered when member-labeled samples are
    # present — i.e. the source is a merged fleet page, not a single
    # process's endpoint. Per-member breakdowns: serving occupancy,
    # embedding hit ratio, goodput min/mean, scrape age + staleness.
    fleet_members = sorted({dict(lab).get("member")
                            for (n, lab), v in samples.items()
                            if "member" in dict(lab)} - {None})
    fleet_stale = sorted({dict(lab).get("member")
                          for (n, lab), v in samples.items()
                          if dict(lab).get("stale") == "true"} - {None})
    fleet_tok_rate = fleet_occ = fleet_emb = fleet_good = None
    fleet_ages = {}
    if fleet_members:
        fleet_tok_rate, _ = rate("mxt_serving_tokens_total")
        # per-replica occupancy (summed over members — each replica's
        # gauge is published by exactly one pool), falling back to the
        # per-member active-request gauge for non-serving members
        occ_by_rep = {}
        for (n, lab), v in samples.items():
            if n == "mxt_fleet_replica_occupancy":
                d = dict(lab)
                if "replica" in d:
                    occ_by_rep[d["replica"]] = \
                        occ_by_rep.get(d["replica"], 0.0) + v
        if occ_by_rep:
            fleet_occ = ["r%s=%d" % (r, int(v))
                         for r, v in sorted(occ_by_rep.items())]
        else:
            fleet_occ = []
            for m in fleet_members:
                occ = metric_sum(samples,
                                 "mxt_serving_active_requests",
                                 member=m)
                if occ is not None:
                    fleet_occ.append("%s=%d" % (m, int(occ)))
        fleet_emb = []
        for m in fleet_members:
            h = metric_sum(samples, "mxt_embedding_cache_hits_total",
                           member=m)
            ms_ = metric_sum(samples, "mxt_embedding_cache_misses_total",
                             member=m)
            if h is None and ms_ is None:
                continue
            tot = (h or 0) + (ms_ or 0)
            if tot:
                fleet_emb.append("%s=%.3f" % (m, (h or 0) / tot))
        goods = [metric_sum(samples, "mxt_goodput_ratio", member=m)
                 for m in fleet_members]
        goods = [g for g in goods if g is not None]
        if goods:
            fleet_good = (min(goods), sum(goods) / len(goods))
        for m in fleet_members:
            age = metric_sum(samples, "mxt_fleet_scrape_age_seconds",
                             member=m)
            if age is not None:
                fleet_ages[m] = age

    # data-plane section (mxnet_tpu/data_plane/): only rendered when a
    # streaming loader's decode fleet has published its host-labeled
    # gauges — a per-process-iterator trainer shows no data noise.
    # Per-host rec/s + data_wait share is the input-boundness
    # attribution: the host whose wait share grows is the one starving.
    data_hosts = sorted({dict(lab).get("host")
                         for (n, lab), v in samples.items()
                         if n == "mxt_data_records_per_second"} - {None})
    data_steals = metric_sum(samples, "mxt_data_steals_total")
    data_stale = metric_sum(samples, "mxt_data_stale_leases_total")
    data_bytes_rate, _ = rate("mxt_data_bytes_total")
    data_rps = {h: metric_sum(samples, "mxt_data_records_per_second",
                              host=h) for h in data_hosts}
    data_q = {h: metric_sum(samples, "mxt_data_queue_depth", host=h)
              for h in data_hosts}
    data_wait = {h: rate("mxt_data_wait_seconds_total", host=h)[0]
                 for h in data_hosts}

    # serving section (mxnet_tpu/serving/): only rendered when the
    # process has served — a pure trainer shows no serving noise
    tok_rate, tok_total = rate("mxt_serving_tokens_total")
    srv_queue = metric_sum(samples, "mxt_serving_queue_depth")
    srv_active = metric_sum(samples, "mxt_serving_active_requests")
    srv_p50, srv_p99 = histogram_quantiles(
        samples, "mxt_serving_request_latency_seconds", (0.50, 0.99),
        phase="decode")
    pages_used = metric_sum(samples, "mxt_serving_kv_pages_in_use")
    pages_total = metric_sum(samples, "mxt_serving_kv_pages_total")
    evicted = metric_sum(samples, "mxt_serving_requests_total",
                         outcome="evicted")
    # speculative decode + quantized-page gauges (PR 12): rendered only
    # when the engine actually speculates / serves int8 pages
    spec_prop = metric_sum(samples,
                           "mxt_serving_spec_proposed_tokens_total")
    spec_acc = metric_sum(samples,
                          "mxt_serving_spec_accepted_tokens_total")
    quant_pages = metric_sum(samples,
                             "mxt_serving_kv_quant_pages_in_use")
    # shared-prefix reuse gauges (PR 16): rendered only when the engine
    # runs with prefix_cache=True (the counters exist only then)
    pfx_hits = metric_sum(samples, "mxt_serving_prefix_hits_total")
    pfx_miss = metric_sum(samples, "mxt_serving_prefix_misses_total")
    pfx_shared = metric_sum(samples, "mxt_serving_shared_pages")
    pfx_cow = metric_sum(samples, "mxt_serving_cow_copies_total")

    # autoscaler / QoS section (serving/autoscaler.py + qos.py): only
    # rendered when an autoscaler has stood up its target gauge or a
    # QoS policy has admitted per-tenant traffic — an unscaled,
    # single-tenant fleet shows no control-loop noise
    asc_target = metric_sum(samples, "mxt_autoscale_target_replicas")
    asc_events = {}
    asc_last = {}
    for (n, lab), v in samples.items():
        d = dict(lab)
        if "direction" not in d:
            continue
        if n == "mxt_autoscale_events_total":
            asc_events[d["direction"]] = \
                asc_events.get(d["direction"], 0.0) + v
        elif n == "mxt_autoscale_last_decision":
            # monotonic decision seq per direction: the max IS the
            # most recent decision
            asc_last[d["direction"]] = \
                max(asc_last.get(d["direction"], 0.0), v)
    asc_latest = max(asc_last, key=asc_last.get) if asc_last else None
    qos_tenants = sorted(
        {dict(lab).get("tenant") for (n, lab), v in samples.items()
         if n in ("mxt_tenant_admitted_total", "mxt_tenant_rejected_total",
                  "mxt_tenant_preempted_total",
                  "mxt_tenant_inflight_requests")
         and "tenant" in dict(lab)} - {None})

    # training-health section (mxnet_tpu/health.py): only rendered when
    # a HealthMonitor / rules engine has published — a process without
    # the health plane armed shows no training-health noise
    hl_ema = metric_sum(samples, "mxt_health_loss_ema")
    hl_skew = metric_sum(samples, "mxt_health_step_skew_ratio")
    hl_step_ms = metric_sum(samples, "mxt_health_host_step_ms")
    hl_anoms = []  # (count, kind, layer), top-3 by count
    hl_rules_ok, hl_rules_bad = [], []
    for (n, lab), v in sorted(samples.items()):
        d = dict(lab)
        if n == "mxt_health_anomalies_total" and "kind" in d:
            hl_anoms.append((v, d["kind"], d.get("layer", "?")))
        elif n == "mxt_health_rule_ok" and "rule" in d:
            (hl_rules_ok if v else hl_rules_bad).append(d["rule"])
    hl_anoms.sort(key=lambda r: (-r[0], r[1], r[2]))
    hl_present = (hl_ema is not None or hl_skew is not None
                  or hl_step_ms is not None or hl_anoms
                  or hl_rules_ok or hl_rules_bad)

    lines = [
        "mxt_top  %s" % time.strftime("%H:%M:%S"),
        "-" * 46,
        "  steps/s          %s   (total %s)"
        % (_fmt(steps_rate), _fmt(steps_total, "%.0f")),
        "  host_syncs/step  %s" % _fmt(per_step(syncs_rate), "%.3f"),
        "  launches/step    %s" % _fmt(per_step(launch_rate), "%.2f"),
        "  dispatch depth   %s" % _fmt(depth, "%.0f"),
        "  kv rpc p50/p99   %s / %s" % (_fmt_s(p50), _fmt_s(p99)),
        "  workers live     %s   lost %s"
        % (_fmt(live, "%.0f"), _fmt(lost, "%.0f")),
        "  skipped steps    %s" % _fmt(skipped, "%.0f"),
        "  xla compiles     %s   (%s)   cache %s/%s hit/miss"
        % (_fmt(compiles, "%.0f"), _fmt_s(compile_s),
           _fmt(cc_hits, "%.0f"), _fmt(cc_miss, "%.0f")),
        "  tune cache       %s/%s hit/miss"
        % (_fmt(tune_hits, "%.0f"), _fmt(tune_miss, "%.0f")),
    ]
    if mesh_dev is not None:
        lines += [
            "-" * 46,
            "  mesh             %s dev   %s   zero=%s"
            % (_fmt(mesh_dev, "%.0f"),
               " ".join(mesh_axes) if mesh_axes else "--",
               _fmt(zero_stage, "%.0f")),
            "  per-dev bytes    params %s   opt %s"
            % (_fmt_b(mesh_pbytes), _fmt_b(mesh_obytes)),
            "  reshards         %s" % _fmt(reshards, "%.0f"),
        ]
        if moe_load:
            lines.append(
                "  moe load         %s   drops=%s"
                % (" ".join(moe_load), _fmt(moe_drops, "%.0f")))
    if hbm_pools or goodput is not None:
        lines.append("-" * 46)
        for pool in sorted(hbm_pools):
            lines.append(
                "  hbm %-12s %s   (peak %s)"
                % (pool, _fmt_b(hbm_pools[pool]),
                   _fmt_b(hbm_peaks.get(pool))))
        if goodput is not None:
            top = ", ".join("%s %s" % (c, _fmt_s(v))
                            for v, c in lost_causes[:3]) or "none"
            lines.append("  goodput          %s   lost: %s"
                         % (_fmt(goodput, "%.3f"), top))
        if stalls:
            lines.append("  watchdog stalls  %s" % _fmt(stalls, "%.0f"))
    if emb_resident is not None or emb_ratio is not None:
        lines += [
            "-" * 46,
            "  emb rows res.    %s   hit ratio %s"
            % (_fmt(emb_resident, "%.0f"),
               _fmt(emb_ratio, "%.3f")),
            "  emb pull p50/p99 %s / %s   evicted %s"
            % (_fmt_s(emb_p50), _fmt_s(emb_p99),
               _fmt(emb_evict, "%.0f")),
            "  emb bytes/s      %s" % _fmt_b(emb_bytes_rate),
        ]
    if fleet_members:
        ages = ["%s %s" % (m, _fmt_s(fleet_ages[m]))
                for m in sorted(fleet_ages)]
        lines += [
            "-" * 46,
            "  fleet members    %d   stale: %s"
            % (len(fleet_members),
               ", ".join(fleet_stale) if fleet_stale else "none"),
            "  fleet tok/s      %s" % _fmt(fleet_tok_rate),
            "  occupancy        %s"
            % (" ".join(fleet_occ) if fleet_occ else "--"),
        ]
        if fleet_emb:
            lines.append("  emb hit ratio    %s" % " ".join(fleet_emb))
        if fleet_good is not None:
            lines.append("  goodput min/mean %.3f / %.3f" % fleet_good)
        if ages:
            lines.append("  scrape age       %s" % "  ".join(ages))
    if flt_states:
        lines += [
            "-" * 46,
            "  fleet replicas   %s routable / %s total   (drain %s "
            "dead %s)"
            % (_fmt(flt_states.get("routable", 0), "%.0f"),
               _fmt(sum(flt_states.values()), "%.0f"),
               _fmt(flt_states.get("draining", 0)
                    + flt_states.get("drained", 0), "%.0f"),
               _fmt(flt_states.get("dead", 0), "%.0f")),
            "  disp/hedge/fail  %s / %s / %s   stale refused %s"
            % (_fmt(flt_disp, "%.0f"), _fmt(flt_hedge, "%.0f"),
               _fmt(flt_fail, "%.0f"), _fmt(flt_stale, "%.0f")),
            "  routed p50/p99   %s / %s"
            % (_fmt_s(flt_p50), _fmt_s(flt_p99)),
        ]
    if data_hosts:
        lines += [
            "-" * 46,
            "  data rec/s       %s   bytes/s %s"
            % ("  ".join("h%s %s" % (h, _fmt(data_rps[h], "%.0f"))
                         for h in data_hosts),
               _fmt_b(data_bytes_rate)),
            "  data queue       %s   steals %s   stale refused %s"
            % ("  ".join("h%s %s" % (h, _fmt(data_q[h], "%.0f"))
                         for h in data_hosts),
               _fmt(data_steals, "%.0f"), _fmt(data_stale, "%.0f")),
            "  data_wait share  %s"
            % "  ".join("h%s %s" % (h, _fmt(data_wait[h], "%.3f"))
                        for h in data_hosts),
        ]
    if tok_total is not None:
        lines += [
            "-" * 46,
            "  serving tok/s    %s   (total %s)"
            % (_fmt(tok_rate), _fmt(tok_total, "%.0f")),
            "  queue depth      %s   active %s   evicted %s"
            % (_fmt(srv_queue, "%.0f"), _fmt(srv_active, "%.0f"),
               _fmt(evicted, "%.0f")),
            "  request p50/p99  %s / %s (decode)"
            % (_fmt_s(srv_p50), _fmt_s(srv_p99)),
            "  kv pages         %s / %s in use"
            % (_fmt(pages_used, "%.0f"), _fmt(pages_total, "%.0f")),
        ]
        if spec_prop:
            lines.append(
                "  spec accept      %s   (%s / %s draft tokens)"
                % (_fmt((spec_acc or 0) / spec_prop, "%.3f"),
                   _fmt(spec_acc, "%.0f"), _fmt(spec_prop, "%.0f")))
        if quant_pages is not None:
            lines.append("  int8 kv pages    %s in use"
                         % _fmt(quant_pages, "%.0f"))
        if pfx_hits is not None or pfx_miss is not None:
            total = (pfx_hits or 0) + (pfx_miss or 0)
            ratio = (pfx_hits or 0) / total if total else 0.0
            lines.append(
                "  prefix           hit %s (%s/%s)   shared pages %s"
                "   cow %s"
                % (_fmt(ratio, "%.3f"), _fmt(pfx_hits, "%.0f"),
                   _fmt(total, "%.0f"), _fmt(pfx_shared, "%.0f"),
                   _fmt(pfx_cow, "%.0f")))
    if asc_target is not None or qos_tenants:
        lines.append("-" * 46)
        if asc_target is not None:
            lines.append(
                "  autoscale        target %s   up %s  down %s"
                "  refused %s"
                % (_fmt(asc_target, "%.0f"),
                   _fmt(asc_events.get("up", 0), "%.0f"),
                   _fmt(asc_events.get("down", 0), "%.0f"),
                   _fmt(asc_events.get("refused", 0), "%.0f")))
            if asc_latest is not None:
                lines.append("  last decision    %s (#%s)"
                             % (asc_latest,
                                _fmt(asc_last[asc_latest], "%.0f")))
        for t in qos_tenants:
            adm = metric_sum(samples, "mxt_tenant_admitted_total",
                             tenant=t)
            rej = metric_sum(samples, "mxt_tenant_rejected_total",
                             tenant=t)
            pre = metric_sum(samples, "mxt_tenant_preempted_total",
                             tenant=t)
            inflt = metric_sum(samples, "mxt_tenant_inflight_requests",
                               tenant=t)
            lines.append(
                "  tenant %-9s adm %s  rej %s  pre %s  inflight %s"
                % (t, _fmt(adm, "%.0f"), _fmt(rej, "%.0f"),
                   _fmt(pre, "%.0f"), _fmt(inflt, "%.0f")))
    if hl_present:
        lines += [
            "-" * 46,
            "  health loss ema  %s   step %s ms"
            % (_fmt(hl_ema, "%.5g"), _fmt(hl_step_ms, "%.1f")),
        ]
        if hl_skew is not None:
            lines.append("  step skew        %s" % _fmt(hl_skew, "%.2f"))
        if hl_anoms:
            lines.append(
                "  anomalies        %s"
                % "  ".join("%s:%s=%d" % (k, l, int(c))
                            for c, k, l in hl_anoms[:3]))
        if hl_rules_ok or hl_rules_bad:
            lines.append(
                "  rules            %d ok / %d breached%s"
                % (len(hl_rules_ok), len(hl_rules_bad),
                   ("   (" + ", ".join(sorted(hl_rules_bad)) + ")")
                   if hl_rules_bad else ""))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default=None,
                   help="Prometheus endpoint (default: "
                        "http://127.0.0.1:$MXT_TELEMETRY_PORT)")
    p.add_argument("--jsonl", default=None,
                   help="tail a telemetry JSONL file instead")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (no screen clear)")
    p.add_argument("--fleet", action="store_true",
                   help="scrape the fleet collector's merged page "
                        "(--url + /fleet): member-labeled samples from "
                        "every fleet member, with a fleet-scope "
                        "section (tokens/s, per-replica occupancy, "
                        "per-server embedding hit ratio, goodput "
                        "min/mean, scrape ages)")
    args = p.parse_args(argv)

    if args.jsonl:
        src = JsonlSource(args.jsonl)
    else:
        url = args.url
        if url is None:
            port = os.environ.get("MXT_TELEMETRY_PORT")
            if not port:
                p.error("give --url or --jsonl (or set "
                        "MXT_TELEMETRY_PORT)")
            url = "http://127.0.0.1:%s" % port
        if args.fleet:
            url = url.rstrip("/") + "/fleet"
        src = EndpointSource(url)

    prev, t_prev = None, None
    while True:
        try:
            samples = src.sample()
        except OSError as e:
            print("mxt_top: source unreachable: %s" % e, file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        now = time.monotonic()
        frame = render(samples, prev, 0 if t_prev is None
                       else now - t_prev)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
        sys.stdout.flush()
        prev, t_prev = samples, now
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
