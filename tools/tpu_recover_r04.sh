#!/bin/bash
# Round-4 recovery runbook: the moment the axon tunnel answers a probe,
# capture everything the round needs from the real chip, in priority
# order (VERDICT r3 #1): hardware lane -> artifact, full bench, LSTM
# batch sweep, ResNet MFU lever sweep. Each stage is budget-bounded and
# syncs eagerly so a mid-stage kill can't re-wedge the tunnel.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_recover_r04.log}

run() {  # run <name> <timeout> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(date -u +%H:%M:%S)] start $name" >> "$LOG"
  timeout "$t" "$@" >> "$LOG" 2>&1
  echo "[$(date -u +%H:%M:%S)] $name rc=$?" >> "$LOG"
}

# 1) hardware lane, persisted as a committed artifact
MXT_TEST_TPU=1 timeout 2400 python -m pytest -m tpu -q -s \
    2>&1 | tee TPU_LANE_r04.txt >> "$LOG"
echo "[$(date -u +%H:%M:%S)] tpu lane done rc=${PIPESTATUS[0]}" >> "$LOG"

# 2) official bench sweep (headline + every config, budget-gated)
run bench 1800 env BENCH_BUDGET=1500 python bench.py

# 3) LSTM PTB batch sweep (VERDICT #3: batch 128/256 rows)
run lstm128 600 env BENCH_CONFIGS=lstm_ptb BENCH_LSTM_BATCH=128 \
    BENCH_BUDGET=500 python bench.py
run lstm256 600 env BENCH_CONFIGS=lstm_ptb BENCH_LSTM_BATCH=256 \
    BENCH_BUDGET=500 python bench.py

# 3a') LSTM wavefront A/B at the parity config (serial-chain lever)
run lstm_wavefront 600 env BENCH_CONFIGS=lstm_ptb MXT_RNN_WAVEFRONT=1 \
    BENCH_BUDGET=500 python bench.py
run lstm_wf128 600 env BENCH_CONFIGS=lstm_ptb MXT_RNN_WAVEFRONT=1 \
    BENCH_LSTM_BATCH=128 BENCH_BUDGET=500 python bench.py

# 3b) BERT through the canonical Gluon loop (fused donated Trainer.step)
run bert_gluon 900 env BENCH_CONFIGS=bert BENCH_BERT_PATH=trainer \
    BENCH_BUDGET=800 python bench.py

# 4) ResNet-50 MFU levers (VERDICT #2): batch 256, remat variants
run resnet_b256 900 env BENCH_CONFIGS=resnet50 BENCH_BATCH=256 \
    BENCH_BUDGET=800 BENCH_DUMP_HLO=/tmp/resnet_b256_axon.hlo \
    python bench.py
run resnet_remat 900 env BENCH_CONFIGS=resnet50 BENCH_REMAT=full \
    BENCH_BUDGET=800 python bench.py
run resnet_remat_dots 900 env BENCH_CONFIGS=resnet50 \
    BENCH_REMAT=dots_saveable BENCH_BUDGET=800 python bench.py

# 5) profiler trace of the ResNet step (PERF.md attachment)
run profile 900 python tools/profile_resnet.py --batch 64 --steps 8 \
    --out profiles/resnet50_r04

echo "RECOVERY_DONE" >> "$LOG"
