#!/usr/bin/env python
"""im2rec — build .rec/.idx/.lst files from an image folder
(ref: tools/im2rec.py).

Usage:
  python tools/im2rec.py prefix root --list      # write prefix.lst
  python tools/im2rec.py prefix root             # write prefix.rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def list_images(root, recursive, exts):
    i = 0
    cat = {}
    for path, dirs, files in os.walk(root, followlinks=True):
        dirs.sort()
        files.sort()
        for fname in files:
            fpath = os.path.join(path, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                if path not in cat:
                    cat[path] = len(cat)
                yield (i, os.path.relpath(fpath, root), cat[path])
                i += 1
        if not recursive:
            break


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for item in image_list:
            line = "%d\t%f\t%s\n" % (item[0], item[2], item[1])
            fout.write(line)


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield (int(parts[0]), parts[-1],
                   [float(x) for x in parts[1:-1]])


def make_rec(args, image_list):
    from mxnet_tpu import recordio

    record = recordio.MXIndexedRecordIO(
        args.prefix + ".idx", args.prefix + ".rec", "w")
    for idx, fname, labels in image_list:
        fpath = os.path.join(args.root, fname)
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        if args.pass_through:
            with open(fpath, "rb") as f:
                record.write_idx(idx, recordio.pack(header, f.read()))
        else:
            from PIL import Image
            import numpy as np

            img = Image.open(fpath).convert("RGB")
            if args.resize > 0:
                w, h = img.size
                if w < h:
                    img = img.resize((args.resize,
                                      int(h * args.resize / w)))
                else:
                    img = img.resize((int(w * args.resize / h),
                                      args.resize))
            record.write_idx(idx, recordio.pack_img(
                header, np.asarray(img), quality=args.quality))
    record.close()


def main():
    parser = argparse.ArgumentParser(description="make image record files")
    parser.add_argument("prefix", help="output prefix")
    parser.add_argument("root", help="image root folder")
    parser.add_argument("--list", action="store_true",
                        help="only create the .lst file")
    parser.add_argument("--recursive", action="store_true", default=True)
    parser.add_argument("--shuffle", action="store_true", default=True)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--pass-through", action="store_true",
                        help="store raw bytes, no re-encode")
    parser.add_argument("--exts", nargs="+",
                        default=[".jpg", ".jpeg", ".png"])
    args = parser.parse_args()

    if args.list:
        images = list(list_images(args.root, args.recursive, args.exts))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        write_list(args.prefix + ".lst", images)
        return

    lst = args.prefix + ".lst"
    if os.path.exists(lst):
        images = list(read_list(lst))
    else:
        raw = list(list_images(args.root, args.recursive, args.exts))
        if args.shuffle:
            random.seed(100)
            random.shuffle(raw)
        images = [(i, fname, [float(label)]) for i, fname, label in raw]
    make_rec(args, images)


if __name__ == "__main__":
    main()
