"""Capture a profiler trace of the ResNet-50 train step on the real chip
(the VERDICT-r3 'attach a trace to PERF.md' artifact; run by
tools/tpu_recover_r04.sh once the tunnel answers).

Usage: python tools/profile_resnet.py [--batch 64] [--steps 8]
                                      [--out profiles/resnet50]
Writes a Perfetto trace directory via mx.profiler (jax.profiler
underneath) and prints its path.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--out", default="profiles/resnet50")
    p.add_argument("--platform", default=None,
                   help="force a platform (e.g. cpu for a smoke run)")
    args = p.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel, profiler
    from mxnet_tpu.gluon import model_zoo, nn

    mx.random.seed(0)
    with nn.layout_scope("NHWC"):
        net = model_zoo.get_model("resnet50_v1", classes=1000)
    net.initialize()
    net.cast("bfloat16")
    x = nd.zeros((args.batch, 224, 224, 3), dtype="bfloat16")
    net(x)

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9})

    rng = np.random.RandomState(0)
    xb = nd.array(rng.uniform(-1, 1, x.shape).astype(np.float32))
    xb = xb.astype("bfloat16")
    yb = nd.array(rng.randint(0, 1000, (args.batch,)).astype(np.float32))

    # warm up (compile) OUTSIDE the trace, syncing eagerly
    for _ in range(2):
        step(xb, yb).wait_to_read()

    profiler.set_config(filename=args.out, profile_all=True)
    profiler.start()
    # sync EVERY step: an external kill mid-window must never find a deep
    # un-synced dispatch queue (the tunnel-wedge mechanism, PERF.md §1.4).
    # Per-step RTT gaps appear in the trace but each step's device
    # timeline is intact, which is what the backward analysis needs.
    for _ in range(args.steps):
        step(xb, yb).wait_to_read()
    trace_dir = profiler.dump()
    print("trace:", trace_dir)


if __name__ == "__main__":
    main()
