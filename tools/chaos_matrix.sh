#!/usr/bin/env bash
# Chaos matrix: sweep the seeded MXT_FAULT rules across injector seeds
# and fail on ANY hang (every cell runs under `timeout`).
#
# The chaos-marked tests (tests/test_membership.py, tests/test_resilience.py)
# arm their own MXT_FAULT specs; they read MXT_CHAOS_SEED (set per cell
# here) so each sweep re-seeds the injector RNGs — kv_drop/kv_delay,
# ckpt_crash, and the membership rules hb_drop / worker_freeze /
# rejoin_race all get exercised at every seed.
#
# Usage: tools/chaos_matrix.sh [seed...]          (default seeds: 0 1 2)
#        CHAOS_CELL_TIMEOUT=600 tools/chaos_matrix.sh 7 11
set -u

cd "$(dirname "$0")/.."

SEEDS=("$@")
[ "${#SEEDS[@]}" -eq 0 ] && SEEDS=(0 1 2)
CELL_TIMEOUT="${CHAOS_CELL_TIMEOUT:-600}"
FILES=(tests/test_membership.py tests/test_resilience.py)

fail=0
for seed in "${SEEDS[@]}"; do
    echo "== chaos sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest "${FILES[@]}" -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- watchdog sweep ---------------------------------------------------------
# Seeded worker_freeze / kv_drop rules must end in a TYPED outcome now
# (diagnostics.py): a watchdog stall report with a parsed post-mortem
# file, and under MXT_WATCHDOG_ACTION=abort a WATCHDOG_EXIT_CODE death
# that tools/launch.py --respawn restarts — the chaos-marked tests in
# tests/test_diagnostics.py assert all of it, so the outer `timeout`
# is only the backstop, not the detector.
for seed in "${SEEDS[@]}"; do
    echo "== watchdog sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_diagnostics.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: watchdog sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: watchdog sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- embedding fleet sweep --------------------------------------------------
# embedding_server_kill: the chaos-marked cells in tests/test_embedding.py
# kill one embedding server mid-train (consistent-hash remap to the
# survivors, worker-side re-seed of inherited rows), then restart it from
# its shard snapshot and fold it back into the ring — all typed, no
# hang; the outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== embedding sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_embedding.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: embedding sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: embedding sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- serving fleet sweep ------------------------------------------------------
# replica_kill / replica_slow: the chaos-marked cells in tests/test_fleet.py
# kill one serving replica at a seeded router tick (in-flight requests fail
# over to survivors, token-exact, zero lost) and brown one out (the hedge
# fires at the SLO-derived delay, the healthy replica wins, the loser is
# cancelled) — all typed, no hang; the outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== fleet sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_fleet.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: fleet sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: fleet sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- speculative-decode fleet sweep -------------------------------------------
# spec_replica_kill: the chaos-marked cells in tests/test_speculative.py
# kill one replica of a SPECULATIVE-engine fleet mid-run — the router
# fails the in-flight requests over and every completed stream is
# token-exact vs the unkilled single-replica oracle (failover replays
# speculative requests without re-decode divergence); typed, no hang.
for seed in "${SEEDS[@]}"; do
    echo "== speculative sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_speculative.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: speculative sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: speculative sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- fleet observability sweep ------------------------------------------------
# replica_kill during ACTIVE traces: the chaos-marked cells in
# tests/test_telemetry_fleet.py kill one replica mid-run and assert the
# failed-over requests' trace trees still export (failover_reenqueue
# span present, commits==1) and that the collector scraping a dead
# endpoint gets a typed stale verdict — bounded, never a hang; the
# outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== fleet-obs sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_telemetry_fleet.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: fleet-obs sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: fleet-obs sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- streaming data-plane sweep -----------------------------------------------
# data_host_kill / data_worker_slow: the chaos-marked cells in
# tests/test_data_plane.py kill one in-process host's decode fleet at a
# chunk boundary mid-epoch (survivors steal its reclaimed chunks and
# the epoch completes with 0 lost / 0 duplicated records; the zombie's
# stale-lease commit is refused typed) and slow one host's decode until
# its peer's steal fires — bounded, never a hang; the outer `timeout`
# is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== data-plane sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_data_plane.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: data-plane sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: data-plane sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- disaggregated-handoff sweep ----------------------------------------------
# replica_kill of a PREFILL-role replica mid-ship: the chaos-marked
# cell in tests/test_prefix.py asserts the router re-ships the same
# copy id from a surviving prefill replica (idempotent — never a
# re-prefill on the dead one), and with the prefill tier gone falls
# back to local prefill on the decode tier; zero requests lost,
# outputs token-exact vs the oracle, no surviving replica leaks KV
# pages — bounded, never a hang; the outer `timeout` is only the
# backstop.
for seed in "${SEEDS[@]}"; do
    echo "== disagg-handoff sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_prefix.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: disagg-handoff sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: disagg-handoff sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- autoscale sweep ----------------------------------------------------------
# traffic_storm / replica_spawn_slow: the chaos-marked cells in
# tests/test_autoscaler.py flip the seeded TrafficGenerator to a flash
# crowd mid-run (the autoscaler must scale up, absorb it, and account
# every request: submitted == committed + typed-rejected, zero lost)
# and slow the spawned spare's warm-up (the router must keep serving
# off the existing routable tier — a warming spare is never dispatched
# to and never stalls the control loop) — bounded, never a hang; the
# outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== autoscale sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_autoscaler.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: autoscale sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: autoscale sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- 4D elastic-reshard sweep -------------------------------------------------
# Seeded host kill on the (2,1,2,2) dp×tp×pp×ep mesh: the chaos-marked
# cell in tests/test_reshard.py picks the victim dp rank from
# MXT_CHAOS_SEED, fences it via the membership reaper, and asserts the
# survivors reshard IN PLACE to (1,1,2,2) — pipeline stages preserved,
# experts remapped, ZeRO re-decided — finishing BIT-exact vs a
# from-checkpoint restart with zero steps lost; the inner run is
# already subprocess-isolated, the outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== 4D-reshard sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_reshard.py -k elastic_reshard_4d \
        -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: 4D-reshard sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: 4D-reshard sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

# -- training-health sweep ------------------------------------------------------
# grad_spike: the chaos-marked cells in tests/test_health.py arm the
# seeded on-device gradient perturbation (one layer, scaled 1e6 after a
# seeded dispatch) and assert the health detectors catch it WITHIN ONE
# InflightWindow retirement — typed health_anomaly flight-recorder
# event, mxt_health_anomalies_total bumped, a post-mortem dumped — and
# that with the guard hook off the training numerics equal an unwatched
# run bit-for-bit (detection is observability, never a silent rescue);
# bounded, never a hang; the outer `timeout` is only the backstop.
for seed in "${SEEDS[@]}"; do
    echo "== training-health sweep: MXT_CHAOS_SEED=$seed (cell timeout ${CELL_TIMEOUT}s)"
    timeout -k 10 "$CELL_TIMEOUT" env JAX_PLATFORMS=cpu \
        MXT_CHAOS_SEED="$seed" \
        python -m pytest tests/test_health.py -q -m "chaos and not slow" \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "!! HANG: training-health sweep seed=$seed exceeded ${CELL_TIMEOUT}s" >&2
        fail=1
    elif [ "$rc" -ne 0 ]; then
        echo "!! FAIL: training-health sweep seed=$seed rc=$rc" >&2
        fail=1
    fi
done

[ "$fail" -eq 0 ] && echo "chaos matrix: all seeds clean"
exit "$fail"
