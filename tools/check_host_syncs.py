#!/usr/bin/env python
"""Static host-sync lint for the async dispatch hot path.

The async engine (mxnet_tpu/engine.py) only pays off while the fused-step
hot path performs NO device->host read outside the deferred-handle
protocol (ndarray/pending.py — PendingValue) and the engine's token
retirement. A single stray ``asnumpy()`` / ``np.asarray()`` / ``float()``
on a device value re-synchronizes every step and silently undoes the
pipelining — exactly the regression class this pass exists to catch.

Mechanism: scan the hot-path modules line by line (skipping comments and
docstrings) for sync-shaped constructs. Every INTENTIONAL sync point
carries a ``sync-ok: <reason>`` marker comment on its line; anything
unmarked fails the build. Runs standalone and from tier-1
(tests/test_engine_async.py::test_static_host_sync_pass).

Usage: python tools/check_host_syncs.py [repo_root]
"""
from __future__ import annotations

import os
import re
import sys

# constructs that force (or usually force) a device->host transfer
_ALL = [
    r"\.asnumpy\(",
    r"\.asscalar\(",
    r"\bnp\.asarray\(",
    r"\b_np\.asarray\(",
    r"\bnumpy\.asarray\(",
    r"\bfloat\(",
    r"\.item\(",
    r"block_until_ready",
    r"\bjax\.device_get\b",
]

# hot-path modules -> the patterns scanned there. metric.py hosts the
# legitimate numpy fallback path (host math on already-transferred
# arrays), so only the transfer itself is policed there; monitor.py's
# one sanctioned read is the batched tap materialization in toc().
# telemetry.py and the estimator event handlers run INSIDE the step/
# epoch loops — an accidental device read there would silently undo the
# async pipeline, so they are policed with the full pattern set.
_TRANSFER = [r"\.asnumpy\(", r"\.asscalar\(", r"\bnp\.asarray\(",
             r"block_until_ready"]

SCAN = {
    "mxnet_tpu/engine.py": _ALL,
    # diagnostics hooks ride INSIDE the hot paths (window pushes/retires,
    # decode ticks, RPC completions): the watchdog observes host
    # heartbeat counters and the HBM ledger observes shape metadata —
    # never device values. The ONE deliberate sync is the OOM handler's
    # window drain (the hot path is already dead there), sync-ok marked.
    "mxnet_tpu/diagnostics.py": _ALL,
    "mxnet_tpu/gluon/train_step.py": _ALL,
    "mxnet_tpu/gluon/trainer.py": _ALL,
    "mxnet_tpu/ndarray/pending.py": _ALL,
    "mxnet_tpu/telemetry.py": _ALL,
    # the fleet observability plane: the collector runs OFF the serving
    # hot path, but its span-stamping hooks live inside the router tick
    # and the scheduler's deferred retirements — everything here must
    # be host wall clocks and wire payloads; the sanctioned float()s
    # are config scalars and already-transferred wire values, each
    # sync-ok annotated.
    "mxnet_tpu/telemetry_fleet.py": _ALL,
    # the training-health plane: stat rows are computed ON DEVICE inside
    # the fused step and reach the host only through the InflightWindow's
    # deferred value channel — HealthMonitor.consume / the detectors run
    # at window retirement over rows that are already host data, and the
    # rules engine reads registry scalars. The annotated reads are those
    # retired rows and host rule params; an UNMARKED read here would
    # mean the Monitor heritage crept back in (a per-step gradient peek).
    "mxnet_tpu/health.py": _ALL,
    "mxnet_tpu/gluon/contrib/estimator.py": _ALL,
    "mxnet_tpu/monitor.py": _TRANSFER,
    "mxnet_tpu/metric.py": [r"\.asnumpy\(", r"\.asscalar\(",
                            r"block_until_ready"],
    # the tuning layer sits NEXT to the hot path: kernel-config lookups
    # run inside dispatch, so any device read there must be an annotated
    # autotuner measurement loop (never the per-call resolve path)
    "mxnet_tpu/tuning/__init__.py": _ALL,
    "mxnet_tpu/tuning/table.py": _ALL,
    "mxnet_tpu/tuning/autotune.py": _ALL,
    "mxnet_tpu/tuning/warmup.py": _ALL,
    "mxnet_tpu/tuning/compile_cache.py": _ALL,
    # the GSPMD sharded-step layer: the step itself is ONE launch with
    # zero reads, so any sync here is control-plane by construction —
    # mesh setup, checkpoint spill/restore for the elastic reshard
    # transfer format, cross-process reduce re-entry, and rare cursor
    # reads. Each carries its sync-ok justification; an UNMARKED read
    # would mean the per-step path started syncing.
    "mxnet_tpu/parallel/mesh.py": _ALL,
    "mxnet_tpu/parallel/sharded.py": _ALL,
    "mxnet_tpu/parallel/reshard.py": _ALL,
    # the 4D composition: pipeline schedule + MoE routing run INSIDE the
    # one donated step program, and the router accounting accumulates in
    # device-resident aux params — the only sanctioned reads are the
    # windowed publish_moe_telemetry transfer (sync-ok marked) and
    # nothing else. pipeline.py/moe.py are the island building blocks
    # the unified step subsumes; their shard_map programs must be just
    # as read-free.
    "mxnet_tpu/parallel/pipeline.py": _ALL,
    "mxnet_tpu/parallel/moe.py": _ALL,
    "mxnet_tpu/parallel/unified.py": _ALL,
    # the serving decode loop IS a hot path with an SLO: scheduler ticks
    # and cache bookkeeping run between every decode dispatch, so one
    # stray read there re-synchronizes every token of every request.
    # Tokens/flags leave the device ONLY through the InflightWindow's
    # deferred protocol (one stacked read per K steps) and the
    # per-request prefill PendingValue. model.py's reference_decode is
    # the parity oracle and marks its per-step read sync-ok.
    # kvstore's sparse paths: _merge now reduces row_sparse lists over
    # the index union ON DEVICE, and the dist_embedding row push/pull
    # runs between every sparse step — the intended syncs left are the
    # network-serialization boundaries (a frame must be host bytes) and
    # host config scalars, each annotated.
    "mxnet_tpu/kvstore.py": _ALL,
    # the sharded embedding client/cache sit on the per-step sparse
    # path: row ids are host metadata by design (routing is control
    # plane), and row values leave the device only at the RPC
    # serialization boundary — any UNMARKED read means the cache
    # started round-tripping device rows per lookup.
    "mxnet_tpu/embedding/__init__.py": _ALL,
    "mxnet_tpu/embedding/hashing.py": _ALL,
    "mxnet_tpu/embedding/cache.py": _ALL,
    "mxnet_tpu/embedding/client.py": _ALL,
    "mxnet_tpu/embedding/store.py": _ALL,
    # the streaming data plane: decode WORKERS do host-side numpy by
    # design (that layer is the one place host memory is supposed to be
    # touched — JPEG decode + augment + batchify), so their intentional
    # host reads are sync-ok annotated at the worker boundary. The FEED
    # path (loader.py into _DevicePrefetcher) and the lease ledger
    # (host-integer bookkeeping + wire frames) must stay lint-clean: a
    # stray device read there re-serializes the consumer against every
    # batch.
    "mxnet_tpu/data_plane/__init__.py": _ALL,
    "mxnet_tpu/data_plane/manifest.py": _ALL,
    "mxnet_tpu/data_plane/ledger.py": _ALL,
    "mxnet_tpu/data_plane/workers.py": _ALL,
    "mxnet_tpu/data_plane/loader.py": _ALL,
    "mxnet_tpu/serving/__init__.py": _ALL,
    "mxnet_tpu/serving/engine.py": _ALL,
    "mxnet_tpu/serving/scheduler.py": _ALL,
    "mxnet_tpu/serving/kv_cache.py": _ALL,
    "mxnet_tpu/serving/model.py": _ALL,
    "mxnet_tpu/serving/metrics.py": _ALL,
    # shared-prefix reuse is an ADMISSION-time feature: the blake2b
    # chain hashes host token lists (annotated at the one asarray),
    # and index bookkeeping is pure host dict/tuple work — the decode
    # loop never consults it, so any unmarked device read here would
    # mean prefix lookups started syncing the hot path
    "mxnet_tpu/serving/prefix.py": _ALL,
    # the speculative round is TWO traced programs per k committed
    # tokens; the accepted-prefix commit is device-side by design, so
    # any unmarked read here would mean the host started peeking at
    # accept counts per round — exactly the sync class the staged
    # (B, k+1) row protocol exists to avoid
    "mxnet_tpu/serving/speculative.py": _ALL,
    # the fleet router sits ABOVE the decode hot path but runs between
    # every decode tick of every replica: routing decisions must be
    # host arithmetic on gauges and wall clocks, never a device read —
    # one stray sync here re-serializes the whole fleet's pipelines.
    # Control-plane scalars (config values, fault-rule params) are the
    # only sanctioned float()s, each sync-ok annotated.
    "mxnet_tpu/serving/fleet.py": _ALL,
    "mxnet_tpu/serving/router.py": _ALL,
    # the autoscaler's control loop and the QoS admission gate run
    # between decode ticks of the whole fleet: both must stay pure
    # host arithmetic over already-merged gauges/histograms — a device
    # read (or a blocking scrape) inside either would stall every
    # replica once per control period, turning the thing that absorbs
    # flash crowds into the thing that causes them
    "mxnet_tpu/serving/autoscaler.py": _ALL,
    "mxnet_tpu/serving/qos.py": _ALL,
}

_MARKER = "sync-ok"


def _strip_docstrings(lines):
    """Yield (lineno, line) for lines outside triple-quoted strings (a
    coarse tracker — good enough for these modules' style)."""
    in_doc = False
    for i, line in enumerate(lines, 1):
        quotes = line.count('"""') + line.count("'''")
        if in_doc:
            if quotes % 2 == 1:
                in_doc = False
            continue
        if quotes % 2 == 1:
            in_doc = True
            continue
        if quotes and quotes % 2 == 0:
            continue  # one-line docstring
        yield i, line


def check(root):
    """[(path, lineno, line)] of unmarked sync constructs."""
    bad = []
    for rel, patterns in sorted(SCAN.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            bad.append((rel, 0, "<hot-path module missing>"))
            continue
        regexes = [re.compile(p) for p in patterns]
        with open(path) as f:
            lines = f.read().splitlines()
        for lineno, line in _strip_docstrings(lines):
            code = line.split("#", 1)[0]
            if not code.strip():
                continue
            if _MARKER in line:
                continue
            for rx in regexes:
                if rx.search(code):
                    bad.append((rel, lineno, line.strip()))
                    break
    return bad


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    bad = check(root)
    if bad:
        print("check_host_syncs: %d unmarked host-sync point(s) in the "
              "async hot path:" % len(bad))
        for rel, lineno, line in bad:
            print("  %s:%d: %s" % (rel, lineno, line))
        print("route the read through the deferred protocol "
              "(ndarray/pending.py / engine.StepStream), or mark an "
              "intentional sync with `# sync-ok: <reason>`.")
        return 1
    print("check_host_syncs: hot path clean (%d modules)" % len(SCAN))
    return 0


if __name__ == "__main__":
    sys.exit(main())
