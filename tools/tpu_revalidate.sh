#!/bin/bash
# Watch for the axon tunnel to recover, then run the hardware test lane
# and the full benchmark suite. Round-3 context: a killed deep-queue
# process wedged the single-client tunnel; this script turns recovery
# into results without babysitting.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_revalidate.log}
DEADLINE=$(( $(date +%s) + ${2:-21600} ))  # default: watch up to 6h

probe() {
  timeout 120 python -u -c "
import jax
jax.config.update('jax_platforms','axon')
import jax.numpy as jnp, numpy as np
x = jnp.ones((128,128)) @ jnp.ones((128,128))
print('PROBE_OK', np.asarray(jax.jit(lambda v: v.ravel()[:1])(x))[0])
" 2>/dev/null | grep -q PROBE_OK
}

echo "[$(date -u +%H:%M:%S)] watcher started" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "[$(date -u +%H:%M:%S)] TPU recovered — running validation" >> "$LOG"
    MXT_TEST_TPU=1 timeout 1800 python -m pytest -m tpu -q >> "$LOG" 2>&1
    echo "[$(date -u +%H:%M:%S)] tpu lane rc=$?" >> "$LOG"
    timeout 2400 python bench.py >> "$LOG" 2>&1
    echo "[$(date -u +%H:%M:%S)] bench rc=$?" >> "$LOG"
    echo "DONE" >> "$LOG"
    exit 0
  fi
  echo "[$(date -u +%H:%M:%S)] still wedged" >> "$LOG"
  sleep 300
done
echo "TIMEOUT — tunnel never recovered" >> "$LOG"
exit 1
