#!/usr/bin/env python
"""Cluster launcher (ref: tools/launch.py + dmlc-core tracker — which
started a scheduler plus ssh/mpi/local worker+server processes with
DMLC_* rendezvous env).

TPU-native topology has no parameter servers: workers are SPMD peers that
rendezvous through the JAX coordination service (`jax.distributed`), and
gradients ride XLA collectives. So this launcher starts N *worker*
processes with the coordinator env set; ``-s/--num-servers`` is accepted
for command-line parity and ignored (documented reference deviation).

Local mode (the ``--launcher local`` test pattern from
tests/nightly/dist_sync_kvstore.py):

    python tools/launch.py -n 4 --launcher local python my_train.py

SSH mode reads ``-H hostfile`` (one host per line, first host also runs
the coordinator) and launches one worker per host:

    python tools/launch.py -n 4 --launcher ssh -H hosts python my_train.py

Workers read MXT_COORDINATOR / MXT_NUM_WORKERS / MXT_WORKER_ID (set
here) via ``mxnet_tpu.parallel.init_distributed()``. ``--mesh dp,tp``
(+ optional ``--mesh-axes`` / ``--zero-stage``) exports
MXT_MESH_SHAPE / MXT_MESH_AXES / MXT_ZERO_STAGE so a no-arg
``parallel.make_mesh()`` + ``ShardedTrainStep`` training script scales
from one host to N by changing only this launch line:

    python tools/launch.py -n 16 --launcher ssh -H hosts \\
        --mesh 64,2 --zero-stage 2 python train.py

``--respawn`` (local launcher) supervises the workers: a crashed one is
restarted with its original rank/env so it rejoins the kvstore
membership view (fresh generation + snapshot handoff, membership.py),
up to ``--max-restarts`` times per slot.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

# mxnet_tpu.diagnostics.WATCHDOG_EXIT_CODE (kept in sync; not imported
# so the launcher stays dependency-free): a worker that died this way
# was aborted by its hang watchdog after dumping a post-mortem.
WATCHDOG_EXIT_CODE = 134


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, coordinator, n, i, extra=None):
    env = dict(base)
    env["MXT_COORDINATOR"] = coordinator
    env["MXT_NUM_WORKERS"] = str(n)
    env["MXT_WORKER_ID"] = str(i)
    # reference-compatible spellings, for scripts that read DMLC_*
    env["DMLC_NUM_WORKER"] = str(n)
    env["DMLC_WORKER_ID"] = str(i)
    env["DMLC_ROLE"] = "worker"
    if extra:
        env.update(extra)
    return env


def _mesh_env(args):
    """MXT_MESH_SHAPE / MXT_MESH_AXES / MXT_ZERO_STAGE from the launch
    line: workers' no-arg parallel.make_mesh() and ShardedTrainStep
    calls pick these up, so the SAME training script runs a 1-host dev
    mesh and an N-host pod mesh with no code change (the GSPMD
    scale-out contract)."""
    extra = {}
    if getattr(args, "mesh", None):
        extra["MXT_MESH_SHAPE"] = args.mesh
    if getattr(args, "mesh_axes", None):
        extra["MXT_MESH_AXES"] = args.mesh_axes
    if getattr(args, "zero_stage", None) is not None:
        extra["MXT_ZERO_STAGE"] = str(args.zero_stage)
    if getattr(args, "watchdog", None) is not None:
        # arm every worker's hang watchdog (diagnostics.py) from the
        # launch line: a silent worker_freeze becomes a stall report,
        # and with abort + --respawn a typed death the launcher heals
        extra["MXT_WATCHDOG_TIMEOUT"] = str(args.watchdog)
        if getattr(args, "watchdog_action", None):
            extra["MXT_WATCHDOG_ACTION"] = args.watchdog_action
    return extra


def launch_local(n, command, respawn=False, max_restarts=2, extra_env=None):
    """Start n local workers. With ``respawn`` the launcher supervises
    them: a worker that exits non-zero (crash, SIGKILL) is restarted
    with its ORIGINAL rank/env — same MXT_WORKER_ID, same coordinator,
    same forwarded secret — so the membership rejoin path (re-register,
    fresh generation, snapshot handoff) is exercised end to end. Each
    slot restarts at most ``max_restarts`` times."""
    import time

    coordinator = "127.0.0.1:%d" % _free_port()
    envs = [_worker_env(os.environ, coordinator, n, i, extra_env)
            for i in range(n)]
    procs = [subprocess.Popen(command, env=envs[i]) for i in range(n)]
    if not respawn:
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        return rc
    restarts = [0] * n
    final = [None] * n
    while any(f is None for f in final):
        for i, p in enumerate(procs):
            if final[i] is not None or p.poll() is None:
                continue
            rc = p.returncode
            if rc == 0:
                final[i] = 0
            elif restarts[i] < max_restarts:
                restarts[i] += 1
                why = " (watchdog abort — see its mxt-postmortem-*.json)" \
                    if rc == WATCHDOG_EXIT_CODE else ""
                sys.stderr.write(
                    "launch: worker %d exited rc=%d%s — respawning with "
                    "original rank/env (%d/%d)\n"
                    % (i, rc, why, restarts[i], max_restarts))
                sys.stderr.flush()
                procs[i] = subprocess.Popen(command, env=envs[i])
            else:
                final[i] = rc
        time.sleep(0.05)
    return next((rc for rc in final if rc), 0)


def launch_ssh(n, hostfile, command, extra_env=None):
    with open(hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()
                 and not h.startswith("#")]
    if len(hosts) < n:
        raise SystemExit("hostfile has %d hosts, need %d" % (len(hosts), n))
    coordinator = "%s:%d" % (hosts[0], 9378)
    procs = []
    for i in range(n):
        env = _worker_env({}, coordinator, n, i, extra_env)
        envs = " ".join("%s=%s" % kv for kv in env.items())
        remote = "cd %s && %s %s" % (os.getcwd(), envs,
                                     " ".join(command))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[i], remote]))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference CLI parity; the TPU "
                         "topology has no parameter servers (ignored)")
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--respawn", action="store_true",
                    help="supervise local workers: restart a crashed "
                         "worker with its original rank/env so it "
                         "rejoins the membership view (local launcher "
                         "only)")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="per-worker restart budget under --respawn")
    ap.add_argument("--mesh", default=None,
                    help="global mesh shape exported as MXT_MESH_SHAPE "
                         "(e.g. '16,2' for dp×tp, '2,1,2,2' for the "
                         "full dp×tp×pp×ep; one -1 wildcard allowed) — "
                         "workers' no-arg parallel.make_mesh() builds "
                         "this mesh over the GLOBAL device list")
    ap.add_argument("--mesh-axes", default=None,
                    help="axis names paired with --mesh (exported as "
                         "MXT_MESH_AXES; default data,model,pipe,expert "
                         "truncated to the shape's rank — dp,tp,pp,ep "
                         "are accepted synonyms)")
    ap.add_argument("--zero-stage", type=int, default=None,
                    choices=(0, 1, 2, 3),
                    help="default ZeRO weight-update sharding stage for "
                         "ShardedTrainStep (exported as MXT_ZERO_STAGE)")
    ap.add_argument("--watchdog", type=float, default=None,
                    help="arm each worker's hang watchdog: seconds "
                         "without progress before a stall report "
                         "(exported as MXT_WATCHDOG_TIMEOUT)")
    ap.add_argument("--watchdog-action", choices=("report", "abort"),
                    default=None,
                    help="stall response (exported as "
                         "MXT_WATCHDOG_ACTION): 'abort' + --respawn "
                         "turns a hang into a respawned worker")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command to launch")
    extra = _mesh_env(args)
    if args.launcher == "local":
        return launch_local(args.num_workers, args.command,
                            respawn=args.respawn,
                            max_restarts=args.max_restarts,
                            extra_env=extra)
    if args.respawn:
        ap.error("--respawn supports the local launcher only")
    if not args.hostfile:
        ap.error("ssh launcher requires -H hostfile")
    return launch_ssh(args.num_workers, args.hostfile, args.command,
                      extra_env=extra)


if __name__ == "__main__":
    sys.exit(main())
