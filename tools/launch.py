#!/usr/bin/env python
"""Cluster launcher (ref: tools/launch.py + dmlc-core tracker — which
started a scheduler plus ssh/mpi/local worker+server processes with
DMLC_* rendezvous env).

TPU-native topology has no parameter servers: workers are SPMD peers that
rendezvous through the JAX coordination service (`jax.distributed`), and
gradients ride XLA collectives. So this launcher starts N *worker*
processes with the coordinator env set; ``-s/--num-servers`` is accepted
for command-line parity and ignored (documented reference deviation).

Local mode (the ``--launcher local`` test pattern from
tests/nightly/dist_sync_kvstore.py):

    python tools/launch.py -n 4 --launcher local python my_train.py

SSH mode reads ``-H hostfile`` (one host per line, first host also runs
the coordinator) and launches one worker per host:

    python tools/launch.py -n 4 --launcher ssh -H hosts python my_train.py

Workers read MXT_COORDINATOR / MXT_NUM_WORKERS / MXT_WORKER_ID (set
here) via ``mxnet_tpu.parallel.init_distributed()``.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, coordinator, n, i):
    env = dict(base)
    env["MXT_COORDINATOR"] = coordinator
    env["MXT_NUM_WORKERS"] = str(n)
    env["MXT_WORKER_ID"] = str(i)
    # reference-compatible spellings, for scripts that read DMLC_*
    env["DMLC_NUM_WORKER"] = str(n)
    env["DMLC_WORKER_ID"] = str(i)
    env["DMLC_ROLE"] = "worker"
    return env


def launch_local(n, command):
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = []
    for i in range(n):
        procs.append(subprocess.Popen(
            command, env=_worker_env(os.environ, coordinator, n, i)))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def launch_ssh(n, hostfile, command):
    with open(hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()
                 and not h.startswith("#")]
    if len(hosts) < n:
        raise SystemExit("hostfile has %d hosts, need %d" % (len(hosts), n))
    coordinator = "%s:%d" % (hosts[0], 9378)
    procs = []
    for i in range(n):
        env = _worker_env({}, coordinator, n, i)
        envs = " ".join("%s=%s" % kv for kv in env.items())
        remote = "cd %s && %s %s" % (os.getcwd(), envs,
                                     " ".join(command))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[i], remote]))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference CLI parity; the TPU "
                         "topology has no parameter servers (ignored)")
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command to launch")
    if args.launcher == "local":
        return launch_local(args.num_workers, args.command)
    if not args.hostfile:
        ap.error("ssh launcher requires -H hostfile")
    return launch_ssh(args.num_workers, args.hostfile, args.command)


if __name__ == "__main__":
    sys.exit(main())
