#!/usr/bin/env python
"""Perf-regression gate over the recorded bench trajectory.

``bench.py`` appends one JSONL row per run into ``bench_results.jsonl``
— until now a log, not a baseline. This tool turns the history into an
enforced gate: for each bench key (``config``, per platform) the
CANDIDATE row (the newest in the file, or every row of a ``--candidate``
file) is compared against the median of the PRIOR rows for the same
key, and a drop past the tolerance band exits non-zero — wire it after
a bench run and the perf trajectory becomes CI-enforced.

Metric selection per row, in priority order:

- ``step_time_ms``      — lower is better (a 1.5x slowdown regresses)
- ``images_or_tokens_per_sec_per_chip`` — higher is better

Verdicts are typed, one per candidate row:

- ``OK``                   — within ``--tolerance`` of the history median
- ``REGRESSION``           — worse than median by more than the band
- ``IMPROVED``             — better than median by more than the band
  (informational; never fails the gate)
- ``INSUFFICIENT_HISTORY`` — fewer than ``--min-history`` prior rows
  for this key (never fails: a brand-new bench has no trajectory yet)
- ``NO_METRIC``            — the row carries neither gated metric

Exit status: 1 iff any candidate row is a REGRESSION, else 0.

Comparisons never cross platforms or workload shapes: a ``cpu`` smoke
row is not judged against the ``axon`` trajectory, and a batch-256 run
is not judged against batch-4 history (the key is config + platform +
chips + batch/seq/dtype). The band also self-calibrates: it widens to
the history's own relative median-absolute-deviation (times
``--mad-mult``), so a key whose trajectory is historically noisy
doesn't false-positive while a tight trajectory still gates at
``--tolerance``.

Usage:
  python tools/bench_regression.py                      # newest row per key
  python tools/bench_regression.py --candidate new.jsonl  # gate a fresh run
  python tools/bench_regression.py --history bench_results.jsonl --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# (metric, direction): +1 = higher is better, -1 = lower is better
_METRICS = (
    ("step_time_ms", -1),
    ("images_or_tokens_per_sec_per_chip", +1),
)


def _load(path):
    rows = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                sys.stderr.write("%s:%d: unparseable row skipped\n"
                                 % (path, lineno))
                continue
            if isinstance(row, dict) and row.get("config"):
                rows.append(row)
    return rows


def _key(row):
    return (str(row.get("config")), str(row.get("platform") or ""),
            str(row.get("chips") or ""), str(row.get("batch_size") or ""),
            str(row.get("seq_len") or ""), str(row.get("dtype") or ""))


def _metric(row):
    for name, direction in _METRICS:
        v = row.get(name)
        if isinstance(v, (int, float)) and v > 0:
            return name, direction, float(v)
    return None, 0, None


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    return vals[n // 2] if n % 2 else 0.5 * (vals[n // 2 - 1]
                                             + vals[n // 2])


def judge(history, candidates, tolerance=0.25, min_history=3,
          mad_mult=3.0):
    """One verdict dict per candidate row, against the per-key median
    of ``history`` (candidate rows themselves are never in the band).
    The band is ``max(tolerance, mad_mult * relative MAD)`` of the
    prior rows, capped at 0.9 — a tight trajectory gates tightly, a
    historically noisy one gates loosely instead of crying wolf."""
    by_key = {}
    for row in history:
        by_key.setdefault(_key(row), []).append(row)
    verdicts = []
    for row in candidates:
        key = _key(row)
        name, direction, value = _metric(row)
        verdict = {"config": key[0], "platform": key[1], "metric": name,
                   "value": value, "median": None, "history": 0,
                   "ratio": None, "band": None, "verdict": "NO_METRIC",
                   "detail": ""}
        if name is None:
            verdict["detail"] = "row carries no gated metric"
            verdicts.append(verdict)
            continue
        prior = []
        for h in by_key.get(key, ()):
            if h is row:
                continue
            hv = h.get(name)
            if isinstance(hv, (int, float)) and hv > 0:
                prior.append(float(hv))
        verdict["history"] = len(prior)
        if len(prior) < min_history:
            verdict["verdict"] = "INSUFFICIENT_HISTORY"
            verdict["detail"] = ("%d prior row(s) for this key, need %d"
                                 % (len(prior), min_history))
            verdicts.append(verdict)
            continue
        med = _median(prior)
        rel_mad = _median([abs(v - med) / med for v in prior])
        band = min(0.9, max(tolerance, mad_mult * rel_mad))
        verdict["median"] = med
        verdict["band"] = band
        # normalize so ratio > 1 is always BETTER than the median
        ratio = (value / med) if direction > 0 else (med / value)
        verdict["ratio"] = ratio
        if ratio < 1.0 - band:
            verdict["verdict"] = "REGRESSION"
        elif ratio > 1.0 + band:
            verdict["verdict"] = "IMPROVED"
        else:
            verdict["verdict"] = "OK"
        verdict["detail"] = ("%s=%.6g vs median %.6g over %d rows "
                             "(%.2fx, band %.0f%%)"
                             % (name, value, med, len(prior), ratio,
                                100 * band))
        verdicts.append(verdict)
    return verdicts


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--history", default=None,
                   help="bench trajectory JSONL (default: "
                        "bench_results.jsonl next to the repo root)")
    p.add_argument("--candidate", default=None,
                   help="JSONL of fresh rows to gate; omitted, the "
                        "newest history row per bench key is the "
                        "candidate and the rest is its baseline")
    p.add_argument("--tolerance", type=float, default=0.25,
                   help="fractional band around the history median "
                        "(default 0.25: a 1.34x step-time slowdown or "
                        "a 25%% throughput drop regresses)")
    p.add_argument("--min-history", type=int, default=3,
                   help="prior rows required before the gate engages "
                        "(default 3)")
    p.add_argument("--mad-mult", type=float, default=3.0,
                   help="widen the band to this multiple of the "
                        "history's relative median-absolute-deviation "
                        "when that exceeds --tolerance (default 3.0)")
    p.add_argument("--json", action="store_true",
                   help="emit verdicts as JSON instead of a table")
    args = p.parse_args(argv)

    history_path = args.history
    if history_path is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        history_path = os.path.join(root, "bench_results.jsonl")
    if not os.path.exists(history_path):
        sys.stderr.write("bench_regression: no history at %s\n"
                         % history_path)
        return 0
    history = _load(history_path)

    if args.candidate:
        candidates = _load(args.candidate)
        baseline = history
    else:
        # newest row per key gates against everything before it
        newest = {}
        for row in history:
            newest[_key(row)] = row  # file order: last wins
        candidates = [newest[k] for k in sorted(newest)]
        baseline = history
    verdicts = judge(baseline, candidates, tolerance=args.tolerance,
                     min_history=args.min_history,
                     mad_mult=args.mad_mult)

    if args.json:
        print(json.dumps(verdicts, indent=2))
    else:
        for v in verdicts:
            print("%-22s %-10s %-20s %s"
                  % (v["verdict"], v["platform"] or "-", v["config"],
                     v["detail"]))
    regressions = [v for v in verdicts if v["verdict"] == "REGRESSION"]
    if regressions:
        sys.stderr.write(
            "bench_regression: %d regression(s) against the recorded "
            "trajectory\n" % len(regressions))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
