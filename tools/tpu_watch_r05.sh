#!/bin/bash
# Round-5 tunnel watchdog. The axon remote_compile endpoint died mid-bench
# at 04:28 UTC (connection refused — service down, not a client wedge).
# This watches for recovery at a GENTLE cadence (a killed probe can renew
# a stuck lease, so: 20-min period, one probe per period, probe budget
# well under the period) and, on recovery, captures the round's chip
# results ONCE in priority order, then exits so nothing contends with the
# driver's end-of-round bench. Single-client tunnel: while this script is
# in its recovery phase NOTHING else may touch the chip.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch_r05.log}
DEADLINE=$(( $(date +%s) + ${2:-28800} ))  # default: watch up to 8h

probe() {
  # true host read through a jitted slice — block_until_ready lies on
  # this tunnel (PERF.md §1.1)
  timeout 150 python -u -c "
import jax
jax.config.update('jax_platforms','axon')
import jax.numpy as jnp, numpy as np
x = jnp.ones((128,128)) @ jnp.ones((128,128))
print('PROBE_OK', np.asarray(jax.jit(lambda v: v.ravel()[:1])(x))[0])
" 2>/dev/null | grep -q PROBE_OK
}

run() {  # run <name> <timeout> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(date -u +%H:%M:%S)] start $name" >> "$LOG"
  timeout "$t" "$@" >> "$LOG" 2>&1
  echo "[$(date -u +%H:%M:%S)] $name rc=$?" >> "$LOG"
}

echo "[$(date -u +%H:%M:%S)] watcher started (20-min cadence)" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "[$(date -u +%H:%M:%S)] TPU recovered — capturing round results" >> "$LOG"
    # 1) official bench sweep first this time (the round's #1 gap is a
    #    driver-visible axon row; the lane is already green+committed)
    run bench 1800 env BENCH_BUDGET=1500 python bench.py
    # 2) ResNet MFU levers (VERDICT #2)
    run resnet_b256 900 env BENCH_CONFIGS=resnet50 BENCH_BATCH=256 \
        BENCH_BUDGET=800 python bench.py
    run resnet_remat 900 env BENCH_CONFIGS=resnet50 BENCH_REMAT=full \
        BENCH_BUDGET=800 python bench.py
    run resnet_remat_dots 900 env BENCH_CONFIGS=resnet50 \
        BENCH_REMAT=dots_saveable BENCH_BUDGET=800 python bench.py
    # BN Pallas A/B (r5: fused BN backward, ops/bn_pallas.py)
    run resnet_bnpallas 900 env BENCH_CONFIGS=resnet50 MXT_BN_PALLAS=1 \
        BENCH_BUDGET=800 python bench.py
    run resnet_bnpallas_b256 900 env BENCH_CONFIGS=resnet50 \
        MXT_BN_PALLAS=1 BENCH_BATCH=256 BENCH_BUDGET=800 python bench.py
    # 3) LSTM batch sweep + wavefront A/B (VERDICT #3)
    run lstm128 600 env BENCH_CONFIGS=lstm_ptb BENCH_LSTM_BATCH=128 \
        BENCH_BUDGET=500 python bench.py
    run lstm256 600 env BENCH_CONFIGS=lstm_ptb BENCH_LSTM_BATCH=256 \
        BENCH_BUDGET=500 python bench.py
    run lstm_wf32 600 env BENCH_CONFIGS=lstm_ptb MXT_RNN_WAVEFRONT=1 \
        BENCH_BUDGET=500 python bench.py
    run lstm_wf128 600 env BENCH_CONFIGS=lstm_ptb MXT_RNN_WAVEFRONT=1 \
        BENCH_LSTM_BATCH=128 BENCH_BUDGET=500 python bench.py
    # 4) BERT through the canonical fused Trainer loop (VERDICT #4)
    run bert_gluon 900 env BENCH_CONFIGS=bert BENCH_BERT_PATH=trainer \
        BENCH_BUDGET=800 python bench.py
    # BERT batch/seq levers (r5: MFU push past the 0.36 r3 row)
    run bert_b64 900 env BENCH_CONFIGS=bert BENCH_BERT_BATCH=64 \
        BENCH_BUDGET=800 python bench.py
    run bert_b64_s256 900 env BENCH_CONFIGS=bert BENCH_BERT_BATCH=64 \
        BENCH_BERT_SEQLEN=256 BENCH_BUDGET=800 python bench.py
    # block override only bites when seqlen exceeds it (blocks clamp to T)
    run bert_flash_q256 900 env BENCH_CONFIGS=bert BENCH_BERT_BATCH=64 \
        BENCH_BERT_SEQLEN=256 MXT_FLASH_BLOCK_Q=256 \
        MXT_FLASH_BLOCK_K=256 BENCH_BUDGET=800 python bench.py
    # 5) fresh hardware-lane log (validates post-crash health; artifact)
    MXT_TEST_TPU=1 timeout 1800 python -m pytest -m tpu -q \
        2>&1 | tee TPU_LANE_r05_post.txt >> "$LOG"
    echo "[$(date -u +%H:%M:%S)] lane rc=${PIPESTATUS[0]}" >> "$LOG"
    # 6) profiler trace for PERF.md
    run profile 900 python tools/profile_resnet.py --batch 64 --steps 8 \
        --out profiles/resnet50_r05
    echo "CAPTURE_DONE" >> "$LOG"
    exit 0
  fi
  echo "[$(date -u +%H:%M:%S)] still down" >> "$LOG"
  sleep 1050
done
echo "TIMEOUT — tunnel never recovered" >> "$LOG"
exit 1
