"""Benchmark harness — prints ONE JSON line with the headline metric.

Config: ResNet-50 training throughput (images/sec/chip), the SURVEY §6
headline. Runs on whatever accelerator JAX exposes (the driver provides one
real TPU chip); the full train step (fwd+loss+bwd+SGD) is one jitted XLA
program in bfloat16 compute via ShardedTrainStep.

vs_baseline: BASELINE.json's published table is empty (mount was empty at
survey time), so the ratio is computed against the public MXNet-era
V100 fp32 figure (~390 img/s, docs/faq/perf.md) as the stand-in
denominator; see BASELINE.md.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 390.0  # MXNet ResNet-50 V100 fp32 (unverified, BASELINE.md)


def main():
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import model_zoo
    from mxnet_tpu import parallel

    mx.random.seed(0)
    net = model_zoo.get_model("resnet50_v1", classes=1000)
    net.initialize()
    # bf16 params/compute: MXU-native. BN stats stay f32 inside the op.
    if os.environ.get("BENCH_DTYPE", "bfloat16") == "bfloat16":
        net.cast("bfloat16")

    x0 = nd.zeros((batch, 3, 224, 224), dtype="bfloat16")
    net(x0)  # resolve deferred shapes eagerly

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9})

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (batch, 3, 224, 224)).astype(np.float32))
    x = x.astype(os.environ.get("BENCH_DTYPE", "bfloat16"))
    y = nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))

    for _ in range(warmup):
        loss = step(x, y)
    loss.wait_to_read()

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
