"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline config: ResNet-50 training throughput (images/sec/chip), the
SURVEY §6 headline. A second config (BERT-base MLM, tokens/sec/chip,
BASELINE config 3) is also measured; all configs append JSONL rows to
bench_results.jsonl with the BASELINE.md-required fields plus MFU
(model flops / chip peak, v5e bf16 peak = 197 TFLOP/s).

Backend init is hardened (round-1 failure was `RuntimeError: Unable to
initialize backend 'axon'` with no retry): the TPU is probed in a
subprocess with a timeout, retried, and on persistent failure the bench
falls back to CPU so a numeric value is always emitted — the JSON then
carries platform="cpu" and the failure note, never a bare traceback.

vs_baseline: BASELINE.json's published table is empty (mount was empty at
survey time), so the ratio is computed against the public MXNet-era
V100 fp32 figure (~390 img/s, docs/faq/perf.md) as the stand-in
denominator; see BASELINE.md.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 390.0  # MXNet ResNet-50 V100 fp32 (unverified, BASELINE.md)
V5E_PEAK_FLOPS = 197e12  # TPU v5e bf16 peak per chip
JSONL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_results.jsonl")

# Wall-clock budget. The round-3 wedge was caused by an external `timeout`
# killing bench.py mid-compile (deep un-synced dispatch queue -> tunnel
# lease stuck for hours, PERF.md §1.4). The fix is to never be there when
# the driver's kill lands: every config is cost-gated against a global
# deadline and the bench exits cleanly with whatever rows completed.
_T0 = time.monotonic()
_BUDGET = float(os.environ.get("BENCH_BUDGET", "1500"))

# conservative per-config wall-clock estimates (compile + warmup + window),
# measured on the axon tunnel in round 3; CPU small-shape runs are cheaper
# but CPU is the fallback path where the budget rarely binds
_CONFIG_COST = {"resnet50": 420, "bert": 300, "lstm_ptb": 200,
                "wide_deep": 200, "lenet": 150, "pipeline": 150,
                "async_ab": 90, "telemetry_ab": 60, "diag_ab": 60,
                "cold_warm": 120, "serving": 150, "zero_stage": 90,
                "embedding_ab": 90, "serving_fleet": 120,
                "speculative": 120, "kv_quant": 90, "fleet_obs": 90,
                "streaming_input": 90, "prefix_reuse": 120,
                "autoscale": 150, "parallel_4d": 90,
                "training_health": 60}


def _remaining():
    return _BUDGET - (time.monotonic() - _T0)


def _probe_axon(timeout):
    """Try to init the axon TPU backend in a subprocess (so a hang cannot
    wedge the bench process). Returns (ok, error_tail). The probe reads a
    result element to host: block_until_ready returns before compute
    finishes on this tunnel (PERF.md), so it alone would false-OK a
    wedged device."""
    code = (
        "import jax; jax.config.update('jax_platforms','axon'); "
        "d = jax.devices(); assert d; "
        "import jax.numpy as jnp, numpy as np; "
        "x = jnp.ones((128,128))@jnp.ones((128,128)); "
        "v = np.asarray(jax.jit(lambda a: a.ravel()[:1])(x)); "
        "assert v[0] == 128.0, v; "
        "print('PROBE_OK', d[0])"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        if r.returncode == 0 and "PROBE_OK" in r.stdout:
            return True, ""
        return False, (r.stderr or r.stdout or "")[-500:]
    except subprocess.TimeoutExpired:
        # terminal: a timed-out probe means the tunnel is hung at the
        # chip claim (and killing even a tiny probe mid-dispatch risks
        # wedging it further) — re-probing would just burn the budget
        return None, "axon probe timed out after %ds" % timeout


def _init_backend():
    """Pick + force a platform at the jax.config level (the axon plugin
    overrides the JAX_PLATFORMS env var, so config.update is the only
    reliable switch). Returns (platform, note)."""
    import jax

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
        return forced, "forced by BENCH_PLATFORM"

    # healthy init is ~30s (compile included); a wedged tunnel hangs at
    # the chip claim, so waiting longer than ~2.5 min per try only eats
    # into the driver's overall bench budget before the CPU fallback.
    # Failure modes differ (VERDICT r4 #1 hardening):
    #   - probe TIMEOUT  -> client wedge at the chip claim; terminal
    #     (re-probing burns budget, and killing probes can renew the
    #     stuck lease — round-3/4 lesson)
    #   - probe ERROR (connection refused / init exception) -> service
    #     down; retrying over a longer backoff window is cheap and is
    #     exactly how round 4's test lane caught its recovery window
    tries = int(os.environ.get("BENCH_INIT_TRIES", "5"))
    timeout = int(os.environ.get("BENCH_INIT_TIMEOUT", "150"))
    last = ""
    for i in range(tries):
        if _remaining() < timeout + 60:
            last = ("budget exhausted before attempt %d; last error: %s"
                    % (i + 1, last or "none"))
            tries = i
            break
        ok, last = _probe_axon(timeout)
        if ok:
            jax.config.update("jax_platforms", "axon")
            return "axon", ""
        tail_lines = last.strip().splitlines()
        print("bench: axon probe attempt %d/%d failed: %s"
              % (i + 1, tries, tail_lines[-1] if tail_lines else "?"),
              file=sys.stderr, flush=True)
        if ok is None:  # timeout — hung tunnel, retries are wasted budget
            tries = i + 1
            break
        if i < tries - 1:  # no pointless backoff after the last attempt
            time.sleep(min(60, 15 * (i + 1)))
    jax.config.update("jax_platforms", "cpu")
    return "cpu", "axon unavailable after %d tries: %s" % (tries, last[-200:])


def _emit_jsonl(row):
    with open(JSONL_PATH, "a") as f:
        f.write(json.dumps(row) + "\n")


def _timed_steps(step, x, y, iters, warmup):
    # Warmup syncs every step (surfaces compile/runtime errors eagerly and
    # never leaves a deep queue if we die). The timed window dispatches
    # steps back-to-back and syncs once per SYNC_EVERY: the axon tunnel has
    # ~100ms+ RTT, so a per-step wait_to_read measures round-trips, not
    # device throughput (round-3 regression: 2025 -> 364 img/s from this
    # alone). Real training is pipelined the same way — the reference's
    # async engine never syncs per step either (SURVEY §3.1); the queue
    # stays bounded by iters, which is <= 50 everywhere.
    # Returns (wall seconds, framework launch dispatches, host syncs) for
    # the timed window — the launch count (profiler.launch_count) makes
    # fusion health visible per row (a fused step is exactly 1/step), and
    # the host-sync count makes ASYNC health visible: a K-deep engine
    # window shows <= 1/K framework reads per step.
    from mxnet_tpu import profiler, tuning

    sync_every = int(os.environ.get("BENCH_SYNC_EVERY", "0"))  # 0 = window end
    if not sync_every and iters > 50:
        sync_every = 50  # bound the un-synced queue (tunnel-wedge guard)
    # compile + tune-cache accounting spans warmup AND the timed window:
    # the warmup steps are where a cold config pays its JIT, and the row
    # must expose that cost (cold-vs-warm is invisible in step_time_ms —
    # by the timed window everything is compiled either way)
    c0 = tuning.compile_stats()
    tc0 = _tune_cache_counts()
    loss = None
    for _ in range(warmup):
        loss = step(x, y)
        loss.wait_to_read()
    t0 = time.perf_counter()
    l0 = profiler.launch_count()
    h0 = profiler.host_sync_count()
    for i in range(iters):
        loss = step(x, y)
        if sync_every and (i + 1) % sync_every == 0:
            loss.wait_to_read()
    loss.wait_to_read()
    c1 = tuning.compile_stats()
    tc1 = _tune_cache_counts()
    extras = {
        "compile_time_ms": round(
            (c1["compile_seconds"] - c0["compile_seconds"]) * 1e3, 1),
        "compiles": c1["compiles"] - c0["compiles"],
        "tune_cache": {"hits": tc1[0] - tc0[0],
                       "misses": tc1[1] - tc0[1]},
    }
    return (time.perf_counter() - t0, profiler.launch_count() - l0,
            profiler.host_sync_count() - h0, extras)


def _tune_cache_counts():
    """(hits, misses) of the tuning-table lookup counters."""
    from mxnet_tpu import telemetry

    reg = telemetry.registry()
    out = []
    for name in ("mxt_tune_cache_hits_total", "mxt_tune_cache_misses_total"):
        fam = reg.get(name)
        out.append(int(fam.value) if fam is not None else 0)
    return tuple(out)


def _step_stats(dt, launches, syncs, iters, extras=None):
    """The per-row fusion-health fields every _timed_steps config emits."""
    row = {
        "step_time_ms": round(dt / iters * 1e3, 3),
        "launches_per_step": round(launches / iters, 2),
        "host_syncs_per_step": round(syncs / iters, 3),
    }
    if extras:
        row.update(extras)
    return row


def _mfu(samples_per_sec, flops_per_sample, platform):
    if not flops_per_sample or platform == "cpu":
        return None
    return round(samples_per_sec * flops_per_sample / V5E_PEAK_FLOPS, 4)


def bench_resnet50(platform, dtype, batch=None, remat="env"):
    """remat: "env" reads BENCH_REMAT; "none" forces no remat (the
    variant sweep needs to express 'explicitly off' even when the stage
    env sets BENCH_REMAT); any other value is a remat policy name."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import model_zoo
    from mxnet_tpu import parallel

    small = platform == "cpu"
    if batch is None:
        batch = int(os.environ.get("BENCH_BATCH", "8" if small else "64"))
    if remat == "env":
        remat = os.environ.get("BENCH_REMAT") or None
    elif remat == "none":
        remat = None
    iters = int(os.environ.get("BENCH_ITERS", "3" if small else "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "1" if small else "3"))
    # channels-last is the MXU-native layout (gluon/nn/layout.py); NCHW
    # stays selectable for A/B runs
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")

    mx.random.seed(0)
    from mxnet_tpu.gluon import nn as _nn
    with _nn.layout_scope(layout):
        net = model_zoo.get_model("resnet50_v1", classes=1000)
    net.initialize()
    if dtype == "bfloat16":
        net.cast("bfloat16")  # MXU-native; BN stats stay f32 inside the op

    in_shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x0 = nd.zeros(in_shape, dtype=dtype)
    net(x0)  # resolve deferred shapes eagerly

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        remat=remat)

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, in_shape).astype(np.float32))
    x = x.astype(dtype)
    y = nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))

    dt, launches, syncs, extras = _timed_steps(step, x, y, iters, warmup)
    img_s = batch * iters / dt

    dump = os.environ.get("BENCH_DUMP_HLO")
    # post-run: one AOT compile, shared with the MFU accounting — but a
    # compile can take minutes, so only start it with real headroom
    # (being killed mid-compile is the tunnel-wedge mechanism)
    if dump and _remaining() > 300:
        try:
            step.dump_hlo(x, y, dump)
        except Exception as e:  # noqa: BLE001 — diagnostics only
            print("bench: HLO dump failed: %r" % (e,), file=sys.stderr)
    elif dump:
        print("bench: skipping HLO dump — %.0fs budget left" % _remaining(),
              file=sys.stderr)

    flops_per_img = step.flops_per_step(x, y)
    if flops_per_img:
        flops_per_img /= batch
    else:
        flops_per_img = 3 * 8.2e9  # fwd ~4.1 GMACs @224; train ≈ 3x fwd

    row = {
        "config": "resnet50_v1_train", "chips": 1, "batch_size": batch,
        "dtype": dtype, "layout": layout,
        "remat": remat,
        "images_or_tokens_per_sec_per_chip": round(img_s, 2),
        "mfu": _mfu(img_s, flops_per_img, platform), "platform": platform,
        "flops_per_sample": flops_per_img,
        **_step_stats(dt, launches, syncs, iters, extras),
    }
    _emit_jsonl(row)
    return img_s, row


def bench_bert_mlm(platform, dtype):
    """BERT-base MLM pretraining step throughput (BASELINE config 3)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import Block, model_zoo
    from mxnet_tpu import parallel

    small = platform == "cpu"
    seq_len = int(os.environ.get("BENCH_BERT_SEQLEN", "32" if small
                                 else "128"))
    batch = int(os.environ.get("BENCH_BERT_BATCH", "4" if small else "32"))
    iters = int(os.environ.get("BENCH_BERT_ITERS", "2" if small else "10"))
    warmup = int(os.environ.get("BENCH_BERT_WARMUP", "1" if small else "2"))

    mx.random.seed(0)
    if small:
        bert = model_zoo.bert.bert_3_64_2(use_classifier=False, dropout=0.0)
        vocab = 1000
    else:
        bert = model_zoo.bert.bert_12_768_12(use_classifier=False,
                                             dropout=0.0,
                                             max_length=seq_len)
        vocab = 30522

    class _MLMNet(Block):
        """Single-input wrapper so ShardedTrainStep can drive BERT:
        token ids in, vocabulary scores out (all positions)."""

        def __init__(self, bert_model):
            super().__init__(prefix="bench_mlm_")
            with self.name_scope():
                self.bert = bert_model

        def forward(self, x):
            from mxnet_tpu import nd as F

            seq, _ = self.bert(x, F.zeros_like(x))
            return self.bert.decode_mlm(seq)

    net = _MLMNet(bert)
    net.initialize()
    if dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (batch, seq_len)).astype(np.float32))
    y = nd.array(rng.randint(0, vocab, (batch, seq_len)).astype(np.float32))
    net(x)  # resolve deferred shapes

    # BENCH_BERT_PATH selects what a user script gets (SURVEY §3.1):
    #   trainer    — the CANONICAL Gluon loop (hybridize + record/backward
    #                + fused donated Trainer.step): forward launch +
    #                per-node backward walk + 1 optimizer launch
    #   fused_step — the same canonical API through Trainer.fuse_step
    #                (gluon.CachedTrainStep): the WHOLE step is one
    #                donated launch, like ShardedTrainStep but without
    #                leaving the Gluon surface
    #   sharded    — ShardedTrainStep (default; the headline config)
    # A sharded step provides the flop accounting for ALL paths (same
    # model/loss/optimizer); on the trainer/fused_step paths it is built
    # only AFTER the timed window so its Adam state doesn't inflate HBM
    # use during the measurement.
    path = os.environ.get("BENCH_BERT_PATH", "sharded")

    def make_sharded():
        return parallel.ShardedTrainStep(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 1e-4})

    if path == "trainer":
        from mxnet_tpu import autograd as ag

        bert.hybridize()  # _MLMNet is a plain Block; the BERT core jits
        loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                                   {"learning_rate": 1e-4})

        def step(xb, yb):
            with ag.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
            return loss
        sharded = None
    elif path == "fused_step":
        loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                                   {"learning_rate": 1e-4})
        step = trainer.fuse_step(net, loss_fn)
        sharded = None
    else:
        sharded = step = make_sharded()

    dt, launches, syncs, extras = _timed_steps(step, x, y, iters, warmup)
    tok_s = batch * seq_len * iters / dt

    flops_per_tok = (sharded or make_sharded()).flops_per_step(x, y)
    if flops_per_tok:
        flops_per_tok /= batch * seq_len

    config_name = {"trainer": "bert_base_mlm_train_gluon",
                   "fused_step": "bert_base_mlm_train_fused_step"}.get(
                       path, "bert_base_mlm_train")
    row = {
        "config": config_name, "chips": 1,
        "batch_size": batch,
        "seq_len": seq_len, "dtype": dtype,
        "images_or_tokens_per_sec_per_chip": round(tok_s, 2),
        "mfu": _mfu(tok_s, flops_per_tok, platform), "platform": platform,
        "flops_per_sample": flops_per_tok,
        **_step_stats(dt, launches, syncs, iters, extras),
    }
    _emit_jsonl(row)
    return tok_s, row


def bench_lenet_mnist(platform, dtype):
    """LeNet-5 on MNIST-shaped data via Gluon (BASELINE config 1)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import parallel

    small = platform == "cpu"
    batch = int(os.environ.get("BENCH_LENET_BATCH", "32" if small
                               else "256"))
    iters = int(os.environ.get("BENCH_LENET_ITERS", "3" if small else "20"))
    warmup = int(os.environ.get("BENCH_LENET_WARMUP", "1" if small
                                else "3"))

    mx.random.seed(0)
    net = nn.HybridSequential(prefix="lenet_")
    with net.name_scope():
        net.add(nn.Conv2D(20, kernel_size=5, activation="tanh"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Conv2D(50, kernel_size=5, activation="tanh"),
                nn.MaxPool2D(pool_size=2, strides=2),
                nn.Flatten(),
                nn.Dense(500, activation="tanh"),
                nn.Dense(10))
    net.initialize()
    if dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(0, 1, (batch, 1, 28, 28)).astype(np.float32))
    x = x.astype(dtype)
    y = nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
    net(x)

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05, "momentum": 0.9})

    dt, launches, syncs, extras = _timed_steps(step, x, y, iters, warmup)
    img_s = batch * iters / dt
    flops = step.flops_per_step(x, y)
    if flops:
        flops /= batch

    row = {
        "config": "lenet_mnist_train", "chips": 1, "batch_size": batch,
        "dtype": dtype,
        "images_or_tokens_per_sec_per_chip": round(img_s, 2),
        "mfu": _mfu(img_s, flops, platform), "platform": platform,
        "flops_per_sample": flops,
        **_step_stats(dt, launches, syncs, iters, extras),
    }
    _emit_jsonl(row)
    return img_s, row


def bench_lstm_ptb(platform, dtype):
    """LSTM language model, PTB 'medium' shape (BASELINE config 4;
    fused lax.scan RNN, ref: src/operator/rnn.cc cuDNN fused RNN)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import Block, nn, rnn
    from mxnet_tpu import parallel

    small = platform == "cpu"
    seq_len = int(os.environ.get("BENCH_LSTM_SEQLEN", "8" if small
                                 else "35"))
    batch = int(os.environ.get("BENCH_LSTM_BATCH", "4" if small else "32"))
    iters = int(os.environ.get("BENCH_LSTM_ITERS", "2" if small else "10"))
    warmup = int(os.environ.get("BENCH_LSTM_WARMUP", "1" if small else "2"))
    hidden = 64 if small else 650
    layers = 1 if small else 2
    vocab = 1000 if small else 10000

    mx.random.seed(0)

    class _LM(Block):
        def __init__(self):
            super().__init__(prefix="ptb_")
            with self.name_scope():
                self.embed = nn.Embedding(vocab, hidden)
                self.lstm = rnn.LSTM(hidden_size=hidden, num_layers=layers,
                                     layout="NTC")
                self.decoder = nn.Dense(vocab, flatten=False)

        def forward(self, x):
            return self.decoder(self.lstm(self.embed(x)))

    net = _LM()
    net.initialize()
    if dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (batch, seq_len)).astype(np.float32))
    y = nd.array(rng.randint(0, vocab, (batch, seq_len)).astype(np.float32))
    net(x)

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 1.0})

    dt, launches, syncs, extras = _timed_steps(step, x, y, iters, warmup)
    tok_s = batch * seq_len * iters / dt
    flops_per_tok = step.flops_per_step(x, y)
    if flops_per_tok:
        flops_per_tok /= batch * seq_len

    row = {
        "config": "lstm_ptb_train", "chips": 1, "batch_size": batch,
        "seq_len": seq_len, "dtype": dtype,
        "wavefront": bool(__import__("mxnet_tpu").config.get(
            "MXT_RNN_WAVEFRONT")),
        "images_or_tokens_per_sec_per_chip": round(tok_s, 2),
        "mfu": _mfu(tok_s, flops_per_tok, platform), "platform": platform,
        "flops_per_sample": flops_per_tok,
        **_step_stats(dt, launches, syncs, iters, extras),
    }
    _emit_jsonl(row)
    return tok_s, row


def bench_wide_deep(platform, dtype):
    """Wide&Deep CTR throughput (BASELINE config 5; ref:
    example/sparse/wide_deep). The jitted step keeps embeddings dense
    (XLA scatter-add); the framework-level sparse path is covered by
    tests/test_sparse.py."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import Block, model_zoo
    from mxnet_tpu import parallel

    small = platform == "cpu"
    batch = int(os.environ.get("BENCH_WD_BATCH", "16" if small else "2048"))
    iters = int(os.environ.get("BENCH_WD_ITERS", "2" if small else "20"))
    warmup = int(os.environ.get("BENCH_WD_WARMUP", "1" if small else "3"))
    n_wide, n_deep = 8, 4
    wide_vocab = 1000 if small else 100000
    deep_vocab = 500 if small else 10000

    mx.random.seed(0)
    wd = model_zoo.wide_deep(
        wide_vocab=wide_vocab, deep_vocab=deep_vocab,
        embed_dim=16, hidden=(64, 32), classes=2, sparse_grad=False)

    class _Packed(Block):
        """Single-input wrapper: columns [0:n_wide) are wide ids, the
        rest deep ids — lets ShardedTrainStep drive the two towers."""

        def __init__(self):
            super().__init__(prefix="wd_pack_")
            with self.name_scope():
                self.wd = wd

        def forward(self, x):
            return self.wd(x[:, :n_wide], x[:, n_wide:])

    net = _Packed()
    net.initialize()
    if dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    xw = rng.randint(0, wide_vocab, (batch, n_wide))
    xd = rng.randint(0, deep_vocab, (batch, n_deep))
    x = nd.array(np.concatenate([xw, xd], axis=1).astype(np.float32))
    y = nd.array(rng.randint(0, 2, (batch,)).astype(np.float32))
    net(x)

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-3})

    dt, launches, syncs, extras = _timed_steps(step, x, y, iters, warmup)
    samp_s = batch * iters / dt
    flops = step.flops_per_step(x, y)
    if flops:
        flops /= batch

    # MFU is near-meaningless for this config (tiny gemms, lookup-bound);
    # the device-side metric that matters is embedding traffic: per
    # sample, each id costs a gather (fwd) + scatter-add (bwd) row of
    # embed_dim (deep) / 1 (wide logistic weights), at the table dtype
    # (bf16 after net.cast, else f32).
    esize = 2 if dtype == "bfloat16" else 4  # net.cast covers the tables
    emb_bytes_per_sample = 2 * esize * (n_wide * 1 + n_deep * 16)
    row = {
        "config": "wide_deep_train", "chips": 1, "batch_size": batch,
        "dtype": dtype,
        "images_or_tokens_per_sec_per_chip": round(samp_s, 2),
        "mfu": _mfu(samp_s, flops, platform), "platform": platform,
        "flops_per_sample": flops,
        "embedding_bytes_per_sec": round(samp_s * emb_bytes_per_sample),
        **_step_stats(dt, launches, syncs, iters, extras),
    }
    _emit_jsonl(row)
    return samp_s, row


def bench_input_pipeline(platform, dtype):
    """Host-feed ceiling (SURVEY hard part #4; VERDICT r4 #4): JPEG
    decode + augment + batch through ImageRecordIter on ImageNet-shaped
    records, NO model — measures whether the host can out-feed the
    chip's train rate (target ≥2× config-2's img/s). Pure host work;
    the `platform` tag records the host context it ran under."""
    import shutil
    import tempfile

    import numpy as np

    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter

    del dtype
    n_img = int(os.environ.get("BENCH_PIPE_IMAGES", "192"))
    batch = int(os.environ.get("BENCH_PIPE_BATCH", "64"))
    threads = int(os.environ.get("BENCH_PIPE_THREADS",
                                 str(max(1, (os.cpu_count() or 1)))))
    epochs = int(os.environ.get("BENCH_PIPE_EPOCHS", "3"))

    tmp = tempfile.mkdtemp(prefix="mxt_pipe_bench_")
    try:
        frec, fidx = os.path.join(tmp, "i.rec"), os.path.join(tmp, "i.idx")
        w = recordio.MXIndexedRecordIO(fidx, frec, "w")
        rng = np.random.RandomState(0)
        # piecewise-smooth synthetic photos: JPEG entropy (and therefore
        # decode cost) in the ballpark of natural images, unlike pure
        # noise which decodes slow and unlike flat color which is free
        for i in range(n_img):
            base = rng.randint(0, 255, (8, 8, 3))
            img = np.kron(base, np.ones((32, 32, 1)))
            img = np.clip(img + rng.randint(0, 12, img.shape),
                          0, 255).astype(np.uint8)  # no uint8 wraparound
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i % 1000), i, 0), img,
                img_fmt=".jpg", quality=90))
        w.close()

        it = ImageRecordIter(
            path_imgrec=frec, path_imgidx=fidx,
            data_shape=(3, 224, 224), batch_size=batch, shuffle=True,
            rand_crop=True, rand_mirror=True,
            preprocess_threads=threads)
        # warm epoch (thread spin-up, page cache), then timed epochs
        for b in it:
            pass
        it.reset()
        seen = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            for b in it:
                seen += b.data[0].shape[0]
            it.reset()
        dt = time.perf_counter() - t0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    img_s = seen / dt
    row = {
        "config": "input_pipeline_only", "chips": 0, "batch_size": batch,
        "dtype": "uint8->float32", "preprocess_threads": threads,
        "host_cores": os.cpu_count(),
        "images_or_tokens_per_sec_per_chip": round(img_s, 2),
        "mfu": None, "platform": platform,
        "flops_per_sample": None,
        "note": "host-only: decode(224x224 jpeg)+augment+batch, no model",
    }
    _emit_jsonl(row)
    return img_s, row


def bench_streaming_input(platform, dtype):
    """Streaming data plane A/B (mxnet_tpu/data_plane/): the SAME
    synthetic recordio shards consumed by (a) the per-process gluon
    DataLoader (locked shared reader + per-sample decode in
    ``__getitem__`` — the pattern the data plane replaces) and (b) the
    chunk-leased decode-worker fleet as TWO in-process hosts sharing one
    lease ledger. Both legs run the full feed path (decode + augment +
    batchify + NDArray device wrap) and report consumer-observed
    ``data_wait`` per step; the plane leg also reports the ledger's
    steal count. The plane's per-core edge is algorithmic, not just
    parallel: chunk-sequential reads, decode straight into preallocated
    batch slots (no per-sample Python/np.stack pass), and JPEG
    draft-mode DCT downscaling when a resize target is set. Legs are
    shape-warm: each runs one discarded warm epoch first (the PR 12
    bench gotcha)."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from mxnet_tpu import data_plane, recordio
    from mxnet_tpu.gluon.data import DataLoader, Dataset
    from mxnet_tpu.io.io import _crop, _resize_short
    from mxnet_tpu.recordio import unpack_img

    del dtype  # host decode A/B: uint8 jpeg -> float32 both ways
    n_img = int(os.environ.get("BENCH_SIAB_IMAGES", "192"))
    hw = int(os.environ.get("BENCH_SIAB_HW", "192"))
    resize = int(os.environ.get("BENCH_SIAB_RESIZE", "96"))
    crop = int(os.environ.get("BENCH_SIAB_CROP", "64"))
    batch = int(os.environ.get("BENCH_SIAB_BATCH", "32"))
    epochs = int(os.environ.get("BENCH_SIAB_EPOCHS", "2"))
    workers = int(os.environ.get("BENCH_SIAB_WORKERS", "2"))
    chunk = int(os.environ.get("BENCH_SIAB_CHUNK", "32"))

    tmp = tempfile.mkdtemp(prefix="mxt_siab_bench_")
    try:
        rng = np.random.RandomState(0)
        shards = []
        gid = 0
        for s in range(2):
            frec = os.path.join(tmp, "part-%d.rec" % s)
            fidx = os.path.join(tmp, "part-%d.idx" % s)
            w = recordio.MXIndexedRecordIO(fidx, frec, "w")
            for _ in range(n_img // 2):
                base = rng.randint(0, 255, (8, 8, 3))
                img = np.kron(base, np.ones((hw // 8, hw // 8, 1)))
                img = np.clip(img + rng.randint(0, 12, img.shape),
                              0, 255).astype(np.uint8)
                w.write_idx(gid, recordio.pack_img(
                    recordio.IRHeader(0, float(gid % 10), gid, 0), img,
                    img_fmt=".jpg", quality=90))
                gid += 1
            w.close()
            shards.append(frec)

        class _RecDataset(Dataset):
            """The per-process pattern: one shared (locked) reader,
            per-sample decode in __getitem__."""

            def __init__(self, recs):
                self._readers = []
                self._index = []
                self._lock = threading.Lock()
                for si, r in enumerate(recs):
                    rd = recordio.MXIndexedRecordIO(
                        os.path.splitext(r)[0] + ".idx", r, "r")
                    self._readers.append(rd)
                    self._index.extend((si, k) for k in rd.keys)
                self._rng = np.random.RandomState(0)

            def __len__(self):
                return len(self._index)

            def __getitem__(self, i):
                si, k = self._index[i]
                with self._lock:
                    raw = self._readers[si].read_idx(k)
                header, img = unpack_img(raw)
                img = _resize_short(img, resize)
                img = _crop(img, crop, crop, rand=True, rng=self._rng)
                return img.astype(np.float32), np.float32(header.label)

        def leg_loader():
            ds = _RecDataset(shards)
            n_batches = [0]

            def one_epoch():
                dl = DataLoader(ds, batch_size=batch, shuffle=True,
                                num_workers=workers, thread_pool=True,
                                last_batch="keep")
                seen = 0
                for b in dl:
                    seen += b[0].shape[0]
                    n_batches[0] += 1
                return seen

            one_epoch()  # warm: thread spin-up, page cache
            n_batches[0] = 0
            seen = 0
            t0 = time.perf_counter()
            for _ in range(epochs):
                seen += one_epoch()
            dt = time.perf_counter() - t0
            return seen / dt, dt / max(1, n_batches[0])

        manifest = data_plane.ShardManifest(shards, chunk_records=chunk)
        decoder = data_plane.ImageDecoder(
            (3, crop, crop), rand_crop=True, resize=resize,
            layout="NHWC", dtype="float32")

        def plane_epoch(seed, epoch):
            """One epoch as TWO in-process hosts over a shared ledger
            (each host: `workers` decode threads), aggregate img/s."""
            ledger = data_plane.ChunkLedger()
            counts = {}
            waits = {}

            def host(h):
                # heterogeneous hosts (host 1 decodes with ONE worker):
                # the realistic slow-peer scenario — host 0 runs dry
                # first and steals host 1's tail, so the row's steal
                # count exercises the cross-host path
                loader = data_plane.StreamingDataLoader(
                    manifest, batch, decoder, host_id=h, num_hosts=2,
                    ledger=ledger, seed=seed, start_epoch=epoch,
                    num_workers=workers if h == 0 else 1)
                seen = nb = 0
                for b in loader:
                    seen += b.data.shape[0]
                    nb += 1
                counts[h] = seen
                waits[h] = nb

            ts = [threading.Thread(target=host, args=(h,))
                  for h in (0, 1)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            stats = ledger.stats()
            # fleet-level input latency: wall time per DELIVERED batch
            # (the same definition the baseline leg's single consumer
            # measures — its loop time per batch)
            wait = dt / max(1, sum(waits.values()))
            return sum(counts.values()) / dt, wait, stats

        def leg_plane():
            plane_epoch(0, 0)  # warm
            seen_rate = steals = 0
            waits = []
            for e in range(epochs):
                r, w, stats = plane_epoch(0, e + 1)
                seen_rate += r
                waits.append(w)
                steals += stats["steals"]
            return seen_rate / epochs, max(waits), steals

        loader_img_s, loader_wait = leg_loader()
        plane_img_s, plane_wait, steals = leg_plane()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    speedup = plane_img_s / loader_img_s if loader_img_s else 0.0
    row = {
        "config": "streaming_input_ab", "chips": 0, "batch_size": batch,
        "dtype": "uint8->float32", "platform": platform,
        "host_cores": os.cpu_count(), "decode_workers": workers,
        "hosts": 2, "chunk_records": chunk,
        "dataloader_img_per_sec": round(loader_img_s, 2),
        "data_plane_img_per_sec": round(plane_img_s, 2),
        "dataloader_data_wait_ms_per_step": round(loader_wait * 1e3, 3),
        "data_plane_data_wait_ms_per_step": round(plane_wait * 1e3, 3),
        "steal_count": int(steals),
        "images_or_tokens_per_sec_per_chip": round(plane_img_s, 2),
        "mfu": None, "flops_per_sample": None,
        "streaming_input_speedup": round(speedup, 4),
        "note": "host decode A/B on %dx%d jpeg -> resize %d -> crop %d; "
                "plane uses jpeg draft-mode DCT downscale + slot decode "
                "(deterministic; pixel values differ from the full-res "
                "decode+resize baseline by construction)"
                % (hw, hw, resize, crop),
    }
    _emit_jsonl(row)
    return speedup, row


def bench_async_ab(platform, dtype):
    """Async dispatch A/B (engine.py): the SAME fused Gluon step with the
    non-finite guard compiled in, run with the in-flight window at K=1
    (synchronous: every step's flag read back immediately) and at K=4
    (deferred: one mask read retires 4 steps' flags). The delta is pure
    dispatch/round-trip overhead — visible on CPU, dominant on the axon
    tunnel where every host read costs ~100ms+ RTT."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import engine, nd, profiler
    from mxnet_tpu.gluon import Trainer, nn

    del dtype  # f32: the A/B isolates dispatch, not math throughput
    batch = int(os.environ.get("BENCH_AB_BATCH", "64"))
    hidden = int(os.environ.get("BENCH_AB_HIDDEN", "256"))
    iters = int(os.environ.get("BENCH_AB_ITERS", "40"))
    warmup = int(os.environ.get("BENCH_AB_WARMUP", "3"))
    window = int(os.environ.get("BENCH_AB_INFLIGHT", "4"))

    prev_guard = os.environ.get("MXT_SKIP_NONFINITE")
    os.environ["MXT_SKIP_NONFINITE"] = "1"
    try:
        def run(k):
            mx.random.seed(0)
            net = nn.Sequential(prefix="ab%d_" % k)
            with net.name_scope():
                net.add(nn.Dense(hidden, activation="relu"),
                        nn.Dense(hidden, activation="relu"),
                        nn.Dense(10))
            net.initialize()
            tr = Trainer(net.collect_params(), "adam",
                         {"learning_rate": 1e-3})
            step = tr.fuse_step(net,
                                mx.gluon.loss.SoftmaxCrossEntropyLoss())
            rng = np.random.RandomState(0)
            x = nd.array(rng.uniform(-1, 1, (batch, 32)).astype(np.float32))
            y = nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
            with engine.bulk(k):
                for _ in range(warmup):
                    step(x, y).wait_to_read()
                t0 = time.perf_counter()
                h0 = profiler.host_sync_count()
                for _ in range(iters):
                    step(x, y)
                nd.waitall()
                dt = time.perf_counter() - t0
                syncs = profiler.host_sync_count() - h0
            return dt / iters * 1e3, syncs / iters

        sync_ms, sync_sps = run(1)
        async_ms, async_sps = run(window)
    finally:
        if prev_guard is None:
            os.environ.pop("MXT_SKIP_NONFINITE", None)
        else:
            os.environ["MXT_SKIP_NONFINITE"] = prev_guard

    speedup = sync_ms / async_ms if async_ms else 0.0
    row = {
        "config": "fused_step_async_ab", "chips": 1, "batch_size": batch,
        "dtype": "float32", "platform": platform,
        "inflight_window": window,
        "sync_step_time_ms": round(sync_ms, 3),
        "async_step_time_ms": round(async_ms, 3),
        "host_syncs_per_step_sync": round(sync_sps, 3),
        "host_syncs_per_step_async": round(async_sps, 3),
        "images_or_tokens_per_sec_per_chip": round(
            batch * 1e3 / async_ms, 2),
        "mfu": None, "flops_per_sample": None,
        "async_speedup": round(speedup, 3),
    }
    _emit_jsonl(row)
    return speedup, row


def bench_telemetry_ab(platform, dtype):
    """Telemetry overhead A/B (telemetry.py): the SAME fused Gluon step
    run with the telemetry JSONL sink OFF and then ON. The registry's
    histograms/spans are host-side wall-clock only, so the contract is
    (a) IDENTICAL host_syncs_per_step both ways — telemetry adds zero
    device reads to the hot path — and (b) <= ~3% step-time overhead
    with the sink on (=~0 when disabled: the sink check is one dict
    lookup). The row self-reports both so the driver can gate on them."""
    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import engine, nd, profiler, telemetry
    from mxnet_tpu.gluon import Trainer, nn

    del dtype  # f32: the A/B isolates instrumentation, not math
    batch = int(os.environ.get("BENCH_TAB_BATCH", "64"))
    hidden = int(os.environ.get("BENCH_TAB_HIDDEN", "256"))
    iters = int(os.environ.get("BENCH_TAB_ITERS", "40"))
    warmup = int(os.environ.get("BENCH_TAB_WARMUP", "3"))
    window = int(os.environ.get("BENCH_TAB_INFLIGHT", "4"))

    jsonl = tempfile.mktemp(prefix="mxt_bench_telemetry_",
                            suffix=".jsonl")
    prev_sink = os.environ.get("MXT_TELEMETRY_JSONL")

    def run(tag, sink_on):
        if sink_on:
            os.environ["MXT_TELEMETRY_JSONL"] = jsonl
        else:
            os.environ.pop("MXT_TELEMETRY_JSONL", None)
        try:
            mx.random.seed(0)
            net = nn.Sequential(prefix="tab_%s_" % tag)
            with net.name_scope():
                net.add(nn.Dense(hidden, activation="relu"),
                        nn.Dense(hidden, activation="relu"),
                        nn.Dense(10))
            net.initialize()
            tr = Trainer(net.collect_params(), "adam",
                         {"learning_rate": 1e-3})
            step = tr.fuse_step(net,
                                mx.gluon.loss.SoftmaxCrossEntropyLoss())
            rng = np.random.RandomState(0)
            x = nd.array(rng.uniform(-1, 1,
                                     (batch, 32)).astype(np.float32))
            y = nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
            with engine.bulk(window):
                for _ in range(warmup):
                    step(x, y).wait_to_read()
                t0 = time.perf_counter()
                h0 = profiler.host_sync_count()
                for _ in range(iters):
                    step(x, y)
                nd.waitall()
                dt = time.perf_counter() - t0
                syncs = profiler.host_sync_count() - h0
            return dt / iters * 1e3, syncs / iters
        finally:
            if prev_sink is None:
                os.environ.pop("MXT_TELEMETRY_JSONL", None)
            else:
                os.environ["MXT_TELEMETRY_JSONL"] = prev_sink

    off_ms, off_sps = run("off", False)
    on_ms, on_sps = run("on", True)
    telemetry.flush()
    try:
        with open(jsonl) as f:
            events = sum(1 for _ in f)
        os.remove(jsonl)
    except OSError:
        events = 0

    overhead = on_ms / off_ms if off_ms else 0.0
    row = {
        "config": "fused_step_telemetry_ab", "chips": 1,
        "batch_size": batch, "dtype": "float32", "platform": platform,
        "inflight_window": window,
        "telemetry_off_step_time_ms": round(off_ms, 3),
        "telemetry_on_step_time_ms": round(on_ms, 3),
        "host_syncs_per_step_off": round(off_sps, 3),
        "host_syncs_per_step_on": round(on_sps, 3),
        "jsonl_events": events,
        "images_or_tokens_per_sec_per_chip": round(
            batch * 1e3 / on_ms, 2),
        "mfu": None, "flops_per_sample": None,
        "telemetry_overhead": round(overhead, 4),
    }
    _emit_jsonl(row)
    return overhead, row


def bench_diagnostics_ab(platform, dtype):
    """Diagnostics overhead A/B (diagnostics.py): the SAME fused Gluon
    step run with the diagnostics layer disarmed (no flight-recorder
    tap, no watchdog) and then fully armed (flight recorder + watchdog
    daemon in report mode + HBM ledger, which is always on). The
    contract mirrors the telemetry A/B: (a) IDENTICAL host_syncs_per_step
    both ways — the watchdog observes heartbeat counters and the ledger
    observes shape metadata, so diagnostics add ZERO device reads to the
    hot path — and (b) step-time overhead within noise. The row
    self-reports both so the driver can gate on them."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import diagnostics, engine, nd, profiler
    from mxnet_tpu.gluon import Trainer, nn

    del dtype  # f32: the A/B isolates instrumentation, not math
    batch = int(os.environ.get("BENCH_DAB_BATCH", "64"))
    hidden = int(os.environ.get("BENCH_DAB_HIDDEN", "256"))
    iters = int(os.environ.get("BENCH_DAB_ITERS", "40"))
    warmup = int(os.environ.get("BENCH_DAB_WARMUP", "3"))
    window = int(os.environ.get("BENCH_DAB_INFLIGHT", "4"))

    def run(tag, armed):
        if armed:
            # recorder tap + watchdog thread (timeout far above any
            # real step so it never fires mid-bench)
            diagnostics.enable(timeout=3600.0, action="report",
                               handlers=False)
        else:
            diagnostics.disable()
        try:
            mx.random.seed(0)
            net = nn.Sequential(prefix="dab_%s_" % tag)
            with net.name_scope():
                net.add(nn.Dense(hidden, activation="relu"),
                        nn.Dense(hidden, activation="relu"),
                        nn.Dense(10))
            net.initialize()
            tr = Trainer(net.collect_params(), "adam",
                         {"learning_rate": 1e-3})
            step = tr.fuse_step(net,
                                mx.gluon.loss.SoftmaxCrossEntropyLoss())
            rng = np.random.RandomState(0)
            x = nd.array(rng.uniform(-1, 1,
                                     (batch, 32)).astype(np.float32))
            y = nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
            with engine.bulk(window):
                for _ in range(warmup):
                    step(x, y).wait_to_read()
                t0 = time.perf_counter()
                h0 = profiler.host_sync_count()
                for _ in range(iters):
                    step(x, y)
                nd.waitall()
                dt = time.perf_counter() - t0
                syncs = profiler.host_sync_count() - h0
            return dt / iters * 1e3, syncs / iters
        finally:
            diagnostics.disable()

    off_ms, off_sps = run("off", False)
    on_ms, on_sps = run("on", True)
    ring_events = len(diagnostics.recorder())
    diagnostics.disable()

    overhead = on_ms / off_ms if off_ms else 0.0
    row = {
        "config": "diagnostics_overhead_ab", "chips": 1,
        "batch_size": batch, "dtype": "float32", "platform": platform,
        "inflight_window": window,
        "diagnostics_off_step_time_ms": round(off_ms, 3),
        "diagnostics_on_step_time_ms": round(on_ms, 3),
        "host_syncs_per_step_off": round(off_sps, 3),
        "host_syncs_per_step_on": round(on_sps, 3),
        "flight_recorder_events": ring_events,
        "hbm_pools": sorted(diagnostics.ledger().snapshot()),
        "images_or_tokens_per_sec_per_chip": round(
            batch * 1e3 / on_ms, 2),
        "mfu": None, "flops_per_sample": None,
        "diagnostics_overhead": round(overhead, 4),
    }
    _emit_jsonl(row)
    return overhead, row


_COLD_WARM_CODE = r"""
import json, os, sys, time
import jax
jax.config.update("jax_platforms", os.environ["BENCH_CW_PLATFORM"])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, tuning
from mxnet_tpu.gluon import Trainer, nn

mx.random.seed(0)
net = nn.Sequential(prefix="cw_")
with net.name_scope():
    net.add(nn.Dense(128, activation="relu"), nn.Dense(10))
net.initialize()
tr = Trainer(net.collect_params(), "sgd",
             {"learning_rate": 0.1, "momentum": 0.9})
step = tr.fuse_step(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
rng = np.random.RandomState(0)
x = nd.array(rng.uniform(-1, 1, (32, 16)).astype(np.float32))
y = nd.array(rng.randint(0, 10, (32,)).astype(np.float32))
# BOTH legs AOT-warm-start so the code paths (and so the cache keys)
# are identical: the cold leg pays full XLA here, the warm leg replays
# deserializations from the shared on-disk cache
w0 = tuning.compile_stats()
t0 = time.perf_counter()
step.aot_warmup(x, y)
warmup_s = time.perf_counter() - t0
w1 = tuning.compile_stats()
pre = tuning.compile_stats()
t0 = time.perf_counter()
for _ in range(5):
    step(x, y)
nd.waitall()
dt = time.perf_counter() - t0
post = tuning.compile_stats()
print("CWROW " + json.dumps({
    "step_time_ms": dt / 5 * 1e3,
    "warmup_ms": warmup_s * 1e3,
    "warmup_compile_ms": (w1["compile_seconds"]
                          - w0["compile_seconds"]) * 1e3,
    "warmup_cache_misses": w1["cache_misses"] - w0["cache_misses"],
    "hot_compiles": post["compiles"] - pre["compiles"],
    "hot_compile_ms": (post["compile_seconds"]
                       - pre["compile_seconds"]) * 1e3,
    "hot_cache_misses": post["cache_misses"] - pre["cache_misses"],
    "total_compile_ms": post["compile_seconds"] * 1e3,
    "cache_hits": post["cache_hits"],
    "cache_misses": post["cache_misses"]}))
"""


def bench_embedding_ab(platform, dtype):
    """embedding_server_ab (embedding/): the SAME zipf-skewed
    pull/push row traffic driven against an in-process sharded
    embedding fleet of 1 and then 2 servers. Reports
    `embedding_bytes_per_sec` (the PERF.md r5 device-side metric, here
    measured over the fleet transport), the hot-row cache hit ratio,
    and RPCs/step — the scaling claim is bytes/sec increasing with
    server count (each server applies its shard's sparse updates on its
    own connection thread, so the fan-out overlaps)."""
    import numpy as np

    from mxnet_tpu import embedding, telemetry
    from mxnet_tpu import optimizer as opt

    del dtype  # row traffic is f32: the A/B isolates fleet scaling
    small = platform == "cpu"
    vocab = int(os.environ.get("BENCH_EMB_VOCAB",
                               "50000" if small else "500000"))
    dim = int(os.environ.get("BENCH_EMB_DIM", "64"))
    # 16k rows/step: the PERF.md-recorded geometry where the server-side
    # sparse apply (the part that scales with the fleet) dominates the
    # per-RPC fixed cost — smaller batches mostly measure transport
    batch = int(os.environ.get("BENCH_EMB_BATCH", "16384"))
    iters = int(os.environ.get("BENCH_EMB_ITERS", "8" if small else "20"))
    # shape warmup: with the pow2 row-count buckets the first few steps
    # compile one program per touched bucket and the timed lap replays
    # them — 3 laps cover the unique/hit/miss buckets this geometry
    # visits, so the A/B measures transport+apply, not XLA compiles
    # (the pre-bucket rows measured ~320 compiles over 8 steps)
    warmup = int(os.environ.get("BENCH_EMB_WARMUP", "3"))
    cache_rows = int(os.environ.get("BENCH_EMB_CACHE", "8192"))

    def counter_total(name):
        fam = telemetry.registry().get(name)
        if fam is None:
            return 0.0
        return float(sum(ch.value for ch in fam.children().values()))

    def run(n_servers):
        fleet, handles = embedding.local_fleet(n_servers, worker_id=0)
        tbl = embedding.ShardedEmbedding(
            fleet, "bench_emb_%d" % n_servers, (vocab, dim),
            cache_rows=cache_rows)
        # lazy init: rows materialize server-side on first touch — the
        # full table never exists on this worker (the >=10x-HBM shape)
        tbl.init_lazy(seed=0, scale=0.01)
        fleet.set_optimizer(opt.create("sgd", learning_rate=0.1))
        rng = np.random.RandomState(0)

        def sample():
            # zipf-skewed ids: a hot set the cache can hold plus a
            # long cold tail that keeps the fleet busy
            return (rng.zipf(1.2, size=batch) % vocab).astype(np.int64)

        from mxnet_tpu import tuning

        try:
            for _ in range(warmup):
                ids = sample()
                rows = tbl.pull(ids)
                tbl.push(ids, rows * 0.01)
            b0 = counter_total("mxt_embedding_bytes_total")
            r0 = counter_total("mxt_embedding_rpcs_total")
            c0 = tuning.compile_stats()
            t0 = time.perf_counter()
            for _ in range(iters):
                ids = sample()
                rows = tbl.pull(ids)
                tbl.push(ids, rows * 0.01)
            dt = time.perf_counter() - t0
            c1 = tuning.compile_stats()
            nbytes = counter_total("mxt_embedding_bytes_total") - b0
            rpcs = counter_total("mxt_embedding_rpcs_total") - r0
            return {
                "bytes_per_sec": nbytes / dt if dt else 0.0,
                "samples_per_sec": batch * iters / dt if dt else 0.0,
                "rpcs_per_step": rpcs / (2.0 * iters),  # pull+push = 1 step
                "hit_ratio": tbl.cache.hit_ratio,
                # bucket-bounded claim: compiles in the TIMED lap (the
                # pre-bucket code recompiled the sparse path per step)
                "measured_compiles": c1["compiles"] - c0["compiles"],
                "measured_compile_ms": round(
                    (c1["compile_seconds"] - c0["compile_seconds"]) * 1e3),
            }
        finally:
            tbl.close()
            fleet.close()
            # non-coordinator servers first (deregister needs server 0)
            for h in reversed(handles):
                h.close()

    def best(n_servers, reps=2):
        # best-of-reps per leg: the legs run sequentially, so one
        # scheduler hiccup would otherwise skew the ratio either way
        runs = [run(n_servers) for _ in range(reps)]
        return max(runs, key=lambda r: r["bytes_per_sec"])

    one = best(1)
    two = best(2)
    scaling = two["bytes_per_sec"] / one["bytes_per_sec"] \
        if one["bytes_per_sec"] else 0.0
    row = {
        "config": "embedding_server_ab", "chips": 0, "batch_size": batch,
        "dtype": "float32", "platform": platform, "mfu": None,
        "vocab": vocab, "embed_dim": dim, "cache_rows": cache_rows,
        "embedding_bytes_per_sec": round(two["bytes_per_sec"]),
        "embedding_bytes_per_sec_1srv": round(one["bytes_per_sec"]),
        "embedding_bytes_per_sec_2srv": round(two["bytes_per_sec"]),
        "server_scaling_x": round(scaling, 3),
        "cache_hit_ratio_1srv": round(one["hit_ratio"], 4),
        "cache_hit_ratio_2srv": round(two["hit_ratio"], 4),
        "rpcs_per_step_1srv": round(one["rpcs_per_step"], 2),
        "rpcs_per_step_2srv": round(two["rpcs_per_step"], 2),
        "samples_per_sec_2srv": round(two["samples_per_sec"], 1),
        "measured_compiles_1srv": one["measured_compiles"],
        "measured_compiles_2srv": two["measured_compiles"],
        "measured_compile_ms_2srv": two["measured_compile_ms"],
    }
    _emit_jsonl(row)
    return scaling, row


def bench_serving_fleet(platform, dtype):
    """serving_fleet_ab (serving/fleet.py + router.py): the SAME
    mixed-length traffic routed through a 1-replica and a 2-replica
    membership-backed serving fleet (SLO-aware router, load-aware
    placement), plus a kill-one-replica-mid-run chaos cell on the
    2-replica fleet — the row records tokens/s and request p50/p99 per
    fleet size and asserts-by-record that the kill cell loses ZERO
    accepted requests (every one completes via failover, idempotency-
    deduped, `kill_failovers` > 0)."""
    import numpy as np

    from mxnet_tpu import serving

    del dtype  # f32: the A/B isolates routing, not math throughput
    slots = int(os.environ.get("BENCH_FLEET_SLOTS", "4"))
    n_req = int(os.environ.get("BENCH_FLEET_REQUESTS", "16"))
    layers, heads, hdim = 2, 2, 16
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)

    def factory():
        return serving.DecodeEngine(
            model, params=params, slots=slots,
            cache=serving.PagedKVCache(layers, heads, hdim,
                                       num_pages=256, page_size=16),
            prefill_buckets=(64,), max_context=128)

    def traffic(router):
        rng = np.random.RandomState(11)
        out = []
        for i in range(n_req):
            plen = int(rng.randint(4, 49))
            mnew = int(rng.randint(4, 17))
            out.append(router.submit(
                rng.randint(1, 512, plen).tolist(),
                max_new_tokens=mnew, token="fb-%d" % i))
        return out

    def run(n, kill_at=None):
        pool, srv = serving.local_serving_fleet(n, factory)
        router = serving.FleetRouter(pool)
        try:
            reqs = traffic(router)
            t0 = time.perf_counter()
            if kill_at is not None:
                while router.step() and router.steps < kill_at:
                    pass
                pool.get(n - 1).kill()
            router.run(max_steps=20000)
            dt = time.perf_counter() - t0
            done = [r for r in reqs if r.state == "completed"]
            tokens = sum(len(r.result) for r in done)
            lats = sorted(r.t_finish - r.t_submit for r in done)
            pick = lambda q: lats[min(len(lats) - 1,
                                      int(q * len(lats)))] \
                if lats else 0.0
            return {
                "tokens_per_sec": tokens / dt if dt else 0.0,
                "completed": len(done),
                "lost": len(reqs) - len(done),
                "p50_ms": pick(0.50) * 1e3, "p99_ms": pick(0.99) * 1e3,
                "failovers": sum(r.failovers for r in reqs),
                "hedges": sum(r.hedges for r in reqs),
            }
        finally:
            for h in pool.replicas():
                try:
                    h.close()
                except Exception:  # noqa: BLE001 — killed handles
                    pass
            srv.close()

    one = run(1)
    two = run(2)
    killed = run(2, kill_at=6)
    scaling = two["tokens_per_sec"] / one["tokens_per_sec"] \
        if one["tokens_per_sec"] else 0.0
    row = {
        "config": "serving_fleet_ab", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform, "requests": n_req,
        "images_or_tokens_per_sec_per_chip": round(
            two["tokens_per_sec"], 2),
        "tokens_per_sec_1rep": round(one["tokens_per_sec"], 2),
        "tokens_per_sec_2rep": round(two["tokens_per_sec"], 2),
        "replica_scaling_x": round(scaling, 3),
        "p99_ms_1rep": round(one["p99_ms"], 2),
        "p99_ms_2rep": round(two["p99_ms"], 2),
        "kill_completed": killed["completed"],
        "kill_lost_requests": killed["lost"],
        "kill_failovers": killed["failovers"],
        "kill_p99_ms": round(killed["p99_ms"], 2),
        "kill_tokens_per_sec": round(killed["tokens_per_sec"], 2),
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return scaling, row


def bench_fleet_observability(platform, dtype):
    """fleet_observability_ab (telemetry_fleet.py): the SAME
    mixed-length traffic routed through a 2-replica membership-backed
    fleet with the fleet collector scraping on a background thread vs
    observability idle. The collector reads registries and wall clocks
    — never the device — so the row asserts-by-record that serving-path
    host-sync counts per decode step are IDENTICAL and records the
    tokens/s overhead ratio (target >= 0.97x)."""
    import numpy as np

    from mxnet_tpu import profiler, serving, telemetry_fleet

    del dtype  # f32: the A/B isolates observability overhead
    slots = int(os.environ.get("BENCH_FLEET_SLOTS", "4"))
    n_req = int(os.environ.get("BENCH_FLEET_REQUESTS", "16"))
    layers, heads, hdim = 2, 2, 16
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)

    def factory():
        return serving.DecodeEngine(
            model, params=params, slots=slots,
            cache=serving.PagedKVCache(layers, heads, hdim,
                                       num_pages=256, page_size=16),
            prefill_buckets=(64,), max_context=128)

    def run(collect):
        pool, srv = serving.local_serving_fleet(2, factory)
        router = serving.FleetRouter(pool)
        coll = None
        if collect:
            coll = telemetry_fleet.FleetCollector(server=srv)
            coll.refresh()
            coll.start(interval=0.05)
        try:
            rng = np.random.RandomState(11)
            reqs = []
            for i in range(n_req):
                plen = int(rng.randint(4, 49))
                mnew = int(rng.randint(4, 17))
                reqs.append(router.submit(
                    rng.randint(1, 512, plen).tolist(),
                    max_new_tokens=mnew, token="fo-%d" % i))
            h0 = profiler.host_sync_count()
            t0 = time.perf_counter()
            router.run(max_steps=20000)
            dt = time.perf_counter() - t0
            syncs = profiler.host_sync_count() - h0
            steps = sum(h.batcher.steps for h in pool.replicas())
            done = [r for r in reqs if r.state == "completed"]
            tokens = sum(len(r.result) for r in done)
            scrapes = 0
            if coll is not None:
                coll.scrape()  # at least one full pass is guaranteed
                scrapes = coll.scrapes
            return {
                "tokens_per_sec": tokens / dt if dt else 0.0,
                "completed": len(done),
                "syncs_per_step": syncs / max(1, steps),
                "scrapes": scrapes,
            }
        finally:
            if coll is not None:
                coll.close()
            for h in pool.replicas():
                try:
                    h.close()
                except Exception:  # noqa: BLE001 — teardown best effort
                    pass
            srv.close()

    run(False)  # discarded warmup leg: both timed legs run shape-warm
    base = run(False)
    obs = run(True)
    ratio = obs["tokens_per_sec"] / base["tokens_per_sec"] \
        if base["tokens_per_sec"] else 0.0
    row = {
        "config": "fleet_observability_ab", "chips": 1,
        "batch_size": slots, "dtype": "float32", "platform": platform,
        "requests": n_req,
        "images_or_tokens_per_sec_per_chip": round(
            obs["tokens_per_sec"], 2),
        "idle_tokens_per_sec": round(base["tokens_per_sec"], 2),
        "collector_tokens_per_sec": round(obs["tokens_per_sec"], 2),
        "observability_overhead_x": round(ratio, 3),
        "syncs_per_step_idle": round(base["syncs_per_step"], 4),
        "syncs_per_step_collector": round(obs["syncs_per_step"], 4),
        "sync_parity": base["syncs_per_step"] == obs["syncs_per_step"],
        "collector_scrapes": obs["scrapes"],
        "completed_idle": base["completed"],
        "completed_collector": obs["completed"],
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return ratio, row


def bench_training_health_ab(platform, dtype):
    """training_health_ab (health.py): the SAME fused Gluon step run
    with the per-layer training-health plane OFF and then ON. The stat
    row (grad/param norms, update ratios, loss stats) is computed
    INSIDE the donated step and rides the InflightWindow's staged value
    channel, so the contract is the strongest of the observability
    A/Bs: (a) host_syncs_per_step BIT-EQUAL both ways (parity
    asserted-by-record), (b) per-step losses BIT-IDENTICAL (the row is
    an extra output, never a feedback path), (c) overhead ratio
    recorded (target >= 0.97x at accelerator scale — on a ~1ms CPU toy
    step the extra norm outputs are a visible fraction; on a real step
    they are noise). A third seeded leg injects a
    ``grad_spike`` chaos fault and records that the anomaly detectors
    fired — the end-to-end proof that the plane actually watches."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import engine, health, nd, profiler, resilience
    from mxnet_tpu.gluon import Trainer, nn

    del dtype  # f32: the A/B isolates instrumentation, not math
    batch = int(os.environ.get("BENCH_HAB_BATCH", "64"))
    hidden = int(os.environ.get("BENCH_HAB_HIDDEN", "256"))
    iters = int(os.environ.get("BENCH_HAB_ITERS", "40"))
    warmup = int(os.environ.get("BENCH_HAB_WARMUP", "3"))
    window = int(os.environ.get("BENCH_HAB_INFLIGHT", "4"))

    prev = {k: os.environ.get(k)
            for k in ("MXT_HEALTH", "MXT_FAULT", "MXT_CHAOS_SEED",
                      "MXT_HEALTH_POSTMORTEM")}

    def _restore():
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        resilience.reset_faults()
        health.reset()

    def run(tag, armed, fault=None):
        os.environ["MXT_HEALTH"] = "1" if armed else "0"
        if fault:
            os.environ["MXT_FAULT"] = fault
            os.environ["MXT_CHAOS_SEED"] = "0"
            # counting anomalies, not collecting dumps — don't litter
            # the bench cwd with post-mortem files
            os.environ["MXT_HEALTH_POSTMORTEM"] = "0"
        else:
            os.environ.pop("MXT_FAULT", None)
        resilience.reset_faults()
        health.reset()
        try:
            mx.random.seed(0)
            net = nn.Sequential(prefix="hab_%s_" % tag)
            with net.name_scope():
                net.add(nn.Dense(hidden, activation="relu"),
                        nn.Dense(hidden, activation="relu"),
                        nn.Dense(10))
            net.initialize()
            tr = Trainer(net.collect_params(), "adam",
                         {"learning_rate": 1e-3})
            step = tr.fuse_step(net,
                                mx.gluon.loss.SoftmaxCrossEntropyLoss())
            rng = np.random.RandomState(0)
            x = nd.array(rng.uniform(-1, 1,
                                     (batch, 32)).astype(np.float32))
            y = nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))
            losses = []
            with engine.bulk(window):
                for _ in range(warmup):
                    step(x, y).wait_to_read()
                t0 = time.perf_counter()
                h0 = profiler.host_sync_count()
                for _ in range(iters):
                    losses.append(step(x, y))
                nd.waitall()
                dt = time.perf_counter() - t0
                syncs = profiler.host_sync_count() - h0
            # loss reads happen OUTSIDE the timed/sync-counted region
            blob = b"".join(np.asarray(v.asnumpy(), dtype=np.float32)
                            .tobytes() for v in losses)
            anomalies = (step._health_mon.anomaly_count
                         if getattr(step, "_health_mon", None) else 0)
            return dt / iters * 1e3, syncs / iters, blob, anomalies
        finally:
            _restore()

    off_ms, off_sps, off_blob, _ = run("off", False)
    on_ms, on_sps, on_blob, _ = run("on", True)
    spike_after = max(2, warmup)
    _, _, _, spike_anoms = run(
        "spike", True,
        fault="grad_spike:layer=0,after=%d,scale=1e6,n=1" % spike_after)

    overhead = on_ms / off_ms if off_ms else 0.0
    row = {
        "config": "training_health_ab", "chips": 1,
        "batch_size": batch, "dtype": "float32", "platform": platform,
        "inflight_window": window,
        "health_off_step_time_ms": round(off_ms, 3),
        "health_on_step_time_ms": round(on_ms, 3),
        "host_syncs_per_step_off": round(off_sps, 3),
        "host_syncs_per_step_on": round(on_sps, 3),
        "sync_parity": off_sps == on_sps,  # bit-equal, not tolerance
        "losses_equal": off_blob == on_blob,  # bit-identical streams
        "spike_anomalies": spike_anoms,
        "spike_detected": spike_anoms > 0,
        "images_or_tokens_per_sec_per_chip": round(
            batch * 1e3 / on_ms, 2) if on_ms else 0.0,
        "mfu": None, "flops_per_sample": None,
        "training_health_overhead": round(overhead, 4),
    }
    _emit_jsonl(row)
    return overhead, row


def bench_speculative(platform, dtype):
    """speculative_ab (serving/speculative.py): the SAME mixed-length
    traffic decoded by the plain engine and by the speculative engine
    (1-layer truncated draft of the 4-layer target, draft_k proposals
    verified in one wide launch). Records tokens/s both ways, the
    acceptance rate, host syncs/step, and asserts-by-record that the
    two engines' token streams are IDENTICAL (greedy token-exact —
    speculation changes the schedule, never the output)."""
    import numpy as np

    from mxnet_tpu import profiler, serving

    del dtype  # f32: the A/B isolates scheduling, not math throughput
    slots = int(os.environ.get("BENCH_SPEC_SLOTS", "8"))
    n_req = int(os.environ.get("BENCH_SPEC_REQUESTS", "24"))
    draft_k = int(os.environ.get("BENCH_SPEC_K", "4"))
    layers, heads, hdim = 4, 2, 32
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)
    draft, dparams = model.truncated(params, 1)

    def traffic(n):
        rng = np.random.RandomState(7)
        return [(rng.randint(1, 512, int(rng.randint(4, 97))).tolist(),
                 int(rng.randint(8, 65))) for _ in range(n)]

    def run(spec):
        if spec:
            eng = serving.SpeculativeEngine(
                model, draft, params=params, draft_params=dparams,
                draft_k=draft_k, slots=slots,
                cache=serving.PagedKVCache(layers, heads, hdim,
                                           num_pages=128, page_size=16),
                draft_cache=serving.PagedKVCache(
                    1, heads, hdim, num_pages=128, page_size=16),
                prefill_buckets=(64, 128), max_context=176)
        else:
            eng = serving.DecodeEngine(
                model, params=params, slots=slots,
                cache=serving.PagedKVCache(layers, heads, hdim,
                                           num_pages=128, page_size=16),
                prefill_buckets=(64, 128), max_context=176)
        eng.aot_warmup()
        warm = serving.ContinuousBatcher(eng)
        for p, m in traffic(6):
            warm.submit(serving.Request(p, max_new_tokens=m))
        warm.run()
        best = None
        for _ in range(3):  # best-of-3: steady-state, box-noise-proof
            sched = serving.ContinuousBatcher(eng)
            reqs = [sched.submit(serving.Request(p, max_new_tokens=m))
                    for p, m in traffic(n_req)]
            h0 = profiler.host_sync_count()
            t0 = time.perf_counter()
            sched.run(max_steps=50000)
            dt = time.perf_counter() - t0
            syncs = profiler.host_sync_count() - h0
            toks = sum(len(r.output_tokens) for r in reqs)
            lap = {"streams": [r.output_tokens for r in reqs],
                   "tokens_per_sec": toks / dt if dt else 0.0,
                   "steps": sched.steps,
                   "host_syncs_per_step": syncs / max(1, sched.steps)}
            if best is None or lap["tokens_per_sec"] \
                    > best["tokens_per_sec"]:
                best = lap
        return best

    def counter_total(name):
        from mxnet_tpu import telemetry

        fam = telemetry.registry().get(name)
        if fam is None:
            return 0.0
        return float(sum(ch.value for ch in fam.children().values()))

    base = run(False)
    p0 = counter_total("mxt_serving_spec_proposed_tokens_total")
    a0 = counter_total("mxt_serving_spec_accepted_tokens_total")
    spec = run(True)
    proposed = counter_total(
        "mxt_serving_spec_proposed_tokens_total") - p0
    accepted = counter_total(
        "mxt_serving_spec_accepted_tokens_total") - a0
    speedup = spec["tokens_per_sec"] / base["tokens_per_sec"] \
        if base["tokens_per_sec"] else 0.0
    row = {
        "config": "speculative_ab", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform, "requests": n_req,
        "draft_k": draft_k,
        "images_or_tokens_per_sec_per_chip": round(
            spec["tokens_per_sec"], 2),
        "baseline_tokens_per_sec": round(base["tokens_per_sec"], 2),
        "speculative_tokens_per_sec": round(spec["tokens_per_sec"], 2),
        "speculative_speedup": round(speedup, 3),
        "token_exact": base["streams"] == spec["streams"],
        "acceptance_rate": round(accepted / proposed, 4)
        if proposed else None,
        "baseline_steps": base["steps"],
        "speculative_steps": spec["steps"],
        "host_syncs_per_step": round(spec["host_syncs_per_step"], 3),
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return speedup, row


def bench_kv_quant(platform, dtype):
    """kv_quant_ab (serving/kv_cache.py quantized pools): the SAME
    short-sequence flood served from an f32 KV pool and from an int8
    pool holding the SAME DEVICE BYTE BUDGET — the quantized pool packs
    ~3-4x the pages, so admission keeps ~3-4x the sequences resident
    concurrently (the capacity half), at bounded output divergence and
    unchanged decode-loop syncs/step (the quality/async halves)."""
    import numpy as np

    from mxnet_tpu import profiler, serving

    del dtype
    # slots exceed what the f32 pool can seat at this byte budget: the
    # POOL is the binding resource, so resident concurrency measures
    # page capacity (the quantized pool's whole point), not slot count
    slots = int(os.environ.get("BENCH_KVQ_SLOTS", "48"))
    n_req = int(os.environ.get("BENCH_KVQ_REQUESTS", "64"))
    budget = int(os.environ.get("BENCH_KVQ_BYTES", str(768 << 10)))
    layers, heads, hdim = 2, 2, 32
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)

    def traffic(n):
        rng = np.random.RandomState(11)
        return [(rng.randint(1, 512, int(rng.randint(8, 33))).tolist(),
                 int(rng.randint(8, 25))) for _ in range(n)]

    def run(quantized):
        pages = serving.PagedKVCache.pages_for_budget(
            budget, layers, heads, hdim, page_size=16,
            quantized=quantized)
        cache = serving.PagedKVCache(layers, heads, hdim,
                                     num_pages=pages, page_size=16,
                                     quantized=quantized)
        eng = serving.DecodeEngine(model, params=params, slots=slots,
                                   cache=cache,
                                   prefill_buckets=(64,),
                                   max_context=64)
        eng.aot_warmup()
        warm = serving.ContinuousBatcher(eng)
        warm.submit(serving.Request([1, 2, 3], max_new_tokens=4))
        warm.run()
        sched = serving.ContinuousBatcher(eng)
        reqs = [sched.submit(serving.Request(p, max_new_tokens=m))
                for p, m in traffic(n_req)]
        peak = 0
        h0 = profiler.host_sync_count()
        t0 = time.perf_counter()
        while (sched._queue or sched._slot_req) and sched.steps < 20000:
            sched.step()
            peak = max(peak, len(cache._quota))
        sched.drain()
        dt = time.perf_counter() - t0
        syncs = profiler.host_sync_count() - h0
        toks = sum(len(r.output_tokens) for r in reqs)
        return {"streams": [r.output_tokens for r in reqs],
                "pages": pages, "peak_resident": peak,
                "tokens_per_sec": toks / dt if dt else 0.0,
                "page_bytes": cache.page_bytes,
                "host_syncs_per_step": syncs / max(1, sched.steps)}

    f32 = run(False)
    q8 = run(True)
    total = sum(len(s) for s in f32["streams"])
    same = sum(sum(1 for x, y in zip(a, b) if x == y)
               for a, b in zip(f32["streams"], q8["streams"]))
    ratio = q8["peak_resident"] / f32["peak_resident"] \
        if f32["peak_resident"] else 0.0
    row = {
        "config": "kv_quant_ab", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform, "requests": n_req,
        "byte_budget": budget,
        "pages_f32": f32["pages"], "pages_int8": q8["pages"],
        "page_bytes_f32": f32["page_bytes"],
        "page_bytes_int8": q8["page_bytes"],
        "peak_resident_f32": f32["peak_resident"],
        "peak_resident_int8": q8["peak_resident"],
        "resident_ratio": round(ratio, 3),
        "token_agreement": round(same / total, 4) if total else None,
        "tokens_per_sec_f32": round(f32["tokens_per_sec"], 2),
        "tokens_per_sec_int8": round(q8["tokens_per_sec"], 2),
        "images_or_tokens_per_sec_per_chip": round(
            q8["tokens_per_sec"], 2),
        "host_syncs_per_step_f32": round(
            f32["host_syncs_per_step"], 3),
        "host_syncs_per_step_int8": round(
            q8["host_syncs_per_step"], 3),
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return ratio, row


def bench_prefix_reuse(platform, dtype):
    """prefix_reuse_ab (serving/prefix.py + kv_cache refcounts): the
    SAME prefix-heavy traffic (every request opens with one shared
    system prompt — BENCH_PFX_SYSLEN tokens) served with the prefix
    cache off and on.
    A hit points the new sequence's page table at the already-resident
    prefix pages (copy-on-write on divergence) and prefills only the
    suffix — so the A/B measures tokens/s, admission latency p50/p99,
    and (at a fixed page budget) how many sequences stay resident
    concurrently. One extra leg runs the reuse-on pool quantized: int8
    pages times shared prefixes compound into the resident-capacity
    headline. Token-exact by record on the f32 legs (masked suffix
    attention over stored pages is bit-identical to full prefill)."""
    import numpy as np

    from mxnet_tpu import serving

    del dtype  # f32 A/B isolates admission scheduling, not math
    slots = int(os.environ.get("BENCH_PFX_SLOTS", "8"))
    n_req = int(os.environ.get("BENCH_PFX_REQUESTS", "16"))
    sys_len = int(os.environ.get("BENCH_PFX_SYSLEN", "256"))
    layers, heads, hdim = 4, 2, 32
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)
    rng0 = np.random.RandomState(3)
    system = rng0.randint(1, 512, sys_len).tolist()

    def traffic(n):
        rng = np.random.RandomState(13)
        reqs = [(system + rng.randint(1, 512,
                                      int(rng.randint(1, 33))).tolist(),
                 8) for _ in range(n)]
        # request 0 ends page-aligned, and every 8th request replays it
        # verbatim: the FULL-match path (share every page, copy-on-write
        # the tail page before the first decode write) stays live in the
        # A/B, not just in unit tests
        reqs[0] = (system + rng.randint(1, 512, 16).tolist(), 8)
        for i in range(7, n, 8):
            reqs[i] = reqs[0]
        return reqs

    def counter_total(name):
        from mxnet_tpu import telemetry

        fam = telemetry.registry().get(name)
        if fam is None:
            return 0.0
        return float(sum(ch.value for ch in fam.children().values()))

    def run(reuse, quantized=False, num_pages=512, nslots=None,
            nreq=None):
        cache = serving.PagedKVCache(layers, heads, hdim,
                                     num_pages=num_pages, page_size=16,
                                     quantized=quantized)
        eng = serving.DecodeEngine(model, params=params,
                                   slots=nslots or slots, cache=cache,
                                   prefill_buckets=(32, 512),
                                   max_context=320, prefix_cache=reuse)
        eng.aot_warmup()
        warm = serving.ContinuousBatcher(eng)
        wt = traffic(2)
        # warm every admission program the lap will hit: the plain
        # prefill (miss), the partial-hit suffix prefill, and the
        # full-match replay (its COW + last-page program)
        for p, m in (wt[0], wt[0], wt[1]):
            warm.submit(serving.Request(p, max_new_tokens=m))
        warm.run()
        best = None
        for _ in range(3):  # best-of-3: steady-state, box-noise-proof
            if eng.prefix is not None:
                eng.prefix.clear()  # every lap starts cold
            sched = serving.ContinuousBatcher(eng)
            reqs = [sched.submit(serving.Request(p, max_new_tokens=m))
                    for p, m in traffic(nreq or n_req)]
            peak = 0
            t0 = time.perf_counter()
            while (sched._queue or sched._slot_req) \
                    and sched.steps < 50000:
                sched.step()
                peak = max(peak, len(cache._quota))
            sched.drain()
            dt = time.perf_counter() - t0
            toks = sum(len(r.output_tokens) for r in reqs)
            admit = sorted(r.t_first - r.t_submit for r in reqs
                           if r.t_first is not None)
            lap = {"streams": [r.output_tokens for r in reqs],
                   "tokens_per_sec": toks / dt if dt else 0.0,
                   "peak_resident": peak,
                   "admit_p50": admit[len(admit) // 2]
                   if admit else None,
                   "admit_p99": admit[min(len(admit) - 1,
                                          int(len(admit) * 0.99))]
                   if admit else None}
            if best is None or lap["tokens_per_sec"] \
                    > best["tokens_per_sec"]:
                best = lap
        return best

    base = run(False)
    h0 = counter_total("mxt_serving_prefix_hits_total")
    m0 = counter_total("mxt_serving_prefix_misses_total")
    c0 = counter_total("mxt_serving_cow_copies_total")
    on = run(True)
    hits = counter_total("mxt_serving_prefix_hits_total") - h0
    misses = counter_total("mxt_serving_prefix_misses_total") - m0
    cows = counter_total("mxt_serving_cow_copies_total") - c0
    # capacity legs: a page pool too small to seat everyone without
    # sharing — resident concurrency is what reuse (and int8 x reuse)
    # buys at a FIXED device byte budget
    cap_pages = int(os.environ.get("BENCH_PFX_CAP_PAGES", "48"))
    budget = cap_pages * serving.PagedKVCache(
        layers, heads, hdim, num_pages=1, page_size=16).page_bytes
    cap_off = run(False, num_pages=cap_pages, nslots=24, nreq=24)
    cap_on = run(True, num_pages=cap_pages, nslots=24, nreq=24)
    q_pages = serving.PagedKVCache.pages_for_budget(
        budget, layers, heads, hdim, page_size=16, quantized=True)
    cap_q = run(True, quantized=True, num_pages=q_pages, nslots=24,
                nreq=24)
    speedup = on["tokens_per_sec"] / base["tokens_per_sec"] \
        if base["tokens_per_sec"] else 0.0
    resident_ratio = cap_on["peak_resident"] / cap_off["peak_resident"] \
        if cap_off["peak_resident"] else 0.0
    resident_q = cap_q["peak_resident"] / cap_off["peak_resident"] \
        if cap_off["peak_resident"] else 0.0
    row = {
        "config": "prefix_reuse_ab", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform, "requests": n_req,
        "system_prompt_tokens": sys_len,
        "images_or_tokens_per_sec_per_chip": round(
            on["tokens_per_sec"], 2),
        "baseline_tokens_per_sec": round(base["tokens_per_sec"], 2),
        "reuse_tokens_per_sec": round(on["tokens_per_sec"], 2),
        "prefix_reuse_speedup": round(speedup, 3),
        "token_exact": base["streams"] == on["streams"],
        "admit_p50_off": round(base["admit_p50"], 5)
        if base["admit_p50"] is not None else None,
        "admit_p50_on": round(on["admit_p50"], 5)
        if on["admit_p50"] is not None else None,
        "admit_p99_off": round(base["admit_p99"], 5)
        if base["admit_p99"] is not None else None,
        "admit_p99_on": round(on["admit_p99"], 5)
        if on["admit_p99"] is not None else None,
        "prefix_hit_ratio": round(hits / (hits + misses), 4)
        if hits + misses else None,
        "cow_copies": int(cows),
        "cap_page_budget_bytes": budget,
        "peak_resident_off": cap_off["peak_resident"],
        "peak_resident_on": cap_on["peak_resident"],
        "peak_resident_int8": cap_q["peak_resident"],
        "resident_ratio": round(resident_ratio, 3),
        "resident_int8_ratio": round(resident_q, 3),
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return speedup, row


def bench_autoscale(platform, dtype):
    """autoscale_ab (serving/autoscaler.py + qos.py): a seeded flash
    crowd (the traffic_storm fault rule) hits a fleet held at its
    1-replica floor while the autoscaler watches the merged fleet page.
    Asserts-by-record: the fleet scales UP (up decisions > 0, visible
    as scale_up spans on the autoscaler's trace track in the Perfetto
    fleet timeline), EVERY offered request is accounted — submitted ==
    completed + typed-rejected, zero lost — and the p99 of the LAST
    half of completions (after the spare went routable) recovers to
    within the SLO. A second cell is the QoS isolation assert: a bulk
    tenant saturates admission, its over-quota submits are refused
    typed (OverQuotaError), and the interactive tenant's p99 stays
    within a bounded multiple of the unloaded p99."""
    import numpy as np

    from mxnet_tpu import resilience, serving

    del dtype  # f32: the A/B isolates the control loop, not math
    slots = int(os.environ.get("BENCH_FLEET_SLOTS", "4"))
    n_req = int(os.environ.get("BENCH_AUTOSCALE_REQUESTS", "24"))
    window = float(os.environ.get("BENCH_AUTOSCALE_WINDOW", "120"))
    layers, heads, hdim = 2, 2, 16
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)

    def factory():
        return serving.DecodeEngine(
            model, params=params, slots=slots,
            cache=serving.PagedKVCache(layers, heads, hdim,
                                       num_pages=256, page_size=16),
            prefill_buckets=(64,), max_context=128)

    def close_fleet(pool, srv):
        for h in pool.replicas():
            try:
                h.close()
            except Exception:  # noqa: BLE001 — drained/killed handles
                pass
        srv.close()

    def pick(lats, q):
        return lats[min(len(lats) - 1, int(q * len(lats)))] \
            if lats else 0.0

    # -- phase A: unloaded p99 at the floor — the yardstick both the
    # SLO and the QoS isolation multiple are calibrated against
    pool, srv = serving.local_serving_fleet(1, factory)
    router = serving.FleetRouter(pool)
    try:
        rng = np.random.RandomState(7)
        base = []
        for i in range(6):
            base.append(router.submit(
                rng.randint(1, 512, 8).tolist(), max_new_tokens=6,
                token="base-%d" % i))
            router.run(max_steps=20000)
        blats = sorted(r.t_finish - r.t_submit for r in base
                       if r.state == "completed")
        p99_base = pick(blats, 0.99)
    finally:
        close_fleet(pool, srv)
    slo = max(8 * p99_base, 0.25)

    # -- phase B: flash crowd, autoscaler closing the loop
    old_fault = os.environ.get("MXT_FAULT")
    os.environ["MXT_FAULT"] = "traffic_storm:rps=200,after=2"
    resilience.reset_faults()
    pool, srv = serving.local_serving_fleet(1, factory)
    router = serving.FleetRouter(pool, slo=slo)
    scaler = serving.FleetAutoscaler(
        router, factory, slo=slo, min_replicas=1, max_replicas=3,
        cooldown=0.25, queue_high=1.0, calm_ticks=10 ** 6)
    gen = serving.TrafficGenerator(
        router, rate=5.0, seed=3, vocab=512, prompt_len=(4, 16),
        max_new_tokens=6, max_requests=n_req)
    try:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < window:
            gen.tick(router._now())
            router.step()
            scaler.step()
            if gen.total_offered() >= n_req \
                    and all(r.done for r in gen.submitted):
                break
        storm_dt = time.perf_counter() - t0
        done = [r for r in gen.submitted if r.state == "completed"]
        lost = len(gen.submitted) - len(done)
        tokens = sum(len(r.result) for r in done)
        by_finish = sorted(done, key=lambda r: r.t_finish)
        tail = sorted(r.t_finish - r.t_submit
                      for r in by_finish[len(by_finish) // 2:])
        p99_tail = pick(tail, 0.99)
        up_events = sum(1 for d in scaler.decisions
                        if d["direction"] == "up")
        replicas_end = len(pool.routable())
        scaler._collector.scrape()
        span_names = {s.get("name")
                      for s in scaler._collector.spans(scaler.trace_id)}
        on_timeline = "scale_up" in span_names
    finally:
        scaler.close()
        close_fleet(pool, srv)
        if old_fault is None:
            os.environ.pop("MXT_FAULT", None)
        else:
            os.environ["MXT_FAULT"] = old_fault
        resilience.reset_faults()

    # -- phase C: QoS isolation — bulk saturates admission, interactive
    # rides the priority queue, over-quota bulk is refused typed
    qos = serving.QosPolicy.parse("interactive:bulk")
    qos.add_tenant("bulk", max_requests=3)
    pool, srv = serving.local_serving_fleet(1, factory)
    router = serving.FleetRouter(pool, qos=qos)
    try:
        rng = np.random.RandomState(5)
        bulk_ok = bulk_refused = 0
        for i in range(12):
            try:
                router.submit(rng.randint(1, 512, 12).tolist(),
                              max_new_tokens=8, token="blk-%d" % i,
                              tenant="bulk")
                bulk_ok += 1
            except serving.OverQuotaError:
                bulk_refused += 1
        inter = [router.submit(rng.randint(1, 512, 8).tolist(),
                               max_new_tokens=6, token="int-%d" % i,
                               tenant="interactive")
                 for i in range(6)]
        router.run(max_steps=40000)
        ilats = sorted(r.t_finish - r.t_submit for r in inter
                       if r.state == "completed")
        p99_inter = pick(ilats, 0.99)
    finally:
        close_fleet(pool, srv)

    recovery = slo / p99_tail if p99_tail else 0.0
    row = {
        "config": "autoscale_ab", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform, "requests": n_req,
        "images_or_tokens_per_sec_per_chip": round(
            tokens / storm_dt if storm_dt else 0.0, 2),
        "slo_s": round(slo, 4),
        "p99_base_ms": round(p99_base * 1e3, 2),
        "p99_storm_tail_ms": round(p99_tail * 1e3, 2),
        "slo_recovery_x": round(recovery, 3),
        "replicas_start": 1, "replicas_end": replicas_end,
        "scale_up_events": up_events,
        "scale_up_span_on_timeline": on_timeline,
        "submitted": len(gen.submitted),
        "typed_rejected": gen.rejected,
        "completed": len(done), "lost_requests": lost,
        "qos_bulk_admitted": bulk_ok,
        "qos_bulk_refused_typed": bulk_refused,
        "p99_interactive_ms": round(p99_inter * 1e3, 2),
        "qos_isolation_x": round(p99_inter / p99_base, 3)
        if p99_base else None,
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return recovery, row


def bench_cold_warm(platform, dtype):
    """Cold-vs-warm start A/B (tuning/): the SAME canonical fused-step
    loop run in two fresh processes sharing one persistent compile cache
    + tune table. Process 1 is the cold path (every XLA compile is a
    cache miss, paid in-loop); process 2 AOT-warm-starts via
    ``step.aot_warmup`` and must show ~0 hot-loop compile time and ZERO
    hot-loop cache misses — the zero-JIT-resume acceptance, self-
    reported per bench round."""
    import tempfile

    del dtype  # f32 — the A/B isolates compilation, not math
    tmp = tempfile.mkdtemp(prefix="mxt_bench_coldwarm_")
    env = dict(os.environ)
    env.update({"MXT_COMPILE_CACHE_DIR": os.path.join(tmp, "xla"),
                "MXT_TUNE_TABLE": os.path.join(tmp, "tune.json"),
                "BENCH_CW_PLATFORM":
                    "cpu" if platform == "cpu" else platform})

    def run():
        r = subprocess.run([sys.executable, "-c", _COLD_WARM_CODE],
                           env=env, capture_output=True, text=True,
                           timeout=600)
        for line in r.stdout.splitlines():
            if line.startswith("CWROW "):
                return json.loads(line[len("CWROW "):])
        raise RuntimeError("cold/warm subprocess produced no row: %s"
                           % (r.stderr or r.stdout)[-400:])

    cold = run()  # fresh cache: warmup + hot loop pay real XLA
    warm = run()  # same code, warm cache: must show ~0 compile time
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    cold_total = cold["warmup_compile_ms"] + cold["hot_compile_ms"]
    warm_total = warm["warmup_compile_ms"] + warm["hot_compile_ms"]
    ratio = cold_total / warm_total if warm_total else 0.0
    row = {
        "config": "cold_vs_warm_start", "chips": 1, "batch_size": 32,
        "dtype": "float32", "platform": platform,
        "cold_compile_ms": round(cold_total, 1),
        "cold_warmup_ms": round(cold["warmup_ms"], 1),
        "cold_cache_misses": cold["cache_misses"],
        "warm_compile_ms": round(warm_total, 1),
        "warm_warmup_ms": round(warm["warmup_ms"], 1),
        "warm_hot_compile_ms": round(warm["hot_compile_ms"], 1),
        "warm_hot_cache_misses": warm["hot_cache_misses"],
        "warm_cache_misses": warm["cache_misses"],
        "warm_cache_hits": warm["cache_hits"],
        "cold_step_time_ms": round(cold["step_time_ms"], 3),
        "warm_step_time_ms": round(warm["step_time_ms"], 3),
        # the acceptance bit: a warm-started process's fused-step loop
        # performs zero real JIT compiles (cache misses) on the hot path
        "zero_jit_resume": warm["hot_cache_misses"] == 0,
        "images_or_tokens_per_sec_per_chip": round(
            32 * 1e3 / warm["step_time_ms"], 2) if warm["step_time_ms"]
        else 0.0,
        "mfu": None, "flops_per_sample": None,
        "cold_warm_compile_ratio": round(ratio, 2),
    }
    _emit_jsonl(row)
    return ratio, row


def _zero_stage_measure():
    """The zero_stage_ab measurement body: the SAME 3-layer MLP sharded
    step at ZeRO stages 0-3 on the CURRENT jax backend (the caller is
    responsible for putting it on an 8-device mesh — bench_zero_stages
    shells into a subprocess with a forced CPU mesh; the tier-1 smoke
    test, already on that mesh, calls this in-process)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel
    from mxnet_tpu.gluon import nn

    batch = int(os.environ.get("BENCH_ZERO_BATCH", "64"))
    hidden = int(os.environ.get("BENCH_ZERO_HIDDEN", "512"))
    iters = int(os.environ.get("BENCH_ZERO_ITERS", "10"))
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (batch, 64)).astype(np.float32)
    y = rng.randint(0, 8, (batch,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    out = {"batch": batch, "hidden": hidden}
    losses = {}
    for stage in (0, 1, 2, 3):
        mx.random.seed(7)
        net = nn.HybridSequential(prefix="z%d_" % stage)
        with net.name_scope():
            net.add(nn.Dense(hidden, activation="relu", in_units=64),
                    nn.Dense(hidden, activation="relu", in_units=hidden),
                    nn.Dense(8, in_units=hidden))
        net.initialize()
        mesh = parallel.make_mesh(axis_names=("data",))
        step = parallel.ShardedTrainStep(net, loss_fn, "adam",
                                         {"learning_rate": 1e-3},
                                         mesh=mesh, zero_stage=stage)
        loss = step(nd.array(x), nd.array(y))
        loss.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(nd.array(x), nd.array(y))
        loss.wait_to_read()
        dt = (time.perf_counter() - t0) / iters
        b = step.per_device_bytes()
        out["z%d" % stage] = {
            "step_time_ms": round(dt * 1e3, 3),
            "opt_bytes_per_device": b["opt_state_bytes"],
            "param_bytes_per_device": b["param_bytes"]}
        losses["z%d" % stage] = round(float(loss.asscalar()), 7)
    out["losses"] = losses
    return out


_ZERO_STAGE_CODE = r'''
import json, os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["MXT_BENCH_DIR"])
import bench
print("ZROW " + json.dumps(bench._zero_stage_measure()))
'''


def bench_zero_stages(platform, dtype, _data=None):
    """ZeRO weight-update-sharding A/B (parallel/sharded.py, arXiv
    2004.13336): the SAME 3-layer MLP fused SPMD step on the 8-device
    CPU mesh at ZeRO stages 0/1/2/3. The contract: identical losses at
    every stage (layout, never math), per-device OPTIMIZER-STATE bytes
    shrink ~dp× from stage 1 on (reduce-scatter + sharded update from
    stage 2), and per-device PARAM bytes shrink ~dp× at stage 3
    (FSDP-style storage). Runs in a subprocess so the forced 8-device
    CPU mesh never disturbs the parent's backend (which may hold the
    axon tunnel)."""
    del dtype  # f32 — the A/B isolates memory/layout, not math throughput
    data = _data  # tests (already on the 8-dev mesh) measure in-process
    if data is None:
        env = dict(os.environ)
        env["MXT_BENCH_DIR"] = os.path.dirname(os.path.abspath(__file__))
        r = subprocess.run([sys.executable, "-c", _ZERO_STAGE_CODE],
                           env=env, capture_output=True, text=True,
                           timeout=600)
        for line in r.stdout.splitlines():
            if line.startswith("ZROW "):
                data = json.loads(line[len("ZROW "):])
        if data is None:
            raise RuntimeError("zero-stage subprocess produced no row: %s"
                               % (r.stderr or r.stdout)[-400:])
    shrink_opt = data["z0"]["opt_bytes_per_device"] / max(
        1, data["z2"]["opt_bytes_per_device"])
    shrink_par = data["z0"]["param_bytes_per_device"] / max(
        1, data["z3"]["param_bytes_per_device"])
    row = {
        "config": "zero_stage_ab", "chips": 8,
        "batch_size": data["batch"], "dtype": "float32",
        "platform": "cpu",  # always the virtual CPU mesh (subprocess)
        "stages": {k: data[k] for k in ("z0", "z1", "z2", "z3")},
        "losses_equal": len(set(data["losses"].values())) == 1,
        "opt_bytes_shrink_z2": round(shrink_opt, 2),
        "param_bytes_shrink_z3": round(shrink_par, 2),
        "step_time_ms": data["z2"]["step_time_ms"],
        "images_or_tokens_per_sec_per_chip": round(
            data["batch"] * 1e3 / data["z2"]["step_time_ms"] / 8, 2)
        if data["z2"]["step_time_ms"] else 0.0,
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return shrink_opt, row


def _parallel_4d_measure():
    """The parallel_4d_ab measurement body: the SAME pp=2/ep=2 toy LM
    stepped two ways on ONE (2,1,2,2) dp×tp×pp×ep mesh — the island
    composition (one value_and_grad launch plus one eager fused-optimizer
    launch per parameter: the pre-unification dispatch shape) vs the
    unified ShardedTrainStep (the whole schedule + MoE + loss + update
    as its single donated jit). Both legs run exactly
    ``pipeline_moe_forward`` and the same loss/update op math from the
    same placed initial params, so the loss series must match
    bit-for-bit: the A/B isolates launch structure, never math. (The
    genuinely different island programs — shard_map pipeline_apply +
    moe_apply on their own sub-meshes — can't be bit-compared, which is
    why the baseline here is the same math split into launches.)"""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import nd, parallel, profiler
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.parallel import unified as _u

    batch = int(os.environ.get("BENCH_4D_BATCH", "16"))
    hidden = int(os.environ.get("BENCH_4D_HIDDEN", "16"))
    iters = int(os.environ.get("BENCH_4D_ITERS", "20"))
    stages, experts, micro, cf, lr = 2, 2, 4, 1.25, 0.05
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (batch, hidden)).astype(np.float32)
    y = rng.randint(0, 8, (batch,)).astype(np.float32)

    mx.random.seed(7)
    mesh = parallel.make_mesh((2, 1, 2, 2), ("dp", "tp", "pp", "ep"))
    net = parallel.PipelineMoEBlock(
        num_stages=stages, num_experts=experts, in_units=hidden,
        hidden=hidden, expert_hidden=2 * hidden, num_classes=8,
        num_microbatches=micro, capacity_factor=cf)
    net.initialize()
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": lr}, mesh=mesh, rules=net.sharding_rules(mesh),
        zero_stage=1)
    vals0 = net.param_values()  # placed initial params, pre-first-step

    # --- island leg: same math, pre-unification launch structure ------
    def island_loss(vals, xb, yb):
        logits, _, _ = _u.pipeline_moe_forward(
            vals, xb, micro, cf, mesh=mesh, dp="dp", pp="pp", ep="ep")
        # gluon/loss.py SoftmaxCrossEntropyLoss math, op for op
        pred = jax.nn.log_softmax(logits, axis=-1)
        idx = jnp.clip(yb.astype(jnp.int32), 0, logits.shape[-1] - 1)
        lp = jnp.take_along_axis(pred, idx[:, None], axis=-1)
        return jnp.mean(jnp.mean(-lp, axis=1))

    grad_fn = jax.jit(jax.value_and_grad(island_loss))
    sgd = get_op("sgd_update").fn
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    ys = jax.device_put(y, NamedSharding(mesh, P("dp")))

    def island_step(vals):
        loss, grads = grad_fn(vals, xs, ys)  # launch 1: fwd+bwd
        # one eager fused-optimizer launch PER parameter — the island tax
        return loss, {k: sgd(vals[k], grads[k], lr=lr) for k in vals}

    vals = dict(vals0)
    island_losses = []
    l, vals = island_step(vals)  # compile lap (lands in the series too)
    island_losses.append(l)
    island_ms, island_syncs = float("inf"), 0
    for _ in range(3):  # best-of-3 windows: the 8-thread CPU rendezvous
        h0 = profiler.host_sync_count()  # is jittery per window
        t0 = time.perf_counter()
        for _ in range(iters):
            l, vals = island_step(vals)
            island_losses.append(l)
        island_syncs = max(island_syncs, profiler.host_sync_count() - h0)
        l.block_until_ready()
        island_ms = min(island_ms, (time.perf_counter() - t0) / iters * 1e3)
    island_launches = 1 + len(vals0)

    # --- unified leg: ONE donated jit (island leg never mutated net).
    # Inputs convert ONCE, like the island leg's device_put above — the
    # A/B measures launch structure, not host->device feeding.
    xa, ya = nd.array(x), nd.array(y)
    unified_losses = [step(xa, ya)]
    unified_ms, unified_syncs = float("inf"), 0
    n0 = profiler.launch_count()
    for _ in range(3):
        h0 = profiler.host_sync_count()
        t0 = time.perf_counter()
        for _ in range(iters):
            unified_losses.append(step(xa, ya))
        unified_syncs = max(unified_syncs,
                            profiler.host_sync_count() - h0)
        unified_losses[-1].wait_to_read()
        unified_ms = min(unified_ms,
                         (time.perf_counter() - t0) / iters * 1e3)
    unified_launches = (profiler.launch_count() - n0) // (3 * iters)

    il = [float(v) for v in island_losses]  # sync-ok: post-loop reads
    ul = [float(v.asscalar()) for v in unified_losses]  # sync-ok: post-loop
    moe = parallel.publish_moe_telemetry(net)
    pdb = step.per_device_bytes()
    return {
        "batch": batch, "hidden": hidden, "iters": iters,
        "mesh": {"dp": 2, "tp": 1, "pp": 2, "ep": 2},
        "island_step_time_ms": round(island_ms, 3),
        "unified_step_time_ms": round(unified_ms, 3),
        "island_launches_per_step": island_launches,
        "unified_launches_per_step": int(unified_launches),
        "island_hot_loop_syncs": int(island_syncs),
        "unified_hot_loop_syncs": int(unified_syncs),
        "losses_island": [round(v, 7) for v in il],
        "losses_unified": [round(v, 7) for v in ul],
        "losses_equal": il == ul,  # bit-exact, not tolerance
        "param_bytes_per_device": pdb["param_bytes"],
        "opt_bytes_per_device": pdb["opt_state_bytes"],
        "moe_expert_load": moe["expert_load"],
        "moe_router_drops": moe["drops"],
    }


_PARALLEL_4D_CODE = r'''
import json, os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["MXT_BENCH_DIR"])
import bench
print("P4DROW " + json.dumps(bench._parallel_4d_measure()))
'''


def bench_parallel_4d(platform, dtype, _data=None):
    """Unified 4D parallelism A/B (parallel/unified.py): pipeline + MoE
    as shardings inside the one-launch sharded step vs the same math
    stepped as launch islands, on the 8-device CPU mesh. The contract:
    bit-identical loss series (layout and launch structure, never math),
    ``launches_per_step == 1`` for the unified leg, sync parity on the
    hot loop (zero host syncs both legs), and the unified leg at least
    matching the island composition's step time. Runs in a subprocess
    so the forced 8-device CPU mesh never disturbs the parent backend."""
    del dtype  # f32 — the A/B isolates launch structure, not math
    data = _data  # tests (already on the 8-dev mesh) measure in-process
    if data is None:
        env = dict(os.environ)
        env["MXT_BENCH_DIR"] = os.path.dirname(os.path.abspath(__file__))
        r = subprocess.run([sys.executable, "-c", _PARALLEL_4D_CODE],
                           env=env, capture_output=True, text=True,
                           timeout=600)
        for line in r.stdout.splitlines():
            if line.startswith("P4DROW "):
                data = json.loads(line[len("P4DROW "):])
        if data is None:
            raise RuntimeError("parallel_4d subprocess produced no row: %s"
                               % (r.stderr or r.stdout)[-400:])
    speedup = (data["island_step_time_ms"] / data["unified_step_time_ms"]
               if data["unified_step_time_ms"] else 0.0)
    row = {
        "config": "parallel_4d_ab", "chips": 8,
        "batch_size": data["batch"], "dtype": "float32",
        "platform": "cpu",  # always the virtual CPU mesh (subprocess)
        "mesh": data["mesh"],
        "island_step_time_ms": data["island_step_time_ms"],
        "unified_step_time_ms": data["unified_step_time_ms"],
        "step_time_ms": data["unified_step_time_ms"],
        "launches_per_step": data["unified_launches_per_step"],
        "island_launches_per_step": data["island_launches_per_step"],
        "losses_equal": data["losses_equal"],
        "sync_parity": (data["island_hot_loop_syncs"]
                        == data["unified_hot_loop_syncs"]),
        "param_bytes_per_device": data["param_bytes_per_device"],
        "opt_bytes_per_device": data["opt_bytes_per_device"],
        "moe_expert_load": data["moe_expert_load"],
        "moe_router_drops": data["moe_router_drops"],
        "unified_speedup": round(speedup, 2),
        "images_or_tokens_per_sec_per_chip": round(
            data["batch"] * 1e3 / data["unified_step_time_ms"] / 8, 2)
        if data["unified_step_time_ms"] else 0.0,
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    return speedup, row


def bench_serving(platform, dtype):
    """Serving stack (mxnet_tpu/serving/): mixed-length synthetic
    traffic through the paged-KV decode engine, once under the
    continuous batcher (recompose every step) and once under the
    static batcher (admission only at batch boundaries). Emits two
    rows: `serving_decode` (continuous-mode tokens/s, request p50/p99,
    KV-page occupancy) and the `serving_continuous_vs_static_ab` proof
    row. Useful tokens only — idle static slots earn nothing, which is
    exactly the measured difference."""
    import numpy as np

    from mxnet_tpu import profiler, serving

    del dtype  # f32: the A/B isolates scheduling, not math throughput
    slots = int(os.environ.get("BENCH_SERVING_SLOTS", "8"))
    n_req = int(os.environ.get("BENCH_SERVING_REQUESTS", "24"))
    layers, heads, hdim = 2, 4, 16
    model = serving.TinyDecoder(vocab=512, num_layers=layers,
                                num_heads=heads, head_dim=hdim,
                                max_len=512)
    params = model.init_params(0)

    def make_requests():
        rng = np.random.RandomState(7)
        out = []
        for _ in range(n_req):
            plen = int(rng.randint(4, 97))
            mnew = int(rng.randint(4, 49))
            out.append(serving.Request(
                rng.randint(1, 512, plen).tolist(),
                max_new_tokens=mnew))
        return out

    def run(batcher_cls):
        cache = serving.PagedKVCache(layers, heads, hdim, num_pages=512,
                                     page_size=16)
        eng = serving.DecodeEngine(model, params=params, slots=slots,
                                   cache=cache, prefill_buckets=(64, 128),
                                   max_context=256)
        eng.aot_warmup()
        # warm lap: absorb eager-glue compiles so the timed lap measures
        # scheduling, not JIT
        warm = batcher_cls(eng)
        warm.submit(serving.Request([1, 2, 3], max_new_tokens=4))
        warm.run()
        sched = batcher_cls(eng)
        for r in make_requests():
            sched.submit(r)
        peak_pages = 0
        h0 = profiler.host_sync_count()
        t0 = time.perf_counter()
        while (sched._queue or sched._slot_req) and sched.steps < 20000:
            sched.step()
            peak_pages = max(peak_pages, cache.pages_in_use())
        sched.drain()
        dt = time.perf_counter() - t0
        syncs = profiler.host_sync_count() - h0
        done = [r for r in sched.completed if r.state == "completed"]
        tokens = sum(len(r.output_tokens) for r in done)
        lats = sorted(r.t_finish - r.t_submit for r in done
                      if r.t_finish is not None)
        pick = lambda q: lats[min(len(lats) - 1, int(q * len(lats)))] \
            if lats else 0.0
        return {
            "tokens_per_sec": tokens / dt if dt else 0.0,
            "completed": len(done), "steps": sched.steps,
            "p50_ms": pick(0.50) * 1e3, "p99_ms": pick(0.99) * 1e3,
            "peak_kv_pages": peak_pages,
            "host_syncs_per_step": syncs / max(1, sched.steps),
        }

    cont = run(serving.ContinuousBatcher)
    stat = run(serving.StaticBatcher)
    speedup = cont["tokens_per_sec"] / stat["tokens_per_sec"] \
        if stat["tokens_per_sec"] else 0.0

    row = {
        "config": "serving_decode", "chips": 1, "batch_size": slots,
        "dtype": "float32", "platform": platform,
        "requests": n_req,
        "images_or_tokens_per_sec_per_chip": round(
            cont["tokens_per_sec"], 2),
        "request_p50_ms": round(cont["p50_ms"], 2),
        "request_p99_ms": round(cont["p99_ms"], 2),
        "peak_kv_pages": cont["peak_kv_pages"],
        "host_syncs_per_step": round(cont["host_syncs_per_step"], 3),
        "decode_steps": cont["steps"],
        "mfu": None, "flops_per_sample": None,
    }
    _emit_jsonl(row)
    row_ab = {
        "config": "serving_continuous_vs_static_ab", "chips": 1,
        "batch_size": slots, "dtype": "float32", "platform": platform,
        "requests": n_req,
        "continuous_tokens_per_sec": round(cont["tokens_per_sec"], 2),
        "static_tokens_per_sec": round(stat["tokens_per_sec"], 2),
        "continuous_steps": cont["steps"],
        "static_steps": stat["steps"],
        "images_or_tokens_per_sec_per_chip": round(
            cont["tokens_per_sec"], 2),
        "mfu": None, "flops_per_sample": None,
        "continuous_speedup": round(speedup, 3),
    }
    _emit_jsonl(row_ab)
    return speedup, row_ab


def main():
    platform, note = _init_backend()
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    configs = os.environ.get(
        "BENCH_CONFIGS",
        "resnet50,bert,lstm_ptb,wide_deep,lenet,pipeline,async_ab,"
        "telemetry_ab,diag_ab,cold_warm,serving,zero_stage,parallel_4d,"
        "embedding_ab,serving_fleet,speculative,kv_quant,fleet_obs,"
        "streaming_input,prefix_reuse,autoscale,training_health"
    ).split(",")

    # headline priority: resnet50 (the SURVEY §6 headline) > bert > rest
    metric_info = {
        "resnet50": ("resnet50_train_throughput", "images/sec/chip",
                     bench_resnet50),
        "bert": ("bert_base_mlm_throughput", "tokens/sec/chip",
                 bench_bert_mlm),
        "lstm_ptb": ("lstm_ptb_train_throughput", "tokens/sec/chip",
                     bench_lstm_ptb),
        "wide_deep": ("wide_deep_train_throughput", "samples/sec/chip",
                      bench_wide_deep),
        "lenet": ("lenet_mnist_train_throughput", "images/sec/chip",
                  bench_lenet_mnist),
        "pipeline": ("input_pipeline_throughput", "images/sec/host",
                     bench_input_pipeline),
        "async_ab": ("async_dispatch_speedup", "x (sync/async step time)",
                     bench_async_ab),
        "telemetry_ab": ("telemetry_overhead", "x (on/off step time)",
                         bench_telemetry_ab),
        "diag_ab": ("diagnostics_overhead", "x (on/off step time)",
                    bench_diagnostics_ab),
        "cold_warm": ("cold_warm_compile_ratio",
                      "x (cold/warm compile time)", bench_cold_warm),
        "serving": ("serving_continuous_vs_static",
                    "x (continuous/static tokens/s)", bench_serving),
        "zero_stage": ("zero_opt_bytes_shrink",
                       "x (replicated/ZeRO-2 opt bytes per device)",
                       bench_zero_stages),
        "parallel_4d": ("parallel_4d_unified_speedup",
                        "x (island/unified 4D step time, bit-exact)",
                        bench_parallel_4d),
        "embedding_ab": ("embedding_server_scaling",
                         "x (2srv/1srv embedding bytes/sec)",
                         bench_embedding_ab),
        "serving_fleet": ("serving_fleet_scaling",
                          "x (2rep/1rep fleet tokens/s)",
                          bench_serving_fleet),
        "speculative": ("speculative_decode_speedup",
                        "x (speculative/plain tokens/s, token-exact)",
                        bench_speculative),
        "kv_quant": ("kv_quant_resident_ratio",
                     "x (int8/f32 resident sequences at equal bytes)",
                     bench_kv_quant),
        "fleet_obs": ("fleet_observability_overhead",
                      "x (collector-on/off fleet tokens/s)",
                      bench_fleet_observability),
        "streaming_input": ("streaming_input_speedup",
                            "x (data plane/per-process DataLoader img/s)",
                            bench_streaming_input),
        "prefix_reuse": ("prefix_reuse_speedup",
                         "x (reuse-on/off tokens/s, token-exact)",
                         bench_prefix_reuse),
        "autoscale": ("autoscale_slo_recovery",
                      "x (SLO / post-scale p99 — >=1 means recovered)",
                      bench_autoscale),
        "training_health": ("training_health_overhead",
                            "x (on/off step time, syncs bit-equal)",
                            bench_training_health_ab),
    }
    headline = None
    errors = []
    skipped = []
    best_resnet = None
    for name in ("resnet50", "bert", "lstm_ptb", "wide_deep", "lenet",
                 "pipeline", "async_ab", "telemetry_ab", "diag_ab",
                 "cold_warm", "serving", "zero_stage", "parallel_4d",
                 "embedding_ab", "serving_fleet", "speculative",
                 "kv_quant", "fleet_obs", "streaming_input",
                 "prefix_reuse", "autoscale", "training_health"):
        if name not in configs:
            continue
        cost = float(os.environ.get("BENCH_COST_%s" % name.upper(),
                                    _CONFIG_COST[name]))
        if _remaining() < cost:
            skipped.append(name)
            print("bench: skipping %s — %.0fs left < %.0fs estimate "
                  "(BENCH_BUDGET=%s)" % (name, _remaining(), cost, _BUDGET),
                  file=sys.stderr, flush=True)
            continue
        metric, unit, fn = metric_info[name]
        try:
            val, row = fn(platform, dtype)
            if name == "resnet50":
                best_resnet = (val, row)
            if headline is None:
                headline = {
                    "metric": metric,
                    "value": round(val, 2),
                    "unit": unit,
                    # only resnet50 has a (stand-in) published baseline
                    "vs_baseline": round(val / BASELINE_IMG_S, 3)
                    if name == "resnet50" else 0.0,
                    "mfu": row["mfu"],
                    "platform": platform,
                }
        except Exception as e:  # noqa: BLE001 — diagnostic JSON, not crash
            errors.append("%s: %r" % (name, e))

    # perf-round lever sweep on TRULY leftover budget (after every
    # standard config had its chance): batch/remat resnet variants, with
    # the headline updated to the BEST resnet row (VERDICT r3 #2 — the
    # official number should reflect the best landed configuration)
    if platform == "axon" and best_resnet is not None:
        variants = os.environ.get("BENCH_RESNET_VARIANTS", "256:,256:full")
        base_cost = float(os.environ.get("BENCH_COST_RESNET50",
                                         _CONFIG_COST["resnet50"]))
        for spec in [s for s in variants.split(",") if s]:
            vb, _, vr = spec.partition(":")
            # per-step work scales with batch; same iters -> same scaling
            cost = base_cost * max(1.0, int(vb) / 64.0) + 30
            if _remaining() < cost:
                skipped.append("resnet50@%s" % spec)
                continue
            try:
                v2, row2 = bench_resnet50(platform, dtype, batch=int(vb),
                                          remat=vr or "none")
                if v2 > best_resnet[0]:
                    best_resnet = (v2, row2)
            except Exception as e:  # noqa: BLE001
                errors.append("resnet50@%s: %r" % (spec, e))
        if headline is not None and \
                headline["metric"] == "resnet50_train_throughput":
            val, row = best_resnet
            headline["value"] = round(val, 2)
            headline["vs_baseline"] = round(val / BASELINE_IMG_S, 3)
            headline["mfu"] = row["mfu"]

    if headline is None:
        first = next((c for c in ("resnet50", "bert", "lstm_ptb",
                                  "wide_deep", "lenet") if c in configs),
                     "resnet50")
        metric, unit, _ = metric_info[first]
        headline = {"metric": metric, "value": 0.0,
                    "unit": unit, "vs_baseline": 0.0,
                    "platform": platform,
                    "error": "; ".join(errors)[-800:]}
    else:
        if errors:
            headline["partial_errors"] = "; ".join(errors)[-400:]
        if note:
            headline["note"] = note
    if skipped:
        headline["skipped_configs"] = ",".join(skipped)
    print(json.dumps(headline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
