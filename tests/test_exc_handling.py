"""Async-error semantics (models tests/python/unittest/test_exc_handling.py
— ops dispatch asynchronously; failures must surface at the sync points
(asnumpy / wait_to_read / asscalar), never pass silently).

The device-side failure is produced by a Custom op whose host callback
raises — the same mechanism the reference tests with a throwing CustomOp.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class _Raiser(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        raise ValueError("deliberate failure inside the operator")

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise ValueError("deliberate failure inside backward")


@mx.operator.register("test_exc_raiser")
class _RaiserProp(mx.operator.CustomOpProp):
    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Raiser()


def test_error_surfaces_at_asnumpy():
    with pytest.raises(Exception) as ei:
        y = nd.Custom(nd.ones((2, 2)), op_type="test_exc_raiser")
        y.asnumpy()  # the sync point — the error must surface by here
    assert "deliberate failure" in str(ei.value)


def test_error_surfaces_at_wait_to_read():
    with pytest.raises(Exception) as ei:
        y = nd.Custom(nd.ones((2, 2)), op_type="test_exc_raiser")
        y.wait_to_read()
    assert "deliberate failure" in str(ei.value)


def test_error_surfaces_at_asscalar():
    with pytest.raises(Exception) as ei:
        y = nd.Custom(nd.ones((1,)), op_type="test_exc_raiser")
        y.asscalar()
    assert "deliberate failure" in str(ei.value)


def test_error_does_not_poison_later_ops():
    """After a failed computation, fresh ops keep working (the reference's
    engine keeps scheduling after an op failure)."""
    try:
        nd.Custom(nd.ones((2, 2)), op_type="test_exc_raiser").asnumpy()
    except Exception:
        pass
    a = nd.array(np.arange(4.0, dtype="f4"))
    np.testing.assert_array_equal((a + 1).asnumpy(), [1, 2, 3, 4])


def test_backward_error_surfaces():
    from mxnet_tpu import autograd as ag

    x = nd.ones((2, 2))
    x.attach_grad()
    with pytest.raises(Exception) as ei:
        with ag.record():
            y = nd.Custom(x, op_type="test_exc_raiser")
        y.backward()
        x.grad.asnumpy()
    assert "deliberate failure" in str(ei.value)
