"""Fleet-wide observability plane (mxnet_tpu/telemetry_fleet.py):
membership-driven metric aggregation + end-to-end distributed request
tracing.

Covers the merged FleetRegistry (member labeling, typed label-collision
and schema-mismatch errors, cross-PROCESS histogram merge equal to the
union), the FleetCollector's scrape loop (tel_snapshot/tel_spans over
the real async transport, stale-member hygiene when a member dies
mid-loop, bounded — never a hang), the distributed trace
(queue/prefill/decode/commit spans reconstructing from trace_ids alone,
hedge rendering as two replica tracks with the loser's cancel visible,
failover re-enqueue span under seeded chaos), Chrome trace-event JSON
export + /debug/timeline, `mxt_top --fleet`, and serving-path host-sync
parity with the collector on vs off.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry, telemetry_fleet, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (DecodeEngine, FleetRouter, PagedKVCache,
                               TinyDecoder)
from mxnet_tpu.telemetry_fleet import (FleetCollector, FleetRegistry,
                                       chrome_trace, trace_tree)


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch, tmp_path):
    """Dead members must surface in milliseconds, not the production
    30s retry budget; every test gets its own tuning table and a clean
    trace-span log."""
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    telemetry.clear_trace_spans()
    yield
    telemetry.clear_trace_spans()
    tuning.reset()


MODEL = TinyDecoder(vocab=64, num_layers=1, num_heads=2, head_dim=8,
                    max_len=256)
PARAMS = MODEL.init_params(3)

_FREE_ENGINES = []  # drained engines recycled across tests (trace cost)


def _factory():
    while _FREE_ENGINES:
        eng = _FREE_ENGINES.pop()
        if eng.cache.pages_in_use() == 0 and not eng._seq_of_slot:
            return eng
    return DecodeEngine(
        MODEL, params=PARAMS, slots=2,
        cache=PagedKVCache(1, 2, 8, num_pages=64, page_size=8),
        prefill_buckets=(16,), max_context=64)


def _fleet(n, now_fn=time.monotonic):
    return serving.local_serving_fleet(n, _factory, now_fn=now_fn,
                                       warm=False)


def _close(pool, srv):
    for h in pool.replicas():
        if h.engine is not None and h.state != "dead":
            _FREE_ENGINES.append(h.engine)
        try:
            h.close()
        except Exception:  # noqa: BLE001 — killed handles
            pass
    srv.close()


def _ref(prompt, n):
    return MODEL.reference_decode(PARAMS, list(prompt), n)


def _mxt_top():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import mxt_top
    finally:
        sys.path.pop(0)
    return mxt_top


def _hist_export(name, labelnames, observations, help="x"):
    """A synthetic one-family registry export (unit-test ingest fuel)."""
    h = telemetry.Histogram(name, help, labelnames)
    for values, v in observations:
        h.labels(*values).observe(v)
    reg = telemetry.MetricsRegistry()
    reg._metrics[h.name] = h
    return reg.export()


# ---------------------------------------------------------------------------
# FleetRegistry: member labels, typed errors, merge semantics
# ---------------------------------------------------------------------------
def test_fleet_registry_member_label_and_per_member_values():
    exp0 = _hist_export("frh_lat", ("op",), [(("read",), 0.01)] * 3)
    exp1 = _hist_export("frh_lat", ("op",), [(("read",), 0.5)] * 2)
    reg = FleetRegistry()
    reg.ingest("m0", exp0)
    reg.ingest("m1", exp1, stale=True)
    page = reg.render_prometheus()
    top = _mxt_top()
    samples = top.parse_prometheus(page)
    assert top.metric_sum(samples, "frh_lat_count",
                          op="read", member="m0") == 3
    # the stale member's samples are labeled, not dropped silently
    assert top.metric_sum(samples, "frh_lat_count", op="read",
                          member="m1", stale="true") == 2
    assert sorted(reg.members()) == ["m0", "m1"]
    # drop-half of drop-or-label
    reg.drop_member("m1")
    assert reg.members() == ["m0"]


def test_fleet_registry_label_collision_typed():
    reg = FleetRegistry()
    bad = _hist_export("frh_bad", ("member",), [(("x",), 0.1)])
    with pytest.raises(MXNetError, match="label collision"):
        reg.ingest("m0", bad)
    bad2 = _hist_export("frh_bad2", ("stale",), [(("x",), 0.1)])
    with pytest.raises(MXNetError, match="label collision"):
        reg.ingest("m0", bad2)


def test_fleet_registry_schema_mismatch_typed():
    reg = FleetRegistry()
    reg.ingest("m0", _hist_export("frh_s", ("op",), [(("r",), 0.1)]))
    # different label schema
    with pytest.raises(MXNetError, match="schema mismatch"):
        reg.ingest("m1", _hist_export("frh_s", ("kind",),
                                      [(("r",), 0.1)]))
    # different kind under the same name
    c = telemetry.Counter("frh_s", "x", ("op",))
    creg = telemetry.MetricsRegistry()
    creg._metrics[c.name] = c
    c.labels("r").inc()
    with pytest.raises(MXNetError, match="schema mismatch"):
        reg.ingest("m2", creg.export())
    # different histogram buckets
    h = telemetry.Histogram("frh_s", "x", ("op",), buckets=(1.0, 2.0))
    hreg = telemetry.MetricsRegistry()
    hreg._metrics[h.name] = h
    h.labels("r").observe(0.5)
    with pytest.raises(MXNetError, match="buckets"):
        reg.ingest("m3", hreg.export())


def test_merged_histogram_equals_union_in_process():
    rng = np.random.RandomState(11)
    a = (rng.rand(40) * 0.2).tolist()
    b = (rng.rand(25) * 2.0).tolist()
    reg = FleetRegistry()
    reg.ingest("m0", _hist_export("frh_u", (), [((), v) for v in a]))
    reg.ingest("m1", _hist_export("frh_u", (), [((), v) for v in b]))
    union = telemetry.Histogram("frh_union", "x")
    for v in a + b:
        union.observe(v)
    snap = union.snapshot()
    merged = reg.merged_histogram("frh_u")
    assert merged["counts"] == snap["counts"]
    assert merged["count"] == snap["count"]
    assert abs(merged["sum"] - snap["sum"]) < 1e-9
    for q in (0.5, 0.9, 0.99):
        assert reg.quantile("frh_u", q) == union.quantile(q)


# ---------------------------------------------------------------------------
# cross-PROCESS merge: two real processes, scraped over the transport
# ---------------------------------------------------------------------------
_MEMBER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from mxnet_tpu import telemetry
from mxnet_tpu.async_server import AsyncParamServer

seed = int(sys.argv[1])
rng = np.random.RandomState(seed)
h = telemetry.histogram("mxt_xproc_lat_seconds", "x", ("op",))
for v in (rng.rand(30) * 0.3).tolist():
    h.labels("read").observe(v)
telemetry.counter("mxt_xproc_total", "x").inc(seed + 1)
telemetry.record_trace_span("remote_work", "trace-xproc-%d" % seed,
                            0.0, 0.001, clock_now=0.001,
                            track="member-%d" % seed)
srv = AsyncParamServer("127.0.0.1", 0)
print("PORT=%d" % srv._sock.getsockname()[1], flush=True)
time.sleep(120)
"""


def _spawn_member(tmp_path, seed):
    script = tmp_path / ("member_%d.py" % seed)
    script.write_text(_MEMBER_SCRIPT)
    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (root, os.environ.get("PYTHONPATH")) if p))
    proc = subprocess.Popen(
        [sys.executable, str(script), str(seed)],
        stdout=subprocess.PIPE, text=True, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    line = proc.stdout.readline()
    assert line.startswith("PORT="), line
    return proc, int(line.strip().split("=", 1)[1])


def test_cross_process_histogram_merge(tmp_path):
    """Two REAL processes exporting the same histogram family are
    scraped over the authenticated transport and merged: fleet
    quantiles equal the union's, counters sum, trace spans from both
    processes reassemble."""
    p0, port0 = _spawn_member(tmp_path, 1)
    p1, port1 = _spawn_member(tmp_path, 2)
    coll = FleetCollector(include_local=False, timeout=10.0)
    try:
        coll.add_member("p1", "127.0.0.1", port0)
        coll.add_member("p2", "127.0.0.1", port1)
        coll.scrape()
        reg = coll.fleet_registry()
        # parent recomputes each child's observations (same seeds)
        union = telemetry.Histogram("mxt_xproc_union", "x")
        for seed in (1, 2):
            rng = np.random.RandomState(seed)
            for v in (rng.rand(30) * 0.3).tolist():
                union.observe(v)
        merged = reg.merged_histogram("mxt_xproc_lat_seconds",
                                      labels={"op": "read"})
        snap = union.snapshot()
        assert merged["counts"] == snap["counts"]
        assert merged["count"] == snap["count"] == 60
        for q in (0.5, 0.99):
            assert reg.quantile("mxt_xproc_lat_seconds", q,
                                labels={"op": "read"}) \
                == union.quantile(q)
        assert reg.merged_value("mxt_xproc_total") == 2 + 3
        # per-member page values match the members' own registries
        top = _mxt_top()
        samples = top.parse_prometheus(reg.render_prometheus())
        assert top.metric_sum(samples, "mxt_xproc_total",
                              member="p1") == 2
        assert top.metric_sum(samples, "mxt_xproc_total",
                              member="p2") == 3
        # both processes' trace spans came back over tel_spans
        spans = coll.spans()
        tracks = {s.get("track") for s in spans}
        assert {"member-1", "member-2"} <= tracks
    finally:
        coll.close()
        for p in (p0, p1):
            p.terminate()
            p.wait(timeout=10)


def test_stale_member_mid_scrape_loop(tmp_path):
    """Kill a member between scrapes: the collector marks it stale
    (typed, bounded — no hang), its last values stay on the page
    labeled stale="true", and mxt_fleet_scrape_age_seconds{member}
    grows while the live member's age resets."""
    p0, port0 = _spawn_member(tmp_path, 3)
    p1, port1 = _spawn_member(tmp_path, 4)
    coll = FleetCollector(include_local=False, timeout=1.0)
    top = _mxt_top()
    try:
        coll.add_member("alive", "127.0.0.1", port0)
        coll.add_member("victim", "127.0.0.1", port1)
        coll.scrape()
        assert not coll.targets()["victim"].stale
        p1.kill()
        p1.wait(timeout=10)
        time.sleep(0.2)
        t0 = time.monotonic()
        coll.scrape()
        elapsed = time.monotonic() - t0
        assert elapsed < 15.0, "stale scrape must be bounded, not a hang"
        victim = coll.targets()["victim"]
        assert victim.stale and victim.error is not None
        samples = top.parse_prometheus(coll.render_prometheus())
        # the dead member's gauges are labeled, never silently live
        assert top.metric_sum(samples, "mxt_xproc_total",
                              member="victim", stale="true") == 5
        assert top.metric_sum(samples, "mxt_xproc_total",
                              member="alive") == 4
        age_v = top.metric_sum(samples, "mxt_fleet_scrape_age_seconds",
                               member="victim")
        age_a = top.metric_sum(samples, "mxt_fleet_scrape_age_seconds",
                               member="alive")
        assert age_v is not None and age_v > 0
        assert age_a is not None and age_a <= age_v
        assert top.metric_sum(samples, "mxt_fleet_members",
                              state="stale") == 1
        # merged aggregates exclude stale members by default...
        reg = coll.fleet_registry()
        assert reg.merged_value("mxt_xproc_total") == 4
        # ...and include them only on request
        assert reg.merged_value("mxt_xproc_total",
                                include_stale=True) == 9
    finally:
        coll.close()
        p0.terminate()
        p0.wait(timeout=10)


def test_stale_member_in_process_kill():
    """Tier-1 twin of the subprocess stale test: a scrape target whose
    server dies between scrapes goes stale (typed, bounded), keeps its
    last snapshot labeled, and its age gauge grows."""
    from mxnet_tpu.async_server import AsyncParamServer

    telemetry.counter("mxt_inproc_stale_total", "x").inc(7)
    srv = AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    clock = [100.0]
    coll = FleetCollector(include_local=False, timeout=0.5,
                          now_fn=lambda: clock[0])
    top = _mxt_top()
    try:
        coll.add_member("m", "127.0.0.1", port)
        coll.scrape()
        assert not coll.targets()["m"].stale
        srv.close()  # the member dies mid-scrape-loop
        clock[0] = 103.0
        t0 = time.monotonic()
        coll.scrape()
        assert time.monotonic() - t0 < 15.0
        assert coll.targets()["m"].stale
        samples = top.parse_prometheus(coll.render_prometheus())
        assert top.metric_sum(samples, "mxt_inproc_stale_total",
                              member="m", stale="true") == 7
        assert top.metric_sum(samples, "mxt_fleet_scrape_age_seconds",
                              member="m") == 3.0
    finally:
        coll.close()


# ---------------------------------------------------------------------------
# distributed request tracing over the in-process fleet
# ---------------------------------------------------------------------------
def test_trace_lifecycle_spans_and_chrome_export():
    """One routed request yields the full span tree — queue/prefill/
    decode on the replica track, dispatch/commit/request on the router
    track — reconstructed from the trace_id alone, and the Chrome
    trace-event export is valid JSON with matching events."""
    pool, srv = _fleet(1)
    router = FleetRouter(pool)
    rr = router.submit([5, 9, 2], max_new_tokens=3, token="tl1")
    assert rr.trace_id is not None
    router.run(max_steps=2000)
    assert rr.state == "completed"
    coll = FleetCollector(server=srv)
    coll.refresh()
    coll.scrape()
    tree = coll.trace_tree(rr.trace_id)
    names = set(tree["names"])
    assert {"queue", "prefill", "decode",
            "dispatch", "commit", "request"} <= names
    assert set(tree["tracks"]) == {"router", "replica-0"}
    rep = [s["name"] for s in tree["tracks"]["replica-0"]]
    assert rep.index("queue") < rep.index("prefill") < rep.index("decode")
    # exactly one commit span, stamped with the committing replica
    commits = [s for s in tree["tracks"]["router"]
               if s["name"] == "commit"]
    assert len(commits) == 1
    assert commits[0]["attrs"]["replica"] == 0
    assert commits[0]["attrs"]["commits"] == 1
    # Chrome trace-event JSON: loadable, one X/i event per span plus
    # process/thread metadata
    doc = json.loads(json.dumps(coll.chrome_trace(rr.trace_id)))
    evs = doc["traceEvents"]
    assert all(set(e) >= {"name", "ph", "pid", "tid", "ts"} for e in evs)
    span_evs = [e for e in evs if e["ph"] in ("X", "i")]
    assert len(span_evs) == len(tree["names"])
    proc_names = {e["args"]["name"] for e in evs
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert proc_names == {"router", "replica-0"}
    coll.close()
    _close(pool, srv)


def test_trace_hedge_renders_two_replica_tracks():
    """A hedged request's trace shows spans on BOTH replica tracks,
    the hedge instant on the router track, and the loser's cancel —
    commits stays 1."""
    clock = [0.0]
    pool, srv = _fleet(2, now_fn=lambda: clock[0])
    router = FleetRouter(pool, now_fn=lambda: clock[0],
                         hedge_delay=1.0, hedge_budget=4)
    rr = router.submit([5, 9, 2], max_new_tokens=3, token="th1")
    router.step()
    rid0 = next(iter(rr.copies))
    pool.get(rid0).slow_until = 1e9  # brownout: hedge bait
    clock[0] = 1.5
    router.step()
    assert rr.hedges == 1
    router.run(max_steps=2000)
    assert rr.state == "completed" and rr.commits == 1
    pool.get(rid0).slow_until = 0.0
    tree = trace_tree(telemetry.trace_spans(), rr.trace_id)
    tracks = set(tree["tracks"])
    assert {"router", "replica-0", "replica-1"} <= tracks
    names = set(tree["names"])
    assert "hedge" in names and "cancel" in names
    # the loser's cancel names the browned-out replica; its own track
    # carries the evicted span (cancelled through the eviction path)
    cancels = [s for s in tree["tracks"]["router"]
               if s["name"] == "cancel"]
    assert any(s["attrs"]["replica"] == rid0 for s in cancels)
    loser_names = [s["name"]
                   for s in tree["tracks"]["replica-%d" % rid0]]
    assert "evicted" in loser_names
    commits = [s for s in tree["tracks"]["router"]
               if s["name"] == "commit"]
    assert len(commits) == 1
    _close(pool, srv)


def test_untraced_requests_cost_nothing():
    """A plain batcher request without a trace_id records zero spans
    (the tracing layer is strictly pay-per-use)."""
    eng = _factory()
    sched = serving.ContinuousBatcher(eng)
    sched.submit(serving.Request([3, 4], max_new_tokens=3))
    sched.run()
    assert telemetry.trace_spans() == []
    _FREE_ENGINES.append(eng)


def test_standalone_replica_spans_over_the_wire():
    """trace_id rides the srv_submit frame to a standalone replica;
    its queue/prefill/decode spans come back over tel_spans and merge
    with the router's — the cross-process trace tree."""
    from mxnet_tpu.async_server import AsyncParamServer
    from mxnet_tpu.serving import fleet as fleet_mod

    coord_srv = AsyncParamServer("127.0.0.1", 0)
    coord = ("127.0.0.1", coord_srv._sock.getsockname()[1])
    eng = _factory()
    rep_srv, host, member, stop = fleet_mod.serve_replica(
        eng, coord, index=7)
    try:
        pool = fleet_mod.ReplicaPool(coordinator=coord,
                                     server=coord_srv)
        pool.refresh()
        router = FleetRouter(pool)
        rr = router.submit([3, 1, 4], max_new_tokens=3, token="rs1")
        deadline = time.monotonic() + 30.0
        while not rr.done and time.monotonic() < deadline:
            router.step()
            time.sleep(0.01)
        assert rr.state == "completed"
        # the collector discovers the standalone replica from the
        # membership meta and scrapes its spans over tel_spans
        coll = FleetCollector(server=coord_srv)
        coll.refresh()
        assert "replica-7" in coll.targets()
        coll.scrape()
        tree = coll.trace_tree(rr.trace_id)
        assert {"router", "replica-7"} <= set(tree["tracks"])
        rep_names = [s["name"] for s in tree["tracks"]["replica-7"]]
        assert {"queue", "prefill", "decode"} <= set(rep_names)
        # the scraped page carries the replica's serving metrics under
        # its member label
        top = _mxt_top()
        samples = top.parse_prometheus(coll.render_prometheus())
        assert top.metric_sum(samples, "mxt_serving_tokens_total",
                              member="replica-7") is not None
        coll.close()
        pool.close()
    finally:
        stop()
        coord_srv.close()


# ---------------------------------------------------------------------------
# chaos: failover during an active trace + dead-endpoint scrape
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_chaos_failover_trace_and_dead_endpoint(monkeypatch):
    """Seeded replica_kill during active traces: every trace tree still
    exports, the failed-over request's tree carries the
    failover_reenqueue span and commits==1 — and the collector scraping
    a dead endpoint gets a typed stale verdict, never a hang."""
    from mxnet_tpu import resilience

    monkeypatch.setenv(
        "MXT_FAULT",
        "replica_kill:replica=1,after=2,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    try:
        pool, srv = _fleet(2)
        router = FleetRouter(pool)
        rng = np.random.RandomState(_seed())
        reqs = [router.submit(rng.randint(1, 64, 4).tolist(),
                              max_new_tokens=8, token="cf%d" % i)
                for i in range(6)]
        router.run(max_steps=2000)
        assert pool.get(1).state == "dead"
        assert all(rr.state == "completed" for rr in reqs)
        assert all(rr.result == _ref(rr.prompt, rr.max_new_tokens)
                   for rr in reqs)
        failed_over = [rr for rr in reqs if rr.failovers > 0]
        assert failed_over
        coll = FleetCollector(server=srv, timeout=0.5)
        coll.refresh()
        # a dead endpoint in the target set: typed stale, bounded
        coll.add_member("ghost", "127.0.0.1", 1)
        t0 = time.monotonic()
        coll.scrape()
        assert time.monotonic() - t0 < 15.0
        assert coll.targets()["ghost"].stale
        for rr in reqs:
            tree = coll.trace_tree(rr.trace_id)
            assert "request" in tree["names"]
            assert rr.commits == 1
            commits = [s for s in tree["names"] if s == "commit"]
            assert len(commits) == 1
        for rr in failed_over:
            tree = coll.trace_tree(rr.trace_id)
            assert "failover_reenqueue" in tree["names"]
            # the whole-fleet chrome export stays loadable JSON
        doc = json.loads(json.dumps(coll.chrome_trace()))
        assert doc["traceEvents"]
        coll.close()
        _close(pool, srv)
    finally:
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# /debug/timeline + /fleet endpoint routes
# ---------------------------------------------------------------------------
def test_debug_timeline_route():
    pool, srv = _fleet(1)
    router = FleetRouter(pool)
    rr = router.submit([5, 2], max_new_tokens=2, token="dt1")
    router.run(max_steps=2000)
    coll = FleetCollector(server=srv)
    coll.refresh()
    coll.scrape()
    telemetry_fleet.set_default_collector(coll)
    try:
        from mxnet_tpu import diagnostics

        status, ctype, body = diagnostics.handle_debug(
            "/debug/timeline", "trace_id=%s" % rr.trace_id)
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body.decode("utf-8"))
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"queue", "prefill", "decode", "commit"} <= names
        # whole-fleet timeline (no trace_id) also exports
        status, _, body = diagnostics.handle_debug("/debug/timeline", "")
        assert status == 200
        assert json.loads(body.decode("utf-8"))["traceEvents"]
    finally:
        telemetry_fleet.set_default_collector(None)
        coll.close()
        _close(pool, srv)


def test_timeline_without_collector_serves_local_spans():
    """A bare replica (no collector registered) still serves its own
    span log from /debug/timeline."""
    assert telemetry_fleet.default_collector() is None
    telemetry.record_trace_span("solo", "trace-solo", 0.0, 0.01,
                                clock_now=0.01, track="replica-0")
    from mxnet_tpu import diagnostics

    status, _, body = diagnostics.handle_debug(
        "/debug/timeline", "trace_id=trace-solo")
    assert status == 200
    doc = json.loads(body.decode("utf-8"))
    assert any(e.get("name") == "solo" for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# host-sync parity: the collector adds ZERO serving-path syncs
# ---------------------------------------------------------------------------
def test_collector_host_sync_parity():
    """The same traffic with a collector scraping on a background
    thread vs observability idle: serving-path host-sync counts are
    bit-identical (the collector reads registries, never the device)."""
    from mxnet_tpu import profiler

    def run(with_collector):
        pool, srv = _fleet(2)
        router = FleetRouter(pool)
        coll = None
        if with_collector:
            coll = FleetCollector(server=srv)
            coll.refresh()
            coll.start(interval=0.02)
        rng = np.random.RandomState(5)
        reqs = [router.submit(rng.randint(1, 64, 5).tolist(),
                              max_new_tokens=4, token="sp%d" % i)
                for i in range(6)]
        h0 = profiler.host_sync_count()
        router.run(max_steps=2000)
        syncs = profiler.host_sync_count() - h0
        assert all(rr.state == "completed" for rr in reqs)
        if coll is not None:
            coll.scrape()  # at least one full pass before teardown
            coll.close()
        _close(pool, srv)
        return syncs

    base = run(False)
    with_coll = run(True)
    assert with_coll == base, (base, with_coll)


# ---------------------------------------------------------------------------
# mxt_top --fleet
# ---------------------------------------------------------------------------
def test_mxt_top_fleet_section_golden():
    pool, srv = _fleet(2)
    router = FleetRouter(pool)
    rng = np.random.RandomState(9)
    for i in range(4):
        router.submit(rng.randint(1, 64, 4).tolist(), max_new_tokens=3,
                      token="mt%d" % i)
    router.run(max_steps=2000)
    coll = FleetCollector(server=srv)
    coll.refresh()
    coll.scrape()
    top = _mxt_top()
    samples = top.parse_prometheus(coll.render_prometheus())
    frame = top.render(samples, None, 0)
    assert "fleet members" in frame
    assert "occupancy" in frame
    assert "scrape age" in frame
    # fleet tok/s needs a rate window: second frame with a delta
    frame2 = top.render(samples, samples, 1.0)
    assert "fleet tok/s" in frame2
    coll.close()
    _close(pool, srv)


# ---------------------------------------------------------------------------
# acceptance: hedged + failed-over traffic -> one fleet page whose
# per-member values match the per-process page, and both requests'
# span trees reconstruct from trace_ids alone
# ---------------------------------------------------------------------------
def test_fleet_observability_acceptance():
    clock = [0.0]
    pool, srv = _fleet(2, now_fn=lambda: clock[0])
    router = FleetRouter(pool, now_fn=lambda: clock[0],
                         hedge_delay=1.0, hedge_budget=4)
    # request A: hedged (replica brownout past the hedge delay)
    ra = router.submit([5, 9, 2], max_new_tokens=3, token="accA")
    router.step()
    rid0 = next(iter(ra.copies))
    pool.get(rid0).slow_until = 1e9
    clock[0] = 1.5
    router.step()
    assert ra.hedges == 1
    router.run(max_steps=2000)
    pool.get(rid0).slow_until = 0.0
    # request B: failed over (its replica killed mid-flight)
    rb = router.submit([7, 1, 3, 2], max_new_tokens=4, token="accB")
    router.step()
    victim = next(iter(rb.copies))
    pool.get(victim).kill()
    router.run(max_steps=2000)
    assert ra.state == rb.state == "completed"
    assert ra.commits == rb.commits == 1
    assert ra.result == _ref(ra.prompt, 3)
    assert rb.result == _ref(rb.prompt, 4)

    coll = FleetCollector(server=srv)
    coll.refresh()
    coll.scrape()
    top = _mxt_top()
    fleet_page = top.parse_prometheus(coll.render_prometheus())
    local_page = top.parse_prometheus(telemetry.render_prometheus())
    # (a) the fleet page's per-member samples are bit-identical to the
    # per-process page for every serving/fleet family (histogram
    # buckets included — the merge adds provenance, never rewrites)
    checked = 0
    for (name, labels), v in fleet_page.items():
        base = name.partition("_bucket")[0]
        if not (base.startswith("mxt_serving")
                or base.startswith("mxt_fleet_request")):
            continue
        lab = dict(labels)
        if lab.pop("member", None) != "local":
            continue
        lab.pop("stale", None)
        assert local_page[(name, frozenset(lab.items()))] == v
        checked += 1
    assert checked > 20, "acceptance must compare real families"
    # (b) both requests' full span trees reconstruct from trace_ids
    ta = coll.trace_tree(ra.trace_id)
    assert {"queue", "prefill", "decode", "commit", "hedge",
            "cancel"} <= set(ta.get("names"))
    assert len(set(ta["tracks"]) & {"replica-0", "replica-1"}) == 2
    tb = coll.trace_tree(rb.trace_id)
    assert {"queue", "prefill", "decode", "commit",
            "failover_reenqueue"} <= set(tb["names"])
    assert [s for s in tb["names"] if s == "commit"] == ["commit"]
    doc = json.loads(json.dumps(coll.chrome_trace()))
    assert doc["traceEvents"]
    coll.close()
    _close(pool, srv)


# ---------------------------------------------------------------------------
# lint: the new modules stay on the host-sync scan list
# ---------------------------------------------------------------------------
def test_fleet_observability_lint_enforced():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert "mxnet_tpu/telemetry_fleet.py" in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root)
           if b[0] in ("mxnet_tpu/telemetry_fleet.py",
                       "mxnet_tpu/telemetry.py",
                       "mxnet_tpu/serving/router.py",
                       "mxnet_tpu/serving/scheduler.py")]
    assert not bad, bad
