"""Extended op families added in round 3: block/space rearrangement,
index transforms, im2col/col2im, cumulative reductions, shrink
activations, AMP casts, multinomial sampling, and the spatial-transform /
detection ops (ref: src/operator/tensor/matrix_op.cc, ravel.cc,
nn/im2col.h, nn/moments.cc, amp_cast.cc, random/multisample_op.cc,
spatial_transformer.cc, grid_generator.cc, roi_pooling.cc,
correlation.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import autograd as ag


def test_tril_triu():
    a = nd.array(np.arange(16, dtype="f4").reshape(4, 4))
    np.testing.assert_array_equal(nd.tril(a).asnumpy(),
                                  np.tril(a.asnumpy()))
    np.testing.assert_array_equal(nd.triu(a, k=1).asnumpy(),
                                  np.triu(a.asnumpy(), 1))


def test_depth_space_roundtrip():
    x = nd.array(np.random.RandomState(0).rand(2, 12, 4, 6).astype("f4"))
    y = nd.depth_to_space(x, block_size=2)
    assert y.shape == (2, 3, 8, 12)
    z = nd.space_to_depth(y, block_size=2)
    np.testing.assert_array_equal(z.asnumpy(), x.asnumpy())


def test_depth_to_space_dcr_semantics():
    # y[n, c, h*b+i, w*b+j] = x[n, (i*b+j)*C + c, h, w]
    x = np.arange(1 * 8 * 2 * 2, dtype="f4").reshape(1, 8, 2, 2)
    y = nd.depth_to_space(nd.array(x), block_size=2).asnumpy()
    b, c = 2, 2
    for i in range(b):
        for j in range(b):
            for ch in range(c):
                np.testing.assert_array_equal(
                    y[0, ch, i::b, j::b], x[0, (i * b + j) * c + ch])


def test_reshape_like():
    lhs = nd.array(np.arange(24, dtype="f4"))
    rhs = nd.zeros((2, 3, 4))
    assert nd.reshape_like(lhs, rhs).shape == (2, 3, 4)
    lhs2 = nd.array(np.arange(24, dtype="f4").reshape(6, 4))
    out = nd.reshape_like(lhs2, nd.zeros((2, 3)), lhs_begin=0, lhs_end=1,
                          rhs_begin=0, rhs_end=2)
    assert out.shape == (2, 3, 4)


def test_ravel_unravel_roundtrip():
    shape = (3, 4, 5)
    flat = nd.array(np.array([0, 7, 59, 23], dtype="f4"))
    coords = nd.unravel_index(flat, shape=shape)
    back = nd.ravel_multi_index(coords, shape=shape)
    np.testing.assert_array_equal(back.asnumpy(), [0, 7, 59, 23])


def test_batch_take_and_fill():
    a = nd.array(np.arange(12, dtype="f4").reshape(3, 4))
    idx = nd.array(np.array([1, 0, 3], dtype="f4"))
    np.testing.assert_array_equal(nd.batch_take(a, idx).asnumpy(),
                                  [1.0, 4.0, 11.0])
    np.testing.assert_array_equal(
        nd.choose_element_0index(a, idx).asnumpy(), [1.0, 4.0, 11.0])
    filled = nd.fill_element_0index(a, nd.array(np.array([9, 8, 7], "f4")),
                                    idx)
    assert filled.asnumpy()[0, 1] == 9 and filled.asnumpy()[2, 3] == 7


def test_im2col_col2im_transpose_pair():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(2, 3, 6, 6).astype("f4"))
    col = nd.im2col(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1))
    assert col.shape == (2, 27, 36)
    img = nd.col2im(col, output_size=(6, 6), kernel=(3, 3), stride=(1, 1),
                    pad=(1, 1))
    # col2im(im2col(x)) multiplies each pixel by its patch-coverage count;
    # interior pixels of a 3x3/pad-1 window are covered 9 times
    np.testing.assert_allclose(img.asnumpy()[:, :, 2:4, 2:4],
                               9 * x.asnumpy()[:, :, 2:4, 2:4], rtol=1e-5)


def test_cumsum_cumprod_grad():
    x = nd.array(np.arange(1, 7, dtype="f4").reshape(2, 3))
    np.testing.assert_allclose(nd.cumsum(x, axis=1).asnumpy(),
                               np.cumsum(x.asnumpy(), axis=1))
    np.testing.assert_allclose(nd.cumprod(x, axis=0).asnumpy(),
                               np.cumprod(x.asnumpy(), axis=0))
    xa = nd.array(np.ones((3,), "f4"))
    xa.attach_grad()
    with ag.record():
        y = nd.cumsum(xa).sum()
    y.backward()
    np.testing.assert_allclose(xa.grad.asnumpy(), [3.0, 2.0, 1.0])


def test_moments():
    x = np.random.RandomState(0).randn(4, 5).astype("f4")
    m, v = nd.moments(nd.array(x), axes=(0,))
    np.testing.assert_allclose(m.asnumpy(), x.mean(0), rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), x.var(0), rtol=1e-4)


def test_shrink_ops():
    x = nd.array(np.array([-2.0, -0.3, 0.1, 0.9], dtype="f4"))
    np.testing.assert_allclose(nd.hardshrink(x, lambd=0.5).asnumpy(),
                               [-2.0, 0.0, 0.0, 0.9])
    np.testing.assert_allclose(nd.softshrink(x, lambd=0.5).asnumpy(),
                               [-1.5, 0.0, 0.0, 0.4], rtol=1e-6)


def test_digamma():
    from scipy.special import digamma as ref  # noqa: F401
    # scipy may be absent; compare against the known value psi(1) = -gamma
    out = float(nd.digamma(nd.array(np.array([1.0], "f4"))).asnumpy()[0])
    assert abs(out - (-0.5772157)) < 1e-4


def test_amp_cast_multicast():
    a = nd.array(np.ones(4, "f4"))
    assert nd.amp_cast(a, dtype="float16").dtype == np.float16
    outs = nd.amp_multicast(nd.array(np.ones(3, "f2")),
                            nd.array(np.ones(3, "f4")))
    assert all(o.dtype == np.float32 for o in outs)


def test_multinomial_distribution():
    mx.random.seed(0)
    p = nd.array(np.array([[0.9, 0.05, 0.05], [0.05, 0.05, 0.9]], "f4"))
    s = nd.sample_multinomial(p, shape=(500,)).asnumpy()
    assert np.bincount(s[0]).argmax() == 0
    assert np.bincount(s[1]).argmax() == 2
    s2, logp = nd.sample_multinomial(p, shape=(4,), get_prob=True)
    assert s2.shape == (2, 4) and logp.shape == (2, 4)
    assert np.all(logp.asnumpy() <= 0)


def test_spatial_transformer_identity():
    x = nd.array(np.random.RandomState(0).rand(2, 3, 8, 8).astype("f4"))
    theta = nd.array(np.tile(np.array([1, 0, 0, 0, 1, 0], "f4"), (2, 1)))
    out = nd.SpatialTransformer(x, theta, target_shape=(8, 8))
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), atol=1e-5)


def test_grid_generator_warp_zero_flow():
    flow = nd.zeros((1, 2, 4, 4))
    grid = nd.GridGenerator(flow, transform_type="warp").asnumpy()
    assert grid[0, 0, 0, 0] == -1.0 and grid[0, 0, 0, -1] == 1.0
    assert grid[0, 1, 0, 0] == -1.0 and grid[0, 1, -1, 0] == 1.0


def test_roi_pooling_full_roi_is_global_max():
    x = nd.array(np.random.RandomState(1).rand(2, 4, 7, 7).astype("f4"))
    rois = nd.array(np.array([[0, 0, 0, 6, 6], [1, 0, 0, 6, 6]], "f4"))
    out = nd.ROIPooling(x, rois, pooled_size=(1, 1), spatial_scale=1.0)
    np.testing.assert_allclose(out.asnumpy()[:, :, 0, 0],
                               x.asnumpy().max(axis=(2, 3)), rtol=1e-6)


def test_roi_pooling_quadrants():
    x = np.zeros((1, 1, 4, 4), "f4")
    x[0, 0, 0, 0] = 5.0   # top-left
    x[0, 0, 3, 3] = 7.0   # bottom-right
    out = nd.ROIPooling(nd.array(x), nd.array(np.array([[0, 0, 0, 3, 3]],
                                                       "f4")),
                        pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    assert out[0, 0, 0, 0] == 5.0
    assert out[0, 0, 1, 1] == 7.0


def test_correlation_self_zero_displacement():
    x = nd.array(np.random.RandomState(2).rand(2, 8, 6, 6).astype("f4"))
    out = nd.Correlation(x, x, kernel_size=1, max_displacement=1)
    assert out.shape == (2, 9, 6, 6)
    np.testing.assert_allclose(out.asnumpy()[:, 4],
                               (x.asnumpy() ** 2).mean(axis=1), rtol=1e-5)


def test_bilinear_sampler_zero_pads_outside():
    # grid points fully outside the image must sample ZERO (the
    # reference's between() guard), not replicate the border
    x = nd.array(np.full((1, 1, 4, 4), 5.0, "f4"))
    grid = np.zeros((1, 2, 1, 2), "f4")
    grid[0, 0, 0, 0] = -3.0  # x far left of the image
    grid[0, 1, 0, 0] = 0.0
    grid[0, 0, 0, 1] = 0.0   # center: in-bounds
    grid[0, 1, 0, 1] = 0.0
    out = nd.BilinearSampler(x, nd.array(grid)).asnumpy()
    assert abs(out[0, 0, 0, 0]) < 1e-6
    np.testing.assert_allclose(out[0, 0, 0, 1], 5.0, atol=1e-5)


def test_bilinear_sampler_partial_corner_zero():
    # a sample half a pixel past the right edge keeps only its in-bounds
    # corner pair weighted by the bilinear weights: value * (1 - wx)
    x = nd.array(np.full((1, 1, 4, 4), 2.0, "f4"))
    grid = np.zeros((1, 2, 1, 1), "f4")
    # gx = (g+1)*(w-1)/2 = 3.5 at g = 4/3 -> corners x0=3 (in),
    # x1=4 (out), wx=0.5 -> only the in-bounds pair contributes
    grid[0, 0, 0, 0] = 4.0 / 3.0
    out = nd.BilinearSampler(x, nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out[0, 0, 0, 0], 1.0, atol=1e-5)


def test_grid_generator_warp_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    flow = np.random.RandomState(3).uniform(
        -0.3, 0.3, (1, 2, 3, 4)).astype("f8")
    check_numeric_gradient(
        lambda f: nd.GridGenerator(f, transform_type="warp").sum(), [flow])


def test_comparison_and_logical_elemwise_aliases():
    a = nd.array(np.array([1.0, 2.0, 3.0], "f4"))
    b = nd.array(np.array([2.0, 2.0, 1.0], "f4"))
    np.testing.assert_array_equal(nd.equal(a, b).asnumpy(), [0, 1, 0])
    np.testing.assert_array_equal(nd.not_equal(a, b).asnumpy(), [1, 0, 1])
    np.testing.assert_array_equal(nd.greater(a, b).asnumpy(), [0, 0, 1])
    np.testing.assert_array_equal(nd.greater_equal(a, b).asnumpy(),
                                  [0, 1, 1])
    np.testing.assert_array_equal(nd.lesser(a, b).asnumpy(), [1, 0, 0])
    np.testing.assert_array_equal(nd.lesser_equal(a, b).asnumpy(),
                                  [1, 1, 0])
    x = nd.array(np.array([0.0, 1.0, 2.0], "f4"))
    z = nd.array(np.array([0.0, 0.0, 3.0], "f4"))
    np.testing.assert_array_equal(nd.logical_and(x, z).asnumpy(), [0, 0, 1])
    np.testing.assert_array_equal(nd.logical_or(x, z).asnumpy(), [0, 1, 1])
    np.testing.assert_array_equal(nd.logical_xor(x, z).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose(nd.mod(a, b).asnumpy(), [1, 0, 0])


def test_all_finite_ops():
    good = nd.array(np.ones((3, 3), "f4"))
    bad = nd.array(np.array([[1.0, np.inf], [0.0, 1.0]], "f4"))
    nan = nd.array(np.array([np.nan], "f4"))
    assert nd.all_finite(good).asnumpy().tolist() == [1.0]
    assert nd.all_finite(bad).asnumpy().tolist() == [0.0]
    assert nd.all_finite(nan).asnumpy().tolist() == [0.0]
    assert nd.multi_all_finite(good, good, num_arrays=2
                               ).asnumpy().tolist() == [1.0]
    assert nd.multi_all_finite(good, bad, num_arrays=2
                               ).asnumpy().tolist() == [0.0]


def test_crop_op_variants():
    x = nd.array(np.arange(2 * 1 * 6 * 6, dtype="f4").reshape(2, 1, 6, 6))
    like = nd.zeros((2, 1, 4, 4))
    o = nd.Crop(x, like, num_args=2, center_crop=True)
    np.testing.assert_array_equal(o.asnumpy(), x.asnumpy()[:, :, 1:5, 1:5])
    o2 = nd.Crop(x, h_w=(3, 3), offset=(2, 1))
    np.testing.assert_array_equal(o2.asnumpy(), x.asnumpy()[:, :, 2:5, 1:4])
    with pytest.raises(ValueError):
        nd.Crop(x, h_w=(7, 7))


def test_svm_output_forward_identity_and_training():
    # forward is identity; gradients push violating classes down
    from mxnet_tpu import autograd as ag

    x = nd.array(np.array([[2.0, 1.0, 0.0]], "f4"))
    y = nd.array(np.array([0.0], "f4"))
    out = nd.SVMOutput(x, y)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    xv = nd.array(np.array([[0.5, 1.0, 0.2]], "f4"))
    xv.attach_grad()
    with ag.record():
        o = nd.SVMOutput(xv, y)
        o.backward(nd.ones(o.shape))
    g = xv.grad.asnumpy()[0]
    assert g[1] > 0 and g[0] < 0  # violator pushed down, true class up
