"""Async dispatch engine (mxnet_tpu/engine.py): ThreadedEngine semantics
over XLA — K-deep in-flight fused steps, deferred host reads, waitall as
the drain barrier, and the static host-sync lint.

The load-bearing properties:

- numerics are bit-exact at ANY window depth (the non-finite skip is
  compiled on-device; only host *bookkeeping* is deferred);
- the fused-step hot path performs <= 1 host sync per K steps;
- ``nd.waitall()`` / ``CheckpointManager`` drain the window, so counters
  and snapshots are consistent at every barrier.
"""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import engine, metric, nd, profiler, resilience
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.ndarray.pending import PendingValue

_loss_fn = mx.gluon.loss.L2Loss()


@pytest.fixture(autouse=True)
def _drained():
    """Leave no in-flight tokens behind for the next test."""
    yield
    engine.wait_all()


def _make(opt, opt_args, seed=11, prefix="asy_"):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    tr = Trainer(net.collect_params(), opt, dict(opt_args))
    return net, tr


def _batches(n, nan_at=None, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for t in range(n):
        x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
        y = rng.uniform(-1, 1, (8, 4)).astype(np.float32)
        if t == nan_at:
            x[0, 0] = np.nan
        out.append((nd.array(x), nd.array(y)))
    return out


def _weights(net):
    return [p.data().asnumpy().copy()
            for _, p in sorted(net.collect_params().items())]


# ---------------------------------------------------------------------------
# bit-exactness: async vs sync
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
@pytest.mark.parametrize("guard", ["0", "1"])
def test_async_vs_sync_bitexact(monkeypatch, opt, args, guard):
    """5+ steps through the fused path at window K=1 vs K=4: losses and
    weights match bit-exactly, guard on and off (with a NaN batch when
    the guard is on, so the deferred skip path is exercised)."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", guard)
    data = _batches(6, nan_at=3 if guard == "1" else None)

    def run(k):
        net, tr = _make(opt, args)
        step = tr.fuse_step(net, _loss_fn)
        losses = []
        with engine.bulk(k):
            for x, y in data:
                losses.append(step(x, y))
            nd.waitall()
        assert step.fused
        return ([l.asnumpy() for l in losses], _weights(net),
                tr._optimizer.num_update)

    l1, w1, n1 = run(1)
    l4, w4, n4 = run(4)
    assert n1 == n4 == (5 if guard == "1" else 6)
    for a, b in zip(l1, l4):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(w1, w4):
        np.testing.assert_array_equal(a, b)


def test_trainer_step_guarded_async_bitexact(monkeypatch):
    """The canonical record/backward/trainer.step loop with the guard on:
    the fused in-program guard + deferred flag matches the synchronous
    window bit-exactly, including the skip."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    data = _batches(5, nan_at=2, seed=3)

    def run(k):
        net, tr = _make("sgd", {"learning_rate": 0.1, "momentum": 0.9})
        with engine.bulk(k):
            for x, y in data:
                with ag.record():
                    loss = _loss_fn(net(x), y)
                loss.backward()
                tr.step(8)
            nd.waitall()
        return _weights(net), tr._optimizer.num_update

    w1, n1 = run(1)
    w4, n4 = run(4)
    assert n1 == n4 == 4  # one skipped
    for a, b in zip(w1, w4):
        np.testing.assert_array_equal(a, b)
    assert resilience.skipped_step_count() >= 2


# ---------------------------------------------------------------------------
# host-sync accounting
# ---------------------------------------------------------------------------
def test_at_most_one_host_sync_per_window(monkeypatch):
    """With the guard on and K=4, 8 fused steps cost at most 8/K = 2
    framework host reads before the drain (the host_syncs gauge is the
    bench's host_syncs_per_step source)."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    net, tr = _make("adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, _loss_fn)
    (x, y), = _batches(1)
    step(x, y)
    nd.waitall()  # build + compile + land the first flag
    with engine.bulk(4):
        h0 = profiler.host_sync_count()
        for _ in range(8):
            step(x, y)
        mid = profiler.host_sync_count() - h0
        nd.waitall()
    assert mid <= 2, "expected <= 8/K deferred reads, saw %d" % mid
    assert profiler.gauge_value("dispatch_depth") == 0  # drained


def test_waitall_drains_bookkeeping(monkeypatch):
    """Counters lag while steps are in flight; nd.waitall() is the
    barrier that lands them (the chaos_matrix.sh contract)."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    net, tr = _make("adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, _loss_fn)
    data = _batches(6)
    with engine.bulk(8):
        for x, y in data:
            step(x, y)
        assert engine.inflight_depth() > 0
        nd.waitall()
        assert engine.inflight_depth() == 0
        assert tr._optimizer.num_update == 6


def test_bulk_is_the_real_knob():
    """set_bulk_size returns the previous effective depth and bulk()
    scopes it (the reference API, now load-bearing)."""
    prev = engine.set_bulk_size(8)
    assert engine.max_inflight() == 8
    assert engine.set_bulk_size(prev) == 8
    with engine.bulk(1):
        assert engine.max_inflight() == 1
    with engine.bulk(64):
        assert engine.max_inflight() == 15  # clamped to the mask width


def test_pending_value_protocol():
    """PendingValue defers the read, fires callbacks once, and counts
    exactly one host sync per materialization."""
    import jax.numpy as jnp

    pv = PendingValue(jnp.float32(4.0) * 2)
    fired = []
    pv.on_ready(fired.append)
    assert not pv.materialized
    h0 = profiler.host_sync_count()
    assert float(pv) == 8.0
    assert float(pv) == 8.0  # second read is free
    assert profiler.host_sync_count() - h0 == 1
    assert len(fired) == 1 and float(fired[0]) == 8.0
    late = []
    pv.on_ready(late.append)  # after materialization: fires immediately
    assert len(late) == 1


# ---------------------------------------------------------------------------
# metrics accumulate on device
# ---------------------------------------------------------------------------
def test_metric_device_accumulation_no_per_batch_sync():
    rng = np.random.RandomState(0)
    preds = [rng.uniform(0, 1, (16, 10)).astype(np.float32)
             for _ in range(4)]
    labels = [rng.randint(0, 10, (16,)).astype(np.float32)
              for _ in range(4)]

    acc = metric.Accuracy()
    loss_m = metric.Loss()
    dp = [nd.array(p) for p in preds]
    dl = [nd.array(l) for l in labels]
    h0 = profiler.host_sync_count()
    for p, l in zip(dp, dl):
        acc.update([l], [p])
        loss_m.update(None, [p])
    assert profiler.host_sync_count() == h0  # zero reads during update
    name, val = acc.get()  # the ONE deferred read
    assert profiler.host_sync_count() > h0

    ref = metric.Accuracy()
    for p, l in zip(preds, labels):
        ref.update([l], [p])  # numpy host path
    assert val == ref.get()[1]
    want = sum(float(p.sum()) for p in preds) / \
        sum(p.size for p in preds)
    assert abs(loss_m.get()[1] - want) < 1e-5
    # reset clears the device accumulator too
    acc.reset()
    assert acc.get()[1] != acc.get()[1]  # nan


# ---------------------------------------------------------------------------
# checkpoint drains the window
# ---------------------------------------------------------------------------
def test_kill_mid_window_resume_bitexact(monkeypatch, tmp_path):
    """Save with 5 steps in flight (guard on, K=8): CheckpointManager
    drains before snapshotting, so a 'killed' run resumed into FRESH
    objects continues bit-identically with an uninterrupted sync run."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    data = _batches(8, nan_at=2, seed=5)

    # uninterrupted synchronous reference
    net_r, tr_r = _make("adam", {"learning_rate": 1e-2})
    step_r = tr_r.fuse_step(net_r, _loss_fn)
    with engine.bulk(1):
        for x, y in data:
            step_r(x, y)
        nd.waitall()

    # async run killed after 5 steps — none of them observed yet
    net_a, tr_a = _make("adam", {"learning_rate": 1e-2})
    step_a = tr_a.fuse_step(net_a, _loss_fn)
    mgr = resilience.CheckpointManager(tmp_path, net=net_a, trainer=tr_a)
    with engine.bulk(8):
        for x, y in data[:5]:
            step_a(x, y)
        assert engine.inflight_depth() > 0
        mgr.save(step=5)  # must drain: counts/weights/opt-state coherent
    assert tr_a._optimizer.num_update == 4  # 5 dispatched, 1 skipped

    # "kill" + resume into fresh objects, finish the schedule async
    net_b, tr_b = _make("adam", {"learning_rate": 1e-2}, seed=99)
    mgr_b = resilience.CheckpointManager(tmp_path, net=net_b, trainer=tr_b)
    state = mgr_b.resume()
    assert state is not None and state.step == 5
    step_b = tr_b.fuse_step(net_b, _loss_fn)
    with engine.bulk(4):
        for x, y in data[5:]:
            step_b(x, y)
        nd.waitall()

    for a, b in zip(_weights(net_r), _weights(net_b)):
        np.testing.assert_array_equal(a, b)
    assert tr_b._optimizer.num_update == tr_r._optimizer.num_update == 7


# ---------------------------------------------------------------------------
# profiler thread-safety (counters bumped from deferred-read callbacks)
# ---------------------------------------------------------------------------
def test_profiler_counters_thread_safe():
    n_threads, per_thread = 8, 2000
    l0 = profiler.launch_count()
    h0 = profiler.host_sync_count()
    ctr = profiler.Counter(None, "ts_regression", 0)

    def hammer():
        for _ in range(per_thread):
            profiler.record_launch()
            profiler.record_host_sync()
            ctr.increment()

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert profiler.launch_count() - l0 == total
    assert profiler.host_sync_count() - h0 == total
    assert profiler.counter_value("ts_regression") == total


# ---------------------------------------------------------------------------
# CI: no new hot-path sync points
# ---------------------------------------------------------------------------
def test_static_host_sync_pass():
    """tools/check_host_syncs.py is clean — a new unmarked asnumpy()/
    float()/np.asarray() in the fused-step hot path fails tier-1."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(root, "tools", "check_host_syncs.py")
    r = subprocess.run([sys.executable, tool, root],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
