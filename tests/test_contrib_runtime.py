"""Contrib ops (ref: src/operator/contrib/*) + mx.runtime feature flags
+ mx.util parity shims."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_fft_ifft_roundtrip():
    x = np.random.RandomState(0).randn(3, 8).astype("f4")
    f = nd.fft(nd.array(x))
    assert f.shape == (3, 16)
    # interleaved (re, im) matches numpy fft
    ref = np.fft.fft(x, axis=-1)
    got = f.asnumpy().reshape(3, 8, 2)
    np.testing.assert_allclose(got[..., 0], ref.real, atol=1e-4)
    np.testing.assert_allclose(got[..., 1], ref.imag, atol=1e-4)
    # reference ifft is unnormalized: ifft(fft(x)) == n * x
    back = nd.ifft(f).asnumpy()
    np.testing.assert_allclose(back, 8 * x, rtol=1e-4, atol=1e-4)


def test_index_copy_add():
    old = nd.zeros((4, 3))
    new = nd.array(np.ones((2, 3), "f4"))
    idx = nd.array(np.array([1.0, 3.0], "f4"))
    out = nd.index_copy(old, idx, new).asnumpy()
    assert out[1].sum() == 3 and out[3].sum() == 3 and out[0].sum() == 0
    out2 = nd.index_add(nd.array(out), idx, new).asnumpy()
    assert out2[1].sum() == 6


def test_count_sketch():
    x = np.array([[1.0, 2.0, 3.0]], dtype="f4")
    h = nd.array(np.array([0.0, 1.0, 0.0], "f4"))
    s = nd.array(np.array([1.0, -1.0, 1.0], "f4"))
    out = nd.count_sketch(nd.array(x), h, s, out_dim=2).asnumpy()
    np.testing.assert_allclose(out, [[4.0, -2.0]])


def test_boolean_mask():
    x = nd.array(np.arange(12, dtype="f4").reshape(4, 3))
    m = nd.array(np.array([1.0, 0.0, 1.0, 0.0], "f4"))
    out = nd.boolean_mask(x, m).asnumpy()
    np.testing.assert_array_equal(out, x.asnumpy()[[0, 2]])


def test_multibox_prior():
    data = nd.zeros((1, 16, 4, 4))
    anchors = nd.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1.0, 2.0))
    # A = len(sizes) + len(ratios) - 1 = 3 anchors per pixel
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor at first pixel: size .5, ratio 1 centered at (1/8, 1/8)
    np.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                      0.125 + 0.25, 0.125 + 0.25],
                               atol=1e-6)
    # reference enumeration order: sizes-first with ratios[0], then
    # remaining ratios with sizes[0] — anchor 1 is size .25/ratio 1,
    # anchor 2 is size .5/ratio 2
    np.testing.assert_allclose(a[1, 2] - a[1, 0], 0.25, atol=1e-6)
    np.testing.assert_allclose(a[2, 2] - a[2, 0], 0.5 * np.sqrt(2),
                               atol=1e-6)
    np.testing.assert_allclose(a[2, 3] - a[2, 1], 0.5 / np.sqrt(2),
                               atol=1e-6)
    # widths/heights positive, centers inside the unit square
    assert np.all(a[:, 2] > a[:, 0]) and np.all(a[:, 3] > a[:, 1])


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    assert not feats.is_enabled("CUDA")
    assert "NATIVE_RECORDIO" in feats
    # flash-attention probe must agree with the op's own dispatch
    from mxnet_tpu.ops import attention

    assert feats.is_enabled("FLASH_ATTENTION") == attention._use_pallas()
    lst = mx.runtime.feature_list()
    assert any(f.name == "TPU" for f in lst)


def test_util_shims():
    assert mx.util.is_np_shape() and mx.util.is_np_array()
    with mx.util.np_shape():
        pass

    @mx.util.use_np
    def f(x):
        return x + 1

    assert f(1) == 2
    import pytest

    with pytest.raises(RuntimeError):
        mx.util.get_cuda_compute_capability()


def test_multibox_target_matching_and_encoding():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                  [0.6, 0.6, 0.9, 0.9]]], "f4"))
    label = nd.array(np.array([[[2, 0.1, 0.1, 0.5, 0.5],
                                [-1, 0, 0, 0, 0]]], "f4"))
    lt, lm, ct = nd.MultiBoxTarget(anchors, label, nd.zeros((1, 4, 2)))
    ct = ct.asnumpy()
    assert ct[0, 0] == 3  # class 2 -> target 2+1
    assert ct[0, 1] == 0  # unmatched -> background
    # exact-overlap anchor encodes ~zero offsets, mask covers only it
    np.testing.assert_allclose(lt.asnumpy()[0, :4], 0, atol=1e-5)
    np.testing.assert_allclose(lm.asnumpy()[0], [1, 1, 1, 1, 0, 0, 0, 0])


def test_multibox_target_force_matches_best_anchor():
    # gt overlaps anchor0 only weakly (< threshold) but must still get
    # its best anchor force-matched — INCLUDING when a cls=-1 padding
    # row is present (its meaningless argmax must not clobber the match)
    anchors = nd.array(np.array([[[0.0, 0.0, 0.4, 0.4],
                                  [0.6, 0.6, 1.0, 1.0]]], "f4"))
    for rows in ([[1, 0.3, 0.3, 0.7, 0.7]],
                 [[1, 0.3, 0.3, 0.7, 0.7], [-1, 0, 0, 0, 0]]):
        label = nd.array(np.array([rows], "f4"))
        _, _, ct = nd.MultiBoxTarget(anchors, label,
                                     nd.zeros((1, 3, 2)),
                                     overlap_threshold=0.9)
        assert (ct.asnumpy()[0] > 0).sum() == 1, rows


def test_multibox_detection_decode_and_nms():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                  [0.1, 0.1, 0.5, 0.5],
                                  [0.6, 0.6, 0.9, 0.9]]], "f4"))
    # two identical anchors with same class: NMS keeps the higher score
    probs = nd.array(np.array([[[0.1, 0.2, 0.8],
                                [0.9, 0.7, 0.1],
                                [0.0, 0.1, 0.1]]], "f4"))
    det = nd.MultiBoxDetection(probs, nd.zeros((1, 12)), anchors,
                               nms_threshold=0.5).asnumpy()
    assert det.shape == (1, 3, 6)
    # rows are score-sorted: winner, the distant low-score box, then the
    # NMS-suppressed duplicate (-1) last
    r0, r1, r2 = det[0]
    assert r0[0] == 0 and abs(r0[1] - 0.9) < 1e-6
    assert r1[1] <= 0.2 and r1[0] >= 0
    assert r2[0] == -1
    # decoded boxes equal anchors for zero offsets
    np.testing.assert_allclose(r0[2:], [0.1, 0.1, 0.5, 0.5], atol=1e-5)


def test_multibox_detection_offset_decode():
    anchors = nd.array(np.array([[[0.2, 0.2, 0.6, 0.6]]], "f4"))
    probs = nd.array(np.array([[[0.1], [0.9]]], "f4"))
    # shift center by +0.1 in x: t_x = 0.1 / (0.1 variance * w 0.4) = 2.5
    loc = nd.array(np.array([[2.5, 0, 0, 0]], "f4"))
    det = nd.MultiBoxDetection(probs, loc, anchors).asnumpy()
    np.testing.assert_allclose(det[0, 0, 2:], [0.3, 0.2, 0.7, 0.6],
                               atol=1e-5)


def test_multibox_detection_nms_topk_caps_output():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.3, 0.3],
                                  [0.6, 0.6, 0.9, 0.9]]], "f4"))
    probs = nd.array(np.array([[[0.1, 0.2], [0.9, 0.8]]], "f4"))
    det = nd.MultiBoxDetection(probs, nd.zeros((1, 8)), anchors,
                               nms_topk=1).asnumpy()
    assert abs(det[0, 0, 1] - 0.9) < 1e-6
    assert det[0, 1, 0] == -1  # beyond top-k invalidated


def test_proposal_shapes_and_validity():
    rng = np.random.RandomState(0)
    B, A, H, W = 2, 6, 4, 4
    cls = nd.array(rng.uniform(0, 1, (B, 2 * A, H, W)).astype("f4"))
    bbox = nd.array((rng.randn(B, 4 * A, H, W) * 0.1).astype("f4"))
    im_info = nd.array(np.array([[64, 64, 1.0], [64, 64, 1.0]], "f4"))
    rois, scores = nd.Proposal(
        cls, bbox, im_info, scales=(2, 4), ratios=(0.5, 1, 2),
        feature_stride=16, rpn_pre_nms_top_n=50, rpn_post_nms_top_n=8,
        rpn_min_size=4, output_score=True)
    r = rois.asnumpy()
    assert r.shape == (16, 5)
    np.testing.assert_array_equal(r[:8, 0], 0)
    np.testing.assert_array_equal(r[8:, 0], 1)
    assert (r[:, 1:3] >= 0).all() and (r[:, 3:] <= 63).all()
    assert (r[:, 3] >= r[:, 1]).all() and (r[:, 4] >= r[:, 2]).all()
    s0 = scores.asnumpy()[:8, 0]
    assert np.isfinite(s0).all()
    assert abs(s0.max() - s0[0]) < 1e-6  # best survivor leads


def test_proposal_nms_suppresses_duplicates():
    # one dominant location: high fg score everywhere forces NMS to thin
    B, A, H, W = 1, 1, 2, 2
    cls = np.zeros((B, 2, H, W), "f4")
    cls[0, 1] = 0.9  # all fg
    bbox = np.zeros((B, 4, H, W), "f4")
    im_info = nd.array(np.array([[32, 32, 1.0]], "f4"))
    rois = nd.Proposal(nd.array(cls), nd.array(bbox), im_info,
                       scales=(2,), ratios=(1.0,), feature_stride=16,
                       rpn_pre_nms_top_n=4, rpn_post_nms_top_n=4,
                       threshold=0.3, rpn_min_size=1).asnumpy()
    # 4 anchors at stride-16 cells of a 32px image, heavily overlapping
    # after clipping -> NMS keeps fewer distinct boxes; padding repeats
    # the top row, so all rows must be among the survivors
    uniq = np.unique(rois[:, 1:], axis=0)
    assert len(uniq) <= 3


def test_proposal_symbolic_two_outputs():
    import mxnet_tpu as mxx

    cls = mxx.sym.Variable("cls")
    bbox = mxx.sym.Variable("bbox")
    info = mxx.sym.Variable("info")
    p = mxx.sym.Proposal(cls, bbox, info, scales=(2,), ratios=(1.0,),
                         output_score=True)
    assert len(p.list_outputs()) == 2


def test_box_iou_and_nms():
    a = nd.array(np.array([[0, 0, 2, 2], [1, 1, 3, 3]], "f4"))
    b = nd.array(np.array([[0, 0, 2, 2]], "f4"))
    iou = nd.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[:, 0], [1.0, 1.0 / 7.0], atol=1e-5)
    # center format agrees with corner format
    ac = nd.array(np.array([[1, 1, 2, 2], [2, 2, 2, 2]], "f4"))
    bc = nd.array(np.array([[1, 1, 2, 2]], "f4"))
    iou_c = nd.box_iou(ac, bc, format="center").asnumpy()
    np.testing.assert_allclose(iou_c[:, 0], iou[:, 0], atol=1e-5)

    rows = np.array([[[0, 0.9, 0, 0, 2, 2],
                      [0, 0.8, 0.1, 0.1, 2, 2],
                      [1, 0.7, 5, 5, 6, 6],
                      [0, -1.0, 0, 0, 1, 1]]], "f4")
    out = nd.box_nms(nd.array(rows), overlap_thresh=0.5,
                     valid_thresh=0.0, id_index=0).asnumpy()
    # score-sorted survivors; the overlapping same-class duplicate and
    # the below-valid_thresh row are fully -1
    assert abs(out[0, 0, 1] - 0.9) < 1e-6
    assert abs(out[0, 1, 1] - 0.7) < 1e-6
    assert (out[0, 2] == -1).all() and (out[0, 3] == -1).all()
    # id_index + force_suppress=False: different class ids never
    # suppress each other even with full overlap
    rows2 = np.array([[[0, 0.9, 0, 0, 2, 2],
                       [1, 0.8, 0, 0, 2, 2]]], "f4")
    out2 = nd.box_nms(nd.array(rows2), id_index=0).asnumpy()
    assert (out2[0, :, 1] > 0).all()
    out3 = nd.box_nms(nd.array(rows2), id_index=0,
                      force_suppress=True).asnumpy()
    assert (out3[0, 1] == -1).all()


def test_proposal_reference_anchor_enumeration():
    """First anchor must equal py-faster-rcnn generate_anchors()[0] for
    base 16, ratio 0.5, scale 8: (-84, -40, 99, 55) at cell (0, 0)."""
    B, H, W = 1, 1, 1
    A = 1
    cls = np.zeros((B, 2 * A, H, W), "f4")
    cls[0, 1] = 1.0
    bbox = np.zeros((B, 4 * A, H, W), "f4")
    info = nd.array(np.array([[1000, 1000, 1.0]], "f4"))
    rois = nd.Proposal(nd.array(cls), nd.array(bbox), info,
                       scales=(8,), ratios=(0.5,), feature_stride=16,
                       rpn_pre_nms_top_n=1, rpn_post_nms_top_n=1,
                       rpn_min_size=0).asnumpy()
    # clipped to the (large) image, so the raw anchor passes through
    np.testing.assert_allclose(rois[0, 1:], [0, 0, 99, 55], atol=1e-4)
    # unclipped extents visible with an offset cell: anchor at cell (1,1)
    cls2 = np.zeros((1, 2, 2, 2), "f4"); cls2[0, 1, 1, 1] = 1.0
    bbox2 = np.zeros((1, 4, 2, 2), "f4")
    rois2 = nd.Proposal(nd.array(cls2), nd.array(bbox2), info,
                        scales=(8,), ratios=(0.5,), feature_stride=16,
                        rpn_pre_nms_top_n=1, rpn_post_nms_top_n=1,
                        rpn_min_size=0).asnumpy()
    # negative extents clip to the image (reference clips proposals too)
    np.testing.assert_allclose(rois2[0, 1:],
                               [0, 0, 99 + 16, 55 + 16], atol=1e-4)


def test_roi_align_bilinear_average():
    x = nd.array(np.full((1, 2, 8, 8), 3.0, "f4"))
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], "f4"))
    out = nd.ROIAlign(x, rois, pooled_size=(2, 2))
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out.asnumpy(), 3.0, atol=1e-5)
    # ramp: left bin average < right bin average, exact for 2-sample bins
    ramp = np.tile(np.arange(8, dtype="f4")[None, None, None, :],
                   (1, 1, 8, 1))
    o = nd.ROIAlign(nd.array(ramp), rois, pooled_size=(1, 2)).asnumpy()
    np.testing.assert_allclose(o[0, 0, 0], [1.75, 5.25], atol=1e-5)


def test_box_nms_topk_beyond_survives_unless_suppressed():
    # 3 disjoint boxes, topk=2: reference keeps all 3 (beyond-topk boxes
    # cannot suppress but do survive)
    rows = np.array([[[0, 0.9, 0, 0, 1, 1],
                      [0, 0.8, 2, 2, 3, 3],
                      [0, 0.7, 5, 5, 6, 6]]], "f4")
    out = nd.box_nms(nd.array(rows), topk=2, id_index=0).asnumpy()
    assert (out[0, :, 1] > 0).all()


def test_roi_align_out_of_image_samples_are_zero():
    x = nd.array(np.full((1, 1, 8, 8), 3.0, "f4"))
    rois = nd.array(np.array([[0, -20, -20, 7, 7]], "f4"))
    out = nd.ROIAlign(x, rois, pooled_size=(2, 2)).asnumpy()
    # top-left bin samples entirely outside the map -> 0; bottom-right
    # bin has 1 of its 4 samples inside (at 3.0) -> 0.75 exactly
    assert out[0, 0, 0, 0] < 1e-5
    np.testing.assert_allclose(out[0, 0, 1, 1], 0.75, atol=1e-5)


def test_deformable_convolution_zero_offsets_match_conv():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 9, 9).astype("f4")
    w = rng.randn(6, 2, 3, 3).astype("f4")
    off = np.zeros((2, 18, 5, 5), "f4")
    od = nd.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), None, kernel=(3, 3),
        stride=(2, 2), pad=(1, 1), num_group=2, no_bias=True).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                         stride=(2, 2), pad=(1, 1), num_group=2,
                         no_bias=True).asnumpy()
    np.testing.assert_allclose(od, ref, atol=1e-4)


def test_deformable_convolution_integer_shift():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 1, 6, 6).astype("f4")
    w = np.zeros((1, 1, 3, 3), "f4")
    w[0, 0, 0, 0] = 1.0  # kernel picks only tap (0, 0)
    off = np.ones((1, 18, 4, 4), "f4")  # every tap shifts (+1, +1)
    o = nd.DeformableConvolution(nd.array(x), nd.array(off), nd.array(w),
                                 None, kernel=(3, 3),
                                 no_bias=True).asnumpy()
    np.testing.assert_allclose(o[0, 0], x[0, 0][1:5, 1:5], atol=1e-5)


def test_deformable_convolution_fractional_offset_interpolates():
    # half-pixel x-shift averages horizontal neighbors
    x = np.zeros((1, 1, 4, 4), "f4")
    x[0, 0, 1, 1] = 2.0
    x[0, 0, 1, 2] = 4.0
    w = np.ones((1, 1, 1, 1), "f4")
    off = np.zeros((1, 2, 4, 4), "f4")
    off[0, 1] = 0.5  # dx = +0.5
    o = nd.DeformableConvolution(nd.array(x), nd.array(off), nd.array(w),
                                 None, kernel=(1, 1),
                                 no_bias=True).asnumpy()
    np.testing.assert_allclose(o[0, 0, 1, 1], 3.0, atol=1e-5)


def test_multibox_target_hard_negative_mining():
    # 1 gt matching anchor0; 4 pure negatives with distinct "hardness"
    # (hottest non-background score). ratio=2 -> 2 mined negatives stay
    # background (the 2 hottest), the rest become ignore_label.
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                  [0.52, 0.52, 0.6, 0.6],
                                  [0.62, 0.62, 0.7, 0.7],
                                  [0.72, 0.72, 0.8, 0.8],
                                  [0.82, 0.82, 0.9, 0.9]]], "f4"))
    label = nd.array(np.array([[[0, 0.1, 0.1, 0.5, 0.5]]], "f4"))
    # scores: (B, C=2, A=5); non-background row ranks neg hardness
    hard = np.array([[[0, 0, 0, 0, 0],
                      [0.0, 0.9, 0.1, 0.8, 0.2]]], "f4")
    _, _, ct = nd.MultiBoxTarget(anchors, label, nd.array(hard),
                                 negative_mining_ratio=2.0,
                                 negative_mining_thresh=0.5,
                                 ignore_label=-1.0)
    ct = ct.asnumpy()[0]
    assert ct[0] == 1  # matched -> class 0 + 1
    assert ct[1] == 0 and ct[3] == 0  # two hottest negatives kept
    assert ct[2] == -1 and ct[4] == -1  # mined out
    # without mining every negative trains as background
    _, _, ct0 = nd.MultiBoxTarget(anchors, label, nd.array(hard))
    assert (ct0.asnumpy()[0][1:] == 0).all()


def test_multibox_target_minimum_negative_samples():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                  [0.52, 0.52, 0.6, 0.6],
                                  [0.62, 0.62, 0.7, 0.7]]], "f4"))
    # no gt at all -> num_pos 0 -> ratio alone keeps 0 negatives, so
    # minimum_negative_samples must floor it
    label = nd.array(np.array([[[-1, 0, 0, 0, 0]]], "f4"))
    hard = np.array([[[0, 0, 0], [0.3, 0.9, 0.1]]], "f4")
    _, _, ct = nd.MultiBoxTarget(anchors, label, nd.array(hard),
                                 negative_mining_ratio=3.0,
                                 minimum_negative_samples=1)
    ct = ct.asnumpy()[0]
    assert (ct == 0).sum() == 1 and ct[1] == 0  # the hottest one
    assert (ct == -1).sum() == 2


def test_roi_align_adaptive_sample_count():
    # big square ROI: bin size 3 -> adaptive picks ceil(6/2)=3 samples
    # per axis, identical to forcing sample_ratio=3
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (1, 3, 12, 12)).astype("f4"))
    rois = nd.array(np.array([[0, 2, 2, 8, 8]], "f4"))
    auto = nd.ROIAlign(x, rois, pooled_size=(2, 2)).asnumpy()
    forced = nd.ROIAlign(x, rois, pooled_size=(2, 2),
                         sample_ratio=3).asnumpy()
    np.testing.assert_allclose(auto, forced, atol=1e-6)
    # tiny ROI (smaller than the pooled grid): adaptive -> 1 sample/axis
    tiny = nd.array(np.array([[0, 3, 3, 4, 4]], "f4"))
    auto_t = nd.ROIAlign(x, tiny, pooled_size=(2, 2)).asnumpy()
    forced_t = nd.ROIAlign(x, tiny, pooled_size=(2, 2),
                           sample_ratio=1).asnumpy()
    np.testing.assert_allclose(auto_t, forced_t, atol=1e-6)
