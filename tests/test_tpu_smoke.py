"""TPU hardware smoke lane (run: ``MXT_TEST_TPU=1 python -m pytest -m tpu``).

Every test here executes on the real chip — no interpret mode, no CPU
forcing. This lane exists because round 2 shipped a Pallas kernel that was
correct under ``interpret=True`` but failed Mosaic lowering on hardware
(invalid BlockSpec); hardware-only failure modes must have hardware tests.

Models the reference's GPU test tier (SURVEY §4: tests/python/gpu re-runs
the op suite under a GPU context) at smoke-test size: flash attention
fwd/bwd vs the XLA reference, one hybridized ResNet step, one BERT step,
fused RNN, fused optimizer updates, and async sync-point semantics.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu


def _require_tpu():
    if jax.default_backend() not in ("tpu", "axon"):
        pytest.skip("no TPU backend available (got %s)"
                    % jax.default_backend())


@pytest.fixture(autouse=True)
def _tpu_only():
    _require_tpu()


def _maxerr(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                 - b.astype(jnp.float32))))


# ---------------------------------------------------------------------------
# flash attention on hardware
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("causal", [False, True])
def test_flash_fwd_hardware(causal):
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(s, (2, 4, 384, 64), jnp.bfloat16)
               for s in jax.random.split(key, 3))
    out, _ = A._flash_forward_pallas(q, k, v, None, causal, 0.125,
                                     128, 128, interpret=False)
    ref = A._attention_reference(q, k, v, None, causal, 0.125)
    assert _maxerr(out, ref) < 2e-2  # bf16 inputs, f32 accumulation


def test_flash_fwd_bias_hardware():
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(s, (2, 4, 384, 64), jnp.bfloat16)
               for s in jax.random.split(key, 3))
    bias = A.make_padding_bias(jnp.asarray([300, 150]), max_len=384)
    out, _ = A._flash_forward_pallas(q, k, v, bias, True, 0.125,
                                     128, 128, interpret=False)
    ref = A._attention_reference(q, k, v, bias, True, 0.125)
    assert _maxerr(out, ref) < 2e-2


def test_flash_fwd_ragged_seqlen_hardware():
    """T=300 is not a block multiple — exercises the padding path."""
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(2)
    q, k, v = (jax.random.normal(s, (2, 2, 300, 64), jnp.bfloat16)
               for s in jax.random.split(key, 3))
    out, _ = A._flash_forward_pallas(q, k, v, None, True, 0.125,
                                     128, 128, interpret=False)
    ref = A._attention_reference(q, k, v, None, True, 0.125)
    assert _maxerr(out, ref) < 2e-2


def test_ragged_paged_attention_hardware():
    """The serving decode kernel on real Mosaic: mixed ragged lengths,
    shuffled page table, both head-block widths vs the gather+dense
    reference (the round-2 lesson: interpret-green is not
    Mosaic-green, so the paged kernel gets its own hardware gate)."""
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(5)
    B, H, D, S, P = 4, 4, 128, 16, 40
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
    k_pages = jax.random.normal(ks[1], (P, S, H, D), jnp.float32)
    v_pages = jax.random.normal(ks[2], (P, S, H, D), jnp.float32)
    pt = jnp.asarray(np.random.RandomState(0).permutation(P)[
        :B * 8].reshape(B, 8), jnp.int32)
    cl = jnp.array([1, 17, 100, 128], jnp.int32)
    ref = A._paged_gather_reference(q, k_pages, v_pages, pt, cl, 0.125)
    for block_h in (1, 4):
        out = A._paged_decode_pallas(q, k_pages, v_pages, pt, cl,
                                     0.125, block_h, interpret=False)
        assert _maxerr(out, ref) < 1e-3, "block_h=%d" % block_h


def test_flash_lse_hardware():
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(s, (1, 2, 256, 64), jnp.float32)
               for s in jax.random.split(key, 3))
    _, lse = A._flash_forward_pallas(q, k, v, None, False, 0.125,
                                     128, 128, interpret=False)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 0.125
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    assert _maxerr(lse, ref_lse) < 2e-2


def test_flash_grads_hardware():
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(4)
    q, k, v = (jax.random.normal(s, (2, 4, 384, 64), jnp.bfloat16)
               for s in jax.random.split(key, 3))
    bias = A.make_padding_bias(jnp.asarray([384, 200]), max_len=384)

    def loss(q, k, v):
        o = A.flash_attention(q, k, v, bias=bias, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        o = A._attention_reference(q, k, v, bias, True,
                                   1.0 / np.sqrt(q.shape[-1]))
        return jnp.sum(o.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g, gr):
        assert _maxerr(a, b) < 1e-1  # bf16 grads


def test_flash_long_seq_chunked_hardware():
    """T long enough that K/V exceed the VMEM budget → lax.scan path."""
    from mxnet_tpu.ops import attention as A
    key = jax.random.PRNGKey(5)
    T = 20480  # 2*20480*64*2B = 5.2 MB > _VMEM_KV_BYTES (4 MB)
    q, k, v = (jax.random.normal(s, (1, 1, T, 64), jnp.bfloat16)
               for s in jax.random.split(key, 3))
    assert not A._kv_fits_vmem(k)
    out = A.flash_attention(q, k, v, causal=True)
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


# ---------------------------------------------------------------------------
# framework paths on hardware
# ---------------------------------------------------------------------------
def test_resnet18_train_step_hardware():
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import model_zoo

    mx.random.seed(0)
    net = model_zoo.get_model("resnet18_v1", classes=10)
    net.initialize()
    net.cast("bfloat16")
    x = nd.array(np.random.RandomState(0)
                 .uniform(-1, 1, (8, 3, 64, 64)).astype("f4"))
    x = x.astype("bfloat16")
    y = nd.array(np.random.RandomState(1).randint(0, 10, (8,)).astype("f4"))
    net(x)
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.02, "momentum": 0.9})
    losses = [float(step(x, y).asnumpy()) for _ in range(6)]
    assert all(np.isfinite(losses))
    # optimizing, not just running (early bf16 steps can overshoot, so
    # check the best later loss rather than strict monotonicity)
    assert min(losses[1:]) < losses[0]


def test_bert_mini_train_step_hardware():
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon import model_zoo

    mx.random.seed(0)
    bert = model_zoo.bert.bert_3_64_2(use_classifier=False, dropout=0.0)
    bert.initialize()
    trainer = mx.gluon.Trainer(bert.collect_params(), "adam",
                               {"learning_rate": 1e-4})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 1000, (4, 48)).astype("f4"))
    y = nd.array(rng.randint(0, 1000, (4, 48)).astype("f4"))
    with ag.record():
        seq, _ = bert(x, nd.zeros_like(x))
        out = bert.decode_mlm(seq)
        loss = loss_fn(out.reshape((-1, out.shape[-1])), y.reshape((-1,)))
        loss = loss.mean()
    loss.backward()
    trainer.step(1)
    assert np.isfinite(float(loss.asnumpy()))


def test_fused_rnn_hardware():
    from mxnet_tpu import nd
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon import rnn

    layer = rnn.LSTM(hidden_size=32, num_layers=2)
    layer.initialize()
    x = nd.array(np.random.RandomState(0)
                 .normal(size=(20, 4, 16)).astype("f4"))
    x.attach_grad()
    with ag.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    assert np.all(np.isfinite(out.asnumpy()))
    assert np.all(np.isfinite(x.grad.asnumpy()))


def test_fused_optimizer_update_hardware():
    """Fused adam_update on device matches the CPU-side numpy recipe."""
    from mxnet_tpu import nd
    w = nd.array(np.linspace(-1, 1, 64).astype("f4"))
    g = nd.array(np.linspace(1, -1, 64).astype("f4"))
    m = nd.zeros((64,))
    v = nd.zeros((64,))
    out = nd.adam_update(w, g, m, v, lr=0.1, beta1=0.9, beta2=0.999,
                         epsilon=1e-8)
    wn, gn = np.linspace(-1, 1, 64, dtype="f4"), np.linspace(
        1, -1, 64, dtype="f4")
    mn = 0.1 * gn
    vn = 0.001 * gn * gn
    exp = wn - 0.1 * mn / (np.sqrt(vn) + 1e-8)
    np.testing.assert_allclose(out.asnumpy(), exp, rtol=1e-5, atol=1e-6)


def test_hybridize_jit_cache_hardware():
    """hybridize() compiles once and reuses the executable on hardware."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(64, activation="relu"),
            mx.gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).normal(size=(8, 32)).astype("f4"))
    out1 = net(x)
    out2 = net(x)
    np.testing.assert_allclose(out1.asnumpy(), out2.asnumpy(), rtol=1e-6)


def test_asnumpy_sync_point_hardware():
    """asnumpy() is the sync point and round-trips device data exactly."""
    from mxnet_tpu import nd
    a = nd.array(np.arange(1024, dtype="f4").reshape(32, 32))
    b = (a * 2 + 1).reshape((16, 64))
    expected = (np.arange(1024, dtype="f4") * 2 + 1).reshape(16, 64)
    np.testing.assert_array_equal(b.asnumpy(), expected)


def test_batchnorm_custom_vjp_hardware():
    """Fused BN kernel (custom VJP) matches numpy fwd + finite-diff bwd."""
    from mxnet_tpu import nd
    from mxnet_tpu import autograd as ag

    rng = np.random.RandomState(0)
    x = rng.randn(8, 16, 6, 6).astype("f4")
    g = (rng.rand(16) + 0.5).astype("f4")
    b = rng.randn(16).astype("f4")
    xa, ga, ba = nd.array(x), nd.array(g), nd.array(b)
    for a in (xa, ga, ba):
        a.attach_grad()
    with ag.record():
        out, _, _ = nd.BatchNorm(xa, ga, ba, nd.zeros((16,)),
                                 nd.ones((16,)), fix_gamma=False,
                                 train_mode=True)
        loss = (out * out).sum()
    loss.backward()
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    xh = (x - mean[None, :, None, None]) / \
        np.sqrt(var + 1e-5)[None, :, None, None]
    ref = xh * g[None, :, None, None] + b[None, :, None, None]
    assert np.abs(out.asnumpy() - ref).max() < 1e-2
    # dL/dbeta = sum(2*out) per channel — closed form for this loss
    db_ref = (2 * ref).sum(axis=(0, 2, 3))
    np.testing.assert_allclose(ba.grad.asnumpy(), db_ref, rtol=1e-2,
                               atol=1e-2)


def test_layernorm_custom_vjp_hardware():
    """Fused LN kernel matches numpy forward on the chip."""
    from mxnet_tpu import nd

    rng = np.random.RandomState(1)
    x = (rng.randn(4, 12, 64) * 3 + 5).astype("f4")
    g = (rng.rand(64) + 0.5).astype("f4")
    b = rng.randn(64).astype("f4")
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) * g + b
    assert np.abs(out.asnumpy() - ref).max() < 1e-2


def test_nhwc_resnet_train_step_hardware():
    """Channels-last resnet trains on the chip via the layout scope."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import model_zoo, nn

    mx.random.seed(0)
    with nn.layout_scope("NHWC"):
        net = model_zoo.get_model("resnet18_v1", classes=10)
    net.initialize()
    net.cast("bfloat16")
    x = nd.array(np.random.RandomState(0)
                 .uniform(-1, 1, (8, 64, 64, 3)).astype("f4"))
    x = x.astype("bfloat16")
    y = nd.array(np.random.RandomState(1).randint(0, 10, (8,)).astype("f4"))
    net(x)
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.02, "momentum": 0.9})
    losses = [float(step(x, y).asnumpy()) for _ in range(6)]
    assert all(np.isfinite(losses))
    assert min(losses[1:]) < losses[0]


def test_native_recordio_feeds_device_hardware():
    """Native C++ record pipeline -> device batch round-trip."""
    import tempfile

    from mxnet_tpu import nd, native, recordio

    if not native.available():
        pytest.skip("no native toolchain")
    d = tempfile.mkdtemp()
    p = d + "/t.rec"
    w = recordio.MXRecordIO(p, "w")
    rows = [np.arange(i, i + 8, dtype=np.float32) for i in range(32)]
    for arr in rows:
        w.write(arr.tobytes())
    w.close()
    r = native.NativeRecordReader(p)
    offs, lens = r.scan()
    pf = native.NativePrefetcher(p, offs, lens, np.arange(32),
                                 num_threads=2, capacity=8)
    batch = np.stack([np.frombuffer(b, np.float32) for b in pf])
    dev = nd.array(batch)
    out = (dev * 2).asnumpy()
    np.testing.assert_allclose(out, batch * 2)


# ---------------------------------------------------------------------------
# train-tier convergence on hardware (SURVEY §4 tests/python/train analog)
# ---------------------------------------------------------------------------
def test_mnist_convergence_hardware():
    """LeNet trained to >=0.95 val accuracy ON THE CHIP in bounded steps.

    Real MNIST files aren't shippable in this environment (zero egress),
    so the task is synthetic-but-learnable 'digits': 10 fixed random
    prototypes + Gaussian noise. A broken optimizer step, loss, BN/pool
    lowering, or sync-point semantics fails this; random labels can't
    pass it. Accuracy is printed so the TPU-lane artifact records it."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd as ag, nd
    from mxnet_tpu.gluon import Trainer, nn

    rng = np.random.RandomState(0)
    # smooth prototypes (coarse 7x7 upsampled): conv/pool-friendly spatial
    # structure — pure per-pixel noise patterns defeat pooling layers
    protos = np.repeat(np.repeat(rng.rand(10, 1, 7, 7), 4, axis=2),
                       4, axis=3).astype("f4")

    def make(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 10, (n,))
        x = protos[y] + r.normal(0, 0.35, (n, 1, 28, 28))
        return x.astype("f4"), y.astype("f4")

    xtr, ytr = make(2048, 1)
    xva, yva = make(512, 2)

    mx.random.seed(0)
    net = nn.HybridSequential(prefix="conv_mnist_")
    with net.name_scope():
        net.add(nn.Conv2D(16, kernel_size=5, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(32, kernel_size=5, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(128, activation="relu"),
                nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-3})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    batch = 256
    acc = 0.0
    for epoch in range(12):  # bounded: 12 * 8 = 96 steps max
        order = np.random.RandomState(10 + epoch).permutation(len(xtr))
        for i in range(0, len(xtr), batch):
            idx = order[i:i + batch]
            x = nd.array(xtr[idx])
            y = nd.array(ytr[idx])
            with ag.record():
                loss = loss_fn(net(x), y)  # per-sample; step() normalizes
            loss.backward()
            trainer.step(len(idx))
        preds = net(nd.array(xva)).asnumpy().argmax(axis=1)
        acc = float((preds == yva).mean())
        print("epoch %d val_acc %.4f" % (epoch, acc), flush=True)
        if acc >= 0.97:
            break
    assert acc >= 0.95, "val accuracy %.4f below the train-tier bar" % acc


# ---------------------------------------------------------------------------
# round-4 additions: CTC scan kernel + wavefront LSTM parity on hardware
# ---------------------------------------------------------------------------
def test_ctc_loss_hardware():
    """The lax.scan alpha recursion compiles and matches the CPU-verified
    torch-parity values on chip (scan + take_along_axis + masked
    logaddexp is exactly the op mix Mosaic has rejected before)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    T, N, C = 12, 3, 6
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3, 2], [2, 2, 0, 0], [4, 1, 5, 3]],
                      dtype=np.float32)
    x = mx.nd.array(logits)
    x.attach_grad()
    with autograd.record():
        loss = mx.nd.CTCLoss(x, mx.nd.array(labels), blank_label="first")
    loss.backward()
    vals = loss.asnumpy()
    # CPU-verified torch ground truth for this exact seed/config
    np.testing.assert_allclose(
        vals, [10.896658, 19.76711, 11.33562], rtol=1e-3)
    g = x.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_wavefront_lstm_parity_hardware():
    """MXT_RNN_WAVEFRONT batches all layers' recurrent gemms per
    diagonal; outputs must match the sequential path on chip."""
    import os

    from mxnet_tpu.ops.rnn import rnn_op, rnn_param_size

    T, B, I, H, L = 16, 8, 32, 32, 3
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    data = jax.random.normal(k1, (T, B, I), jnp.float32)
    params = jax.random.normal(
        k2, (rnn_param_size("lstm", I, H, num_layers=L),),
        jnp.float32) * 0.1
    state = jnp.zeros((L, B, H), jnp.float32)
    cell = jnp.zeros((L, B, H), jnp.float32)

    old = os.environ.get("MXT_RNN_WAVEFRONT")
    try:
        os.environ["MXT_RNN_WAVEFRONT"] = "0"
        seq = rnn_op(data, params, state, cell, mode="lstm",
                     state_size=H, num_layers=L)
        os.environ["MXT_RNN_WAVEFRONT"] = "1"
        wave = rnn_op(data, params, state, cell, mode="lstm",
                      state_size=H, num_layers=L)
    finally:
        if old is None:
            os.environ.pop("MXT_RNN_WAVEFRONT", None)
        else:
            os.environ["MXT_RNN_WAVEFRONT"] = old
    assert _maxerr(jnp.asarray(seq[0]), jnp.asarray(wave[0])) < 1e-4
    assert _maxerr(jnp.asarray(seq[1]), jnp.asarray(wave[1])) < 1e-4
    assert _maxerr(jnp.asarray(seq[2]), jnp.asarray(wave[2])) < 1e-4


# ---------------------------------------------------------------------------
# sparse tier on hardware (VERDICT r4 #7: the row_sparse push/pull +
# sparse-optimizer path must be exercised on the chip lane, not only the
# CPU suite; ref: SURVEY §2.2 sparse row + §2.4 PullRowSparse)
# ---------------------------------------------------------------------------
def test_embedding_sparse_grad_train_step_hardware():
    """Embedding(sparse_grad) fwd/bwd + lazy sparse SGD on the chip:
    the gather fwd, row_sparse grad extraction, and touched-rows-only
    update all ride device buffers."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd as ag

    mx.random.seed(3)
    net = mx.gluon.nn.Embedding(512, 32, sparse_grad=True)
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.5})
    ids = np.random.RandomState(0).randint(0, 512, (8, 16)).astype("f4")
    x = nd.array(ids)
    w_before = net.weight.data().asnumpy().copy()
    with ag.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    g = net.weight.grad()
    assert g.stype == "row_sparse"
    touched = set(int(i) for i in g.indices.asnumpy())
    assert touched == set(int(i) for i in np.unique(ids))
    tr.step(1)
    w_after = net.weight.data().asnumpy()
    untouched = sorted(set(range(512)) - touched)
    np.testing.assert_array_equal(w_after[untouched], w_before[untouched])
    assert not np.allclose(w_after[sorted(touched)],
                           w_before[sorted(touched)])


def test_kvstore_row_sparse_pull_hardware():
    """row_sparse_pull + sparse push through a server-side optimizer,
    with every buffer on the chip."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sparse

    kv = mx.kv.create("local")
    w = np.arange(256 * 8, dtype="f4").reshape(256, 8)
    kv.init("emb", nd.array(w))
    out = sparse.zeros("row_sparse", (256, 8))
    rows = nd.array(np.array([3.0, 77.0, 200.0], "f4"))
    kv.row_sparse_pull("emb", out=out, row_ids=rows)
    np.testing.assert_array_equal(out.indices.asnumpy(), [3, 77, 200])
    np.testing.assert_array_equal(out.data.asnumpy(), w[[3, 77, 200]])

    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    gvals = np.full((2, 8), 0.5, "f4")
    kv.push("emb", sparse.row_sparse_array(
        (gvals, np.array([3, 200], "i8")), shape=(256, 8)))
    pulled = nd.zeros((256, 8))
    kv.pull("emb", out=pulled)
    pn = pulled.asnumpy()
    np.testing.assert_array_equal(pn[77], w[77])        # untouched
    np.testing.assert_allclose(pn[[3, 200]], w[[3, 200]] - 0.5, rtol=1e-6)


def test_sparse_adam_lazy_update_hardware():
    """Sparse Adam on chip: touched rows match the dense update,
    untouched rows (weight AND optimizer state) stay put — the
    reference's lazy-update contract."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sparse

    shape, rows = (128, 16), [5, 44, 91]
    w_s = nd.array(np.ones(shape, "f4"))
    w_d = nd.array(np.ones(shape, "f4"))
    gd = np.zeros(shape, "f4")
    gd[rows] = 0.25
    opt_s, opt_d = (mx.optimizer.Adam(learning_rate=0.1) for _ in range(2))
    st_s = opt_s.create_state(0, w_s)
    st_d = opt_d.create_state(0, w_d)
    opt_s.update(0, w_s, sparse.row_sparse_array(gd), st_s)
    opt_d.update(0, w_d, nd.array(gd), st_d)
    np.testing.assert_allclose(w_s.asnumpy()[rows], w_d.asnumpy()[rows],
                               rtol=1e-5, atol=1e-6)
    other = sorted(set(range(shape[0])) - set(rows))
    np.testing.assert_array_equal(w_s.asnumpy()[other],
                                  np.ones(shape, "f4")[other])


def test_bn_pallas_backward_hardware():
    """Compiled (Mosaic) fused BN backward vs the XLA custom-VJP path on
    the chip — interpret-mode parity is NOT sufficient (round-2 lesson)."""
    from mxnet_tpu.ops import bn_pallas
    if not bn_pallas.available():
        pytest.skip("pallas unavailable")
    key = jax.random.PRNGKey(0)
    m, c = 8 * 56 * 56, 64  # resnet stage-1 NHWC flattened
    kx, kd = jax.random.split(key)
    x = jax.random.normal(kx, (m, c), jnp.bfloat16)
    dy = jax.random.normal(kd, (m, c), jnp.bfloat16)
    g = jnp.ones((c,), jnp.float32) * 1.3
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=0)
    var = jnp.mean(jnp.square(x32 - mean), axis=0)
    inv = jax.lax.rsqrt(var + 1e-5)

    dx, dg, db = bn_pallas.bn_bwd_pallas(x, dy, mean, inv, g)

    # the oracle must take the XLA path — with MXT_BN_PALLAS=1 exported
    # (the A/B env) _bn_core_bwd would otherwise route the oracle through
    # the very kernel under test
    import os
    prev = os.environ.pop("MXT_BN_PALLAS", None)
    try:
        from mxnet_tpu.ops.nn import _bn_core
        (out, m_, v_), vjp = jax.vjp(
            lambda xx, gg, bb: _bn_core(1e-5, (0,), xx, gg, bb),
            x, g, jnp.zeros_like(g))
        odx, odg, odb = vjp((dy.astype(out.dtype), jnp.zeros_like(m_),
                             jnp.zeros_like(v_)))
    finally:
        if prev is not None:
            os.environ["MXT_BN_PALLAS"] = prev
    assert _maxerr(db, odb) < 1.0          # f32 sums over 25k rows
    assert _maxerr(dg, odg) < 1.0
    assert _maxerr(dx, odx) < 0.05         # bf16 elementwise


def test_quantized_conv_fc_hardware():
    """s8xs8->s32 conv + matmul on the MXU (ops/quantization.py): the
    int8 path must lower and match the f32 reference on chip."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(8, 16, 28, 28).astype(np.float32))
    W = rs.randn(32, 16, 3, 3).astype(np.float32)
    b = rs.randn(32).astype(np.float32)
    qx, xmn, xmx = nd.contrib.quantize_v2(x)
    qw, wmn, wmx = nd.contrib.quantize_v2(nd.array(W))
    acc, omn, omx = nd.contrib.quantized_conv(
        qx, qw, nd.array(b), xmn, xmx, wmn, wmx,
        kernel=(3, 3), num_filter=32, pad=(1, 1))
    assert acc.dtype == np.int32
    out = nd.contrib.dequantize(acc, omn, omx).asnumpy()
    ref = nd.Convolution(x, nd.array(W), nd.array(b), kernel=(3, 3),
                         num_filter=32, pad=(1, 1)).asnumpy()
    denom = np.abs(ref).max()
    assert np.abs(out - ref).max() / denom < 0.05, \
        np.abs(out - ref).max() / denom

    xf = nd.array(rs.randn(64, 256).astype(np.float32))
    Wf = rs.randn(128, 256).astype(np.float32)
    qxf, fmn, fmx = nd.contrib.quantize_v2(xf)
    qwf, gmn, gmx = nd.contrib.quantize_v2(nd.array(Wf))
    accf, fomn, fomx = nd.contrib.quantized_fully_connected(
        qxf, qwf, None, fmn, fmx, gmn, gmx, num_hidden=128, no_bias=True)
    outf = nd.contrib.dequantize(accf, fomn, fomx).asnumpy()
    reff = xf.asnumpy() @ Wf.T
    assert np.abs(outf - reff).max() / np.abs(reff).max() < 0.05
