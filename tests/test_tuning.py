"""Kernel autotuner + persistent compile cache (mxnet_tpu/tuning/).

Covers the PR-6 acceptance surface on CPU: shape-aware tiling-legal
configs for arbitrary (odd) shapes with interpret-mode parity against
the XLA reference, tune-table persistence (round-trip, corrupted/stale
fallback), warmup compile-counter behavior, and the zero-JIT-resume
two-process A/B over a shared persistent compilation cache.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import config, nd, tuning
from mxnet_tpu.ops import attention as A
from mxnet_tpu.ops import bn_pallas
from mxnet_tpu.ops.nn import _bn_core
from mxnet_tpu.test_utils import with_seed


@pytest.fixture(autouse=True)
def _fresh_table(monkeypatch, tmp_path):
    """Every test gets its own on-disk tune table (and therefore a
    clean in-memory instance — table() swaps on path change)."""
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    yield
    tuning.reset()


# ---------------------------------------------------------------------------
# shape-aware configs: legality + odd-shape parity (BENCH_r02 regression)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("tq,tk,d", [
    (257, 257, 32),   # the classic non-multiple sequence
    (100, 100, 64),
    (257, 129, 32),   # rectangular (cross-attention shaped)
    (7, 7, 16),       # smaller than one sublane tile
    (1024, 1024, 64),
])
def test_attention_candidates_tiling_legal(tq, tk, d):
    cands = tuning.attention_candidates(tq, tk, d, jnp.float32)
    assert cands, "no candidates for (%d, %d, %d)" % (tq, tk, d)
    for bq, bk in cands:
        assert bq % 8 == 0 and bq >= 8, (bq, bk)
        assert bk % 8 == 0 and bk >= 8, (bq, bk)
    ent = tuning.heuristic_attention((2, 2, tq, d), tk, "float32", False)
    assert (ent["block_q"], ent["block_k"]) in cands
    assert ent["backend"] in ("pallas", "xla")


@with_seed()
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(257, 257), (100, 100), (129, 257)])
def test_flash_odd_shapes_match_reference(causal, tq, tk):
    """The shape-aware config path must make the Pallas kernel (run in
    interpret mode on CPU) agree with the XLA reference at non-multiple
    shapes — the BENCH_r02 `partial_errors` class."""
    rng = np.random.RandomState(0)
    B, H, D = 1, 2, 32
    q = jnp.asarray(rng.normal(size=(B, H, tq, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, tk, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, tk, D)).astype("f4"))
    cfg = tuning.resolve_attention(q.shape, tk, "float32", causal)
    assert cfg["block_q"] % 8 == 0 and cfg["block_k"] % 8 == 0
    ref = A._attention_reference(q, k, v, None, causal, 0.125)
    out, _ = A._flash_forward_pallas(
        q, k, v, None, causal, 0.125, cfg["block_q"], cfg["block_k"],
        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@with_seed()
@pytest.mark.parametrize("m,c", [(257, 100), (100, 100), (72, 24)])
def test_bn_odd_shapes_match_reference(m, c):
    """BN backward at non-multiple (rows, channels) through the tuned
    block_rows path matches the XLA custom-VJP formulas."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.normal(size=(m, c)).astype("f4"))
    dy = jnp.asarray(rng.normal(size=(m, c)).astype("f4"))
    mean = jnp.mean(x, axis=0)
    var = jnp.mean(jnp.square(x - mean), axis=0)
    inv = jax.lax.rsqrt(var + 1e-5)
    g = jnp.asarray(rng.normal(size=(c,)).astype("f4")) + 1.5

    ent = tuning.resolve_bn(m, c, "float32")
    bm = ent["block_rows"]
    assert bm % 8 == 0 and bm >= 8
    dx, dg, db = bn_pallas.bn_bwd_pallas(x, dy, mean, inv, g,
                                         interpret=True, block_rows=bm)
    b0 = jnp.zeros_like(g)
    (out, mn, vr), vjp = jax.vjp(
        lambda xx, gg, bb: _bn_core(1e-5, (0,), xx, gg, bb), x, g, b0)
    odx, odg, odb = vjp((dy, jnp.zeros_like(mn), jnp.zeros_like(vr)))
    np.testing.assert_allclose(np.asarray(dx), np.asarray(odx),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dg), np.asarray(odg),
                               rtol=1e-6, atol=5e-6)
    np.testing.assert_allclose(np.asarray(db), np.asarray(odb),
                               rtol=1e-6, atol=5e-6)


def test_bn_bwd_rejects_illegal_block():
    x = jnp.ones((16, 8))
    with pytest.raises(ValueError):
        bn_pallas.bn_bwd_pallas(x, x, jnp.zeros(8), jnp.ones(8),
                                jnp.ones(8), interpret=True, block_rows=12)


# ---------------------------------------------------------------------------
# default_blocks: resettable, config-change aware (satellite 1)
# ---------------------------------------------------------------------------
def test_default_blocks_config_change_aware(monkeypatch):
    monkeypatch.delenv("MXT_FLASH_BLOCK_Q", raising=False)
    monkeypatch.delenv("MXT_FLASH_BLOCK_K", raising=False)
    assert A.default_blocks() == (128, 128)
    assert not A.blocks_pinned()
    # set_default takes effect WITHOUT a fresh process (the old memo
    # latched the first read forever)
    config.set_default("MXT_FLASH_BLOCK_Q", 64)
    try:
        assert A.default_blocks() == (64, 128)
        assert A.blocks_pinned()
        monkeypatch.setenv("MXT_FLASH_BLOCK_K", "32")
        assert A.default_blocks() == (64, 32)
        # a pinned config bypasses the tuning table entirely
        cfg = A._tuned_config(jnp.zeros((1, 1, 256, 32)),
                              jnp.zeros((1, 1, 256, 32)), None, None,
                              False, 0.125)
        assert cfg["source"] == "pinned"
        assert (cfg["block_q"], cfg["block_k"]) == (64, 32)
    finally:
        config._overrides.pop("MXT_FLASH_BLOCK_Q", None)
    monkeypatch.setenv("MXT_FLASH_BLOCK_Q", "20")  # not a multiple of 8
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        A.default_blocks()


# ---------------------------------------------------------------------------
# tune table: round-trip, corruption, staleness, measured precedence
# ---------------------------------------------------------------------------
def test_tune_table_roundtrip(tmp_path):
    path = str(tmp_path / "t.json")
    t = tuning.TuneTable(path)
    key = tuning.attn_key((2, 4, 257, 64), 257, "float32", True)
    ent = {"backend": "pallas", "block_q": 64, "block_k": 128,
           "source": "measured", "score": 1.25}
    t.record(key, ent)
    t.record_signature("flash_attention", {"q_shape": [2, 4, 257, 64]})
    assert t.save() == path

    t2 = tuning.TuneTable(path)  # fresh registry, same file
    assert t2.load_error is None
    got = t2.lookup(key)
    assert got == ent
    assert t2.signatures("flash_attention") == [{"q_shape": [2, 4, 257, 64]}]
    # same decisions through the resolve path: the stored entry wins
    # (no re-measure, no heuristic overwrite)
    assert t2.peek(key)["block_q"] == 64


def test_tune_table_corrupted_falls_back(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write("{not json at all")
    t = tuning.TuneTable(path)
    assert t.load_error is not None
    assert t.entries() == {}
    # resolution still works — heuristic path answers
    ent = tuning.heuristic_attention((1, 1, 64, 32), 64, "float32", False)
    assert ent["source"] == "heuristic"


def test_tune_table_stale_version_falls_back(tmp_path):
    path = str(tmp_path / "stale.json")
    with open(path, "w") as f:
        json.dump({"version": tuning.TABLE_VERSION + 1,
                   "entries": {"k": {"backend": "pallas"}},
                   "signatures": {}}, f)
    t = tuning.TuneTable(path)
    assert t.load_error is not None and "version" in t.load_error
    assert t.entries() == {}
    # and the save path writes the CURRENT version back out
    t.record("k2", {"backend": "xla", "source": "heuristic"})
    t.save()
    with open(path) as f:
        assert json.load(f)["version"] == tuning.TABLE_VERSION


def test_measured_entry_not_downgraded():
    t = tuning.TuneTable()
    t.record("k", {"backend": "pallas", "block_q": 32, "block_k": 32,
                   "source": "measured"})
    out = t.record("k", {"backend": "xla", "block_q": 8, "block_k": 8,
                         "source": "heuristic"})
    assert out["source"] == "measured" and out["block_q"] == 32
    assert t.peek("k")["source"] == "measured"


def test_resolve_records_and_hits_counters():
    from mxnet_tpu import telemetry

    def counts():
        reg = telemetry.registry()
        h = reg.get("mxt_tune_cache_hits_total")
        m = reg.get("mxt_tune_cache_misses_total")
        return (int(h.value) if h else 0, int(m.value) if m else 0)

    h0, m0 = counts()
    shape = (1, 2, 192, 32)
    ent1 = tuning.resolve_attention(shape, 192, "float32", False)
    h1, m1 = counts()
    assert m1 == m0 + 1  # first sight of the bucket: miss
    ent2 = tuning.resolve_attention(shape, 192, "float32", False)
    h2, m2 = counts()
    assert h2 == h1 + 1 and m2 == m1  # second: table hit
    assert ent1 == ent2  # same decision both times


def test_measure_mode_records_measured(monkeypatch):
    """MXT_TUNE_MODE=measure forces the timed path even on CPU (tiny
    shapes, interpret-mode pallas candidates + XLA reference)."""
    monkeypatch.setenv("MXT_TUNE_MODE", "measure")
    monkeypatch.setenv("MXT_TUNE_ITERS", "1")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.normal(size=(1, 1, 16, 8)).astype("f4"))
    ent = tuning.resolve_attention(
        q.shape, 16, "float32", False,
        arrays=(q, q, q, None, 0.3535))
    assert ent["source"] == "measured"
    assert ent["backend"] in ("pallas", "xla")
    # the measured entry is served (not re-measured) on the next call
    again = tuning.resolve_attention(q.shape, 16, "float32", False)
    assert again == ent


# ---------------------------------------------------------------------------
# signatures + warmup (compile-counter asserts, CPU-runnable)
# ---------------------------------------------------------------------------
@with_seed()
def test_flash_dispatch_records_signature():
    q = nd.array(np.random.RandomState(0).normal(
        size=(1, 2, 24, 8)).astype("f4"))
    nd.flash_attention(q, q, q)
    sigs = tuning.signatures("flash_attention")
    assert any(s["q_shape"] == [1, 2, 24, 8] for s in sigs)


@with_seed()
def test_warmup_compiles_recorded_signatures():
    """tuning.warmup() AOT-compiles every recorded kernel signature —
    the compile counter must move, and the summary must say what was
    warmed."""
    q = nd.array(np.random.RandomState(0).normal(
        size=(1, 1, 16, 8)).astype("f4"))
    nd.flash_attention(q, q, q)  # records the signature
    before = tuning.compile_stats()
    summary = tuning.warmup(include_live=False)
    after = tuning.compile_stats()
    assert "flash_attention" in summary["entries"]
    assert not summary["errors"], summary["errors"]
    assert summary["compiles"] >= 2  # fwd + grad programs at least
    assert after["compiles"] - before["compiles"] == summary["compiles"]


@with_seed()
def test_step_aot_warmup_compiles_and_steps(tmp_path, monkeypatch):
    """CachedTrainStep.aot_warmup compiles the fused program without
    touching weights; the subsequent real steps run fused and match a
    twin that never warmed up."""
    from mxnet_tpu.gluon import Trainer, nn as gnn

    def build(prefix):
        mx.random.seed(7)
        net = gnn.Sequential(prefix=prefix)
        with net.name_scope():
            # explicit in_units: no deferred init, so the pre-warmup
            # weight snapshot below can read the arrays directly
            net.add(gnn.Dense(16, activation="relu", in_units=6),
                    gnn.Dense(4, in_units=16))
        net.initialize()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.1, "momentum": 0.9})
        step = tr.fuse_step(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
        return net, step

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (8, 6)).astype("f4"))
    y = nd.array(rng.randint(0, 4, (8,)).astype("f4"))

    net_a, step_a = build("warm_")
    w_before = {n: p.data().asnumpy()
                for n, p in net_a.collect_params().items()}
    c0 = tuning.compile_stats()
    assert step_a.aot_warmup(x, y) == 1
    c1 = tuning.compile_stats()
    assert c1["compiles"] > c0["compiles"]
    for n, p in net_a.collect_params().items():  # weights untouched
        np.testing.assert_array_equal(w_before[n], p.data().asnumpy())

    net_b, step_b = build("warm_")  # same seed + prefix = same init
    la = [float(step_a(x, y).mean().asnumpy()) for _ in range(3)]
    lb = [float(step_b(x, y).mean().asnumpy()) for _ in range(3)]
    assert step_a.fused and step_b.fused
    np.testing.assert_allclose(la, lb, rtol=0, atol=0)


def test_fused_update_aot_warmup():
    """The Trainer's _FusedUpdate AOT-compiles from live param shapes."""
    from mxnet_tpu.gluon import Parameter, Trainer

    from mxnet_tpu.gluon.trainer import _FusedUpdate

    p = Parameter("w", shape=(4, 3))
    p.initialize()
    tr = Trainer([p], "adam", {"learning_rate": 1e-3}, kvstore=None)
    tr._init_kvstore()
    assert _FusedUpdate.eligible(tr)
    fused = _FusedUpdate(tr)  # what trainer.step builds on first call
    c0 = tuning.compile_stats()
    assert fused.aot_warmup() >= 1
    assert tuning.compile_stats()["compiles"] > c0["compiles"]


@with_seed()
def test_warmup_second_pass_hits_persistent_cache(tmp_path, monkeypatch):
    """With MXT_COMPILE_CACHE_DIR set, re-warming the same signatures
    serves the compiles from the persistent cache (hits, not misses)."""
    from jax._src import compilation_cache as _cc

    monkeypatch.setenv("MXT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    # unique shape for this test: other tests may have compiled the
    # common ones already, and JAX's in-memory cache layer would then
    # swallow the hit/miss events this test observes
    q = nd.array(np.random.RandomState(0).normal(
        size=(1, 3, 40, 8)).astype("f4"))
    nd.flash_attention(q, q, q)
    _cc.reset_cache()  # route compiles through the (fresh) disk cache
    s1 = tuning.warmup(include_live=False)
    assert s1["cache_misses"] >= 2  # cold: fwd + grad really compiled
    # drop the in-memory layer again so the second pass must go to
    # disk — the in-process stand-in for a fresh replica
    _cc.reset_cache()
    s2 = tuning.warmup(include_live=False)
    assert s2["cache_hits"] >= 2  # fwd + grad replayed from disk
    assert s2["cache_misses"] == 0


# ---------------------------------------------------------------------------
# the acceptance A/B: zero hot-path JIT in a warm-started second process
# ---------------------------------------------------------------------------
_CW_SCRIPT = r"""
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, tuning
from mxnet_tpu.gluon import Trainer, nn

mx.random.seed(0)
net = nn.Sequential(prefix="zj_")
with net.name_scope():
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
net.initialize()
tr = Trainer(net.collect_params(), "sgd",
             {"learning_rate": 0.1, "momentum": 0.9})
step = tr.fuse_step(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
rng = np.random.RandomState(0)
x = nd.array(rng.uniform(-1, 1, (8, 6)).astype(np.float32))
y = nd.array(rng.randint(0, 4, (8,)).astype(np.float32))
step.aot_warmup(x, y)
pre = tuning.compile_stats()
losses = []
for _ in range(3):
    losses.append(float(step(x, y).mean().asnumpy()))
nd.waitall()
post = tuning.compile_stats()
print("ROW " + json.dumps({
    "losses": losses, "fused": step.fused,
    "hot_cache_misses": post["cache_misses"] - pre["cache_misses"],
    "hot_compile_s": post["compile_seconds"] - pre["compile_seconds"],
    "total_misses": post["cache_misses"]}))
"""


def test_zero_jit_resume_second_process(tmp_path):
    """PR acceptance: with a warm persistent cache + tune table, a
    second process running the canonical fused-step loop performs zero
    hot-path JIT compiles (every backend compile in its hot loop is a
    persistent-cache hit), with identical numerics."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "MXT_COMPILE_CACHE_DIR": str(tmp_path / "xla"),
                "MXT_TUNE_TABLE": str(tmp_path / "tune.json")})
    env.pop("XLA_FLAGS", None)  # no 8-device CPU mesh in the children

    def run():
        r = subprocess.run(
            [sys.executable, "-c", _CW_SCRIPT],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for line in r.stdout.splitlines():
            if line.startswith("ROW "):
                return json.loads(line[4:])
        raise AssertionError("no ROW in output: %s"
                             % (r.stderr or r.stdout)[-800:])

    cold = run()
    warm = run()
    assert cold["fused"] and warm["fused"]
    # the acceptance bit: ZERO real JIT compiles on the warm hot path
    assert warm["hot_cache_misses"] == 0, warm
    # and the warm process's tune table came from disk: same numerics
    np.testing.assert_allclose(cold["losses"], warm["losses"],
                               rtol=0, atol=0)
    # the cold process really did pay compiles (sanity of the A/B)
    assert cold["total_misses"] > 0
