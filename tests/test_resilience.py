"""Resilience subsystem (mxnet_tpu/resilience.py): fused + eager
non-finite step guards, atomic checkpoint/auto-resume, and fault-injected
KVStore retry.

The fault-injection tests run deterministically off a seeded ``MXT_FAULT``
spec (marker: chaos); the long kill-and-resume soak is marked slow and
stays out of tier-1.
"""
import os
import signal

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd, profiler, resilience
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.resilience import (CheckpointManager, KVStoreError,
                                  SimulatedCrash)


@pytest.fixture(autouse=True)
def _fault_isolation(monkeypatch):
    """Every test starts with no armed faults and a fresh injector RNG."""
    monkeypatch.delenv("MXT_FAULT", raising=False)
    resilience.reset_faults()
    yield
    resilience.reset_faults()


def _make_net(seed=7, prefix="res_"):
    mx.random.seed(seed)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    return net


def _batch(t, nan=False):
    rng = np.random.RandomState(100 + t)
    x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
    y = rng.uniform(-1, 1, (8, 4)).astype(np.float32)
    if nan:
        x[0, 0] = np.nan
    return nd.array(x), nd.array(y)


def _weights(net):
    return {k: v.data().asnumpy().copy()
            for k, v in net.collect_params().items()}


def _states(trainer):
    out = {}
    for i, s in trainer._updaters[0].states.items():
        leaves = s if isinstance(s, tuple) else (() if s is None else (s,))
        out[i] = [l.asnumpy().copy() for l in leaves]
    return out


_loss_fn = mx.gluon.loss.L2Loss()


# ---------------------------------------------------------------------------
# pillar 1 — non-finite step guard
# ---------------------------------------------------------------------------
def test_fused_guard_one_launch_and_nan_skip(monkeypatch):
    """Guard enabled: still EXACTLY one launch per step, and a NaN batch
    leaves weights + optimizer state bit-identical while bumping the
    skipped-step counter and freezing the step count. The guard flag is
    now observed DEFERRED through the async engine window, so host
    counters are asserted behind an nd.waitall() barrier."""
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    step = tr.fuse_step(net, _loss_fn)
    data = [_batch(t) for t in range(4)]
    bad = _batch(99, nan=True)
    step(*data[0]).wait_to_read()  # build + compile
    step(*data[1]).wait_to_read()
    assert step.fused and step._guard

    c0 = profiler.launch_count()
    step(*data[2]).wait_to_read()
    assert profiler.launch_count() - c0 == 1  # guard costs zero launches

    nd.waitall()  # land deferred flags before sampling counters
    w0, s0 = _weights(net), _states(tr)
    n0 = tr._optimizer.num_update
    k0 = resilience.skipped_step_count()
    c1 = profiler.launch_count()
    loss = step(*bad)
    assert profiler.launch_count() - c1 == 1
    assert not np.isfinite(loss.asnumpy()).all()  # loss still reported
    w1, s1 = _weights(net), _states(tr)
    for k in w0:
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)
    for i in s0:
        for a, b in zip(s0[i], s1[i]):
            np.testing.assert_array_equal(a, b)
    nd.waitall()
    assert tr._optimizer.num_update == n0  # counter did not advance
    assert resilience.skipped_step_count() == k0 + 1

    # a clean step afterwards updates again
    step(*data[3])
    nd.waitall()
    assert tr._optimizer.num_update == n0 + 1


def test_fused_guard_matches_eager_numerics(monkeypatch):
    """With finite batches the guard is numerically invisible."""
    data = [_batch(t) for t in range(3)]

    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    net_g = _make_net()
    tr_g = Trainer(net_g.collect_params(), "sgd",
                   {"learning_rate": 0.1, "momentum": 0.9})
    step = tr_g.fuse_step(net_g, _loss_fn)
    for x, y in data:
        step(x, y)
    nd.waitall()  # land deferred update counts
    assert step.fused and step._guard

    monkeypatch.delenv("MXT_SKIP_NONFINITE")
    monkeypatch.setenv("MXT_FUSED_STEP", "0")
    monkeypatch.setenv("MXT_FUSED_TRAINER", "0")
    net_e = _make_net()
    tr_e = Trainer(net_e.collect_params(), "sgd",
                   {"learning_rate": 0.1, "momentum": 0.9})
    for x, y in data:
        with ag.record():
            loss = _loss_fn(net_e(x), y)
        loss.backward()
        tr_e.step(8)

    wg, we = _weights(net_g), _weights(net_e)
    for k in wg:
        np.testing.assert_allclose(wg[k], we[k], rtol=1e-6, atol=1e-6,
                                   err_msg=k)
    assert tr_g._optimizer.num_update == tr_e._optimizer.num_update == 3


def test_fused_guard_drives_loss_scaler(monkeypatch):
    """The AMP LossScaler backs off from the in-program overflow flag —
    one host read, no extra launches."""
    from mxnet_tpu.amp import LossScaler

    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    scaler = LossScaler(init_scale=2.0 ** 10)
    tr._amp_scaler = scaler
    step = tr.fuse_step(net, _loss_fn)
    step(*_batch(0))
    nd.waitall()  # the scaler consumes flags from the trailing window
    assert scaler.loss_scale == 2.0 ** 10 and scaler._unskipped == 1
    step(*_batch(99, nan=True))
    nd.waitall()
    assert scaler.loss_scale == 2.0 ** 9  # halved on overflow
    assert scaler._unskipped == 0


@pytest.mark.parametrize("fused_trainer", ["1", "0"])
def test_eager_trainer_skip_nonfinite(monkeypatch, fused_trainer):
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    monkeypatch.setenv("MXT_FUSED_TRAINER", fused_trainer)
    net = _make_net()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    x, y = _batch(0)
    with ag.record():
        loss = _loss_fn(net(x), y)
    loss.backward()
    tr.step(8)
    nd.waitall()  # the fused guard defers its flag through the window
    w0, n0 = _weights(net), tr._optimizer.num_update
    k0 = resilience.skipped_step_count()

    bx, by = _batch(1, nan=True)
    with ag.record():
        loss = _loss_fn(net(bx), by)
    loss.backward()
    tr.step(8)  # grads are NaN: the whole update is skipped
    nd.waitall()
    for k, v in _weights(net).items():
        np.testing.assert_array_equal(v, w0[k], err_msg=k)
    assert tr._optimizer.num_update == n0
    assert resilience.skipped_step_count() == k0 + 1


def test_module_update_skip_nonfinite(monkeypatch):
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.module import Module

    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1")
    mx.random.seed(0)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="resfc")
    out = sym.SoftmaxOutput(net, name="softmax")
    mod = Module(out, label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))

    x = np.random.RandomState(0).uniform(-1, 1, (4, 8)).astype(np.float32)
    lbl = np.array([0, 1, 2, 3], np.float32)
    batch = DataBatch(data=[nd.array(x)], label=[nd.array(lbl)])
    mod.forward(batch)
    mod.backward()
    mod.update()
    w0 = {n: a.asnumpy().copy() for n, a in mod._exec.arg_dict.items()
          if n.startswith("resfc")}

    bad = x.copy()
    bad[0, 0] = np.inf
    k0 = resilience.skipped_step_count()
    mod.forward(DataBatch(data=[nd.array(bad)], label=[nd.array(lbl)]))
    mod.backward()
    mod.update()  # non-finite grads: skipped wholesale
    for n, a in mod._exec.arg_dict.items():
        if n.startswith("resfc"):
            np.testing.assert_array_equal(a.asnumpy(), w0[n], err_msg=n)
    assert resilience.skipped_step_count() == k0 + 1


# ---------------------------------------------------------------------------
# pillar 2 — atomic checkpoint + auto-resume
# ---------------------------------------------------------------------------
def _train_fused(net, trainer, start, stop, mgr=None, save_every=1,
                 crash_collector=None):
    step = trainer.fuse_step(net, _loss_fn)
    for t in range(start, stop):
        step(*_batch(t))
        if mgr is not None and (t + 1) % save_every == 0:
            try:
                mgr.save(epoch=0, step=t + 1)
            except SimulatedCrash:
                crash_collector.append(t + 1)
                return step
    return step


@pytest.mark.chaos
@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2}),
])
def test_kill_and_resume_matches_uninterrupted(tmp_path, monkeypatch,
                                               optimizer, opt_params):
    """Kill mid-epoch — during a checkpoint write, at the manifest crash
    point — then resume: final params bit-identical to an uninterrupted
    run over the same batch sequence."""
    total = 6

    net_u = _make_net()
    tr_u = Trainer(net_u.collect_params(), optimizer, dict(opt_params))
    _train_fused(net_u, tr_u, 0, total)
    ref = _weights(net_u)

    ckdir = str(tmp_path / "ck")
    net1 = _make_net()
    tr1 = Trainer(net1.collect_params(), optimizer, dict(opt_params))
    mgr1 = CheckpointManager(ckdir, net=net1, trainer=tr1, keep_last=2)
    crashes = []
    _train_fused(net1, tr1, 0, 4, mgr=mgr1)           # ckpts 1..4 land
    monkeypatch.setenv("MXT_FAULT", "ckpt_crash:at=manifest,n=1")
    resilience.reset_faults()
    _train_fused(net1, tr1, 4, total, mgr=mgr1,
                 crash_collector=crashes)              # save(5) crashes
    assert crashes == [5]
    monkeypatch.delenv("MXT_FAULT")
    resilience.reset_faults()

    # "new process": fresh net (different init!), fresh trainer — resume
    # must restore params, optimizer state, counters, and stay fused
    net2 = _make_net(seed=99)
    tr2 = Trainer(net2.collect_params(), optimizer, dict(opt_params))
    mgr2 = CheckpointManager(ckdir, net=net2, trainer=tr2, keep_last=2)
    state = mgr2.resume()
    assert state is not None and state.step == 4
    assert tr2._optimizer.num_update == 4
    step2 = _train_fused(net2, tr2, state.step, total)
    assert step2.fused, step2.fallback_reason  # fused-step re-eligibility
    got = _weights(net2)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)


@pytest.mark.chaos
@pytest.mark.parametrize("point", ["params", "states", "manifest"])
def test_ckpt_crash_point_leaves_previous_intact(tmp_path, monkeypatch,
                                                 point):
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr)
    step = tr.fuse_step(net, _loss_fn)
    step(*_batch(0))
    mgr.save(step=1)
    step(*_batch(1))
    monkeypatch.setenv("MXT_FAULT", "ckpt_crash:at=%s,n=1" % point)
    resilience.reset_faults()
    with pytest.raises(SimulatedCrash):
        mgr.save(step=2)
    # the torn write is invisible; the previous checkpoint still resumes
    assert mgr.latest()["step"] == 1
    # the n=1 budget is spent: the very next save succeeds end-to-end
    mgr.save(step=2)
    assert mgr.latest()["step"] == 2


def test_truncated_checkpoint_falls_back(tmp_path):
    """A payload truncated after publication (torn FS write, bit rot) is
    rejected by size/CRC and resume() demotes to the previous one."""
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr)
    step = tr.fuse_step(net, _loss_fn)
    step(*_batch(0))
    mgr.save(step=1)
    w1 = _weights(net)
    step(*_batch(1))
    mgr.save(step=2)

    params2 = [n for n in os.listdir(str(tmp_path))
               if n.endswith("0000000002.params")][0]
    path = os.path.join(str(tmp_path), params2)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])

    assert [m["step"] for m, _ in mgr.checkpoints()] == [1]
    net2 = _make_net(seed=99)
    tr2 = Trainer(net2.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr2 = CheckpointManager(str(tmp_path), net=net2, trainer=tr2)
    state = mgr2.resume()
    assert state.step == 1
    for k, v in _weights(net2).items():
        np.testing.assert_array_equal(v, w1[k], err_msg=k)


def test_corrupt_manifest_ignored(tmp_path):
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr)
    step = tr.fuse_step(net, _loss_fn)
    step(*_batch(0))
    mgr.save(step=1)
    with open(os.path.join(str(tmp_path),
                           "ckpt-0000000009.manifest.json"), "w") as f:
        f.write("{not json")
    assert mgr.latest()["step"] == 1


def test_checkpoint_rotation_keeps_last_k(tmp_path):
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr,
                            keep_last=2)
    step = tr.fuse_step(net, _loss_fn)
    for t in range(4):
        step(*_batch(t))
        mgr.save(step=t + 1)
    steps = [m["step"] for m, _ in mgr.checkpoints()]
    assert steps == [3, 4]
    # rotated payloads are gone from disk too
    leftovers = [n for n in os.listdir(str(tmp_path))
                 if "0000000001" in n or "0000000002" in n]
    assert leftovers == []


def test_checkpoint_restores_loss_scale_and_prng(tmp_path):
    from mxnet_tpu.amp import LossScaler

    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    tr._amp_scaler = LossScaler(init_scale=2.0 ** 8)
    tr._amp_scaler.loss_scale = 128.0  # pretend backoff happened
    mx.random.seed(42)
    mx.random.new_key()  # evolve past the seed
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr)
    step = tr.fuse_step(net, _loss_fn)
    step(*_batch(0))
    key_state = mx.random.get_state()
    mgr.save(step=1)

    net2 = _make_net(seed=99)
    tr2 = Trainer(net2.collect_params(), "adam", {"learning_rate": 1e-2})
    mgr2 = CheckpointManager(str(tmp_path), net=net2, trainer=tr2)
    assert mgr2.resume() is not None
    assert tr2._amp_scaler.loss_scale == 128.0
    restored = mx.random.get_state()
    assert restored["seed"] == 42
    assert restored["key_data"] == key_state["key_data"]


def test_resume_empty_dir_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.resume() is None and mgr.latest() is None


@pytest.mark.chaos
@pytest.mark.slow
def test_kill_resume_soak(tmp_path, monkeypatch):
    """Repeated kill/resume cycles — each cycle dies at a different
    checkpoint-write phase — must still land bit-identical to one
    uninterrupted run."""
    total = 12
    net_u = _make_net()
    tr_u = Trainer(net_u.collect_params(), "adam", {"learning_rate": 1e-2})
    _train_fused(net_u, tr_u, 0, total)
    ref = _weights(net_u)

    ckdir = str(tmp_path / "soak")
    cursor = 0
    points = ["params", "states", "manifest", "rotate"]
    for cycle in range(5):
        # same init seed as the reference run: a cycle with no checkpoint
        # yet must start exactly where the uninterrupted run started
        net = _make_net()
        tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
        mgr = CheckpointManager(ckdir, net=net, trainer=tr, keep_last=2)
        state = mgr.resume()
        cursor = state.step if state is not None else 0
        if cursor >= total:
            break
        kill_at = min(cursor + 3, total)
        monkeypatch.setenv(
            "MXT_FAULT",
            "ckpt_crash:at=%s,n=1" % points[cycle % len(points)])
        resilience.reset_faults()
        crashes = []
        step = _train_fused(net, tr, cursor, kill_at, mgr=mgr,
                            crash_collector=crashes)
        monkeypatch.delenv("MXT_FAULT")
        resilience.reset_faults()
        if not crashes and kill_at >= total:
            break
    final_net = _make_net()
    final_tr = Trainer(final_net.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    mgr = CheckpointManager(ckdir, net=final_net, trainer=final_tr,
                            keep_last=2)
    state = mgr.resume()
    _train_fused(final_net, final_tr,
                 state.step if state is not None else 0, total)
    got = _weights(final_net)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)


# ---------------------------------------------------------------------------
# pillar 3 — KVStore retry + fault injection
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_dist_sync_push_retries_through_drops(monkeypatch):
    """Injected socket drops on dist_sync push recover within the retry
    budget (p=1 with a hard n cap: the failure sequence is exact)."""
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    monkeypatch.setenv("MXT_FAULT", "kv_drop:p=1.0,n=3")
    resilience.reset_faults()
    kv = mx.kv.create("dist_sync")
    kv.init(3, nd.ones((4,)))
    kv.push(3, nd.array(np.full(4, 2.0, np.float32)))  # 3 drops, then ok
    out = nd.zeros((4,))
    kv.pull(3, out)
    np.testing.assert_array_equal(out.asnumpy(), np.full(4, 2.0))


@pytest.mark.chaos
def test_dist_sync_push_exhausted_raises_kvstore_error(monkeypatch):
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    monkeypatch.setenv("MXT_KV_RETRIES", "2")
    monkeypatch.setenv("MXT_FAULT", "kv_drop:p=1.0")
    resilience.reset_faults()
    kv = mx.kv.create("dist_sync")
    kv.init(5, nd.ones((4,)))
    with pytest.raises(KVStoreError, match="failed after 2 retries"):
        kv.push(5, nd.ones((4,)))


@pytest.mark.chaos
def test_dist_sync_trainer_trains_through_drops(monkeypatch):
    """The whole eager dist_sync training path (push→server update→pull)
    survives a burst of injected drops and keeps training."""
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    monkeypatch.setenv("MXT_FAULT", "kv_drop:p=0.5,seed=11,n=6")
    resilience.reset_faults()
    net = _make_net()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore="dist_sync")
    w0 = _weights(net)
    for t in range(4):
        x, y = _batch(t)
        with ag.record():
            loss = _loss_fn(net(x), y)
        loss.backward()
        tr.step(8)
    assert any((w0[k] != v).any() for k, v in _weights(net).items())


@pytest.mark.chaos
def test_async_client_reconnects_through_drops(monkeypatch):
    from mxnet_tpu.async_server import AsyncParamServer, AsyncClient

    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    srv = AsyncParamServer("127.0.0.1", 0)
    try:
        port = srv._sock.getsockname()[1]
        cli = AsyncClient("127.0.0.1", port, timeout=5.0)
        cli.request("init", "0", np.ones(3, np.float32))
        monkeypatch.setenv("MXT_FAULT", "kv_drop:p=1.0,n=2")
        resilience.reset_faults()
        # two injected drops → two reconnect+retry cycles → success
        cli.request("push", "0", np.full(3, 5.0, np.float32))
        monkeypatch.delenv("MXT_FAULT")
        resilience.reset_faults()
        got = cli.request("pull", "0")
        np.testing.assert_array_equal(got, np.full(3, 5.0))
        cli.close()
    finally:
        srv.close()


@pytest.mark.chaos
def test_async_client_dead_server_raises_not_hangs(monkeypatch):
    import time

    from mxnet_tpu.async_server import AsyncParamServer, AsyncClient

    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    srv = AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    cli = AsyncClient("127.0.0.1", port, timeout=2.0)
    cli.request("init", "0", np.ones(3, np.float32))
    srv.close()  # server truly gone: listener AND live conns torn down
    cli._timeout = 1.0  # bound the reconnect probe for the test
    t0 = time.monotonic()
    with pytest.raises(KVStoreError):
        cli.request("push", "0", np.ones(3, np.float32))
    assert time.monotonic() - t0 < 10.0  # clean error, not a hang


def test_retry_policy_backoff_shape():
    p = resilience.RetryPolicy(retries=5, base=0.1, max_delay=0.8,
                               deadline=30, jitter=0.0)
    assert [p.delay(a) for a in (1, 2, 3, 4, 5)] == \
        [0.1, 0.2, 0.4, 0.8, 0.8]


def test_kv_retry_deadline(monkeypatch):
    calls = {"n": 0}

    def always_drop():
        calls["n"] += 1
        raise ConnectionError("down")

    policy = resilience.RetryPolicy(retries=100, base=0.05,
                                    max_delay=0.05, deadline=0.01)
    with pytest.raises(KVStoreError, match="deadline"):
        resilience.kv_retry("push", "k", always_drop, policy=policy)
    assert calls["n"] == 1  # the deadline cut the budget short


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------
def test_save_states_before_first_step(tmp_path):
    """No IndexError/AssertionError before the first step(): an early
    save records the optimizer + empty state and loads back cleanly."""
    net = _make_net()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    fname = str(tmp_path / "early.states")
    tr.save_states(fname)  # before any step
    tr2 = Trainer(_make_net().collect_params(), "adam",
                  {"learning_rate": 1e-2})
    tr2.load_states(fname)
    assert tr2._optimizer.num_update == 0

    tr3 = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    tr3._optimizer = None
    with pytest.raises(mx.MXNetError, match="no optimizer"):
        tr3.save_states(str(tmp_path / "x.states"))


def test_load_states_then_fuse_step_rebuilds(tmp_path):
    """load_states swaps the optimizer object; the fused step must
    rebuild against it and continue bit-identically with the donor."""
    net1 = _make_net()
    tr1 = Trainer(net1.collect_params(), "adam", {"learning_rate": 1e-2})
    step1 = tr1.fuse_step(net1, _loss_fn)
    for t in range(3):
        step1(*_batch(t))
    states = str(tmp_path / "t.states")
    params = str(tmp_path / "t.params")
    tr1.save_states(states)
    net1.save_parameters(params)

    net2 = _make_net(seed=99)
    tr2 = Trainer(net2.collect_params(), "adam", {"learning_rate": 1e-2})
    step2 = tr2.fuse_step(net2, _loss_fn)
    for t in range(2):  # diverge first so the restore must really work
        step2(*_batch(50 + t))
    old_opt = tr2._optimizer
    net2.load_parameters(params)
    tr2.load_states(states)
    assert tr2._optimizer is not old_opt
    assert tr2._optimizer.num_update == 3

    for t in range(3, 5):  # both continue over the same batches
        step1(*_batch(t))
        step2(*_batch(t))
    assert step2.fused and step2._built_opt is tr2._optimizer
    w1, w2 = _weights(net1), _weights(net2)
    for k in w1:
        np.testing.assert_array_equal(w1[k], w2[k], err_msg=k)


def test_load_checkpoint_reader_leniency(tmp_path):
    """Extra (unprefixed) keys are skipped, missing keys simply absent —
    and the strict unpacker still rejects malformed dicts."""
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.model import unpack_param_dict

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="lenfc")
    out = sym.SoftmaxOutput(fc, name="softmax")
    prefix = str(tmp_path / "model")
    arg = {"lenfc_weight": nd.ones((4, 8)), "lenfc_bias": nd.zeros((4,))}
    mx.save_checkpoint(prefix, 1, out, arg, {})

    pfile = prefix + "-0001.params"
    blob = nd.load(pfile)
    blob["stray_unprefixed_key"] = nd.ones((2,))
    del blob["arg:lenfc_bias"]
    nd.save(pfile, blob)

    sym2, arg2, aux2 = mx.load_checkpoint(prefix, 1)
    assert set(arg2) == {"lenfc_weight"}  # stray skipped, missing absent
    assert aux2 == {}
    assert "lenfc_weight" in sym2.list_arguments()

    with pytest.raises(mx.MXNetError, match="no arg:/aux: prefix"):
        unpack_param_dict({"nope": nd.ones((1,))}, strict=True)


def test_download_backoff_and_hoisted_ssl(monkeypatch, tmp_path):
    import ssl
    import time as time_mod
    import urllib.request

    from mxnet_tpu.gluon import utils as gutils

    sleeps = []
    monkeypatch.setattr(time_mod, "sleep", sleeps.append)
    ctx_calls = {"n": 0}
    real_ctx = ssl._create_unverified_context

    def counting_ctx(*a, **k):
        ctx_calls["n"] += 1
        return real_ctx(*a, **k)

    monkeypatch.setattr(ssl, "_create_unverified_context", counting_ctx)
    attempts = {"n": 0}

    def failing_urlopen(url, context=None):
        attempts["n"] += 1
        raise OSError("no egress")

    monkeypatch.setattr(urllib.request, "urlopen", failing_urlopen)
    with pytest.raises(OSError, match="failed after 4"):
        gutils.download("http://example.invalid/f.bin",
                        path=str(tmp_path / "f.bin"), retries=4,
                        verify_ssl=False)
    assert attempts["n"] == 4
    assert sleeps == [0.5, 1.0, 2.0]  # exponential, between attempts only
    assert ctx_calls["n"] == 1        # context hoisted out of the loop


class _KillerDataset:
    """Worker suicide at one index — emulates the OOM killer."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 4:
            os.kill(os.getpid(), signal.SIGKILL)
        return np.zeros(2, np.float32)


def test_dataloader_dead_process_worker_raises():
    from mxnet_tpu.gluon.data import DataLoader

    loader = DataLoader(_KillerDataset(), batch_size=2, num_workers=1,
                        thread_pool=False)
    with pytest.raises(mx.MXNetError, match="worker process died"):
        for _ in loader:
            pass


def test_estimator_full_state_checkpoint_resume(tmp_path):
    """CheckpointHandler(full_state=True) + resume_from_checkpoint: a
    killed fit() picks up at the next epoch and lands identical to an
    uninterrupted run."""
    from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                                   Estimator)

    rng = np.random.RandomState(3)
    data = [(nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32)),
             nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32)))
            for _ in range(3)]

    def fit(epochs, handler=None, seed=7):
        net = _make_net(seed=seed)
        tr = Trainer(net.collect_params(), "adam",
                     {"learning_rate": 1e-2})
        est = Estimator(net, _loss_fn, trainer=tr)
        est.fit(data, epochs=epochs,
                event_handlers=[handler] if handler else None)
        return est

    ref = fit(3)

    ckdir = str(tmp_path / "est")
    h1 = CheckpointHandler(ckdir, full_state=True)
    est1 = fit(2, handler=h1)  # "killed" after epoch 1's checkpoint
    assert est1.epoch == 2

    h2 = CheckpointHandler(ckdir, full_state=True,
                           resume_from_checkpoint=True)
    est2 = fit(1, handler=h2, seed=99)  # resumes at epoch 2, runs it
    assert est2.epoch == 3
    wr, w2 = _weights(ref.net), _weights(est2.net)
    for k in wr:
        np.testing.assert_array_equal(wr[k], w2[k], err_msg=k)
