"""Sparse subsystem tests (models tests/python/unittest/test_sparse_ndarray.py
+ test_sparse_operator.py + the sparse optimizer coverage in
test_optimizer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import rand_ndarray, with_seed

sparse = nd.sparse


# ---------------------------------------------------------------------------
# storage types
# ---------------------------------------------------------------------------
@with_seed()
def test_row_sparse_roundtrip():
    d = np.zeros((8, 4), "f4")
    d[[1, 5, 6]] = np.random.rand(3, 4).astype("f4")
    rsp = sparse.row_sparse_array(d)
    assert rsp.stype == "row_sparse"
    assert rsp.shape == (8, 4)
    assert rsp.num_rows == 3
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 5, 6])
    np.testing.assert_array_equal(rsp.asnumpy(), d)
    # (data, indices) constructor, unsorted indices get sorted
    rsp2 = sparse.row_sparse_array(
        (d[[5, 1, 6]], np.array([5, 1, 6])), shape=(8, 4))
    np.testing.assert_array_equal(rsp2.asnumpy(), d)
    # dense round-trips
    back = rsp.tostype("default")
    assert isinstance(back, nd.NDArray)
    np.testing.assert_array_equal(back.asnumpy(), d)


@with_seed()
def test_csr_roundtrip_and_slice():
    d = np.zeros((6, 5), "f4")
    d[0, 1] = 1.0
    d[2, [0, 4]] = [2.0, 3.0]
    d[5, 2] = 4.0
    csr = sparse.csr_matrix(d)
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), d)
    np.testing.assert_array_equal(csr.indptr.asnumpy(),
                                  [0, 1, 1, 3, 3, 3, 4])
    # row slicing keeps csr storage
    sl = csr[2:6]
    assert sl.shape == (4, 5)
    np.testing.assert_array_equal(sl.asnumpy(), d[2:6])
    one = csr[2]
    np.testing.assert_array_equal(one.asnumpy(), d[2:3])


def test_cast_storage_matrix():
    d = np.diag(np.arange(1, 5)).astype("f4")
    dn = nd.array(d)
    for stype, cls in (("row_sparse", sparse.RowSparseNDArray),
                       ("csr", sparse.CSRNDArray)):
        s = sparse.cast_storage(dn, stype)
        assert isinstance(s, cls)
        np.testing.assert_array_equal(s.asnumpy(), d)
        back = sparse.cast_storage(s, "default")
        np.testing.assert_array_equal(back.asnumpy(), d)
    with pytest.raises(MXNetError):
        sparse.cast_storage(sparse.cast_storage(dn, "row_sparse"), "csr")


def test_sparse_zeros_and_rand_ndarray():
    z = sparse.zeros("row_sparse", (5, 3))
    assert z.num_rows == 0
    np.testing.assert_array_equal(z.asnumpy(), np.zeros((5, 3)))
    zc = sparse.zeros("csr", (5, 3))
    np.testing.assert_array_equal(zc.asnumpy(), np.zeros((5, 3)))
    # the latent ImportError from round 2: rand_ndarray(stype="row_sparse")
    r = rand_ndarray((10, 4), stype="row_sparse", density=0.5)
    assert r.stype == "row_sparse"
    assert r.shape == (10, 4)


@with_seed()
def test_sparse_retain_and_add():
    d = np.zeros((10, 2), "f4")
    d[[1, 3, 7]] = np.random.rand(3, 2).astype("f4")
    rsp = sparse.row_sparse_array(d)
    kept = sparse.sparse_retain(rsp, nd.array([3.0, 7.0, 9.0]))
    exp = np.zeros_like(d)
    exp[[3, 7]] = d[[3, 7]]
    np.testing.assert_array_equal(kept.asnumpy(), exp)

    d2 = np.zeros((10, 2), "f4")
    d2[[3, 4]] = np.random.rand(2, 2).astype("f4")
    total = sparse.add(rsp, sparse.row_sparse_array(d2))
    np.testing.assert_allclose(total.asnumpy(), d + d2, rtol=1e-6)
    np.testing.assert_array_equal(total.indices.asnumpy(), [1, 3, 4, 7])


@with_seed()
def test_sparse_dot():
    d = np.zeros((6, 5), "f4")
    d[[0, 2, 4]] = np.random.rand(3, 5).astype("f4")
    csr = sparse.csr_matrix(d)
    rhs = np.random.rand(5, 3).astype("f4")
    out = sparse.dot(csr, nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), d @ rhs, rtol=1e-5)
    outT = sparse.dot(csr, nd.array(np.random.rand(6, 3).astype("f4")),
                      transpose_a=True)
    assert outT.shape == (5, 3)


# ---------------------------------------------------------------------------
# sparse optimizer updates — lazy semantics (ref: _sparse_sgd_update etc.)
# ---------------------------------------------------------------------------
def _rsp_grad(shape, rows, seed=0):
    g = np.zeros(shape, "f4")
    g[rows] = np.random.RandomState(seed).rand(len(rows), *shape[1:])
    return sparse.row_sparse_array(g)


def test_sparse_sgd_lazy_update():
    w0 = np.ones((6, 3), "f4")
    w = nd.array(w0.copy())
    mom = nd.zeros((6, 3))
    g = _rsp_grad((6, 3), [1, 4])
    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9)
    opt.update(0, w, g, mom)
    wn = w.asnumpy()
    # untouched rows identical; touched rows moved
    np.testing.assert_array_equal(wn[[0, 2, 3, 5]], w0[[0, 2, 3, 5]])
    assert not np.allclose(wn[[1, 4]], w0[[1, 4]])
    # momentum of untouched rows stays zero (lazy update!)
    mn = mom.asnumpy()
    np.testing.assert_array_equal(mn[[0, 2, 3, 5]], 0)
    assert np.abs(mn[[1, 4]]).sum() > 0


def test_sparse_adam_matches_dense_on_touched_rows():
    shape = (5, 2)
    rows = [0, 3]
    w_s = nd.array(np.ones(shape, "f4"))
    w_d = nd.array(np.ones(shape, "f4"))
    gd = np.zeros(shape, "f4")
    gd[rows] = 0.5
    opt_s = mx.optimizer.Adam(learning_rate=0.1)
    opt_d = mx.optimizer.Adam(learning_rate=0.1)
    st_s = opt_s.create_state(0, w_s)
    st_d = opt_d.create_state(0, w_d)
    opt_s.update(0, w_s, sparse.row_sparse_array(gd), st_s)
    opt_d.update(0, w_d, nd.array(gd), st_d)
    # touched rows agree with the dense update
    np.testing.assert_allclose(w_s.asnumpy()[rows], w_d.asnumpy()[rows],
                               rtol=1e-5, atol=1e-6)
    # untouched rows agree with init (dense adam moves them only via eps)
    np.testing.assert_array_equal(w_s.asnumpy()[[1, 2, 4]],
                                  np.ones(shape, "f4")[[1, 2, 4]])


def test_sparse_adagrad_and_ftrl_update_touched_only():
    for name in ("adagrad", "ftrl"):
        opt = mx.optimizer.create(name, learning_rate=0.1)
        w0 = np.ones((6, 2), "f4")
        w = nd.array(w0.copy())
        st = opt.create_state(0, w)
        opt.update(0, w, _rsp_grad((6, 2), [2, 5]), st)
        wn = w.asnumpy()
        np.testing.assert_array_equal(wn[[0, 1, 3, 4]], w0[[0, 1, 3, 4]])
        assert not np.allclose(wn[[2, 5]], w0[[2, 5]])


def _multi_step_touched_parity(name, nsteps=3, atol=1e-7, **hp):
    """Drive the server-side sparse update fns (sparse_adagrad_update /
    sparse_ftrl_update via Optimizer.update's stype dispatch) against
    the dense optimizer fed the zero-padded dense gradient: touched
    rows must bit-match the dense arithmetic, untouched rows (weight
    AND state) must be exactly unchanged — the lazy-update contract the
    embedding servers rely on."""
    shape = (10, 4)
    touched = np.array([1, 4, 6, 9])
    untouched = [0, 2, 3, 5, 7, 8]
    rng = np.random.RandomState(7)
    w0 = rng.randn(*shape).astype("f4")
    opt_s = mx.optimizer.create(name, **hp)
    opt_d = mx.optimizer.create(name, **hp)
    w_s, w_d = nd.array(w0.copy()), nd.array(w0.copy())
    st_s = opt_s.create_state(0, w_s)
    st_d = opt_d.create_state(0, w_d)

    def leaves(st):
        return st if isinstance(st, tuple) else (st,)

    st0 = [l.asnumpy() for l in leaves(st_s)]
    for _ in range(nsteps):
        gvals = rng.randn(len(touched), shape[1]).astype("f4")
        gd = np.zeros(shape, "f4")
        gd[touched] = gvals
        opt_s.update(0, w_s,
                     sparse.row_sparse_array((gvals, touched),
                                             shape=shape), st_s)
        opt_d.update(0, w_d, nd.array(gd), st_d)
    ws, wd_ = w_s.asnumpy(), w_d.asnumpy()
    # touched rows: identical arithmetic to the dense kernel
    np.testing.assert_allclose(ws[touched], wd_[touched],
                               rtol=0, atol=atol)
    # untouched rows: weight AND optimizer state untouched (no wd
    # decay, no history drift — ref lazy_update semantics)
    np.testing.assert_array_equal(ws[untouched], w0[untouched])
    for l0, l in zip(st0, leaves(st_s)):
        np.testing.assert_array_equal(l.asnumpy()[untouched],
                                      l0[untouched])
    for l_s, l_d in zip(leaves(st_s), leaves(st_d)):
        np.testing.assert_allclose(l_s.asnumpy()[touched],
                                   l_d.asnumpy()[touched],
                                   rtol=0, atol=atol)


def test_sparse_adagrad_update_parity_vs_dense_rows():
    _multi_step_touched_parity("adagrad", learning_rate=0.2, wd=0.01,
                               rescale_grad=0.5, clip_gradient=0.4)


def test_sparse_ftrl_update_parity_vs_dense_rows():
    # ftrl recomputes w from (z, n) wholesale; the dense kernel and the
    # sparse path order the float32 ops differently, so parity is
    # ulp-level, not bit-level
    _multi_step_touched_parity("ftrl", learning_rate=0.2, wd=0.01,
                               rescale_grad=0.5, clip_gradient=0.4,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# kvstore row_sparse
# ---------------------------------------------------------------------------
def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.arange(12, dtype="f4").reshape(6, 2)
    kv.init("emb", nd.array(w))
    out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array([1.0, 4.0]))
    assert out.num_rows == 2
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 4])
    np.testing.assert_array_equal(out.data.asnumpy(), w[[1, 4]])


def test_kvstore_sparse_push_with_optimizer():
    kv = mx.kv.create("local")
    kv.init("w", nd.array(np.ones((6, 2), "f4")))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    g = _rsp_grad((6, 2), [0, 2])
    kv.push("w", g)
    out = nd.zeros((6, 2))
    kv.pull("w", out=out)
    wn = out.asnumpy()
    np.testing.assert_array_equal(wn[[1, 3, 4, 5]], 1.0)
    assert not np.allclose(wn[[0, 2]], 1.0)


# ---------------------------------------------------------------------------
# Embedding sparse_grad end-to-end
# ---------------------------------------------------------------------------
@with_seed()
def test_embedding_sparse_grad_training():
    from mxnet_tpu import autograd as ag

    net = mx.gluon.nn.Embedding(20, 4, sparse_grad=True)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.5})
    x = nd.array(np.array([[1, 3], [3, 7]], "f4"))
    w_before = net.weight.data().asnumpy().copy()
    with ag.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    g = net.weight.grad()
    assert g.stype == "row_sparse"
    touched = set(g.indices.asnumpy().tolist())
    assert touched == {1, 3, 7}
    trainer.step(1)
    w_after = net.weight.data().asnumpy()
    untouched = [i for i in range(20) if i not in touched]
    np.testing.assert_array_equal(w_after[untouched], w_before[untouched])
    assert not np.allclose(w_after[sorted(touched)],
                           w_before[sorted(touched)])


@with_seed()
def test_wide_deep_trains():
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon.model_zoo import wide_deep

    net = wide_deep(wide_vocab=50, deep_vocab=30, embed_dim=4,
                    hidden=(8,), classes=2)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "adagrad",
                               {"learning_rate": 0.1})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    wide_x = nd.array(rng.randint(0, 50, (8, 5)).astype("f4"))
    deep_x = nd.array(rng.randint(0, 30, (8, 3)).astype("f4"))
    y = nd.array(rng.randint(0, 2, (8,)).astype("f4"))
    losses = []
    for _ in range(5):
        with ag.record():
            out = net(wide_x, deep_x)
            loss = loss_fn(out, y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_libsvm_iter(tmp_path):
    """mx.io.LibSVMIter yields CSR batches matching the text file
    (ref: src/io/iter_libsvm.cc)."""
    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:0.5\n")
        f.write("1 2:3.0 4:1.0\n")
        f.write("0 0:2.5 4:0.5\n")
        f.write("1 3:1.25\n")

    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(5,), batch_size=2)
    batches = list(it)
    assert len(batches) == 3 and batches[-1].pad == 1
    b0 = batches[0]
    assert b0.data[0].stype == "csr"
    dense = b0.data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(dense, [[1.5, 0, 0, 2.0, 0],
                                       [0, 0.5, 0, 0, 0]])
    np.testing.assert_allclose(b0.label[0].asnumpy(), [1.0, 0.0])
    # wrapped row in the padded final batch duplicates row 0
    last = batches[-1].data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(last[1], [1.5, 0, 0, 2.0, 0])
    # bad index surfaces clearly
    p2 = str(tmp_path / "bad.libsvm")
    with open(p2, "w") as f:
        f.write("1 9:1.0\n")
    with pytest.raises(mx.MXNetError, match="feature index"):
        mx.io.LibSVMIter(data_libsvm=p2, data_shape=(5,), batch_size=1)
    p3 = str(tmp_path / "neg.libsvm")
    with open(p3, "w") as f:
        f.write("1 -2:7.0\n")
    with pytest.raises(mx.MXNetError, match="feature index"):
        mx.io.LibSVMIter(data_libsvm=p3, data_shape=(5,), batch_size=1)


def test_libsvm_iter_edge_cases(tmp_path):
    p = str(tmp_path / "e.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.0\n0 1:2.0\n")
    # batch larger than 2x rows: wraparound must modulo, not crash
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=5)
    b = next(iter(it))
    assert b.pad == 3 and b.data[0].shape == (5, 3)
    # round_batch=False discards the short batch (CSVIter semantics)
    it2 = mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=5,
                           round_batch=False)
    assert list(it2) == []
    # label-count mismatch surfaces at construction
    lbl = str(tmp_path / "l.txt")
    with open(lbl, "w") as f:
        f.write("1\n0\n1\n")
    with pytest.raises(mx.MXNetError, match="label file"):
        mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=1,
                         label_libsvm=lbl)
    # num_parts sharding splits rows disjointly
    p3 = str(tmp_path / "s.libsvm")
    with open(p3, "w") as f:
        for i in range(6):
            f.write("%d %d:1.0\n" % (i, i % 3))
    parts = []
    for pi in range(2):
        itp = mx.io.LibSVMIter(data_libsvm=p3, data_shape=(3,),
                               batch_size=3, num_parts=2, part_index=pi)
        for b in itp:
            parts.extend(b.label[0].asnumpy().tolist())
    assert sorted(parts) == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
