"""Parallel/sharding tests on the 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8 — the SURVEY §4 pattern for testing
multi-device semantics without hardware)."""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def test_make_mesh_shapes():
    mesh = parallel.make_mesh()
    assert mesh.devices.size == 8
    mesh2 = parallel.make_mesh((4, 2), ("data", "model"))
    assert mesh2.shape == {"data": 4, "model": 2}
    mesh3 = parallel.make_mesh((-1, 2), ("data", "model"))
    assert mesh3.shape == {"data": 4, "model": 2}
    with pytest.raises(mx.MXNetError):
        parallel.make_mesh((3, 2), ("a", "b"))


def _mlp():
    net = nn.HybridSequential(prefix="ptest_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=4))
        net.add(nn.Dense(3, in_units=16))
    net.initialize()
    return net


@with_seed()
def test_sharded_step_data_parallel_matches_single():
    """The sharded dp step must produce the same update as an eager
    single-device step (allreduce-by-construction)."""
    np.random.seed(0)
    x = np.random.uniform(-1, 1, (16, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)

    mx.random.seed(7)
    net_a = _mlp()
    mx.random.seed(7)
    net_b = _mlp()
    for (na, pa), (nb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        assert_almost_equal(pa.data().asnumpy(), pb.data().asnumpy())

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    # eager reference step
    trainer = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    with mx.autograd.record():
        loss_a = loss_fn(net_a(nd.array(x)), nd.array(y)).mean()
    loss_a.backward()
    trainer.step(1)  # rescale 1/1: ShardedTrainStep loss is already a mean

    # sharded step over the 8-device data axis
    mesh = parallel.make_mesh(axis_names=("data",))
    step = parallel.ShardedTrainStep(net_b, loss_fn, "sgd",
                                     {"learning_rate": 0.1}, mesh=mesh)
    loss_b = step(nd.array(x), nd.array(y))

    assert abs(float(loss_a.asscalar()) - float(loss_b.asscalar())) < 1e-5
    for (na, pa), (nb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        assert_almost_equal(pa.data().asnumpy(), pb.data().asnumpy(),
                            rtol=1e-4, atol=1e-5)


@with_seed()
def test_sharded_step_tensor_parallel():
    """dp×tp mesh with Megatron-sharded Dense layers still trains."""
    net = _mlp()
    mesh = parallel.make_mesh((4, 2), ("data", "model"))
    rules = parallel.sharding_rule(
        (r"dense0_weight", P("model", None)),
        (r"dense0_bias", P("model")),
        (r"dense1_weight", P(None, "model")),
    )
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, rules=rules)
    # the weight is actually sharded over the model axis
    w = sorted(net.collect_params().items())[1][1]  # dense0_weight
    assert "model" in str(w.data().data.sharding.spec)

    x = np.random.uniform(-1, 1, (8, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    losses = [float(step(nd.array(x), nd.array(y)).asscalar())
              for _ in range(10)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # it learns


@with_seed()
def test_sharded_step_adam_and_batchnorm_aux():
    """Adam path + BatchNorm running-stat carry through the jitted step."""
    net = nn.HybridSequential(prefix="pbn_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Dense(2, in_units=8))
    net.initialize()
    net(nd.zeros((2, 4)))

    params = dict(net.collect_params().items())
    rm_name = [n for n in params if n.endswith("running_mean")][0]
    rm_before = params[rm_name].data().asnumpy().copy()

    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01})
    x = np.random.uniform(1, 2, (8, 4)).astype(np.float32)
    y = np.random.randint(0, 2, (8,)).astype(np.float32)
    for _ in range(3):
        loss = step(nd.array(x), nd.array(y))
    assert np.isfinite(float(loss.asscalar()))
    rm_after = params[rm_name].data().asnumpy()
    assert not np.allclose(rm_before, rm_after)  # stats updated in-program


def test_graft_entry_contract():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


# ---------------------------------------------------------------------------
# sequence parallelism (ring + Ulysses) on the 8-device CPU mesh
# ---------------------------------------------------------------------------
def test_ring_attention_matches_reference():
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _attention_reference
    from mxnet_tpu.ops.attention import make_padding_bias

    mesh = parallel.make_mesh((8,), ("sp",))
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 4, 64, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    for causal in (False, True):
        out = parallel.ring_attention(q, k, v, mesh=mesh, seq_axis="sp",
                                      causal=causal)
        ref = _attention_reference(q, k, v, None, causal, 0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    # padding bias rides the ring with K/V
    bias = make_padding_bias(jnp.asarray([40, 64]), T)
    out = parallel.ring_attention(q, k, v, bias=bias, mesh=mesh,
                                  seq_axis="sp")
    ref = _attention_reference(q, k, v, bias, False, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_matches_reference():
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _attention_reference

    mesh = parallel.make_mesh((8,), ("sp",))
    rng = np.random.RandomState(1)
    B, H, T, D = 2, 8, 64, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    for causal in (False, True):
        out = parallel.ulysses_attention(q, k, v, mesh=mesh, seq_axis="sp",
                                         causal=causal)
        ref = _attention_reference(q, k, v, None, causal, 0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_flow():
    """Ring attention is differentiable through shard_map + ppermute."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _attention_reference

    mesh = parallel.make_mesh((4,), ("sp",),
                              devices=jax.devices()[:4])
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))

    def loss_ring(q_, k_, v_):
        return jnp.sum(parallel.ring_attention(
            q_, k_, v_, mesh=mesh, seq_axis="sp") ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(_attention_reference(q_, k_, v_, None, False,
                                            1.0 / np.sqrt(D)) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ring_attention_grads_causal_bias():
    """Backward through the custom VJP: causal mask + bias riding the ring."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import _attention_reference
    from mxnet_tpu.ops.attention import make_padding_bias

    mesh = parallel.make_mesh((4,), ("sp",), devices=jax.devices()[:4])
    rng = np.random.RandomState(3)
    B, H, T, D = 2, 2, 32, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    bias = make_padding_bias(jnp.asarray([20, 32]), T)

    def loss_ring(q_, k_, v_, b_):
        return jnp.sum(parallel.ring_attention(
            q_, k_, v_, bias=b_, mesh=mesh, seq_axis="sp",
            causal=True) ** 2)

    def loss_ref(q_, k_, v_, b_):
        return jnp.sum(_attention_reference(q_, k_, v_, b_, True,
                                            1.0 / np.sqrt(D)) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ring_attention_backward_memory_is_o_t_over_n():
    """The VJP residuals must be O(T/n) per shard — NOT the O(T^2/n) that
    naive autodiff of the unrolled ring produces by saving every hop's
    (B, H, Tl, Tl) probability block (round-1 ADVICE #1)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import sequence as seq

    n = 8
    mesh = parallel.make_mesh((n,), ("sp",))
    B, H, T, D = 1, 2, 8 * n, 8  # T = 8·n per the verdict's test spec
    spec = P(None, None, "sp", None)

    def fwd_residuals(q_, k_, v_):
        _, res = seq._ring_core_fwd(q_, k_, v_, None, "sp", True,
                                    0.35, n)
        return [r for r in res if r is not None]

    out_specs = [spec] * 4 + [P(None, None, "sp")]  # q,k,v,out + lse
    shapes = jax.eval_shape(
        seq.shard_map(fwd_residuals, mesh=mesh,
                      in_specs=(spec, spec, spec), out_specs=out_specs),
        *[jax.ShapeDtypeStruct((B, H, T, D), jnp.float32)] * 3)
    total = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    # per-shard budget: q,k,v,out (B*H*Tl*D each) + lse (B*H*Tl), n shards.
    # The old path saved n extra (B,H,Tl,Tl) blocks per shard on top.
    tl = T // n
    budget = n * (4 * B * H * tl * D + B * H * tl)
    assert total <= budget, (total, budget)


def test_sync_batchnorm_global_stats_on_mesh():
    """SyncBatchNorm's design claim (basic_layers.py): inside the SPMD
    sharded step the batch is a global array, so BN batch stats are
    global — an 8-way sharded step must update running stats and params
    identically to a single-device run over the same global batch."""
    np.random.seed(1)
    x = np.random.uniform(-2, 2, (16, 6, 5, 5)).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)

    def build():
        net = mx.gluon.nn.HybridSequential()
        net.add(mx.gluon.nn.Conv2D(8, kernel_size=3, padding=1,
                                   use_bias=False),
                mx.gluon.nn.SyncBatchNorm(),
                mx.gluon.nn.Activation("relu"),
                mx.gluon.nn.Flatten(),
                mx.gluon.nn.Dense(3))
        net.initialize()
        net(nd.array(x))  # resolve shapes
        return net

    mx.random.seed(3)
    net_a = build()
    mx.random.seed(3)
    net_b = build()

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net_a.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    with mx.autograd.record():
        loss_a = loss_fn(net_a(nd.array(x)), nd.array(y)).mean()
    loss_a.backward()
    trainer.step(1)

    mesh = parallel.make_mesh(axis_names=("data",))
    step = parallel.ShardedTrainStep(net_b, loss_fn, "sgd",
                                     {"learning_rate": 0.1}, mesh=mesh)
    loss_b = step(nd.array(x), nd.array(y))

    assert abs(float(loss_a.asscalar()) - float(loss_b.asscalar())) < 1e-5
    pa = dict(net_a.collect_params().items())
    pb = dict(net_b.collect_params().items())
    for (ka, va), (kb, vb) in zip(sorted(pa.items()), sorted(pb.items())):
        assert_almost_equal(va.data().asnumpy(), vb.data().asnumpy(),
                            rtol=1e-4, atol=1e-5)
    # running stats specifically: the sharded step must have used GLOBAL
    # batch stats (a per-shard implementation would disagree here)
    rm_a = [v.data().asnumpy() for k, v in sorted(pa.items())
            if k.endswith("running_mean")]
    rm_b = [v.data().asnumpy() for k, v in sorted(pb.items())
            if k.endswith("running_mean")]
    for a, b in zip(rm_a, rm_b):
        assert_almost_equal(a, b, rtol=1e-4, atol=1e-6)
    assert any(np.abs(a).max() > 0 for a in rm_a)  # stats actually moved


def test_bert_tensor_parallel_rules_match_replicated():
    """model_zoo.bert.tensor_parallel_rules: a dp2 x tp4 sharded BERT
    step must produce the same loss/params as pure dp (GSPMD inserts the
    Megatron all-reduce pair; numerics must agree)."""
    from mxnet_tpu.gluon import Block, model_zoo

    class MLM(Block):
        def __init__(self, bert):
            super().__init__(prefix="tpmlm_")
            with self.name_scope():
                self.bert = bert

        def forward(self, x):
            seq, _ = self.bert(x, nd.zeros_like(x))
            return self.bert.decode_mlm(seq)

    def build():
        mx.random.seed(11)
        net = MLM(model_zoo.bert.bert_3_64_2(use_classifier=False,
                                             dropout=0.0))
        net.initialize()
        return net

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 1000, (8, 12)).astype("f4"))
    y = nd.array(rng.randint(0, 1000, (8, 12)).astype("f4"))

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    class SeqLoss:
        def __call__(self, out, label):
            return loss_fn(out.reshape((-1, out.shape[-1])),
                           label.reshape((-1,)))

    net_dp = build()
    net_dp(x)
    mesh_dp = parallel.make_mesh(axis_names=("data",))
    step_dp = parallel.ShardedTrainStep(net_dp, SeqLoss(), "sgd",
                                        {"learning_rate": 0.1},
                                        mesh=mesh_dp)
    loss_a = step_dp(x, y)

    net_tp = build()
    net_tp(x)
    mesh_tp = parallel.make_mesh((2, 4), ("data", "model"))
    step_tp = parallel.ShardedTrainStep(
        net_tp, SeqLoss(), "sgd", {"learning_rate": 0.1}, mesh=mesh_tp,
        rules=model_zoo.bert.tensor_parallel_rules())
    loss_b = step_tp(x, y)

    assert abs(float(loss_a.asscalar()) - float(loss_b.asscalar())) < 1e-4
    pa = dict(net_dp.collect_params().items())
    pb = dict(net_tp.collect_params().items())
    for (ka, va), (kb, vb) in zip(sorted(pa.items()), sorted(pb.items())):
        assert_almost_equal(va.data().asnumpy(), vb.data().asnumpy(),
                            rtol=2e-3, atol=2e-4)


@with_seed()
def test_sharded_step_zero1_update_sharding():
    """shard_update=True (ZeRO-1, arXiv:2004.13336): adam states shard
    dim-0 over the data axis, numerics match the unsharded step."""
    np.random.seed(1)
    x = np.random.uniform(-1, 1, (16, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)

    mx.random.seed(9)
    net_a = _mlp()
    mx.random.seed(9)
    net_b = _mlp()

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(axis_names=("data",))
    step_ref = parallel.ShardedTrainStep(net_a, loss_fn, "adam",
                                         {"learning_rate": 0.01},
                                         mesh=mesh)
    step_z = parallel.ShardedTrainStep(net_b, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh, shard_update=True)

    # eligible states (dim0 % 8 == 0) are sharded over the data axis;
    # biases of width 3 (indivisible) stay replicated
    sharded = replicated = 0
    for n in step_z._train_names:
        z = step_z._zero_shardings[n]
        for s in step_z._states[n]:
            if z is not None:
                assert "data" in str(s.sharding.spec)
                # per-device shard really is 1/8 of the state
                assert s.addressable_shards[0].data.shape[0] \
                    == s.shape[0] // 8
                sharded += 1
            else:
                replicated += 1
    assert sharded > 0  # the path is actually exercised

    for _ in range(3):
        la = step_ref(nd.array(x), nd.array(y))
        lb = step_z(nd.array(x), nd.array(y))
    assert abs(float(la.asscalar()) - float(lb.asscalar())) < 1e-5
    for (na, pa), (nb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        assert_almost_equal(pa.data().asnumpy(), pb.data().asnumpy(),
                            rtol=1e-4, atol=1e-5)


@with_seed()
def test_sharded_step_zero1_composes_with_tp():
    """ZeRO-1 over the data axis composes with Megatron tp rules: params
    the rules shard stay out of the update-sharding set."""
    net = _mlp()
    mesh = parallel.make_mesh((4, 2), ("data", "model"))
    rules = parallel.sharding_rule((r"dense0_weight", P("model", None)))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01}, mesh=mesh, rules=rules,
        shard_update=True)
    zs = step._zero_shardings
    w_tp = [n for n in step._train_names if "dense0_weight" in n][0]
    assert zs[w_tp] is None  # tp-sharded param excluded from ZeRO
    assert any(z is not None for z in zs.values())
    x = np.random.uniform(-1, 1, (8, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (8,)).astype(np.float32)
    losses = [float(step(nd.array(x), nd.array(y)).asscalar())
              for _ in range(3)]
    assert losses[-1] < losses[0]


@with_seed()
def test_sharded_step_fsdp_style_param_sharding():
    """FSDP/ZeRO-3-style: rules shard the PARAMS over the data axis;
    GSPMD all-gathers at use and keeps grads/updates sharded. Numerics
    must match the replicated step exactly."""
    np.random.seed(2)
    x = np.random.uniform(-1, 1, (16, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)

    mx.random.seed(11)
    net_a = _mlp()
    mx.random.seed(11)
    net_b = _mlp()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(axis_names=("data",))

    step_ref = parallel.ShardedTrainStep(net_a, loss_fn, "adam",
                                         {"learning_rate": 0.01},
                                         mesh=mesh)
    # dense0_weight is (16, 4): dim0 divides the 8-way axis — shard it
    # over the SAME axis the batch uses. dense1_weight (3, 16) is left
    # out of the rule ON PURPOSE: rules apply unconditionally (no
    # divisibility fallback on this path), so a matching rule on an
    # indivisible dim would error rather than silently replicate
    rules = parallel.sharding_rule((r"dense0_weight", P("data", None)))
    step_f = parallel.ShardedTrainStep(net_b, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh, rules=rules)
    w = [p for n, p in sorted(net_b.collect_params().items())
         if "dense0_weight" in n][0]
    assert "data" in str(w.data().data.sharding.spec)
    # each device holds 1/8 of the sharded weight (the FSDP memory win)
    assert w.data().data.addressable_shards[0].data.shape[0] \
        == w.shape[0] // 8

    for _ in range(3):
        la = step_ref(nd.array(x), nd.array(y))
        lb = step_f(nd.array(x), nd.array(y))
    assert abs(float(la.asscalar()) - float(lb.asscalar())) < 1e-5
    for (na, pa), (nb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        assert_almost_equal(pa.data().asnumpy(), pb.data().asnumpy(),
                            rtol=1e-4, atol=1e-5)
    # the sharding must SURVIVE training — output propagation regressions
    # would otherwise replicate the param after step 1 with identical
    # numerics, silently losing the memory win this test locks in
    assert "data" in str(w.data().data.sharding.spec)
    assert w.data().data.addressable_shards[0].data.shape[0] \
        == w.shape[0] // 8


@with_seed()
def test_sharded_step_zero1_composes_with_remat():
    """shard_update and remat both rewrite the step program — together
    they must still train and keep states sharded."""
    net = _mlp()
    mesh = parallel.make_mesh(axis_names=("data",))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01}, mesh=mesh, remat="full",
        shard_update=True)
    assert any(z is not None for z in step._zero_shardings.values())
    x = np.random.uniform(-1, 1, (16, 4)).astype(np.float32)
    y = np.random.randint(0, 3, (16,)).astype(np.float32)
    losses = [float(step(nd.array(x), nd.array(y)).asscalar())
              for _ in range(4)]
    assert all(np.isfinite(losses)) and min(losses[1:]) < losses[0]
    for n in step._train_names:
        if step._zero_shardings[n] is not None:
            for s in step._states[n]:
                assert "data" in str(s.sharding.spec)  # survived updates
