"""nn.layout_scope: channels-last models must match channels-first ones.

Weights stay logical OIHW in both layouts, so a state_dict copied across
layouts must produce identical outputs (up to float assoc) when the input
is transposed — this is the checkpoint-portability contract of
gluon/nn/layout.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import autograd as ag
from mxnet_tpu.gluon import nn, model_zoo


def _copy_params(src, dst):
    """Positional copy: the two nets differ only in the auto-generated
    top-level prefix (resnetv10_ vs resnetv11_), structure is identical."""
    sp = src.collect_params()
    dp = dst.collect_params()
    assert len(sp) == len(dp)
    for ks, kd in zip(sorted(sp.keys()), sorted(dp.keys())):
        assert ks.split("_", 1)[-1] == kd.split("_", 1)[-1], (ks, kd)
        assert sp[ks].shape == dp[kd].shape, (ks, kd)
        dp[kd].data()._set_data(sp[ks].data().data)


def _check_model(name, hw, classes=10, tol=1e-4):
    mx.random.seed(0)
    net_cf = model_zoo.get_model(name, classes=classes)
    net_cf.initialize()
    with nn.layout_scope("NHWC"):
        net_cl = model_zoo.get_model(name, classes=classes)
    net_cl.initialize()

    x = nd.array(np.random.RandomState(0)
                 .uniform(-1, 1, (2, 3, hw, hw)).astype("f4"))
    x_cl = nd.array(x.asnumpy().transpose(0, 2, 3, 1))
    net_cf(x)
    net_cl(x_cl)  # resolve deferred shapes before copying
    _copy_params(net_cf, net_cl)

    np.testing.assert_allclose(net_cl(x_cl).asnumpy(),
                               net_cf(x).asnumpy(), rtol=tol, atol=tol)


def test_resnet18_nhwc_matches_nchw():
    _check_model("resnet18_v1", 64)


def test_resnet50_v2_nhwc_matches_nchw():
    _check_model("resnet50_v2", 64, tol=5e-4)


def test_squeezenet_nhwc_matches_nchw():
    _check_model("squeezenet1.0", 96, tol=5e-4)


def test_densenet_nhwc_matches_nchw():
    # head is a fixed 7x7 AvgPool -> input must be the full 224
    _check_model("densenet121", 224, tol=5e-4)


def test_mobilenet_nhwc_matches_nchw():
    _check_model("mobilenetv2_0.5", 64, tol=5e-4)


def test_layout_scope_restores_default():
    with nn.layout_scope("NHWC"):
        assert nn.current_layout() == "NHWC"
        assert nn.channel_axis() == -1
        with nn.layout_scope("NCHW"):
            assert nn.channel_axis() == 1
        assert nn.current_layout() == "NHWC"
    assert nn.current_layout() is None
    assert nn.channel_axis() == 1


def test_explicit_layout_wins_over_scope():
    with nn.layout_scope("NHWC"):
        conv = nn.Conv2D(8, kernel_size=3, layout="NCHW")
        bn = nn.BatchNorm(axis=1)
    assert conv._layout == "NCHW"
    assert bn._axis == 1


def _small_convnet():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, use_bias=False),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(16, kernel_size=3, padding=1, use_bias=False),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.GlobalAvgPool2D(),
            nn.Flatten(),
            nn.Dense(10))
    return net


def test_nhwc_train_step_gradients():
    """Backward through conv/BN/pool in each layout gives the same grads.

    Deliberately a small, well-conditioned net: a full untrained resnet18
    has near-zero-variance BN channels whose rsqrt amplifies the
    layout-dependent f32 reduction order into O(1) grad differences on
    CPU (on TPU both layouts match bit-exactly) — that's conditioning,
    not a layout bug, and it would make any tolerance meaningless."""
    mx.random.seed(0)
    net_cf = _small_convnet()
    net_cf.initialize()
    with nn.layout_scope("NHWC"):
        net_cl = _small_convnet()
    net_cl.initialize()

    rng = np.random.RandomState(1)
    x = nd.array(rng.uniform(-1, 1, (4, 3, 16, 16)).astype("f4"))
    x_cl = nd.array(x.asnumpy().transpose(0, 2, 3, 1))
    y = nd.array(rng.randint(0, 10, (4,)).astype("f4"))
    net_cf(x)
    net_cl(x_cl)
    _copy_params(net_cf, net_cl)

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    grads = []
    for net, xin in ((net_cf, x), (net_cl, x_cl)):
        params = net.collect_params()
        for p in params.values():
            if p.grad_req != "null":
                p.zero_grad()
        with ag.record():
            loss = loss_fn(net(xin), y).mean()
        loss.backward()
        grads.append({k.split("_", 1)[-1]: p.grad().asnumpy()
                      for k, p in params.items() if p.grad_req != "null"})
    a, b = grads
    assert sorted(a.keys()) == sorted(b.keys())
    for k in a:
        np.testing.assert_allclose(b[k], a[k], rtol=1e-3, atol=1e-4,
                                   err_msg=k)
