"""Data pipeline tests (models tests/python/unittest/test_io.py,
test_recordio.py, and the gluon data portions of test_gluon_data.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.io import NDArrayIter, DataBatch, DataDesc, ResizeIter, \
    PrefetchingIter, ImageRecordIter
from mxnet_tpu.test_utils import assert_almost_equal


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------
def test_recordio_roundtrip(tmp_path):
    frec = str(tmp_path / "test.rec")
    N = 25
    writer = recordio.MXRecordIO(frec, "w")
    for i in range(N):
        writer.write(b"x" * i + b"payload%d" % i)
    writer.close()

    reader = recordio.MXRecordIO(frec, "r")
    for i in range(N):
        buf = reader.read()
        assert buf == b"x" * i + b"payload%d" % i
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    frec = str(tmp_path / "test.rec")
    fidx = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    for i in range(10):
        writer.write_idx(i, b"record_%d" % i)
    writer.close()

    reader = recordio.MXIndexedRecordIO(fidx, frec, "r")
    assert reader.keys == list(range(10))
    for i in (3, 7, 0, 9):
        assert reader.read_idx(i) == b"record_%d" % i
    reader.close()


def test_irheader_pack_unpack():
    header = recordio.IRHeader(0, 3.5, 42, 0)
    s = recordio.pack(header, b"imagebytes")
    h2, payload = recordio.unpack(s)
    assert h2.label == 3.5
    assert h2.id == 42
    assert payload == b"imagebytes"
    # multi-label path
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 7, 0)
    s = recordio.pack(header, b"xyz")
    h3, payload = recordio.unpack(s)
    assert h3.flag == 3
    assert_almost_equal(h3.label, np.array([1.0, 2.0, 3.0]))
    assert payload == b"xyz"


def test_pack_img_unpack_img():
    img = (np.random.uniform(0, 255, (32, 24, 3))).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    header, img2 = recordio.unpack_img(s)
    assert header.label == 1.0
    assert img2.shape == (32, 24, 3)
    assert np.array_equal(img, img2)  # png is lossless


# ---------------------------------------------------------------------------
# NDArrayIter
# ---------------------------------------------------------------------------
def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    assert_almost_equal(batches[0].data[0].asnumpy(), data[:3])

    it.reset()
    again = list(it)
    assert len(again) == 4


def test_ndarray_iter_discard_and_shuffle():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    it = NDArrayIter(data, None, batch_size=3, shuffle=True,
                     last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 3
    seen = np.concatenate([b.data[0].asnumpy() for b in batches])
    assert seen.shape == (9, 4)


def test_ndarray_iter_provide_data():
    data = np.zeros((8, 2, 3), dtype=np.float32)
    it = NDArrayIter(data, np.zeros(8), batch_size=4)
    d = it.provide_data[0]
    assert d.name == "data"
    assert d.shape == (4, 2, 3)
    assert it.provide_label[0].name == "softmax_label"


def test_resize_and_prefetch_iter():
    data = np.arange(24).reshape(12, 2).astype(np.float32)
    base = NDArrayIter(data, np.zeros(12), batch_size=4)
    r = ResizeIter(base, 5)
    assert len(list(r)) == 5

    base.reset()
    p = PrefetchingIter(NDArrayIter(data, np.zeros(12), batch_size=4))
    batches = list(p)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 2)


# ---------------------------------------------------------------------------
# ImageRecordIter over a generated .rec
# ---------------------------------------------------------------------------
def _make_rec(tmp_path, n=12, size=(20, 18)):
    frec = str(tmp_path / "imgs.rec")
    fidx = str(tmp_path / "imgs.idx")
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, size + (3,)).astype(np.uint8)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    writer.close()
    return frec, fidx


def test_image_record_iter(tmp_path):
    frec, fidx = _make_rec(tmp_path)
    it = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                         data_shape=(3, 16, 16), batch_size=4,
                         shuffle=True, rand_crop=True, rand_mirror=True,
                         preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 16, 16)
    assert batches[0].label[0].shape == (4,)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert set(labels.tolist()) <= {0.0, 1.0, 2.0}
    it.reset()
    assert len(list(it)) == 3


def test_image_det_record_iter(tmp_path):
    from mxnet_tpu.io import ImageDetRecordIter

    frec = str(tmp_path / "det.rec")
    fidx = str(tmp_path / "det.idx")
    writer = recordio.MXIndexedRecordIO(fidx, frec, "w")
    rng = np.random.RandomState(0)
    widths = []
    for i in range(8):
        img = rng.randint(0, 255, (20, 18, 3)).astype(np.uint8)
        n_obj = 1 + i % 3
        label = [2.0, 5.0]  # header_width, object_width
        for j in range(n_obj):
            label += [float(j % 4), 0.1 + 0.05 * j, 0.2, 0.6, 0.8]
        widths.append(len(label))
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, np.array(label, dtype=np.float32), i, 0),
            img, img_fmt=".png"))
    writer.close()

    it = ImageDetRecordIter(path_imgrec=frec, path_imgidx=fidx,
                            data_shape=(3, 16, 16), batch_size=4,
                            preprocess_threads=2)
    assert it.label_pad_width == max(widths)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 16, 16)
    assert batches[0].label[0].shape == (4, max(widths))
    lab = batches[0].label[0].asnumpy()
    np.testing.assert_allclose(lab[:, 0], 2.0)  # header width preserved
    np.testing.assert_allclose(lab[:, 1], 5.0)
    # single-object rows are padded with -1 past their boxes
    one_obj = lab[lab[:, 7] == -1.0]
    if len(one_obj):
        assert (one_obj[:, 7:] == -1.0).all()

    # mirror flips normalized x coords, boxes stay ordered/in-range
    it_m = ImageDetRecordIter(path_imgrec=frec, path_imgidx=fidx,
                              data_shape=(3, 16, 16), batch_size=8,
                              rand_mirror=True, seed=3,
                              preprocess_threads=1)
    b = next(iter(it_m))
    la = b.label[0].asnumpy()
    xmin, xmax = la[:, 3], la[:, 5]
    valid = la[:, 2] >= 0
    assert (xmin[valid] < xmax[valid]).all()
    assert (xmin[valid] >= 0).all() and (xmax[valid] <= 1.0).all()

    # rand_crop would shift boxes -> rejected loudly
    with pytest.raises(Exception, match="rand_crop"):
        ImageDetRecordIter(path_imgrec=frec, path_imgidx=fidx,
                           data_shape=(3, 16, 16), batch_size=4,
                           rand_crop=True)
    # too-narrow pad width surfaces the real error, not a thread crash
    it_bad = ImageDetRecordIter(path_imgrec=frec, path_imgidx=fidx,
                                data_shape=(3, 16, 16), batch_size=4,
                                label_pad_width=3)
    with pytest.raises(Exception, match="label_pad_width"):
        next(iter(it_bad))


def test_image_record_iter_sharded(tmp_path):
    frec, fidx = _make_rec(tmp_path)
    it0 = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                          data_shape=(3, 16, 16), batch_size=2,
                          part_index=0, num_parts=2)
    it1 = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                          data_shape=(3, 16, 16), batch_size=2,
                          part_index=1, num_parts=2)
    assert len(list(it0)) == 3
    assert len(list(it1)) == 3


# ---------------------------------------------------------------------------
# Gluon data
# ---------------------------------------------------------------------------
def test_array_dataset_and_loader():
    X = np.arange(20).reshape(10, 2).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 10
    x0, y0 = ds[3]
    assert_almost_equal(x0, X[3])

    loader = gdata.DataLoader(ds, batch_size=4, shuffle=False,
                              last_batch="keep")
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    assert batches[2][0].shape == (2, 2)

    loader2 = gdata.DataLoader(ds, batch_size=4, shuffle=True,
                               last_batch="discard", num_workers=2)
    batches2 = list(loader2)
    assert len(batches2) == 2


def test_dataset_transform():
    X = np.arange(10).astype(np.float32)
    ds = gdata.SimpleDataset(list(X)).transform(lambda x: x * 2)
    assert ds[3] == 6.0
    ds2 = gdata.ArrayDataset(X, X).transform_first(lambda x: x + 1)
    a, b = ds2[0]
    assert a == 1.0 and b == 0.0


def test_samplers():
    s = gdata.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    r = gdata.RandomSampler(5)
    assert sorted(list(r)) == [0, 1, 2, 3, 4]
    b = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "keep")
    assert [len(x) for x in b] == [3, 3, 1]
    assert len(b) == 3
    b2 = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "discard")
    assert [len(x) for x in b2] == [3, 3]
    b3 = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "rollover")
    assert [len(x) for x in list(b3)] == [3, 3]
    assert [len(x) for x in list(b3)] == [3, 3]  # rolled-over 1 + 7 = 8 → 2x3


def test_record_file_dataset(tmp_path):
    frec, fidx = _make_rec(tmp_path, n=6)
    ds = gdata.vision.ImageRecordDataset(frec)
    assert len(ds) == 6
    img, label = ds[2]
    assert img.shape == (20, 18, 3)
    assert label == 2.0


def test_transforms():
    from mxnet_tpu.gluon.data.vision import transforms as T

    img = nd.array(np.random.randint(0, 255, (20, 16, 3)).astype(np.uint8))
    t = T.ToTensor()(img)
    assert t.shape == (3, 20, 16)
    assert float(t.max().asscalar()) <= 1.0

    n = T.Normalize(mean=(0.5, 0.5, 0.5), std=(2.0, 2.0, 2.0))(t)
    assert n.shape == (3, 20, 16)

    r = T.Resize((8, 10))(img)
    assert r.shape == (10, 8, 3)

    c = T.CenterCrop(8)(img)
    assert c.shape == (8, 8, 3)

    rc = T.RandomResizedCrop(8)(img)
    assert rc.shape == (8, 8, 3)

    comp = T.Compose([T.Resize(12), T.ToTensor()])
    out = comp(img)
    assert out.shape == (3, 12, 12)

    f = T.RandomFlipLeftRight()(img)
    assert f.shape == img.shape
    cj = T.RandomColorJitter(0.4, 0.4, 0.4)(img)
    assert cj.shape == img.shape
    rl = T.RandomLighting(0.1)(img)
    assert rl.shape == img.shape


def test_transforms_hue_crop_rotate():
    from mxnet_tpu.gluon.data.vision import transforms as T

    img = nd.array(np.random.randint(0, 255, (20, 16, 3)).astype(np.uint8))

    h = T.RandomHue(0.3)(img)
    assert h.shape == img.shape
    # hue=0 factor range collapses to 1.0 -> identity (up to clip/float)
    h0 = T.RandomHue(0.0)(img)
    np.testing.assert_allclose(h0.asnumpy(), img.asnumpy().astype(np.float32),
                               atol=1e-2)
    # jitter with hue enabled routes through RandomHue
    cj = T.RandomColorJitter(hue=0.2)(img)
    assert cj.shape == img.shape

    cr = T.CropResize(2, 4, 10, 12)(img)
    assert cr.shape == (12, 10, 3)
    cr2 = T.CropResize(2, 4, 10, 12, size=(6, 8))(img)
    assert cr2.shape == (8, 6, 3)
    import pytest as _pytest
    with _pytest.raises(Exception):
        T.CropResize(10, 10, 10, 12)(img)

    # 4x90-degree rotations of a square image compose to identity
    sq = nd.array(np.random.randint(0, 255, (16, 16, 3)).astype(np.uint8))
    r = sq
    for _ in range(4):
        r = T.Rotate(90)(r)
    np.testing.assert_allclose(r.asnumpy(), sq.asnumpy(), atol=1.0)
    assert T.Rotate(37, zoom_in=True)(sq).shape == (16, 16, 3)
    assert T.Rotate(37, zoom_out=True)(sq).shape == (16, 16, 3)
    # float images (mid-pipeline, after color jitter) must work too
    fsq = T.RandomBrightness(0.3)(sq)
    assert T.Rotate(20, zoom_in=True)(fsq).shape == (16, 16, 3)
    assert T.Rotate(20, zoom_out=True)(fsq).shape == (16, 16, 3)
    with _pytest.raises(Exception):  # negative origin must raise
        T.CropResize(-5, 0, 4, 4)(img)
    with _pytest.raises(Exception):  # non-positive dims must raise
        T.CropResize(0, 0, 0, 10)(img)
    # zoom_out on a non-square image: content scales uniformly (a square
    # marker stays square), no stretch
    rect = np.zeros((10, 30, 3), dtype=np.uint8)
    rect[3:7, 13:17] = 255  # 4x4 marker
    rot = T.Rotate(90, zoom_out=True)(nd.array(rect)).asnumpy()
    ys, xs = np.where(rot[:, :, 0] > 128)
    hspan, wspan = ys.max() - ys.min() + 1, xs.max() - xs.min() + 1
    assert abs(hspan - wspan) <= 1, (hspan, wspan)

    rr = T.RandomRotation((-30, 30))(sq)
    assert rr.shape == (16, 16, 3)
    # proba=0 -> identity
    rr0 = T.RandomRotation((-30, 30), rotate_with_proba=0.0)(sq)
    np.testing.assert_array_equal(rr0.asnumpy(), sq.asnumpy())
    with _pytest.raises(Exception):
        T.RandomRotation((30, -30))
    with _pytest.raises(Exception):
        T.Rotate(10, zoom_in=True, zoom_out=True)


def test_dataloader_with_transform_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms as T

    imgs = [np.random.randint(0, 255, (20, 16, 3)).astype(np.uint8)
            for _ in range(8)]
    labels = list(range(8))
    ds = gdata.ArrayDataset(gdata.SimpleDataset(imgs),
                            gdata.SimpleDataset(labels))
    tds = ds.transform_first(
        T.Compose([T.Resize(12), T.ToTensor()]))
    loader = gdata.DataLoader(tds, batch_size=4)
    for x, y in loader:
        assert x.shape == (4, 3, 12, 12)
        assert y.shape == (4,)


def test_ndarray_iter_roll_over():
    """roll_over withholds the partial batch and rolls it into next epoch."""
    X = np.arange(10).astype(np.float32).reshape(10, 1)
    it = NDArrayIter(X, batch_size=4, last_batch_handle="roll_over")
    b1 = list(it)
    assert len(b1) == 2  # 8 samples; 2 leftover withheld
    assert all(b.pad == 0 for b in b1)
    it.reset()
    b2 = list(it)
    # next epoch leads with the 2 leftover samples: 2 + 10 = 12 → 3 batches
    assert len(b2) == 3
    first = b2[0].data[0].asnumpy().ravel()
    assert first[0] == 8.0 and first[1] == 9.0
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in b2])
    assert sorted(seen.tolist()) == sorted([8., 9.] + list(range(10)))


def test_image_record_iter_round_batch_false(tmp_path):
    frec, fidx = _make_rec(tmp_path, n=10)
    it = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                         data_shape=(3, 16, 16), batch_size=4,
                         round_batch=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].data[0].shape[0] == 2  # short final batch, no wrap
    assert batches[-1].pad == 0
    # round_batch=True wraps and reports pad
    it2 = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                          data_shape=(3, 16, 16), batch_size=4)
    batches2 = list(it2)
    assert batches2[-1].data[0].shape[0] == 4
    assert batches2[-1].pad == 2


def test_record_file_dataset_threaded_reads(tmp_path):
    """Concurrent __getitem__ must not race the shared seek+read handle."""
    import threading as _threading

    frec, fidx = _make_rec(tmp_path, n=12)
    ds = gdata.vision.ImageRecordDataset(frec)
    errors = []

    def reader(tid):
        rng = np.random.RandomState(tid)
        try:
            for _ in range(40):
                i = int(rng.randint(0, 12))
                img, label = ds[i]
                assert label == float(i % 3)
                assert img.shape == (20, 18, 3)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [_threading.Thread(target=reader, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_prefetching_iter_reset_no_leak():
    X = np.arange(40).astype(np.float32).reshape(20, 2)
    base = NDArrayIter(X, batch_size=4)
    pf = PrefetchingIter(base)
    import threading as _threading

    n0 = _threading.active_count()
    for _ in range(5):
        batches = list(pf)
        assert len(batches) == 5
        pf.reset()
    assert _threading.active_count() <= n0 + 1  # no thread pile-up


# ---------------------------------------------------------------------------
# process-worker DataLoader (ref: gluon/data/dataloader.py fork workers +
# src/storage/cpu_shared_storage_manager.h — our redesign ships pickled
# numpy from forked children; see dataloader.py module docstring)
# ---------------------------------------------------------------------------
class _GilHeavyDataset(gdata.Dataset):
    """Pure-Python per-sample transform — holds the GIL (the workload the
    reference's fork workers exist for)."""

    def __init__(self, n=64, work=4000):
        self._n, self._work = n, work

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        acc = 0.0
        for i in range(self._work):  # GIL-bound Python loop
            acc += (idx * 31 + i) % 7
        return np.full((8,), np.float32(acc)), np.float32(idx)


class _FailingDataset(gdata.Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, idx):
        if idx == 11:
            raise ValueError("poisoned sample 11")
        return np.zeros((2,), np.float32)


def test_process_workers_match_thread_workers():
    ds = _GilHeavyDataset(n=24, work=50)
    thr = list(gdata.DataLoader(ds, batch_size=8, num_workers=2,
                                thread_pool=True))
    prc = list(gdata.DataLoader(ds, batch_size=8, num_workers=2,
                                thread_pool=False))
    assert len(thr) == len(prc) == 3
    for (tx, ty), (px, py) in zip(thr, prc):
        assert_almost_equal(tx, px.asnumpy())
        assert_almost_equal(ty, py.asnumpy())


def test_process_workers_custom_batchify():
    ds = _GilHeavyDataset(n=16, work=10)

    def batchify(samples):
        xs = np.stack([s[0] for s in samples])
        return mx.nd.array(xs * 2.0)

    out = list(gdata.DataLoader(ds, batch_size=8, num_workers=2,
                                thread_pool=False, batchify_fn=batchify))
    ref = list(gdata.DataLoader(ds, batch_size=8, num_workers=0,
                                batchify_fn=batchify))
    for a, b in zip(out, ref):
        assert_almost_equal(a, b.asnumpy())


def test_process_worker_error_propagates():
    ds = _FailingDataset()
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2,
                              thread_pool=False)
    with pytest.raises(ValueError, match="poisoned sample 11"):
        list(loader)


def test_thread_worker_error_propagates():
    ds = _FailingDataset()
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2,
                              thread_pool=True)
    with pytest.raises(ValueError, match="poisoned sample 11"):
        list(loader)


@pytest.mark.skipif(len(getattr(os, "sched_getaffinity", lambda _: [0])(0))
                    < 4,
                    reason="needs >=4 schedulable cores for a "
                           "meaningful A/B")
def test_process_workers_beat_threads_on_gil_heavy_transform():
    """The reason the escape hatch exists: a GIL-bound transform chain
    serializes under threads but scales under processes."""
    import time
    ds = _GilHeavyDataset(n=48, work=20000)

    def run(thread_pool):
        t0 = time.perf_counter()
        for _ in gdata.DataLoader(ds, batch_size=8, num_workers=4,
                                  thread_pool=thread_pool):
            pass
        return time.perf_counter() - t0

    run(True)  # warm both paths (pool spin-up, imports)
    # scheduler-dependent timings: take the best of two runs per mode and
    # allow a small margin — the claim is "processes aren't serialized by
    # the GIL", not an exact speedup factor
    t_thread = min(run(True), run(True))
    t_proc = min(run(False), run(False))
    assert t_proc < t_thread * 1.1, (t_proc, t_thread)


def test_image_record_iter_nhwc_layout(tmp_path):
    """layout='NHWC' (TPU extension): channels-last batches, pixel-equal
    to the NCHW path transposed."""
    frec, fidx = _make_rec(tmp_path)
    common = dict(path_imgrec=frec, path_imgidx=fidx,
                  data_shape=(3, 16, 16), batch_size=4, shuffle=False,
                  mean_r=10.0, std_r=2.0,  # exercise normalization too
                  preprocess_threads=2)
    nchw = list(ImageRecordIter(**common))
    nhwc = list(ImageRecordIter(layout="NHWC", **common))
    it = ImageRecordIter(layout="NHWC", **common)
    assert it.provide_data[0].shape == (4, 16, 16, 3)
    assert it.provide_data[0].layout == "NHWC"
    for a, b in zip(nchw, nhwc):
        np.testing.assert_array_equal(
            a.data[0].asnumpy().transpose(0, 2, 3, 1),
            b.data[0].asnumpy())
        np.testing.assert_array_equal(a.label[0].asnumpy(),
                                      b.label[0].asnumpy())
    with pytest.raises(Exception):
        ImageRecordIter(layout="NCWH", **common)


def test_image_record_uint8_iter(tmp_path):
    """ImageRecordUInt8Iter (ref: iter_image_recordio_2.cc uint8
    registration): raw uint8 batches, device-side normalization."""
    from mxnet_tpu.io import ImageRecordUInt8Iter
    frec, fidx = _make_rec(tmp_path)
    it = ImageRecordUInt8Iter(path_imgrec=frec, path_imgidx=fidx,
                              data_shape=(3, 16, 16), batch_size=4,
                              shuffle=False, preprocess_threads=2)
    b = next(iter(it))
    assert b.data[0].dtype == np.uint8
    assert it.provide_data[0].dtype == np.dtype("uint8")
    # pixel-equal to the f32 path
    it_f = ImageRecordIter(path_imgrec=frec, path_imgidx=fidx,
                           data_shape=(3, 16, 16), batch_size=4,
                           shuffle=False, preprocess_threads=2)
    bf = next(iter(it_f))
    np.testing.assert_array_equal(b.data[0].asnumpy().astype(np.float32),
                                  bf.data[0].asnumpy())
    # mean/std are a device-side job in uint8 mode
    with pytest.raises(Exception, match="uint8"):
        ImageRecordUInt8Iter(path_imgrec=frec, path_imgidx=fidx,
                             data_shape=(3, 16, 16), batch_size=4,
                             mean_r=1.0)


def test_image_record_uint8_iter_rejects_conflicting_dtype(tmp_path):
    from mxnet_tpu.io import ImageRecordUInt8Iter
    frec, fidx = _make_rec(tmp_path)
    with pytest.raises(Exception, match="uint8 by definition"):
        ImageRecordUInt8Iter(path_imgrec=frec, path_imgidx=fidx,
                             data_shape=(3, 16, 16), batch_size=4,
                             dtype="float32")
