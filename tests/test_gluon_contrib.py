"""gluon.contrib.nn / gluon.contrib.rnn block zoo
(ref: tests/python/unittest/test_gluon_contrib.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.test_utils import with_seed


def test_concurrent():
    net = cnn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(4), cnn.Identity())
    net.initialize()
    x = mx.nd.ones((2, 3))
    out = net(x)
    assert out.shape == (2, 7)
    # Identity branch passes x through untouched
    assert np.array_equal(out.asnumpy()[:, 4:], x.asnumpy())
    dyn = cnn.Concurrent(axis=-1)
    dyn.add(cnn.Identity(), cnn.Identity())
    dyn.initialize()
    assert dyn(x).shape == (2, 6)


def test_pixelshuffle2d_values():
    ps = cnn.PixelShuffle2D(2)
    a = np.arange(1 * 4 * 2 * 2, dtype=np.float32).reshape(1, 4, 2, 2)
    got = ps(mx.nd.array(a)).asnumpy()
    ref = a.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(1, 1, 4, 4)
    assert np.array_equal(got, ref)


@pytest.mark.parametrize("cls,shape,factor,out_shape", [
    (cnn.PixelShuffle1D, (1, 6, 4), 3, (1, 2, 12)),
    (cnn.PixelShuffle2D, (1, 8, 3, 3), 2, (1, 2, 6, 6)),
    (cnn.PixelShuffle2D, (1, 6, 3, 3), (3, 2), (1, 1, 9, 6)),
    (cnn.PixelShuffle3D, (1, 8, 2, 2, 2), 2, (1, 1, 4, 4, 4)),
])
def test_pixelshuffle_shapes(cls, shape, factor, out_shape):
    assert cls(factor)(mx.nd.ones(shape)).shape == out_shape


def test_pixelshuffle_bad_channels_message():
    with pytest.raises(ValueError, match="not divisible"):
        cnn.PixelShuffle2D(2)(mx.nd.ones((1, 6, 3, 3)))


def test_pixelshuffle_symbolic():
    """Shape-free formulation must trace through the Symbol path
    (export / SymbolBlock)."""
    import mxnet_tpu.symbol as sym

    for ps, shape in [(cnn.PixelShuffle1D(2), (1, 4, 5)),
                      (cnn.PixelShuffle2D(2), (1, 8, 3, 3)),
                      (cnn.PixelShuffle3D(2), (1, 8, 2, 2, 2))]:
        out = ps(sym.var("data"))
        eager = ps(mx.nd.ones(shape))
        bound = out.bind(mx.cpu(), {"data": mx.nd.ones(shape)})
        np.testing.assert_allclose(bound.forward()[0].asnumpy(),
                                   eager.asnumpy(), rtol=1e-6)


def test_sparse_embedding():
    se = cnn.SparseEmbedding(10, 4)
    se.initialize()
    out = se(mx.nd.array([[1, 2]]))
    assert out.shape == (1, 2, 4)
    assert se.weight._grad_stype == "row_sparse"


def test_lstmp_cell():
    c = crnn.LSTMPCell(8, 3)
    c.initialize()
    out, states = c(mx.nd.ones((2, 5)), c.begin_state(2))
    assert out.shape == (2, 3)
    assert states[0].shape == (2, 3) and states[1].shape == (2, 8)
    outs, _ = c.unroll(4, mx.nd.ones((2, 4, 5)), merge_outputs=True)
    assert outs.shape == (2, 4, 3)


@with_seed()
def test_variational_dropout_mask_fixed_over_time():
    base = gluon.rnn.LSTMCell(6)
    vd = crnn.VariationalDropoutCell(base, drop_inputs=0.5, drop_outputs=0.5)
    vd.initialize()
    with autograd.record():
        o, _ = vd.unroll(3, mx.nd.ones((2, 3, 5)), merge_outputs=False)
    m0 = o[0].asnumpy() == 0
    m1 = o[1].asnumpy() == 0
    assert np.any(m0), "dropout must actually fire during training"
    assert np.array_equal(m0, m1), "output mask must be shared across time"
    # inference mode: no dropout at all
    vd.reset()
    o, _ = vd.unroll(2, mx.nd.ones((2, 2, 5)), merge_outputs=False)
    assert not np.any(o[0].asnumpy() == 0)


def test_conv_lstm_cell():
    cc = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=4,
                             i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cc.initialize()
    o, s = cc(mx.nd.ones((2, 3, 8, 8)), cc.begin_state(2))
    assert o.shape == (2, 4, 8, 8)
    assert s[1].shape == (2, 4, 8, 8)
    o2, _ = cc.unroll(3, mx.nd.ones((2, 3, 3, 8, 8)), merge_outputs=True)
    assert o2.shape == (2, 3, 4, 8, 8)


@pytest.mark.parametrize("cls,gates", [
    (crnn.Conv1DRNNCell, 1),
    (crnn.Conv1DLSTMCell, 4),
    (crnn.Conv1DGRUCell, 3),
])
def test_conv_cells_1d(cls, gates):
    c = cls(input_shape=(2, 10), hidden_channels=3, i2h_kernel=3,
            h2h_kernel=3, i2h_pad=1)
    c.initialize()
    o, _ = c(mx.nd.ones((2, 2, 10)), c.begin_state(2))
    assert o.shape == (2, 3, 10)
    assert c.i2h_weight.shape[0] == gates * 3


def test_conv_lstm_channels_last():
    """TPU-preferred NHWC layout: state/weight shapes follow the C axis."""
    cc = crnn.Conv2DLSTMCell(input_shape=(8, 8, 3), hidden_channels=4,
                             i2h_kernel=3, h2h_kernel=3, i2h_pad=1,
                             conv_layout="NHWC")
    cc.initialize()
    o, s = cc(mx.nd.ones((2, 8, 8, 3)), cc.begin_state(2))
    assert o.shape == (2, 8, 8, 4)
    assert s[1].shape == (2, 8, 8, 4)
    # value parity with the NCHW cell under transposed inputs + same params
    ref = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=4,
                              i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    ref.initialize()
    for name in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
        getattr(ref, name).set_data(getattr(cc, name).data())
    x = mx.nd.random.uniform(shape=(2, 3, 8, 8))
    o_ref, _ = ref(x, ref.begin_state(2))
    o_nhwc, _ = cc(x.transpose((0, 2, 3, 1)), cc.begin_state(2))
    np.testing.assert_allclose(o_nhwc.asnumpy().transpose(0, 3, 1, 2),
                               o_ref.asnumpy(), rtol=2e-5, atol=2e-5)


def test_conv_rnn_grad_flows():
    c = crnn.Conv2DRNNCell(input_shape=(1, 4, 4), hidden_channels=2,
                           i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    c.initialize()
    x = mx.nd.ones((1, 3, 1, 4, 4))
    with autograd.record():
        o, _ = c.unroll(3, x, merge_outputs=True)
        loss = o.sum()
    loss.backward()
    g = c.i2h_weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
