"""contrib.onnx export/import roundtrips (models the reference's
tests/python-pytest/onnx — forward-equivalence after a save/load through
the ONNX wire format)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.contrib import onnx as onnx_mxnet


def _init_args(s, rng, **input_shapes):
    arg_shapes, _, aux_shapes = s.infer_shape(**input_shapes)
    args = {}
    for name, shape in zip(s.list_arguments(), arg_shapes):
        if name in input_shapes:
            continue
        args[name] = nd.array(rng.uniform(-0.5, 0.5, shape).astype("f4"))
    aux = {}
    for name, shape in zip(s.list_auxiliary_states(), aux_shapes):
        val = rng.uniform(0.5, 1.5, shape) if name.endswith("var") \
            else rng.uniform(-0.1, 0.1, shape)
        aux[name] = nd.array(val.astype("f4"))
    return args, aux


def _forward(s, args, aux, **inputs):
    ex = s.bind(args={**args, **{k: nd.array(v) for k, v in
                                 inputs.items()}},
                aux_states=dict(aux) if aux else None, grad_req="null")
    outs = ex.forward(is_train=False)
    return outs[0].asnumpy()


def _roundtrip(s, input_shapes, tmp_path, atol=1e-5):
    rng = np.random.RandomState(0)
    args, aux = _init_args(s, rng, **input_shapes)
    inputs = {k: rng.uniform(-1, 1, v).astype("f4")
              for k, v in input_shapes.items()}
    ref = _forward(s, args, aux, **inputs)

    path = str(tmp_path / "model.onnx")
    onnx_mxnet.export_model(s, {**args, **aux},
                            [input_shapes[k] for k in sorted(input_shapes)],
                            np.float32, path)
    s2, arg2, aux2 = onnx_mxnet.import_model(path)
    out = _forward(s2, arg2, aux2, **inputs)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=atol)
    return s2


def test_onnx_mlp_roundtrip(tmp_path):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = sym.softmax(net, name="prob")
    _roundtrip(net, {"data": (2, 20)}, tmp_path)


def test_onnx_lenet_roundtrip(tmp_path):
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    net = sym.Activation(net, act_type="tanh", name="t1")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                      name="p1")
    net = sym.Convolution(net, kernel=(3, 3), num_filter=16, pad=(1, 1),
                          name="c2")
    net = sym.Activation(net, act_type="relu", name="r2")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="avg",
                      name="p2")
    net = sym.Flatten(net, name="flat")
    net = sym.FullyConnected(net, num_hidden=10, name="fc")
    _roundtrip(net, {"data": (2, 1, 28, 28)}, tmp_path)


def test_onnx_conv_bn_global_pool_roundtrip(tmp_path):
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                          no_bias=True, name="conv")
    net = sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = sym.LeakyReLU(net, slope=0.1, name="lrelu")
    net = sym.Pooling(net, global_pool=True, pool_type="avg", name="gap")
    net = sym.Flatten(net, name="fl")
    _roundtrip(net, {"data": (2, 3, 8, 8)}, tmp_path, atol=1e-4)


def test_onnx_elemwise_and_scalar_roundtrip(tmp_path):
    a = sym.Variable("a")
    net = sym.broadcast_add(a * 2.0, sym.sqrt(sym.abs(a)) + 1.0)
    net = sym.tanh(net)
    _roundtrip(net, {"a": (3, 4)}, tmp_path)


def test_onnx_reshape_transpose_concat_roundtrip(tmp_path):
    a = sym.Variable("a")
    left = sym.Reshape(a, shape=(2, 12), name="rs")
    right = sym.Reshape(sym.transpose(a, axes=(0, 2, 1), name="tr"),
                        shape=(2, 12), name="rs2")
    net = sym.Concat(left, right, dim=1, name="cat")
    _roundtrip(net, {"a": (2, 3, 4)}, tmp_path)


def test_onnx_embedding_roundtrip(tmp_path):
    idx = sym.Variable("idx")
    net = sym.Embedding(idx, input_dim=11, output_dim=6, name="emb")
    net = sym.FullyConnected(net, num_hidden=4, flatten=True, name="fc")
    rng = np.random.RandomState(1)
    s = net
    args, aux = _init_args(s, rng, idx=(2, 5))
    x = rng.randint(0, 11, (2, 5)).astype("f4")
    ref = _forward(s, args, aux, idx=x)
    path = str(tmp_path / "emb.onnx")
    onnx_mxnet.export_model(s, args, [(2, 5)], np.float32, path)
    s2, arg2, aux2 = onnx_mxnet.import_model(path)
    out = _forward(s2, arg2, aux2, idx=x)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_onnx_model_metadata(tmp_path):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=3, name="fc")
    rng = np.random.RandomState(0)
    args, _ = _init_args(net, rng, data=(4, 7))
    path = str(tmp_path / "meta.onnx")
    onnx_mxnet.export_model(net, args, [(4, 7)], np.float32, path)
    meta = onnx_mxnet.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (4, 7))]
    assert len(meta["output_tensor_data"]) == 1


def test_onnx_import_to_gluon(tmp_path):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=5, name="fc1")
    net = sym.Activation(net, act_type="relu", name="r")
    net = sym.FullyConnected(net, num_hidden=3, name="fc2")
    rng = np.random.RandomState(0)
    args, _ = _init_args(net, rng, data=(2, 6))
    x = rng.uniform(-1, 1, (2, 6)).astype("f4")
    ref = _forward(net, args, {}, data=x)
    path = str(tmp_path / "g.onnx")
    onnx_mxnet.export_model(net, args, [(2, 6)], np.float32, path)
    block = onnx_mxnet.import_to_gluon(path)
    out = block(nd.array(x)).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_onnx_unsupported_op_errors(tmp_path):
    data = sym.Variable("data")
    net = sym.SequenceReverse(data)
    with pytest.raises(mx.MXNetError, match="no ONNX converter"):
        onnx_mxnet.export_model(net, {}, [(2, 3, 4)], np.float32,
                                str(tmp_path / "x.onnx"))


def test_onnx_batchnorm_fix_gamma_roundtrip(tmp_path):
    # fix_gamma=True (the BatchNorm default) forces scale=1 at runtime;
    # the exporter must write a ones scale, not the stored gamma values
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                          name="conv")
    net = sym.BatchNorm(net, name="bn")  # fix_gamma defaults True
    net = sym.Activation(net, act_type="relu", name="r")
    net = sym.Flatten(net, name="f")
    net = sym.FullyConnected(net, num_hidden=3, name="fc")
    net = sym.softmax(net, name="prob")
    _roundtrip(net, {"data": (2, 3, 6, 6)}, tmp_path, atol=1e-4)


def test_onnx_deconv_clip_pad_roundtrip(tmp_path):
    data = sym.Variable("data")
    net = sym.Deconvolution(data, kernel=(2, 2), stride=(2, 2),
                            num_filter=4, name="up")
    net = sym.clip(net, a_min=-0.4, a_max=0.6)
    net = sym.pad(net, mode="constant", constant_value=0.5,
                  pad_width=(0, 0, 0, 0, 1, 1, 1, 1))
    _roundtrip(net, {"data": (2, 3, 5, 5)}, tmp_path, atol=1e-4)


def test_onnx_reduce_and_l2norm_roundtrip(tmp_path):
    a = sym.Variable("a")
    parts = [
        sym.sum(a, axis=(1,), keepdims=True),
        sym.mean(a, axis=(1,), keepdims=True),
        sym.max(a, axis=(1,), keepdims=True),
        sym.min(a, axis=(1,), keepdims=True),
    ]
    net = sym.Concat(*parts, dim=1, name="cat")
    _roundtrip(net, {"a": (3, 5)}, tmp_path)

    x = sym.Variable("x")
    net2 = sym.L2Normalization(x, mode="channel", name="l2")
    _roundtrip(net2, {"x": (2, 4, 3, 3)}, tmp_path, atol=1e-5)


def test_onnx_cast_roundtrip(tmp_path):
    a = sym.Variable("a")
    net = sym.cast(sym.cast(a, dtype="float64") * 1.5, dtype="float32")
    _roundtrip(net, {"a": (2, 3)}, tmp_path)
