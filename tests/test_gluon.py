"""Gluon Block/HybridBlock tests (modeled on tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
def test_dense_explicit_shape():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = nd.random.uniform(shape=(2, 3))
    out = layer(x)
    assert out.shape == (2, 4)
    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy()
    assert_almost_equal(out, x.asnumpy() @ w.T + b, rtol=1e-4)


@with_seed()
def test_dense_deferred_init():
    layer = nn.Dense(7)
    layer.initialize()
    assert layer.weight.shape == (7, 0)
    out = layer(nd.ones((4, 5)))
    assert layer.weight.shape == (7, 5)
    assert out.shape == (4, 7)


@with_seed()
def test_sequential_and_naming():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(8))
    net.initialize()
    out = net(nd.ones((2, 4)))
    assert out.shape == (2, 8)
    names = list(net.collect_params().keys())
    assert len(names) == 4
    assert all(n.startswith(net.prefix) for n in names)


@with_seed()
def test_conv_pool_stack():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2))
        net.add(nn.Conv2D(16, kernel_size=3))
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Flatten())
        net.add(nn.Dense(10))
    net.initialize()
    out = net(nd.random.uniform(shape=(2, 3, 16, 16)))
    assert out.shape == (2, 10)


@with_seed()
def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="tanh"))
        net.add(nn.Dense(5))
    net.initialize()
    x = nd.random.uniform(shape=(4, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5)
    # different batch size triggers retrace, still works
    out2 = net(nd.random.uniform(shape=(2, 8)))
    assert out2.shape == (2, 5)


@with_seed()
def test_hybridize_gradients():
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(6, activation="relu", in_units=3))
            net.add(nn.Dense(2, in_units=6))
        return net

    mx.random.seed(11)
    np.random.seed(11)
    net_e = build()
    net_e.initialize()
    mx.random.seed(11)
    np.random.seed(11)
    net_h = build()
    net_h.initialize()
    net_h.hybridize()

    x = nd.random.uniform(shape=(5, 3))
    for net in (net_e, net_h):
        with ag.record():
            out = net(x)
            loss = nd.sum(out * out)
        loss.backward()
    for (n1, p1), (n2, p2) in zip(
        sorted(net_e.collect_params().items()),
        sorted(net_h.collect_params().items()),
    ):
        assert_almost_equal(p1.data().grad, p2.data().grad, rtol=1e-4,
                            atol=1e-5)


@with_seed()
def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm(in_channels=3, momentum=0.5)
    bn.initialize()
    x = nd.random.normal(2.0, 3.0, shape=(8, 3, 4, 4))
    rm0 = bn.running_mean.data().asnumpy().copy()
    with ag.record():
        out = bn(x)
    out.wait_to_read()
    rm1 = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1)  # stats moved
    # inference mode: no update, uses running stats
    out_inf = bn(x)
    rm2 = bn.running_mean.data().asnumpy()
    assert_almost_equal(rm1, rm2)


@with_seed()
def test_batchnorm_aux_updates_under_hybridize():
    bn = nn.BatchNorm(in_channels=3, momentum=0.5)
    bn.initialize()
    bn.hybridize()
    x = nd.random.normal(1.0, 2.0, shape=(8, 3, 4, 4))
    rm0 = bn.running_mean.data().asnumpy().copy()
    with ag.record():
        out = bn(x)
    out.wait_to_read()
    rm1 = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1)  # aux writeback escaped the jit


@with_seed()
def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
        net.add(nn.Dense(3, in_units=8))
    net.initialize()
    x = nd.random.uniform(shape=(2, 4))
    ref = net(x).asnumpy()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4))
        net2.add(nn.Dense(3, in_units=8))
    net2.load_parameters(fname)
    assert_almost_equal(net2(x), ref)


@with_seed()
def test_embedding_and_dropout():
    emb = nn.Embedding(10, 6)
    emb.initialize()
    idx = nd.array([1, 2, 3], dtype="int32")
    out = emb(idx)
    assert out.shape == (3, 6)
    assert_almost_equal(out, emb.weight.data().asnumpy()[[1, 2, 3]])

    do = nn.Dropout(0.5)
    do.initialize()
    x = nd.ones((50, 50))
    assert_almost_equal(do(x), x.asnumpy())  # inference: identity


@with_seed()
def test_losses():
    pred = nd.array(np.random.randn(4, 5).astype(np.float32))
    label = nd.array([0, 2, 1, 4])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    logp = np.log(
        np.exp(pred.asnumpy())
        / np.exp(pred.asnumpy()).sum(-1, keepdims=True))
    expected = -logp[np.arange(4), label.asnumpy().astype(int)]
    assert_almost_equal(l, expected, rtol=1e-4)

    p2 = nd.array([[1.0, 2.0]])
    t2 = nd.array([[0.0, 4.0]])
    l2 = gluon.loss.L2Loss()(p2, t2)
    assert_almost_equal(l2, np.array([(0.5 * 1 + 0.5 * 4) / 2.0]), rtol=1e-4)
    l1 = gluon.loss.L1Loss(weight=1.0)(p2, t2)
    assert_almost_equal(l1, np.array([1.5]), rtol=1e-4)


@with_seed()
def test_custom_hybrid_block():
    class MLP(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.fc1 = nn.Dense(16)
                self.fc2 = nn.Dense(4)

        def hybrid_forward(self, F, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = MLP()
    net.initialize()
    out = net(nd.ones((2, 7)))
    assert out.shape == (2, 4)
    net.hybridize()
    out2 = net(nd.ones((2, 7)))
    assert_almost_equal(out, out2.asnumpy(), rtol=1e-5)


@with_seed()
def test_layernorm_groupnorm():
    ln = nn.LayerNorm()
    ln.initialize()
    x = nd.random.uniform(shape=(3, 7))
    out = ln(x)
    xn = x.asnumpy()
    expected = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
        xn.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, expected, rtol=1e-4)

    gn = nn.GroupNorm(num_groups=2, in_channels=4)
    gn.initialize()
    out = gn(nd.random.uniform(shape=(2, 4, 3, 3)))
    assert out.shape == (2, 4, 3, 3)


@with_seed()
def test_split_and_load():
    data = nd.arange(0, 24).reshape((8, 3))
    ctxs = [mx.cpu(0), mx.cpu(0)]
    parts = gluon.split_and_load(data, ctxs)
    assert len(parts) == 2
    assert parts[0].shape == (4, 3)
    with pytest.raises(mx.MXNetError):
        gluon.split_data(nd.ones((7, 2)), 2)


@with_seed()
def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-4
    assert norm > 1.0


@with_seed()
def test_user_initializers_win():
    # regression: bias_initializer/gamma_initializer must override suffix dispatch
    d = nn.Dense(4, in_units=3, bias_initializer="ones")
    d.initialize()
    assert_almost_equal(d.bias.data(), np.ones(4))
    bn = nn.BatchNorm(in_channels=3, gamma_initializer="zeros")
    bn.initialize()
    assert_almost_equal(bn.gamma.data(), np.zeros(3))


@with_seed()
def test_constant_survives_force_reinit():
    c = gluon.Constant("c", nd.array([1.0, 2.0, 3.0]))
    c.initialize(force_reinit=True)
    assert_almost_equal(c.data(), np.array([1.0, 2.0, 3.0]))


@with_seed()
def test_set_data_shape_mismatch_raises():
    p = gluon.Parameter("w", shape=(4, 3))
    p.initialize()
    with pytest.raises(mx.MXNetError):
        p.set_data(nd.ones((5, 5)))
