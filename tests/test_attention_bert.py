"""Flash attention kernel + BERT model tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import model_zoo
from mxnet_tpu.ops import attention as A
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("with_bias", [False, True])
def test_flash_kernel_vs_reference(causal, with_bias):
    """Pallas kernel (interpret mode) must match the O(T^2) reference."""
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 3, 80, 32
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    bias = A.make_padding_bias(jnp.asarray([37, 80]), T) if with_bias \
        else None
    ref = A._attention_reference(q, k, v, bias, causal, 0.125)
    out, lse = A._flash_forward_pallas(q, k, v, bias, causal, 0.125,
                                       32, 32, interpret=True)
    assert_almost_equal(np.asarray(out), np.asarray(ref), rtol=1e-5,
                        atol=1e-5)
    # lse-based backward must match autodiff-of-reference
    do = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    dq, dk, dv, _ = A._flash_bwd(causal, 0.125,
                                 (q, k, v, bias, out, lse), do)
    g_ref = jax.grad(
        lambda q_, k_, v_: jnp.sum(
            A._attention_reference(q_, k_, v_, bias, causal, 0.125) * do),
        argnums=(0, 1, 2))(q, k, v)
    assert_almost_equal(np.asarray(dq), np.asarray(g_ref[0]), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(np.asarray(dk), np.asarray(g_ref[1]), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(np.asarray(dv), np.asarray(g_ref[2]), rtol=1e-4,
                        atol=1e-4)


@with_seed()
def test_flash_attention_op_and_grad():
    """Registered op works through nd + autograd."""
    rng = np.random.RandomState(1)
    q = nd.array(rng.normal(size=(2, 2, 16, 8)).astype("f4"))
    k = nd.array(rng.normal(size=(2, 2, 16, 8)).astype("f4"))
    v = nd.array(rng.normal(size=(2, 2, 16, 8)).astype("f4"))
    q.attach_grad()
    with ag.record():
        out = nd.flash_attention(q, k, v)
        loss = (out * out).sum()
    loss.backward()
    assert out.shape == (2, 2, 16, 8)
    assert float(np.abs(q.grad.asnumpy()).sum()) > 0


@with_seed()
def test_bert_forward_shapes():
    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    B, T = 2, 12
    tokens = nd.array(np.random.RandomState(0).randint(0, 1000, (B, T)))
    types = nd.zeros((B, T))
    vl = nd.array([8, 12])
    seq, pooled = net(tokens, types, vl)
    assert seq.shape == (B, T, 64)
    assert pooled.shape == (B, 64)
    scores = net.decode_mlm(seq)
    assert scores.shape == (B, T, 1000)
    nsp = net.classify_nsp(pooled)
    assert nsp.shape == (B, 2)


@with_seed()
def test_bert_padding_invariance():
    """Tokens past valid_length must not affect valid positions."""
    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(0)
    t1 = rng.randint(0, 1000, (1, 10))
    t2 = t1.copy()
    t2[0, 6:] = rng.randint(0, 1000, 4)  # change only padding region
    vl = nd.array([6])
    types = nd.zeros((1, 10))
    s1, _ = net(nd.array(t1), types, vl)
    s2, _ = net(nd.array(t2), types, vl)
    assert_almost_equal(s1.asnumpy()[:, :6], s2.asnumpy()[:, :6],
                        rtol=1e-4, atol=1e-5)


@with_seed()
def test_bert_mlm_training_step():
    """One hybridized MLM pretraining step decreases loss over iterations."""
    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    params = net.collect_params()
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-3})
    rng = np.random.RandomState(0)
    B, T = 4, 16
    tokens = nd.array(rng.randint(0, 1000, (B, T)))
    types = nd.zeros((B, T))
    labels = nd.array(rng.randint(0, 1000, (B, T)))

    losses = []
    for _ in range(12):
        with ag.record():
            seq, pooled = net(tokens, types)
            scores = net.decode_mlm(seq)
            loss = loss_fn(scores.reshape((-1, 1000)),
                           labels.reshape((-1,)))
        loss.backward()
        trainer.step(B * T)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < losses[0] * 0.8, losses


@with_seed()
def test_bert_hybridize_consistency():
    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    tokens = nd.array(np.random.RandomState(0).randint(0, 1000, (2, 8)))
    types = nd.zeros((2, 8))
    s0, p0 = net(tokens, types)
    net.hybridize()
    s1, p1 = net(tokens, types)
    assert_almost_equal(s0.asnumpy(), s1.asnumpy(), rtol=1e-5, atol=1e-5)
    assert_almost_equal(p0.asnumpy(), p1.asnumpy(), rtol=1e-5, atol=1e-5)


@with_seed()
def test_causal_cross_length_alignment():
    """Tq != Tk causal must be bottom-right aligned in ALL paths."""
    rng = np.random.RandomState(2)
    B, H, Tq, Tk, D = 1, 1, 4, 12, 8
    q = jnp.asarray(rng.normal(size=(B, H, Tq, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, Tk, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, Tk, D)).astype("f4"))
    ref = A._attention_reference(q, k, v, None, True, 0.3)
    out_p, _ = A._flash_forward_pallas(q, k, v, None, True, 0.3, 4, 4,
                                       interpret=True)
    assert_almost_equal(np.asarray(out_p), np.asarray(ref), rtol=1e-5,
                        atol=1e-5)
    out_s, _ = A._attention_scan_fwd(q, k, v, None, True, 0.3, chunk=4)
    assert_almost_equal(np.asarray(out_s), np.asarray(ref), rtol=1e-5,
                        atol=1e-5)


@with_seed()
def test_long_sequence_chunked_path():
    """KV beyond the VMEM budget takes the scan path; fwd+bwd match ref."""
    rng = np.random.RandomState(3)
    B, H, T, D = 1, 1, 64, 16
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    bias = A.make_padding_bias(jnp.asarray([50]), T)
    out, lse = A._attention_scan_fwd(q, k, v, bias, False, 0.25, chunk=16)
    ref = A._attention_reference(q, k, v, bias, False, 0.25)
    assert_almost_equal(np.asarray(out), np.asarray(ref), rtol=1e-5,
                        atol=1e-5)
    do = jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f4"))
    dq, dk, dv, db = A._bwd_chunked(q, k, v, bias, out, lse, do, False,
                                    0.25, chunk=16)
    g_ref = jax.grad(
        lambda q_, k_, v_, b_: jnp.sum(
            A._attention_reference(q_, k_, v_, b_, False, 0.25) * do),
        argnums=(0, 1, 2, 3))(q, k, v, bias)
    assert_almost_equal(np.asarray(dq), np.asarray(g_ref[0]), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(np.asarray(dk), np.asarray(g_ref[1]), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(np.asarray(dv), np.asarray(g_ref[2]), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(np.asarray(db), np.asarray(g_ref[3]), rtol=1e-3,
                        atol=1e-3)


@with_seed()
def test_bert_mlm_weight_tying():
    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    embed_w = net.word_embed.weight
    dec_w = net.mlm_decoder.weight
    assert embed_w is dec_w  # literally the same Parameter


@with_seed()
def test_bert_export_symbol_block(tmp_path):
    """BERT must trace symbolically (shape-free hybrid_forward)."""
    from mxnet_tpu import symbol as sym
    from mxnet_tpu import gluon

    net = model_zoo.bert_3_64_2(dropout=0.0)
    net.initialize()
    tokens = nd.array(np.random.RandomState(0).randint(0, 1000, (2, 8)))
    types = nd.zeros((2, 8))
    s0, p0 = net(tokens, types)
    data = sym.Variable("data")
    ttypes = sym.Variable("token_types")
    out = net(data, ttypes)  # symbolic trace
    g = sym.Group(list(out))
    args = g.list_arguments()
    assert "data" in args and "token_types" in args
    blk = gluon.SymbolBlock(g, [data, ttypes])
    for name, p in net.collect_params().items():
        if name in blk.params:
            blk.params[name].set_data(p.data())
    s1, p1 = blk(tokens, types)
    assert_almost_equal(s0.asnumpy(), s1.asnumpy(), rtol=1e-4, atol=1e-5)
    assert_almost_equal(p0.asnumpy(), p1.asnumpy(), rtol=1e-4, atol=1e-5)
