"""int8 quantization (ref: tests/python/quantization/test_quantization.py;
ops in src/operator/quantization/*, API in contrib/quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import Module

RS = np.random.RandomState(7)


# ------------------------------------------------------------------- ops
def test_quantize_dequantize_roundtrip():
    x = nd.array(RS.randn(3, 17).astype(np.float32) * 4)
    q, mn, mx_ = nd.contrib.quantize_v2(x)
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, mn, mx_)
    step = float(mx_.asnumpy()) / 127
    assert np.abs(back.asnumpy() - x.asnumpy()).max() <= step / 2 + 1e-7


def test_quantize_calibrated_range_clips():
    x = nd.array(np.array([[-10.0, 0.5, 3.0]], np.float32))
    q, mn, mx_ = nd.contrib.quantize_v2(x, min_calib_range=-4.0,
                                        max_calib_range=4.0)
    assert float(mn.asnumpy()) == -4.0 and float(mx_.asnumpy()) == 4.0
    assert q.asnumpy()[0, 0] == -127  # clipped, not wrapped


def test_quantized_fc_matches_f32():
    x = nd.array(RS.randn(5, 12).astype(np.float32))
    W = RS.randn(6, 12).astype(np.float32)
    b = RS.randn(6).astype(np.float32)
    qx, xmn, xmx = nd.contrib.quantize_v2(x)
    qw, wmn, wmx = nd.contrib.quantize_v2(nd.array(W))
    acc, omn, omx = nd.contrib.quantized_fully_connected(
        qx, qw, nd.array(b), xmn, xmx, wmn, wmx, num_hidden=6)
    assert acc.dtype == np.int32
    out = nd.contrib.dequantize(acc, omn, omx).asnumpy()
    ref = x.asnumpy() @ W.T + b
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_conv_matches_f32():
    x = nd.array(RS.randn(2, 3, 8, 8).astype(np.float32))
    W = RS.randn(5, 3, 3, 3).astype(np.float32)
    b = RS.randn(5).astype(np.float32)
    qx, xmn, xmx = nd.contrib.quantize_v2(x)
    qw, wmn, wmx = nd.contrib.quantize_v2(nd.array(W))
    acc, omn, omx = nd.contrib.quantized_conv(
        qx, qw, nd.array(b), xmn, xmx, wmn, wmx,
        kernel=(3, 3), num_filter=5, pad=(1, 1))
    out = nd.contrib.dequantize(acc, omn, omx).asnumpy()
    ref_sym = nd.Convolution(x, nd.array(W), nd.array(b), kernel=(3, 3),
                             num_filter=5, pad=(1, 1)).asnumpy()
    assert np.abs(out - ref_sym).max() / np.abs(ref_sym).max() < 0.03


def test_requantize_to_calibrated_int8():
    x = nd.array(RS.randn(4, 9).astype(np.float32))
    qx, xmn, xmx = nd.contrib.quantize_v2(x)
    qw, wmn, wmx = nd.contrib.quantize_v2(nd.array(
        RS.randn(3, 9).astype(np.float32)))
    acc, amn, amx = nd.contrib.quantized_fully_connected(
        qx, qw, None, xmn, xmx, wmn, wmx, num_hidden=3, no_bias=True)
    ref = nd.contrib.dequantize(acc, amn, amx).asnumpy()
    cal = float(np.abs(ref).max())
    q8, rmn, rmx = nd.contrib.requantize(acc, amn, amx,
                                         min_calib_range=-cal,
                                         max_calib_range=cal)
    assert q8.dtype == np.int8
    out = nd.contrib.dequantize(q8, rmn, rmx).asnumpy()
    assert np.abs(out - ref).max() <= cal / 127 + 1e-6


def test_quantized_pooling_triple():
    x = nd.array(RS.randn(2, 4, 6, 6).astype(np.float32))
    q, mn, mx_ = nd.contrib.quantize_v2(x)
    p, pmn, pmx = nd.contrib.quantized_pooling(q, mn, mx_, kernel=(2, 2),
                                               stride=(2, 2),
                                               pool_type="max")
    assert p.dtype == np.int8 and p.shape == (2, 4, 3, 3)
    ref = nd.Pooling(nd.contrib.dequantize(q, mn, mx_), kernel=(2, 2),
                     stride=(2, 2), pool_type="max").asnumpy()
    out = nd.contrib.dequantize(p, pmn, pmx).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


# ----------------------------------------------------------- graph level
def _lenet_symbol():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                         name="conv1")
    a1 = sym.Activation(c1, act_type="relu", name="relu1")
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool1")
    c2 = sym.Convolution(p1, kernel=(3, 3), num_filter=16, pad=(1, 1),
                         name="conv2")
    a2 = sym.Activation(c2, act_type="relu", name="relu2")
    p2 = sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool2")
    f = sym.Flatten(p2, name="flat")
    fc1 = sym.FullyConnected(f, num_hidden=32, name="fc1")
    a3 = sym.Activation(fc1, act_type="relu", name="relu3")
    fc2 = sym.FullyConnected(a3, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def _proto_dataset(n, img=12, classes=4, noise=0.3, seed=42):
    """Learnable synthetic task: smooth, mutually-orthogonal per-class
    prototypes + noise (orthogonality guarantees separability, so the
    fp32 baseline trains to confident margins — without that, int8
    rounding collapses near-ties and the accuracy delta measures the
    task's noise, not the quantizer). Own RandomState: sharing the
    module-level RS made the data depend on test execution order."""
    coarse = np.linalg.qr(np.random.RandomState(0).randn(9, 9))[0][:classes]
    protos = []
    for c in range(classes):
        up = np.kron(coarse[c].reshape(3, 3) * 3.0,
                     np.ones((img // 3 + 1, img // 3 + 1)))
        protos.append(up[:img, :img])
    protos = np.stack(protos)
    r = np.random.RandomState(seed + n)
    y = r.randint(0, classes, n)
    x = protos[y] + noise * r.randn(n, img, img)
    return x[:, None].astype(np.float32), y.astype(np.float32)


def _train_fp32_lenet():
    X, y = _proto_dataset(768)
    it = NDArrayIter(X, y, batch_size=64, shuffle=True,
                     label_name="softmax_label")
    mod = Module(_lenet_symbol(), data_names=["data"],
                 label_names=["softmax_label"])
    mod.fit(it, num_epoch=4,
            optimizer="adam", optimizer_params={"learning_rate": 2e-3},
            eval_metric="acc")
    return mod


def _accuracy(symbol, args, auxs, X, y, batch=64):
    mod = Module(symbol, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", (batch,) + X.shape[1:])],
             for_training=False)
    mod.set_params(args, auxs, allow_missing=False)
    correct = 0
    for i in range(0, len(X) - batch + 1, batch):
        b = mx.io.DataBatch(data=[nd.array(X[i:i + batch])], label=None)
        mod.forward(b, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        correct += (pred == y[i:i + batch]).sum()
    return correct / (len(X) // batch * batch)


@pytest.fixture(scope="module")
def trained_lenet():
    mod = _train_fp32_lenet()
    arg, aux = mod.get_params()
    return mod._symbol, arg, aux


def test_quantize_model_accuracy_within_1pt(trained_lenet):
    symbol, arg, aux = trained_lenet
    Xv, yv = _proto_dataset(512)
    calib = NDArrayIter(Xv[:256], yv[:256], batch_size=64,
                        label_name="softmax_label")
    qsym, qarg, qaux = qz.quantize_model(
        symbol, arg, aux, calib_mode="naive", calib_data=calib,
        num_calib_examples=256, excluded_sym_names=())
    acc_f = _accuracy(symbol, arg, aux, Xv, yv)
    acc_q = _accuracy(qsym, qarg, qaux, Xv, yv)
    assert acc_f > 0.8, "fp32 baseline did not train (acc=%.3f)" % acc_f
    assert acc_f - acc_q <= 0.01 + 1e-9, (acc_f, acc_q)
    # the rewritten graph really runs int8 kernels
    ops = {n.op for n in qsym._topo_nodes() if not n.is_var()}
    assert "quantized_conv" in ops and "quantized_fully_connected" in ops
    assert "quantized_pooling" in ops  # pool rides the int8 triple


def test_quantize_model_entropy_calibration(trained_lenet):
    symbol, arg, aux = trained_lenet
    Xv, yv = _proto_dataset(320)
    calib = NDArrayIter(Xv[:192], yv[:192], batch_size=64,
                        label_name="softmax_label")
    qsym, qarg, qaux = qz.quantize_model(
        symbol, arg, aux, calib_mode="entropy", calib_data=calib,
        num_calib_examples=192)
    acc_f = _accuracy(symbol, arg, aux, Xv, yv)
    acc_q = _accuracy(qsym, qarg, qaux, Xv, yv)
    assert acc_f - acc_q <= 0.02 + 1e-9, (acc_f, acc_q)


def test_quantize_model_excluded_layer(trained_lenet):
    symbol, arg, aux = trained_lenet
    Xv, yv = _proto_dataset(128)
    calib = NDArrayIter(Xv, yv, batch_size=64,
                        label_name="softmax_label")
    qsym, qarg, qaux = qz.quantize_model(
        symbol, arg, aux, calib_mode="naive", calib_data=calib,
        excluded_sym_names=("fc2",))
    ops = [n for n in qsym._topo_nodes()
           if not n.is_var() and n.op == "FullyConnected"]
    assert len(ops) == 1 and ops[0].name == "fc2"
    assert "fc2_weight" in qarg  # stays f32


def test_quantized_symbol_json_roundtrip(trained_lenet, tmp_path):
    """A quantized graph survives Symbol JSON + binary params save/load
    (the deployment path)."""
    symbol, arg, aux = trained_lenet
    Xv, yv = _proto_dataset(128)
    calib = NDArrayIter(Xv, yv, batch_size=64, label_name="softmax_label")
    qsym, qarg, qaux = qz.quantize_model(
        symbol, arg, aux, calib_mode="naive", calib_data=calib)
    from mxnet_tpu.model import save_checkpoint, load_checkpoint
    save_checkpoint(str(tmp_path / "q"), 0, qsym, qarg, qaux)
    qsym2, qarg2, qaux2 = load_checkpoint(str(tmp_path / "q"), 0)
    a1 = _accuracy(qsym, qarg, qaux, Xv, yv)
    a2 = _accuracy(qsym2, qarg2, qaux2, Xv, yv)
    assert a1 == a2
    assert qarg2["conv1_weight_quantize"].dtype == np.int8


def test_dynamic_quantization_no_calib(trained_lenet):
    symbol, arg, aux = trained_lenet
    Xv, yv = _proto_dataset(128)
    qsym, qarg, qaux = qz.quantize_model(
        symbol, arg, aux, calib_mode="none")
    acc_f = _accuracy(symbol, arg, aux, Xv, yv)
    acc_q = _accuracy(qsym, qarg, qaux, Xv, yv)
    assert acc_f - acc_q <= 0.02 + 1e-9, (acc_f, acc_q)


def test_quantize_net_gluon_surface(tmp_path):
    """quantize_net: gluon block in, int8 SymbolBlock out
    (ref: contrib/quantization.py — quantize_net_v2)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn as gnn

    net = gnn.HybridSequential()
    with net.name_scope():
        net.add(gnn.Conv2D(8, 3, padding=1, in_channels=1))
        net.add(gnn.Activation("relu"))
        net.add(gnn.MaxPool2D(2, 2))
        net.add(gnn.Flatten())
        net.add(gnn.Dense(4))
    net.initialize()
    X, y = _proto_dataset(128)
    net(mx.nd.array(X[:4]))  # shape init
    calib = NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive",
                           num_calib_examples=128, tmpdir=str(tmp_path))
    ref = net(mx.nd.array(X[:64])).asnumpy()
    out = qnet(mx.nd.array(X[:64])).asnumpy()
    # int8 logits track the f32 block closely
    denom = np.abs(ref).max() or 1.0
    assert np.abs(out - ref).max() / denom < 0.05
    # the imported graph must actually carry int8 kernels — numeric
    # closeness alone would pass trivially for an unquantized graph
    kinds = {n.op for n in qnet._sb_symbol._topo_nodes()
             if not n.is_var()}
    assert "quantized_conv" in kinds
    assert "quantized_fully_connected" in kinds
