"""Autograd semantics (modeled on tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = x * x + 2.0
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


@with_seed()
def test_chain_and_broadcast():
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    w = nd.array(np.random.rand(5, 4).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = nd.dot(x, w, transpose_b=True)
        z = nd.sum(y * y)
    z.backward()
    y_np = x.asnumpy() @ w.asnumpy().T
    assert_almost_equal(x.grad, 2 * y_np @ w.asnumpy(), rtol=1e-4)
    assert_almost_equal(w.grad, 2 * y_np.T @ x.asnumpy(), rtol=1e-4)


@with_seed()
def test_recording_scopes():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
            assert not ag.is_training()
        with ag.predict_mode():
            assert ag.is_recording()
            assert not ag.is_training()
    assert not ag.is_recording()
    with ag.train_mode():
        assert ag.is_training()
        assert not ag.is_recording()


@with_seed()
def test_grad_req_add_and_null():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = 3.0 * x
        y.backward()
    assert_almost_equal(x.grad, np.full(2, 9.0))

    z = nd.array([1.0])
    z.attach_grad(grad_req="null")
    with ag.record():
        w = z * 2
    w.backward()
    assert_almost_equal(z.grad, np.zeros(1))


@with_seed()
def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 4
    y.backward(nd.array([2.0, 3.0]))
    assert_almost_equal(x.grad, np.array([8.0, 12.0]))


@with_seed()
def test_detach_stops_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0]))  # d(4*x)/dx, y treated const


@with_seed()
def test_grad_function():
    x = nd.array(np.random.rand(4).astype(np.float32))
    x.attach_grad()
    with ag.record():
        y = nd.exp(x).sum()
    g = ag.grad(y, x)
    assert_almost_equal(g, np.exp(x.asnumpy()))
    # .grad untouched
    assert_almost_equal(x.grad, np.zeros(4))


@with_seed()
def test_multiple_heads_backward():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = x * 3
    ag.backward([y, z])
    assert_almost_equal(x.grad, np.full(2, 5.0))


@with_seed()
def test_mark_variables():
    x = nd.array([3.0])
    gbuf = nd.zeros((1,))
    ag.mark_variables([x], [gbuf])
    with ag.record():
        y = x * x
    y.backward()
    assert_almost_equal(gbuf, np.array([6.0]))


@with_seed()
def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.random.uniform(-2, 2, 5).astype(np.float32))
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


@with_seed()
def test_numeric_gradient_check():
    from mxnet_tpu.test_utils import check_numeric_gradient

    def f(a, b):
        return nd.sum(nd.dot(a, b) ** 2)

    a = nd.array(np.random.rand(3, 4).astype(np.float64))
    b = nd.array(np.random.rand(4, 2).astype(np.float64))
    check_numeric_gradient(f, [a, b], eps=1e-5, rtol=1e-4, atol=1e-5)


@with_seed()
def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, np.array([4.0]))
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


@with_seed()
def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    with ag.record(train_mode=False):
        y = nd.Dropout(x, p=0.5, train_mode=ag.is_training())
    assert_almost_equal(y, x.asnumpy())
    with ag.record():
        z = nd.Dropout(x, p=0.5, train_mode=ag.is_training())
    zn = z.asnumpy()
    assert 0.3 < (zn == 0).mean() < 0.7


@with_seed()
def test_inplace_op_keeps_tape_node():
    # regression: y *= 3 inside record must contribute to the gradient
    x = nd.array([1.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        y *= 3
    y.backward()
    assert_almost_equal(x.grad, np.array([6.0]))


@with_seed()
def test_setitem_preserves_leaf():
    # regression: slice-assign after attach_grad must not detach the leaf
    x = nd.zeros((3,))
    x.attach_grad()
    x[0] = 1.0
    with ag.record():
        y = x * 2
    y.backward()
    assert_almost_equal(x.grad, np.full(3, 2.0))


@with_seed()
def test_list_heads_with_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = x * 3
    ag.backward([y, z], [nd.ones((2,)), nd.ones((2,))])
    assert_almost_equal(x.grad, np.full(2, 5.0))
    import pytest

    with pytest.raises(ValueError):
        with ag.record():
            y = x * 2
            z = x * 3
        ag.backward([y, z], nd.ones((2,)))
