"""Autograd semantics (modeled on tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = x * x + 2.0
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


@with_seed()
def test_chain_and_broadcast():
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    w = nd.array(np.random.rand(5, 4).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = nd.dot(x, w, transpose_b=True)
        z = nd.sum(y * y)
    z.backward()
    y_np = x.asnumpy() @ w.asnumpy().T
    assert_almost_equal(x.grad, 2 * y_np @ w.asnumpy(), rtol=1e-4)
    assert_almost_equal(w.grad, 2 * y_np.T @ x.asnumpy(), rtol=1e-4)


@with_seed()
def test_recording_scopes():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
            assert not ag.is_training()
        with ag.predict_mode():
            assert ag.is_recording()
            assert not ag.is_training()
    assert not ag.is_recording()
    with ag.train_mode():
        assert ag.is_training()
        assert not ag.is_recording()


@with_seed()
def test_grad_req_add_and_null():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = 3.0 * x
        y.backward()
    assert_almost_equal(x.grad, np.full(2, 9.0))

    z = nd.array([1.0])
    z.attach_grad(grad_req="null")
    with ag.record():
        w = z * 2
    w.backward()
    assert_almost_equal(z.grad, np.zeros(1))


@with_seed()
def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 4
    y.backward(nd.array([2.0, 3.0]))
    assert_almost_equal(x.grad, np.array([8.0, 12.0]))


@with_seed()
def test_detach_stops_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0]))  # d(4*x)/dx, y treated const


@with_seed()
def test_grad_function():
    x = nd.array(np.random.rand(4).astype(np.float32))
    x.attach_grad()
    with ag.record():
        y = nd.exp(x).sum()
    g = ag.grad(y, x)
    assert_almost_equal(g, np.exp(x.asnumpy()))
    # .grad untouched
    assert_almost_equal(x.grad, np.zeros(4))


@with_seed()
def test_multiple_heads_backward():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = x * 3
    ag.backward([y, z])
    assert_almost_equal(x.grad, np.full(2, 5.0))


@with_seed()
def test_mark_variables():
    x = nd.array([3.0])
    gbuf = nd.zeros((1,))
    ag.mark_variables([x], [gbuf])
    with ag.record():
        y = x * x
    y.backward()
    assert_almost_equal(gbuf, np.array([6.0]))


@with_seed()
def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.random.uniform(-2, 2, 5).astype(np.float32))
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


@with_seed()
def test_numeric_gradient_check():
    from mxnet_tpu.test_utils import check_numeric_gradient

    def f(a, b):
        return nd.sum(nd.dot(a, b) ** 2)

    a = nd.array(np.random.rand(3, 4).astype(np.float64))
    b = nd.array(np.random.rand(4, 2).astype(np.float64))
    check_numeric_gradient(f, [a, b], eps=1e-5, rtol=1e-4, atol=1e-5)


@with_seed()
def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, np.array([4.0]))
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


@with_seed()
def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    with ag.record(train_mode=False):
        y = nd.Dropout(x, p=0.5, train_mode=ag.is_training())
    assert_almost_equal(y, x.asnumpy())
    with ag.record():
        z = nd.Dropout(x, p=0.5, train_mode=ag.is_training())
    zn = z.asnumpy()
    assert 0.3 < (zn == 0).mean() < 0.7


@with_seed()
def test_inplace_op_keeps_tape_node():
    # regression: y *= 3 inside record must contribute to the gradient
    x = nd.array([1.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        y *= 3
    y.backward()
    assert_almost_equal(x.grad, np.array([6.0]))


@with_seed()
def test_setitem_preserves_leaf():
    # regression: slice-assign after attach_grad must not detach the leaf
    x = nd.zeros((3,))
    x.attach_grad()
    x[0] = 1.0
    with ag.record():
        y = x * 2
    y.backward()
    assert_almost_equal(x.grad, np.full(3, 2.0))


@with_seed()
def test_list_heads_with_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = x * 3
    ag.backward([y, z], [nd.ones((2,)), nd.ones((2,))])
    assert_almost_equal(x.grad, np.full(2, 5.0))
    import pytest

    with pytest.raises(ValueError):
        with ag.record():
            y = x * 2
            z = x * 3
        ag.backward([y, z], nd.ones((2,)))


# ---------------------------------------------------------------------------
# higher-order gradients — grad(create_graph=True)
# (ref: python/mxnet/autograd.py — grad(create_graph); replay design in
# autograd._grad_create_graph)
# ---------------------------------------------------------------------------
import pytest  # noqa: E402

from mxnet_tpu import autograd  # noqa: E402


def test_create_graph_second_order_polynomial():
    # y = x^3  →  dy/dx = 3x^2, d2y/dx2 = 6x
    x = mx.nd.array(np.array([1.0, 2.0, -3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x ** 3
        (gx,) = [autograd.grad(y, x, create_graph=True)]
        z = (gx * gx).sum()
    z.backward()
    # dz/dx = 2 * (3x^2) * 6x = 36 x^3
    np.testing.assert_allclose(x.grad.asnumpy(),
                               36 * x.asnumpy() ** 3, rtol=1e-5)


def test_create_graph_grad_penalty_vs_torch():
    """Gradient-penalty double-backward against the torch oracle."""
    import torch
    rs = np.random.RandomState(3)
    Wn = rs.randn(4, 5).astype(np.float32)
    xn = rs.randn(2, 5).astype(np.float32)

    # torch oracle
    tw = torch.tensor(Wn, requires_grad=True)
    tx = torch.tensor(xn)
    ty = torch.tanh(tx @ tw.t()).sum()
    (tg,) = torch.autograd.grad(ty, tw, create_graph=True)
    tp = (tg ** 2).sum()
    tp.backward()
    oracle = tw.grad.numpy()

    W = mx.nd.array(Wn)
    W.attach_grad()
    x = mx.nd.array(xn)
    with autograd.record():
        y = mx.nd.tanh(mx.nd.dot(x, W.T)).sum()
        g = autograd.grad(y, W, create_graph=True)
        penalty = (g ** 2).sum()
    penalty.backward()
    np.testing.assert_allclose(W.grad.asnumpy(), oracle, rtol=1e-4,
                               atol=1e-6)


def test_create_graph_third_order():
    # y = x^4: y' = 4x^3, y'' = 12x^2, y''' = 24x
    x = mx.nd.array(np.array([1.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x ** 4
        g1 = autograd.grad(y, x, create_graph=True)
        g2 = autograd.grad(g1, x, create_graph=True)
        g3 = autograd.grad(g2, x, create_graph=True)
    np.testing.assert_allclose(g3.asnumpy(), [24 * 1.5], rtol=1e-5)


def test_create_graph_multi_variable_and_heads():
    a = mx.nd.array(np.array([2.0], np.float32)); a.attach_grad()
    b = mx.nd.array(np.array([3.0], np.float32)); b.attach_grad()
    with autograd.record():
        h1 = a * a * b          # d/da = 2ab, d/db = a^2
        h2 = a + b
        ga, gb = autograd.grad([h1, h2], [a, b], create_graph=True)
        s = (ga * gb).sum()     # (2ab+1)(a^2+1)
    s.backward()
    # ds/da = 2b(a^2+1) + 2a(2ab+1); ds/db = 2a(a^2+1)
    np.testing.assert_allclose(a.grad.asnumpy(),
                               [2*3*(4+1) + 2*2*(2*2*3+1)], rtol=1e-5)
    np.testing.assert_allclose(b.grad.asnumpy(), [2*2*(4+1)], rtol=1e-5)


def test_create_graph_through_dropout_replay_deterministic():
    """The replay must reuse the forward's PRNG keys: grad-of-grad through
    dropout is consistent with the sampled mask."""
    mx.random.seed(7)
    x = mx.nd.array(np.full((64,), 2.0, np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Dropout(x, p=0.5, mode="always")  # y = mask*x/keep
        s = (y * y).sum()
        g = autograd.grad(s, x, create_graph=True)  # 2*(mask/keep)^2*x
        z = g.sum()
    z.backward()
    # d z/dx = 2*(mask/keep)^2 — recover mask from y and compare
    mask_scaled = (y.asnumpy() / 2.0)  # mask/keep
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * mask_scaled ** 2,
                               rtol=1e-5)


def test_create_graph_unused_variable_zero_grad():
    a = mx.nd.array(np.array([1.0], np.float32)); a.attach_grad()
    b = mx.nd.array(np.array([5.0], np.float32)); b.attach_grad()
    with autograd.record():
        y = a * a
        ga, gb = autograd.grad(y, [a, b], create_graph=True)
    np.testing.assert_allclose(ga.asnumpy(), [2.0], rtol=1e-6)
    np.testing.assert_allclose(gb.asnumpy(), [0.0])


def test_create_graph_custom_function_raises():
    class Sq(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x
        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy
    x = mx.nd.array(np.array([2.0], np.float32)); x.attach_grad()
    with autograd.record():
        y = Sq()(x)
        with pytest.raises(NotImplementedError):
            autograd.grad(y, x, create_graph=True)


def test_first_order_grad_unchanged_after_create_graph():
    """create_graph leaves the tape intact: a later backward on the same
    head still works (implied retain)."""
    x = mx.nd.array(np.array([3.0], np.float32)); x.attach_grad()
    with autograd.record():
        y = x * x
        g = autograd.grad(y, x, create_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0], rtol=1e-6)
    np.testing.assert_allclose(g.asnumpy(), [6.0], rtol=1e-6)


def test_create_graph_cross_leaf_wgan_gp_vs_torch():
    """grad(y, x, create_graph=True) must stay differentiable w.r.t. the
    OTHER tracked leaves (W), not just x — the WGAN-GP pattern."""
    import torch
    rs = np.random.RandomState(11)
    Wn = rs.randn(3, 5).astype(np.float32)
    xn = rs.randn(4, 5).astype(np.float32)

    tW = torch.tensor(Wn, requires_grad=True)
    tx = torch.tensor(xn, requires_grad=True)
    ty = (tx @ tW.t()).tanh().sum()
    (tgx,) = torch.autograd.grad(ty, tx, create_graph=True)
    tp = ((tgx.norm(dim=1) - 1.0) ** 2).mean()
    tp.backward()
    oracle_W = tW.grad.numpy()

    W = mx.nd.array(Wn); W.attach_grad()
    x = mx.nd.array(xn); x.attach_grad()
    with autograd.record():
        y = mx.nd.tanh(mx.nd.dot(x, W.T)).sum()
        gx = autograd.grad(y, x, create_graph=True)
        p = ((mx.nd.sqrt((gx * gx).sum(axis=1)) - 1.0) ** 2).mean()
    p.backward()
    np.testing.assert_allclose(W.grad.asnumpy(), oracle_W,
                               rtol=1e-4, atol=1e-6)


def test_create_graph_after_reattach():
    """attach_grad() called again after the forward must not silently
    zero create_graph gradients (leaves match by array identity, like
    the first-order path)."""
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        x.attach_grad()  # fresh AGLeaf for the same array
        g = autograd.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.asnumpy(), [12.0], rtol=1e-6)
