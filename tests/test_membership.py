"""Elastic membership for the distributed KVStore (mxnet_tpu/membership.py
+ async_server.py membership ops): heartbeats/liveness, stale-push
fencing, elastic barrier/reduce degradation, rejoin with snapshot
handoff, and server-restart resync.

All fault scenarios run deterministically off seeded ``MXT_FAULT``
rules (hb_drop / worker_freeze / rejoin_race) with millisecond-scale
heartbeat and liveness windows — no test sleeps longer than the
configured liveness window; waits are bounded polls. ``MXT_CHAOS_SEED``
(set by tools/chaos_matrix.sh) re-seeds the injector RNGs per sweep.
"""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import async_server, membership, resilience
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore import KVStore
from mxnet_tpu.membership import (BarrierTimeout, MembershipTable,
                                  StaleWorkerError, WorkerMembership)
from mxnet_tpu.resilience import KVStoreError

# tiny, test-scale liveness windows: death is declared within ~4 missed
# beats; every bounded wait below is a multiple of this window
HB = 0.05
LIVENESS = 0.2
WINDOW = LIVENESS + 4 * HB  # one liveness window + reaper slack


def _seed():
    """Injector seed — swept by tools/chaos_matrix.sh via MXT_CHAOS_SEED."""
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _membership_env(monkeypatch):
    """Fast heartbeats, clean injectors, membership on."""
    monkeypatch.setenv("MXT_HEARTBEAT_INTERVAL", str(HB))
    monkeypatch.setenv("MXT_LIVENESS_TIMEOUT", str(LIVENESS))
    monkeypatch.delenv("MXT_FAULT", raising=False)
    monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
    monkeypatch.setenv("MXT_MEMBERSHIP", "1")
    resilience.reset_faults()
    yield
    resilience.reset_faults()


@pytest.fixture
def server():
    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    yield srv, srv._sock.getsockname()[1]
    srv.close()


def _wait_until(cond, deadline=None, msg="condition"):
    """Bounded poll (10ms ticks) — never an unconditional sleep."""
    deadline = 5 * WINDOW if deadline is None else deadline
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < deadline, \
            "timed out after %.2fs waiting for %s" % (deadline, msg)
        time.sleep(0.01)


def _member(port, wid, register=True, beats=True):
    m = WorkerMembership("127.0.0.1", port, wid)
    if register:
        m.register()
    if beats:
        m.start_heartbeats()
    return m


# ---------------------------------------------------------------------------
# membership table basics
# ---------------------------------------------------------------------------
def test_register_assigns_monotone_generations():
    tbl = MembershipTable()
    g0, e0, rejoin0 = tbl.register(0)
    g1, e1, _ = tbl.register(1)
    g0b, e2, rejoin0b = tbl.register(0)  # rejoin fences g0
    assert g0 < g1 < g0b
    assert e0 < e1 < e2
    assert not rejoin0 and rejoin0b
    tbl.check(0, g0b)
    with pytest.raises(StaleWorkerError, match="fenced"):
        tbl.check(0, g0)
    with pytest.raises(StaleWorkerError, match="not a registered member"):
        tbl.check(7, 1)


def test_generation_counter_survives_reset():
    """A store reset starts a new world but can never hand out a
    generation an old world already holds (fencing stays sound)."""
    tbl = MembershipTable()
    g0, _, _ = tbl.register(0)
    tbl.reset()
    g0b, _, _ = tbl.register(0)
    assert g0b > g0
    with pytest.raises(StaleWorkerError):
        tbl.check(0, g0)


def test_reap_marks_dead_and_bumps_epoch():
    tbl = MembershipTable()
    g0, _, _ = tbl.register(0, now=100.0)
    tbl.register(1, now=100.0)
    tbl.heartbeat(1, 2, now=105.0)
    dead = tbl.reap(timeout=3.0, now=105.5)  # w0 silent 5.5s, w1 fresh
    assert dead == [0]
    assert tbl.view()["dead"] == {0: g0}
    with pytest.raises(StaleWorkerError, match="declared dead"):
        tbl.heartbeat(0, g0, now=105.6)
    # idempotent: already-dead workers are not re-reaped
    assert tbl.reap(timeout=3.0, now=106.0) == []


def test_deregister_is_graceful_not_lost():
    tbl = MembershipTable()
    g0, _, _ = tbl.register(0)
    tbl.register(1)
    tbl.deregister(0, g0)
    v = tbl.view()
    assert 0 not in v["members"] and v["lost_total"] == 0
    # a zombie's stale deregister cannot evict the live replacement
    g1b, _, _ = tbl.register(1)
    tbl.deregister(1, g1b - 1)
    assert 1 in tbl.view()["members"]


# ---------------------------------------------------------------------------
# heartbeats + liveness over the wire
# ---------------------------------------------------------------------------
def test_heartbeat_thread_keeps_worker_alive(server):
    srv, port = server
    m = _member(port, 0)
    try:
        # survive several liveness windows on background beats alone
        # (8 beats ≈ 2 liveness windows of sustained beating)
        _wait_until(lambda: m._beats >= 8, msg="8 beats")
        assert 0 in m.members()["members"]
        assert not m.fenced
    finally:
        m.stop()


@pytest.mark.chaos
def test_hb_drop_within_budget_survives(monkeypatch, server):
    """A capped burst of lost heartbeats (n=2 < the miss window) must
    not get the worker declared dead."""
    srv, port = server
    monkeypatch.setenv("MXT_FAULT",
                       "hb_drop:p=1.0,n=2,seed=%d" % _seed())
    resilience.reset_faults()
    m = _member(port, 0)
    try:
        _wait_until(lambda: m._beats >= 5, msg="beats past the drop burst")
        assert 0 in m.members()["members"]
    finally:
        m.stop()


@pytest.mark.chaos
def test_hb_drop_sustained_gets_reaped(monkeypatch, server):
    """Heartbeats lost on the wire forever = death within one liveness
    window, surfaced in the lost_workers profiler counter."""
    srv, port = server
    lost0 = membership.lost_worker_count()
    monkeypatch.setenv("MXT_FAULT", "hb_drop:p=1.0,seed=%d" % _seed())
    resilience.reset_faults()
    m = _member(port, 0)
    probe = _member(port, 1)  # keeps its own beats (hb_drop is global —
    # but worker 1's membership view probe rides the ctl client, not
    # beats, so it can observe worker 0's death even while its own
    # beats drop; both end up reaped, we assert on worker 0)
    try:
        _wait_until(lambda: 0 in probe.members()["dead"],
                    msg="worker 0 reaped")
        assert membership.lost_worker_count() > lost0
    finally:
        m.stop(deregister=False)
        probe.stop(deregister=False)


# ---------------------------------------------------------------------------
# the acceptance scenario: freeze → fence zombie → rejoin with snapshot
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_worker_death_fencing_and_rejoin(monkeypatch, server):
    """3-worker dist_async membership, worker 2 freezes mid-epoch
    (seeded MXT_FAULT worker_freeze): (a) survivors keep making
    progress within one liveness window, (b) the zombie's delayed
    in-flight push is rejected with StaleWorkerError, (c) the respawned
    worker rejoins after snapshot handoff and its pushes are accepted."""
    srv, port = server
    monkeypatch.setenv(
        "MXT_FAULT",
        "worker_freeze:worker=2,after=1,p=1.0,seed=%d" % _seed())
    resilience.reset_faults()

    members = [_member(port, i) for i in range(3)]
    clients = []
    for m in members:
        c = async_server.AsyncClient("127.0.0.1", port)
        c.set_credentials(m.worker_id, m.generation)
        clients.append(c)
    old_gen2 = members[2].generation
    try:
        # every worker initializes + pushes once (the "epoch" begins)
        clients[0].request("init", "w", np.zeros((4,), np.float32))
        for i, c in enumerate(clients):
            c.request("push", "w", np.full((4,), i + 1.0, np.float32))

        # worker 2's heartbeat thread freezes itself via the injector
        _wait_until(lambda: members[2].frozen, msg="worker 2 freeze")
        t_freeze = time.monotonic()
        _wait_until(lambda: 2 in members[0].members()["dead"],
                    msg="worker 2 declared dead")

        # (a) survivors make progress within one liveness window of the
        # detection: pushes land and a live-member barrier releases
        # without worker 2
        for i in (0, 1):
            clients[i].request("push", "w",
                               np.full((4,), 10.0 + i, np.float32))
        res = []

        def arrive(i):
            res.append(members[i].barrier("progress", timeout=WINDOW))

        ths = [threading.Thread(target=arrive, args=(i,)) for i in (0, 1)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(2 * WINDOW)
        assert len(res) == 2, "survivor barrier did not release"
        assert time.monotonic() - t_freeze < LIVENESS + 3 * WINDOW

        # (b) the zombie's delayed in-flight push: its PROCESS is alive,
        # its data connection is open, but its generation is fenced
        with pytest.raises(StaleWorkerError, match="declared dead"):
            clients[2].request("push", "w",
                               np.full((4,), 666.0, np.float32))
        # server-side weight untouched by the zombie
        assert clients[0].request("pull", "w")[0] != 666.0

        # (c) respawn: a fresh incarnation of worker 2 re-registers,
        # receives the current epoch + a CRC-verified snapshot, and may
        # push again under its new generation
        w2 = WorkerMembership("127.0.0.1", port, 2)
        w2.register(want_snapshot=True)
        try:
            assert w2.generation > old_gen2
            assert w2.epoch == members[0].members()["epoch"]
            snap = w2.snapshot
            assert snap is not None and "w" in snap["weights"]
            np.testing.assert_array_equal(
                snap["weights"]["w"], clients[0].request("pull", "w"))
            w2.start_heartbeats()
            c2 = async_server.AsyncClient("127.0.0.1", port)
            c2.set_credentials(2, w2.generation)
            c2.request("push", "w", np.full((4,), 5.0, np.float32))
            np.testing.assert_array_equal(
                clients[0].request("pull", "w"), np.full((4,), 5.0))
            # and the old zombie stays fenced even after the rejoin
            with pytest.raises(StaleWorkerError, match="fenced"):
                clients[2].request("push", "w",
                                   np.full((4,), 667.0, np.float32))
            c2.close()
        finally:
            w2.stop(deregister=False)
    finally:
        for m in members:
            m.stop(deregister=False)
        for c in clients:
            c.close()


@pytest.mark.chaos
def test_rejoin_race_zombie_fenced_during_handoff(monkeypatch, server):
    """A zombie push racing the re-registration window (widened by the
    seeded rejoin_race rule) must be refused: the old generation is
    fenced BEFORE the rejoin reply is sent."""
    srv, port = server
    m = _member(port, 0, beats=False)
    old_gen = m.generation
    zombie = async_server.AsyncClient("127.0.0.1", port)
    zombie.set_credentials(0, old_gen)
    zombie.request("init", "w", np.ones((2,), np.float32))

    monkeypatch.setenv("MXT_FAULT",
                       "rejoin_race:ms=80,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    fresh = WorkerMembership("127.0.0.1", port, 0)
    errs = []

    def rejoin():
        fresh.register(want_snapshot=True)

    th = threading.Thread(target=rejoin)
    th.start()
    # fire the zombie push inside the widened handoff window
    time.sleep(0.02)
    try:
        zombie.request("push", "w", np.full((2,), 9.0, np.float32))
    except StaleWorkerError as e:
        errs.append(e)
    th.join(5.0)
    try:
        assert errs, "zombie push during rejoin window was accepted"
        assert fresh.generation > old_gen
        np.testing.assert_array_equal(
            fresh.snapshot["weights"]["w"], np.ones((2,)))
        # the rejoined incarnation pushes fine
        c = async_server.AsyncClient("127.0.0.1", port)
        c.set_credentials(0, fresh.generation)
        c.request("push", "w", np.full((2,), 2.0, np.float32))
        c.close()
    finally:
        fresh.stop(deregister=False)
        m.stop(deregister=False)
        zombie.close()


def test_unregistered_mutation_refused_when_membership_active(server):
    """With membership active, a credential-free connection may read but
    not mutate: a restarted-but-unregistered worker cannot corrupt the
    store."""
    srv, port = server
    m = _member(port, 0, beats=False)
    cred = async_server.AsyncClient("127.0.0.1", port)
    cred.set_credentials(0, m.generation)
    cred.request("init", "w", np.ones((2,), np.float32))
    bare = async_server.AsyncClient("127.0.0.1", port)
    try:
        with pytest.raises(StaleWorkerError, match="unregistered"):
            bare.request("push", "w", np.zeros((2,), np.float32))
        # reads stay open (pull is how a rejoiner resyncs)
        np.testing.assert_array_equal(bare.request("pull", "w"),
                                      np.ones((2,)))
        # ... and with no members registered, bare stores keep working
        # (single-host rigs, pre-membership flows)
        m.stop()  # deregisters: table empties
        _wait_until(lambda: not srv.membership.has_members(),
                    msg="table empty")
        bare.request("push", "w", np.zeros((2,), np.float32))
    finally:
        bare.close()
        cred.close()


# ---------------------------------------------------------------------------
# elastic degradation: barrier + reduce over survivors
# ---------------------------------------------------------------------------
def test_barrier_excludes_dead_and_times_out_on_live(server):
    srv, port = server
    ms = [_member(port, i) for i in range(2)]
    try:
        # both live and only one arrives → bounded KVStoreError, no hang.
        # The match pins the SERVER's typed timeout reply: the transport
        # deadline is rendezvous + margin, so the server's answer wins
        # the race against a client-side retry (which would park a
        # duplicate waiter and inflate the effective deadline).
        t0 = time.monotonic()
        with pytest.raises(KVStoreError, match="waiting on live workers"):
            ms[0].barrier("lonely", timeout=WINDOW)
        assert time.monotonic() - t0 < 3 * WINDOW
        # the timed-out round left no bookkeeping behind
        _wait_until(lambda: not srv.membership._barriers,
                    msg="barrier table drained")
        # kill worker 1's beats: after death, a solo barrier releases
        ms[1]._stop.set()
        _wait_until(lambda: 1 in ms[0].members()["dead"],
                    msg="worker 1 reaped")
        assert isinstance(ms[0].barrier("solo", timeout=WINDOW), int)
    finally:
        for m in ms:
            m.stop(deregister=False)


def test_elastic_reduce_renormalizes_over_survivors(monkeypatch, server):
    """KVStore dist path: a 3-worker elastic sum where worker 2 dies
    mid-epoch degrades to the survivors, renormalized by
    num_workers/len(survivors), and surfaces in lost_workers()."""
    srv, port = server
    monkeypatch.setattr(KVStore, "num_workers",
                        property(lambda self: 3))
    ms = [_member(port, i) for i in range(3)]
    kvs = []
    for i in range(3):
        kv = KVStore("dist_sync")
        kv.attach_membership(ms[i])
        kvs.append(kv)
    try:
        from mxnet_tpu import nd

        # round 1: all three contribute — plain sum, no renormalization
        outs = {}

        def push_round(i, value):
            kvs[i].init("g", nd.zeros((2,)))
            kvs[i].push("g", nd.full((2,), value))
            out = nd.zeros((2,))
            kvs[i].pull("g", out=out)
            outs[i] = out.asnumpy()

        ths = [threading.Thread(target=push_round, args=(i, i + 1.0))
               for i in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10 * WINDOW)
        for i in range(3):
            np.testing.assert_allclose(outs[i], 6.0)  # 1+2+3

        # worker 2 dies; survivors' round releases within the liveness
        # window and the sum 1+2=3 renormalizes to 3 * (3/2) = 4.5
        ms[2]._stop.set()
        _wait_until(lambda: 2 in ms[0].members()["dead"],
                    msg="worker 2 reaped")
        outs.clear()
        ths = [threading.Thread(target=push_round, args=(i, i + 1.0))
               for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10 * WINDOW)
        for i in range(2):
            np.testing.assert_allclose(outs[i], 4.5)
        assert kvs[0].lost_workers() == 0 or True  # cached on next beat
        _wait_until(lambda: kvs[0].lost_workers() >= 1,
                    msg="lost_workers heartbeat cache")
    finally:
        for m in ms:
            m.stop(deregister=False)


def test_reduce_is_idempotent_per_worker(server):
    """At-least-once delivery: a re-sent contribution (retry after a
    drop) must not double-count."""
    srv, port = server
    ms = [_member(port, i, beats=False) for i in range(2)]
    try:
        out = {}

        def contribute(i, repeat):
            for _ in range(repeat):
                out[i] = ms[i].reduce("k", 1, np.ones((2,), np.float32),
                                      timeout=5.0)

        t0 = threading.Thread(target=contribute, args=(0, 1))
        t1 = threading.Thread(target=contribute, args=(1, 1))
        t0.start()
        t1.start()
        t0.join(10.0)
        t1.join(10.0)
        total, wids = out[0]
        np.testing.assert_allclose(total, 2.0)
        assert wids == [0, 1]
    finally:
        for m in ms:
            m.stop(deregister=False)


def test_barrier_duplicate_waiter_refcount_and_replay():
    """Review fix: a client-retry duplicate waiter for the same
    (tag, worker) must not leak bookkeeping — cleanup is refcounted by
    WAITER, not by arrived-worker count — and a retry arriving AFTER
    the round released is acked immediately instead of recreating the
    entry (which leaked forever: tags are never reused)."""
    tbl = MembershipTable()
    g0, _, _ = tbl.register(0)
    g1, _, _ = tbl.register(1)
    done = []

    def wait0():
        done.append(tbl.barrier(0, g0, "t:1", timeout=5.0))

    dups = [threading.Thread(target=wait0) for _ in range(2)]
    for t in dups:
        t.start()
    _wait_until(lambda: tbl._barriers.get("t:1", {}).get("waiters") == 2,
                msg="duplicate waiters parked")
    done.append(tbl.barrier(1, g1, "t:1", timeout=5.0))
    for t in dups:
        t.join(5.0)
    assert len(done) == 3
    assert tbl._barriers == {}, "waiter refcount leaked an entry"
    # at-least-once replay: the released tag acks immediately
    t0 = time.monotonic()
    tbl.barrier(0, g0, "t:1", timeout=5.0)
    assert time.monotonic() - t0 < 1.0


def test_reduce_replay_after_release_and_stale_seq_refused():
    """Review fix: a reduce frame retried after its round was popped
    used to open a fresh solo round and wait out the full timeout — it
    now replays the released result; a frame older than the last
    released round is refused with a typed error."""
    tbl = MembershipTable()
    g0, _, _ = tbl.register(0)
    g1, _, _ = tbl.register(1)
    out = {}

    def contribute(i, g):
        out[i] = tbl.reduce(i, g, "k", 2, np.ones((2,), np.float32),
                            timeout=5.0)

    ths = [threading.Thread(target=contribute, args=a)
           for a in ((0, g0), (1, g1))]
    for t in ths:
        t.start()
    for t in ths:
        t.join(5.0)
    np.testing.assert_allclose(out[0][0], 2.0)
    assert tbl._reduces == {}, "reduce round leaked an entry"
    # replay: the released round answers immediately with its result
    t0 = time.monotonic()
    total, wids = tbl.reduce(0, g0, "k", 2, np.ones((2,), np.float32),
                             timeout=5.0)
    assert time.monotonic() - t0 < 1.0
    np.testing.assert_allclose(total, 2.0)
    assert wids == [0, 1]
    # a zombie frame for an already-finished older round is refused
    with pytest.raises(BarrierTimeout, match="older"):
        tbl.reduce(0, g0, "k", 1, np.ones((2,), np.float32), timeout=5.0)


def test_rejoined_worker_resumes_rendezvous_seqs(monkeypatch, server):
    """Review fix: a respawned worker's KVStore used to restart its
    barrier/reduce counters at 0 and could never match the survivors'
    rounds again; the rejoin snapshot now carries the server-issued
    last released sequence numbers and the fresh store fast-forwards."""
    srv, port = server
    monkeypatch.setattr(KVStore, "num_workers",
                        property(lambda self: 2))
    from mxnet_tpu import nd

    ms = [_member(port, i) for i in range(2)]
    kvs = []
    for i in range(2):
        kv = KVStore("dist_sync")
        kv.attach_membership(ms[i])
        kvs.append(kv)

    def one_round(kv, value, outs):
        kv.init("g", nd.zeros((2,)))
        kv.push("g", nd.full((2,), value))
        o = nd.zeros((2,))
        kv.pull("g", out=o)
        outs.append(o.asnumpy())
        kv._barrier()

    try:
        outs = []
        ths = [threading.Thread(target=one_round, args=(kvs[i], i + 1.0,
                                                        outs))
               for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10 * WINDOW)
        assert len(outs) == 2
        for o in outs:
            np.testing.assert_allclose(o, 3.0)  # 1+2

        # "respawn" worker 1: its old incarnation stops, a fresh one
        # re-registers (rejoin) and a FRESH KVStore adopts the
        # server-issued seqs from the snapshot
        ms[1].stop(deregister=False)
        m1b = WorkerMembership("127.0.0.1", port, 1)
        m1b.register(want_snapshot=True)
        m1b.start_heartbeats()
        ms.append(m1b)
        kv1b = KVStore("dist_sync")
        kv1b.attach_membership(m1b)
        assert kv1b._barrier_seq == kvs[0]._barrier_seq
        assert kv1b._reduce_seq.get("g") == kvs[0]._reduce_seq.get("g")

        # and a joint round with the survivor actually completes:
        # matching (key, seq) and matching barrier tags
        outs2 = []
        ths = [threading.Thread(target=one_round, args=(kv, v, outs2))
               for kv, v in ((kvs[0], 5.0), (kv1b, 7.0))]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10 * WINDOW)
        assert len(outs2) == 2, "rejoined round never released"
        for o in outs2:
            np.testing.assert_allclose(o, 12.0)
    finally:
        for m in ms:
            m.stop(deregister=False)


# ---------------------------------------------------------------------------
# KVStore barrier deadline (works with membership DISABLED too)
# ---------------------------------------------------------------------------
def test_kvstore_barrier_deadline_without_membership(monkeypatch):
    """Satellite: the jax.distributed barrier path gets the RetryPolicy
    deadline treatment — a never-arriving peer raises KVStoreError
    instead of hanging forever."""
    monkeypatch.setenv("MXT_BARRIER_TIMEOUT", "0.2")
    monkeypatch.setattr(KVStore, "num_workers",
                        property(lambda self: 2))
    never = threading.Event()  # a peer that will never arrive

    def hang_forever(tag):
        never.wait()

    from jax.experimental import multihost_utils

    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        hang_forever)
    kv = KVStore("dist_sync")
    assert kv._member is None
    t0 = time.monotonic()
    with pytest.raises(KVStoreError, match="deadline"):
        kv._barrier()
    assert time.monotonic() - t0 < 5.0
    never.set()


def test_kvstore_barrier_propagates_collective_errors(monkeypatch):
    monkeypatch.setattr(KVStore, "num_workers",
                        property(lambda self: 2))

    def boom(tag):
        raise RuntimeError("collective exploded")

    from jax.experimental import multihost_utils

    monkeypatch.setattr(multihost_utils, "sync_global_devices", boom)
    kv = KVStore("dist_sync")
    with pytest.raises(RuntimeError, match="collective exploded"):
        kv._barrier()


# ---------------------------------------------------------------------------
# server restart detection + resync (satellite)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_server_bounce_detected_and_resynced(monkeypatch):
    """A server restarted mid-run presents a new boot id: the client's
    reconnect detects it, runs the resync hook (membership
    re-registration), and the retried frame lands under fresh
    credentials instead of desyncing against stale expectations."""
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.01")
    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    m = _member(port, 0)
    cli = async_server.AsyncClient("127.0.0.1", port)
    cli.set_credentials(0, m.generation)
    resyncs = []

    def on_restart(c):
        m.re_register()
        c.set_credentials(m.worker_id, m.generation)
        resyncs.append(m.generation)

    cli.on_server_restart = on_restart
    cli.request("init", "w", np.ones((2,), np.float32))

    # bounce: tear the instance down, bind a fresh one on the same port
    # (plus an injected drop so the reconnect path is exercised even if
    # the OS delivered the close lazily)
    srv.close()
    monkeypatch.setenv("MXT_FAULT", "kv_drop:p=1.0,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    deadline = time.monotonic() + 10.0
    while True:
        try:
            srv2 = async_server.AsyncParamServer("127.0.0.1", port)
            break
        except OSError:
            assert time.monotonic() < deadline, "port never freed"
            time.sleep(0.05)
    try:
        cli.request("push", "w", np.full((2,), 3.0, np.float32))
        assert cli.server_restarts == 1
        assert resyncs, "resync hook never ran"
        np.testing.assert_array_equal(cli.request("pull", "w"),
                                      np.full((2,), 3.0))
        # heartbeats resumed against the new instance
        _wait_until(lambda: 0 in m.members()["members"],
                    msg="re-registered on new instance")
    finally:
        m.stop(deregister=False)
        cli.close()
        srv2.close()


def _rebind(port, deadline=10.0):
    """Bind a fresh server instance on a just-freed port (bounded)."""
    t0 = time.monotonic()
    while True:
        try:
            return async_server.AsyncParamServer("127.0.0.1", port)
        except OSError:
            assert time.monotonic() - t0 < deadline, "port never freed"
            time.sleep(0.05)


@pytest.mark.chaos
def test_server_restart_resync_restores_optimizer_and_weights(monkeypatch):
    """Review fix (high): a bounced server boots with an empty store and
    no optimizer — the resync hook must restore BOTH before the
    survivor's retried frame lands, else the retried push takes the
    first-push-initializes branch (a raw gradient becomes the weight)
    and every later push replaces instead of updating: silent
    corruption while training appears to continue."""
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.01")
    from mxnet_tpu import nd, optimizer

    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    m = _member(port, 0)
    kv = KVStore("local")
    kv._type = "dist_async"
    kv._async = async_server.AsyncClient("127.0.0.1", port)
    kv.attach_membership(m)
    kv.set_optimizer(optimizer.SGD(learning_rate=1.0))
    kv.init("w", nd.full((2,), 10.0))
    kv.push("w", nd.ones((2,)))      # SGD lr=1: w = 10 - 1 = 9
    out = nd.zeros((2,))
    kv.pull("w", out=out)            # shadow caches the observed 9.0
    np.testing.assert_allclose(out.asnumpy(), 9.0)

    srv.close()
    srv2 = _rebind(port)
    try:
        kv.push("w", nd.full((2,), 2.0))  # retried against the restart
        assert kv._async.server_restarts == 1
        kv.pull("w", out=out)
        # restored weight 9 updated BY the gradient: 9 - 2 = 7 — not the
        # raw gradient 2.0 (first-push-initializes) and not a replace
        # to 2.0 (lost optimizer)
        np.testing.assert_allclose(out.asnumpy(), 7.0)
    finally:
        m.stop(deregister=False)
        kv._async.close()
        srv2.close()


@pytest.mark.chaos
def test_server_restart_without_resync_refuses_mutation(monkeypatch):
    """Review fix (high): with NO resync hook installed, a retried
    mutating op against a restarted (empty) server fails loudly with
    KVStoreError instead of silently installing a gradient as the
    weight; an explicit re-registration + set_credentials clears the
    fence."""
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.01")
    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    cli = async_server.AsyncClient("127.0.0.1", port)
    cli.request("init", "w", np.ones((2,), np.float32))
    srv.close()
    srv2 = _rebind(port)
    m = None
    try:
        with pytest.raises(KVStoreError, match="RESTARTED"):
            cli.request("push", "w", np.full((2,), 3.0, np.float32))
        assert not srv2._store, "the fenced push still mutated the store"
        # reads stay open (a recovery path needs them) — the empty
        # store answers with a typed error, not corruption
        with pytest.raises(MXNetError, match="not initialized"):
            cli.request("pull", "w")
        # explicit rejoin acknowledges the new world and clears the fence
        m = WorkerMembership("127.0.0.1", port, 0).register()
        cli.set_credentials(0, m.generation)
        cli.request("push", "w", np.full((2,), 3.0, np.float32))
        np.testing.assert_array_equal(cli.request("pull", "w"),
                                      np.full((2,), 3.0))
    finally:
        if m is not None:
            m.stop(deregister=False)
        cli.close()
        srv2.close()


def test_rank0_respawn_rejoins_live_world_instead_of_reset(monkeypatch):
    """Review fix: a respawned rank 0 (tools/launch.py --respawn keeps
    MXT_WORKER_ID=0) must treat a membership table with live members as
    a RUNNING world and rejoin it — its old 'reset' wiped the live
    store and fenced every survivor with an unrecoverable
    StaleWorkerError. And when the coordinator port is already served
    (standalone kvstore_server), rank 0 falls back to a plain client
    instead of dying with EADDRINUSE."""
    import itertools

    from mxnet_tpu import kvstore as kvmod

    srv = async_server.AsyncParamServer("127.0.0.1", 0)  # standalone
    port = srv._sock.getsockname()[1]
    monkeypatch.setenv(
        "MXT_COORDINATOR",
        "127.0.0.1:%d" % (port - async_server.ASYNC_PORT_OFFSET))
    monkeypatch.setattr(KVStore, "num_workers",
                        property(lambda self: 2))
    # a respawned process is creating its FIRST store
    monkeypatch.setattr(kvmod, "_async_world_counter", itertools.count(1))

    # the surviving world: worker 1 registered and store populated
    m1 = _member(port, 1)
    c1 = async_server.AsyncClient("127.0.0.1", port)
    c1.set_credentials(1, m1.generation)
    c1.request("init", "w", np.full((2,), 4.0, np.float32))
    kv = None
    try:
        kv = KVStore("dist_async")  # the respawned rank 0
        assert kv._async is not None, "async mode did not engage"
        assert kv._async_server is None, "re-hosted an occupied port"
        assert srv._store, "rank-0 respawn reset wiped the live store"
        np.testing.assert_array_equal(kv._async.request("pull", "w"),
                                      np.full((2,), 4.0))
        # the survivor's generation is still honored (not fenced)
        c1.request("push", "w", np.full((2,), 6.0, np.float32))
        # and rank 0 itself is a registered member of the live world
        assert 0 in m1.members()["members"]
    finally:
        if kv is not None and kv._member is not None:
            kv._member.stop(deregister=False)
        if kv is not None and kv._async is not None:
            kv._async.close()
        m1.stop(deregister=False)
        c1.close()
        srv.close()


# ---------------------------------------------------------------------------
# estimator event
# ---------------------------------------------------------------------------
def test_estimator_workers_lost_event():
    """The estimator surfaces membership deaths as a workers_lost event
    driven by the kvstore's heartbeat-cached lost count."""
    from mxnet_tpu import nd
    from mxnet_tpu.gluon import Trainer, loss as gloss, nn
    from mxnet_tpu.gluon.contrib.estimator import Estimator, EventHandler

    class _Recorder(EventHandler):
        def __init__(self):
            self.fired = []

        def workers_lost(self, estimator):
            self.fired.append(estimator.lost_workers)

    class _FakeKV:
        """Stands in for a dist kvstore whose reaper declared a death
        after the first batch."""

        type = "local"

        def __init__(self):
            self.calls = 0

        def init(self, key, value):
            pass

        def push(self, key, value, priority=0):
            pass

        def pull(self, key, out=None, priority=0, ignore_sparse=True):
            pass

        def lost_workers(self):
            self.calls += 1
            return 0 if self.calls < 2 else 1

    mx.random.seed(0)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    est = Estimator(net, gloss.L2Loss(), trainer=tr)
    rec = _Recorder()
    tr._kvstore = _FakeKV()
    tr._kv_initialized = True  # keep step() from re-resolving the store
    tr._update_on_kvstore = False
    rng = np.random.RandomState(0)
    data = [(nd.array(rng.uniform(-1, 1, (4, 4)).astype(np.float32)),
             nd.array(rng.uniform(-1, 1, (4, 2)).astype(np.float32)))
            for _ in range(3)]
    est.fit(data, epochs=1, event_handlers=[rec])
    assert rec.fired == [1]  # fired exactly once, at the transition
    assert est.lost_workers == 1


# ---------------------------------------------------------------------------
# snapshot integrity
# ---------------------------------------------------------------------------
def test_snapshot_crc_verification():
    good = {"weights": {"w": np.ones((2, 2), np.float32)}}
    good["crc32"] = membership.snapshot_checksums(good["weights"])
    assert membership.verify_snapshot(good) is good
    bad = {"weights": {"w": np.zeros((2, 2), np.float32)},
           "crc32": good["crc32"]}
    with pytest.raises(MXNetError, match="CRC"):
        membership.verify_snapshot(bad)
    assert membership.verify_snapshot(None) is None


# ---------------------------------------------------------------------------
# teardown order: graceful deregister is best-effort and SHORT-bounded
# ---------------------------------------------------------------------------
def test_deregister_bounded_after_coordinator_close():
    """The PR 10 teardown-order gotcha, generalized: closing a
    coordinator BEFORE its dependents used to cost each dependent's
    graceful deregister a full transport deadline (the reconnect spun
    out the handle's whole connect timeout). Deregister is now
    best-effort under membership._DEREGISTER_DEADLINE — a reversed
    close order costs ~2s per handle, not 30s, and never raises."""
    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    wm = membership.WorkerMembership("127.0.0.1", port, 7, timeout=30.0)
    wm.register()
    srv.close()                      # the coordinator dies FIRST
    t0 = time.monotonic()
    wm.stop(deregister=True)         # must not park for ~timeout
    dt = time.monotonic() - t0
    assert dt < 4 * membership._DEREGISTER_DEADLINE, \
        "deregister against a dead coordinator took %.1fs" % dt
    # the bound is per-stop, so closing N dependents after the
    # coordinator is N * ~2s, not N * 30s; and a LIVE coordinator
    # still deregisters gracefully (fast path unaffected)
    srv2 = async_server.AsyncParamServer("127.0.0.1", 0)
    port2 = srv2._sock.getsockname()[1]
    wm2 = membership.WorkerMembership("127.0.0.1", port2, 8)
    wm2.register()
    assert 8 in srv2.membership.live_ids()
    wm2.stop(deregister=True)
    assert 8 not in srv2.membership.live_ids()
    srv2.close()
