"""Async parameter-server trust boundary (threat model in
async_server.py docstring; ref: ps-lite ``Van`` membership — the
reference's only admission control was the network perimeter)."""
import socket
import struct

import numpy as np
import pytest

from mxnet_tpu import async_server
from mxnet_tpu.base import MXNetError


@pytest.fixture
def secret_env(monkeypatch):
    monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_nonloopback_bind_refused_without_secret(monkeypatch):
    monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
    for host in ("0.0.0.0", ""):  # "" binds INADDR_ANY too
        with pytest.raises(MXNetError, match="MXT_KVSTORE_SECRET"):
            async_server.AsyncParamServer(host, _free_port())


def test_nonloopback_bind_allowed_with_secret(secret_env):
    srv = async_server.AsyncParamServer("0.0.0.0", _free_port())
    srv.close()


def test_authenticated_roundtrip(secret_env):
    port = _free_port()
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        cli = async_server.AsyncClient("127.0.0.1", port)
        cli.request("init", "w", np.ones((2, 2), np.float32))
        out = cli.request("pull", "w")
        np.testing.assert_array_equal(out, np.ones((2, 2)))
        cli.close()
    finally:
        srv.close()


def test_tampered_frame_rejected(secret_env):
    """Flip one payload byte after the MAC is computed: the server must
    drop the connection without answering (and without unpickling)."""
    port = _free_port()
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        cli = async_server.AsyncClient("127.0.0.1", port)
        import pickle
        payload = pickle.dumps(("pull", "w", None))
        mac = cli._ch._mac(b"C", 0, payload)  # valid MAC for this payload
        bad = bytearray(payload)
        bad[-1] ^= 0xFF
        cli._sock.sendall(struct.pack("!Q", len(bad)) + mac + bytes(bad))
        # server drops the connection: the next read hits EOF
        cli._sock.settimeout(5.0)
        assert cli._sock.recv(1) == b""
        cli.close()
    finally:
        srv.close()


def test_replayed_frame_rejected(secret_env):
    """A frame captured from one connection fails on another (nonce) and
    a re-sent frame fails within a connection (sequence)."""
    port = _free_port()
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        cli = async_server.AsyncClient("127.0.0.1", port)
        cli.request("init", "w", np.zeros((1,), np.float32))
        # re-send the exact bytes of the last frame (seq now stale)
        import pickle
        payload = pickle.dumps(("init", "w", np.zeros((1,), np.float32)),
                               protocol=pickle.HIGHEST_PROTOCOL)
        mac = cli._ch._mac(b"C", 0, payload)  # seq 0 already consumed
        cli._sock.sendall(struct.pack("!Q", len(payload)) + mac + payload)
        cli._sock.settimeout(5.0)
        assert cli._sock.recv(1) == b""  # dropped
        cli.close()
    finally:
        srv.close()


def test_wrong_secret_rejected(monkeypatch):
    port = _free_port()
    monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        monkeypatch.setenv("MXT_KVSTORE_SECRET", "attacker-guess")
        cli = async_server.AsyncClient("127.0.0.1", port)
        with pytest.raises((MXNetError, ConnectionError)):
            cli.request("pull", "w")
        cli.close()
    finally:
        monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")
        srv.close()


def test_secret_presence_mismatch_is_clean_error(monkeypatch):
    """Server-with-secret + client-without (and vice versa) must error at
    connect, not hang in a desynced frame protocol."""
    port = _free_port()
    monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
        with pytest.raises(MXNetError, match="requires frame auth"):
            async_server.AsyncClient("127.0.0.1", port)
    finally:
        monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")
        srv.close()

    port2 = _free_port()
    monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
    srv2 = async_server.AsyncParamServer("127.0.0.1", port2)
    try:
        monkeypatch.setenv("MXT_KVSTORE_SECRET", "test-secret-r5")
        with pytest.raises(MXNetError, match="downgrade"):
            async_server.AsyncClient("127.0.0.1", port2)
    finally:
        monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
        srv2.close()


def test_unauthenticated_localhost_still_works(monkeypatch):
    """Single-host rigs (no secret) keep working on loopback."""
    monkeypatch.delenv("MXT_KVSTORE_SECRET", raising=False)
    port = _free_port()
    srv = async_server.AsyncParamServer("127.0.0.1", port)
    try:
        cli = async_server.AsyncClient("127.0.0.1", port)
        cli.request("init", 3, np.full((2,), 7.0, np.float32))
        np.testing.assert_array_equal(cli.request("pull", 3),
                                      np.full((2,), 7.0))
        cli.close()
    finally:
        srv.close()
