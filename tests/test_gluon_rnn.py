"""gluon.rnn tests (modeled on tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import rnn
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


@with_seed()
@pytest.mark.parametrize("mode,cls", [
    ("rnn", rnn.RNN), ("lstm", rnn.LSTM), ("gru", rnn.GRU)])
def test_layer_forward_shapes(mode, cls):
    layer = cls(hidden_size=16, num_layers=2)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 3, 8))  # (T, N, C)
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(batch_size=3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert len(new_states) == len(states)
    for s in new_states:
        assert s.shape == (2, 3, 16)


@with_seed()
def test_layer_ntc_layout():
    layer = rnn.LSTM(hidden_size=8, layout="NTC")
    layer.initialize()
    x = nd.random.uniform(shape=(4, 6, 5))  # (N, T, C)
    out = layer(x)
    assert out.shape == (4, 6, 8)


@with_seed()
def test_layer_bidirectional_shapes():
    layer = rnn.GRU(hidden_size=12, num_layers=2, bidirectional=True)
    layer.initialize()
    x = nd.random.uniform(shape=(7, 2, 4))
    out, states = layer(x, layer.begin_state(2))
    assert out.shape == (7, 2, 24)
    assert states[0].shape == (4, 2, 12)


@with_seed()
def test_lstm_layer_vs_cell_unroll():
    """Fused packed-weight layer must agree with the step-level cell."""
    T, N, C, H = 6, 3, 5, 7
    layer = rnn.LSTM(hidden_size=H, input_size=C)
    layer.initialize()
    cell = rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    for conn in ("i2h", "h2h"):
        for kind in ("weight", "bias"):
            getattr(cell, "%s_%s" % (conn, kind)).set_data(
                getattr(layer, "l0_%s_%s" % (conn, kind)).data())

    x = nd.random.uniform(shape=(T, N, C))
    h0 = nd.zeros((1, N, H))
    c0 = nd.zeros((1, N, H))
    out_l, states_l = layer(x, [h0, c0])

    outs_c, states_c = cell.unroll(
        T, x, begin_state=[h0[0], c0[0]], layout="TNC", merge_outputs=True)
    assert_almost_equal(out_l, outs_c.asnumpy(), rtol=1e-4, atol=1e-5)
    assert_almost_equal(states_l[0][0], states_c[0].asnumpy(), rtol=1e-4,
                        atol=1e-5)
    assert_almost_equal(states_l[1][0], states_c[1].asnumpy(), rtol=1e-4,
                        atol=1e-5)


@with_seed()
@pytest.mark.parametrize("mode,cls", [
    ("rnn", rnn.RNN), ("gru", rnn.GRU)])
def test_single_gate_layer_vs_cell(mode, cls):
    T, N, C, H = 4, 2, 3, 5
    layer = cls(hidden_size=H, input_size=C) if mode == "gru" else \
        cls(hidden_size=H, input_size=C, activation="tanh")
    layer.initialize()
    cell = (rnn.GRUCell(H, input_size=C) if mode == "gru"
            else rnn.RNNCell(H, activation="tanh", input_size=C))
    cell.initialize()
    for conn in ("i2h", "h2h"):
        for kind in ("weight", "bias"):
            getattr(cell, "%s_%s" % (conn, kind)).set_data(
                getattr(layer, "l0_%s_%s" % (conn, kind)).data())
    x = nd.random.uniform(shape=(T, N, C))
    out_l = layer(x)
    outs_c, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    assert_almost_equal(out_l, outs_c.asnumpy(), rtol=1e-4, atol=1e-5)


@with_seed()
def test_layer_backward():
    layer = rnn.LSTM(hidden_size=8)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 4, 3))
    x.attach_grad()
    with ag.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    assert x.grad.shape == x.shape
    assert float(np.abs(x.grad.asnumpy()).sum()) > 0
    g = layer.l0_i2h_weight.grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


@with_seed()
def test_layer_deferred_input_size():
    layer = rnn.GRU(hidden_size=10, num_layers=2)
    layer.initialize()
    assert layer.l0_i2h_weight.shape[1] == 0
    out = layer(nd.ones((3, 2, 6)))
    assert layer.l0_i2h_weight.shape == (30, 6)
    assert layer.l1_i2h_weight.shape == (30, 10)
    assert out.shape == (3, 2, 10)


@with_seed()
def test_layer_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "lstm.params")
    layer = rnn.LSTM(hidden_size=6, num_layers=2, input_size=4)
    layer.initialize()
    x = nd.random.uniform(shape=(3, 2, 4))
    y0 = layer(x).asnumpy()
    layer.save_parameters(f)
    layer2 = rnn.LSTM(hidden_size=6, num_layers=2, input_size=4)
    layer2.load_parameters(f)
    assert_almost_equal(layer2(x), y0)


@with_seed()
@pytest.mark.parametrize("cell_cls,n_states", [
    (rnn.RNNCell, 1), (rnn.LSTMCell, 2), (rnn.GRUCell, 1)])
def test_cell_step_and_unroll(cell_cls, n_states):
    cell = cell_cls(20, input_size=10)
    cell.initialize()
    x = nd.random.uniform(shape=(4, 10))
    states = cell.begin_state(4)
    assert len(states) == n_states
    out, new_states = cell(x, states)
    assert out.shape == (4, 20)
    assert len(new_states) == n_states

    seq = nd.random.uniform(shape=(4, 3, 10))
    outs, last = cell.unroll(3, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (4, 3, 20)
    outs_list, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=False)
    assert len(outs_list) == 3
    assert outs_list[0].shape == (4, 20)


@with_seed()
def test_sequential_rnn_cell():
    stack = rnn.SequentialRNNCell()
    with stack.name_scope():
        stack.add(rnn.LSTMCell(12, input_size=6))
        stack.add(rnn.DropoutCell(0.3))
        stack.add(rnn.GRUCell(8, input_size=12))
    stack.initialize()
    seq = nd.random.uniform(shape=(2, 5, 6))
    outs, states = stack.unroll(5, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 8)
    assert len(states) == 3  # lstm h,c + gru h
    assert len(stack) == 3
    assert isinstance(stack[0], rnn.LSTMCell)


@with_seed()
def test_residual_cell():
    cell = rnn.ResidualCell(rnn.GRUCell(6, input_size=6))
    cell.initialize()
    seq = nd.random.uniform(shape=(3, 4, 6))
    outs, _ = cell.unroll(4, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (3, 4, 6)
    # residual really adds the input: with zeroed params GRU outputs 0,
    # so the residual output equals the input exactly
    zcell = rnn.ResidualCell(rnn.GRUCell(6, input_size=6))
    zcell.initialize(init="zeros")
    z_outs, _ = zcell.unroll(4, seq, layout="NTC", merge_outputs=True)
    # zero weights => update gate z=0.5, candidate n=0 => h decays but
    # starts at 0 so stays 0; residual = input
    assert_almost_equal(z_outs, seq.asnumpy(), rtol=1e-6, atol=1e-6)


@with_seed()
def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(
        rnn.LSTMCell(5, input_size=3), rnn.LSTMCell(5, input_size=3))
    cell.initialize()
    seq = nd.random.uniform(shape=(2, 7, 3))
    outs, states = cell.unroll(7, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 7, 10)
    assert len(states) == 4


@with_seed()
def test_unroll_default_returns_step_list():
    cell = rnn.RNNCell(4, input_size=3)
    cell.initialize()
    seq = nd.random.uniform(shape=(2, 5, 3))
    outs, _ = cell.unroll(5, seq, layout="NTC")  # merge_outputs=None
    assert isinstance(outs, list) and len(outs) == 5
    assert outs[0].shape == (2, 4)


@with_seed()
def test_bidirectional_valid_length():
    """Backward cell must consume the valid prefix reversed, not padding."""
    H, C, T = 4, 3, 6
    cell = rnn.BidirectionalCell(
        rnn.LSTMCell(H, input_size=C), rnn.LSTMCell(H, input_size=C))
    cell.initialize()
    seq = nd.random.uniform(shape=(2, T, C))
    vl = nd.array([3, 6])
    outs, states = cell.unroll(T, seq, layout="NTC", merge_outputs=True,
                               valid_length=vl)
    assert outs.shape == (2, T, 2 * H)
    # sample 0 (valid 3) must match unrolling just its prefix alone
    outs_ref, _ = cell.unroll(3, seq[0:1, :3], layout="NTC",
                              merge_outputs=True)
    assert_almost_equal(outs.asnumpy()[0:1, :3], outs_ref.asnumpy(),
                        rtol=1e-4, atol=1e-5)
    # padding region masked to zero
    assert np.abs(outs.asnumpy()[0, 3:]).sum() == 0


@with_seed()
def test_zoneout_cell_smoke():
    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=4),
                           zoneout_outputs=0.5, zoneout_states=0.5)
    cell.initialize()
    seq = nd.random.uniform(shape=(2, 3, 4))
    outs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 3, 4)
    with ag.record(train_mode=True):
        outs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 3, 4)
    # zoneout must actually fire in training: zoned-out outputs at t=0
    # take the previous output, which starts at zeros — exact zeros that
    # a tanh RNN output essentially never produces on its own
    assert np.any(outs.asnumpy()[:, 0, :] == 0), \
        "zoneout produced no zoned elements under record()"


@with_seed()
def test_unroll_valid_length():
    cell = rnn.LSTMCell(4, input_size=2)
    cell.initialize()
    seq = nd.random.uniform(shape=(3, 5, 2))
    vl = nd.array([2, 5, 3])
    outs, states = cell.unroll(5, seq, layout="NTC", merge_outputs=True,
                               valid_length=vl)
    assert outs.shape == (3, 5, 4)
    o = outs.asnumpy()
    # steps past valid_length must be masked to zero
    assert np.abs(o[0, 2:]).sum() == 0
    assert np.abs(o[2, 3:]).sum() == 0
    assert np.abs(o[0, :2]).sum() > 0
    # final states are the state AT valid_length, not at T
    outs2, states2 = cell.unroll(2, seq[:, :2], layout="NTC",
                                 merge_outputs=True)
    assert_almost_equal(states[0][0], states2[0][0].asnumpy(), rtol=1e-5,
                        atol=1e-6)


@with_seed()
def test_rnn_layer_hybridize():
    layer = rnn.LSTM(hidden_size=8, num_layers=1)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 3, 4))
    y0 = layer(x).asnumpy()
    layer.hybridize()
    y1 = layer(x).asnumpy()
    assert_almost_equal(y0, y1, rtol=1e-5, atol=1e-6)


@with_seed()
def test_rnn_layer_in_training_loop():
    """Tiny LSTM regression converges (end-to-end train signal)."""
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        pass
    layer = rnn.LSTM(hidden_size=16, input_size=3)
    head = gluon.nn.Dense(1, flatten=False)
    layer.initialize()
    head.initialize()
    params = gluon.ParameterDict()
    params.update(layer.collect_params())
    params.update(head.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 1e-2})
    loss_fn = gluon.loss.L2Loss()
    x = nd.random.uniform(shape=(10, 8, 3))
    target = x.sum(axis=2, keepdims=True) * 0.5  # (T,N,1)

    first = None
    for i in range(30):
        with ag.record():
            out = head(layer(x))
            loss = loss_fn(out, target)
        loss.backward()
        trainer.step(8)
        cur = float(loss.mean().asnumpy())
        if first is None:
            first = cur
    assert cur < first * 0.5, (first, cur)


def test_lstm_wavefront_matches_sequential(monkeypatch):
    """MXT_RNN_WAVEFRONT=1 runs multi-layer LSTM as a diagonal wavefront
    (ops/rnn.py _wavefront_lstm); outputs, final states, and the whole
    training step must match the sequential path bit-for-bit in f32."""
    import numpy as np

    from mxnet_tpu import autograd as ag

    from mxnet_tpu.ops import rnn as rnn_ops

    calls = []
    real_wf = rnn_ops._wavefront_lstm

    def spy(*args, **kw):
        calls.append(1)
        return real_wf(*args, **kw)

    monkeypatch.setattr(rnn_ops, "_wavefront_lstm", spy)

    def run(env):
        if env:
            monkeypatch.setenv("MXT_RNN_WAVEFRONT", "1")
        else:
            monkeypatch.delenv("MXT_RNN_WAVEFRONT", raising=False)
        mx.random.seed(3)
        net = rnn.LSTM(hidden_size=8, num_layers=3, layout="NTC",
                       prefix="wf_%d_" % env)
        net.initialize()
        x = nd.array(np.random.RandomState(0).uniform(
            -1, 1, (4, 6, 5)).astype("f4"))
        x.attach_grad()
        with ag.record():
            out = net(x)
            loss = (out ** 2).sum()
        loss.backward()
        return out.asnumpy(), x.grad.asnumpy()

    out_seq, g_seq = run(0)
    assert not calls  # sequential run must not dispatch the wavefront
    out_wf, g_wf = run(1)
    assert calls, "MXT_RNN_WAVEFRONT=1 did not dispatch _wavefront_lstm"
    np.testing.assert_allclose(out_wf, out_seq, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(g_wf, g_seq, rtol=1e-5, atol=1e-6)
