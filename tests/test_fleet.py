"""Fault-tolerant serving fleet (mxnet_tpu/serving/fleet.py +
serving/router.py): membership-backed replica pool, SLO-aware routing,
hedged dispatch, failover with idempotency tokens, drain/rejoin, and
kill-mid-run survival.

Fleet tests run IN-PROCESS (serving.local_serving_fleet — a real
coordinator async server on loopback, real membership registrations and
heartbeats, replicas driven co-operatively by the router) so every
scenario is deterministic: fake clocks for the hedge timing, seeded
MXT_FAULT rules (replica_kill / replica_slow) for the chaos cells swept
by tools/chaos_matrix.sh via MXT_CHAOS_SEED.
"""
import os
import time

import numpy as np
import pytest

from mxnet_tpu import serving, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import KVStoreError
from mxnet_tpu.serving import fleet as fleet_mod
from mxnet_tpu.serving import (ContinuousBatcher, DecodeEngine,
                               FleetRouter, PagedKVCache, Request,
                               StaleReplicaError, TinyDecoder)


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch, tmp_path):
    """Dead replicas must surface in milliseconds, not the production
    30s retry budget; every test gets its own tuning table."""
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    yield
    tuning.reset()


MODEL = TinyDecoder(vocab=64, num_layers=1, num_heads=2, head_dim=8,
                    max_len=256)
PARAMS = MODEL.init_params(3)

_FREE_ENGINES = []  # drained engines recycled across tests (trace cost)


def _factory():
    while _FREE_ENGINES:
        eng = _FREE_ENGINES.pop()
        if eng.cache.pages_in_use() == 0 and not eng._seq_of_slot:
            return eng
    return DecodeEngine(
        MODEL, params=PARAMS, slots=2,
        cache=PagedKVCache(1, 2, 8, num_pages=64, page_size=8),
        prefill_buckets=(16,), max_context=64)


def _fleet(n, now_fn=time.monotonic, warm=False):
    return serving.local_serving_fleet(n, _factory, now_fn=now_fn,
                                       warm=warm)


def _close(pool, srv):
    for h in pool.replicas():
        if h.engine is not None and h.state != "dead":
            _FREE_ENGINES.append(h.engine)
        try:
            h.close()
        except Exception:  # noqa: BLE001 — killed handles
            pass
    srv.close()


def _ref(prompt, n):
    return MODEL.reference_decode(PARAMS, list(prompt), n)


def _traffic(router, n, seed, max_plen=12, max_new=6, prefix="t"):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(1, max_plen))
        mnew = int(rng.randint(2, max_new))
        out.append(router.submit(
            rng.randint(1, 64, plen).tolist(), max_new_tokens=mnew,
            token="%s%d" % (prefix, i)))
    return out


# ---------------------------------------------------------------------------
# the acceptance scenario: kill one replica mid-run
# ---------------------------------------------------------------------------
def test_fleet_kill_one_replica_acceptance():
    """2-replica fleet under mixed-length traffic, one replica killed
    mid-run: every accepted request completes with token-exact output
    vs an unkilled 1-replica oracle, failover counter > 0, p99
    bounded, and no request is decoded twice (idempotency token
    asserted — a replay returns the recorded result with zero new
    decode steps)."""
    # the unkilled 1-replica oracle over the same traffic
    pool1, srv1 = _fleet(1)
    r1 = FleetRouter(pool1)
    oracle = _traffic(r1, 8, seed=_seed())
    r1.run(max_steps=2000)
    assert all(rr.state == "completed" for rr in oracle)
    _close(pool1, srv1)

    pool, srv = _fleet(2)
    router = FleetRouter(pool)
    reqs = _traffic(router, 8, seed=_seed())
    for _ in range(4):   # let traffic spread over both replicas
        router.step()
    assert any(1 in rr.copies for rr in reqs), "nothing on replica 1"
    pool.get(1).kill()   # SIGKILL emulation: no deregister, mid-flight
    router.run(max_steps=2000)

    lats = []
    for rr, orr in zip(reqs, oracle):
        assert rr.state == "completed", (rr.token, rr.state)
        assert rr.result == orr.result == _ref(rr.prompt,
                                               rr.max_new_tokens)
        assert rr.commits == 1          # committed exactly once
        lats.append(rr.t_finish - rr.t_submit)
    assert sum(rr.failovers for rr in reqs) > 0
    assert all(rr.committed_by == 0 for rr in reqs
               if rr.failovers)        # survivors decoded the orphans
    lats.sort()
    assert lats[int(0.99 * (len(lats) - 1))] < 60.0  # p99 bounded

    # idempotency: replaying a completed token returns the recorded
    # result and decodes NOTHING
    steps0 = sum(h.batcher.steps for h in pool.replicas()
                 if h.batcher is not None)
    again = router.submit(reqs[0].prompt, token=reqs[0].token)
    assert again is reqs[0] and again.result == reqs[0].result
    assert router.replays == 1
    assert sum(h.batcher.steps for h in pool.replicas()
               if h.batcher is not None) == steps0
    _close(pool, srv)


def test_router_load_aware_dispatch():
    """Dispatch follows the queue-depth/active-slot gauges: 4 requests
    over 2 idle 2-slot replicas spread 2/2, never 4/0."""
    pool, srv = _fleet(2)
    router = FleetRouter(pool)
    reqs = _traffic(router, 4, seed=1, prefix="l")
    router.step()
    placed = [next(iter(rr.copies)) for rr in reqs]
    assert placed.count(0) == 2 and placed.count(1) == 2, placed
    router.run(max_steps=2000)
    assert all(rr.state == "completed" for rr in reqs)
    _close(pool, srv)


def test_no_routable_replicas_is_typed_error():
    pool, srv = _fleet(1)
    router = FleetRouter(pool)
    pool.get(0).kill()
    router.submit([5], max_new_tokens=2)
    with pytest.raises(KVStoreError):
        router.run(max_steps=50)
    _close(pool, srv)


# ---------------------------------------------------------------------------
# hedged dispatch (fake clock)
# ---------------------------------------------------------------------------
def test_hedge_fires_at_delay_first_completion_wins():
    """A request stalled past the hedge delay is duplicated onto the
    second replica; the first completion wins (committed once) and the
    loser is cancelled through the eviction path."""
    clock = [0.0]
    pool, srv = _fleet(2, now_fn=lambda: clock[0])
    router = FleetRouter(pool, now_fn=lambda: clock[0],
                         hedge_delay=1.0, hedge_budget=4)
    rr = router.submit([5, 9, 2], max_new_tokens=3, token="h1")
    router.step()
    rid0 = next(iter(rr.copies))
    h0 = pool.get(rid0)
    loser = h0._copies[rr.copies[rid0]]
    h0.slow_until = 1e9            # brownout: no decode progress
    router.step()
    assert rr.hedges == 0          # below the delay: no hedge yet
    clock[0] = 1.5
    router.step()
    assert rr.hedges == 1 and len(rr.copies) == 2  # fired at the delay
    router.run(max_steps=2000)
    assert rr.state == "completed" and rr.commits == 1
    assert rr.committed_by != rid0
    assert rr.result == _ref(rr.prompt, 3)
    assert loser.state == "evicted"  # loser cancelled, pages freed
    h0.slow_until = 0.0
    _close(pool, srv)


def test_hedge_budget_bounds_load():
    """hedge_budget=0 disables hedging outright — a brownout cannot
    recruit extra fleet load."""
    clock = [0.0]
    pool, srv = _fleet(2, now_fn=lambda: clock[0])
    router = FleetRouter(pool, now_fn=lambda: clock[0],
                         hedge_delay=0.1, hedge_budget=0)
    rr = router.submit([7], max_new_tokens=2, token="h2")
    router.step()
    clock[0] = 50.0
    router.step()
    assert rr.hedges == 0 and len(rr.copies) == 1
    router.run(max_steps=2000)
    assert rr.state == "completed"
    _close(pool, srv)


def test_hedge_delay_derived_from_slo():
    """Without an explicit delay, the hedge point is SLO-derived: half
    the per-request deadline (or the router's slo)."""
    pool, srv = _fleet(1)
    router = FleetRouter(pool, slo=2.0)
    a = router.submit([5], max_new_tokens=2, deadline=1.0)
    b = router.submit([5], max_new_tokens=2)
    assert a.hedge_delay == pytest.approx(0.5)   # half its deadline
    assert b.hedge_delay == pytest.approx(1.0)   # half the router slo
    router.run(max_steps=2000)
    no_slo = FleetRouter(pool)
    c = no_slo.submit([5], max_new_tokens=2)
    assert c.hedge_delay is None                 # nothing to derive
    no_slo.run(max_steps=2000)
    _close(pool, srv)


# ---------------------------------------------------------------------------
# fencing: a zombie's late reply is refused typed
# ---------------------------------------------------------------------------
def test_fenced_zombie_late_reply_refused_typed():
    """A replica fenced by the reaper whose process keeps decoding: its
    late completion raises StaleReplicaError at the accept gate, is
    counted, and is never committed — the failover copy wins."""
    pool, srv = _fleet(2)
    router = FleetRouter(pool)
    rr = router.submit([9, 1], max_new_tokens=2, token="z1")
    router.step()
    rid = next(iter(rr.copies))
    hz = pool.get(rid)
    hz.member.fenced = True   # the verdict the beat loop observes
    # the zombie decodes to completion anyway
    for _ in range(8):
        hz.batcher.step()
    hz.batcher.drain()
    # the accept gate is the typed refusal (any reply, any copy)
    with pytest.raises(StaleReplicaError):
        router.accept(hz, "any#0", "completed", [1, 2])
    # ...and the router's natural path collects the zombie's REAL
    # completion, refuses it typed (counted), marks the replica dead,
    # and fails over: the survivor's commit is the only one
    router.run(max_steps=2000)
    assert router.stale_replies >= 1
    assert rr.state == "completed" and rr.commits == 1
    assert rr.committed_by != rid
    assert rr.result == _ref(rr.prompt, 2)
    assert hz.state == "dead"
    _close(pool, srv)


def test_membership_reaper_death_listener():
    """The coordinator's reaper declares a silent replica dead; the
    pool's death listener (MembershipTable.add_death_listener reuse)
    hands it to the router's next step."""
    pool, srv = _fleet(2)
    h1 = pool.get(1)
    h1.member._stop.set()          # beats silently stop (zombie)
    if h1.member._thread is not None:
        h1.member._thread.join(timeout=5.0)
    future = time.monotonic() + 100.0
    srv.membership.heartbeat(fleet_mod._replica_member_id(0),
                             pool.get(0).generation, now=future)
    dead = srv.membership.reap(5.0, now=future)
    assert fleet_mod._replica_member_id(1) in dead
    assert pool.poll_deaths() == [1]
    assert h1.state == "dead"
    _close(pool, srv)


# ---------------------------------------------------------------------------
# drain + AOT-warm rejoin
# ---------------------------------------------------------------------------
def test_drain_migrates_queue_and_rejoin_serves_warm(tmp_path,
                                                     monkeypatch):
    """Graceful drain: queued copies migrate to peers, running ones
    finish, the replica deregisters clean; a rejoin rebuilds a FRESH
    engine that AOT-warms through tuning.warmup() + the shared compile
    cache and serves with ZERO request-path cache-miss compiles."""
    from jax._src import compilation_cache as _cc

    monkeypatch.setenv("MXT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    _cc.reset_cache()

    def fresh_factory():
        return DecodeEngine(
            MODEL, params=PARAMS, slots=2,
            cache=PagedKVCache(1, 2, 8, num_pages=64, page_size=8),
            prefill_buckets=(16,), max_context=64)

    pool, srv = serving.local_serving_fleet(2, fresh_factory, warm=True)
    router = FleetRouter(pool)
    reqs = _traffic(router, 6, seed=2, prefix="d")
    router.step()
    n_live = len(srv.membership.view()["members"])
    router.drain(1)
    router.run(max_steps=2000)
    assert all(rr.state == "completed" for rr in reqs)
    assert all(rr.result == _ref(rr.prompt, rr.max_new_tokens)
               for rr in reqs)
    h1 = pool.get(1)
    assert h1.state == "drained"
    # deregistered clean: not a lost worker, just gone from the view
    view = srv.membership.view()
    assert fleet_mod._replica_member_id(1) not in view["members"]
    assert fleet_mod._replica_member_id(1) not in view["dead"]
    assert len(view["members"]) == n_live - 1

    # hot-spare rejoin: fresh engine + fresh in-memory jit caches — the
    # shared DISK cache must cover the whole request path
    _cc.reset_cache()
    h1.rejoin(warm=True)
    assert h1.state == "routable" and h1.generation is not None
    c0 = tuning.compile_stats()
    more = [router.submit([3, 1, 4, 1], max_new_tokens=3,
                          token="dr%d" % i) for i in range(4)]
    router.run(max_steps=2000)
    c1 = tuning.compile_stats()
    assert all(rr.state == "completed" for rr in more)
    assert any(rr.committed_by == 1 for rr in more)
    assert c1["cache_misses"] - c0["cache_misses"] == 0, \
        "rejoined replica compiled on the request path"
    _close(pool, srv)


# ---------------------------------------------------------------------------
# scheduler cancel hook (the hedge-loser / drain-migration primitive)
# ---------------------------------------------------------------------------
def test_scheduler_cancel_queued_and_running():
    eng = _factory()
    sched = ContinuousBatcher(eng)
    a = sched.submit(Request([3, 4], max_new_tokens=8))
    b = sched.submit(Request([5], max_new_tokens=8))
    c = sched.submit(Request([7], max_new_tokens=8))  # queued (2 slots)
    sched.step()
    assert a.state == "running" and c.state == "queued"
    assert sched.cancel(c) and c.state == "evicted"
    assert sched.cancel(a) and a.state == "evicted"
    assert not sched.cancel(a)          # idempotent
    assert eng.cache.pages_in_use() <= 2  # a's pages freed
    sched.run()
    assert b.state == "completed"
    assert b.output_tokens == _ref([5], 8)
    _FREE_ENGINES.append(eng)


# ---------------------------------------------------------------------------
# standalone replica role (srv_* ops over the async transport)
# ---------------------------------------------------------------------------
def test_remote_replica_and_serving_host():
    from mxnet_tpu.async_server import AsyncParamServer

    srv = AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    eng = _factory()
    host = fleet_mod.ServingHost(ContinuousBatcher(eng))
    srv.attach_serving(host)
    rem = fleet_mod.RemoteReplica(0, "127.0.0.1", port, slots=eng.slots)
    assert rem.submit_copy("c1", [3, 1, 4], 3) == "queued"
    assert rem.load() == {"queue": 1, "active": 0, "slots": 2}
    assert rem.queued_copies() == ["c1"]
    while host.step():
        pass
    assert rem.poll() == [("c1", "completed", _ref([3, 1, 4], 3))]
    # drain closes admission remotely
    rem.drain_start()
    assert not host.admitting
    rem.close()
    srv.close()
    _FREE_ENGINES.append(eng)


def test_standalone_replica_discovered_and_routed():
    """The full standalone role: serve_replica() registers endpoint +
    capacity meta at the coordinator, ReplicaPool.refresh() discovers
    it as a RemoteReplica, and the router completes a request over the
    srv_* transport (the replica's own decode-loop thread drives)."""
    from mxnet_tpu.async_server import AsyncParamServer

    coord_srv = AsyncParamServer("127.0.0.1", 0)
    coord = ("127.0.0.1", coord_srv._sock.getsockname()[1])
    eng = _factory()
    rep_srv, host, member, stop = fleet_mod.serve_replica(
        eng, coord, index=0)
    try:
        pool = fleet_mod.ReplicaPool(coordinator=coord,
                                     server=coord_srv)
        pool.refresh()
        assert isinstance(pool.get(0), fleet_mod.RemoteReplica)
        assert pool.get(0).capacity == eng.slots
        router = FleetRouter(pool)
        rr = router.submit([3, 1, 4], max_new_tokens=3, token="rm1")
        deadline = time.monotonic() + 30.0
        while not rr.done and time.monotonic() < deadline:
            router.step()
            time.sleep(0.01)
        assert rr.state == "completed"
        assert rr.result == _ref([3, 1, 4], 3)
        pool.close()
    finally:
        stop()
        coord_srv.close()


def test_serving_host_rejects_while_draining():
    from mxnet_tpu.async_server import AsyncParamServer

    srv = AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    eng = _factory()
    host = fleet_mod.ServingHost(ContinuousBatcher(eng))
    srv.attach_serving(host)
    rem = fleet_mod.RemoteReplica(0, "127.0.0.1", port, slots=eng.slots)
    rem.drain_start()
    with pytest.raises(MXNetError):
        rem.submit_copy("c9", [1, 2], 2)
    rem.close()
    srv.close()
    _FREE_ENGINES.append(eng)


# ---------------------------------------------------------------------------
# chaos cells (swept per seed by tools/chaos_matrix.sh)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_chaos_replica_kill_failover(monkeypatch):
    """Seeded replica_kill mid-run: deterministic kill at a router
    tick, zero lost requests, token-exact failover."""
    from mxnet_tpu import resilience

    monkeypatch.setenv(
        "MXT_FAULT",
        "replica_kill:replica=1,after=2,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    try:
        pool, srv = _fleet(2)
        router = FleetRouter(pool)
        # budgets long enough that replica 1's copies are mid-decode at
        # its 2nd tick, whatever the seed — the kill is always mid-run
        rng = np.random.RandomState(_seed())
        reqs = [router.submit(rng.randint(1, 64, 4).tolist(),
                              max_new_tokens=8, token="ck%d" % i)
                for i in range(6)]
        router.run(max_steps=2000)
        assert pool.get(1).state == "dead"
        assert all(rr.state == "completed" for rr in reqs)
        assert all(rr.result == _ref(rr.prompt, rr.max_new_tokens)
                   for rr in reqs)
        assert sum(rr.failovers for rr in reqs) > 0
        _close(pool, srv)
    finally:
        resilience.reset_faults()


@pytest.mark.chaos
def test_chaos_replica_slow_hedges(monkeypatch):
    """Seeded replica_slow brownout under a fake clock: the hedge fires
    at the delay and the fleet completes everything on the healthy
    replica."""
    from mxnet_tpu import resilience

    monkeypatch.setenv(
        "MXT_FAULT",
        "replica_slow:replica=0,ms=60000,after=1,n=1,seed=%d" % _seed())
    resilience.reset_faults()
    try:
        clock = [0.0]
        pool, srv = _fleet(2, now_fn=lambda: clock[0])
        router = FleetRouter(pool, now_fn=lambda: clock[0],
                             hedge_delay=1.0, hedge_budget=4)
        reqs = [router.submit([5, 9, 2], max_new_tokens=3,
                              token="cs%d" % i) for i in range(2)]
        router.step()
        clock[0] = 2.0
        for _ in range(40):
            if all(rr.done for rr in reqs):
                break
            router.step()
        router.flush()
        assert all(rr.state == "completed" for rr in reqs)
        assert all(rr.result == _ref(rr.prompt, 3) for rr in reqs)
        # whoever was browned out lost every race it was hedged on
        slow = [h for h in pool.replicas() if h.slow_until > 0]
        assert slow and all(rr.committed_by != slow[0].index
                            for rr in reqs if rr.hedges)
        _close(pool, srv)
    finally:
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# telemetry + lint
# ---------------------------------------------------------------------------
def test_fleet_modules_lint_enforced():
    """fleet.py and router.py stay on the static host-sync scan list."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    for rel in ("mxnet_tpu/serving/fleet.py",
                "mxnet_tpu/serving/router.py"):
        assert rel in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root)
           if b[0].startswith("mxnet_tpu/serving/")]
    assert not bad, bad


def test_mxt_top_fleet_section():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    samples = {
        ("mxt_fleet_replicas", frozenset({("state", "routable")})): 2,
        ("mxt_fleet_replicas", frozenset({("state", "dead")})): 1,
        ("mxt_fleet_dispatch_total", frozenset({("replica", "0")})): 9,
        ("mxt_fleet_hedges_total", frozenset({("replica", "0")})): 2,
        ("mxt_fleet_failovers_total", frozenset({("replica", "1")})): 3,
    }
    frame = mod.render(samples, None, 0)
    assert "fleet replicas" in frame
    assert "disp/hedge/fail" in frame
    # a process with no fleet gauges renders no fleet noise
    assert "fleet replicas" not in mod.render({}, None, 0)


def test_fleet_metrics_published():
    """The router publishes the ISSUE's telemetry surface: replica
    state gauges, per-replica dispatch counters, latency histogram."""
    from mxnet_tpu import telemetry

    pool, srv = _fleet(1)
    router = FleetRouter(pool)
    rr = router.submit([5, 1], max_new_tokens=2, token="m1")
    router.run(max_steps=2000)
    assert rr.state == "completed"
    reg = telemetry.registry()
    fam = reg.get("mxt_fleet_replicas")
    assert fam is not None
    fam = reg.get("mxt_fleet_dispatch_total")
    assert fam is not None and sum(
        ch.value for ch in fam.children().values()) >= 1
    assert reg.get("mxt_fleet_request_latency_seconds") is not None
    _close(pool, srv)
