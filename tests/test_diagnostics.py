"""Flight recorder & diagnostics (mxnet_tpu/diagnostics.py): ring
buffer, hang watchdog, HBM ledger, goodput accounting, post-mortems,
and the /debug/* routes.

The load-bearing properties:

- the flight recorder is a bounded ring tapped off telemetry events —
  ordering preserved, oldest dropped first, every existing event source
  (spans, RPC spans, checkpoint/reshard/membership events) lands in it;
- the watchdog detects a deliberately-frozen in-flight window with a
  FAKE clock (no sleeps): stall reports carry thread stacks, window
  state, and the recorder tail, dump a parseable post-mortem, and
  re-arm on progress;
- seeded ``MXT_FAULT`` ``worker_freeze``/``kv_drop`` chaos ends in a
  TYPED outcome (stall report with post-mortem; KVStoreError with a
  flight event) instead of a silent hang, and ``abort`` mode dies with
  WATCHDOG_EXIT_CODE that ``tools/launch.py --respawn`` heals;
- the HBM ledger covers params/optimizer/inflight pools on a live
  fused run AND kv_cache on a serving run, peaks are monotone,
  reconciliation degrades gracefully on CPU, and a forced allocation
  failure re-raises annotated with the ledger snapshot;
- goodput arithmetic is exact under injected checkpoint+reshard pauses;
- diagnostics add ZERO host syncs to a fused 3-step run (armed vs
  disarmed parity — the bench row's contract, asserted in tier-1).
"""
import glob
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import diagnostics as dg
from mxnet_tpu import engine, nd, profiler, resilience, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.resilience import KVStoreError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_loss_fn = mx.gluon.loss.L2Loss()


def _seed():
    """Injector seed — swept by tools/chaos_matrix.sh via MXT_CHAOS_SEED."""
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean():
    """Recorder tap installed (an earlier disable() may have removed
    it), window drained on exit, goodput epoch restored."""
    dg.recorder()
    yield
    engine.wait_all()
    dg.reset_goodput()


def _subenv(tmp_path, **extra):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=ROOT,
               MXT_POSTMORTEM_DIR=str(tmp_path))
    env.pop("MXT_WATCHDOG_TIMEOUT", None)
    env.update(extra)
    return env


def _postmortems(tmp_path):
    return sorted(glob.glob(os.path.join(str(tmp_path),
                                         "mxt-postmortem-*.json")))


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_bounds_ordering():
    r = dg.FlightRecorder(size=8)
    for i in range(20):
        r.record("e", i=i)
    assert len(r) == 8
    assert r.recorded == 20
    assert [e["i"] for e in r.events()] == list(range(12, 20))
    assert [e["i"] for e in r.events(last=3)] == [17, 18, 19]
    assert all(e["kind"] == "e" and "ts" in e for e in r.events())
    r.clear()
    assert len(r) == 0
    with pytest.raises(MXNetError):
        dg.FlightRecorder(size=0)


def test_recorder_taps_every_telemetry_event():
    rec = dg.recorder()
    marker = "tap_probe_%s" % uuid.uuid4().hex[:8]
    telemetry.emit_event(marker, foo="bar")
    dg.record_event(marker, foo="baz")  # the diagnostics spelling
    evs = [e for e in rec.events() if e["kind"] == marker]
    assert [e["foo"] for e in evs] == ["bar", "baz"]


# ---------------------------------------------------------------------------
# progress sources + hang watchdog (fake clock — zero sleeps)
# ---------------------------------------------------------------------------
def test_pending_scope_and_progress_counters():
    name = "unit_rpc_%s" % uuid.uuid4().hex[:6]
    with dg.pending_scope(name):
        count, pend = dg.progress_counts()[name]
        assert (count, pend) == (0, 1)
    assert dg.progress_counts()[name][1] == 0
    dg.progress(name)
    dg.progress(name)
    assert dg.progress_counts()[name][0] == 2
    dg.unregister_source(name)
    assert name not in dg.progress_counts()


def test_watchdog_fake_clock_detects_frozen_window(monkeypatch, tmp_path):
    import jax.numpy as jnp

    nd.waitall()  # only OUR stream may be pending below
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    w = engine.InflightWindow(name="frozen_test")
    with engine.bulk(4):
        w.push(jnp.float32(1.0))  # 1 push < K: stays in flight forever
    assert w.pending == 1

    wd = dg.Watchdog(timeout=5.0, action="report", interval=1.0,
                     clock=lambda: 0.0)
    assert wd.check(now=0.0) == []          # first sight seeds
    assert wd.check(now=4.0) == []          # under the timeout
    stalled = wd.check(now=10.0)
    assert "engine_retire" in stalled
    rep = wd.stall_reports[-1]
    assert rep["pending"] == 1 and rep["action"] == "report"
    # the report carries the frozen window's state...
    assert any(s["name"] == "frozen_test" and s["pending"] == 1
               for s in rep["windows"])
    # ...every thread's stack (this function is on the main one)...
    flat = "\n".join("\n".join(s) for s in rep["threads"].values())
    assert "test_watchdog_fake_clock_detects_frozen_window" in flat
    # ...and the flight-recorder tail (the push's dispatch span)
    assert rep["flight_recorder_tail"]

    # the stall counter and the post-mortem landed
    fam = telemetry.registry().get("mxt_watchdog_stalls_total")
    assert fam is not None and fam.labels("engine_retire").value >= 1
    pms = _postmortems(tmp_path)
    assert pms
    doc = json.load(open(pms[-1]))
    assert doc["reason"] == "watchdog:engine_retire"
    assert any(s["name"] == "frozen_test" for s in doc["windows"])

    # within one timeout window the stall re-reports at most once
    assert "engine_retire" in wd.check(now=11.0)
    assert len(wd.stall_reports) == 1
    # progress re-arms: draining the window moves the retire counter
    w.flush()
    assert wd.check(now=12.0) == []


def test_watchdog_suppressed_during_profiler_capture():
    """A profiler capture pauses every loop by design; the watchdog
    must re-arm instead of reporting (abort mode would otherwise kill
    a healthy replica for being profiled)."""
    name = "cap_%s" % uuid.uuid4().hex[:6]
    dg.register_source(name, pending_fn=lambda: 1)
    try:
        wd = dg.Watchdog(timeout=1.0, action="report", interval=1.0,
                         dump=False, clock=lambda: 0.0)
        wd.check(now=0.0)
        assert dg._trace_lock.acquire(blocking=False)
        try:
            assert wd.check(now=100.0) == []  # capture in flight: re-arm
        finally:
            dg._trace_lock.release()
        # the re-arm reset the stall clock: still nothing at +100+eps
        assert name not in wd.check(now=100.5)
        # ...but a real stall after the capture still reports
        assert name in wd.check(now=200.0)
    finally:
        dg.unregister_source(name)


def test_watchdog_idle_source_never_stalls():
    name = "idle_%s" % uuid.uuid4().hex[:6]
    dg.register_source(name, pending_fn=lambda: 0)
    try:
        wd = dg.Watchdog(timeout=1.0, action="report", interval=1.0,
                         dump=False, clock=lambda: 0.0)
        wd.check(now=0.0)
        assert name not in wd.check(now=100.0)
    finally:
        dg.unregister_source(name)


def test_watchdog_config_validation(monkeypatch):
    monkeypatch.delenv("MXT_WATCHDOG_TIMEOUT", raising=False)
    with pytest.raises(MXNetError):
        dg.Watchdog()  # no timeout anywhere
    with pytest.raises(MXNetError):
        dg.Watchdog(timeout=1.0, action="explode")


def test_thread_stacks_contents():
    stacks = dg.thread_stacks()
    assert any("MainThread" in name for name in stacks)
    flat = "\n".join("\n".join(s) for s in stacks.values())
    assert "test_thread_stacks_contents" in flat


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------
def test_hbm_ledger_set_release_peak_and_export():
    pool = "testpool_%s" % uuid.uuid4().hex[:6]
    led = dg.ledger()
    assert led.set(pool, "a", 100) == 100
    assert led.set(pool, "b", 50) == 150
    assert led.set(pool, "a", 10) == 60       # replace, not accumulate
    snap = led.snapshot()[pool]
    assert snap["bytes"] == 60
    assert snap["peak_bytes"] == 150          # watermark is monotone
    assert snap["entries"] == {"a": 10, "b": 50}
    assert led.release(pool, "a") == 10
    assert led.pool_bytes(pool) == 50
    text = telemetry.render_prometheus()
    assert 'mxt_hbm_bytes{pool="%s"} 50' % pool in text
    assert 'mxt_hbm_peak_bytes{pool="%s"} 150' % pool in text
    led.release(pool, "b")
    assert led.pool_bytes(pool) == 0


def test_hbm_reconcile_tolerates_missing_device_stats():
    pool = "recon_%s" % uuid.uuid4().hex[:6]
    dg.hbm_set(pool, "x", 4096)
    try:
        out = dg.reconcile()
        assert out["ledger_bytes"] >= 4096
        # CPU backends report no memory_stats: reconciliation degrades
        # to ledger-only instead of failing (on TPU delta_bytes is real)
        if out["device_bytes_in_use"] is None:
            assert out["delta_bytes"] is None
            assert out["within_tolerance"] is True
        else:
            assert out["delta_bytes"] == \
                out["device_bytes_in_use"] - out["ledger_bytes"]
    finally:
        dg.hbm_release(pool, "x")


def _fused_run(prefix, steps=3):
    mx.random.seed(7)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    step = tr.fuse_step(net, _loss_fn)
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32))
    y = nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32))
    with engine.bulk(2):
        step(x, y)
        nd.waitall()  # build + compile + land the warmup token
        h0 = profiler.host_sync_count()
        for _ in range(steps):
            step(x, y)
        nd.waitall()
        return step, profiler.host_sync_count() - h0


def test_hbm_pools_cover_fused_and_serving_runs():
    # live fused-step run: params + optimizer registered at first
    # dispatch, the window's staged bytes under inflight_window
    step, _ = _fused_run("hbm_fused_")
    snap = dg.ledger().snapshot()
    key = step._sig_entry()
    assert snap["params"]["entries"][key] > 0
    assert snap["optimizer"]["entries"][key] > 0
    assert "inflight_window" in snap

    # live serving run: the KV page pool + the replica's weights
    from mxnet_tpu import serving

    model = serving.TinyDecoder(vocab=64, num_layers=1, num_heads=1,
                                head_dim=8, max_len=64)
    cache = serving.PagedKVCache(1, 1, 8, num_pages=8, page_size=8)
    eng = serving.DecodeEngine(model, slots=2, cache=cache,
                               prefill_buckets=(8,), max_context=32)
    sched = serving.ContinuousBatcher(eng)
    sched.submit(serving.Request([3, 5, 7], max_new_tokens=3))
    done = sched.run()
    assert len(done) == 1 and done[0].state == "completed"
    snap = dg.ledger().snapshot()
    assert snap["kv_cache"]["bytes"] >= \
        cache.k_pages.nbytes + cache.v_pages.nbytes
    assert snap["params"]["entries"]["decode_engine"] > 0
    # the decode loop registered with the watchdog and made progress
    assert dg.progress_counts()["serving_decode"][0] > 0


def test_oom_reraises_annotated_with_ledger(monkeypatch, tmp_path):
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    pool = "oomtest_%s" % uuid.uuid4().hex[:6]
    dg.hbm_set(pool, "big", 123456)
    err = RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9437184 bytes.")
    try:
        with pytest.raises(MXNetError) as ei:
            try:
                raise err
            except Exception as e:
                dg.reraise_if_oom(e, "unit_site")
                raise
        msg = str(ei.value)
        assert "HBM ledger" in msg and pool in msg and "unit_site" in msg
        assert ei.value.__cause__ is err
        # a non-OOM error passes through untouched
        assert dg.reraise_if_oom(ValueError("boom"), "unit_site") is None
        # the ring recorded the oom event with the pool breakdown
        oom = [e for e in dg.recorder().events() if e["kind"] == "oom"]
        assert oom and oom[-1]["site"] == "unit_site"
        assert oom[-1]["hbm"][pool] == 123456
    finally:
        dg.hbm_release(pool, "big")


def test_fused_step_dispatch_oom_annotated():
    step, _ = _fused_run("oom_fused_", steps=1)

    def raiser(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    step._jit = raiser
    x = nd.array(np.zeros((8, 8), np.float32))
    y = nd.array(np.zeros((8, 4), np.float32))
    with pytest.raises(MXNetError, match="fused_step"):
        step(x, y)


# ---------------------------------------------------------------------------
# goodput ledger
# ---------------------------------------------------------------------------
def test_goodput_arithmetic_with_injected_pauses():
    dg.reset_goodput(start=0.0)
    dg.record_lost("checkpoint", 2.0)
    dg.record_lost("checkpoint", 1.0)
    dg.record_lost("reshard", 1.5)
    snap = dg.goodput_snapshot(now=10.0)
    assert snap["elapsed_s"] == 10.0
    assert snap["lost_by_cause"]["checkpoint"] == 3.0
    assert snap["lost_by_cause"]["reshard"] == 1.5
    assert snap["lost_s"] == pytest.approx(4.5)
    assert snap["goodput_ratio"] == pytest.approx(0.55)
    # ratio floors at 0 when lost exceeds elapsed (clock skew)
    assert dg.goodput_snapshot(now=1.0)["goodput_ratio"] == 0.0
    # the counters exported
    text = telemetry.render_prometheus()
    assert 'mxt_lost_seconds_total{cause="checkpoint"}' in text
    assert "mxt_goodput_ratio" in text


def test_checkpoint_pause_lands_in_goodput(tmp_path):
    net = nn.Sequential(prefix="gp_ckpt_%s_" % uuid.uuid4().hex[:6])
    with net.name_scope():
        net.add(nn.Dense(4, in_units=4))
    net.initialize()
    dg.reset_goodput()
    mgr = resilience.CheckpointManager(str(tmp_path / "ck"), net=net)
    mgr.save(step=1)
    snap = dg.goodput_snapshot()
    assert snap["lost_by_cause"].get("checkpoint", 0.0) > 0.0
    # ...and the save event rode the flight recorder via the tap
    assert any(e["kind"] == "checkpoint_save"
               for e in dg.recorder().events())


# ---------------------------------------------------------------------------
# /debug/* routes
# ---------------------------------------------------------------------------
def _endpoint():
    if telemetry.http_port() is None:
        telemetry.start_http_server(0)
    return "http://127.0.0.1:%d" % telemetry.http_port()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_debug_routes_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    base = _endpoint()
    dg.record_event("debug_probe", n=1)

    status, ctype, body = _get(base + "/debug/stacks")
    assert status == 200 and "text/plain" in ctype
    assert b"MainThread" in body

    status, ctype, body = _get(base + "/debug/memory")
    assert status == 200 and "json" in ctype
    doc = json.loads(body)
    assert "hbm" in doc and "reconcile" in doc and "goodput" in doc

    status, ctype, body = _get(base + "/debug/flightrecorder")
    assert status == 200
    doc = json.loads(body)
    assert any(e["kind"] == "debug_probe" for e in doc["events"])
    assert "progress_sources" in doc and "windows" in doc

    status, _, body = _get(base + "/debug/postmortem")
    assert status == 200
    assert os.path.exists(json.loads(body)["path"])

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base + "/debug/nonsense")
    assert ei.value.code == 404

    # /metrics (any non-debug path) still serves the exposition
    status, _, body = _get(base + "/")
    assert status == 200 and b"# TYPE" in body


def test_debug_trace_returns_profile_archive():
    import jax.numpy as jnp

    base = _endpoint()
    # some device work for the profiler to see
    (jnp.ones((32, 32)) @ jnp.ones((32, 32))).block_until_ready()
    status, ctype, body = _get(base + "/debug/trace?ms=10")
    assert status == 200 and ctype == "application/zip"
    assert body[:2] == b"PK" and len(body) > 100  # a real zip archive


# ---------------------------------------------------------------------------
# post-mortems (subprocess: handlers + unhandled exception)
# ---------------------------------------------------------------------------
_EXCEPT_WORKER = """
import mxnet_tpu as mx
from mxnet_tpu import diagnostics as dg
dg.enable(handlers=True)  # no watchdog timeout: recorder + handlers only
dg.record_event("about_to_die", step=3)
raise ValueError("chaos-test unhandled")
"""


def test_postmortem_on_unhandled_exception_subprocess(tmp_path):
    script = tmp_path / "worker_exc.py"
    script.write_text(_EXCEPT_WORKER)
    proc = subprocess.run(
        [sys.executable, str(script)], env=_subenv(tmp_path),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "chaos-test unhandled" in proc.stderr
    pms = _postmortems(tmp_path)
    assert len(pms) == 1
    doc = json.load(open(pms[0]))
    assert doc["reason"] == "unhandled:ValueError"
    assert any(e["kind"] == "about_to_die" for e in doc["events"])
    assert doc["threads"] and doc["config"]["MXT_POSTMORTEM_DIR"] == \
        str(tmp_path)


# ---------------------------------------------------------------------------
# chaos: seeded faults end in typed, diagnosable outcomes
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_worker_freeze_ends_in_watchdog_stall(monkeypatch, tmp_path):
    """The silent zombie (seeded worker_freeze: beats stop, process
    lives) becomes a typed watchdog stall report with a parseable
    post-mortem — detection on a FAKE clock, only the freeze itself
    takes (milliseconds of) real time."""
    from mxnet_tpu import async_server
    from mxnet_tpu.membership import WorkerMembership

    monkeypatch.setenv("MXT_HEARTBEAT_INTERVAL", "0.02")
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv(
        "MXT_FAULT",
        "worker_freeze:worker=0,after=1,p=1.0,seed=%d" % _seed())
    resilience.reset_faults()
    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    try:
        port = srv._sock.getsockname()[1]
        m = WorkerMembership("127.0.0.1", port, 0)
        m.register()
        m.start_heartbeats()
        deadline = time.monotonic() + 10.0
        while not m.frozen and time.monotonic() < deadline:
            time.sleep(0.01)  # bounded poll, not an unconditional sleep
        assert m.frozen, "worker_freeze fault never fired"

        wd = dg.Watchdog(timeout=5.0, action="report", interval=1.0,
                         clock=lambda: 0.0)
        wd.check(now=0.0)
        stalled = wd.check(now=10.0)
        assert "membership_beat_w0" in stalled
        rep = wd.stall_reports[-1]
        assert rep["pending"] == 1
        pms = _postmortems(tmp_path)
        assert pms
        doc = json.load(open(pms[-1]))
        assert doc["reason"] == "watchdog:membership_beat_w0"
        assert doc["progress_sources"]["membership_beat_w0"]["pending"] \
            == 1
        m.stop()
        assert "membership_beat_w0" not in dg.progress_counts()
    finally:
        monkeypatch.delenv("MXT_FAULT")
        resilience.reset_faults()
        srv.close()


@pytest.mark.chaos
def test_kv_drop_ends_typed_with_flight_event(monkeypatch, tmp_path):
    """Seeded kv_drop exhausts the retry budget into a typed
    KVStoreError (never a hang) AND leaves a kv_retry_exhausted event
    in the flight recorder; the on-demand post-mortem carries it."""
    monkeypatch.setenv("MXT_FAULT", "kv_drop:p=1.0,seed=%d" % _seed())
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.001")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.002")
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    resilience.reset_faults()
    try:
        with pytest.raises(KVStoreError):
            resilience.kv_retry("push", "w0", lambda: "ok")
        evs = [e for e in dg.recorder().events()
               if e["kind"] == "kv_retry_exhausted"]
        assert evs and evs[-1]["op"] == "push" and evs[-1]["key"] == "w0"
        path = dg.dump_postmortem(reason="chaos:kv_drop")
        doc = json.load(open(path))
        assert any(e["kind"] == "kv_retry_exhausted"
                   for e in doc["events"])
    finally:
        resilience.reset_faults()


_ABORT_WORKER = """
import glob, os, sys, time
pmdir = os.environ["MXT_POSTMORTEM_DIR"]
import mxnet_tpu as mx  # MXT_WATCHDOG_TIMEOUT (launcher --watchdog) autostarts
from mxnet_tpu import diagnostics as dg
if glob.glob(os.path.join(pmdir, "mxt-postmortem-*.json")):
    sys.exit(0)  # the respawned incarnation: the watchdog did its job
assert dg.watchdog() is not None, "launcher did not arm the watchdog"
dg.register_source("wedge", pending_fn=lambda: 1)  # work that never moves
deadline = time.time() + 30
while time.time() < deadline:
    time.sleep(0.05)  # the watchdog abort must interrupt this
sys.exit(7)  # watchdog failed to fire
"""


@pytest.mark.chaos
def test_watchdog_abort_is_typed_and_respawnable(tmp_path):
    """abort mode: the stall dumps a post-mortem then dies with
    WATCHDOG_EXIT_CODE; tools/launch.py --respawn recognizes the typed
    death and restarts the worker with its original rank/env — the
    second incarnation finds the post-mortem and exits clean."""
    script = tmp_path / "worker_wedge.py"
    script.write_text(_ABORT_WORKER)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "1", "--respawn", "--max-restarts", "1",
         "--watchdog", "0.4", "--watchdog-action", "abort",
         sys.executable, str(script)],
        env=_subenv(tmp_path), capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    # the launcher logged the typed death...
    assert "watchdog abort" in proc.stderr
    assert "rc=%d" % dg.WATCHDOG_EXIT_CODE in proc.stderr
    # ...and the post-mortem exists, parses, and names the stall
    pms = _postmortems(tmp_path)
    assert pms
    doc = json.load(open(pms[0]))
    assert doc["reason"] == "watchdog:wedge"
    assert doc["extra"]["stall"]["source"] == "wedge"
    assert doc["config"]["MXT_WATCHDOG_ACTION"] == "abort"


# ---------------------------------------------------------------------------
# zero host syncs + satellites
# ---------------------------------------------------------------------------
def test_diagnostics_add_zero_host_syncs():
    """The bench row's contract in tier-1: a fused 3-step run performs
    IDENTICAL device reads with the diagnostics layer fully armed
    (recorder tap + watchdog daemon + ledger) vs disarmed."""
    dg.disable()
    try:
        _, syncs_off = _fused_run("dz_off_")
    finally:
        dg.recorder()  # tap back on
    wd = dg.enable(timeout=3600.0, action="report", handlers=False)
    try:
        assert wd is not None
        _, syncs_on = _fused_run("dz_on_")
    finally:
        dg.disable()
        dg.recorder()
    assert syncs_on == syncs_off


def test_mxt_top_renders_memory_and_goodput_sections():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxt_top
    finally:
        sys.path.pop(0)
    text = (
        'mxt_hbm_bytes{pool="params"} 1048576\n'
        'mxt_hbm_peak_bytes{pool="params"} 2097152\n'
        'mxt_hbm_bytes{pool="kv_cache"} 524288\n'
        'mxt_goodput_ratio 0.875\n'
        'mxt_lost_seconds_total{cause="checkpoint"} 12.5\n'
        'mxt_lost_seconds_total{cause="compile"} 3.25\n'
        'mxt_watchdog_stalls_total{source="engine_retire"} 2\n')
    samples = mxt_top.parse_prometheus(text)
    frame = mxt_top.render(samples, None, 0)
    assert "hbm params" in frame and "1.0MB" in frame \
        and "(peak 2.0MB)" in frame
    assert "hbm kv_cache" in frame
    assert "goodput" in frame and "0.875" in frame
    # top lost causes, largest first
    assert frame.index("checkpoint 12.50s") < frame.index("compile 3.25s")
    assert "watchdog stalls  2" in frame
    # a trainer without the diagnostics layer shows no memory noise
    bare = mxt_top.render(mxt_top.parse_prometheus("up 1\n"), None, 0)
    assert "hbm" not in bare and "goodput" not in bare


def test_host_sync_lint_covers_diagnostics():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_host_syncs as lint
    finally:
        sys.path.pop(0)
    assert "mxnet_tpu/diagnostics.py" in lint.SCAN
    assert lint.SCAN["mxnet_tpu/diagnostics.py"] == lint._ALL
    bad = lint.check(ROOT)
    assert bad == [], "unmarked sync points: %r" % bad


def test_window_states_snapshot():
    import jax.numpy as jnp

    w = engine.InflightWindow(name="ws_probe")
    staged = jnp.arange(4, dtype=jnp.float32)
    with engine.bulk(4):
        w.push(jnp.float32(0.0), value=staged)
    states = {s["name"]: s for s in engine.window_states()}
    st = states["ws_probe"]
    assert st["pending"] == 1 and st["staged"] == 1
    assert st["held_bytes"] == staged.nbytes  # the staged f32[4]
    w.flush()
    st = {s["name"]: s for s in engine.window_states()}["ws_probe"]
    assert st["pending"] == 0 and st["held_bytes"] == 0
