"""contrib.svrg_optimization (ref: tests/python/unittest/
test_contrib_svrg_module.py, test_contrib_svrg_optimizer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.svrg_optimization import SVRGModule, _SVRGOptimizer
from mxnet_tpu.test_utils import with_seed


def _linreg_symbol():
    data = mx.sym.var("data")
    label = mx.sym.var("lin_label")
    fc = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    return mx.sym.LinearRegressionOutput(fc, label, name="lro")


def _make_iter(n=64, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 3)).astype(np.float32)
    w = np.array([[2.0, -3.0, 0.5]], dtype=np.float32)
    y = x @ w.T + 0.01 * rng.randn(n, 1).astype(np.float32)
    return mx.io.NDArrayIter(x, y, batch_size=batch, label_name="lin_label")


def _new_module(update_freq=2):
    return SVRGModule(_linreg_symbol(), data_names=("data",),
                      label_names=("lin_label",), update_freq=update_freq)


def test_update_freq_validation():
    with pytest.raises(ValueError):
        _new_module(update_freq=0)


@with_seed()
def test_bind_and_aux_module():
    mod = _new_module()
    it = _make_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    assert mod.binded and mod._mod_aux.binded
    mod.init_params()
    arg, _ = mod.get_params()
    arg_aux, _ = mod._mod_aux.get_params()
    for k in arg:
        np.testing.assert_array_equal(arg[k].asnumpy(),
                                      arg_aux[k].asnumpy())


@with_seed()
def test_update_full_grads_is_dataset_mean():
    mod = _new_module()
    it = _make_iter(n=32, batch=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),))
    mod.update_full_grads(it)
    assert set(mod._param_dict) == {"fc_weight", "fc_bias"}
    # manual mean of per-batch gradients at the same (snapshot) weights
    it.reset()
    sums, nb = {}, 0
    for batch in it:
        mod._mod_aux.forward_backward(batch)
        for name in ("fc_weight", "fc_bias"):
            g = mod._mod_aux._exec.grad_dict[name].asnumpy()
            sums[name] = sums.get(name, 0) + g
        nb += 1
    for name in sums:
        np.testing.assert_allclose(mod._param_dict[name].asnumpy(),
                                   sums[name] / nb, rtol=1e-5, atol=1e-6)


@with_seed()
def test_svrg_grad_at_snapshot_equals_full_grad():
    """The defining identity: with w == w_snapshot, the variance-reduced
    gradient g_i(w) - g_i(w_snap) + mu collapses to mu for every batch."""
    mod = _new_module()
    it = _make_iter(n=32, batch=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(
        optimizer="sgd",
        optimizer_params=(("learning_rate", 0.0),))  # freeze weights
    mod.update_full_grads(it)
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    for name in ("fc_weight", "fc_bias"):
        g = mod._exec.grad_dict[name]
        g_snap = mod._mod_aux._exec.grad_dict[name]
        combined = (g - g_snap + mod._param_dict[name]).asnumpy()
        np.testing.assert_allclose(combined,
                                   mod._param_dict[name].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


@with_seed()
def test_svrg_fit_converges():
    mod = _new_module(update_freq=2)
    it = _make_iter(n=64, batch=8)
    mod.fit(it, num_epoch=30, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05),),
            eval_metric="mse")
    w = mod.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_allclose(w, [[2.0, -3.0, 0.5]], atol=0.15)


@with_seed()
def test_svrg_optimizer_dispatch():
    opt = _SVRGOptimizer(default_optimizer="sgd", learning_rate=0.5,
                         param_idx2name={0: "w", 1: "w_full"})
    w = mx.nd.ones((2,))
    g = mx.nd.ones((2,)) * 4.0
    # param key: sgd step w -= lr * g
    opt.update(0, w, g, opt.create_state(0, w))
    np.testing.assert_allclose(w.asnumpy(), [-1.0, -1.0], rtol=1e-6)
    # full-grad key: assignment
    slot = mx.nd.zeros((2,))
    opt.update(1, slot, g, opt.create_state(1, slot))
    np.testing.assert_allclose(slot.asnumpy(), [4.0, 4.0], rtol=1e-6)
