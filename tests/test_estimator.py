"""gluon.contrib.Estimator (ref: python/mxnet/gluon/contrib/estimator/)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import Estimator
from mxnet_tpu.gluon.contrib.estimator import (
    CheckpointHandler, EarlyStoppingHandler, EventHandler, LoggingHandler)


def _toy_loader(n=128, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.uniform(-1, 1, (n, 8)).astype("f4")
    w = rng.uniform(-1, 1, (8,))
    y = (x @ w > 0).astype("f4")
    return [(nd.array(x[i:i + batch]), nd.array(y[i:i + batch]))
            for i in range(0, n, batch)]


def _net():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    return net


def test_fit_improves_accuracy():
    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "adam",
                                             {"learning_rate": 0.01}))
    data = _toy_loader()
    est.fit(data, epochs=5)
    name, acc = est.train_metrics[0].get()
    assert name == "accuracy" and acc > 0.8, acc


def test_evaluate_and_val_metrics():
    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    vals = est.evaluate(_toy_loader(seed=1))
    assert vals[0][0] == "accuracy" and 0.0 <= vals[0][1] <= 1.0


def test_event_handler_order_and_counts():
    calls = []

    class Spy(EventHandler):
        def train_begin(self, e):
            calls.append("train_begin")

        def epoch_begin(self, e):
            calls.append("epoch_begin")

        def batch_end(self, e):
            calls.append("batch_end")

        def epoch_end(self, e):
            calls.append("epoch_end")

        def train_end(self, e):
            calls.append("train_end")

    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss())
    est.fit(_toy_loader(n=64), epochs=2, event_handlers=[Spy()])
    assert calls[0] == "train_begin" and calls[-1] == "train_end"
    assert calls.count("epoch_begin") == 2
    assert calls.count("batch_end") == 4  # 64/32 per epoch x 2


def test_early_stopping(caplog):
    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "sgd",
                                             {"learning_rate": 0.0}))
    # lr=0: nothing improves, patience=1 must cut the run short
    stopper = EarlyStoppingHandler(patience=1)
    est.fit(_toy_loader(), epochs=10, event_handlers=[stopper])
    assert est.epoch < 9


def test_checkpoint_handler(tmp_path):
    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(_toy_loader(n=64), epochs=2,
            event_handlers=[CheckpointHandler(str(tmp_path))])
    saved = sorted(p.name for p in tmp_path.iterdir())
    assert saved == ["model-0000.params", "model-0001.params"]
    net2 = _net()
    net2.load_parameters(str(tmp_path / "model-0001.params"))


def test_rejects_non_metric():
    with pytest.raises(mx.MXNetError):
        Estimator(_net(), mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                  metrics="accuracy")


def test_fit_with_dataiter_resets_epochs():
    """DataIter inputs must be reset per epoch (not exhausted once)."""
    net = _net()
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (64, 8)).astype("f4")
    y = (x.sum(axis=1) > 0).astype("f4")
    it = mx.io.NDArrayIter(x, y, 16)
    counts = []

    class Count(EventHandler):
        def epoch_end(self, e):
            counts.append(e.batch_idx + 1)

    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(it, epochs=3, event_handlers=[Count()])
    assert counts == [4, 4, 4], counts


def test_early_stopping_without_val_uses_train_metric():
    """Default monitor must fall back to a train metric that saw data
    (val_metrics exist but are empty without val_data -> NaN trap)."""
    net = _net()
    est = Estimator(net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "adam",
                                             {"learning_rate": 0.02}))
    stopper = EarlyStoppingHandler(patience=3)
    est.fit(_toy_loader(), epochs=6, event_handlers=[stopper])
    assert not np.isnan(stopper._best)
