"""Telemetry subsystem (mxnet_tpu/telemetry.py): typed metrics registry,
step-phase spans, distributed RPC tracing, and export.

The load-bearing properties:

- registry semantics: log-bucket histograms, label dedup (same labels →
  the SAME child), kind/schema mismatch is a hard error, everything
  survives a thread hammer;
- the step timeline costs ZERO new host syncs: a fused run with the
  JSONL sink on performs exactly as many device reads as with it off,
  and every dispatched step retires exactly once;
- a trace id injected at a KVStore push is observable in the
  server-side span log of a real in-process AsyncParamServer round-trip;
- the JSONL sink is flushed (durably on disk) by ``nd.waitall()``;
- ``render_prometheus()`` is format-stable and exposes the acceptance
  metrics (step latency, dispatch depth, RPC latency, lost workers,
  skipped non-finite steps).
"""
import json
import os
import threading
import uuid

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, nd, profiler, resilience, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn

_loss_fn = mx.gluon.loss.L2Loss()


@pytest.fixture(autouse=True)
def _drained():
    """Leave no in-flight tokens behind for the next test."""
    yield
    engine.wait_all()


def _uname(base):
    """Registry-unique metric name (the default registry is process
    global; tests must not collide with each other or the framework)."""
    return "%s_%s" % (base, uuid.uuid4().hex[:8])


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------
def test_histogram_buckets_merge_quantile():
    h = telemetry.Histogram("h", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["counts"] == [1, 1, 1, 1]  # one per bucket + one +Inf
    assert snap["count"] == 4 and snap["sum"] == 555.5

    other = telemetry.Histogram("h2", buckets=(1.0, 10.0, 100.0))
    other.observe(2.0)
    h.merge(other)
    assert h.snapshot()["counts"] == [1, 2, 1, 1]
    assert h.snapshot()["count"] == 5
    assert h.quantile(0.5) == 10.0  # rank 2.5 lands in the (1,10] bucket

    mismatched = telemetry.Histogram("h3", buckets=(2.0, 20.0))
    with pytest.raises(MXNetError):
        h.merge(mismatched)

    # boundary values are inclusive (Prometheus le semantics)
    edge = telemetry.Histogram("h4", buckets=(1.0, 10.0))
    edge.observe(1.0)
    assert edge.snapshot()["counts"][0] == 1

    # default buckets are log-scale and cover us .. minutes
    assert telemetry.DEFAULT_BUCKETS[0] == 1e-6
    assert telemetry.DEFAULT_BUCKETS[-1] > 600


def test_registry_dedup_and_mismatch():
    name = _uname("requests_total")
    fam = telemetry.counter(name, "x", ("code",))
    assert telemetry.counter(name, "ignored", ("code",)) is fam
    # label dedup: identical label values return the SAME child cell
    assert fam.labels(code="200") is fam.labels(code="200")
    assert fam.labels(code="200") is not fam.labels(code="500")
    with pytest.raises(MXNetError):
        telemetry.counter(name, labelnames=("other",))  # schema mismatch
    with pytest.raises(MXNetError):
        telemetry.gauge(name)  # kind mismatch
    with pytest.raises(MXNetError):
        fam.labels(nope="1")  # unknown label
    with pytest.raises(MXNetError):
        fam.labels()  # missing label


def test_registry_thread_hammer():
    n_threads, per_thread = 8, 2000
    c = telemetry.counter(_uname("hammer_total"))
    g = telemetry.gauge(_uname("hammer_gauge"))
    h = telemetry.histogram(_uname("hammer_seconds"), labelnames=("p",))

    def hammer(tid):
        cell = h.labels(p=str(tid % 2))
        for i in range(per_thread):
            c.inc()
            g.inc()
            cell.observe(1e-5 * (i % 7 + 1))

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert c.value == total
    assert g.value == total
    got = sum(h.labels(p=s).snapshot()["count"] for s in ("0", "1"))
    assert got == total


def test_render_prometheus_golden():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("requests_total", "Total requests.", ("code",))
    c.labels(code="200").inc(3)
    c.labels(code="500").inc()
    reg.gauge("queue_depth", "Depth.").set(2)
    h = reg.histogram("latency_seconds", "Latency.", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    expected = "\n".join([
        '# HELP latency_seconds Latency.',
        '# TYPE latency_seconds histogram',
        'latency_seconds_bucket{le="0.1"} 1',
        'latency_seconds_bucket{le="1"} 2',
        'latency_seconds_bucket{le="+Inf"} 3',
        'latency_seconds_sum 5.55',
        'latency_seconds_count 3',
        '# HELP queue_depth Depth.',
        '# TYPE queue_depth gauge',
        'queue_depth 2',
        '# HELP requests_total Total requests.',
        '# TYPE requests_total counter',
        'requests_total{code="200"} 3',
        'requests_total{code="500"} 1',
    ]) + "\n"
    assert reg.render_prometheus() == expected


# ---------------------------------------------------------------------------
# step-phase timeline: 3-step fused run
# ---------------------------------------------------------------------------
def _make_net(prefix):
    mx.random.seed(7)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _fused_syncs(prefix):
    """Host syncs over a 3-step fused window (compile/warmup excluded)."""
    net, tr = _make_net(prefix)
    step = tr.fuse_step(net, _loss_fn)
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (8, 8)).astype(np.float32))
    y = nd.array(rng.uniform(-1, 1, (8, 4)).astype(np.float32))
    with engine.bulk(2):
        step(x, y)
        nd.waitall()  # build + compile + land the warmup token
        h0 = profiler.host_sync_count()
        for _ in range(3):
            step(x, y)
        nd.waitall()
        return profiler.host_sync_count() - h0


def test_step_timeline_three_step_run_no_new_syncs(monkeypatch, tmp_path):
    path = str(tmp_path / "spans.jsonl")

    def latency_count():
        return telemetry.histogram(
            "mxt_step_latency_seconds",
            labelnames=("stream",)).labels("fused_step") \
            .snapshot()["count"]

    monkeypatch.delenv("MXT_TELEMETRY_JSONL", raising=False)
    syncs_off = _fused_syncs("tl_off_")

    monkeypatch.setenv("MXT_TELEMETRY_JSONL", path)
    n0 = latency_count()
    syncs_on = _fused_syncs("tl_on_")

    # telemetry (registry + JSONL sink) adds ZERO host syncs to the hot
    # path: identical runs read the device identically either way
    assert syncs_on == syncs_off

    # every dispatched step retired exactly once into the latency
    # histogram (warmup + 3 timed steps)
    assert latency_count() - n0 == 4

    telemetry.flush()
    rows = [json.loads(line) for line in open(path)]
    retire = [r for r in rows if r.get("kind") == "span"
              and r.get("name") == "retire"
              and r.get("stream") == "fused_step"]
    # exactly ONE retire span per step, in dispatch order
    assert [r["step"] for r in retire] == [1, 2, 3, 4]
    phases = {r.get("name") for r in rows if r.get("kind") == "span"}
    assert {"dispatch", "in_flight", "retire"} <= phases
    # the dispatch-depth occupancy histogram saw the window fill
    occ = telemetry.registry().get("mxt_dispatch_depth_occupancy")
    assert occ is not None and occ.snapshot()["count"] >= 4


def test_dataloader_data_wait_phase():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    x = np.arange(32, dtype="f4").reshape(8, 4)
    loader = DataLoader(ArrayDataset(x), batch_size=4)
    h = telemetry.histogram("mxt_step_phase_seconds",
                            labelnames=("phase",)).labels("data_wait")
    n0 = h.snapshot()["count"]
    batches = list(loader)
    assert len(batches) == 2
    assert h.snapshot()["count"] - n0 == 2  # one data_wait per batch


# ---------------------------------------------------------------------------
# distributed RPC tracing
# ---------------------------------------------------------------------------
def test_rpc_trace_roundtrip_through_real_server():
    from mxnet_tpu import async_server

    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    cli = async_server.AsyncClient("127.0.0.1", port)
    tid = "feedface%08x" % os.getpid()
    try:
        telemetry.clear_rpc_spans()
        with telemetry.trace_scope(tid) as scoped:
            assert scoped == tid
            cli.request("init", "0", np.ones((2, 2)))
            cli.request("push", "0", np.full((2, 2), 3.0))
            pulled = cli.request("pull", "0")
        np.testing.assert_array_equal(pulled, np.full((2, 2), 3.0))
        spans = telemetry.rpc_spans()
        srv_push = [s for s in spans if s["side"] == "server"
                    and s["op"] == "push"]
        cli_push = [s for s in spans if s["side"] == "client"
                    and s["op"] == "push"]
        # the injected trace id crossed the wire and is observable in
        # the SERVER-side span log for that very RPC
        assert srv_push and srv_push[-1]["trace_id"] == tid
        assert cli_push and cli_push[-1]["trace_id"] == tid
        # client and server logged the SAME attempt span
        assert cli_push[-1]["span_id"] == srv_push[-1]["span_id"]
        assert srv_push[-1]["status"] == "ok"
        assert srv_push[-1]["bytes"] and srv_push[-1]["latency_s"] >= 0
        # every op of the scope shares the one trace (init/push/pull)
        scoped_ops = {s["op"] for s in spans if s["trace_id"] == tid}
        assert {"init", "push", "pull"} <= scoped_ops
    finally:
        cli.close()
        srv.close()

    # per-op RPC metrics landed for both sides
    fam = telemetry.registry().get("mxt_kvstore_rpc_latency_seconds")
    assert fam.labels("server", "push").snapshot()["count"] >= 1
    assert fam.labels("client", "pull").snapshot()["count"] >= 1


def test_rpc_spans_without_explicit_trace():
    """AsyncClient generates a trace per request when no scope is
    installed — frames are never untraced."""
    from mxnet_tpu import async_server

    srv = async_server.AsyncParamServer("127.0.0.1", 0)
    port = srv._sock.getsockname()[1]
    cli = async_server.AsyncClient("127.0.0.1", port)
    try:
        telemetry.clear_rpc_spans()
        cli.request("init", "k", np.zeros(3))
        spans = [s for s in telemetry.rpc_spans()
                 if s["side"] == "server" and s["op"] == "init"]
        assert spans and spans[-1]["trace_id"]
    finally:
        cli.close()
        srv.close()


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------
def test_jsonl_sink_flush_on_waitall(monkeypatch, tmp_path):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("MXT_TELEMETRY_JSONL", path)
    telemetry.emit_event("unit_test_event", payload=42)
    nd.waitall()  # the barrier flushes the sink
    rows = [json.loads(line) for line in open(path)]
    mine = [r for r in rows if r.get("kind") == "unit_test_event"]
    assert mine and mine[0]["payload"] == 42
    assert "ts" in mine[0]


def test_render_exposes_acceptance_metrics():
    """render_prometheus() carries at least: step latency, dispatch
    depth, KVStore RPC latency, lost workers, skipped non-finite
    steps."""
    from mxnet_tpu import membership

    telemetry.record_step_retired("selftest", 1, 1e-3)
    telemetry.record_rpc("server", "push", seconds=1e-4, nbytes=64,
                         trace=("t", "s", 0), key="0")
    resilience.record_skipped_step(0)
    membership.record_lost_workers(0)
    profiler.set_gauge("dispatch_depth", 0)
    text = telemetry.render_prometheus()
    for needed in ("mxt_step_latency_seconds_bucket",
                   "dispatch_depth",
                   "mxt_kvstore_rpc_latency_seconds_bucket",
                   "lost_workers",
                   "skipped_nonfinite_steps",
                   "mxt_host_syncs_total",
                   "mxt_xla_launches_total"):
        assert needed in text, "missing %s in exposition" % needed


def test_http_endpoint_serves_metrics():
    import urllib.request

    srv = telemetry.start_http_server(0)
    port = srv.server_address[1]
    assert telemetry.http_port() == port
    telemetry.counter(_uname("http_probe_total")).inc()
    with urllib.request.urlopen("http://127.0.0.1:%d/metrics" % port,
                                timeout=5) as r:
        body = r.read().decode("utf-8")
    assert "# TYPE" in body and "http_probe_total" in body


def test_mxt_top_parses_exposition():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import mxt_top
    finally:
        sys.path.pop(0)
    text = ('a_total{x="1"} 3\n'
            'lat_bucket{le="0.1"} 1\n'
            'lat_bucket{le="+Inf"} 4\n'
            'lat_count 4\n')
    s = mxt_top.parse_prometheus(text)
    assert mxt_top.metric_sum(s, "a_total") == 3
    p50, p99 = mxt_top.histogram_quantiles(s, "lat", (0.5, 0.99))
    assert p50 == 0.1 or p50 is not None


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------
def test_monitor_default_stat_single_batched_read():
    mon = mx.monitor.Monitor(interval=1)
    mon.tic()
    rng = np.random.RandomState(3)
    arrs = [nd.array(rng.normal(size=(4, 5)).astype("f4"))
            for _ in range(6)]
    h0 = profiler.host_sync_count()
    for i, a in enumerate(arrs):
        mon.stat_helper("tap%d" % i, a)
    assert profiler.host_sync_count() == h0  # stats stay on device
    stats = mon.toc()
    assert profiler.host_sync_count() - h0 == 1  # ONE read per tap batch
    assert len(stats) == 6
    for (_, _, v), a in zip(stats, arrs):
        np.testing.assert_allclose(
            v, np.abs(a.asnumpy()).mean(), rtol=1e-6)


def test_speedometer_jsonl_async_health_fields(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    speedo = mx.callback.Speedometer(8, frequent=2, jsonl=path,
                                     config="telemetry_test")

    class _P:
        epoch = 0
        eval_metric = None
        nbatch = 0

    for i in range(5):
        p = _P()
        p.nbatch = i
        profiler.record_host_sync()
        profiler.record_launch(2)
        speedo(p)
    rows = [json.loads(line) for line in open(path)]
    assert len(rows) == 2  # batches 2 and 4
    for row in rows:
        assert "dispatch_depth" in row
        assert row["launches_per_step"] >= 1.0
        assert row["host_syncs_per_step"] >= 0.0
    # reset-aware: a counter reset mid-window must not go negative
    profiler.reset_host_sync_count()
    profiler.reset_launch_count()
    p = _P()
    p.nbatch = 6
    speedo(p)
    rows = [json.loads(line) for line in open(path)]
    assert rows[-1]["host_syncs_per_step"] >= 0.0
    assert rows[-1]["launches_per_step"] >= 0.0


def test_bench_telemetry_ab_smoke(monkeypatch, tmp_path):
    """The tier-1 telemetry-overhead smoke: the A/B row runs and shows
    host-sync parity between telemetry on and off (the ≤3% step-time
    bar is asserted loosely here — CI wall clocks are noisy; the bench
    row carries the real number)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(bench, "JSONL_PATH", str(tmp_path / "b.jsonl"))
    monkeypatch.setenv("BENCH_TAB_ITERS", "6")
    monkeypatch.setenv("BENCH_TAB_WARMUP", "1")
    monkeypatch.setenv("BENCH_TAB_HIDDEN", "16")
    monkeypatch.setenv("BENCH_TAB_BATCH", "8")
    overhead, row = bench.bench_telemetry_ab("cpu", "float32")
    assert row["config"] == "fused_step_telemetry_ab"
    # the acceptance invariant: telemetry adds NO host syncs
    assert row["host_syncs_per_step_on"] == row["host_syncs_per_step_off"]
    assert row["jsonl_events"] > 0
    assert 0.0 < overhead < 3.0  # sanity, not the 3% bar (CI noise)


def test_profiler_shims_ride_registry():
    """counter_value/set_gauge still work AND the values show in the
    Prometheus exposition (the registry is the one storage)."""
    name = _uname("shim_counter")
    ctr = profiler.Counter(None, name, 0)
    ctr.increment(5)
    assert profiler.counter_value(name) == 5
    assert name in profiler._counters  # the live-view back-compat path
    gname = _uname("shim_gauge")
    profiler.set_gauge(gname, 7)
    assert profiler.gauge_value(gname) == 7
    text = telemetry.render_prometheus()
    assert name in text and gname in text
