"""Training-health plane (mxnet_tpu/health.py): per-layer stats computed
INSIDE the donated step, staged through the InflightWindow, anomaly
detection at retirement, the declarative rules engine, the fleet skew
watch, and the perf-regression gate.

The load-bearing properties:

- arming MXT_HEALTH adds ZERO host syncs: sync counts are bit-equal on
  vs off (the stat row rides the window's staged value channel, and in
  guard mode the guard bit packs into the row's last column so flags
  and stats retire from the SAME stacked read);
- numerics are untouched: losses and weights bit-identical on vs off,
  guard on and off, fused and sharded;
- a seeded ``grad_spike`` chaos fault is detected (typed event +
  counter) within one window retirement of the firing step.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, health, nd, profiler, resilience, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import Trainer, nn

_loss_fn = mx.gluon.loss.L2Loss()


@pytest.fixture(autouse=True)
def _drained(tmp_path, monkeypatch):
    """Leave no in-flight tokens, fault rules, default-rule state, or
    cwd post-mortem dumps behind for the next test (NaN-injection tests
    trip the nonfinite anomaly, whose post-mortem defaults to cwd)."""
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    yield
    engine.wait_all()
    resilience.reset_faults()
    health.reset()


def _make(prefix, health_on, guard=False, monkeypatch=None):
    monkeypatch.setenv("MXT_HEALTH", "1" if health_on else "0")
    monkeypatch.setenv("MXT_SKIP_NONFINITE", "1" if guard else "0")
    mx.random.seed(11)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    return net, tr, tr.fuse_step(net, _loss_fn)


def _batches(n, nan_at=None, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for t in range(n):
        x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
        y = rng.uniform(-1, 1, (8, 4)).astype(np.float32)
        if t == nan_at:
            x[0, 0] = np.nan
        out.append((nd.array(x), nd.array(y)))
    return out


def _weights(net):
    return [p.data().asnumpy().copy()
            for _, p in sorted(net.collect_params().items())]


# ---------------------------------------------------------------------------
# stat packing: layout + on-device row
# ---------------------------------------------------------------------------
def test_stat_layout_columns():
    cols = health.stat_layout(["a", "b"])
    assert cols == ["loss", "grad_norm:a", "grad_norm:b",
                    "param_norm:a", "param_norm:b",
                    "update_ratio:a", "update_ratio:b", "nonfinite"]
    assert len(cols) == 3 * 2 + 2


def test_stat_row_values_and_guard_bit():
    import jax.numpy as jnp

    loss = jnp.array([1.0, 3.0], jnp.float32)
    g = (jnp.array([3.0, 4.0], jnp.float32),)
    old = (jnp.array([1.0, 0.0], jnp.float32),)
    new = (jnp.array([0.0, 0.0], jnp.float32),)
    row = np.asarray(health.stat_row(loss, g, old, new))
    assert row.shape == (3 * 1 + 2,)
    assert row[0] == pytest.approx(2.0)        # mean loss
    assert row[1] == pytest.approx(5.0)        # grad L2
    assert row[2] == pytest.approx(0.0)        # new param norm
    assert row[3] == pytest.approx(1.0)        # ||new-old||/||old||
    assert row[4] == 0.0                        # no guard mask -> 0
    # the guard bit packs ONLY this step's (newest) mask bit
    row = np.asarray(health.stat_row(
        loss, g, old, new, mask=jnp.uint32(0b101)))
    assert row[4] == 1.0
    row = np.asarray(health.stat_row(
        loss, g, old, new, mask=jnp.uint32(0b10)))
    assert row[4] == 0.0


# ---------------------------------------------------------------------------
# the zero-sync contract: fused step, guard off and on
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("guard,nan_at", [(False, None), (True, 4)])
def test_fused_step_health_sync_and_numeric_parity(monkeypatch, guard,
                                                   nan_at):
    """10 steps at window K=4 with the health plane off vs on: host
    sync counts BIT-EQUAL, losses and weights BIT-IDENTICAL, and the
    guard's skip bookkeeping unchanged (the stat row is an extra
    output of the same program, never a second read — in guard mode
    the non-finite flag retires from the row's own last column)."""
    def run(health_on):
        net, tr, step = _make("hp%d%d_" % (health_on, guard),
                              health_on, guard=guard,
                              monkeypatch=monkeypatch)
        data = _batches(10, nan_at=nan_at)
        losses = []
        s0 = profiler.host_sync_count()
        with engine.bulk(4):
            for x, y in data:
                losses.append(step(x, y))
            nd.waitall()
        syncs = profiler.host_sync_count() - s0
        assert step.fused, getattr(step, "_fallback_reason", None)
        out = [v.asnumpy() for v in losses]
        return out, _weights(net), syncs, step, tr._optimizer.num_update

    off_l, off_w, off_s, _, off_n = run(False)
    on_l, on_w, on_s, step, on_n = run(True)
    assert off_s == on_s, \
        "health plane added host syncs: %d -> %d" % (off_s, on_s)
    assert off_n == on_n  # guard skip bookkeeping identical
    for a, b in zip(off_l, on_l):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(off_w, on_w):
        np.testing.assert_array_equal(a, b)
    # the monitor consumed every retired row exactly once
    assert step._health_mon is not None
    assert step._health_mon._seen == 10


def test_health_off_builds_no_monitor(monkeypatch):
    _, _, step = _make("hoff_", False, monkeypatch=monkeypatch)
    assert step._health_mon is None


def test_fused_step_health_gauges_published(monkeypatch):
    net, _, step = _make("hg_", True, monkeypatch=monkeypatch)
    with engine.bulk(2):
        for x, y in _batches(4):
            step(x, y)
        nd.waitall()
    reg = telemetry.registry()
    assert reg.get("mxt_health_loss_ema") is not None
    fam = reg.get("mxt_health_grad_norm")
    layers = {v[0] for v in fam.children() if v[0].startswith("hg_")}
    # one per-layer series per trainable parameter of the 2-Dense net
    assert layers == set(step._health_mon.layer_names)
    assert len(layers) == 4


# ---------------------------------------------------------------------------
# detectors (host-side, synthetic rows through the real consume path)
# ---------------------------------------------------------------------------
def _row(loss, gnorms, uratio=0.01, bit=0.0):
    l = len(gnorms)
    return np.array([loss] + list(gnorms) + [1.0] * l
                    + [uratio] * l + [bit], dtype=np.float64)


def _events(stream):
    from mxnet_tpu import diagnostics

    return [e for e in diagnostics.recorder().events()
            if e.get("kind") == "health_anomaly"
            and e.get("stream") == stream]


def test_loss_spike_detector(monkeypatch):
    monkeypatch.setenv("MXT_HEALTH_POSTMORTEM", "0")
    mon = health.HealthMonitor(["d0"], stream="t_spike")
    rng = np.random.RandomState(0)
    for i in range(12):  # noisy-but-sane warmup (sd must be > 0)
        mon.consume(i, _row(1.0 + 0.02 * rng.randn(), [0.5]))
    assert mon.anomaly_count == 0
    mon.consume(12, _row(50.0, [0.5]))
    assert mon.anomaly_count == 1
    evs = _events("t_spike")
    assert evs and evs[-1]["detector"] == "loss_spike"
    assert evs[-1]["layer"] == "loss" and evs[-1]["step"] == 12


def test_grad_explosion_and_nonfinite(monkeypatch):
    monkeypatch.setenv("MXT_HEALTH_POSTMORTEM", "0")
    mon = health.HealthMonitor(["d0", "d1"], stream="t_exp")
    mon.consume(0, _row(1.0, [0.5, 0.5]))
    mon.consume(1, _row(1.0, [0.5, 5e6]))   # > MXT_HEALTH_EXPLODE
    mon.consume(2, _row(1.0, [np.inf, 0.5]))
    kinds = [(e["detector"], e["layer"]) for e in _events("t_exp")]
    assert ("grad_explosion", "d1") in kinds
    assert ("grad_explosion", "d0") in kinds
    fam = telemetry.registry().get("mxt_health_anomalies_total")
    assert fam.labels("grad_explosion", "d1").value >= 1


def test_dead_layer_needs_consecutive_run(monkeypatch):
    monkeypatch.setenv("MXT_HEALTH_POSTMORTEM", "0")
    monkeypatch.setenv("MXT_HEALTH_DEAD_STEPS", "3")
    mon = health.HealthMonitor(["d0"], stream="t_dead")
    for i in range(2):
        mon.consume(i, _row(1.0, [1e-12]))
    mon.consume(2, _row(1.0, [0.5]))         # run broken
    assert mon.anomaly_count == 0
    for i in range(3, 6):
        mon.consume(i, _row(1.0, [1e-12]))
    assert mon.anomaly_count == 1            # fires exactly once at 3
    assert _events("t_dead")[-1]["detector"] == "dead_layer"


def test_guard_hook_routes_explosions(monkeypatch):
    monkeypatch.setenv("MXT_HEALTH_POSTMORTEM", "0")
    calls = []
    monkeypatch.setenv("MXT_HEALTH_GUARD_HOOK", "0")
    mon = health.HealthMonitor(["d0"], stream="t_hk0",
                               guard_hook=lambda: calls.append(1))
    mon.consume(0, _row(1.0, [5e6]))
    assert not calls                          # hook gated off by default
    monkeypatch.setenv("MXT_HEALTH_GUARD_HOOK", "1")
    mon = health.HealthMonitor(["d0"], stream="t_hk1",
                               guard_hook=lambda: calls.append(1))
    mon.consume(0, _row(1.0, [5e6]))
    assert calls == [1]


# ---------------------------------------------------------------------------
# seeded grad_spike chaos: detection within one retirement window
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_grad_spike_detected_within_one_window(monkeypatch, tmp_path):
    """MXT_FAULT=grad_spike seeds ONE gradient spike after dispatch 3;
    the detectors catch it (typed flight-recorder event + counter + one
    post-mortem) no later than one InflightWindow retirement after the
    firing step. The spike itself compiles into the step program and
    fires with the health plane OFF too — watching never changes the
    numerics, so losses match bit-exactly watched vs unwatched."""
    monkeypatch.setenv("MXT_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("MXT_CHAOS_SEED",
                       os.environ.get("MXT_CHAOS_SEED", "42"))
    K, steps, after = 4, 12, 3

    def run(health_on, prefix):
        monkeypatch.setenv(
            "MXT_FAULT", "grad_spike:layer=0,after=%d,scale=1e6,n=1"
            % after)
        resilience.reset_faults()
        net, _, step = _make(prefix, health_on,
                             monkeypatch=monkeypatch)
        losses = []
        with engine.bulk(K):
            for x, y in _batches(steps):
                losses.append(step(x, y))
            nd.waitall()
        return [v.asnumpy() for v in losses], step

    watched_l, step = run(True, "csp1_")
    mon = step._health_mon
    assert mon.anomaly_count > 0, "seeded grad spike never detected"
    evs = _events("fused_step")
    assert evs, "no typed health_anomaly event recorded"
    first = min(e["step"] for e in evs)
    assert first <= after + 1 + K, \
        "detection step %d later than one window after the spike" % first
    assert any(e["detector"] == "grad_explosion" for e in evs)
    fam = telemetry.registry().get("mxt_health_anomalies_total")
    assert sum(c.value for _, c in fam.children().items()) > 0
    assert list(tmp_path.glob("mxt-postmortem-*.json")), \
        "anomaly post-mortem not dumped"

    unwatched_l, _ = run(False, "csp0_")
    for a, b in zip(watched_l, unwatched_l):
        np.testing.assert_array_equal(a, b)


def test_grad_spike_scale_host_side(monkeypatch):
    monkeypatch.setenv("MXT_FAULT",
                       "grad_spike:layer=0,after=3,scale=1e5,n=1")
    monkeypatch.setenv("MXT_CHAOS_SEED", "42")
    resilience.reset_faults()
    scales = [health.grad_spike_scale(i) for i in range(1, 10)]
    assert all(s == 1.0 for s in scales[:3])  # before after=3: never
    assert scales.count(1e5) == 1             # n=1: exactly one firing
    resilience.reset_faults()
    monkeypatch.delenv("MXT_FAULT")
    resilience.reset_faults()
    assert health.grad_spike_scale(99) == 1.0  # no rule -> no-op


# ---------------------------------------------------------------------------
# rules engine
# ---------------------------------------------------------------------------
def _uname(base):
    _uname.n += 1
    return "%s_%d" % (base, _uname.n)


_uname.n = 0


def test_threshold_rule():
    name = _uname("t_health_skew")
    telemetry.gauge(name, "t").set(2.0)
    r = health.HealthRule("skew_hi", name, kind="threshold", op=">",
                          value=1.5)
    v = r.evaluate()
    assert v["ok"] is False and v["value"] == 2.0
    telemetry.gauge(name, "t").set(1.0)
    assert r.evaluate()["ok"] is True


def test_threshold_rule_no_data_is_none():
    r = health.HealthRule("nodata", _uname("t_health_missing"))
    v = r.evaluate()
    assert v["ok"] is None and v["detail"] == "no data"


def test_burn_rate_rule():
    name = _uname("t_health_burn")
    c = telemetry.counter(name, "t")
    c.inc(0)  # materialize the series (a never-bumped counter is no-data)
    r = health.HealthRule("burn", name, kind="burn_rate", op=">",
                          value=0.0)
    assert r.evaluate(now=100.0)["ok"] is None  # warming (1 sample)
    c.inc(5)
    v = r.evaluate(now=101.0)
    assert v["ok"] is False and v["value"] == pytest.approx(5.0)
    v = r.evaluate(now=102.0)                   # flat -> burn stopped
    assert v["ok"] is True


def test_trend_rule_slope_over_window():
    name = _uname("t_health_trend")
    g = telemetry.gauge(name, "t")
    r = health.HealthRule("rising", name, kind="trend", op=">",
                          value=0.0, window=60.0)
    g.set(1.0)
    r.evaluate(now=0.0)
    g.set(1.5)
    r.evaluate(now=10.0)
    g.set(2.0)
    v = r.evaluate(now=20.0)
    assert v["ok"] is False
    assert v["value"] == pytest.approx(0.05)    # slope over the window
    g.set(0.5)
    assert r.evaluate(now=30.0)["ok"] is True


def test_rule_validation_typed_errors():
    with pytest.raises(MXNetError):
        health.HealthRule("bad", "m", kind="gradient")
    with pytest.raises(MXNetError):
        health.HealthRule("bad", "m", op="!=")


def test_rule_engine_publishes_verdict_gauges():
    name = _uname("t_health_eng")
    telemetry.gauge(name, "t").set(9.0)
    eng = health.RuleEngine()
    eng.add(health.HealthRule("eng_hi", name, kind="threshold", op=">",
                              value=1.0))
    eng.evaluate()
    fam = telemetry.registry().get("mxt_health_rule_ok")
    assert fam.labels("eng_hi").value == 0.0    # breached
    telemetry.gauge(name, "t").set(0.5)
    eng.evaluate()
    assert fam.labels("eng_hi").value == 1.0


def test_default_rules_cover_training_and_serving():
    names = {r.name for r in health.default_engine().rules()}
    assert {"train_anomaly_burn", "loss_rising", "step_skew",
            "moe_router_drop_burn"} <= names
    # the serving SLO rules join the same engine
    assert "serving_p99_latency" in names


# ---------------------------------------------------------------------------
# fleet skew watch
# ---------------------------------------------------------------------------
def _member_export(step_ms, fingerprint):
    return {"families": [
        {"name": "mxt_health_host_step_ms", "kind": "gauge", "help": "",
         "labelnames": [], "children": [[[], step_ms]]},
        {"name": "mxt_health_grad_fingerprint", "kind": "gauge",
         "help": "", "labelnames": [], "children": [[[], fingerprint]]},
    ]}


def test_fleet_skew_straggler_and_divergence():
    from mxnet_tpu import diagnostics, telemetry_fleet

    freg = telemetry_fleet.FleetRegistry()
    freg.ingest("host-a", _member_export(10.0, 1.00))
    freg.ingest("host-b", _member_export(11.0, 1.01))
    freg.ingest("host-c", _member_export(40.0, 1.00))  # straggler
    freg.ingest("host-d", _member_export(10.5, 9.00))  # divergent
    v = health.fleet_skew(freg, skew_ratio=1.5, divergence=0.5)
    assert v["slowest"] == "host-c"
    assert v["stragglers"] == ["host-c"]
    assert v["divergent"] == ["host-d"]
    assert v["ok"] is False and v["skew_ratio"] > 1.5
    reg = telemetry.registry()
    assert reg.get("mxt_health_step_skew_ratio").value == \
        pytest.approx(v["skew_ratio"])
    assert reg.get("mxt_health_slowest_host_step_ms") \
        .labels("host-c").value == 40.0
    assert reg.get("mxt_health_fleet_ok").value == 0.0
    assert any(e.get("kind") == "health_fleet_skew"
               for e in diagnostics.recorder().events())


def test_fleet_skew_healthy_fleet():
    from mxnet_tpu import telemetry_fleet

    freg = telemetry_fleet.FleetRegistry()
    for m, ms in (("a", 10.0), ("b", 10.4), ("c", 9.8)):
        freg.ingest(m, _member_export(ms, 2.0))
    v = health.fleet_skew(freg, skew_ratio=1.5, divergence=0.5)
    assert v["ok"] is True and not v["stragglers"]
    assert telemetry.registry().get("mxt_health_fleet_ok").value == 1.0


def test_fleet_member_values_per_host_view():
    from mxnet_tpu import telemetry_fleet

    freg = telemetry_fleet.FleetRegistry()
    freg.ingest("a", _member_export(5.0, 1.0))
    freg.ingest("b", _member_export(7.0, 1.0), stale=True)
    vals = freg.member_values("mxt_health_host_step_ms")
    assert vals == {"a": 5.0}                  # stale members drop out
    assert freg.member_values("mxt_health_host_step_ms",
                              include_stale=True) == {"a": 5.0,
                                                      "b": 7.0}
    assert freg.member_values("mxt_no_such_metric") == {}


# ---------------------------------------------------------------------------
# /health route + mxt_top section
# ---------------------------------------------------------------------------
def test_health_route_payload_and_status():
    status, ctype, body = health.handle_health()
    assert ctype == "application/json"
    doc = json.loads(body)
    assert {"status", "rules", "anomalies", "breached"} <= set(doc)
    # the LB contract: 200 iff the payload itself says ok
    assert (status == 200) == (doc["status"] == "ok")
    rule_names = {r["rule"] for r in doc["rules"]}
    assert "train_anomaly_burn" in rule_names


def test_health_route_served_over_http():
    import urllib.request

    srv = telemetry.start_http_server(0)
    port = srv.server_address[1]
    url = "http://127.0.0.1:%d/health" % port
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            code, body = r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:       # 503 = degraded, still JSON
        code, body = e.code, e.read().decode("utf-8")
    assert code in (200, 503)
    doc = json.loads(body)
    assert doc["status"] in ("ok", "degraded")


def _mxt_top():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import mxt_top
    finally:
        sys.path.pop(0)
    return mxt_top


def test_mxt_top_health_section_golden():
    top = _mxt_top()
    text = "\n".join([
        "mxt_health_loss_ema 0.421",
        "mxt_health_host_step_ms 12.5",
        "mxt_health_step_skew_ratio 2.10",
        'mxt_health_anomalies_total{kind="grad_explosion",layer="d1"} 3',
        'mxt_health_anomalies_total{kind="loss_spike",layer="loss"} 1',
        'mxt_health_rule_ok{rule="loss_rising"} 1',
        'mxt_health_rule_ok{rule="step_skew"} 0',
    ]) + "\n"
    frame = top.render(top.parse_prometheus(text), None, 0)
    assert "health loss ema" in frame
    assert "0.421" in frame and "12.5" in frame
    assert "step skew" in frame and "2.10" in frame
    assert "grad_explosion:d1=3" in frame
    assert "loss_spike:loss=1" in frame
    assert "rules" in frame and "1 ok / 1 breached" in frame
    assert "step_skew" in frame                # the breached rule named
    # a run with the health plane dark renders NO health noise
    bare = top.render(top.parse_prometheus("up 1\n"), None, 0)
    assert "health loss ema" not in bare


# ---------------------------------------------------------------------------
# lint: the health plane itself stays sync-clean
# ---------------------------------------------------------------------------
def test_health_host_sync_lint_enforced():
    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert "mxnet_tpu/health.py" in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root) if b[0] == "mxnet_tpu/health.py"]
    assert not bad, bad


# ---------------------------------------------------------------------------
# perf-regression gate (tools/bench_regression.py)
# ---------------------------------------------------------------------------
def _bench_regression():
    spec = importlib.util.spec_from_file_location(
        "bench_regression", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "bench_regression.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _brow(step_ms=None, tput=None, config="r50", platform="cpu"):
    row = {"config": config, "platform": platform, "chips": 1,
           "batch_size": 8, "dtype": "float32"}
    if step_ms is not None:
        row["step_time_ms"] = step_ms
    if tput is not None:
        row["images_or_tokens_per_sec_per_chip"] = tput
    return row


def test_regression_gate_flags_slowdown():
    br = _bench_regression()
    hist = [_brow(step_ms=v) for v in (100.0, 98.0, 102.0, 101.0)]
    v, = br.judge(hist, [_brow(step_ms=150.0)])  # injected 1.5x
    assert v["verdict"] == "REGRESSION"
    v, = br.judge(hist, [_brow(step_ms=103.0)])
    assert v["verdict"] == "OK"
    v, = br.judge(hist, [_brow(step_ms=60.0)])
    assert v["verdict"] == "IMPROVED"          # informational, never fails


def test_regression_gate_throughput_direction():
    br = _bench_regression()
    hist = [_brow(tput=v) for v in (1000.0, 990.0, 1010.0)]
    v, = br.judge(hist, [_brow(tput=500.0)])
    assert v["verdict"] == "REGRESSION"        # lower throughput = worse
    v, = br.judge(hist, [_brow(tput=1500.0)])
    assert v["verdict"] == "IMPROVED"


def test_regression_gate_keys_and_history_floor():
    br = _bench_regression()
    hist = [_brow(step_ms=100.0), _brow(step_ms=100.0)]
    v, = br.judge(hist, [_brow(step_ms=500.0)])
    assert v["verdict"] == "INSUFFICIENT_HISTORY"  # 2 prior < 3
    # keys never cross platforms: axon history is no cpu baseline
    hist = [_brow(step_ms=10.0, platform="axon") for _ in range(5)]
    v, = br.judge(hist, [_brow(step_ms=100.0, platform="cpu")])
    assert v["verdict"] == "INSUFFICIENT_HISTORY"
    v, = br.judge([], [_brow()])
    assert v["verdict"] == "NO_METRIC"


def test_regression_gate_noisy_history_widens_band():
    br = _bench_regression()
    # 2x spread in history: rel-MAD * 3 beats the 0.25 default band
    hist = [_brow(step_ms=v) for v in (50.0, 100.0, 150.0, 100.0)]
    v, = br.judge(hist, [_brow(step_ms=150.0)])
    assert v["verdict"] == "OK" and v["band"] > 0.25


def test_regression_gate_clean_on_recorded_trajectory(capsys):
    """The repo's own bench_results.jsonl must pass its own gate — the
    newest row per key against the trajectory before it."""
    br = _bench_regression()
    assert br.main([]) == 0


def test_regression_gate_exit_code_on_injected_row(tmp_path):
    br = _bench_regression()
    hist = tmp_path / "hist.jsonl"
    hist.write_text("".join(
        json.dumps(_brow(step_ms=v)) + "\n"
        for v in (100.0, 99.0, 101.0, 100.0)))
    cand = tmp_path / "cand.jsonl"
    cand.write_text(json.dumps(_brow(step_ms=150.0)) + "\n")
    assert br.main(["--history", str(hist),
                    "--candidate", str(cand)]) == 1
    cand.write_text(json.dumps(_brow(step_ms=101.0)) + "\n")
    assert br.main(["--history", str(hist),
                    "--candidate", str(cand)]) == 0
    assert br.main(["--history", str(tmp_path / "missing.jsonl")]) == 0


# ---------------------------------------------------------------------------
# sharded step parity + the reshard standing item with health armed
# ---------------------------------------------------------------------------
def test_sharded_step_health_numeric_parity(monkeypatch):
    """ShardedTrainStep with health on vs off: losses bit-equal, every
    retired row consumed. The health stream adds exactly the sanctioned
    one-deferred-read-per-K budget and NOTHING when dark (the stream
    only exists when armed)."""
    from mxnet_tpu import parallel

    def run(health_on):
        monkeypatch.setenv("MXT_HEALTH", "1" if health_on else "0")
        mx.random.seed(7)
        net = nn.HybridSequential(prefix="shh%d_" % health_on)
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", in_units=4),
                    nn.Dense(3, in_units=16))
        net.initialize()
        mesh = parallel.make_mesh(axis_names=("data",))
        step = parallel.ShardedTrainStep(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=mesh)
        rng = np.random.RandomState(0)
        losses = []
        with engine.bulk(4):
            for _ in range(8):
                x = rng.uniform(-1, 1, (16, 4)).astype(np.float32)
                y = rng.randint(0, 3, (16,)).astype(np.float32)
                losses.append(step(nd.array(x), nd.array(y)))
            out = [float(v.asscalar()) for v in losses]
            nd.waitall()
        return out, step

    off_l, off_step = run(False)
    on_l, on_step = run(True)
    assert off_l == on_l
    assert off_step._health_mon is None and off_step._stream is None
    assert on_step._health_mon._seen == 8
    assert on_step._health_mon.stream == "sharded_step"


def test_reshard_acceptance_with_health_armed():
    """The elastic-reshard acceptance (tests/test_reshard.py standing
    item: subprocess-isolated, inner verdict asserted) still passes
    with the health plane armed — the stat row is an extra step output,
    not part of the spill/restore payload."""
    env = dict(os.environ)
    env["MXT_HEALTH"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    test = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "test_reshard.py")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-x",
         "%s::test_elastic_reshard_acceptance" % test,
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        env=env, timeout=600, capture_output=True, text=True)
    assert r.returncode == 0, \
        "reshard acceptance regressed with MXT_HEALTH=1 (rc=%d)\n%s\n%s" \
        % (r.returncode, r.stdout[-4000:], r.stderr[-2000:])


# ---------------------------------------------------------------------------
# bench row smoke: the A/B asserts its own contract
# ---------------------------------------------------------------------------
def test_bench_training_health_ab_row(monkeypatch):
    monkeypatch.setenv("BENCH_HAB_BATCH", "8")
    monkeypatch.setenv("BENCH_HAB_HIDDEN", "32")
    monkeypatch.setenv("BENCH_HAB_ITERS", "6")
    monkeypatch.setenv("BENCH_HAB_WARMUP", "2")
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(bench, "_emit_jsonl", lambda row: None)
    _, row = bench.bench_training_health_ab("cpu", "float32")
    assert row["config"] == "training_health_ab"
    assert row["sync_parity"] is True
    assert row["losses_equal"] is True
    assert row["spike_detected"] is True
