"""mx.image pipeline + MXT_* config tier + AMP tests (models
tests/python/unittest/test_image.py and the contrib amp coverage)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError


def _png_bytes(h, w, seed=0):
    import io
    from PIL import Image

    arr = np.random.RandomState(seed).randint(0, 255, (h, w, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return arr, buf.getvalue()


# ---------------------------------------------------------------------------
# mx.image
# ---------------------------------------------------------------------------
def test_imdecode_roundtrip():
    arr, png = _png_bytes(20, 30)
    img = mx.image.imdecode(png)
    assert img.shape == (20, 30, 3)
    np.testing.assert_array_equal(img.asnumpy(), arr)  # PNG is lossless
    gray = mx.image.imdecode(png, flag=0)
    assert gray.shape == (20, 30, 1)


def test_resize_and_crops():
    arr, png = _png_bytes(40, 60)
    img = mx.image.imdecode(png)
    r = mx.image.resize_short(img, 20)
    assert min(r.shape[:2]) == 20 and r.shape[1] == 30
    f = mx.image.imresize(img, 10, 14)
    assert f.shape == (14, 10, 3)
    c, (x0, y0, w, h) = mx.image.center_crop(img, (20, 20))
    assert c.shape == (20, 20, 3) and (w, h) == (20, 20)
    rc, _ = mx.image.random_crop(img, (16, 16))
    assert rc.shape == (16, 16, 3)
    norm = mx.image.color_normalize(img, mean=(1.0, 2.0, 3.0),
                                    std=(2.0, 2.0, 2.0))
    np.testing.assert_allclose(
        norm.asnumpy(), (arr.astype("f4") - [1, 2, 3]) / 2.0, rtol=1e-6)


def test_create_augmenter_pipeline():
    augs = mx.image.CreateAugmenter((3, 16, 16), resize=20, rand_crop=True,
                                    rand_mirror=True, mean=True, std=True,
                                    brightness=0.1, contrast=0.1,
                                    saturation=0.1)
    arr, png = _png_bytes(40, 50, seed=1)
    img = mx.image.imdecode(png)
    for aug in augs:
        img = aug(img)
    out = img.asnumpy()
    assert out.shape == (16, 16, 3)
    assert out.dtype == np.float32
    assert np.isfinite(out).all()


def test_image_iter_from_imglist(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(0)
    imglist = []
    for i in range(5):
        arr = rng.randint(0, 255, (24 + i, 30, 3), np.uint8)
        fname = "img%d.png" % i
        Image.fromarray(arr).save(tmp_path / fname)
        imglist.append([float(i % 3), fname])
    it = mx.image.ImageIter(batch_size=2, data_shape=(3, 16, 16),
                            imglist=imglist, path_root=str(tmp_path),
                            shuffle=False)
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 16, 16)
    assert batch.label[0].shape == (2,)
    np.testing.assert_array_equal(batch.label[0].asnumpy(), [0, 1])
    batches = [batch] + [b for b in iter(it.next, None)] \
        if False else None
    it.reset()
    n = 0
    while True:
        try:
            b = it.next()
        except StopIteration:
            break
        n += 1
    assert n == 3  # 5 images, batch 2 → 2 full + 1 padded
    del batches


def test_image_iter_from_rec(tmp_path):
    from mxnet_tpu import recordio

    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(1)
    for i in range(4):
        _, png = _png_bytes(20, 20, seed=i)
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, recordio.pack(header, png))
    rec.close()
    it = mx.image.ImageIter(batch_size=2, data_shape=(3, 12, 12),
                            path_imgrec=rec_path, path_imgidx=idx_path)
    b = it.next()
    assert b.data[0].shape == (2, 3, 12, 12)
    np.testing.assert_array_equal(b.label[0].asnumpy(), [0, 1])


# ---------------------------------------------------------------------------
# config tier
# ---------------------------------------------------------------------------
def test_config_env_precedence(monkeypatch):
    assert mx.config.get("MXT_NUM_WORKERS") >= 1
    monkeypatch.setenv("MXT_NUM_WORKERS", "7")
    assert mx.config.get("MXT_NUM_WORKERS") == 7
    monkeypatch.delenv("MXT_NUM_WORKERS")
    mx.config.set_default("MXT_NUM_WORKERS", 3)
    assert mx.config.get("MXT_NUM_WORKERS") == 3
    mx.config.set_default("MXT_NUM_WORKERS", 1)
    with pytest.raises(MXNetError):
        mx.config.get("MXT_NOT_A_VAR")
    monkeypatch.setenv("MXT_PROFILER_AUTOSTART", "true")
    assert mx.config.get("MXT_PROFILER_AUTOSTART") is True
    table = mx.config.describe()
    assert "MXT_ENGINE_TYPE" in table


def test_config_naive_engine_runs_unjitted():
    import jax

    with mx.config.naive_engine():
        assert jax.config.jax_disable_jit
        out = (nd.ones((2, 2)) * 3).asnumpy()
    np.testing.assert_array_equal(out, 3)
    assert not jax.config.jax_disable_jit


# ---------------------------------------------------------------------------
# AMP
# ---------------------------------------------------------------------------
def test_amp_autocast_lists():
    import mxnet_tpu.amp as amp

    amp.init(target_dtype="bfloat16")
    try:
        a = nd.array(np.random.RandomState(0)
                     .normal(size=(4, 8)).astype("f4"))
        b = nd.array(np.random.RandomState(1)
                     .normal(size=(8, 2)).astype("f4"))
        out = nd.dot(a, b)
        assert out.dtype == np.dtype("bfloat16")  # MXU op ran low-precision
        sm = nd.softmax(a)
        assert sm.dtype == np.float32  # sensitive op stayed f32
        bf = a.astype("bfloat16")
        assert nd.softmax(bf).dtype == np.dtype("bfloat16")  # cast back
        with pytest.raises(MXNetError):
            amp.init(target_dtype="float16")  # conflicting re-init
    finally:
        amp._deinit_for_tests()


def test_amp_dynamic_loss_scaling():
    import mxnet_tpu.amp as amp
    from mxnet_tpu import autograd as ag

    net = mx.gluon.nn.Dense(4)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    scaler = trainer._amp_scaler
    scale0 = scaler.loss_scale
    x = nd.array(np.random.RandomState(0).normal(size=(2, 3)).astype("f4"))
    with ag.record():
        loss = (net(x) ** 2).mean()
        # reference usage: scale_loss + backward inside record()
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
    w_before = net.weight.data().asnumpy().copy()
    trainer.step(2)
    assert not np.allclose(net.weight.data().asnumpy(), w_before)

    # overflow: grads forced to inf → step is SKIPPED, scale halves
    w_before = net.weight.data().asnumpy().copy()
    with ag.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    net.weight.data()._grad = nd.full(net.weight.shape, np.inf)
    trainer.step(2)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_before)
    assert scaler.loss_scale == max(1.0, scale0 / 2.0)
