"""linalg op family vs numpy (models the la_op coverage in
tests/python/unittest/test_operator.py::test_laop*)."""
import numpy as np

from mxnet_tpu import nd
from mxnet_tpu.test_utils import with_seed


def _spd(n, batch=(), seed=0):
    rng = np.random.RandomState(seed)
    m = rng.rand(*batch, n, n)
    return m @ np.swapaxes(m, -1, -2) + n * np.eye(n)


@with_seed()
def test_gemm_and_gemm2():
    rng = np.random.RandomState(0)
    A = rng.rand(2, 3, 4)
    B = rng.rand(2, 4, 5)
    C = rng.rand(2, 3, 5)
    out = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5)
    np.testing.assert_allclose(out.asnumpy(), 2.0 * A @ B + 0.5 * C,
                               rtol=1e-5)
    outT = nd.linalg_gemm2(nd.array(A), nd.array(np.swapaxes(B, 1, 2)),
                           transpose_b=True)
    np.testing.assert_allclose(outT.asnumpy(), A @ B, rtol=1e-5)


def test_potrf_potri_roundtrip():
    A = _spd(4, batch=(2,))
    L = nd.linalg_potrf(nd.array(A))
    np.testing.assert_allclose(
        L.asnumpy() @ np.swapaxes(L.asnumpy(), -1, -2), A, rtol=1e-5)
    Ainv = nd.linalg_potri(L)
    np.testing.assert_allclose(Ainv.asnumpy() @ A,
                               np.broadcast_to(np.eye(4), (2, 4, 4)),
                               atol=1e-8)


def test_trsm_trmm():
    rng = np.random.RandomState(1)
    L = np.linalg.cholesky(_spd(3)) + np.eye(3)
    B = rng.rand(3, 2)
    X = nd.linalg_trsm(nd.array(L), nd.array(B), alpha=2.0)
    np.testing.assert_allclose(L @ X.asnumpy(), 2.0 * B, rtol=1e-6)
    Xr = nd.linalg_trsm(nd.array(L), nd.array(B.T), rightside=True)
    np.testing.assert_allclose(Xr.asnumpy() @ L, B.T, rtol=1e-6)
    M = rng.rand(3, 3)
    out = nd.linalg_trmm(nd.array(M), nd.array(B))
    np.testing.assert_allclose(out.asnumpy(), np.tril(M) @ B, rtol=1e-6)


def test_syrk_diag_trian():
    rng = np.random.RandomState(2)
    A = rng.rand(3, 4)
    np.testing.assert_allclose(nd.linalg_syrk(nd.array(A)).asnumpy(),
                               A @ A.T, rtol=1e-6)
    np.testing.assert_allclose(
        nd.linalg_syrk(nd.array(A), transpose=True).asnumpy(),
        A.T @ A, rtol=1e-6)
    v = rng.rand(4)
    D = nd.linalg_makediag(nd.array(v))
    np.testing.assert_allclose(D.asnumpy(), np.diag(v))
    np.testing.assert_allclose(
        nd.linalg_extractdiag(D).asnumpy(), v)
    off = nd.linalg_makediag(nd.array(v), offset=1)
    np.testing.assert_allclose(off.asnumpy(), np.diag(v, k=1))
    packed = rng.rand(6)
    T = nd.linalg_maketrian(nd.array(packed))
    np.testing.assert_allclose(
        nd.linalg_extracttrian(T).asnumpy(), packed)
    assert np.allclose(np.triu(T.asnumpy(), 1), 0)


def test_det_inverse_sumlogdiag():
    A = _spd(3, batch=(2,))
    np.testing.assert_allclose(nd.linalg_det(nd.array(A)).asnumpy(),
                               np.linalg.det(A), rtol=1e-5)
    sign, logabs = nd.linalg_slogdet(nd.array(A))
    s_ref, l_ref = np.linalg.slogdet(A)
    np.testing.assert_allclose(sign.asnumpy(), s_ref)
    np.testing.assert_allclose(logabs.asnumpy(), l_ref, rtol=1e-5)
    inv = nd.linalg_inverse(nd.array(A))
    np.testing.assert_allclose(inv.asnumpy(), np.linalg.inv(A), rtol=1e-4)
    L = np.linalg.cholesky(_spd(3))
    np.testing.assert_allclose(
        nd.linalg_sumlogdiag(nd.array(L)).asnumpy(),
        np.log(np.diag(L)).sum(), rtol=1e-6)
