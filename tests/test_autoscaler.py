"""SLO-driven autoscaler + multi-tenant QoS (serving/autoscaler.py +
serving/qos.py): the control loop that closes the PR 13 observability
loop, plus the priority/quota layer that keeps tenants honest under a
flash crowd.

Covers the FleetAutoscaler's hysteresis (scale UP on backlog, DOWN only
after a calm streak, cooldown between actions, typed floor/ceiling
refusals with `refused` events), the warming→routable spare lifecycle
under the seeded ``replica_spawn_slow`` rule (a slow spare never stalls
the router), the PR 18 lifecycle-race bugfix (``router.drain`` of a
warming / already-draining replica is a typed error, not a silent
no-op), the seeded ``traffic_storm`` flash crowd (deterministic per
MXT_CHAOS_SEED) with the zero-lost accounting acceptance, per-tenant
quotas (typed OverQuotaError, refunds on finish, replays never
re-charge), priority-aware dispatch + preemption ordering (bulk evicted
strictly before interactive; the preempted request re-enqueues and
replays token-exact), decode-worker fleet resize, the mxt_top
autoscale/tenant section, and the host-sync lint gate over both new
modules.
"""
import os
import sys
import time

import numpy as np
import pytest

from mxnet_tpu import resilience, serving, telemetry, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (DecodeEngine, FleetRouter, PagedKVCache,
                               TinyDecoder)
from mxnet_tpu.serving import metrics as _m
from mxnet_tpu.serving.autoscaler import (AutoscalerError, FleetAutoscaler,
                                          TrafficGenerator)
from mxnet_tpu.serving.fleet import ROUTABLE, WARMING, LocalReplica
from mxnet_tpu.serving.qos import (OverQuotaError, QosPolicy, TenantSpec,
                                   PRIORITY_CLASSES)


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch, tmp_path):
    """Failovers must surface in milliseconds, not the production 30s
    retry budget; every test gets its own tuning table and a clean
    trace-span log (the autoscaler records decision spans)."""
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    telemetry.clear_trace_spans()
    yield
    telemetry.clear_trace_spans()
    tuning.reset()


MODEL = TinyDecoder(vocab=64, num_layers=1, num_heads=2, head_dim=8,
                    max_len=256)
PARAMS = MODEL.init_params(3)

_FREE_ENGINES = []  # drained engines recycled across tests (compile cost)


def _factory():
    while _FREE_ENGINES:
        eng = _FREE_ENGINES.pop()
        if eng.cache.pages_in_use() == 0 and not eng._seq_of_slot:
            return eng
    return DecodeEngine(
        MODEL, params=PARAMS, slots=2,
        cache=PagedKVCache(1, 2, 8, num_pages=64, page_size=8),
        prefill_buckets=(16,), max_context=64)


def _fleet(n, now_fn=time.monotonic):
    return serving.local_serving_fleet(n, _factory, now_fn=now_fn,
                                       warm=False)


def _close(pool, srv):
    for h in pool.replicas():
        if h.engine is not None and h.state != "dead":
            _FREE_ENGINES.append(h.engine)
        try:
            h.close()
        except Exception:  # noqa: BLE001 — killed handles
            pass
    srv.close()


def _ref(prompt, n):
    return MODEL.reference_decode(PARAMS, list(prompt), n)


def _scaler(router, clock_now, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("cooldown", 0.0)
    kw.setdefault("queue_high", 1.0)
    kw.setdefault("occ_low", 1.0)
    kw.setdefault("calm_ticks", 10 ** 6)
    kw.setdefault("warm", False)
    return FleetAutoscaler(router, _factory, now_fn=clock_now, **kw)


def _span_names(scaler):
    scaler._collector.scrape()
    return {s["name"] for s in scaler._collector.spans(scaler.trace_id)}


# ---------------------------------------------------------------------------
# bugfix regression: drain vs the replica lifecycle
# ---------------------------------------------------------------------------
def test_drain_warming_spare_is_typed_error():
    """Draining a spare still warming must refuse typed — the old
    silent no-op let the spare register AFTER the drain and serve
    anyway (the lifecycle race this PR fixes)."""
    clock = [0.0]
    pool, srv = _fleet(1, now_fn=lambda: clock[0])
    router = FleetRouter(pool, now_fn=lambda: clock[0])
    spare = LocalReplica(1, _factory, coordinator=pool.coordinator,
                         now_fn=lambda: clock[0])
    spare.prepare(warm=False)
    pool.add(spare)
    with pytest.raises(MXNetError, match="warming"):
        router.drain(1)
    assert spare.state == WARMING  # the refusal touched nothing
    spare.go_routable()
    pool.publish()
    router.drain(1)  # routable now: the same call succeeds
    assert spare.state != ROUTABLE
    _close(pool, srv)


def test_double_drain_is_typed_error():
    pool, srv = _fleet(2)
    router = FleetRouter(pool)
    router.drain(1)
    with pytest.raises(MXNetError, match="drain"):
        router.drain(1)  # draining: no admission left to stop
    router.step()        # empty replica finishes its drain
    with pytest.raises(MXNetError, match="drain"):
        router.drain(1)  # drained: still a typed error, not a no-op
    assert len(pool.routable()) == 1
    _close(pool, srv)


# ---------------------------------------------------------------------------
# the control loop: up on backlog, down after calm, typed at the rails
# ---------------------------------------------------------------------------
def test_scale_up_on_backlog_all_complete():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(1, now_fn=now)
    router = FleetRouter(pool, now_fn=now)
    scaler = _scaler(router, now)
    rng = np.random.RandomState(_seed() + 1)
    reqs = [router.submit(rng.randint(1, 64, 4).tolist(),
                          max_new_tokens=3, token="up%d" % i)
            for i in range(8)]
    assert scaler.step() == "up"  # queue 8 >= queue_high * capacity
    assert scaler.replica_target() == 2
    assert len(pool.routable()) == 2  # no spawn delay: routable at once
    guard = 0
    while router.step() and guard < 3000:
        clock[0] += 0.05
        scaler.step()
        guard += 1
    assert guard < 3000
    assert 2 <= len(pool.routable()) <= scaler.max_replicas
    for rr in reqs:
        assert rr.state == "completed"
        assert rr.result == _ref(rr.prompt, 3)
    ups = [d for d in scaler.decisions if d["direction"] == "up"]
    assert ups and ups[0]["seq"] == 1
    assert "queue=" in ups[0]["reason"]
    # the decision is a first-class event on the fleet trace timeline
    names = _span_names(scaler)
    assert "scale_up" in names
    assert "replica_routable" in names
    scaler.close()
    _close(pool, srv)


def test_scale_down_needs_calm_streak_and_cooldown_no_flap():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(2, now_fn=now)
    router = FleetRouter(pool, now_fn=now)
    scaler = _scaler(router, now, cooldown=1.0, calm_ticks=3)
    # hysteresis: two calm ticks are not enough
    assert scaler.step() is None
    clock[0] += 0.1
    assert scaler.step() is None
    clock[0] += 0.1
    assert scaler.step() == "down"  # third consecutive calm evaluation
    assert len(pool.routable()) == 1
    router.step()  # the drained-empty replica deregisters
    # cooldown + floor: never a second action, never below min_replicas
    for _ in range(8):
        clock[0] += 0.5
        assert scaler.step() is None
    assert len(pool.routable()) == 1
    assert [d["direction"] for d in scaler.decisions] == ["down"]
    scaler.close()
    _close(pool, srv)


def test_hot_sample_resets_calm_streak():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(2, now_fn=now)
    router = FleetRouter(pool, now_fn=now)
    scaler = _scaler(router, now, max_replicas=2, calm_ticks=3)
    assert scaler.step() is None
    assert scaler.step() is None  # calm streak at 2
    rng = np.random.RandomState(_seed() + 4)
    reqs = [router.submit(rng.randint(1, 64, 4).tolist(),
                          max_new_tokens=2, token="hot%d" % i)
            for i in range(8)]
    scaler.step()  # hot: resets calm (and may scale up — that's fine)
    while router.step():
        clock[0] += 0.05
    # calm again, but the streak starts OVER: two ticks stay hold
    assert scaler.step() is None
    assert scaler.step() is None
    assert not any(d["direction"] == "down" for d in scaler.decisions)
    assert all(rr.state == "completed" for rr in reqs)
    scaler.close()
    _close(pool, srv)


def test_scale_to_explicit_and_typed_refusals():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(1, now_fn=now)
    router = FleetRouter(pool, now_fn=now)
    scaler = _scaler(router, now)
    refused0 = _m.autoscale_events_total().labels("refused").value
    assert scaler.scale_to(3) == 3
    assert len(pool.routable()) == 3
    with pytest.raises(AutoscalerError, match="refused"):
        scaler.scale_to(0)  # an operator typo cannot black-hole the fleet
    with pytest.raises(AutoscalerError, match="refused"):
        scaler.scale_to(4)
    assert _m.autoscale_events_total().labels("refused").value \
        == refused0 + 2
    assert scaler.scale_to(1) == 1
    router.step()  # drained replicas deregister
    assert len(pool.routable()) == 1
    with pytest.raises(AutoscalerError, match="floor"):
        scaler._scale_down(None, now())  # the loop-level guard, typed too
    seq = [d["direction"] for d in scaler.decisions]
    assert seq.count("refused") == 3
    assert seq.count("up") == 2 and seq.count("down") == 2
    scaler.close()
    _close(pool, srv)


def test_autoscaler_ctor_bounds_typed():
    pool, srv = _fleet(1)
    router = FleetRouter(pool)
    with pytest.raises(AutoscalerError, match="floor"):
        FleetAutoscaler(router, _factory, min_replicas=0)
    with pytest.raises(AutoscalerError, match="below its floor"):
        FleetAutoscaler(router, _factory, min_replicas=3, max_replicas=2)
    _close(pool, srv)


# ---------------------------------------------------------------------------
# chaos: slow spare warm-up + the seeded flash crowd
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_spawn_slow_spare_never_stalls_router(monkeypatch):
    monkeypatch.setenv("MXT_FAULT", "replica_spawn_slow:ms=500")
    resilience.reset_faults()
    try:
        clock = [0.0]
        now = lambda: clock[0]  # noqa: E731
        pool, srv = _fleet(1, now_fn=now)
        router = FleetRouter(pool, now_fn=now)
        scaler = _scaler(router, now, max_replicas=2)
        rng = np.random.RandomState(_seed() + 2)
        reqs = [router.submit(rng.randint(1, 64, 4).tolist(),
                              max_new_tokens=3, token="sl%d" % i)
                for i in range(6)]
        assert scaler.step() == "up"
        spare = pool.get(1)
        assert spare.state == WARMING  # held by the 500ms warm horizon
        assert len(pool.routable()) == 1
        # the router keeps serving off the seed replica the whole time
        for _ in range(6):
            clock[0] += 0.05  # stays under the horizon
            router.step()
            assert scaler.step() is None  # one spare warming: no pile-on
        assert spare.state == WARMING
        done_during_warm = sum(1 for rr in reqs if rr.done)
        assert done_during_warm > 0
        clock[0] += 1.0  # past the horizon: the next tick promotes
        scaler.step()
        assert spare.state == ROUTABLE
        assert len(pool.routable()) == 2
        guard = 0
        while router.step() and guard < 2000:
            clock[0] += 0.05
            guard += 1
        for rr in reqs:
            assert rr.state == "completed"
            assert rr.result == _ref(rr.prompt, 3)
        assert "replica_routable" in _span_names(scaler)
        scaler.close()
        _close(pool, srv)
    finally:
        monkeypatch.delenv("MXT_FAULT", raising=False)
        resilience.reset_faults()


@pytest.mark.chaos
def test_traffic_storm_deterministic_and_tenant_tagged(monkeypatch):
    monkeypatch.setenv("MXT_FAULT",
                       "traffic_storm:rps=40,after=3,tenant=bulk")
    resilience.reset_faults()
    try:
        pool, srv = _fleet(1)

        def offer(prefix):
            router = FleetRouter(pool)
            gen = TrafficGenerator(router, rate=1.0, seed=_seed() + 7,
                                   vocab=64, max_requests=10,
                                   prefix=prefix)
            t = 0.0
            while gen.total_offered() < 10 and t < 30.0:
                gen.tick(t)
                t += 0.1
            return gen, t

        g1, t1 = offer("s1")
        g2, t2 = offer("s2")
        assert g1.storm is not None and g1.storm[0] == 40
        assert g1.total_offered() == 10
        # the storm is deterministic: same seed, same arrivals
        assert [rr.prompt for rr in g1.submitted] \
            == [rr.prompt for rr in g2.submitted]
        assert t1 == t2
        # ... and it IS a storm: 10 arrivals land far faster than the
        # 1 rps base rate could deliver them
        assert t1 < 3.0
        # storm traffic carries the rule's tenant tag
        assert any(rr.tenant == "bulk" for rr in g1.submitted)
        assert {rr.tenant for rr in g1.submitted} <= {None, "bulk"}
        _close(pool, srv)
    finally:
        monkeypatch.delenv("MXT_FAULT", raising=False)
        resilience.reset_faults()


@pytest.mark.chaos
def test_flash_crowd_scales_up_zero_lost(monkeypatch):
    """The acceptance loop: a seeded flash crowd hits the 1-replica
    floor, the autoscaler grows the fleet, and EVERY offered request is
    accounted — submitted == completed + typed-rejected, nothing lost,
    the scale-up visible as spans on the fleet trace timeline."""
    monkeypatch.setenv("MXT_FAULT", "traffic_storm:rps=60,after=2")
    resilience.reset_faults()
    try:
        clock = [0.0]
        now = lambda: clock[0]  # noqa: E731
        pool, srv = _fleet(1, now_fn=now)
        router = FleetRouter(pool, now_fn=now)
        scaler = _scaler(router, now, cooldown=0.3)
        gen = TrafficGenerator(router, rate=2.0, seed=_seed() + 3,
                               vocab=64, prompt_len=(2, 8),
                               max_new_tokens=4, max_requests=14,
                               prefix="fc")
        guard = 0
        while guard < 4000 and (gen.total_offered() < 14
                                or router._queue or router._inflight):
            clock[0] += 0.05
            gen.tick(clock[0])
            router.step()
            scaler.step()
            guard += 1
        assert guard < 4000
        assert gen.total_offered() == 14
        completed = [rr for rr in gen.submitted
                     if rr.state == "completed"]
        # zero lost: offered == committed + typed-rejected
        assert len(completed) + gen.rejected == 14
        for rr in completed:
            assert rr.result == _ref(rr.prompt, 4)
        assert any(d["direction"] == "up" for d in scaler.decisions)
        assert len(pool.routable()) > 1
        assert "scale_up" in _span_names(scaler)
        scaler.close()
        _close(pool, srv)
    finally:
        monkeypatch.delenv("MXT_FAULT", raising=False)
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# multi-tenant QoS: quotas, priority dispatch, preemption
# ---------------------------------------------------------------------------
def test_qos_parse_and_priority_classes():
    qos = QosPolicy.parse("interactive:bulk")
    assert qos.tenants() == ["bulk", "interactive"]
    assert qos.priority_of("interactive") == PRIORITY_CLASSES["interactive"]
    assert qos.priority_of("bulk") == PRIORITY_CLASSES["bulk"]
    # name=class spelling, integer classes, typed on garbage
    qos2 = QosPolicy.parse("web=interactive,batch=7")
    assert qos2.priority_of("web") == 0
    assert qos2.priority_of("batch") == 7
    with pytest.raises(MXNetError, match="neither"):
        QosPolicy.parse("x=fastest")
    with pytest.raises(MXNetError):
        TenantSpec("t", max_requests=0)


def test_over_quota_typed_refund_and_replay_never_recharges():
    pool, srv = _fleet(1)
    qos = QosPolicy()
    qos.add_tenant("bulk", max_requests=2)
    router = FleetRouter(pool, qos=qos)
    rej0 = _m.tenant_rejected_total().labels("bulk").value
    rng = np.random.RandomState(_seed() + 5)
    prompts = [rng.randint(1, 64, 4).tolist() for _ in range(3)]
    rr0 = router.submit(prompts[0], max_new_tokens=3, token="q0",
                        tenant="bulk")
    router.submit(prompts[1], max_new_tokens=3, token="q1",
                  tenant="bulk")
    with pytest.raises(OverQuotaError) as ei:
        router.submit(prompts[2], max_new_tokens=3, token="q2",
                      tenant="bulk")
    assert ei.value.tenant == "bulk"
    assert "NOT enqueued" in str(ei.value)
    assert _m.tenant_rejected_total().labels("bulk").value == rej0 + 1
    assert qos.outstanding("bulk")[0] == 2
    router.run()
    # finish refunds the charge: the refused prompt now admits
    assert qos.outstanding("bulk") == (0, 0)
    rr2 = router.submit(prompts[2], max_new_tokens=3, token="q2",
                        tenant="bulk")
    router.run()
    assert rr2.state == "completed"
    assert rr2.result == _ref(prompts[2], 3)
    # an idempotent replay answers from the record — never re-charges
    again = router.submit(prompts[0], max_new_tokens=3, token="q0",
                          tenant="bulk")
    assert again is rr0
    assert qos.outstanding("bulk") == (0, 0)
    _close(pool, srv)


def test_token_quota_axis_typed():
    qos = QosPolicy()
    qos.add_tenant("bulk", max_tokens=20)
    qos.admit("bulk", 15)
    with pytest.raises(OverQuotaError, match="token quota"):
        qos.admit("bulk", 10)
    qos.release("bulk", 15)
    qos.admit("bulk", 10)  # refunded budget admits again
    assert qos.outstanding("bulk") == (1, 10)


def test_interactive_overtakes_queued_bulk():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(1, now_fn=now)
    router = FleetRouter(pool, now_fn=now, qos=QosPolicy())
    rng = np.random.RandomState(_seed() + 6)
    for i in range(3):
        router.submit(rng.randint(1, 64, 4).tolist(), max_new_tokens=3,
                      token="b%d" % i, tenant="bulk")
    router.submit(rng.randint(1, 64, 4).tolist(), max_new_tokens=3,
                  token="i0", tenant="interactive")
    while router.step():
        clock[0] += 0.05
    order = [rr.token for rr in router.finished]
    # 2 decode slots: the late interactive arrival seats in the FIRST
    # admission wave, ahead of bulk requests queued before it
    assert order.index("i0") <= 1
    _close(pool, srv)


def test_preemption_bulk_evicted_before_interactive_replay_exact():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(1, now_fn=now)
    router = FleetRouter(pool, now_fn=now, qos=QosPolicy())
    pre0 = _m.tenant_preempted_total().labels("bulk").value
    rng = np.random.RandomState(_seed() + 8)
    pb = rng.randint(1, 64, 4).tolist()
    pi1 = rng.randint(1, 64, 4).tolist()
    pi2 = rng.randint(1, 64, 4).tolist()
    bulk = router.submit(pb, max_new_tokens=8, token="pb", tenant="bulk")
    int1 = router.submit(pi1, max_new_tokens=8, token="pi1",
                         tenant="interactive")
    for _ in range(3):  # both seat (2 slots) and decode a few tokens
        router.step()
        clock[0] += 0.05
    assert bulk.state == "dispatched" and int1.state == "dispatched"
    int2 = router.submit(pi2, max_new_tokens=4, token="pi2",
                         tenant="interactive")
    guard = 0
    while router.step() and guard < 2000:
        clock[0] += 0.05
        guard += 1
    # ordering: the bulk request was evicted to seat interactive work —
    # the running interactive request was NEVER touched
    assert bulk.preemptions == 1
    assert int1.preemptions == 0 and int2.preemptions == 0
    assert _m.tenant_preempted_total().labels("bulk").value == pre0 + 1
    # late, never lost: the preempted request re-enqueued and replayed
    # from scratch, token-exact
    for rr, (prompt, n) in ((bulk, (pb, 8)), (int1, (pi1, 8)),
                            (int2, (pi2, 4))):
        assert rr.state == "completed"
        assert rr.result == _ref(prompt, n)
    _close(pool, srv)


def test_qos_isolation_interactive_latency_bounded_under_bulk_flood():
    """The acceptance assert: a bulk tenant saturating admission leaves
    interactive completion within a bounded multiple of unloaded, and
    over-quota bulk is refused typed."""
    def run(nbulk):
        clock = [0.0]
        pool, srv = _fleet(1, now_fn=lambda: clock[0])
        qos = QosPolicy()
        qos.add_tenant("bulk", max_requests=4)
        router = FleetRouter(pool, now_fn=lambda: clock[0], qos=qos)
        rng = np.random.RandomState(11)
        refused = 0
        for i in range(nbulk):
            try:
                router.submit(rng.randint(1, 64, 6).tolist(),
                              max_new_tokens=6, tenant="bulk",
                              token="bg%d-%d" % (nbulk, i))
            except OverQuotaError:
                refused += 1
        inter = [router.submit(rng.randint(1, 64, 4).tolist(),
                               max_new_tokens=3, tenant="interactive",
                               token="in%d-%d" % (nbulk, i))
                 for i in range(2)]
        steps0 = router.steps
        guard = 0
        while not all(rr.done for rr in inter) and guard < 2000:
            router.step()
            clock[0] += 0.05
            guard += 1
        steps_inter = router.steps - steps0
        while router.step():
            clock[0] += 0.05
        assert all(rr.state == "completed" for rr in inter)
        _close(pool, srv)
        return steps_inter, refused

    base, _ = run(0)
    loaded, refused = run(6)
    assert refused == 2  # quota 4, offered 6: the excess refused typed
    assert loaded <= 4 * max(base, 1), (loaded, base)


# ---------------------------------------------------------------------------
# decode-worker fleets: resize + the autoscaler's watermark loop
# ---------------------------------------------------------------------------
def test_worker_fleet_resize_typed_floor_and_cooperative_shrink(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.data_plane import (ArrayDecoder, ChunkLedger,
                                      DecodeWorkerFleet, ShardManifest)

    rec = str(tmp_path / "part-0.rec")
    idx = str(tmp_path / "part-0.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for gid in range(40):
        w.write_idx(gid, recordio.pack(
            recordio.IRHeader(0, float(gid), gid, 0),
            np.full((4,), gid, np.float32).tobytes()))
    w.close()
    man = ShardManifest([rec], chunk_records=10)
    ledger = ChunkLedger()
    ledger.begin_epoch(man.manifest_id, 0, man.owners(0, 1, seed=1))
    fleet = DecodeWorkerFleet(man, ledger, 0,
                              ArrayDecoder((4,), "float32"), 5,
                              num_workers=1, buffer_batches=2)
    with pytest.raises(MXNetError, match="at least one"):
        fleet.resize(0)
    fleet.start()
    fleet.resize(2)  # grow spawns the missing worker immediately
    assert fleet.num_workers == 2
    got = []
    for data, labels, ids, cid in fleet.batches():
        got.append(ids)
        if len(got) == 2:
            fleet.resize(1)  # shrink mid-stream: cooperative, no loss
    assert fleet.num_workers == 1
    # exactly-once survives the resize: every record delivered once
    assert sorted(i for ids in got for i in ids) \
        == sorted(man.record_ids())
    fleet.close()
    assert fleet.live_workers() == 0


class _FakeWorkerQueue:
    def __init__(self, qsize, maxsize):
        self._n, self.maxsize = qsize, maxsize

    def qsize(self):
        return self._n


class _FakeWorkerFleet:
    """Duck-typed DecodeWorkerFleet: just the watermark surface the
    autoscaler reads (``_q``, ``num_workers``, ``live_workers``,
    ``resize``)."""

    def __init__(self, qsize, maxsize=8, num_workers=2):
        self._q = _FakeWorkerQueue(qsize, maxsize)
        self.num_workers = num_workers
        self.resized = []

    def live_workers(self):
        return self.num_workers

    def resize(self, n):
        self.resized.append(n)
        self.num_workers = n


def test_autoscaler_scales_worker_fleets_on_watermarks():
    clock = [0.0]
    now = lambda: clock[0]  # noqa: E731
    pool, srv = _fleet(1, now_fn=now)
    router = FleetRouter(pool, now_fn=now)
    scaler = _scaler(router, now, cooldown=1.0)
    starved = scaler.attach_worker_fleet(_FakeWorkerFleet(qsize=0))
    flooded = scaler.attach_worker_fleet(_FakeWorkerFleet(qsize=8,
                                                          num_workers=3))
    scaler.step()
    # empty buffer = starving consumer -> grow; full = producers far
    # ahead -> shrink. Each fleet scales INDEPENDENTLY, one worker at
    # a time.
    assert starved.resized == [3]
    assert flooded.resized == [2]
    # per-fleet cooldown: an immediate second tick holds both
    scaler.step()
    assert starved.resized == [3] and flooded.resized == [2]
    clock[0] += 1.5
    scaler.step()
    assert starved.resized == [3, 4]
    assert flooded.resized == [2, 1]
    # floor of 1: no further shrink is ever attempted
    clock[0] += 1.5
    scaler.step()
    assert flooded.resized == [2, 1]
    dirs = [d["direction"] for d in scaler.decisions]
    assert "workers_up" in dirs and "workers_down" in dirs
    scaler.close()
    _close(pool, srv)


# ---------------------------------------------------------------------------
# mxt_top: the autoscale / tenant section (gated on the gauges)
# ---------------------------------------------------------------------------
def _mxt_top():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import mxt_top
    finally:
        sys.path.pop(0)
    return mxt_top


def test_mxt_top_autoscale_section_golden():
    top = _mxt_top()
    text = "\n".join([
        "mxt_autoscale_target_replicas 3",
        'mxt_autoscale_events_total{direction="up"} 2',
        'mxt_autoscale_events_total{direction="refused"} 1',
        'mxt_autoscale_last_decision{direction="up"} 3',
        'mxt_autoscale_last_decision{direction="refused"} 2',
        'mxt_tenant_admitted_total{tenant="bulk"} 5',
        'mxt_tenant_rejected_total{tenant="bulk"} 2',
        'mxt_tenant_preempted_total{tenant="bulk"} 1',
        'mxt_tenant_inflight_requests{tenant="bulk"} 0',
        'mxt_tenant_admitted_total{tenant="interactive"} 4',
    ]) + "\n"
    frame = top.render(top.parse_prometheus(text), None, 0)
    assert "autoscale" in frame
    assert "target 3" in frame
    assert "up 2" in frame and "refused 1" in frame
    # the max decision seq wins: "up" (#3) is the most recent
    assert "last decision" in frame and "up (#3)" in frame
    assert "tenant bulk" in frame
    assert "adm 5" in frame and "rej 2" in frame and "pre 1" in frame
    assert "tenant interactive" in frame
    # an unscaled single-tenant fleet renders NO control-loop noise
    bare = top.render(top.parse_prometheus("up 1\n"), None, 0)
    assert "autoscale" not in bare
    assert "tenant" not in bare


# ---------------------------------------------------------------------------
# lint: the control loop stays host-pure
# ---------------------------------------------------------------------------
def test_autoscaler_qos_lint_enforced():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert "mxnet_tpu/serving/autoscaler.py" in m.SCAN
    assert "mxnet_tpu/serving/qos.py" in m.SCAN
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = [b for b in m.check(root)
           if b[0] in ("mxnet_tpu/serving/autoscaler.py",
                       "mxnet_tpu/serving/qos.py")]
    assert not bad, bad
