"""CTC loss (vs torch ground truth), contrib.io.DataLoaderIter,
gluon.contrib.data samplers/datasets
(ref: tests/python/unittest/{test_loss.py,test_contrib_data}.py)."""
import os
import zipfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.test_utils import with_seed

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402


@with_seed()
def test_ctc_op_matches_torch():
    rng = np.random.RandomState(0)
    T, N, C = 12, 3, 6
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3, 2], [2, 2, 0, 0], [4, 1, 5, 3]])
    lab_lens = np.array([4, 2, 4])
    dat_lens = np.array([12, 9, 12])

    ours = mx.nd.CTCLoss(
        mx.nd.array(logits), mx.nd.array(labels.astype(np.float32)),
        mx.nd.array(dat_lens.astype(np.float32)),
        mx.nd.array(lab_lens.astype(np.float32)),
        use_data_lengths=True, use_label_lengths=True,
        blank_label="first")
    ref = tF.ctc_loss(
        torch.from_numpy(logits).log_softmax(-1),
        torch.from_numpy(labels), torch.from_numpy(dat_lens),
        torch.from_numpy(lab_lens), blank=0, reduction="none")
    np.testing.assert_allclose(ours.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


@with_seed()
def test_ctc_grad_matches_torch():
    rng = np.random.RandomState(2)
    T, N, C = 10, 2, 5
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3], [2, 1, 0]])

    x = mx.nd.array(logits)
    x.attach_grad()
    with autograd.record():
        loss = mx.nd.CTCLoss(x, mx.nd.array(labels.astype(np.float32)),
                             blank_label="first")
    loss.backward()
    tx = torch.from_numpy(logits).requires_grad_()
    tl = tF.ctc_loss(tx.log_softmax(-1), torch.from_numpy(labels),
                     torch.tensor([T, T]), torch.tensor([3, 2]),
                     blank=0, reduction="sum")
    tl.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-4)


@with_seed()
def test_gluon_ctc_loss_blank_last():
    rng = np.random.RandomState(1)
    N, T, C = 2, 10, 5
    pred = rng.randn(N, T, C).astype(np.float32)
    label = np.array([[1, 2, 3], [0, 2, -1]], dtype=np.float32)
    loss = gluon.loss.CTCLoss()(mx.nd.array(pred), mx.nd.array(label))
    ref = tF.ctc_loss(
        torch.from_numpy(pred.transpose(1, 0, 2)).log_softmax(-1),
        torch.from_numpy(np.array([[1, 2, 3], [0, 2, 0]])),
        torch.tensor([T, T]), torch.tensor([3, 2]),
        blank=C - 1, reduction="none")
    np.testing.assert_allclose(loss.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_ctc_trains():
    """CTC decreases when training toward a target sequence."""
    mx.random.seed(0)
    np.random.seed(0)
    N, T, C = 4, 12, 4
    x = mx.nd.random.uniform(shape=(N, T, 8))
    label = mx.nd.array(np.tile([0, 1, 2], (N, 1)).astype(np.float32))
    net = gluon.nn.Dense(C, flatten=False)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05})
    ctc = gluon.loss.CTCLoss()
    first = None
    for _ in range(60):
        with autograd.record():
            loss = ctc(net(x), label)
        loss.backward()
        tr.step(N)
        if first is None:
            first = float(loss.mean().asnumpy())
    final = float(loss.mean().asnumpy())
    assert final < 0.6 * first, (first, final)


@with_seed()
def test_regression_output_flat_label():
    """(B,) label vs (B,1) prediction must reshape, not broadcast
    (ref: regression_output-inl.h label reshape)."""
    x = mx.nd.array(np.random.randn(4, 3).astype(np.float32))
    w = mx.nd.array(np.random.randn(1, 3).astype(np.float32))
    y = mx.nd.array(np.random.randn(4).astype(np.float32))  # flat label
    w.attach_grad()
    with autograd.record():
        pred = mx.nd.FullyConnected(x, w, None, no_bias=True, num_hidden=1)
        out = mx.nd.LinearRegressionOutput(pred, y)
    out.backward()
    g = w.grad.asnumpy()
    manual = ((pred.asnumpy().ravel() - y.asnumpy())[:, None]
              * x.asnumpy()).sum(0, keepdims=True)
    np.testing.assert_allclose(g, manual, rtol=1e-5, atol=1e-6)


def test_softmax_ce_alias():
    assert gluon.loss.SoftmaxCELoss is gluon.loss.SoftmaxCrossEntropyLoss


def test_dataloader_iter():
    from mxnet_tpu.contrib.io import DataLoaderIter

    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20, dtype=np.float32)
    ds = gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
    loader = gluon.data.DataLoader(ds, batch_size=5)
    it = DataLoaderIter(loader)
    assert it.provide_data[0].shape == (5, 2)
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    first = next(iter(it))
    np.testing.assert_array_equal(first.data[0].asnumpy(), x[:5])


def test_interval_sampler():
    from mxnet_tpu.gluon.contrib.data import IntervalSampler

    s = list(IntervalSampler(10, 3))
    assert s == [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    assert len(IntervalSampler(10, 3)) == 10
    s2 = list(IntervalSampler(10, 3, rollover=False))
    assert s2 == [0, 3, 6, 9]


def test_wikitext_parsing(tmp_path, monkeypatch):
    """Dataset parses a locally-cached corpus (no egress needed)."""
    from mxnet_tpu.gluon.contrib.data import WikiText2

    root = tmp_path / "wt2"
    root.mkdir()
    text = "the cat sat\nthe dog sat\nthe cat ran\n" * 20
    (root / "wiki.train.tokens").write_text(text)
    ds = WikiText2(root=str(root), segment="train", seq_len=5)
    assert len(ds) > 10
    d, l = ds[0]
    assert d.shape == (5,) and l.shape == (5,)
    # label is the next-token shift of data across the flat stream
    d2, _ = ds[1]
    flat = np.concatenate([d.asnumpy(), d2.asnumpy()])
    np.testing.assert_array_equal(l.asnumpy(), flat[1:6])
    # vocabulary covers the corpus
    assert set(ds.vocabulary.to_indices(["the", "cat", "<eos>"]))


def test_wikitext_fails_loudly_without_cache(tmp_path):
    from mxnet_tpu.gluon.contrib.data import WikiText2

    with pytest.raises(Exception):
        WikiText2(root=str(tmp_path / "empty"), segment="train")
