"""Profiler + Monitor tests (models tests/python/unittest/test_profiler.py
and the Monitor usage in python/mxnet/monitor.py docstrings)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.base import MXNetError


def test_profiler_trace_roundtrip(tmp_path):
    """set_config → run → ops → stop leaves a Perfetto trace on disk."""
    trace_dir = str(tmp_path / "prof")
    mx.profiler.set_config(filename=trace_dir, profile_all=True)
    mx.profiler.set_state("run")
    assert mx.profiler.state() == "run"
    a = nd.array(np.random.RandomState(0).normal(size=(64, 64)).astype("f4"))
    nd.dot(a, a).asnumpy()
    mx.profiler.set_state("stop")
    assert mx.profiler.state() == "stop"
    # jax writes plugins/profile/<date>/*.trace.json.gz under the log dir
    found = []
    for root, _, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "no trace files written under %s" % trace_dir


def test_profiler_dump_and_state_errors(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "p2"))
    with pytest.raises(MXNetError):
        mx.profiler.set_state("bogus")
    mx.profiler.start()
    with pytest.raises(MXNetError):
        mx.profiler.set_config(filename="nope")  # reconfig while running
    out = mx.profiler.dump()
    assert mx.profiler.state() == "stop"
    assert out and os.path.isdir(out)
    with pytest.raises(MXNetError):
        mx.profiler.set_config(not_an_option=1)


def test_profiler_scopes_and_dumps():
    dom = mx.profiler.Domain("test")
    task = mx.profiler.Task("work", domain=dom)
    with task:
        x = nd.ones((8, 8))
        (x + x).wait_to_read()
    with mx.profiler.Frame("frame1"):
        pass
    ctr = mx.profiler.Counter(dom, "steps", 0)
    ctr.increment(3)
    mx.profiler.Marker(dom, "tick").mark()
    table = mx.profiler.dumps()
    assert "test::work" in table
    assert "frame1" in table
    assert "test::steps" in table and "value=3" in table
    # pause suppresses aggregation
    mx.profiler.pause()
    with mx.profiler.Task("paused_work"):
        pass
    mx.profiler.resume()
    table = mx.profiler.dumps(reset=True)
    assert "paused_work" not in table
    assert mx.profiler.dumps() .count("::") == 0  # reset cleared entries


def _mlp_module():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=["data"], label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    return mod


def test_monitor_collects_op_outputs():
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=1, sort=True)
    mod.install_monitor(mon)
    rng = np.random.RandomState(0)
    from mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[nd.array(rng.normal(size=(8, 10)).astype("f4"))],
                      label=[nd.array(rng.randint(0, 4, (8,)).astype("f4"))])
    mon.tic()
    mod.forward(batch, is_train=True)
    stats = mon.toc()
    names = [n for _, n, _ in stats]
    assert any(n.startswith("fc1") for n in names), names
    assert any(n.startswith("relu1") for n in names), names
    assert any(n.startswith("softmax") for n in names), names
    for _, _, v in stats:
        assert np.isfinite(v)


def test_monitor_interval_and_pattern():
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=2, pattern=".*fc.*")
    mod.install_monitor(mon)
    rng = np.random.RandomState(1)
    from mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[nd.array(rng.normal(size=(8, 10)).astype("f4"))],
                      label=[nd.array(rng.randint(0, 4, (8,)).astype("f4"))])
    seen = []
    for _ in range(4):
        mon.tic()
        mod.forward(batch, is_train=False)
        seen.append(mon.toc())
    # interval=2 → batches 0 and 2 collect, 1 and 3 don't
    assert seen[0] and not seen[1] and seen[2] and not seen[3]
    for _, name, _ in seen[0]:
        assert "fc" in name, name


def test_monitor_monitor_all_includes_inputs():
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=1)
    mod.install_monitor(mon, monitor_all=True)
    rng = np.random.RandomState(2)
    from mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[nd.array(rng.normal(size=(8, 10)).astype("f4"))],
                      label=[nd.array(rng.randint(0, 4, (8,)).astype("f4"))])
    mon.tic()
    mod.forward(batch, is_train=False)
    names = [n for _, n, _ in mon.toc()]
    assert "data" in names  # variable nodes tapped too
    assert any(n.endswith("_output") for n in names)
