"""Legacy mx.rnn symbolic API
(ref: tests/python/unittest/test_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import with_seed


def _bind_forward(outputs, shapes, seed=0):
    rng = np.random.RandomState(seed)
    args = {}
    sym = outputs if isinstance(outputs, mx.sym.Symbol) \
        else mx.sym.Group(outputs)
    for name in sym.list_arguments():
        if name in shapes:
            args[name] = mx.nd.array(
                rng.uniform(-0.5, 0.5, shapes[name]).astype(np.float32))
    missing = [n for n in sym.list_arguments() if n not in args]
    assert not missing, "unshaped args: %s" % missing
    exe = sym.bind(mx.cpu(), args)
    return exe, args


def _param_shapes(cell_prefix, in_dim, hidden, gates):
    g = gates
    return {
        "%si2h_weight" % cell_prefix: (g * hidden, in_dim),
        "%si2h_bias" % cell_prefix: (g * hidden,),
        "%sh2h_weight" % cell_prefix: (g * hidden, hidden),
        "%sh2h_bias" % cell_prefix: (g * hidden,),
    }


@with_seed()
@pytest.mark.parametrize("cls,gates", [(mx.rnn.RNNCell, 1),
                                       (mx.rnn.LSTMCell, 4),
                                       (mx.rnn.GRUCell, 3)])
def test_cell_unroll_shapes(cls, gates):
    cell = cls(8, prefix="c_")
    outputs, states = cell.unroll(3, mx.sym.var("data"),
                                  merge_outputs=True)
    shapes = {"data": (2, 3, 5)}
    shapes.update(_param_shapes("c_", 5, 8, gates))
    exe, _ = _bind_forward(outputs, shapes)
    out = exe.forward()[0]
    assert out.shape == (2, 3, 8)


@with_seed()
def test_lstm_matches_gluon_cell():
    """Same weights -> same outputs as the gluon LSTMCell."""
    cell = mx.rnn.LSTMCell(6, prefix="l_", forget_bias=0.0)
    outputs, _ = cell.unroll(4, mx.sym.var("data"), merge_outputs=True)
    shapes = {"data": (3, 4, 5)}
    shapes.update(_param_shapes("l_", 5, 6, 4))
    exe, args = _bind_forward(outputs, shapes, seed=3)
    sym_out = exe.forward()[0].asnumpy()

    gcell = mx.gluon.rnn.LSTMCell(6, input_size=5)
    gcell.initialize()
    gcell.i2h_weight.set_data(args["l_i2h_weight"])
    gcell.i2h_bias.set_data(args["l_i2h_bias"])
    gcell.h2h_weight.set_data(args["l_h2h_weight"])
    gcell.h2h_bias.set_data(args["l_h2h_bias"])
    gout, _ = gcell.unroll(4, mx.nd.array(args["data"].asnumpy()),
                           merge_outputs=True)
    np.testing.assert_allclose(sym_out, gout.asnumpy(), rtol=1e-5,
                               atol=1e-5)


@with_seed()
def test_fused_matches_unfused():
    T, B, I, H, L = 3, 2, 4, 5, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode="lstm",
                                prefix="f_", get_next_state=True)
    f_out, f_states = fused.unroll(T, mx.sym.var("data"),
                                   merge_outputs=True)
    from mxnet_tpu.ops.rnn import rnn_param_size

    psize = rnn_param_size("lstm", I, H, num_layers=L)
    exe, args = _bind_forward(f_out, {"data": (B, T, I),
                                      "f_parameters": (psize,)}, seed=5)
    fused_out = exe.forward()[0].asnumpy()

    # unfuse, load the unpacked weights, compare
    stack = fused.unfuse()
    u_out, _ = stack.unroll(T, mx.sym.var("data"), merge_outputs=True)
    unpacked = fused.unpack_weights({"f_parameters": args["f_parameters"],
                                     "data": args["data"]})
    u_args = {k: v for k, v in unpacked.items()}
    u_sym = u_out
    exe2 = u_sym.bind(mx.cpu(), {n: u_args[n]
                                 for n in u_sym.list_arguments()})
    unfused_out = exe2.forward()[0].asnumpy()
    np.testing.assert_allclose(fused_out, unfused_out, rtol=1e-5,
                               atol=1e-5)


@with_seed()
def test_fused_begin_state_batch_size():
    """begin_state(batch_size=...) must produce (L*D, B, H) states."""
    fused = mx.rnn.FusedRNNCell(5, num_layers=2, mode="lstm", prefix="f_")
    states = fused.begin_state(batch_size=3)
    assert len(states) == 2
    from mxnet_tpu.ops.rnn import rnn_param_size

    psize = rnn_param_size("lstm", 4, 5, num_layers=2)
    out, _ = fused.unroll(3, mx.sym.var("data"), begin_state=states,
                          merge_outputs=True)
    exe, _ = _bind_forward(out, {"data": (3, 3, 4),
                                 "f_parameters": (psize,)})
    assert exe.forward()[0].shape == (3, 3, 5)


@with_seed()
def test_fused_nested_in_sequential():
    """FusedRNNCell stacked under SequentialRNNCell with default states."""
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.FusedRNNCell(4, num_layers=1, mode="gru",
                                  prefix="fg_"))
    stack.add(mx.rnn.LSTMCell(4, prefix="top_"))
    out, states = stack.unroll(3, mx.sym.var("data"), merge_outputs=True)
    from mxnet_tpu.ops.rnn import rnn_param_size

    shapes = {"data": (2, 3, 6),
              "fg_parameters": (rnn_param_size("gru", 6, 4),)}
    shapes.update(_param_shapes("top_", 4, 4, 4))
    exe, _ = _bind_forward(out, shapes)
    assert exe.forward()[0].shape == (2, 3, 4)


@with_seed()
def test_pack_unpack_roundtrip():
    fused = mx.rnn.FusedRNNCell(5, num_layers=2, mode="gru", prefix="g_")
    from mxnet_tpu.ops.rnn import rnn_param_size

    psize = rnn_param_size("gru", 4, 5, num_layers=2)
    params = mx.nd.array(np.random.RandomState(0)
                         .uniform(-1, 1, (psize,)).astype(np.float32))
    unpacked = fused.unpack_weights({"g_parameters": params})
    assert "g_parameters" not in unpacked
    assert "g_l0_i2h_weight" in unpacked
    assert unpacked["g_l0_i2h_weight"].shape == (15, 4)
    repacked = fused.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["g_parameters"].asnumpy(),
                               params.asnumpy(), rtol=1e-6)


@with_seed()
def test_bidirectional_unroll():
    cell = mx.rnn.BidirectionalRNNCell(
        mx.rnn.LSTMCell(4, prefix="fw_"),
        mx.rnn.LSTMCell(4, prefix="bw_"))
    outputs, states = cell.unroll(3, mx.sym.var("data"),
                                  merge_outputs=True)
    shapes = {"data": (2, 3, 5)}
    shapes.update(_param_shapes("fw_", 5, 4, 4))
    shapes.update(_param_shapes("bw_", 5, 4, 4))
    exe, _ = _bind_forward(outputs, shapes)
    assert exe.forward()[0].shape == (2, 3, 8)


@with_seed()
def test_sequential_and_residual():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(4, prefix="s0_"))
    stack.add(mx.rnn.ResidualCell(mx.rnn.LSTMCell(4, prefix="s1_")))
    outputs, states = stack.unroll(3, mx.sym.var("data"),
                                   merge_outputs=True)
    shapes = {"data": (2, 3, 4)}
    shapes.update(_param_shapes("s0_", 4, 4, 4))
    shapes.update(_param_shapes("s1_", 4, 4, 4))
    exe, _ = _bind_forward(outputs, shapes)
    assert exe.forward()[0].shape == (2, 3, 4)
    assert len(states) == 4


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11, 12],
                 [1, 3, 5], [2, 4], [9, 9, 9]] * 4
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[3, 5], invalid_label=-1)
    assert it.default_bucket_key == 5
    seen = 0
    for batch in it:
        assert batch.bucket_key in (3, 5)
        assert batch.data[0].shape == (4, batch.bucket_key)
        d = batch.data[0].asnumpy()
        lbl = batch.label[0].asnumpy()
        # label is next-token shift of data
        np.testing.assert_array_equal(lbl[:, :-1], d[:, 1:])
        assert (lbl[:, -1] == -1).all()
        seen += 1
    assert seen >= 2
    it.reset()
    assert sum(1 for _ in it) == seen


@with_seed()
def test_bucketing_module_with_rnn_cells():
    """The canonical bucketing flow: variable-length first-token-recall
    task trained with BucketingModule over mx.rnn cells."""
    rng = np.random.RandomState(0)
    sentences = []
    for _ in range(160):
        ln = rng.choice([3, 5])
        sentences.append(rng.randint(0, 2, ln).tolist())

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=2, output_dim=8,
                                 name="embed")
        cell = mx.rnn.LSTMCell(16, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, embed, merge_outputs=False)
        pred = mx.sym.FullyConnected(outputs[-1], num_hidden=2, name="fc")
        return (mx.sym.SoftmaxOutput(pred, label, name="softmax"),
                ("data",), ("softmax_label",))

    buckets = [3, 5]
    data = [[] for _ in buckets]
    label = [[] for _ in buckets]
    for s in sentences:
        b = buckets.index(len(s))
        data[b].append(s)
        label[b].append([s[0]])  # recall the first token across time

    class _Iter(mx.io.DataIter):
        def __init__(self):
            super().__init__(batch_size=8)
            from mxnet_tpu.io.io import DataDesc

            self.provide_data = [DataDesc("data", (8, 5))]
            self.provide_label = [DataDesc("softmax_label", (8,))]
            self.default_bucket_key = 5
            self._order = []
            for bi, rows in enumerate(data):
                for start in range(0, len(rows) - 7, 8):
                    self._order.append((bi, start))
            self._i = 0

        def reset(self):
            self._i = 0

        def next(self):
            from mxnet_tpu.io.io import DataBatch, DataDesc

            if self._i >= len(self._order):
                raise StopIteration
            bi, start = self._order[self._i]
            self._i += 1
            d = mx.nd.array(np.asarray(data[bi][start:start + 8],
                                       dtype=np.float32))
            lbl = mx.nd.array(np.asarray(
                label[bi][start:start + 8], dtype=np.float32).ravel())
            return DataBatch(
                [d], [lbl], bucket_key=buckets[bi],
                provide_data=[DataDesc("data", d.shape)],
                provide_label=[DataDesc("softmax_label", lbl.shape)])

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=5)
    it = _Iter()
    mod.fit(it, num_epoch=15, optimizer="adam",
            optimizer_params=(("learning_rate", 0.01),))
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy()
        lbl = batch.label[0].asnumpy()
        correct += (pred.argmax(axis=1) == lbl).sum()
        total += len(lbl)
    assert correct / total > 0.9, (correct, total)
