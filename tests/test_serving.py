"""Inference serving stack (mxnet_tpu/serving/ + the ragged paged
attention kernel in ops/attention.py).

Covers the PR-7 acceptance surface on CPU: paged-attention parity
against the ragged dense reference (interpret mode, odd/mixed lengths
incl. 1 and 257 and the {1, 17, 257, 512} mixed batch), KV-page
alloc/free/reuse/defrag invariants, continuous-batching scheduler
join/retire/deadline-eviction, the zero-host-sync decode loop, AOT-warm
decode (zero cache-miss compiles in a warmed replica), and token-exact
end-to-end parity with the cache-free dense decode oracle.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import engine as eng_mod
from mxnet_tpu import nd, profiler, serving, tuning
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops import attention as A
from mxnet_tpu.serving import (ContinuousBatcher, DecodeEngine,
                               PagedKVCache, Request, StaticBatcher,
                               TinyDecoder)


@pytest.fixture(autouse=True)
def _fresh_table(monkeypatch, tmp_path):
    """Every test gets its own on-disk tune table (and a clean
    in-memory instance — table() swaps on path change)."""
    monkeypatch.setenv("MXT_TUNE_TABLE", str(tmp_path / "tune.json"))
    tuning.reset()
    yield
    tuning.reset()


def _pack_pages(k, v, page_size, rng, extra_pages=4):
    """Dense (B, H, T, D) K/V -> shuffled page pools + page table."""
    B, H, T, D = k.shape
    assert T % page_size == 0
    max_pages = T // page_size
    P = B * max_pages + extra_pages
    perm = rng.permutation(P)
    pt = perm[:B * max_pages].reshape(B, max_pages).astype(np.int32)
    k_pages = rng.normal(size=(P, page_size, H, D)).astype("f4")
    v_pages = rng.normal(size=(P, page_size, H, D)).astype("f4")
    for b in range(B):
        kt = k[b].transpose(1, 0, 2)  # (T, H, D)
        vt = v[b].transpose(1, 0, 2)
        for j in range(max_pages):
            k_pages[pt[b, j]] = kt[j * page_size:(j + 1) * page_size]
            v_pages[pt[b, j]] = vt[j * page_size:(j + 1) * page_size]
    return k_pages, v_pages, pt


# ---------------------------------------------------------------------------
# kernel parity: ragged paged attention vs the ragged dense reference
# ---------------------------------------------------------------------------
def test_ragged_reference_matches_manual_softmax():
    """The oracle itself, pinned against per-sequence numpy softmax."""
    rng = np.random.RandomState(0)
    lengths = [1, 5, 12]
    B, H, T, D = len(lengths), 2, 16, 8
    q = rng.normal(size=(B, H, D)).astype("f4")
    k = rng.normal(size=(B, H, T, D)).astype("f4")
    v = rng.normal(size=(B, H, T, D)).astype("f4")
    out = np.array(A.ragged_attention_reference(
        jnp.array(q), jnp.array(k), jnp.array(v),
        jnp.array(lengths, dtype=jnp.int32)))
    scale = 1.0 / np.sqrt(D)
    for b, L in enumerate(lengths):
        for h in range(H):
            s = (q[b, h] @ k[b, h, :L].T) * scale
            p = np.exp(s - s.max())
            p /= p.sum()
            want = p @ v[b, h, :L]
            np.testing.assert_allclose(out[b, h], want, atol=1e-5)


@pytest.mark.parametrize("page_size,lengths,blocks", [
    (16, (1, 17, 257, 512), (4,)),   # the acceptance mixed batch
    (8, (1, 7, 63, 64), (1, 2)),     # odd lengths on a small page
])
def test_paged_attention_parity_interpret(page_size, lengths, blocks):
    """Pallas kernel (interpret) and XLA gather path vs the ragged
    dense reference, <= 1e-5, ragged batch, shuffled page table."""
    rng = np.random.RandomState(1)
    B, H, D = len(lengths), 4, 32
    T = -(-max(lengths) // page_size) * page_size
    q = rng.normal(size=(B, H, D)).astype("f4")
    k = rng.normal(size=(B, H, T, D)).astype("f4")
    v = rng.normal(size=(B, H, T, D)).astype("f4")
    k_pages, v_pages, pt = _pack_pages(k, v, page_size, rng)
    cl = jnp.array(lengths, dtype=jnp.int32)
    ref = np.array(A.ragged_attention_reference(
        jnp.array(q), jnp.array(k), jnp.array(v), cl))

    got_xla = np.array(A._paged_gather_reference(
        jnp.array(q), jnp.array(k_pages), jnp.array(v_pages),
        jnp.array(pt), cl, 1.0 / np.sqrt(D)))
    np.testing.assert_allclose(got_xla, ref, atol=1e-5)

    for block_h in blocks:
        got = np.array(A._paged_decode_pallas(
            jnp.array(q), jnp.array(k_pages), jnp.array(v_pages),
            jnp.array(pt), cl, 1.0 / np.sqrt(D), block_h,
            interpret=True))
        np.testing.assert_allclose(got, ref, atol=1e-5,
                                   err_msg="block_h=%d" % block_h)


def test_paged_op_routes_and_records():
    """The public op: CPU routes to the gather reference, interpret=True
    forces the kernel, a signature lands for warmup replay, and the
    tuning table holds a decode-bucket entry."""
    rng = np.random.RandomState(2)
    B, H, D, S = 2, 2, 16, 8
    q = jnp.array(rng.normal(size=(B, H, D)).astype("f4"))
    kp = jnp.array(rng.normal(size=(10, S, H, D)).astype("f4"))
    vp = jnp.array(rng.normal(size=(10, S, H, D)).astype("f4"))
    pt = jnp.array([[0, 1, 2], [3, 4, 5]], dtype=jnp.int32)
    cl = jnp.array([5, 23], dtype=jnp.int32)
    out = nd.ragged_paged_attention(q, kp, vp, pt, cl)
    got_i = A.ragged_paged_attention(q, kp, vp, pt, cl, interpret=True)
    np.testing.assert_allclose(np.array(out.data), np.array(got_i),
                               atol=1e-5)
    sigs = tuning.signatures("paged_attention")
    assert any(s["q_shape"] == [B, H, D] for s in sigs)
    keys = [k for k in tuning.table().entries() if k.startswith("paged|")]
    assert keys, "resolve_paged recorded no decode-bucket entry"
    summary = tuning.warmup(include_live=False)
    assert "paged_attention" in summary["entries"]
    assert not summary["errors"]


def test_paged_candidates_and_bucketing():
    cands = tuning.paged_candidates(8, 64, 16, jnp.float32)
    assert cands and all(8 % bh == 0 or bh <= 8 for bh in cands)
    for bh in cands:
        assert 8 % bh == 0 and bh >= 1
    ent = tuning.heuristic_paged((4, 8, 64), 16, 32, "float32")
    assert ent["backend"] in ("pallas", "xla")
    assert ent["block_h"] in cands
    # page-table growth inside one pow2 bucket must not churn new keys
    k1 = tuning.paged_key((4, 8, 64), 16, 17, "float32")
    k2 = tuning.paged_key((4, 8, 64), 16, 31, "float32")
    assert k1 == k2


# ---------------------------------------------------------------------------
# paged KV cache invariants
# ---------------------------------------------------------------------------
def test_kv_cache_alloc_free_reuse():
    cache = PagedKVCache(1, 2, 8, num_pages=8, page_size=16)
    assert cache.available() == 8
    assert cache.reserve("a", 40)          # 3 pages promised
    assert cache.available() == 5
    assert cache.pages_of("a") == []
    p0 = cache.alloc_page("a")
    assert cache.pages_in_use() == 1 and cache.available() == 5
    cache.alloc_for("a", 40)
    assert len(cache.pages_of("a")) == 3
    with pytest.raises(MXNetError):
        cache.alloc_page("a")              # quota exhausted
    with pytest.raises(MXNetError):
        cache.reserve("a", 16)             # double reservation
    with pytest.raises(MXNetError):
        cache.alloc_page("ghost")          # no reservation
    assert not cache.reserve("b", 16 * 6)  # 6 > 5 available
    assert cache.reserve("b", 16 * 5)
    assert cache.available() == 0
    freed = cache.free("a")
    assert freed == 3 and cache.available() == 3
    # freed pages recycle (p0 comes back before untouched high ids)
    cache.reserve("c", 16)
    assert cache.alloc_page("c") == p0
    with pytest.raises(MXNetError):
        cache.reserve("huge", 16 * 9)      # can never fit: typed error


def test_kv_cache_defrag_preserves_content_and_compacts():
    cache = PagedKVCache(2, 2, 4, num_pages=8, page_size=8)
    rng = np.random.RandomState(3)
    for seq, ntok in (("a", 16), ("b", 24), ("c", 8)):
        cache.reserve(seq, ntok)
        cache.alloc_for(seq, ntok)
    # fill every allocated page with distinct values
    fill = {}
    for seq in ("a", "b", "c"):
        for p in cache.pages_of(seq):
            val = rng.normal(size=(2, 8, 2, 4)).astype("f4")
            fill[(seq, cache.pages_of(seq).index(p))] = val
            cache.k_pages = cache.k_pages.at[:, p].set(jnp.array(val))
    before = {seq: [np.array(cache.k_pages[:, p])
                    for p in cache.pages_of(seq)]
              for seq in ("a", "b", "c")}
    cache.free("b")                        # pages 2,3,4 fragment the pool
    moved = cache.defrag()
    assert moved > 0
    used = sorted(p for s in ("a", "c") for p in cache.pages_of(s))
    assert used == list(range(len(used))), "pool not compacted"
    for seq in ("a", "c"):
        for old, p in zip(before[seq], cache.pages_of(seq)):
            np.testing.assert_array_equal(old, np.array(
                cache.k_pages[:, p]))
    assert cache.defrag() == 0             # idempotent when compact


# ---------------------------------------------------------------------------
# end-to-end: engine + scheduler vs the dense cache-free oracle
# ---------------------------------------------------------------------------
_ENGINES = {}  # config -> (model, params, engine): reused when drained


def _tiny_engine(layers=2, heads=2, hdim=8, slots=4, pages=64,
                 page_size=8, max_context=128, seed=3, buckets=(16,),
                 fresh=False):
    """Build (or reuse) a tiny serving engine. Tests run serially and
    always drain their traffic, so an engine whose cache is empty and
    whose slots are all free is safe to hand to the next test — reuse
    skips re-tracing the decode/prefill programs (suite time matters:
    the tier-1 gate is dot count under a timeout)."""
    key = (layers, heads, hdim, slots, pages, page_size, max_context,
           seed, buckets)
    if not fresh and key in _ENGINES:
        model, params, eng = _ENGINES[key]
        if eng.cache.pages_in_use() == 0 and not eng._seq_of_slot:
            return model, params, eng
    model = TinyDecoder(vocab=64, num_layers=layers, num_heads=heads,
                        head_dim=hdim, max_len=256)
    params = model.init_params(seed)
    eng = DecodeEngine(
        model, params=params, slots=slots,
        cache=PagedKVCache(layers, heads, hdim, num_pages=pages,
                           page_size=page_size),
        prefill_buckets=buckets, max_context=max_context)
    if not fresh:
        _ENGINES[key] = (model, params, eng)
    return model, params, eng


def test_continuous_batching_matches_dense_oracle():
    """Join/retire through slot churn: 6 mixed-length requests through
    4 slots, every output token-for-token equal to the quadratic
    cache-free dense reference decode."""
    model, params, eng = _tiny_engine()
    sched = ContinuousBatcher(eng)
    rng = np.random.RandomState(0)
    reqs = []
    for plen, mnew in [(3, 6), (9, 4), (1, 8), (14, 3), (5, 5), (2, 7)]:
        r = Request(rng.randint(1, 64, plen).tolist(),
                    max_new_tokens=mnew)
        reqs.append(r)
        sched.submit(r)
    done = sched.run()
    assert len(done) == 6 and sched.steps < 50
    for r in reqs:
        assert r.state == "completed"
        ref = model.reference_decode(params, r.prompt, r.max_new_tokens)
        assert r.output_tokens == ref, r.id
        assert r.t_finish is not None and r.t_first is not None


def test_eos_stops_early():
    model, params, eng = _tiny_engine(layers=1)
    prompt = [5, 9, 2]
    ref = model.reference_decode(params, prompt, 10)
    eos = ref[2]  # an EOS the greedy stream will certainly emit
    stop = ref.index(eos) + 1  # ...at its FIRST occurrence
    sched = ContinuousBatcher(eng)
    r = sched.submit(Request(prompt, max_new_tokens=10, eos_id=eos))
    sched.run()
    assert r.state == "completed"
    assert r.output_tokens == ref[:stop]
    assert r.output_tokens[-1] == eos


def test_deadline_eviction_running_and_queued():
    clock = [0.0]
    model, params, eng = _tiny_engine(layers=1, slots=1)
    sched = ContinuousBatcher(eng, now_fn=lambda: clock[0])
    slow = sched.submit(Request([3, 4], max_new_tokens=50, deadline=5.0))
    queued = sched.submit(Request([7], max_new_tokens=4, deadline=1.0))
    ok = sched.submit(Request([9], max_new_tokens=2))
    sched.step()                     # admits `slow` into the only slot
    assert slow.state == "running"
    clock[0] = 2.0
    sched.step()                     # queued's 1s deadline blown
    assert queued.state == "evicted"
    clock[0] = 6.0
    sched.step()                     # slow's 5s deadline blown mid-decode
    assert slow.state == "evicted"
    assert eng.cache.pages_in_use() == 0 or ok.state == "running"
    sched.run()
    assert ok.state == "completed"
    assert ok.output_tokens == model.reference_decode(params, [9], 2)
    states = {r.state for r in (slow, queued)}
    assert states == {"evicted"}


def test_static_batcher_waits_for_batch():
    """Static admission only opens at batch boundaries — with 2 slots
    and 3 requests the third starts strictly after the first batch's
    longest member, and total steps exceed the continuous schedule."""
    model, params, e1 = _tiny_engine(layers=1, slots=2)
    reqs = [([3, 4], 8), ([5], 2), ([6, 1], 3)]

    def run(cls, eng):
        s = cls(eng)
        rs = [s.submit(Request(p, max_new_tokens=m)) for p, m in reqs]
        s.run()
        return rs, s.steps

    rs_s, steps_static = run(StaticBatcher, e1)
    _, _, e2 = _tiny_engine(layers=1, slots=2)
    rs_c, steps_cont = run(ContinuousBatcher, e2)
    for a, b in zip(rs_s, rs_c):
        assert a.state == b.state == "completed"
        assert a.output_tokens == b.output_tokens
    assert steps_static > steps_cont


def test_rejects_impossible_requests():
    model, params, eng = _tiny_engine(pages=4, page_size=8,
                                      max_context=32)
    sched = ContinuousBatcher(eng)
    r1 = sched.submit(Request([1] * 30, max_new_tokens=10))  # > context
    r2 = sched.submit(Request([1] * 20, max_new_tokens=20))  # > pool
    assert r1.state == "rejected" and r2.state == "rejected"
    assert not sched._queue


# ---------------------------------------------------------------------------
# the async contract: zero per-step host syncs, deferred token delivery
# ---------------------------------------------------------------------------
def test_zero_host_sync_decode_loop():
    """The acceptance bound: <= 1 host sync per K decode steps once the
    loop is steady (the window's stacked deferred read is the only
    device->host transfer)."""
    model, params, eng = _tiny_engine(layers=1, slots=2)
    sched = ContinuousBatcher(eng)
    sched.submit(Request([5, 9, 2], max_new_tokens=40))
    for _ in range(4):                    # admit + absorb prefill read
        sched.step()
    with eng_mod.bulk(4):
        h0 = profiler.host_sync_count()
        for _ in range(12):
            sched.step()
        syncs = profiler.host_sync_count() - h0
    assert syncs <= 12 // 4 + 1, \
        "decode loop performed %d host syncs over 12 steps at K=4" % syncs
    sched.run()


def test_window_values_protocol():
    got = []
    w = eng_mod.InflightWindow(
        name="vals", on_values=lambda n, row: got.append((n, int(row[0]))))
    with eng_mod.bulk(3):
        for i in range(7):
            t = jnp.array([i], jnp.int32)
            w.push(t, value=t)
        assert w.pending > 0
        w.flush()
    assert got == [(i + 1, i) for i in range(7)]
    assert w.pending == 0
    with pytest.raises(MXNetError):
        w.push(jnp.zeros((1,), jnp.uint32),
               flags=jnp.zeros((), jnp.uint32),
               value=jnp.zeros((1,), jnp.int32))


def test_waitall_drains_serving_window():
    model, params, eng = _tiny_engine(layers=1, slots=1)
    sched = ContinuousBatcher(eng)
    r = sched.submit(Request([5], max_new_tokens=6))
    with eng_mod.bulk(8):
        for _ in range(7):
            sched.step()
        nd.waitall()                      # the global barrier drains it
        assert eng.window.pending == 0
    sched.run()
    assert r.state == "completed"


def test_serving_modules_lint_enforced():
    """The decode hot path stays on the static host-sync scan list."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    for rel in ("mxnet_tpu/serving/engine.py",
                "mxnet_tpu/serving/scheduler.py",
                "mxnet_tpu/serving/kv_cache.py",
                "mxnet_tpu/serving/model.py"):
        assert rel in m.SCAN


# ---------------------------------------------------------------------------
# AOT warm decode: a warmed replica pays zero request-path JIT
# ---------------------------------------------------------------------------
def test_aot_warm_decode_zero_cache_misses(tmp_path, monkeypatch):
    """Replica A (cold) warms + serves, seeding the persistent compile
    cache; replica B (same shapes, fresh in-memory caches) warms and
    serves the same traffic with ZERO cache-miss compiles — every
    request-path program replays from disk."""
    from jax._src import compilation_cache as _cc

    monkeypatch.setenv("MXT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))

    def traffic(eng):
        sched = ContinuousBatcher(eng)
        rng = np.random.RandomState(0)
        for plen, mnew in [(3, 3), (9, 2), (1, 4)]:
            sched.submit(Request(rng.randint(1, 64, plen).tolist(),
                                 max_new_tokens=mnew))
        return sched.run()

    _cc.reset_cache()
    _, _, cold = _tiny_engine(layers=1, slots=2, fresh=True)
    # decode step + one fused admission program per prefill bucket
    assert cold.aot_warmup() >= 2
    traffic(cold)

    _cc.reset_cache()               # in-process stand-in for process B
    _, _, warm = _tiny_engine(layers=1, slots=2, fresh=True)
    warm.aot_warmup()
    c0 = tuning.compile_stats()
    out = traffic(warm)
    c1 = tuning.compile_stats()
    assert len(out) == 3
    assert c1["cache_misses"] - c0["cache_misses"] == 0, \
        "warm replica compiled on the request path"
    assert c1["cache_hits"] >= c0["cache_hits"]


def test_engine_defrag_keeps_serving():
    """Defrag mid-traffic: pages move, tables re-emit, decode output
    stays oracle-exact."""
    model, params, eng = _tiny_engine(layers=1, slots=2, pages=32)
    sched = ContinuousBatcher(eng)
    a = sched.submit(Request([3, 1, 4, 1, 5], max_new_tokens=8))
    b = sched.submit(Request([9, 2], max_new_tokens=8))
    for _ in range(3):
        sched.step()
    eng.flush()          # settle in-flight steps before moving pages
    eng.defrag()
    sched.run()
    for r in (a, b):
        assert r.state == "completed"
        assert r.output_tokens == model.reference_decode(
            params, r.prompt, r.max_new_tokens)
