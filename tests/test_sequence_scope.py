"""sequence_scope: every flash_attention dispatches to ring attention
with zero model changes (parallel/sequence.py + ops/attention.py)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, parallel


def _mesh(n):
    return parallel.make_mesh((n,), ("sp",),
                              devices=jax.devices("cpu")[:n])


def test_scope_dispatch_and_restore():
    q = mx.nd.random.uniform(shape=(2, 2, 16, 8))
    base = mx.nd.flash_attention(q, q, q, causal=True).asnumpy()
    with parallel.sequence_scope(_mesh(4), "sp"):
        assert parallel.current_sequence_scope() is not None
        ring = mx.nd.flash_attention(q, q, q, causal=True).asnumpy()
    assert parallel.current_sequence_scope() is None
    np.testing.assert_allclose(ring, base, rtol=2e-4, atol=2e-5)


def test_gpt_forward_and_grads_under_scope():
    """The model-zoo GPT runs sequence-parallel untouched; forward and
    grads match the unscoped run."""
    from mxnet_tpu.gluon.model_zoo.gpt import gpt_mini

    mx.random.seed(0)
    net = gpt_mini(dropout=0.0)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randint(0, 100, (2, 32)).astype(np.float32))
    ref = net(x).asnumpy()
    with parallel.sequence_scope(_mesh(4), "sp"):
        out = net(x).asnumpy()
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
    grads_sp = {k: p.grad().asnumpy().copy()
                for k, p in net.collect_params().items()}
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    for k, p in net.collect_params().items():
        np.testing.assert_allclose(grads_sp[k], p.grad().asnumpy(),
                                   rtol=5e-3, atol=1e-5)


def test_ulysses_schedule_dispatch():
    """schedule='ulysses' routes through the head all-to-all when heads
    divide; falls back to ring for per-head-indivisible or biased
    calls."""
    q = mx.nd.random.uniform(shape=(2, 4, 16, 8))  # H=4 divides 4
    base = mx.nd.flash_attention(q, q, q, causal=True).asnumpy()
    with parallel.sequence_scope(_mesh(4), "sp", schedule="ulysses"):
        out = mx.nd.flash_attention(q, q, q, causal=True).asnumpy()
    np.testing.assert_allclose(out, base, rtol=2e-4, atol=2e-5)
    # H=2 doesn't divide 4 shards -> ring fallback, still correct
    q2 = mx.nd.random.uniform(shape=(2, 2, 16, 8))
    base2 = mx.nd.flash_attention(q2, q2, q2).asnumpy()
    with parallel.sequence_scope(_mesh(4), "sp", schedule="ulysses"):
        out2 = mx.nd.flash_attention(q2, q2, q2).asnumpy()
    np.testing.assert_allclose(out2, base2, rtol=2e-4, atol=2e-5)
    with pytest.raises(ValueError, match="schedule"):
        with parallel.sequence_scope(_mesh(2), "sp", schedule="nope"):
            pass


def test_ulysses_grads_match_flash():
    """Gradients through the ulysses all-to-all path (plain autodiff,
    not ring's custom VJP) must match the flash kernel's."""
    B, H, T, D = 2, 4, 16, 8
    rng = np.random.RandomState(7)
    qn = rng.randn(B, H, T, D).astype(np.float32)

    def run(scoped):
        q = mx.nd.array(qn)
        q.attach_grad()
        with autograd.record():
            if scoped:
                with parallel.sequence_scope(_mesh(4), "sp",
                                             schedule="ulysses"):
                    out = mx.nd.flash_attention(q, q, q, causal=True)
            else:
                out = mx.nd.flash_attention(q, q, q, causal=True)
            (out * out).sum().backward()
        return q.grad.asnumpy()

    np.testing.assert_allclose(run(True), run(False), rtol=2e-3,
                               atol=2e-4)


def test_per_head_bias_grads_match_flash():
    """ALiBi-style (B, H, 1, Tk) bias: ring backward must keep per-head
    bias gradients, not sum heads."""
    B, H, T, D = 2, 3, 16, 8
    rng = np.random.RandomState(0)
    q = mx.nd.array(rng.randn(B, H, T, D).astype(np.float32))
    bias = mx.nd.array(0.1 * rng.randn(B, H, 1, T).astype(np.float32))

    def run(scoped):
        b = bias.copy()
        b.attach_grad()
        with autograd.record():
            if scoped:
                with parallel.sequence_scope(_mesh(4), "sp"):
                    out = mx.nd.flash_attention(q, q, q, b)
            else:
                out = mx.nd.flash_attention(q, q, q, b)
            (out * out).sum().backward()
        return b.grad.asnumpy()

    np.testing.assert_allclose(run(True), run(False), rtol=2e-3,
                               atol=2e-4)


def test_hybridized_net_under_scope():
    """A graph traced outside the scope must not be reused inside it:
    hybridized blocks run eager under the scope (a 1-device whole-block
    jit cannot host the multi-device ring), matching the unscoped
    output."""
    from mxnet_tpu.gluon.model_zoo.bert import BERTSelfAttention

    blk = BERTSelfAttention(16, 2)
    blk.initialize()
    blk.hybridize()
    x = mx.nd.random.uniform(shape=(2, 16, 16))
    base = blk(x).asnumpy()  # traced WITHOUT the scope
    with parallel.sequence_scope(_mesh(4), "sp"):
        scoped = blk(x).asnumpy()  # eager + ring dispatch, not the trace
    np.testing.assert_allclose(scoped, base, rtol=2e-4, atol=2e-5)
    after = blk(x).asnumpy()  # back on the cached fast path
    np.testing.assert_allclose(after, base, rtol=1e-6)


def test_rectangular_attention_falls_back():
    """Cross-attention / decode (Tq != Tk) inside the scope uses the
    flash kernel (the ring schedule is self-attention only)."""
    q = mx.nd.random.uniform(shape=(1, 2, 1, 8))    # Tq=1 decode step
    k = mx.nd.random.uniform(shape=(1, 2, 16, 8))
    base = mx.nd.flash_attention(q, k, k).asnumpy()
    with parallel.sequence_scope(_mesh(4), "sp"):
        out = mx.nd.flash_attention(q, k, k).asnumpy()
    np.testing.assert_allclose(out, base, rtol=1e-5, atol=1e-6)


def test_scope_nested_and_exception_safe():
    m = _mesh(2)
    try:
        with parallel.sequence_scope(m, "sp"):
            with parallel.sequence_scope(m, "sp"):
                raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert parallel.current_sequence_scope() is None


def test_scope_indivisible_seq_raises():
    q = mx.nd.random.uniform(shape=(1, 2, 10, 8))  # T=10, 4 shards
    with parallel.sequence_scope(_mesh(4), "sp"):
        with pytest.raises(Exception, match="not divisible"):
            mx.nd.flash_attention(q, q, q).wait_to_read()
