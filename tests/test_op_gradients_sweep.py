"""Registry-wide numeric-gradient sweep (SURVEY §4 op-unit tier: the
reference's test mass is per-op backward-vs-central-difference checks in
tests/python/unittest/test_operator.py, ~9k lines).

Every differentiable op in the registry must either appear in SPEC below
(and pass check_numeric_gradient at float64) or be listed in EXEMPT with a
reason — test_sweep_is_complete enforces this, so newly registered ops
cannot silently skip gradient coverage. A bf16 pass checks the hot ops'
gradients stay finite and near their f32 values (round 2 shipped a bf16
conv/dot backward bug exactly this would have caught).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.registry import _OPS
from mxnet_tpu.test_utils import check_numeric_gradient

R = np.random.RandomState(7)


def _pos(*s):
    return R.uniform(0.5, 1.5, s)


def _unit(*s):
    return R.uniform(-0.8, 0.8, s)


def _any(*s):
    return R.uniform(-2.0, 2.0, s)


def _distinct(*s):
    """Values with well-separated magnitudes (kink-free for max/sort)."""
    n = int(np.prod(s))
    vals = np.linspace(0.1, 3.0, n)
    R.shuffle(vals)
    return vals.reshape(s)


def _sum_outputs(op, **kw):
    """Wrap a (possibly multi-output) op into a scalar-friendly fn."""
    def fn(*xs):
        out = op(*xs, **kw)
        if isinstance(out, (list, tuple)):
            total = out[0].sum()
            for o in out[1:]:
                total = total + o.sum()
            return total
        return out
    return fn


# op -> (input arrays, kwargs, grad_nodes or None)
SPEC = {
    # unary, full-real domain (kink-free regions where needed)
    "sin": ([_any(3, 4)], {}, None),
    "cos": ([_any(3, 4)], {}, None),
    "tan": ([_unit(3, 4)], {}, None),
    "sinh": ([_unit(3, 4)], {}, None),
    "cosh": ([_unit(3, 4)], {}, None),
    "tanh": ([_unit(3, 4)], {}, None),
    "arcsin": ([_unit(3, 4)], {}, None),
    "arccos": ([_unit(3, 4)], {}, None),
    "arctan": ([_any(3, 4)], {}, None),
    "arcsinh": ([_any(3, 4)], {}, None),
    "arccosh": ([_pos(3, 4) + 1.0], {}, None),
    "arctanh": ([_unit(3, 4) * 0.9], {}, None),
    "exp": ([_unit(3, 4)], {}, None),
    "expm1": ([_unit(3, 4)], {}, None),
    "log": ([_pos(3, 4)], {}, None),
    "log10": ([_pos(3, 4)], {}, None),
    "log2": ([_pos(3, 4)], {}, None),
    "log1p": ([_pos(3, 4)], {}, None),
    "sqrt": ([_pos(3, 4)], {}, None),
    "rsqrt": ([_pos(3, 4)], {}, None),
    "cbrt": ([_pos(3, 4)], {}, None),
    "rcbrt": ([_pos(3, 4)], {}, None),
    "reciprocal": ([_pos(3, 4)], {}, None),
    "square": ([_any(3, 4)], {}, None),
    "abs": ([_pos(3, 4)], {}, None),              # away from the kink
    "negative": ([_any(3, 4)], {}, None),
    "identity": ([_any(3, 4)], {}, None),
    "sigmoid": ([_any(3, 4)], {}, None),
    "softsign": ([_any(3, 4)], {}, None),
    "relu": ([_pos(3, 4)], {}, None),             # positive side
    "gelu": ([_any(3, 4)], {}, None),
    "hard_sigmoid": ([_unit(3, 4) * 0.4], {}, None),  # linear region
    "erf": ([_unit(3, 4)], {}, None),
    "erfinv": ([_unit(3, 4) * 0.7], {}, None),
    "gamma": ([_pos(3, 4) + 1.0], {}, None),
    "gammaln": ([_pos(3, 4) + 1.0], {}, None),
    "degrees": ([_any(3, 4)], {}, None),
    "radians": ([_any(3, 4)], {}, None),
    "smooth_l1": ([_any(3, 4)], {"scalar": 1.0}, None),
    "clip": ([_unit(3, 4) * 0.4], {"a_min": -0.9, "a_max": 0.9}, None),

    # scalar-arg binary
    "_plus_scalar": ([_any(3, 4)], {"scalar": 1.7}, None),
    "_minus_scalar": ([_any(3, 4)], {"scalar": 1.7}, None),
    "_rminus_scalar": ([_any(3, 4)], {"scalar": 1.7}, None),
    "_mul_scalar": ([_any(3, 4)], {"scalar": -2.1}, None),
    "_div_scalar": ([_any(3, 4)], {"scalar": 2.1}, None),
    "_rdiv_scalar": ([_pos(3, 4)], {"scalar": 2.1}, None),
    "_power_scalar": ([_pos(3, 4)], {"scalar": 2.5}, None),
    "_rpower_scalar": ([_unit(3, 4)], {"scalar": 2.0}, None),
    "_mod_scalar": ([_pos(3, 4) * 0.3], {"scalar": 1.0}, None),
    "_rmod_scalar": ([_pos(3, 4) + 2.0], {"scalar": 1.0}, None),
    "_hypot_scalar": ([_pos(3, 4)], {"scalar": 1.0}, None),
    "_maximum_scalar": ([_pos(3, 4) + 1.0], {"scalar": 0.5}, None),
    "_minimum_scalar": ([_pos(3, 4) + 1.0], {"scalar": 9.0}, None),

    # elemwise / broadcast binary
    "elemwise_add": ([_any(3, 4), _any(3, 4)], {}, None),
    "elemwise_sub": ([_any(3, 4), _any(3, 4)], {}, None),
    "elemwise_mul": ([_any(3, 4), _any(3, 4)], {}, None),
    "elemwise_div": ([_any(3, 4), _pos(3, 4)], {}, None),
    "_maximum": ([_pos(3, 4) + 1.0, _pos(3, 4) * 0.3], {}, None),
    "_minimum": ([_pos(3, 4) + 1.0, _pos(3, 4) * 0.3], {}, None),
    "_power": ([_pos(3, 4), _pos(3, 4)], {}, None),
    "_mod": ([_pos(3, 4) * 0.3, _pos(3, 4) + 1.0], {}, None),
    "arctan2": ([_pos(3, 4), _pos(3, 4)], {}, None),
    "broadcast_add": ([_any(3, 4), _any(1, 4)], {}, None),
    "broadcast_sub": ([_any(3, 4), _any(1, 4)], {}, None),
    "broadcast_mul": ([_any(3, 4), _any(1, 4)], {}, None),
    "broadcast_div": ([_any(3, 4), _pos(1, 4)], {}, None),
    "broadcast_power": ([_pos(3, 4), _pos(1, 4)], {}, None),
    "broadcast_maximum": ([_pos(3, 4) + 1.0, _pos(1, 4) * 0.3], {}, None),
    "broadcast_minimum": ([_pos(3, 4) + 1.0, _pos(1, 4) * 0.3], {}, None),
    "broadcast_mod": ([_pos(3, 4) * 0.3, _pos(1, 4) + 1.0], {}, None),
    "broadcast_hypot": ([_pos(3, 4), _pos(1, 4)], {}, None),

    # reductions
    "sum": ([_any(3, 4)], {"axis": 1}, None),
    "mean": ([_any(3, 4)], {"axis": 0}, None),
    "prod": ([_pos(3, 4)], {"axis": 1}, None),
    "nansum": ([_any(3, 4)], {}, None),
    "nanprod": ([_pos(3, 4)], {}, None),
    "max": ([_distinct(3, 4)], {"axis": 1}, None),
    "min": ([_distinct(3, 4)], {"axis": 1}, None),
    "logsumexp": ([_any(3, 4)], {"axis": 1}, None),
    "norm": ([_pos(3, 4)], {"ord": 2, "axis": 1}, None),
    "softmax": ([_any(3, 4)], {"axis": -1}, None),
    "softmin": ([_any(3, 4)], {"axis": -1}, None),
    "log_softmax": ([_any(3, 4)], {"axis": -1}, None),

    # shape / movement
    "reshape": ([_any(3, 4)], {"shape": (4, 3)}, None),
    "transpose": ([_any(3, 4)], {"axes": (1, 0)}, None),
    "flatten": ([_any(2, 3, 2)], {}, None),
    "expand_dims": ([_any(3, 4)], {"axis": 1}, None),
    "squeeze": ([_any(3, 1, 4)], {"axis": 1}, None),
    "flip": ([_any(3, 4)], {"axis": 1}, None),
    "tile": ([_any(2, 3)], {"reps": (2, 2)}, None),
    "repeat": ([_any(2, 3)], {"repeats": 2, "axis": 1}, None),
    "pad": ([_any(1, 1, 3, 3)],
            {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
            None),
    "slice": ([_any(4, 5)], {"begin": (1, 0), "end": (3, 4)}, None),
    "slice_axis": ([_any(4, 5)], {"axis": 1, "begin": 1, "end": 4}, None),
    "slice_like": ([_any(4, 5), np.zeros((2, 3))], {}, [0]),
    "broadcast_to": ([_any(1, 4)], {"shape": (3, 4)}, None),
    "broadcast_axis": ([_any(1, 4)], {"axis": 0, "size": 3}, None),
    "broadcast_like": ([_any(1, 4), np.zeros((3, 4))], {}, [0]),
    "swapaxes": ([_any(2, 3, 4)], {"dim1": 0, "dim2": 2}, None),
    "stack": ([_any(3, 4), _any(3, 4)], {"axis": 1}, None),
    "concat": ([_any(3, 2), _any(3, 3)], {"dim": 1}, None),
    "split": ([_any(3, 4)], {"num_outputs": 2, "axis": 1}, None),
    "split_v2": ([_any(3, 4)], {"indices_or_sections": 2, "axis": 1},
                 None),
    "diag": ([_any(4, 4)], {}, None),
    "where": ([np.array([[1.0, 0.0, 1.0]] * 2), _any(2, 3), _any(2, 3)],
              {}, [1, 2]),
    "sort": ([_distinct(3, 4)], {"axis": 1}, None),

    # indexing
    "take": ([_any(5, 3), np.array([0.0, 2.0, 4.0])], {"axis": 0}, [0]),
    "Embedding": ([np.array([[0.0, 2.0], [3.0, 1.0]]), _any(5, 3)],
                  {"input_dim": 5, "output_dim": 3}, [1]),
    "gather_nd": ([_any(4, 3), np.array([[0.0, 2.0], [1.0, 0.0]])],
                  {}, [0]),
    "scatter_nd": ([_any(2, 3), np.array([[0.0, 3.0]])],
                   {"shape": (5, 3)}, [0]),
    "pick": ([_any(3, 4), np.array([0.0, 2.0, 1.0])], {"axis": 1}, [0]),
    "index_add": ([_any(5, 3), np.array([1.0, 3.0]), _any(2, 3)],
                  {}, [0, 2]),
    "index_copy": ([_any(5, 3), np.array([1.0, 3.0]), _any(2, 3)],
                   {}, [0, 2]),
    "one_hot_like_ops": None,  # placeholder removed below

    # linear algebra
    "dot": ([_any(3, 4), _any(4, 2)], {}, None),
    "batch_dot": ([_any(2, 3, 4), _any(2, 4, 2)], {}, None),
    "khatri_rao": ([_any(2, 3), _any(4, 3)], {}, None),

    # NN ops
    "FullyConnected": ([_any(2, 5), _any(3, 5), _any(3)],
                       {"num_hidden": 3}, None),
    "Convolution": ([_any(1, 2, 5, 5), _any(3, 2, 3, 3), _any(3)],
                    {"kernel": (3, 3), "num_filter": 3}, None),
    "Deconvolution": ([_any(1, 3, 4, 4), _any(3, 2, 3, 3), _any(2)],
                      {"kernel": (3, 3), "num_filter": 2}, None),
    "Pooling": ([_any(1, 2, 4, 4)],
                {"kernel": (2, 2), "pool_type": "avg", "stride": (2, 2)},
                None),
    "Activation": ([_any(3, 4)], {"act_type": "softrelu"}, None),
    "LeakyReLU": ([_pos(3, 4)], {"act_type": "leaky", "slope": 0.3},
                  None),
    "LayerNorm": ([_any(3, 6), _pos(6), _any(6)], {}, None),
    "GroupNorm": ([_any(2, 4, 3), _pos(4), _any(4)],
                  {"num_groups": 2}, None),
    "InstanceNorm": ([_any(2, 3, 4), _pos(3), _any(3)], {}, None),
    "L2Normalization": ([_pos(3, 4)], {}, None),
    "LRN": ([_pos(1, 4, 3, 3)], {"nsize": 3}, None),
    "BatchNorm": ([_any(2, 3, 4), _pos(3), _any(3), np.zeros(3),
                   np.ones(3)],
                  {"fix_gamma": False, "use_global_stats": True},
                  [0, 1, 2]),
    "SequenceMask": ([_any(4, 2, 3), np.array([2.0, 4.0])],
                     {"use_sequence_length": True}, [0]),
    "SequenceLast": ([_any(4, 2, 3), np.array([2.0, 4.0])],
                     {"use_sequence_length": True}, [0]),
    "SequenceReverse": ([_any(4, 2, 3)], {}, None),
    "UpSampling": ([_any(1, 2, 3, 3)], {"scale": 2}, None),

    # plain fused loss (differentiable forward, label non-diff)
    "softmax_cross_entropy": ([_any(4, 5),
                               np.array([0.0, 2.0, 1.0, 4.0])], {}, [0]),
    "MakeLoss": ([_any(3, 4)], {}, None),

    # attention (the north-star hot kernel, CPU/interpret path here)
    "flash_attention": ([_unit(1, 2, 4, 8), _unit(1, 2, 4, 8),
                         _unit(1, 2, 4, 8)], {}, None),
}


def _spd(n, seed=3):
    m = np.random.RandomState(seed).rand(n, n)
    return m @ m.T + n * np.eye(n)


def _chol(n, seed=3):
    return np.linalg.cholesky(_spd(n, seed))


SPEC.update({
    # linalg family (ref: la_op) — SPD/triangular inputs where required
    "linalg_gemm": ([_any(3, 4), _any(4, 2), _any(3, 2)],
                    {"alpha": 1.3, "beta": 0.7}, None),
    "linalg_gemm2": ([_any(3, 4), _any(4, 2)], {"alpha": 1.3}, None),
    "linalg_potrf": ([_spd(3)], {}, None),
    "linalg_potri": ([_chol(3)], {}, None),
    "linalg_trsm": ([_chol(3) + np.eye(3), _any(3, 2)], {}, None),
    "linalg_trmm": ([_any(3, 3), _any(3, 2)], {}, None),
    "linalg_syrk": ([_any(3, 4)], {}, None),
    "linalg_makediag": ([_any(4)], {}, None),
    "linalg_extractdiag": ([_any(4, 4)], {}, None),
    "linalg_maketrian": ([_any(6)], {}, None),
    "linalg_extracttrian": ([_any(3, 3)], {}, None),
    "linalg_sumlogdiag": ([_chol(3) + np.eye(3)], {}, None),
    "linalg_det": ([_spd(3)], {}, None),
    "linalg_slogdet": ([_spd(3)], {}, [0]),
    "linalg_inverse": ([_spd(3)], {}, None),
    # round-3 extended families (matrix_op.cc block ops, ravel.cc,
    # im2col.h, moments.cc, amp_cast.cc, shrinks, vision transforms)
    "tril": ([_any(4, 4)], {}, None),
    "triu": ([_any(4, 4)], dict(k=1), None),
    "depth_to_space": ([_any(1, 8, 2, 3)], dict(block_size=2), None),
    "space_to_depth": ([_any(1, 2, 4, 6)], dict(block_size=2), None),
    "reshape_like": ([_any(2, 6), _any(3, 4)], {}, [0]),
    "batch_take": ([_distinct(3, 4),
                    np.array([1.0, 0.0, 3.0])], {}, [0]),
    "choose_element_0index": ([_distinct(3, 4),
                               np.array([1.0, 0.0, 3.0])], {}, [0]),
    "fill_element_0index": ([_any(3, 4), _any(3),
                             np.array([1.0, 0.0, 3.0])], {}, [0, 1]),
    "im2col": ([_any(1, 2, 5, 5)],
               dict(kernel=(3, 3), stride=(1, 1), pad=(1, 1)), None),
    "col2im": ([_any(1, 18, 25)],
               dict(output_size=(5, 5), kernel=(3, 3), stride=(1, 1),
                    pad=(1, 1)), None),
    "cumsum": ([_any(3, 4)], dict(axis=1), None),
    "cumprod": ([_pos(3, 4)], dict(axis=1), None),
    "moments": ([_any(3, 4)], dict(axes=(0,)), None),
    # shrinks: inputs kept away from the |x| = lambd kink
    "hardshrink": ([_pos(3, 4) + 1.0], dict(lambd=0.5), None),
    "softshrink": ([_pos(3, 4) + 1.0], dict(lambd=0.5), None),
    "digamma": ([_pos(3, 4) + 0.5], {}, None),
    "amp_cast": ([_any(3, 4)], dict(dtype="float64"), None),
    "amp_multicast": ([_any(3, 4), _any(3, 4)], {}, None),
    "GridGenerator": ([_unit(2, 6)],
                      dict(transform_type="affine",
                           target_shape=(4, 5)), None),
    # data grad through bilinear sampling is smooth away from integer
    # grid lines; theta grad flows through the affine grid
    "SpatialTransformer": ([_pos(1, 2, 6, 6), _unit(1, 6) * 0.3],
                           dict(target_shape=(5, 5)), None),
    "ROIPooling": ([_distinct(1, 2, 6, 6),
                    np.array([[0.0, 0.0, 0.0, 5.0, 5.0],
                              [0.0, 1.0, 1.0, 4.0, 4.0]])],
                   dict(pooled_size=(2, 2), spatial_scale=1.0), [0]),
    "Correlation": ([_any(1, 3, 5, 5), _any(1, 3, 5, 5)],
                    dict(kernel_size=1, max_displacement=1), None),
    # bilinear sampling is smooth away from integer grid lines; the
    # fractional roi keeps samples off them
    "ROIAlign": ([_any(1, 2, 6, 6),
                  np.array([[0.0, 0.3, 0.4, 4.6, 4.3]])],
                 dict(pooled_size=(2, 2), spatial_scale=1.0), [0]),
    # offsets bounded to [0.17, 0.33]: every bilinear sample stays well
    # clear of the integer-grid kinks, so the numeric grad is defined
    "DeformableConvolution": (
        [_any(1, 2, 5, 5), _unit(1, 18, 3, 3) * 0.1 + 0.25,
         _any(2, 2, 3, 3), _any(2)],
        dict(kernel=(3, 3)), None),
    # grid stays in [-0.12, 0.12] -> gx,gy in [2.2, 2.8]: strictly inside
    # the 6x6 map AND between integer grid lines (bilinear kink-free)
    "BilinearSampler": ([_pos(1, 2, 6, 6), _unit(1, 2, 3, 3) * 0.15],
                        {}, None),
    # spatial crop is a strided slice — gradient is a zero-padded scatter
    "Crop": ([_any(1, 2, 5, 5)], dict(h_w=(3, 3), offset=(1, 1)), None),
    # contrib family
    "fft": ([_any(3, 8)], {}, None),
    "ifft": ([_any(3, 16)], {}, None),
    "index_copy": ([_any(5, 4), np.array([0.0, 2.0]), _any(2, 4)],
                   {}, [0, 2]),
    "index_add": ([_any(5, 4), np.array([1.0, 3.0]), _any(2, 4)],
                  {}, [0, 2]),
    "count_sketch": ([_any(3, 6), np.array([0.0, 2, 1, 3, 0, 2]),
                      np.array([1.0, -1, 1, -1, 1, 1])],
                     dict(out_dim=4), [0]),
})
del SPEC["one_hot_like_ops"]

# ops whose internals compute in float32 regardless of input dtype (BN/LN
# cast for stability; flash accumulates at f32) — f32-ladder tolerances,
# like the reference's per-dtype tolerance ladder in check_consistency
F32_INTERNAL_TOL = {
    "BatchNorm": dict(eps=1e-2, rtol=2e-2, atol=1e-3),
    "LayerNorm": dict(eps=1e-2, rtol=2e-2, atol=1e-3),
    "flash_attention": dict(eps=1e-2, rtol=2e-2, atol=1e-3),
}

# differentiable in the registry but excluded from the numeric sweep,
# each with a reason
EXEMPT = {
    "Custom": "escape hatch; needs a user-registered python op "
              "(tests/test_custom_compression.py covers fwd+bwd)",
    "RNN": "fused multi-layer recurrence; numeric grad is O(T*P^2) — "
           "covered by tests/test_gluon_rnn.py analytic checks",
    "Dropout": "stochastic in train mode, identity in test mode",
    "norm_like_cast": "dtype cast; gradient is the identity cast",
    "ones_like": "constant output, zero gradient by definition",
    "zeros_like": "constant output, zero gradient by definition",
    "CTCLoss": "integer labels break the sweep's perturb-everything "
               "harness; values AND input grads are pinned against "
               "torch.nn.functional.ctc_loss in "
               "tests/test_ctc_and_contrib_data.py",
}


def test_sweep_is_complete():
    """Every differentiable registry op is swept or explicitly exempted."""
    diff_ops = {n for n, op in _OPS.items() if op.differentiable}
    covered = set(SPEC) | set(EXEMPT) | set(LOSS_HEADS)
    missing = diff_ops - covered
    stale = covered - diff_ops
    assert not missing, "ops missing numeric-grad coverage: %s" % sorted(
        missing)
    assert not stale, "sweep entries for unregistered ops: %s" % sorted(
        stale)


def _op_fn(name):
    """Resolve through the registry — getattr(nd, name) can collide with
    module-internal names (e.g. '_mod' is nd's module alias)."""
    from mxnet_tpu.ops.registry import apply_op, get_op

    op = get_op(name)
    return lambda *xs, **kw: apply_op(op, *xs, **kw)


@pytest.mark.parametrize("name", sorted(SPEC))
def test_numeric_gradient(name):
    inputs, kwargs, grad_nodes = SPEC[name]
    fn = _sum_outputs(_op_fn(name), **kwargs)
    tol = F32_INTERNAL_TOL.get(name,
                               dict(eps=1e-4, rtol=1e-4, atol=1e-5))
    check_numeric_gradient(
        fn, [nd.array(x.astype(np.float64)) for x in inputs],
        grad_nodes=grad_nodes, **tol)


# loss-head ops: backward IGNORES the cotangent and emits the fused loss
# gradient (reference "loss layer" semantics) — so they are checked
# against the numeric gradient of the loss they imply, not the forward's
# jacobian. num_output = size/batch mirrors regression_output-inl.h.
def _implied_linear(d, lbl):
    return 0.5 * np.sum((d - lbl) ** 2) / (d.size // d.shape[0])


def _implied_mae(d, lbl):
    return np.sum(np.abs(d - lbl)) / (d.size // d.shape[0])


def _implied_logistic(d, lbl):
    p = 1.0 / (1.0 + np.exp(-d))
    return np.sum(-lbl * np.log(p) - (1 - lbl) * np.log1p(-p)) / (
        d.size // d.shape[0])


def _implied_softmax(d, lbl):
    e = np.exp(d - d.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return -np.sum(np.log(p[np.arange(d.shape[0]), lbl.astype(int)]))


def _implied_svm(d, lbl):
    # L2-SVM (squared hinge), margin=1, C=1 — the SVMOutput defaults
    y = lbl.astype(int)
    total = 0.0
    for i in range(d.shape[0]):
        xy = d[i, y[i]]
        for j in range(d.shape[1]):
            if j != y[i]:
                v = max(0.0, 1.0 - (xy - d[i, j]))
                total += v * v
    return total


LOSS_HEADS = {
    "LinearRegressionOutput": (
        _any(3, 4), _any(3, 4), _implied_linear),
    "MAERegressionOutput": (
        _pos(3, 4) + 1.0, _pos(3, 4) * 0.3, _implied_mae),
    "LogisticRegressionOutput": (
        _any(3, 4), _pos(3, 4) * 0.4, _implied_logistic),
    "SoftmaxOutput": (
        _any(4, 5), np.array([0.0, 2.0, 1.0, 4.0]), _implied_softmax),
    "SVMOutput": (
        _any(4, 5), np.array([0.0, 2.0, 1.0, 4.0]), _implied_svm),
}


@pytest.mark.parametrize("name", sorted(LOSS_HEADS))
def test_loss_head_gradient(name):
    from mxnet_tpu import autograd as ag

    d_np, l_np, implied = LOSS_HEADS[name]
    d = nd.array(d_np.astype(np.float64))
    lbl = nd.array(l_np.astype(np.float64))
    d.attach_grad()
    with ag.record():
        out = _op_fn(name)(d, lbl)
        out.backward(nd.ones(out.shape, dtype="float64"))
    analytic = d.grad.asnumpy()
    eps = 1e-5
    numeric = np.zeros_like(d_np, dtype=np.float64)
    base = d_np.astype(np.float64).copy()
    for j in range(base.size):
        orig = base.flat[j]
        base.flat[j] = orig + eps
        fp = implied(base, l_np)
        base.flat[j] = orig - eps
        fm = implied(base, l_np)
        base.flat[j] = orig
        numeric.flat[j] = (fp - fm) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=1e-4, atol=1e-6)


BF16_OPS = ["dot", "batch_dot", "Convolution", "FullyConnected",
            "softmax", "LayerNorm", "flash_attention", "BatchNorm"]


@pytest.mark.parametrize("name", BF16_OPS)
def test_bf16_gradients_match_f32(name):
    """Hot ops: bf16 grads must be finite and near the f32 gradient
    (round 2's bf16 conv/dot backward bug would have failed here)."""
    from mxnet_tpu import autograd as ag

    inputs, kwargs, grad_nodes = SPEC[name]
    op = getattr(nd, name)
    grads = {}
    for dt in ("float32", "bfloat16"):
        arrs = [nd.array(x.astype(np.float32)).astype(dt) for x in inputs]
        for a in arrs:
            a.attach_grad()
        with ag.record():
            out = _sum_outputs(op, **kwargs)(*arrs)
            loss = (out * out).sum() if out.size > 1 else out
        loss.backward()
        gn = grad_nodes if grad_nodes is not None else range(len(arrs))
        grads[dt] = [arrs[i].grad.asnumpy().astype(np.float32)
                     for i in gn]
    for g32, g16 in zip(grads["float32"], grads["bfloat16"]):
        assert np.all(np.isfinite(g16))
        scale = np.abs(g32).max() + 1e-6
        assert np.abs(g32 - g16).max() / scale < 0.1, name
