"""Smoke-run every example with tiny settings — the examples are part of
the user-facing surface (README/examples table) and must keep working.
Each runs in-process via runpy with the CPU backend already forced by
conftest."""
import os
import runpy
import sys

import pytest

EXAMPLES = {
    "examples/train_mnist_gluon.py": ["--epochs", "1", "--batch-size",
                                      "128"],
    "examples/train_mnist_module.py": ["--epochs", "1"],
    # ShardedTrainStep shards the batch over conftest's 8-device mesh, so
    # sharded-step examples need batch sizes divisible by 8
    "examples/train_imagenet_resnet.py": [
        "--synthetic", "--iters", "2", "--batch-size", "8",
        "--image-shape", "3,32,32", "--dtype", "float32"],
    "examples/lstm_ptb_bucketing.py": [
        "--epochs", "1", "--sentences", "32", "--batch-size", "4",
        "--hidden", "16", "--vocab", "50", "--layers", "1"],
    "examples/bert_mlm_pretrain.py": [
        "--iters", "2", "--batch-size", "8", "--seq-len", "16"],
    "examples/wide_deep_ctr.py": [
        "--iters", "4", "--batch-size", "32", "--wide-vocab", "500",
        "--deep-vocab", "200"],
    "examples/train_wide_deep.py": [
        "--iters", "2", "--batch-size", "16", "--wide-vocab", "300",
        "--deep-vocab", "100", "--embedding-servers", "2",
        "--cache-rows", "32"],
    "examples/gpt_lm_pretrain.py": [
        "--iters", "2", "--batch-size", "8", "--seq-len", "16",
        "--tp", "2"],
    "examples/train_ssd_toy.py": ["--iters", "4", "--batch-size", "8"],
    "examples/quantize_lenet.py": ["--epochs", "1", "--train-size",
                                   "192", "--calib-mode", "naive"],
    "examples/long_context_gpt.py": [
        "--devices", "4", "--seq-len", "64", "--steps", "1",
        "--batch-size", "1"],
    "examples/serve_bert.py": [
        "--requests", "3", "--slots", "2", "--pages", "128",
        "--layers", "1", "--head-dim", "16", "--max-new", "12"],
    # the unified 4D (dp×tp×pp×ep) pipeline+MoE step — batch must split
    # into 4 microbatches whose slices divide dp=2
    "examples/train_moe_lm.py": [
        "--steps", "3", "--batch-size", "16", "--hidden", "16"],
}


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# train_wide_deep spins a 2-server embedding fleet and compiles a second
# WideDeep train graph — tier-1's budget is dot-count-bound, and the
# dist_embedding path already runs end-to-end in tests/test_embedding.py,
# so the example smoke rides the slow tier
_SLOW_EXAMPLES = {"examples/train_wide_deep.py"}


@pytest.mark.parametrize(
    "script",
    [pytest.param(s, marks=pytest.mark.slow) if s in _SLOW_EXAMPLES
     else s for s in sorted(EXAMPLES)])
def test_example_runs(script, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # scratch data dirs land here
    monkeypatch.setattr(sys, "argv", [script] + list(EXAMPLES[script]))
    runpy.run_path(os.path.join(REPO_ROOT, script), run_name="__main__")


@pytest.mark.slow
def test_example_imagenet_streaming_input(tmp_path, monkeypatch):
    """The --streaming-input path: the same example feeds the sharded
    step through the data plane (chunk-leased decode fleet) instead of
    the per-process ImageRecordIter — tier-1 covers the default path;
    this rides the slow tier to avoid a second ResNet compile."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(sys, "argv", [
        "examples/train_imagenet_resnet.py", "--synthetic", "--iters",
        "2", "--batch-size", "8", "--image-shape", "3,32,32",
        "--dtype", "float32", "--streaming-input", "--telemetry"])
    runpy.run_path(
        os.path.join(REPO_ROOT, "examples/train_imagenet_resnet.py"),
        run_name="__main__")
    assert os.path.exists(str(tmp_path / "imagenet_telemetry.jsonl"))


def test_example_mnist_gluon_converges(tmp_path, monkeypatch, capsys):
    """Train-tier bar on the canonical Gluon example (the synthetic
    fallback is a LEARNABLE prototype task, so accuracy is a real
    convergence signal — models the reference train-tier, SURVEY §4)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(sys, "argv", [
        "examples/train_mnist_gluon.py", "--epochs", "2",
        "--batch-size", "256"])
    runpy.run_path(os.path.join(REPO_ROOT,
                                "examples/train_mnist_gluon.py"),
                   run_name="__main__")
    out = capsys.readouterr().out
    last = [l for l in out.splitlines() if "train acc" in l][-1]
    acc = float(last.rsplit(" ", 1)[1])
    assert acc >= 0.9, out


def test_example_mnist_module_converges(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(sys, "argv", [
        "examples/train_mnist_module.py", "--epochs", "2"])
    runpy.run_path(os.path.join(REPO_ROOT,
                                "examples/train_mnist_module.py"),
                   run_name="__main__")
    out = capsys.readouterr().out
    last = [l for l in out.splitlines() if "final val" in l][-1]
    acc = float(last.split("'accuracy', ")[1].rstrip(")]"))
    assert acc >= 0.9, out
