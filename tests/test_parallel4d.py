"""Unified 4D (dp×tp×pp×ep) parallelism acceptance: pipeline stages
and experts are SHARDINGS inside ShardedTrainStep's single donated
launch (parallel/unified.py) — the microbatched pipeline schedule runs
as masked ticks inside the program and Switch-MoE routing dispatches
with capacity-factor einsums, so ``launches_per_step`` stays 1 while
the math matches the eager island composition BIT-exactly."""
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.sharded import sharding_rule
from mxnet_tpu.test_utils import with_seed


def _mesh4d():
    return parallel.make_mesh((2, 1, 2, 2), ("dp", "tp", "pp", "ep"))


def _block(**kw):
    cfg = dict(num_stages=2, num_experts=2, in_units=8, hidden=8,
               expert_hidden=16, num_classes=8, num_microbatches=4)
    cfg.update(kw)
    net = parallel.PipelineMoEBlock(**cfg)
    net.initialize()
    return net


# ---------------------------------------------------------------------------
# acceptance: one launch, bit-exact vs the eager island composition
# ---------------------------------------------------------------------------
def test_unified_vs_islands_bit_exact_one_launch(monkeypatch):
    """The A/B harness itself (bench.py parallel_4d_ab row, in-process
    `_data=` mode like the zero_stage smoke): the unified one-launch 4D
    step trains BIT-exactly equal to the island composition (jitted
    fwd+bwd launch + per-param eager optimizer launches), with
    launches_per_step == 1 and zero new host syncs on the hot path."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setenv("BENCH_4D_BATCH", "16")
    monkeypatch.setenv("BENCH_4D_HIDDEN", "16")
    monkeypatch.setenv("BENCH_4D_ITERS", "2")
    # keep the smoke run out of the checked-in results file
    monkeypatch.setattr(bench, "JSONL_PATH", os.devnull)
    val, row = bench.bench_parallel_4d(
        "cpu", "float32", _data=bench._parallel_4d_measure())
    assert row["config"] == "parallel_4d_ab"
    assert row["losses_equal"] is True
    assert row["launches_per_step"] == 1
    assert row["island_launches_per_step"] > 1
    # sync parity: the unified step adds no host syncs over the islands
    assert row["sync_parity"] is True
    assert val > 0
    assert row["unified_speedup"] == pytest.approx(val, abs=0.01)


# ---------------------------------------------------------------------------
# satellite 3 regression: ep-sharded params must not silently replicate
# ---------------------------------------------------------------------------
@with_seed()
def test_expert_state_shardings_survive_save_load(tmp_path):
    """Optimizer state of a rule-sharded expert weight stays P(pp, ep)
    — at build, through training, and across save_states/load_states
    (regression: the state path consulted only `_zero_shardings`, so a
    non-ZeRO-eligible-but-rule-sharded param's adam moments silently
    replicated, 4× the per-device bytes they should be)."""
    mesh = _mesh4d()
    net = _block()
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01}, mesh=mesh,
        rules=net.sharding_rules(mesh), zero_stage=2)
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (16, 8)).astype(np.float32))
    y = nd.array(rng.randint(0, 8, (16,)).astype(np.float32))
    step(x, y)

    name = [n for n in step._train_names if n.endswith("expert_w1")][0]
    want = P("pp", "ep")
    # rule-sharded → excluded from ZeRO, pinned to the rule's spec
    assert step._zero_shardings[name] is None
    assert step._state_shardings[name].spec == want
    for s in step._states[name]:
        assert s.sharding.spec == want
    # the param itself is placed per the rule too (not replicated)
    w = net.collect_params()[name].data().data
    assert w.sharding.spec == want
    assert w.addressable_shards[0].data.shape[:2] == (1, 1)

    ck = str(tmp_path / "states.bin")
    step.save_states(ck)
    step.load_states(ck)
    for s in step._states[name]:
        assert s.sharding.spec == want, \
            "expert state replicated by load_states"
    # and a dense (non-rule) param still rides ZeRO over dp
    dense = [n for n in step._train_names if n.endswith("w_in")][0]
    assert step._zero_shardings[dense] is not None
    loss = step(x, y)
    assert np.isfinite(float(loss.asscalar()))


# ---------------------------------------------------------------------------
# typed validation: bad rules and mismatched meshes fail loudly
# ---------------------------------------------------------------------------
def test_sharding_rule_validation_typed_errors():
    mesh = _mesh4d()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    def dense():
        net = nn.HybridSequential(prefix="p4err_")
        with net.name_scope():
            net.add(nn.Dense(8, in_units=8))
        net.initialize()
        return net

    # a rule naming an axis the mesh doesn't have is a typed error,
    # not a silent replication
    with pytest.raises(mx.MXNetError, match="names mesh axis"):
        parallel.ShardedTrainStep(
            dense(), loss_fn, "sgd", {"learning_rate": 0.1}, mesh=mesh,
            rules=sharding_rule((r".*weight$", P("nonexistent"))))
    # so is a rule with more dims than the parameter
    with pytest.raises(mx.MXNetError, match="rank"):
        parallel.ShardedTrainStep(
            dense(), loss_fn, "sgd", {"learning_rate": 0.1}, mesh=mesh,
            rules=sharding_rule((r".*bias$", P("pp", "ep", "dp"))))
    # pp extent must equal the stage count (or 1)
    mesh_pp4 = parallel.make_mesh((1, 1, 4, 2), ("dp", "tp", "pp", "ep"))
    with pytest.raises(mx.MXNetError, match="pipeline"):
        _block().rebind_mesh(mesh_pp4)
    # experts must divide the ep extent
    with pytest.raises(mx.MXNetError, match="experts"):
        _block(num_experts=3).rebind_mesh(mesh)


# ---------------------------------------------------------------------------
# on-device router accounting: conservation, no per-step host syncs
# ---------------------------------------------------------------------------
@with_seed()
def test_moe_accounting_conserves_tokens():
    """Every (stage, token) routing slot is accounted exactly once:
    sum(expert_load) + drops == stages * batch * steps. The counters
    ride the aux-carry (grad_req='null') protocol, so the read is one
    deferred host transfer per telemetry window, not a per-step sync."""
    mesh = _mesh4d()
    net = _block()
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05}, mesh=mesh,
        rules=net.sharding_rules(mesh), zero_stage=1)
    # mesh telemetry covers the new axes (gauge iterates mesh.shape)
    from mxnet_tpu import telemetry

    fam = telemetry.registry().get("mxt_mesh_axis_size")
    assert fam.labels("pp").value == 2
    assert fam.labels("ep").value == 2
    rng = np.random.RandomState(2)
    steps, batch = 3, 16
    x = nd.array(rng.uniform(-1, 1, (batch, 8)).astype(np.float32))
    y = nd.array(rng.randint(0, 8, (batch,)).astype(np.float32))
    for _ in range(steps):
        step(x, y)
    moe = parallel.publish_moe_telemetry(net)
    total = sum(moe["expert_load"]) + moe["drops"]
    assert total == net.num_stages * batch * steps
    assert all(v >= 0 for v in moe["expert_load"])
    # second publish in the same window: the prometheus counter only
    # ever advances by the DELTA (no double count on re-publish)
    from mxnet_tpu import telemetry

    c0 = telemetry.registry().get("mxt_moe_router_drops_total").value
    again = parallel.publish_moe_telemetry(net)
    assert again["drops"] == moe["drops"]  # cumulative, unchanged
    assert again["expert_load"] == moe["expert_load"]
    assert telemetry.registry().get(
        "mxt_moe_router_drops_total").value == c0


@with_seed()
def test_pipeline_moe_forward_batch_divisibility():
    net = _block()
    vals = net.param_values()
    import jax.numpy as jnp

    x = jnp.zeros((10, 8), jnp.float32)  # 10 % 4 != 0
    with pytest.raises(mx.MXNetError, match="microbatch"):
        parallel.pipeline_moe_forward(vals, x, 4, 1.25)


def test_block_params_ride_structural_checkpoint_walk():
    """Regression: every PipelineMoEBlock weight is registered as a
    block ATTRIBUTE, not just in the internal dict — save_parameters
    (and the elastic-reshard spill) walk _reg_params, and a dict-only
    param silently dropped out of every checkpoint, so a reshard
    restored INITIAL weights."""
    net = _block()
    walked = net._collect_params_with_prefix()
    assert len(walked) == len(net.collect_params()) == 13
    for k in ("w_in", "stage_w", "router_w", "expert_w1", "w_out",
              "expert_load"):
        assert k in walked, k


def test_moe_capacity():
    assert parallel.moe_capacity(8, 2, 1.0) == 4
    assert parallel.moe_capacity(8, 2, 1.25) == 5
    assert parallel.moe_capacity(1, 8, 1.0) == 1  # floor of 1


# ---------------------------------------------------------------------------
# 4-axis mesh construction defaults + axis-role synonyms
# ---------------------------------------------------------------------------
def test_make_mesh_4d_default_names_and_synonyms():
    m = parallel.make_mesh((2, 1, 2, 2))
    assert m.axis_names == ("data", "model", "pipe", "expert")
    assert dict(m.shape) == {"data": 2, "model": 1, "pipe": 2,
                             "expert": 2}
    # rank-2 shapes keep the classic names; no-arg keeps (n, 1)
    assert parallel.make_mesh((4, 2)).axis_names == ("data", "model")
    assert dict(parallel.make_mesh().shape) == {"data": 8, "model": 1}
    # synonyms resolve per ROLE, whatever the mesh spelled them
    assert parallel.resolve_mesh_axis(m, "dp") == "data"
    assert parallel.resolve_mesh_axis(m, "pp") == "pipe"
    assert parallel.resolve_mesh_axis(m, "ep") == "expert"
    short = parallel.make_mesh((2, 1, 2, 2), ("dp", "tp", "pp", "ep"))
    assert parallel.resolve_mesh_axis(short, "dp") == "dp"
    assert parallel.resolve_mesh_axis(short, "ep") == "ep"
    two = parallel.make_mesh((4, 2), ("data", "model"))
    assert parallel.resolve_mesh_axis(two, "pp") is None
