"""mx.image detection pipeline (ref: python/mxnet/image/detection.py)."""
import random as _pyrandom

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mximg


def _label(rows):
    return np.asarray(rows, np.float32)


def test_det_horizontal_flip_coords():
    _pyrandom.seed(0)
    img = np.arange(4 * 6 * 3, dtype=np.uint8).reshape(4, 6, 3)
    lbl = _label([[0, 0.1, 0.2, 0.4, 0.6],
                  [-1, 0, 0, 0, 0]])
    aug = mximg.DetHorizontalFlipAug(p=1.0)
    out, l2 = aug(img, lbl)
    np.testing.assert_array_equal(np.asarray(out), img[:, ::-1, :])
    np.testing.assert_allclose(l2[0], [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    assert l2[1, 0] == -1  # padding rows untouched


def test_det_borrow_aug_preserves_label():
    aug = mximg.DetBorrowAug(mximg.CastAug())
    img = np.ones((5, 5, 3), np.uint8) * 7
    lbl = _label([[1, 0.1, 0.1, 0.9, 0.9]])
    out, l2 = aug(img, lbl)
    np.testing.assert_array_equal(l2, lbl)
    assert out.asnumpy().dtype == np.float32


def test_det_random_crop_keeps_covered_objects():
    _pyrandom.seed(3)
    img = np.zeros((40, 40, 3), np.uint8)
    # big centered object — any accepted crop must keep it covered
    lbl = _label([[2, 0.3, 0.3, 0.7, 0.7]])
    aug = mximg.DetRandomCropAug(min_object_covered=0.5,
                                 area_range=(0.5, 1.0))
    for _ in range(10):
        out, l2 = aug(img, lbl)
        if l2[0, 0] >= 0:
            box = l2[0, 1:5]
            assert (box >= -1e-6).all() and (box <= 1 + 1e-6).all()
            assert box[2] > box[0] and box[3] > box[1]


def test_det_random_pad_shrinks_boxes():
    _pyrandom.seed(1)
    img = np.full((20, 20, 3), 9, np.uint8)
    lbl = _label([[0, 0.0, 0.0, 1.0, 1.0]])
    aug = mximg.DetRandomPadAug(area_range=(2.0, 2.5))
    out, l2 = aug(img, lbl)
    oh, ow = np.asarray(out).shape[:2]
    assert oh >= 20 and ow >= 20 and (oh, ow) != (20, 20)
    w_frac = l2[0, 3] - l2[0, 1]
    assert w_frac < 1.0  # box occupies a smaller fraction after padding


def test_create_det_augmenter_chain_runs():
    _pyrandom.seed(0)
    augs = mximg.CreateDetAugmenter((3, 32, 32), rand_crop=0.5,
                                    rand_pad=0.5, rand_mirror=True,
                                    brightness=0.1)
    img = np.random.RandomState(0).randint(0, 255, (48, 48, 3),
                                           dtype=np.uint8)
    lbl = _label([[0, 0.2, 0.2, 0.8, 0.8]])
    for _ in range(5):
        out, l2 = img, lbl
        for a in augs:
            out, l2 = a(out, l2)
        assert l2.shape == lbl.shape


def test_image_det_iter(tmp_path):
    from mxnet_tpu import recordio

    _pyrandom.seed(0)
    p = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, p, "w")
    for i in range(10):
        img = rng.randint(0, 255, (40, 40, 3), dtype=np.uint8)
        # packed label: header_width=2, label_width=5, then 2 objects
        label = [2, 5,
                 i % 3, 0.1, 0.1, 0.5, 0.5,
                 (i + 1) % 3, 0.4, 0.4, 0.9, 0.9]
        w.write_idx(i, recordio.pack_img((len(label), label, i, 0), img,
                                         img_fmt=".png"))
    w.close()

    it = mximg.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=p, max_objects=4,
                            rand_mirror=True)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4, 4, 5)
    lbl = batch.label[0].asnumpy()
    assert (lbl[:, 0, 0] >= 0).all()   # first two rows are objects
    assert (lbl[:, 2:, 0] == -1).all()  # rest padded
    assert it.provide_label[0].shape == (4, 4, 5)


def test_create_det_augmenter_preserves_image_content():
    """Regression: the color chain must not center-crop to 1x1."""
    _pyrandom.seed(0)
    augs = mximg.CreateDetAugmenter((3, 32, 32), brightness=0.0)
    img = np.zeros((32, 32, 3), np.uint8)
    img[:16] = 200  # top half bright: structure must survive
    lbl = _label([[0, 0.1, 0.1, 0.9, 0.9]])
    out, _ = img, lbl
    for a in augs:
        out, _ = a(out, lbl)
    arr = np.asarray(out.asnumpy() if hasattr(out, "asnumpy") else out)
    assert arr.shape[:2] == (32, 32)
    assert arr[:16].mean() > arr[16:].mean() + 50


def test_image_det_iter_shuffle_kwarg(tmp_path):
    from mxnet_tpu import recordio

    p = str(tmp_path / "s.rec")
    w = recordio.MXIndexedRecordIO(str(tmp_path / "s.idx"), p, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        img = rng.randint(0, 255, (36, 36, 3), dtype=np.uint8)
        label = [2, 5, i, 0.1, 0.1, 0.5, 0.5]
        w.write_idx(i, recordio.pack_img((len(label), label, i, 0), img,
                                         img_fmt=".png"))
    w.close()
    it = mximg.ImageDetIter(batch_size=3, data_shape=(3, 32, 32),
                            path_imgrec=p, max_objects=2, shuffle=True)
    ids = []
    for b in it:
        ids.extend(b.label[0].asnumpy()[:, 0, 0].tolist())
    assert sorted(int(v) for v in ids) == list(range(6))


def test_det_random_crop_retries_until_covered():
    # tiny corner object + strict coverage: single-shot sampling almost
    # always fails, the attempt loop must retry geometry until a crop
    # containing the object is found (ref: DetRandomCropAug max_attempts)
    _pyrandom.seed(0)
    img = np.arange(48 * 48 * 3, dtype=np.uint8).reshape(48, 48, 3)
    lbl = _label([[1, 0.05, 0.05, 0.15, 0.15]])
    aug = mximg.DetRandomCropAug(min_object_covered=0.99,
                                 area_range=(0.1, 0.3),
                                 min_eject_coverage=0.5,
                                 max_attempts=100)
    cropped = 0
    for _ in range(20):
        out, l2 = aug(img, lbl)
        if out.shape != img.shape:
            cropped += 1
            assert l2[0, 0] == 1  # object survived fully covered
    assert cropped >= 10  # retries make acceptance the common case


def test_det_random_crop_ejects_low_coverage():
    _pyrandom.seed(1)
    img = np.zeros((40, 40, 3), np.uint8)
    lbl = _label([[1, 0.4, 0.4, 0.6, 0.6],
                  [2, 0.0, 0.0, 0.08, 0.08]])
    aug = mximg.DetRandomCropAug(min_object_covered=0.9,
                                 area_range=(0.2, 0.4),
                                 min_eject_coverage=0.9,
                                 max_attempts=200)
    saw_eject = False
    for _ in range(30):
        _, l2 = aug(img, lbl)
        kept = l2[l2[:, 0] >= 0]
        if len(kept) and len(kept) < 2:
            saw_eject = True
            assert kept[0, 0] == 1  # the centered box is the survivor
    assert saw_eject


def test_multi_rand_crop_augmenter_bank():
    bank = mximg.CreateMultiRandCropAugmenter(
        min_object_covered=[0.1, 0.5, 0.9],
        aspect_ratio_range=(0.75, 1.33),
        area_range=(0.3, 1.0))
    assert len(bank.aug_list) == 3
    assert [a.min_object_covered for a in bank.aug_list] == [0.1, 0.5, 0.9]
    _pyrandom.seed(2)
    img = np.zeros((32, 32, 3), np.uint8)
    lbl = _label([[0, 0.2, 0.2, 0.8, 0.8]])
    out, l2 = bank(img, lbl)
    assert out.shape[2] == 3 and l2.shape == lbl.shape

    with pytest.raises(mx.MXNetError):
        mximg.CreateMultiRandCropAugmenter(
            min_object_covered=[0.1, 0.5],
            min_eject_coverage=[0.1, 0.2, 0.3])


def test_create_det_augmenter_color_zoo():
    augs = mximg.CreateDetAugmenter((3, 32, 32), rand_crop=0.5,
                                    rand_pad=0.5, rand_mirror=True,
                                    brightness=0.1, contrast=0.1,
                                    saturation=0.1, hue=0.1,
                                    pca_noise=0.05, rand_gray=0.2,
                                    min_object_covered=[0.1, 0.7],
                                    mean=(0, 0, 0), std=(1, 1, 1))
    _pyrandom.seed(4)
    img = np.random.RandomState(0).randint(
        0, 255, (40, 40, 3)).astype(np.uint8)
    lbl = _label([[1, 0.25, 0.25, 0.75, 0.75]])
    for _ in range(5):
        out, l2 = img, lbl
        for a in augs:
            out, l2 = a(out, l2)
        arr = np.asarray(out.asnumpy() if hasattr(out, "asnumpy") else out)
        assert np.isfinite(arr).all()
        assert l2.shape == lbl.shape


def test_hue_gray_lighting_augs():
    from mxnet_tpu.image.image import (HueJitterAug, LightingAug,
                                       RandomGrayAug, _PCA_EIGVAL,
                                       _PCA_EIGVEC)

    rng = np.random.RandomState(0)
    img = rng.uniform(0, 255, (8, 8, 3)).astype(np.float32)
    _pyrandom.seed(0)
    # hue=0 is identity (rotation by 0)
    out = HueJitterAug(0.0)(img).asnumpy()
    np.testing.assert_allclose(out, img, atol=1e-3)
    # gray with p=1 has equal channels preserving luma
    g = RandomGrayAug(1.0)(img).asnumpy()
    np.testing.assert_allclose(g[..., 0], g[..., 1], atol=1e-4)
    luma = img @ np.array([0.299, 0.587, 0.114], np.float32)
    np.testing.assert_allclose(g[..., 0], luma, atol=1e-3)
    # lighting with alphastd=0 is identity
    out = LightingAug(0.0, _PCA_EIGVAL, _PCA_EIGVEC)(img).asnumpy()
    np.testing.assert_allclose(out, img, atol=1e-4)
