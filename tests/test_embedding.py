"""Distributed sparse embedding parameter server (mxnet_tpu/embedding/
+ the kvstore 'dist_embedding' type + gluon.Trainer routing).

Fleet tests run IN-PROCESS (embedding.local_fleet — real sockets on
loopback, real membership registrations, no subprocesses) with bounded
polls and millisecond retry budgets — no wall-clock sleeps. The
chaos-marked cells (embedding_server_kill) are swept per seed by
tools/chaos_matrix.sh via MXT_CHAOS_SEED.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import embedding, nd
from mxnet_tpu import optimizer as opt
from mxnet_tpu.base import MXNetError
from mxnet_tpu.membership import StaleWorkerError


def _seed():
    return int(os.environ.get("MXT_CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    """Dead servers must surface in milliseconds, not the production
    30s retry budget; membership stays on (fencing active)."""
    monkeypatch.setenv("MXT_KV_RETRIES", "1")
    monkeypatch.setenv("MXT_KV_RETRY_BASE", "0.02")
    monkeypatch.setenv("MXT_KV_RETRY_MAX", "0.05")
    monkeypatch.setenv("MXT_MEMBERSHIP", "1")
    yield


@pytest.fixture
def fleet2():
    fleet, handles = embedding.local_fleet(2, worker_id=0, timeout=3.0)
    yield fleet, handles
    fleet.close()
    # non-coordinator servers first: their graceful deregister needs
    # server 0 (the fleet coordinator) still listening
    for h in reversed(handles):
        try:
            h.close()
        except Exception:  # noqa: BLE001 — killed handles
            pass


def _counter_total(name):
    from mxnet_tpu import telemetry

    fam = telemetry.registry().get(name)
    if fam is None:
        return 0.0
    return float(sum(ch.value for ch in fam.children().values()))


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------
def test_hash_ring_balance_and_stability():
    ring = embedding.HashRing(vnodes=64).rebuild([0, 1, 2, 3])
    ids = np.arange(20000)
    owners = np.array([ring.owner(i) for i in ids])
    counts = np.bincount(owners, minlength=4)
    # vnodes smooth placement: no server owns more than ~2x its share
    assert counts.min() > 0 and counts.max() < 2 * len(ids) / 4
    # removing one server remaps ONLY that server's rows
    ring.rebuild([0, 1, 3])
    moved = sum(1 for i in ids if owners[i] != 2
                and ring.owner(i) != owners[i])
    assert moved == 0
    # determinism: a fresh ring over the same member set routes the same
    ring2 = embedding.HashRing(vnodes=64).rebuild([0, 1, 3])
    assert all(ring.owner(i) == ring2.owner(i) for i in ids[:500])


def test_route_covers_batch_one_group_per_server():
    ring = embedding.HashRing(vnodes=16).rebuild(["a", "b"])
    ids = np.random.RandomState(_seed()).randint(0, 10000, size=300)
    routed = ring.route(ids)
    assert set(routed) <= {"a", "b"}
    all_pos = np.sort(np.concatenate(list(routed.values())))
    assert np.array_equal(all_pos, np.arange(len(ids)))


# ---------------------------------------------------------------------------
# hot-row cache
# ---------------------------------------------------------------------------
def test_hot_row_cache_lru_and_telemetry():
    from mxnet_tpu import diagnostics

    cache = embedding.HotRowCache("t_unit", capacity=4, dim=2)
    assert diagnostics.ledger().pool_bytes("hot_row_cache") >= 4 * 2 * 4
    rows = np.arange(12, dtype=np.float32).reshape(6, 2)
    cache.insert([0, 1, 2, 3], rows[:4])
    hit_pos, hit_slots, miss_pos = cache.lookup([0, 2, 9])
    assert len(hit_pos) == 2 and list(miss_pos) == [2]
    got = np.asarray(cache.gather(hit_slots))
    assert np.allclose(got, rows[[0, 2]])
    # 0 and 2 are now most-recent; inserting two new rows evicts 1, 3
    cache.insert([4, 5], rows[4:6])
    assert len(cache) == 4
    _, _, miss = cache.lookup([1, 3])
    assert len(miss) == 2
    _, _, miss = cache.lookup([0, 2, 4, 5])
    assert len(miss) == 0
    cache.invalidate([0])
    _, _, miss = cache.lookup([0])
    assert len(miss) == 1
    assert 0.0 < cache.hit_ratio < 1.0
    cache.close()
    assert diagnostics.ledger().pool_bytes("hot_row_cache") == 0 or \
        "t_unit" not in diagnostics.ledger().snapshot().get(
            "hot_row_cache", {}).get("entries", {})


# ---------------------------------------------------------------------------
# sharded push/pull
# ---------------------------------------------------------------------------
def test_push_pull_roundtrip_two_servers(fleet2):
    fleet, _ = fleet2
    init = np.random.RandomState(_seed()).randn(64, 8).astype(np.float32)
    tbl = embedding.ShardedEmbedding(fleet, "rt", (64, 8), cache_rows=16)
    tbl.init(init)
    fleet.set_optimizer(opt.create("sgd", learning_rate=0.5))
    ids = np.array([1, 5, 5, 40])  # duplicate combines client-side
    got = np.asarray(tbl.pull(ids))
    assert got.shape == (4, 8)
    assert np.allclose(got, init[ids])
    g = np.ones((4, 8), np.float32)
    tbl.push(ids, g)  # id 5 contributes twice -> grad 2.0
    after = np.asarray(tbl.pull(np.array([1, 5, 40, 0])))
    exp = init.copy()
    exp[[1, 40]] -= 0.5
    exp[5] -= 0.5 * 2.0
    assert np.allclose(after[:3], exp[[1, 5, 40]], atol=1e-6)
    assert np.allclose(after[3], init[0])
    tbl.close()


def test_batched_ops_cost_one_rpc_per_server(fleet2):
    fleet, _ = fleet2
    tbl = embedding.ShardedEmbedding(fleet, "rpc", (1000, 4),
                                     cache_rows=0)
    tbl.init(np.zeros((1000, 4), np.float32))
    ids = np.arange(500)  # spans both servers for sure
    routed = fleet.ring.route(ids)
    assert len(routed) == 2
    r0 = _counter_total("mxt_embedding_rpcs_total")
    tbl.pull(ids)
    pulls = _counter_total("mxt_embedding_rpcs_total") - r0
    assert pulls == len(routed)  # <=1 RPC per destination server
    r0 = _counter_total("mxt_embedding_rpcs_total")
    tbl.push(ids, np.ones((500, 4), np.float32))
    pushes = _counter_total("mxt_embedding_rpcs_total") - r0
    assert pushes == len(routed)
    tbl.close()


def test_sparse_path_compile_count_bucket_bounded():
    """The PR-11 finding fixed: varying data-dependent unique-row
    counts replay pow2-bucketed programs instead of recompiling the
    sparse path per step (PERF.md measured ~320 compiles/8 steps) —
    after a short shape warmup, steps with FRESH row counts inside the
    same buckets compile NOTHING. One server (multi-server scatter
    threads can race-compile the same program — concurrency noise) and
    no hot-row cache (its hit/miss split drifts as the LRU fills,
    legitimately minting a new smaller bucket mid-run; the cache
    bucket path is covered by the cache tests) keep the lap exact."""
    import jax

    from mxnet_tpu import tuning

    # hermetic: earlier suites can leave jax's bounded eager-dispatch
    # caches near eviction, which would charge THEIR evictions to this
    # test's measured lap
    jax.clear_caches()
    fleet, handles = embedding.local_fleet(1, worker_id=0, timeout=3.0)
    tbl = embedding.ShardedEmbedding(fleet, "cc", (4096, 8),
                                     cache_rows=0)
    tbl.init_lazy(seed=1)
    fleet.set_optimizer(opt.create("sgd", learning_rate=0.1))
    rng = np.random.RandomState(_seed())

    def step(vocab):
        # batch size FIXED (the training-loop shape); the UNIQUE count
        # is data-dependent via the draw range — the exact shape class
        # that used to mint fresh programs every step
        ids = rng.randint(0, vocab, 320).astype(np.int64)
        rows = tbl.pull(ids)
        tbl.push(ids, np.asarray(rows) * 0.01)

    vocabs = (3000, 500, 1500, 420, 2500)
    for _ in range(2):  # warm every bucket this distribution visits
        for vocab in vocabs:
            step(vocab)
    c0 = tuning.compile_stats()
    for vocab in vocabs:  # fresh draws -> fresh unique/hit/miss counts
        step(vocab)
    c1 = tuning.compile_stats()
    fresh = c1["compiles"] - c0["compiles"]
    assert fresh == 0, \
        "sparse path compiled %d fresh programs for same-bucket shapes" \
        % fresh
    tbl.close()
    fleet.close()
    for h in handles:
        h.close()


def test_cache_write_back_on_push(fleet2):
    fleet, _ = fleet2
    tbl = embedding.ShardedEmbedding(fleet, "wb", (50, 4), cache_rows=32)
    tbl.init(np.zeros((50, 4), np.float32))
    fleet.set_optimizer(opt.create("sgd", learning_rate=1.0))
    ids = np.arange(10)
    tbl.pull(ids)  # cold: misses fill the cache
    tbl.push(ids, np.ones((10, 4), np.float32))  # reply writes back
    r0 = _counter_total("mxt_embedding_rpcs_total")
    after = np.asarray(tbl.pull(ids))
    # the post-push pull is served ENTIRELY from the device cache...
    assert _counter_total("mxt_embedding_rpcs_total") == r0
    # ...with the server-updated values, not the stale pre-push rows
    assert np.allclose(after, -1.0)
    tbl.close()


def test_lazy_init_never_materializes_table(fleet2):
    fleet, handles = fleet2
    tbl = embedding.ShardedEmbedding(fleet, "lazy", (10 ** 6, 8),
                                     cache_rows=64)
    tbl.init_lazy(seed=3, scale=0.5)
    ids = np.array([0, 123456, 999999])
    rows = np.asarray(tbl.pull(ids))
    assert rows.shape == (3, 8) and np.abs(rows).max() > 0
    # deterministic: a second pull through a fresh fleet-side path
    # (cache bypass) returns identical values
    rows2 = np.asarray(tbl.pull(ids))
    assert np.allclose(rows, rows2)
    # only the touched rows exist anywhere in the fleet
    resident = sum(h.store.rows_resident() for h in handles)
    assert resident == 3
    tbl.close()


# ---------------------------------------------------------------------------
# generation + ring-epoch fencing for sparse pushes
# ---------------------------------------------------------------------------
def test_fenced_worker_sparse_push_refused_typed():
    fleet, handles = embedding.local_fleet(1, worker_id=7, timeout=3.0)
    try:
        tbl = embedding.ShardedEmbedding(fleet, "f", (20, 4),
                                         cache_rows=0)
        tbl.init(np.zeros((20, 4), np.float32))
        fleet.set_optimizer(opt.create("sgd", learning_rate=1.0))
        tbl.push([1], np.ones((1, 4), np.float32))
        # a second incarnation of worker 7 registers: the first fleet's
        # generation is fenced — its delayed gradient rows must be
        # refused typed and must not touch the weights
        fleet2 = embedding.EmbeddingFleet(coordinator=fleet.coordinator,
                                          timeout=3.0)
        fleet2.refresh()
        fleet2.register_worker(7)
        with pytest.raises(StaleWorkerError, match="fenced"):
            tbl.push([1], np.full((1, 4), 100.0, np.float32))
        tbl2 = embedding.ShardedEmbedding(fleet2, "f", (20, 4),
                                          cache_rows=0)
        vals = np.asarray(tbl2.pull([1]))
        assert np.allclose(vals, -1.0)  # only the live push landed
        fleet2.close()
    finally:
        fleet.close()
        for h in reversed(handles):
            h.close()


def test_reshard_inherited_rows_adopt_ring_epoch():
    """A server that inherits rows (emb_load) adopts the sender's ring
    epoch: a push stamped from BEFORE the reshard is refused typed; the
    client-side heal path refreshes the ring and re-sends under the
    current epoch."""
    from mxnet_tpu.embedding.store import EmbeddingStore

    store = EmbeddingStore()
    store.handle("emb_init", "t",
                 ((10, 2), "float32", np.arange(10),
                  np.zeros((10, 2), np.float32), 0))
    # reshard at epoch 5 hands rows to this server
    store.handle("emb_load", "t",
                 (np.array([3]), np.ones((1, 2), np.float32), 5))
    with pytest.raises(StaleWorkerError, match="stale ring epoch"):
        store.handle("emb_push", "t",
                     (np.array([3]), np.ones((1, 2), np.float32), 4))
    # rows untouched by the stale frame; a current-epoch push applies
    _, (found, rows, _) = store.handle("emb_pull", "t",
                                       (np.array([3]), 5))
    assert np.allclose(rows, 1.0)
    store.handle("emb_push", "t",
                 (np.array([3]), np.ones((1, 2), np.float32), 5))


def test_snapshot_crc_detects_corruption(tmp_path):
    from mxnet_tpu.embedding.store import EmbeddingStore

    store = EmbeddingStore(snapshot_dir=str(tmp_path), server_id=0)
    store.handle("emb_init", "t",
                 ((4, 2), "float32", np.arange(4),
                  np.ones((4, 2), np.float32), 0))
    path = store.save_snapshot()
    # round-trips clean
    restored = EmbeddingStore(snapshot_dir=str(tmp_path), server_id=0)
    assert restored.rows_resident() == 4
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    with pytest.raises(MXNetError, match="CRC"):
        EmbeddingStore(snapshot_dir=str(tmp_path), server_id=0)


# ---------------------------------------------------------------------------
# kvstore 'dist_embedding' + gluon.Trainer
# ---------------------------------------------------------------------------
def test_kvstore_dist_embedding_api(monkeypatch):
    from mxnet_tpu import config, kvstore

    monkeypatch.setenv("MXT_EMBEDDING_LOCAL_SERVERS", "2")
    monkeypatch.setenv("MXT_EMBEDDING_CACHE_ROWS", "8")
    del config  # env vars read at kvstore creation
    kv = kvstore.create("dist_embedding")
    try:
        init = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("0", nd.array(init))
        kv.set_optimizer(opt.create("sgd", learning_rate=1.0))
        from mxnet_tpu.sparse import row_sparse_array

        grad = row_sparse_array(
            (np.ones((2, 4), np.float32), np.array([2, 7])), shape=(10, 4))
        kv.push("0", grad)
        out = nd.array(init.copy())
        kv.row_sparse_pull("0", out=out, row_ids=nd.array([2, 7]))
        got = np.asarray(out.data)
        exp = init.copy()
        exp[[2, 7]] -= 1.0
        assert np.allclose(got, exp)  # touched rows updated, rest kept
        with pytest.raises(MXNetError, match="row_sparse_pull"):
            kv.pull("0", out=out)
    finally:
        kv.close()


def _train_wide_deep(kvstore_name, iters=3, seed=0):
    mx.random.seed(0)
    from mxnet_tpu.gluon import model_zoo

    net = model_zoo.wide_deep(wide_vocab=500, deep_vocab=200, embed_dim=8,
                              hidden=(16,), classes=2, sparse_grad=True)
    net.initialize()
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    tr = mx.gluon.Trainer(net.collect_params(), "adam",
                          {"learning_rate": 1e-2}, kvstore=kvstore_name)
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(iters):
        xw = nd.array(rng.randint(0, 500, (24, 8)).astype("f4"))
        xd = nd.array(rng.randint(0, 200, (24, 4)).astype("f4"))
        y = nd.array(rng.randint(0, 2, (24,)).astype("f4"))
        with mx.autograd.record():
            out = net(xw, xd)
            loss = loss_fn(out, y).mean()
        loss.backward()
        tr.step(24)
        losses.append(float(loss.asnumpy()))
    # keyed by position: gluon name prefixes auto-increment per model
    # instantiation (widedeep0_, widedeep1_, ...) within one process
    weights = {i: np.asarray(p.data().data)
               for i, p in enumerate(tr._params)}
    kv = tr._kvstore
    stats = {}
    if kv is not None and kv.type == "dist_embedding":
        for key, t in kv._emb_tables.items():
            if t.cache is not None:
                stats[key] = (t.cache.hit_ratio, len(t.cache),
                              t.cache.capacity)
        kv.close()
    return np.asarray(losses), weights, stats


def test_wide_deep_dist_embedding_loss_parity(monkeypatch):
    """ACCEPTANCE: Wide&Deep with sharded tables and a hot-row cache
    SMALLER than the table trains loss-equal (<=1e-5) vs the
    single-process dense-KVStore baseline — with the dense towers on
    the fused step and only the hot set resident device-side."""
    base_losses, base_w, _ = _train_wide_deep("local", seed=_seed())
    monkeypatch.setenv("MXT_EMBEDDING_LOCAL_SERVERS", "2")
    monkeypatch.setenv("MXT_EMBEDDING_CACHE_ROWS", "64")  # < 500-row table
    emb_losses, emb_w, stats = _train_wide_deep("dist_embedding",
                                                seed=_seed())
    assert np.abs(base_losses - emb_losses).max() <= 1e-5
    for name in base_w:
        assert np.allclose(base_w[name], emb_w[name], atol=1e-5), name
    assert stats, "no sharded tables were created"
    for _, (ratio, resident, cap) in stats.items():
        assert cap == 64 and resident <= cap
        assert ratio > 0.0  # the write-back path produced device hits


# ---------------------------------------------------------------------------
# bench A/B + console + lint satellites
# ---------------------------------------------------------------------------
def test_bench_embedding_ab_scaling(monkeypatch):
    """ACCEPTANCE: embedding_bytes_per_sec increases with server count
    in the 1-vs-2-server A/B (in-process fleet)."""
    import bench

    monkeypatch.setenv("BENCH_EMB_VOCAB", "20000")
    monkeypatch.setenv("BENCH_EMB_BATCH", "2048")
    monkeypatch.setenv("BENCH_EMB_ITERS", "4")
    monkeypatch.setenv("BENCH_EMB_WARMUP", "1")
    monkeypatch.setenv("BENCH_EMB_CACHE", "4096")
    row = None
    for _ in range(2):  # one retry damps scheduler noise on loaded CI
        scaling, row = bench.bench_embedding_ab("cpu", "float32")
        if row["embedding_bytes_per_sec_2srv"] > \
                row["embedding_bytes_per_sec_1srv"]:
            break
    assert row["embedding_bytes_per_sec_2srv"] > \
        row["embedding_bytes_per_sec_1srv"], row
    assert row["embedding_bytes_per_sec"] > 0
    assert 0.0 < row["cache_hit_ratio_2srv"] < 1.0
    assert row["rpcs_per_step_2srv"] <= 2.0  # <=1 RPC/server/op


def test_mxt_top_embedding_section():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    samples = {
        ("mxt_embedding_rows_resident", frozenset({("table", "t")})): 512,
        ("mxt_embedding_cache_hits_total",
         frozenset({("table", "t")})): 90,
        ("mxt_embedding_cache_misses_total",
         frozenset({("table", "t")})): 10,
        ("mxt_embedding_cache_evictions_total",
         frozenset({("table", "t")})): 3,
    }
    frame = mod.render(samples, None, 0)
    assert "emb rows res." in frame
    assert "0.900" in frame  # hit ratio
    # a process with no embedding gauges renders no embedding noise
    assert "emb rows res." not in mod.render({}, None, 0)


def test_merge_mixed_dense_sparse_reduces_on_device():
    """Satellite: kvstore._merge mixed dense/row_sparse lists reduce
    over the index union on device (no per-value asnumpy densify)."""
    from mxnet_tpu.kvstore import KVStore
    from mxnet_tpu.sparse import row_sparse_array

    kv = KVStore("local")
    dense = nd.array(np.ones((6, 3), np.float32))
    rsp = row_sparse_array(
        (np.full((2, 3), 2.0, np.float32), np.array([1, 4])), shape=(6, 3))
    merged = kv._merge([dense, rsp, dense])
    got = np.asarray(merged.data)
    exp = np.full((6, 3), 2.0, np.float32)
    exp[[1, 4]] += 2.0
    assert np.allclose(got, exp)
    # all-sparse stays sparse (index union)
    m2 = kv._merge([rsp, rsp])
    assert m2.stype == "row_sparse"
    assert np.allclose(np.asarray(m2._values), 4.0)


def test_host_sync_lint_covers_embedding_and_kvstore():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "check_host_syncs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for rel in ("mxnet_tpu/kvstore.py", "mxnet_tpu/embedding/client.py",
                "mxnet_tpu/embedding/cache.py",
                "mxnet_tpu/embedding/store.py",
                "mxnet_tpu/embedding/hashing.py"):
        assert rel in mod.SCAN, rel
    root = os.path.join(os.path.dirname(__file__), "..")
    assert mod.check(root) == []


# ---------------------------------------------------------------------------
# chaos: embedding_server_kill (swept by tools/chaos_matrix.sh)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_embedding_server_kill_remap_rejoin():
    """Kill one embedding server mid-train: the ring remaps its rows to
    the survivors (worker-side re-seed via emb_load), training
    continues, and a restarted server rejoins from its shard snapshot —
    every transition typed, no hang."""
    snap = tempfile.mkdtemp()
    rng = np.random.RandomState(_seed())
    fleet, handles = embedding.local_fleet(2, snapshot_dir=snap,
                                           worker_id=0, timeout=3.0)
    rejoined = None
    try:
        mirror = rng.randn(40, 4).astype(np.float32).copy()
        tbl = embedding.ShardedEmbedding(
            fleet, "ck", (40, 4), cache_rows=8,
            recover=lambda ids: mirror[np.asarray(ids, dtype=np.int64)])
        tbl.init(mirror)
        fleet.set_optimizer(opt.create("sgd", learning_rate=0.1))

        def step():
            ids = rng.randint(0, 40, size=16).astype(np.int64)
            rows = tbl.pull(ids)
            tbl.push(ids, np.asarray(rows) * 0.01)
            # keep the worker-side mirror current (the trainer's dense
            # buffer plays this role on the gluon path)
            got = np.asarray(tbl.pull(ids)).reshape(-1, 4)
            mirror[np.unique(ids)] = np.asarray(
                tbl.pull(np.unique(ids))).reshape(-1, 4)
            return got

        for _ in range(3):
            step()
        fleet.snapshot()  # both shards persist
        handles[1].kill()  # SIGKILL-shaped: socket gone, beats stop
        for _ in range(3):  # remap to survivor + re-seed, no hang
            step()
        assert fleet.live_servers() == [0]
        # rejoin: new server process (new port), same id + snapshot dir
        rejoined = embedding.start_local_server(
            1, coordinator=fleet.coordinator, snapshot_dir=snap)
        assert rejoined.store.rows_resident() > 0  # shard restored
        fleet.refresh()
        assert fleet.live_servers() == [0, 1]
        for _ in range(3):  # rows flow through the rejoined server
            step()
        full = np.asarray(tbl.pull(np.arange(40))).reshape(40, 4)
        assert np.isfinite(full).all()
        assert np.allclose(full, mirror, atol=1e-5)
    finally:
        fleet.close()
        # rejoined first: its graceful deregister needs the coordinator
        # (server 0) alive
        if rejoined is not None:
            rejoined.close()
        for h in handles[:1]:
            h.close()
