"""Expert parallelism (parallel/moe.py) on the virtual CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.moe import (moe_apply, stack_expert_params,
                                    switch_load_balance_loss)


def _setup(E, D, H, seed=0):
    mesh = parallel.make_mesh((E,), ("expert",),
                              devices=jax.devices("cpu")[:E])
    rng = np.random.RandomState(seed)
    experts = [{"w1": jnp.array(rng.normal(size=(D, H))
                                .astype(np.float32)) * 0.3,
                "w2": jnp.array(rng.normal(size=(H, D))
                                .astype(np.float32)) * 0.3}
               for _ in range(E)]
    gate_w = jnp.array(rng.normal(size=(D, E)).astype(np.float32))
    return mesh, experts, gate_w


def _expert(p, h):
    return jax.nn.relu(h @ p["w1"]) @ p["w2"]


@pytest.mark.parametrize("E", [2, 4])
def test_moe_matches_dense_routing(E):
    """With ample capacity every token is processed by its argmax
    expert, scaled by the gate — compare against the dense loop."""
    D, H, N = 6, 8, 16
    mesh, experts, gate_w = _setup(E, D, H)
    params = stack_expert_params(experts)
    x = jnp.array(np.random.RandomState(1)
                  .uniform(-1, 1, (N, D)).astype(np.float32))

    out, (gates, mask) = moe_apply(_expert, params, gate_w, x, mesh,
                                   capacity_factor=float(E * 4))
    g_ref = jax.nn.softmax(x @ gate_w, axis=-1)
    idx = np.asarray(jnp.argmax(g_ref, axis=-1))
    ref = np.stack([
        np.asarray(_expert(experts[idx[i]], x[i][None])[0]
                   * g_ref[i, idx[i]])
        for i in range(N)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                               atol=1e-5)
    assert float(mask.sum()) == N  # nothing dropped


def test_moe_capacity_drops_tokens():
    """Over-capacity tokens combine to zero output (Switch semantics)."""
    E, D, H, N = 2, 4, 6, 8
    mesh, experts, gate_w = _setup(E, D, H, seed=2)
    # force every token to expert 0
    gate_w = gate_w.at[:, 0].set(10.0).at[:, 1].set(-10.0)
    params = stack_expert_params(experts)
    x = jnp.array(np.random.RandomState(3)
                  .uniform(-1, 1, (N, D)).astype(np.float32))
    out, (gates, mask) = moe_apply(_expert, params, gate_w, x, mesh,
                                   capacity_factor=0.5)
    # capacity = max(1, int(4 * 0.5 / 2)) = 1 per device -> 2 of 8 kept
    kept = float(mask.sum())
    assert kept < N
    dropped_rows = np.asarray(mask.sum(-1)) == 0
    np.testing.assert_allclose(np.asarray(out)[dropped_rows], 0.0)


def test_moe_grads_and_training():
    E, D, H, N = 4, 6, 8, 16
    mesh, experts, gate_w = _setup(E, D, H, seed=4)
    params = stack_expert_params(experts)
    rng = np.random.RandomState(5)
    x = jnp.array(rng.uniform(-1, 1, (N, D)).astype(np.float32))
    y = jnp.array(rng.uniform(-1, 1, (N, D)).astype(np.float32))

    @jax.jit
    def step(params, gate_w):
        def loss(p, wg):
            out, (gates, mask) = moe_apply(_expert, p, wg, x, mesh,
                                           capacity_factor=8.0)
            return (((out - y) ** 2).mean()
                    + 0.01 * switch_load_balance_loss(gates, mask))
        l, (gp, gw) = jax.value_and_grad(loss, argnums=(0, 1))(
            params, gate_w)
        params = jax.tree_util.tree_map(lambda a, g: a - 0.3 * g,
                                        params, gp)
        return params, gate_w - 0.3 * gw, l

    first = None
    for _ in range(200):
        params, gate_w, l = step(params, gate_w)
        if first is None:
            first = float(l)
    assert np.isfinite(float(l))
    assert float(l) < 0.75 * first, (first, float(l))


def test_moe_validation():
    mesh, experts, gate_w = _setup(2, 4, 6)
    params = stack_expert_params(experts)
    with pytest.raises(MXNetError, match="not divisible"):
        moe_apply(_expert, params, gate_w, jnp.zeros((5, 4)), mesh)
    with pytest.raises(MXNetError, match="one expert per device"):
        moe_apply(_expert, stack_expert_params(experts + experts),
                  gate_w, jnp.zeros((4, 4)), mesh)
    with pytest.raises(MXNetError, match="no 'nope' axis"):
        moe_apply(_expert, params, gate_w, jnp.zeros((4, 4)), mesh,
                  axis="nope")
    with pytest.raises(MXNetError, match="at least one expert"):
        stack_expert_params([])
