"""Test config: force an 8-device CPU mesh BEFORE jax initializes, so
multi-device sharding paths are exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; see __graft_entry__.py)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# numeric tests compare against numpy float32/64; don't let XLA downcast
jax.config.update("jax_default_matmul_precision", "highest")
