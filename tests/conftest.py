"""Test config: force an 8-device CPU mesh so multi-device sharding paths
are exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py).

NOTE: the axon TPU plugin (sitecustomize) force-sets jax_platforms to
'axon,cpu' at interpreter start, overriding the JAX_PLATFORMS env var — so
the env var alone does NOT keep tests off the TPU tunnel. The config.update
below runs after registration and wins. Without it, every test op rides the
single-client TPU tunnel and can wedge it.
"""
import os

import pytest

_TPU_LANE = os.environ.get("MXT_TEST_TPU", "") == "1"

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

if not _TPU_LANE:
    jax.config.update("jax_platforms", "cpu")
    # numeric tests compare against numpy float32/64; don't let XLA downcast
    jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: hardware smoke test — run with `MXT_TEST_TPU=1 pytest -m tpu` "
        "on a machine with a real TPU (round-2 lesson: interpret-mode-only "
        "Pallas coverage let a hardware-invalid BlockSpec ship)")
    config.addinivalue_line(
        "markers",
        "nightly: slow/large-resource tier (ref: tests/nightly/) — run "
        "with MXT_TEST_NIGHTLY=1; skipped in the default suite")
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (kill-and-resume soaks) — excluded "
        "from the tier-1 gate, which runs -m 'not slow'")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (seeded MXT_FAULT, "
        "resilience.py) — fast enough to run in tier-1")


def pytest_collection_modifyitems(config, items):
    if _TPU_LANE:
        # the CPU-calibrated numeric suite must not run on the TPU backend
        # (tolerances assume highest matmul precision, and hundreds of tests
        # would serialize through the single-client TPU tunnel)
        skip = pytest.mark.skip(
            reason="CPU-lane test skipped under MXT_TEST_TPU=1")
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
        return
    skip = pytest.mark.skip(
        reason="TPU lane disabled (set MXT_TEST_TPU=1 and run -m tpu)")
    skip_nightly = pytest.mark.skip(
        reason="nightly tier disabled (set MXT_TEST_NIGHTLY=1)")
    nightly_on = os.environ.get("MXT_TEST_NIGHTLY", "") == "1"
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
        # NB: get_closest_marker, not `in item.keywords` — keywords
        # include ancestor node names, so the tests/nightly/ DIRECTORY
        # name would gate unmarked tests living there
        if item.get_closest_marker("nightly") is not None \
                and not nightly_on:
            item.add_marker(skip_nightly)
