"""Test config: force an 8-device CPU mesh so multi-device sharding paths
are exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py).

NOTE: the axon TPU plugin (sitecustomize) force-sets jax_platforms to
'axon,cpu' at interpreter start, overriding the JAX_PLATFORMS env var — so
the env var alone does NOT keep tests off the TPU tunnel. The config.update
below runs after registration and wins. Without it, every test op rides the
single-client TPU tunnel and can wedge it.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# numeric tests compare against numpy float32/64; don't let XLA downcast
jax.config.update("jax_default_matmul_precision", "highest")
