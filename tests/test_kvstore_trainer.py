"""KVStore + Trainer tests (models tests/python/unittest/test_kvstore.py and
the trainer portions of test_gluon.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import gluon
from mxnet_tpu.test_utils import assert_almost_equal, with_seed

SHAPE = (4, 4)


# ---------------------------------------------------------------------------
# KVStore
# ---------------------------------------------------------------------------
def test_kvstore_single_push_pull():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE))
    kv.push(3, nd.ones(SHAPE) * 4)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 4)


def test_kvstore_aggregate_list_push():
    kv = mx.kv.create("device")
    kv.init("a", nd.zeros(SHAPE))
    vals = [nd.ones(SHAPE)] * 4
    kv.push("a", vals)
    out = nd.zeros(SHAPE)
    kv.pull("a", out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 4)


def test_kvstore_string_and_list_keys():
    kv = mx.kv.create("local")
    keys = ["b", "c", "d"]
    kv.init(keys, [nd.ones(SHAPE)] * 3)
    outs = [nd.zeros(SHAPE) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        assert_almost_equal(o.asnumpy(), np.ones(SHAPE))


def test_kvstore_updater_on_push():
    kv = mx.kv.create("local")
    kv.init(0, nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(0, nd.ones(SHAPE))  # grad = 1 → w = 1 - 0.1*1
    out = nd.zeros(SHAPE)
    kv.pull(0, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 0.9, rtol=1e-6)


def test_kvstore_pull_uninited_raises():
    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError):
        kv.pull("nope", out=nd.zeros(SHAPE))


def test_kvstore_types():
    for t in ("local", "device", "nccl", "dist_sync", "dist_async"):
        kv = mx.kv.create(t)
        assert kv.type == t
        assert kv.rank == 0
    with pytest.raises(mx.MXNetError):
        mx.kv.create("bogus")


# ---------------------------------------------------------------------------
# Trainer
# ---------------------------------------------------------------------------
def _tiny_net():
    net = gluon.nn.Dense(1, in_units=2, use_bias=False, prefix="tnet_")
    net.initialize()
    return net


@with_seed()
def test_trainer_step_updates_params():
    net = _tiny_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    w_before = net.weight.data().asnumpy().copy()
    x = nd.array(np.ones((4, 2), dtype=np.float32))
    with mx.autograd.record():
        y = net(x)
        loss = (y * y).sum()
    loss.backward()
    trainer.step(4)
    w_after = net.weight.data().asnumpy()
    assert not np.allclose(w_before, w_after)
    # grad is rescaled by 1/batch_size
    g = net.weight.grad().asnumpy()
    assert_almost_equal(w_after, w_before - 0.1 * g / 4.0,
                        rtol=1e-5, atol=1e-6)


@with_seed()
def test_trainer_converges_linear_regression():
    rng = np.random.RandomState(0)
    true_w = np.array([[2.0, -3.4]], dtype=np.float32)
    X = rng.normal(size=(256, 2)).astype(np.float32)
    Y = X @ true_w.T + 1.2

    net = gluon.nn.Dense(1, in_units=2, prefix="linreg_")
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    l2 = gluon.loss.L2Loss()
    for epoch in range(60):
        with mx.autograd.record():
            out = net(nd.array(X))
            loss = l2(out, nd.array(Y)).mean()
        loss.backward()
        trainer.step(1)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    assert_almost_equal(w, true_w, rtol=5e-2, atol=5e-2)
    assert abs(float(b.reshape(())[()]) - 1.2) < 0.1


def test_trainer_update_on_kvstore_dist_semantics():
    net = _tiny_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="dist_sync")
    x = nd.array(np.ones((2, 2), dtype=np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(2)
    assert trainer._update_on_kvstore is True
    assert trainer._kvstore.type == "dist_sync"
    # allreduce_grads forbidden when updating on kvstore (reference behavior)
    with pytest.raises(AssertionError):
        trainer.allreduce_grads()


@with_seed()
def test_trainer_save_load_states(tmp_path):
    net = _tiny_net()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = nd.array(np.ones((2, 2), dtype=np.float32))
    for _ in range(3):
        with mx.autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(2)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)

    net2 = gluon.nn.Dense(1, in_units=2, use_bias=False, prefix="tnet2_")
    net2.initialize()
    net2.weight.set_data(net.weight.data())
    trainer2 = gluon.Trainer(net2.collect_params(), "adam",
                             {"learning_rate": 0.01})
    trainer2.load_states(fname)
    # one more identical step must produce identical weights
    for t, n in ((trainer, net), (trainer2, net2)):
        with mx.autograd.record():
            loss = (n(x) ** 2).sum()
        loss.backward()
        t.step(2)
    assert_almost_equal(net.weight.data().asnumpy(),
                        net2.weight.data().asnumpy(), rtol=1e-6, atol=1e-7)


def test_trainer_learning_rate_set_and_scheduler():
    net = _tiny_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1

    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5, base_lr=1.0)
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 1.0, "lr_scheduler": sched})
    with pytest.raises(UserWarning):
        trainer2.set_learning_rate(0.1)


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------
def test_metric_accuracy():
    m = mx.metric.Accuracy()
    preds = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    labels = nd.array([1, 0, 0])
    m.update([labels], [preds])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3.0) < 1e-6


def test_metric_topk():
    m = mx.metric.TopKAccuracy(top_k=2)
    preds = nd.array([[0.1, 0.2, 0.7], [0.5, 0.4, 0.1]])
    labels = nd.array([1, 1])
    m.update([labels], [preds])
    _, acc = m.get()
    assert abs(acc - 1.0) < 1e-6  # label 1 in top2 both times


def test_metric_mse_mae_rmse():
    labels = nd.array([1.0, 2.0, 3.0])
    preds = nd.array([1.5, 2.0, 2.0])
    mse = mx.metric.MSE()
    mse.update([labels], [preds])
    assert abs(mse.get()[1] - np.mean([0.25, 0.0, 1.0])) < 1e-6
    mae = mx.metric.MAE()
    mae.update([labels], [preds])
    assert abs(mae.get()[1] - np.mean([0.5, 0.0, 1.0])) < 1e-6
    rmse = mx.metric.RMSE()
    rmse.update([labels], [preds])
    assert abs(rmse.get()[1] - np.sqrt(np.mean([0.25, 0.0, 1.0]))) < 1e-6


def test_metric_cross_entropy_and_perplexity():
    preds = nd.array([[0.2, 0.8], [0.6, 0.4]])
    labels = nd.array([1, 0])
    ce = mx.metric.create("ce")
    ce.update([labels], [preds])
    expected = -(np.log(0.8) + np.log(0.6)) / 2
    assert abs(ce.get()[1] - expected) < 1e-6
    ppl = mx.metric.Perplexity(ignore_label=None)
    ppl.update([labels], [preds])
    assert abs(ppl.get()[1] - np.exp(expected)) < 1e-5


def test_metric_f1():
    m = mx.metric.F1()
    preds = nd.array([[0.8, 0.2], [0.3, 0.7], [0.1, 0.9], [0.6, 0.4]])
    labels = nd.array([0, 1, 1, 1])
    m.update([labels], [preds])
    # tp=2 fp=0 fn=1 → p=1, r=2/3, f1=0.8
    assert abs(m.get()[1] - 0.8) < 1e-6


def test_metric_composite_and_custom():
    comp = mx.metric.create(["accuracy", "mse"])
    assert isinstance(comp, mx.metric.CompositeEvalMetric)

    def my_metric(label, pred):
        return float(np.sum(label == label))

    cm = mx.metric.np(my_metric)
    labels = nd.array([1.0, 2.0])
    cm.update([labels], [labels])
    assert cm.get()[1] == 2.0


def test_metric_registry_create():
    for name in ("acc", "top_k_accuracy", "f1", "mae", "mse", "rmse",
                 "ce", "nll_loss", "pearsonr", "loss"):
        m = mx.metric.create(name) if name != "top_k_accuracy" else \
            mx.metric.create(name, top_k=3)
        assert isinstance(m, mx.metric.EvalMetric)
    with pytest.raises(mx.MXNetError):
        mx.metric.create("not_a_metric")


def test_kvstore_server_role_explains_design(monkeypatch):
    """The server-role entry must fail with the collectives-design
    explanation, not an ImportError (ref: kvstore_server.py; the guard
    also runs at module import — covered in test_dist's subprocess
    lane)."""
    from mxnet_tpu import kvstore_server
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError,
                       match="no separate parameter-server process"):
        kvstore_server.KVStoreServer(None)
    monkeypatch.setenv("DMLC_ROLE", "server")
    with pytest.raises(MXNetError, match="workers only"):
        kvstore_server._init_kvstore_server_module()


def test_dist_async_warns_sync_semantics():
    import warnings

    import mxnet_tpu.kvstore as kvs

    kvs._warned_async = False
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        kv = mx.kv.create("dist_async")
        assert kv.type == "dist_async"
    assert any("SYNCHRONOUS semantics" in str(x.message) for x in w), \
        [str(x.message) for x in w]
    with warnings.catch_warnings(record=True) as w2:  # once per process
        warnings.simplefilter("always")
        mx.kv.create("dist_async")
    assert not w2
