"""Multi-host GSPMD scale-out tests on the 8-device CPU mesh: the ZeRO
weight-update-sharding ladder (stages 1/2/3, arXiv 2004.13336), sharded
checkpoint/resume across mesh shapes, and elastic in-place mesh
resharding fused with the membership layer (parallel/reshard.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.membership import MembershipTable
from mxnet_tpu.resilience import CheckpointManager
from mxnet_tpu.test_utils import with_seed


def _bn_mlp(prefix, in_units=8):
    """Dims all divisible by 8 so every trainable tensor is
    ZeRO-eligible at dp=8 (BN gamma/beta included; running stats are
    aux and stay replicated)."""
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, in_units=in_units), nn.BatchNorm(),
                nn.Activation("relu"), nn.Dense(8, in_units=16))
    net.initialize()
    net(nd.zeros((2, in_units)))
    return net


def _params_np(net):
    return {n: p.data().asnumpy()
            for n, p in net.collect_params().items()}


def _gauge_value(name, *labels):
    fam = telemetry.registry().get(name)
    if fam is None:
        return None
    return fam.labels(*labels).value if labels else fam.value


# ---------------------------------------------------------------------------
# ZeRO-2/3 acceptance: bit-exact vs replicated, bytes shrink ~dp×
# ---------------------------------------------------------------------------
@with_seed()
@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_zero_stages_bit_exact_vs_replicated(opt, opt_params):
    """Acceptance: ZeRO-2 and ZeRO-3 train BIT-EXACT (<=1e-6 over 5
    steps, sgd-mom + adam, BatchNorm aux carried) vs the replicated
    stage-0 baseline on the 8-device mesh — the ladder only changes
    layout/collectives, never math. (Stage 1 parity is pinned by the
    legacy shard_update tests in test_parallel.py.)"""
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (16, 8)).astype(np.float32)
    y = rng.randint(0, 8, (16,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(axis_names=("data",))

    mx.random.seed(5)
    ref_net = _bn_mlp("zref%s_" % opt)
    ref = parallel.ShardedTrainStep(ref_net, loss_fn, opt,
                                    dict(opt_params), mesh=mesh,
                                    zero_stage=0)
    for _ in range(5):
        l_ref = ref(nd.array(x), nd.array(y))
    ref_params = _params_np(ref_net)
    # BN aux actually moved (the stats ride the fused program)
    rm = [v for n, v in ref_params.items() if n.endswith("running_mean")]
    assert any(np.abs(a).max() > 0 for a in rm)

    for stage in (2, 3):
        mx.random.seed(5)
        net = _bn_mlp("z%d%s_" % (stage, opt))
        step = parallel.ShardedTrainStep(net, loss_fn, opt,
                                         dict(opt_params), mesh=mesh,
                                         zero_stage=stage)
        for _ in range(5):
            loss = step(nd.array(x), nd.array(y))
        assert abs(float(loss.asscalar()) - float(l_ref.asscalar())) \
            <= 1e-6, "stage %d loss diverged" % stage
        for n, v in _params_np(net).items():
            ref_v = ref_params[n.replace("z%d%s_" % (stage, opt),
                                         "zref%s_" % opt)]
            np.testing.assert_allclose(
                v, ref_v, rtol=1e-6, atol=1e-6,
                err_msg="stage %d param %s" % (stage, n))


@with_seed()
def test_zero_stage_per_device_bytes_shrink():
    """The memory claim itself: optimizer-state bytes/device shrink dp×
    at stages 1-3, param bytes/device shrink at stage 3 only (aux BN
    stats stay replicated by design)."""
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = parallel.make_mesh(axis_names=("data",))
    dp = 8
    sizes = {}
    for stage in (0, 1, 2, 3):
        mx.random.seed(7)
        net = _bn_mlp("zb%d_" % stage)
        step = parallel.ShardedTrainStep(net, loss_fn, "adam",
                                         {"learning_rate": 0.01},
                                         mesh=mesh, zero_stage=stage)
        sizes[stage] = step.per_device_bytes()
        # states for eligible params truly live sharded on device
        if stage >= 1:
            for n in step._train_names:
                z = step._zero_shardings[n]
                assert z is not None, n  # every trainable is eligible
                for s in step._states[n]:
                    assert s.addressable_shards[0].data.shape[0] \
                        == s.shape[0] // dp
    # adam m+v: every trainable eligible -> exactly dp× smaller
    assert sizes[1]["opt_state_bytes"] * dp == sizes[0]["opt_state_bytes"]
    assert sizes[2]["opt_state_bytes"] * dp == sizes[0]["opt_state_bytes"]
    assert sizes[3]["opt_state_bytes"] * dp == sizes[0]["opt_state_bytes"]
    # params replicate until stage 3; aux stays replicated at stage 3 so
    # the shrink is ~dp× on the trainables only
    assert sizes[1]["param_bytes"] == sizes[0]["param_bytes"]
    assert sizes[2]["param_bytes"] == sizes[0]["param_bytes"]
    assert sizes[3]["param_bytes"] < sizes[0]["param_bytes"] / (dp / 2)
    # the gauges mxt_top's mesh section reads are live
    assert _gauge_value("mxt_mesh_devices") == 8
    assert _gauge_value("mxt_zero_stage") == 3
    assert _gauge_value("mxt_per_device_opt_bytes") \
        == sizes[3]["opt_state_bytes"]


@with_seed()
def test_zero_stage_composes_with_tp_rules_and_validates():
    """tp-rule-sharded params are excluded from ZeRO at every stage;
    zero_stage outside 0..3 is a typed error; the legacy shard_update
    flag maps to stage 2."""
    mesh = parallel.make_mesh((4, 2), ("data", "model"))
    rules = parallel.sharding_rule((r"dense0_weight", P("model", None)))
    net = _bn_mlp("ztp_")
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01}, mesh=mesh, rules=rules, zero_stage=3)
    w_tp = [n for n in step._train_names if "dense0_weight" in n][0]
    assert step._zero_shardings[w_tp] is None
    assert "model" in str(
        net.collect_params()[w_tp].data().data.sharding.spec)
    assert any(z is not None for z in step._zero_shardings.values())

    with pytest.raises(mx.MXNetError):
        parallel.ShardedTrainStep(
            _bn_mlp("zbad_"), mx.gluon.loss.SoftmaxCrossEntropyLoss(),
            "sgd", {}, mesh=mesh, zero_stage=4)

    legacy = parallel.ShardedTrainStep(
        _bn_mlp("zleg_"), mx.gluon.loss.SoftmaxCrossEntropyLoss(),
        "adam", {"learning_rate": 0.01},
        mesh=parallel.make_mesh(axis_names=("data",)), shard_update=True)
    assert legacy.zero_stage == 2


# ---------------------------------------------------------------------------
# shard_params satellite: batched placement, already-placed skipped
# ---------------------------------------------------------------------------
@with_seed()
def test_shard_params_skips_already_placed():
    """The resume-path fix: a second shard_params pass over an
    already-placed net moves NOTHING (same buffers), and a partial
    change moves only the changed entries."""
    net = _bn_mlp("sp_")
    mesh = parallel.make_mesh(axis_names=("data",))
    params = net.collect_params()
    moved = parallel.shard_params(params, mesh)
    assert moved == len(params)
    before = {n: p.data().data for n, p in params.items()}
    assert parallel.shard_params(params, mesh) == 0  # all skipped
    for n, p in params.items():
        assert p.data().data is before[n]  # buffers untouched
    # re-rule one param: exactly one placement happens
    rules = parallel.sharding_rule((r"dense1_weight", P(None, "data")))
    assert parallel.shard_params(params, mesh, rules) == 1


# ---------------------------------------------------------------------------
# sharded save/resume across mesh shapes (satellite 3)
# ---------------------------------------------------------------------------
@with_seed()
def test_sharded_save_resume_onto_different_mesh(tmp_path):
    """CheckpointManager.save() on a sharded step, then resume() onto a
    DIFFERENT dp×tp mesh shape: weights restore bit-exactly (shards as
    the transfer format — the same path the elastic reshard rides) and
    training continues."""
    rng = np.random.RandomState(2)
    x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
    y = rng.randint(0, 8, (8,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    rules = parallel.sharding_rule((r"dense0_weight", P("model", None)))

    mx.random.seed(11)
    net_a = _bn_mlp("cka_")
    mesh_a = parallel.make_mesh((4, 2), ("data", "model"))
    step_a = parallel.ShardedTrainStep(net_a, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh_a, rules=rules,
                                       zero_stage=2)
    for _ in range(3):
        step_a(nd.array(x), nd.array(y))
    mgr_a = CheckpointManager(str(tmp_path), net=net_a, trainer=step_a,
                              prefix="shck")
    mgr_a.save(step=step_a.step_count)
    want = _params_np(net_a)

    # fresh process-analog: new net + step on a (2, 4) mesh
    mx.random.seed(99)  # deliberately different init — resume overwrites
    net_b = _bn_mlp("cka_")
    mesh_b = parallel.make_mesh((2, 4), ("data", "model"))
    step_b = parallel.ShardedTrainStep(net_b, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh_b, rules=rules,
                                       zero_stage=2)
    mgr_b = CheckpointManager(str(tmp_path), net=net_b, trainer=step_b,
                              prefix="shck")
    state = mgr_b.resume()
    assert state is not None and state.step == 3
    assert step_b.step_count == 3
    for n, v in _params_np(net_b).items():
        assert np.array_equal(v, want[n]), n  # bit-exact restore
    # placements follow the NEW mesh: tp rule now shards 4-way
    w = net_b.collect_params()[
        [n for n in want if "dense0_weight" in n][0]]
    assert w.data().data.addressable_shards[0].data.shape[0] \
        == w.shape[0] // 4
    # and the step still trains on the new mesh shape
    loss = step_b(nd.array(x), nd.array(y))
    assert np.isfinite(float(loss.asscalar()))


# ---------------------------------------------------------------------------
# survivor-mesh planning units
# ---------------------------------------------------------------------------
def test_host_device_map_and_plan_survivor_mesh():
    mesh = parallel.make_mesh((4, 2), ("data", "model"))
    hm = parallel.HostDeviceMap.from_mesh(mesh, 4)
    assert hm.num_hosts == 4
    # losing host 2 drops exactly its tp pair, order preserved
    devs = hm.devices_for_survivors({2})
    assert len(devs) == 6
    flat = list(mesh.devices.reshape(-1))
    assert devs == flat[:4] + flat[6:]

    small = parallel.plan_survivor_mesh(mesh, {2}, hm)
    assert dict(small.shape) == {"data": 3, "model": 2}
    assert small.axis_names == mesh.axis_names
    # two losses -> (2, 2); no loss -> None (nothing changes)
    small2 = parallel.plan_survivor_mesh(mesh, {1, 2}, hm)
    assert dict(small2.shape) == {"data": 2, "model": 2}
    assert parallel.plan_survivor_mesh(mesh, set(), hm) is None
    # a map that can't keep tp whole is a typed error
    hm_odd = parallel.HostDeviceMap(8, list(mesh.devices.reshape(-1)))
    with pytest.raises(mx.MXNetError):
        parallel.plan_survivor_mesh(mesh, {0}, hm_odd)
    # every host dead is typed too
    with pytest.raises(mx.MXNetError):
        hm.devices_for_survivors({0, 1, 2, 3})
    with pytest.raises(mx.MXNetError):
        parallel.HostDeviceMap(3)  # 8 devices don't split 3 ways


# ---------------------------------------------------------------------------
# elastic reshard acceptance
# ---------------------------------------------------------------------------
@with_seed()
def test_elastic_reshard_acceptance(tmp_path):
    """Acceptance: 8-device (4×2) mesh training; the membership reaper
    fences one data-parallel rank mid-run; survivors reshard IN PLACE
    to (3×2) and continue. The resulting weights match a from-checkpoint
    restart on the smaller mesh BIT-exactly, with zero full-job restarts
    and the resharding event visible in telemetry.

    Runs ISOLATED in a fresh interpreter: in a full-suite session the
    in-place mesh rebuild lands on an XLA CPU client already carrying
    hundreds of compiled programs, which intermittently segfaults at
    interpreter teardown (ROADMAP standing item). A clean process keeps
    the acceptance deterministic without masking real failures — the
    inner run's verdict is asserted, not swallowed."""
    if os.environ.get("MXT_RESHARD_ACCEPTANCE_INNER") != "1":
        env = dict(os.environ)
        env["MXT_RESHARD_ACCEPTANCE_INNER"] = "1"
        env.setdefault("JAX_PLATFORMS", "cpu")
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x",
             "%s::test_elastic_reshard_acceptance"
             % os.path.abspath(__file__),
             "-p", "no:cacheprovider", "-p", "no:xdist",
             "-p", "no:randomly"],
            env=env, timeout=600, capture_output=True, text=True)
        assert r.returncode == 0, \
            "isolated reshard acceptance failed (rc=%d)\n%s\n%s" \
            % (r.returncode, r.stdout[-4000:], r.stderr[-2000:])
        return
    spill = str(tmp_path / "reshard_spill")
    rng = np.random.RandomState(1)
    # batch 12: divisible by dp=4 before and dp=3 after the reshard
    x = rng.uniform(-1, 1, (12, 6)).astype(np.float32)
    y = rng.randint(0, 6, (12,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    def build():
        mx.random.seed(3)
        net = nn.HybridSequential(prefix="ers_")
        with net.name_scope():
            net.add(nn.Dense(24, activation="relu", in_units=6),
                    nn.Dense(6, in_units=24))
        net.initialize()
        return net

    ev0 = _gauge_value("mxt_reshard_events_total") or 0

    # ---- path A: live run with an in-place reshard -------------------
    net_a = build()
    mesh = parallel.make_mesh((4, 2), ("data", "model"))
    step_a = parallel.ShardedTrainStep(net_a, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh, zero_stage=2)
    hm = parallel.HostDeviceMap.from_mesh(mesh, 4)
    ctrl = parallel.ElasticReshardController(step_a, hm, spill_dir=spill)
    table = MembershipTable()
    ctrl.attach(table)
    gens = {w: table.register(w, now=0.0)[0] for w in range(4)}

    losses_a = []
    for _ in range(3):
        assert ctrl.maybe_reshard() is None  # healthy: no-op
        losses_a.append(float(step_a(nd.array(x),
                                     nd.array(y)).asscalar()))
    # worker 2 goes silent; the reaper fences it and (via the death
    # listener) the controller learns without being polled
    for w in (0, 1, 3):
        table.heartbeat(w, gens[w], now=100.0)
    assert table.reap(10.0, now=100.0) == [2]
    assert ctrl.pending == {2}
    event = ctrl.maybe_reshard()
    assert event is not None
    assert event["old_shape"] == {"data": 4, "model": 2}
    assert event["new_shape"] == {"data": 3, "model": 2}
    assert event["lost_workers"] == [2]
    assert event["step"] == 3
    assert dict(step_a.mesh.shape) == {"data": 3, "model": 2}
    # ZeRO eligibility re-decided for dp=3: 24-wide tensors shard, the
    # 6-wide head falls back replicated (24 % 3 == 0, 6 % 3 == 0 — use
    # dim0 checks directly)
    for n in step_a._train_names:
        d = net_a.collect_params()[n].data().data
        if d.shape[0] % 3 == 0:
            assert step_a._zero_shardings[n] is not None, n
    for _ in range(2):
        loss_a = step_a(nd.array(x), nd.array(y))
    weights_a = _params_np(net_a)

    # telemetry: the reshard event is visible
    assert (_gauge_value("mxt_reshard_events_total") or 0) == ev0 + 1
    assert _gauge_value("mxt_mesh_devices") == 6
    assert _gauge_value("mxt_mesh_axis_size", "data") == 3

    # ---- path B: from-checkpoint restart on the smaller mesh ---------
    net_b = build()
    mesh_b = parallel.plan_survivor_mesh(mesh, {2}, hm)
    step_b = parallel.ShardedTrainStep(net_b, loss_fn, "adam",
                                       {"learning_rate": 0.01},
                                       mesh=mesh_b, zero_stage=2)
    mgr = CheckpointManager(spill, net=net_b, trainer=step_b,
                            prefix="reshard")
    state = mgr.resume()
    assert state is not None and state.step == 3
    for _ in range(2):
        loss_b = step_b(nd.array(x), nd.array(y))

    assert float(loss_a.asscalar()) == float(loss_b.asscalar())
    for n, v in _params_np(net_b).items():
        assert np.array_equal(v, weights_a[n]), \
            "in-place reshard diverged from restart at %s" % n


@pytest.mark.chaos
@with_seed()
def test_elastic_reshard_4d_acceptance(tmp_path):
    """Acceptance (4D): a (2,1,2,2) dp×tp×pp×ep mesh trains the unified
    pipeline+MoE step; the reaper fences one dp rank (seeded victim —
    swept by tools/chaos_matrix.sh via MXT_CHAOS_SEED); survivors
    reshard IN PLACE to (1,1,2,2) — pp preserved, experts REMAPPED onto
    the survivor devices with unchanged local shard shapes, ZeRO
    re-decided — and the result matches a from-checkpoint restart on
    the survivor mesh BIT-exactly. Same interpreter isolation as
    test_elastic_reshard_acceptance (in-place mesh rebuild on a hot XLA
    CPU client)."""
    if os.environ.get("MXT_RESHARD_4D_INNER") != "1":
        env = dict(os.environ)
        env["MXT_RESHARD_4D_INNER"] = "1"
        env.setdefault("JAX_PLATFORMS", "cpu")
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x",
             "%s::test_elastic_reshard_4d_acceptance"
             % os.path.abspath(__file__),
             "-p", "no:cacheprovider", "-p", "no:xdist",
             "-p", "no:randomly"],
            env=env, timeout=600, capture_output=True, text=True)
        assert r.returncode == 0, \
            "isolated 4D reshard acceptance failed (rc=%d)\n%s\n%s" \
            % (r.returncode, r.stdout[-4000:], r.stderr[-2000:])
        return
    spill = str(tmp_path / "reshard4d_spill")
    victim = int(os.environ.get("MXT_CHAOS_SEED", "1")) % 2
    rng = np.random.RandomState(4)
    # batch 16 / 4 microbatches = 4-token slices: divide dp=2 and dp=1
    x = rng.uniform(-1, 1, (16, 8)).astype(np.float32)
    y = rng.randint(0, 8, (16,)).astype(np.float32)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    def build():
        mx.random.seed(5)
        net = parallel.PipelineMoEBlock(
            num_stages=2, num_experts=2, in_units=8, hidden=8,
            expert_hidden=16, num_classes=8, num_microbatches=4,
            prefix="ers4d_")
        net.initialize()
        return net

    # ---- path A: live run with an in-place 4D reshard ----------------
    net_a = build()
    mesh = parallel.make_mesh((2, 1, 2, 2), ("dp", "tp", "pp", "ep"))
    step_a = parallel.ShardedTrainStep(
        net_a, loss_fn, "adam", {"learning_rate": 0.01}, mesh=mesh,
        rules=net_a.sharding_rules(mesh), zero_stage=2)
    # 2 hosts × 4 devices: each host holds one full dp rank (a whole
    # tp×pp×ep block), so losing a host shrinks dp 2 -> 1
    hm = parallel.HostDeviceMap.from_mesh(mesh, 2)
    ctrl = parallel.ElasticReshardController(step_a, hm, spill_dir=spill)
    table = MembershipTable()
    ctrl.attach(table)
    gens = {w: table.register(w, now=0.0)[0] for w in range(2)}

    for _ in range(3):
        assert ctrl.maybe_reshard() is None
        step_a(nd.array(x), nd.array(y))
    table.heartbeat(1 - victim, gens[1 - victim], now=100.0)
    assert table.reap(10.0, now=100.0) == [victim]
    assert ctrl.pending == {victim}
    event = ctrl.maybe_reshard()
    assert event is not None
    assert event["old_shape"] == {"dp": 2, "tp": 1, "pp": 2, "ep": 2}
    assert event["new_shape"] == {"dp": 1, "tp": 1, "pp": 2, "ep": 2}
    assert event["lost_workers"] == [victim]
    assert dict(step_a.mesh.shape) == {"dp": 1, "tp": 1, "pp": 2,
                                       "ep": 2}
    # experts remapped onto the 4 survivor devices: sharding spec and
    # LOCAL shard shapes unchanged (ep extent survived the shrink)
    ew = [n for n in step_a._train_names
          if n.endswith("expert_w1")][0]
    d = net_a.collect_params()[ew].data().data
    assert d.sharding.spec == P("pp", "ep")
    assert len(d.sharding.device_set) == 4
    assert d.addressable_shards[0].data.shape[:2] == (1, 1)
    survivors = set(step_a.mesh.devices.reshape(-1))
    assert {s.device for s in d.addressable_shards} <= survivors
    # ZeRO re-decided against the SURVIVOR mesh: rule-sharded expert
    # params stay excluded, dense params' zero shardings now name the
    # new mesh (dp extent 1 — effectively replicated, still dp-owned)
    assert step_a._zero_shardings[ew] is None
    for n in step_a._train_names:
        z = step_a._zero_shardings[n]
        if z is not None:
            assert z.mesh.shape == step_a.mesh.shape, n
    # the resharded 4D program lowers ahead of the next step
    assert step_a.aot_warmup() is True
    for _ in range(2):
        loss_a = step_a(nd.array(x), nd.array(y))
    weights_a = _params_np(net_a)

    # ---- path B: from-checkpoint restart on the survivor mesh --------
    net_b = build()
    mesh_b = parallel.plan_survivor_mesh(mesh, {victim}, hm)
    assert dict(mesh_b.shape) == {"dp": 1, "tp": 1, "pp": 2, "ep": 2}
    step_b = parallel.ShardedTrainStep(
        net_b, loss_fn, "adam", {"learning_rate": 0.01}, mesh=mesh_b,
        rules=net_b.sharding_rules(mesh_b), zero_stage=2)
    mgr = CheckpointManager(spill, net=net_b, trainer=step_b,
                            prefix="reshard")
    state = mgr.resume()
    assert state is not None and state.step == 3
    for _ in range(2):
        loss_b = step_b(nd.array(x), nd.array(y))

    assert float(loss_a.asscalar()) == float(loss_b.asscalar())
    for n, v in _params_np(net_b).items():
        assert np.array_equal(v, weights_a[n]), \
            "4D in-place reshard diverged from restart at %s" % n


@with_seed()
def test_reshard_controller_poll_view_and_cumulative_losses():
    """Worker-side wiring (no table attach): poll a membership view;
    a second loss after a reshard plans against the ORIGINAL host map
    cumulatively."""
    net = nn.HybridSequential(prefix="pv_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
    net.initialize()
    net(nd.zeros((2, 4)))
    mesh = parallel.make_mesh((8,), ("data",))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, zero_stage=1)
    hm = parallel.HostDeviceMap.from_mesh(mesh, 8)
    ctrl = parallel.ElasticReshardController(step, hm)
    x = nd.array(np.random.uniform(-1, 1, (8, 4)).astype(np.float32))
    y = nd.array(np.random.randint(0, 8, (8,)).astype(np.float32))
    step(x, y)
    ctrl.poll_view({"dead": {5: 6}, "members": {}})
    ev = ctrl.maybe_reshard()
    assert ev is not None and ev["devices"] == 7
    assert ev["lost_workers"] == [5]
    # second death: cumulative plan from the original 8-slot map
    ctrl.poll_view({"dead": {5: 6, 1: 2}, "members": {}})
    ev2 = ctrl.maybe_reshard()
    assert ev2 is not None and ev2["devices"] == 6
    assert ev2["lost_workers"] == [1, 5]
    # batch 6 divides the new dp=6
    loss = step(nd.array(np.random.uniform(-1, 1, (6, 4)).astype("f4")),
                nd.array(np.random.randint(0, 8, (6,)).astype("f4")))
    assert np.isfinite(float(loss.asscalar()))


# ---------------------------------------------------------------------------
# AOT warm-start of the (resharded) step
# ---------------------------------------------------------------------------
@with_seed()
def test_sharded_step_aot_warmup_and_signature():
    """The step registers with tuning: a stepped instance records its
    batch signature and aot_warmup() compiles without touching data;
    warmup(steps=[...]) reports it (the reshard path calls exactly
    this, tagged reason='reshard')."""
    from mxnet_tpu import tuning

    tuning.reset()  # drop signatures recorded by earlier tests
    net = nn.HybridSequential(prefix="aw_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
    net.initialize()
    net(nd.zeros((2, 4)))
    mesh = parallel.make_mesh((8,), ("data",))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, zero_stage=2)
    assert step.aot_warmup() is False  # no batch signature yet
    x = nd.array(np.random.uniform(-1, 1, (8, 4)).astype(np.float32))
    y = nd.array(np.random.randint(0, 8, (8,)).astype(np.float32))
    step(x, y)
    sigs = tuning.signatures("sharded_step")
    assert any(tuple(s["x_shape"]) == (8, 4) for s in sigs)
    assert step.aot_warmup() is True
    summary = tuning.warmup(steps=[step], kernels=False,
                            include_live=False, reason="reshard")
    assert "ShardedTrainStep" in summary["entries"]
    assert summary["reason"] == "reshard"
    # warm compile + traced call agree (no numerics drift)
    loss = step(x, y)
    assert np.isfinite(float(loss.asscalar()))


# ---------------------------------------------------------------------------
# fused single-device step refuses mesh-sharded nets
# ---------------------------------------------------------------------------
@with_seed()
def test_cached_train_step_ineligible_on_mesh_sharded_params():
    from mxnet_tpu.gluon.train_step import CachedTrainStep

    net = nn.HybridSequential(prefix="el_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
    net.initialize()
    net(nd.zeros((2, 4)))
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    assert CachedTrainStep.eligible(trainer, net) is None  # single dev ok
    mesh = parallel.make_mesh(axis_names=("data",))
    parallel.shard_params(net.collect_params(), mesh)
    reason = CachedTrainStep.eligible(trainer, net)
    assert reason is not None and "mesh-sharded" in reason


# ---------------------------------------------------------------------------
# launch-line mesh env (tools/launch.py --mesh)
# ---------------------------------------------------------------------------
def test_make_mesh_reads_env(monkeypatch):
    monkeypatch.setenv("MXT_MESH_SHAPE", "4,2")
    mesh = parallel.make_mesh()
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    monkeypatch.setenv("MXT_MESH_SHAPE", "-1,2")
    mesh = parallel.make_mesh()
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    monkeypatch.setenv("MXT_MESH_SHAPE", "8")
    mesh = parallel.make_mesh()  # rank-1 shape trims the axis names
    assert dict(mesh.shape) == {"data": 8}
    monkeypatch.setenv("MXT_MESH_AXES", "dp")
    mesh = parallel.make_mesh()
    assert dict(mesh.shape) == {"dp": 8}
    # explicit shape argument still wins over the env
    mesh = parallel.make_mesh((2, 4), ("a", "b"))
    assert dict(mesh.shape) == {"a": 2, "b": 4}
    # launch.py exports exactly these vars
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "launch", os.path.join(os.path.dirname(__file__), "..",
                               "tools", "launch.py"))
    launch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch)

    class A:
        mesh = "16,2"
        mesh_axes = "data,model"
        zero_stage = 2

    extra = launch._mesh_env(A())
    assert extra == {"MXT_MESH_SHAPE": "16,2",
                     "MXT_MESH_AXES": "data,model",
                     "MXT_ZERO_STAGE": "2"}
    env = launch._worker_env({}, "127.0.0.1:1", 2, 1, extra)
    assert env["MXT_MESH_SHAPE"] == "16,2"
    assert env["MXT_ZERO_STAGE"] == "2"


def test_zero_stage_env_default(monkeypatch):
    monkeypatch.setenv("MXT_ZERO_STAGE", "2")
    net = nn.HybridSequential(prefix="ze_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
    net.initialize()
    net(nd.zeros((2, 4)))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1},
        mesh=parallel.make_mesh(axis_names=("data",)))
    assert step.zero_stage == 2


# ---------------------------------------------------------------------------
# mxt_top mesh section + lint list
# ---------------------------------------------------------------------------
def test_mxt_top_mesh_section_renders_only_with_gauges():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)

    base = {("mxt_step_latency_seconds_count", frozenset()): 10.0}
    frame = top.render(base, None, 0)
    assert "mesh" not in frame  # no gauges -> no mesh section

    samples = dict(base)
    samples[("mxt_mesh_devices", frozenset())] = 6.0
    samples[("mxt_mesh_axis_size", frozenset({("axis", "data")}))] = 3.0
    samples[("mxt_mesh_axis_size", frozenset({("axis", "model")}))] = 2.0
    samples[("mxt_zero_stage", frozenset())] = 2.0
    samples[("mxt_per_device_param_bytes", frozenset())] = 2 * 1024.0
    samples[("mxt_per_device_opt_bytes", frozenset())] = 1536.0
    samples[("mxt_reshard_events_total", frozenset())] = 1.0
    frame = top.render(samples, None, 0)
    assert "mesh" in frame and "6 dev" in frame
    assert "data=3" in frame and "model=2" in frame
    assert "zero=2" in frame
    assert "2.0KB" in frame and "1.5KB" in frame
    assert "reshards" in frame and "1" in frame
    assert "moe load" not in frame  # no moe gauges -> no moe line

    # the 4D mesh renders all four axes + the moe accounting line
    samples[("mxt_mesh_axis_size", frozenset({("axis", "pipe")}))] = 2.0
    samples[("mxt_mesh_axis_size",
             frozenset({("axis", "expert")}))] = 2.0
    samples[("mxt_moe_expert_load", frozenset({("expert", "0")}))] = 90.0
    samples[("mxt_moe_expert_load", frozenset({("expert", "1")}))] = 84.0
    samples[("mxt_moe_router_drops_total", frozenset())] = 18.0
    frame = top.render(samples, None, 0)
    assert "pipe=2" in frame and "expert=2" in frame
    assert "moe load" in frame
    assert "e0=90" in frame and "e1=84" in frame
    assert "drops=18" in frame


def test_mxt_top_jsonl_metrics_snapshot(tmp_path):
    """--jsonl mode surfaces metrics-snapshot rows (regression: tell()
    inside file iteration raised OSError and silently dropped EVERY
    row) and parses the snapshot's unquoted labels so the mesh axes
    render."""
    import importlib.util
    import json as _json

    spec = importlib.util.spec_from_file_location(
        "mxt_top", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "mxt_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)

    path = tmp_path / "t.jsonl"
    path.write_text(_json.dumps({
        "kind": "metrics",
        "data": {"mxt_mesh_devices": 6,
                 "mxt_mesh_axis_size{axis=data}": 3,
                 "mxt_zero_stage": 2}}) + "\n")
    src = top.JsonlSource(str(path))
    samples = src.sample()
    assert top.metric_sum(samples, "mxt_mesh_devices") == 6
    assert top.metric_sum(samples, "mxt_mesh_axis_size", axis="data") == 3
    frame = top.render(samples, None, 0)
    assert "6 dev" in frame and "data=3" in frame


def test_host_sync_lint_covers_parallel_modules():
    """Lint-list regression: the GSPMD layer is policed; the scan is
    clean (control-plane syncs are annotated)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_host_syncs", os.path.join(
            os.path.dirname(__file__), "..",
            "tools", "check_host_syncs.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    for rel in ("mxnet_tpu/parallel/mesh.py",
                "mxnet_tpu/parallel/sharded.py",
                "mxnet_tpu/parallel/reshard.py",
                "mxnet_tpu/parallel/pipeline.py",
                "mxnet_tpu/parallel/moe.py",
                "mxnet_tpu/parallel/unified.py"):
        assert rel in m.SCAN
    root = os.path.join(os.path.dirname(__file__), "..")
    assert m.check(root) == []


# ---------------------------------------------------------------------------
# bench row smoke (subprocess over the 8-device CPU mesh)
# ---------------------------------------------------------------------------
def test_bench_zero_stage_row_smoke(monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setenv("BENCH_ZERO_HIDDEN", "64")
    monkeypatch.setenv("BENCH_ZERO_BATCH", "16")
    monkeypatch.setenv("BENCH_ZERO_ITERS", "2")
    # keep the smoke run out of the checked-in results file
    monkeypatch.setattr(bench, "JSONL_PATH", os.devnull)
    # measure in-process (the test session already runs the 8-device
    # CPU mesh); `python bench.py` covers the subprocess wrapper
    val, row = bench.bench_zero_stages(
        "cpu", "float32", _data=bench._zero_stage_measure())
    assert row["config"] == "zero_stage_ab"
    assert row["losses_equal"] is True
    assert row["opt_bytes_shrink_z2"] == 8.0
    assert row["param_bytes_shrink_z3"] == 8.0
    assert val == 8.0
