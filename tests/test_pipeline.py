"""Pipeline parallelism (parallel/pipeline.py) on the virtual CPU mesh.

Parity bar: the GPipe schedule must match serial stage application
exactly — forward AND gradients (the backward pipeline is autodiff of
the scan, so this pins the whole schedule)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.pipeline import (pipeline_apply,
                                         pipeline_utilization,
                                         stack_stage_params)


def _setup(S, D, seed=0):
    mesh = parallel.make_mesh((S,), ("pipe",),
                              devices=jax.devices("cpu")[:S])
    rng = np.random.RandomState(seed)
    stages = [{"w": jnp.array(rng.uniform(-0.5, 0.5, (D, D))
                              .astype(np.float32)),
               "b": jnp.array(rng.uniform(-0.1, 0.1, (D,))
                              .astype(np.float32))}
              for _ in range(S)]
    return mesh, stages


def _stage(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _serial(stages, x):
    h = x
    for p in stages:
        h = _stage(p, h)
    return h


@pytest.mark.parametrize("S,M", [(2, 2), (4, 8), (8, 8)])
def test_pipeline_forward_parity(S, M):
    mesh, stages = _setup(S, 6)
    params = stack_stage_params(stages)
    x = jnp.array(np.random.RandomState(1)
                  .uniform(-1, 1, (16, 6)).astype(np.float32))
    out = pipeline_apply(_stage, params, x, mesh, num_microbatches=M)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_serial(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grad_parity():
    S, M, B, D = 4, 8, 16, 6
    mesh, stages = _setup(S, D, seed=2)
    params = stack_stage_params(stages)
    x = jnp.array(np.random.RandomState(3)
                  .uniform(-1, 1, (B, D)).astype(np.float32))

    def loss_pipe(params):
        out = pipeline_apply(_stage, params, x, mesh, num_microbatches=M)
        return (out ** 2).sum()

    def loss_serial(stages):
        return (_serial(stages, x) ** 2).sum()

    gp = jax.jit(jax.grad(loss_pipe))(params)
    gs = jax.grad(loss_serial)(stages)
    for i in range(S):
        np.testing.assert_allclose(np.asarray(gp["w"][i]),
                                   np.asarray(gs[i]["w"]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gp["b"][i]),
                                   np.asarray(gs[i]["b"]),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_trains():
    """SGD through the pipeline converges on a regression task."""
    S, M, B, D = 4, 4, 16, 4
    mesh, stages = _setup(S, D, seed=4)
    params = stack_stage_params(stages)
    rng = np.random.RandomState(5)
    x = jnp.array(rng.uniform(-1, 1, (B, D)).astype(np.float32))
    y = jnp.array(rng.uniform(-0.5, 0.5, (B, D)).astype(np.float32))

    @jax.jit
    def step(params):
        def loss(p):
            out = pipeline_apply(_stage, p, x, mesh, num_microbatches=M)
            return ((out - y) ** 2).mean()
        l, g = jax.value_and_grad(loss)(params)
        params = jax.tree_util.tree_map(lambda p_, g_: p_ - 0.5 * g_,
                                        params, g)
        return params, l

    first = None
    for _ in range(200):
        params, l = step(params)
        if first is None:
            first = float(l)
    assert float(l) < 0.75 * first, (first, float(l))


def test_pipeline_validation():
    mesh, stages = _setup(2, 4)
    params = stack_stage_params(stages)
    x = jnp.zeros((5, 4))  # 5 not divisible by 2 microbatches
    with pytest.raises(MXNetError, match="not divisible"):
        pipeline_apply(_stage, params, x, mesh, num_microbatches=2)
    with pytest.raises(MXNetError, match="no 'nope' axis"):
        pipeline_apply(_stage, params, jnp.zeros((4, 4)), mesh,
                       axis="nope")
    with pytest.raises(MXNetError, match="at least one stage"):
        stack_stage_params([])
    # stage count that's a MULTIPLE of the axis size must be rejected
    # (it would silently drop every stage but the first per device)
    mesh4, stages8 = _setup(2, 4)
    params8 = stack_stage_params(stages8 + stages8)  # 4 stages, pipe=2
    with pytest.raises(MXNetError, match="one stage per device"):
        pipeline_apply(_stage, params8, jnp.zeros((4, 4)), mesh4,
                       num_microbatches=2)


def test_pipeline_utilization():
    assert pipeline_utilization(4, 12) == pytest.approx(12 / 15)
