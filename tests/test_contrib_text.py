"""contrib.text vocab/embedding/utils
(ref: tests/python/unittest/test_contrib_text.py)."""
import collections
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str("a b c\nb c c")
    assert c == collections.Counter(
        {"c": 3, "b": 2, "a": 1})
    c2 = text.utils.count_tokens_from_str("A a", to_lower=True)
    assert c2 == collections.Counter({"a": 2})
    base = collections.Counter({"a": 1})
    got = text.utils.count_tokens_from_str("a", counter_to_update=base)
    assert got is base and base["a"] == 2


def test_vocabulary_order_and_lookup():
    counter = collections.Counter(
        {"the": 5, "a": 5, "cat": 3, "dog": 1})
    v = text.Vocabulary(counter, min_freq=2, reserved_tokens=["<pad>"])
    # unk, reserved, then freq-desc (alphabetical ties)
    assert v.idx_to_token == ["<unk>", "<pad>", "a", "the", "cat"]
    assert v.to_indices("cat") == 4
    assert v.to_indices(["zzz", "a"]) == [0, 2]
    assert v.to_tokens([0, 2]) == ["<unk>", "a"]
    assert len(v) == 5
    with pytest.raises(ValueError):
        v.to_tokens(99)


def test_vocabulary_most_freq_count():
    counter = collections.Counter({"a": 3, "b": 2, "c": 1})
    v = text.Vocabulary(counter, most_freq_count=2)
    assert v.idx_to_token == ["<unk>", "a", "b"]


def test_vocabulary_validation():
    with pytest.raises(ValueError):
        text.Vocabulary(min_freq=0)
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["<unk>"])
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["x", "x"])


@pytest.fixture
def vec_file(tmp_path):
    p = tmp_path / "custom.vec"
    p.write_text("hello 0.1 0.2 0.3\nworld 1.0 2.0 3.0\n"
                 "badline 0.5\n"          # malformed: skipped
                 "hello 9.9 9.9 9.9\n")   # duplicate: first wins
    return str(p)


def test_custom_embedding(vec_file):
    emb = text.embedding.CustomEmbedding(vec_file)
    assert emb.vec_len == 3
    assert len(emb) == 3  # unk + hello + world
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [0.1, 0.2, 0.3],
        rtol=1e-6)
    out = emb.get_vecs_by_tokens(["world", "nope"])
    np.testing.assert_allclose(out.asnumpy()[0], [1.0, 2.0, 3.0],
                               rtol=1e-6)
    np.testing.assert_allclose(out.asnumpy()[1], [0, 0, 0])
    # lower_case_backup
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("HELLO", lower_case_backup=True).asnumpy(),
        [0.1, 0.2, 0.3], rtol=1e-6)


def test_update_token_vectors(vec_file):
    emb = text.embedding.CustomEmbedding(vec_file)
    emb.update_token_vectors("hello", mx.nd.array([7.0, 8.0, 9.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [7.0, 8.0, 9.0],
        rtol=1e-6)
    # plain-list vector for a single token must land element-wise
    emb.update_token_vectors("world", [9.0, 8.0, 7.0])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [9.0, 8.0, 7.0],
        rtol=1e-6)
    with pytest.raises(ValueError):
        emb.update_token_vectors("absent", mx.nd.array([1.0, 2.0, 3.0]))
    with pytest.raises(ValueError):  # tokens/vectors length mismatch
        emb.update_token_vectors(["hello", "world"],
                                 mx.nd.array([[1.0, 2.0, 3.0]]))


def test_unknown_vector_from_file(tmp_path):
    """A '<unk>' line in the source file supplies the unknown vector."""
    p = tmp_path / "with_unk.vec"
    p.write_text("<unk> 0.5 0.5 0.5\nhello 0.1 0.2 0.3\n")
    emb = text.embedding.CustomEmbedding(str(p))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("never-seen").asnumpy(), [0.5, 0.5, 0.5],
        rtol=1e-6)
    assert len(emb) == 2  # unk + hello, no duplicate unk row


def test_no_unknown_token_raises(vec_file):
    emb = text.embedding.CustomEmbedding(vec_file)
    vocab = text.Vocabulary(collections.Counter({"hello": 1}),
                            unknown_token=None)
    comp = text.embedding.CompositeEmbedding(vocab, emb)
    with pytest.raises(KeyError):
        comp.get_vecs_by_tokens("missing")


def test_glove_archive_inventory():
    """Every GloVe file maps to its hosting zip (the reference downloads
    archives, not bare .txt)."""
    gl = text.embedding.GloVe
    assert set(gl.pretrained_archive_name) == set(
        gl.pretrained_file_name_sha1)
    assert gl.pretrained_archive_name["glove.6B.50d.txt"] == "glove.6B.zip"
    assert gl.pretrained_archive_name[
        "glove.twitter.27B.25d.txt"] == "glove.twitter.27B.zip"


def test_composite_embedding(vec_file):
    emb = text.embedding.CustomEmbedding(vec_file)
    vocab = text.Vocabulary(collections.Counter({"hello": 2, "new": 1}))
    comp = text.embedding.CompositeEmbedding(vocab, [emb, emb])
    assert comp.vec_len == 6
    got = comp.get_vecs_by_tokens("hello").asnumpy()
    np.testing.assert_allclose(got, [0.1, 0.2, 0.3, 0.1, 0.2, 0.3],
                               rtol=1e-6)
    # token in vocab but not in the source embedding -> unknown vector
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("new").asnumpy(), np.zeros(6))


def test_registry_create_and_inventory(vec_file):
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in names["glove"]
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=vec_file)
    assert emb.vec_len == 3
    with pytest.raises(KeyError):
        text.embedding.create("nosuch")


def test_pretrained_fetch_fails_loudly(tmp_path, monkeypatch):
    """No egress: GloVe construction must raise, not hang or silently
    return an empty table (matches gluon.utils.download posture)."""
    monkeypatch.setenv("HOME", str(tmp_path))
    import mxnet_tpu.gluon.utils as gutils
    with pytest.raises(Exception):
        text.embedding.create(
            "glove", pretrained_file_name="glove.6B.50d.txt",
            embedding_root=str(tmp_path))
